// Package loader + forward executor — the libVeles equivalent
// (libVeles/src/workflow_loader.cc, workflow.cc, unit_factory.cc):
// UnitFactory keyed by the package's unit "type" strings, std::thread
// batch splitting for the hot matmul/conv loops. Execution covers
// arbitrary DAGs (fan-in via "inputs" lists in contents.json, e.g.
// input_joiner) with liveness-based buffer assignment — the reference's
// MemoryOptimizer story (libVeles/src/memory_optimizer.h:43-52): each
// unit's output buffer is refcounted by its consumers and returns to a
// reuse pool the moment its last consumer has run, so peak memory is
// the live set, not the unit count. Chain packages (no "inputs" keys)
// degenerate to exactly two pooled buffers — the old ping-pong.

#include "../include/veles_infer.h"

#include <algorithm>
#include <cmath>
#include <cstring>
#include <fstream>
#include <functional>
#include <map>
#include <memory>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

#include "json.h"
#include "npy.h"

namespace veles {
namespace {

thread_local std::string g_error;

struct Tensor {
  std::vector<int> shape;
  std::vector<float> data;

  size_t size() const {
    size_t n = 1;
    for (int d : shape) n *= static_cast<size_t>(d);
    return n;
  }
  void Resize(std::vector<int> s) {
    shape = std::move(s);
    data.resize(size());
  }
};

void ParallelFor(int n, const std::function<void(int, int)> &fn) {
  unsigned hw = std::thread::hardware_concurrency();
  int workers = std::min<int>(std::max(1u, hw), n);
  if (workers <= 1 || n < 4) {
    fn(0, n);
    return;
  }
  std::vector<std::thread> threads;
  int chunk = (n + workers - 1) / workers;
  for (int w = 0; w < workers; ++w) {
    int lo = w * chunk, hi = std::min(n, lo + chunk);
    if (lo >= hi) break;
    threads.emplace_back(fn, lo, hi);
  }
  for (auto &t : threads) t.join();
}

inline float Sigmoid(float v) { return 1.0f / (1.0f + std::exp(-v)); }

// ---------------------------------------------------------------------------
// Units

struct Unit {
  std::string name, type;
  // producer names ("@input" = the model input); empty = the previous
  // unit in package order (chain packages predate the "inputs" key)
  std::vector<std::string> inputs;
  std::map<std::string, NpyArray> params;

  virtual ~Unit() = default;
  virtual void Run(const Tensor &in, Tensor *out) = 0;
  virtual void RunMulti(const std::vector<const Tensor *> &ins,
                        Tensor *out) {
    if (ins.size() != 1)
      throw std::runtime_error(name + ": unit takes one input, got " +
                               std::to_string(ins.size()));
    Run(*ins[0], out);
  }

  const NpyArray *Param(const std::string &key) const {
    auto it = params.find(key);
    return it == params.end() ? nullptr : &it->second;
  }
};

enum class Act { kNone, kTanhScaled, kRelu, kSigmoid, kSoftmax };

void ApplyAct(Act act, float *data, int batch, int features) {
  switch (act) {
    case Act::kNone:
      return;
    case Act::kTanhScaled:
      for (int i = 0; i < batch * features; ++i)
        data[i] = 1.7159f * std::tanh(0.6666f * data[i]);
      return;
    case Act::kRelu:
      for (int i = 0; i < batch * features; ++i)
        data[i] = std::max(data[i], 0.0f);
      return;
    case Act::kSigmoid:
      for (int i = 0; i < batch * features; ++i) data[i] = Sigmoid(data[i]);
      return;
    case Act::kSoftmax:
      for (int b = 0; b < batch; ++b) {
        float *row = data + static_cast<size_t>(b) * features;
        float mx = row[0];
        for (int j = 1; j < features; ++j) mx = std::max(mx, row[j]);
        float sum = 0;
        for (int j = 0; j < features; ++j) {
          row[j] = std::exp(row[j] - mx);
          sum += row[j];
        }
        for (int j = 0; j < features; ++j) row[j] /= sum;
      }
      return;
  }
}

struct All2All : Unit {
  Act act = Act::kNone;
  std::vector<int> out_shape;  // per-sample

  void Run(const Tensor &in, Tensor *out) override {
    const NpyArray *w = Param("weights");
    const NpyArray *bias = Param("bias");
    int batch = in.shape[0];
    int fin = static_cast<int>(in.size()) / batch;
    int fout = w->shape[1];
    std::vector<int> os = {batch};
    for (int d : out_shape) os.push_back(d);
    out->Resize(os);
    ParallelFor(batch, [&](int lo, int hi) {
      for (int b = lo; b < hi; ++b) {
        const float *x = in.data.data() + static_cast<size_t>(b) * fin;
        float *y = out->data.data() + static_cast<size_t>(b) * fout;
        for (int j = 0; j < fout; ++j)
          y[j] = bias ? bias->data[j] : 0.0f;
        for (int i = 0; i < fin; ++i) {
          float xv = x[i];
          if (xv == 0.0f) continue;
          const float *wrow = w->data.data() +
                              static_cast<size_t>(i) * fout;
          for (int j = 0; j < fout; ++j) y[j] += xv * wrow[j];
        }
      }
    });
    ApplyAct(act, out->data.data(), batch, fout);
  }
};

struct Activation : Unit {
  std::string kind;
  double factor = 1.0;

  void Run(const Tensor &in, Tensor *out) override {
    *out = in;
    size_t n = out->size();
    float *d = out->data.data();
    if (kind == "activation_tanh")
      for (size_t i = 0; i < n; ++i) d[i] = std::tanh(d[i]);
    else if (kind == "activation_relu")  // softplus (Znicz naming)
      for (size_t i = 0; i < n; ++i)
        d[i] = std::max(d[i], 0.0f) + std::log1p(std::exp(-std::fabs(d[i])));
    else if (kind == "activation_str")
      for (size_t i = 0; i < n; ++i) d[i] = std::max(d[i], 0.0f);
    else if (kind == "activation_sigmoid")
      for (size_t i = 0; i < n; ++i) d[i] = Sigmoid(d[i]);
    else if (kind == "activation_log")
      for (size_t i = 0; i < n; ++i) d[i] = std::asinh(d[i]);
    else if (kind == "activation_mul")
      for (size_t i = 0; i < n; ++i) d[i] *= static_cast<float>(factor);
    else if (kind == "dropout") {
      // eval mode: identity
    } else {
      throw std::runtime_error("unknown activation " + kind);
    }
  }
};

struct Conv : Unit {
  int n_kernels, kx, ky, sx, sy, pl, pt, pr, pb;
  Act act = Act::kNone;

  void Run(const Tensor &in, Tensor *out) override {
    const NpyArray *w = Param("weights");  // (ky, kx, cin, cout)
    const NpyArray *bias = Param("bias");
    int batch = in.shape[0], h = in.shape[1], wd = in.shape[2],
        c = in.shape[3];
    int oh = (h + pt + pb - ky) / sy + 1;
    int ow = (wd + pl + pr - kx) / sx + 1;
    out->Resize({batch, oh, ow, n_kernels});
    ParallelFor(batch, [&](int blo, int bhi) {
      for (int b = blo; b < bhi; ++b) {
        for (int i = 0; i < oh; ++i) {
          for (int j = 0; j < ow; ++j) {
            float *y = out->data.data() +
                       (((static_cast<size_t>(b) * oh + i) * ow + j) *
                        n_kernels);
            for (int k = 0; k < n_kernels; ++k)
              y[k] = bias ? bias->data[k] : 0.0f;
            for (int dy = 0; dy < ky; ++dy) {
              int yy = i * sy + dy - pt;
              if (yy < 0 || yy >= h) continue;
              for (int dx = 0; dx < kx; ++dx) {
                int xx = j * sx + dx - pl;
                if (xx < 0 || xx >= wd) continue;
                const float *xrow =
                    in.data.data() +
                    (((static_cast<size_t>(b) * h + yy) * wd + xx) * c);
                const float *wrow =
                    w->data.data() +
                    ((static_cast<size_t>(dy) * kx + dx) * c) * n_kernels;
                for (int ci = 0; ci < c; ++ci) {
                  float xv = xrow[ci];
                  const float *wk = wrow + static_cast<size_t>(ci) *
                                    n_kernels;
                  for (int k = 0; k < n_kernels; ++k) y[k] += xv * wk[k];
                }
              }
            }
          }
        }
      }
    });
    ApplyAct(act, out->data.data(), batch * oh * ow, n_kernels);
  }
};

struct Pooling : Unit {
  int kx, ky, sx, sy;
  bool is_max = true;

  void Run(const Tensor &in, Tensor *out) override {
    int batch = in.shape[0], h = in.shape[1], w = in.shape[2],
        c = in.shape[3];
    // ceil mode with edge-clipped windows (matches the python oracle)
    int oh = h >= ky ? (h - ky + sy - 1) / sy + 1 : 1;
    int ow = w >= kx ? (w - kx + sx - 1) / sx + 1 : 1;
    out->Resize({batch, oh, ow, c});
    ParallelFor(batch, [&](int blo, int bhi) {
      for (int b = blo; b < bhi; ++b)
        for (int i = 0; i < oh; ++i)
          for (int j = 0; j < ow; ++j)
            for (int ci = 0; ci < c; ++ci) {
              float acc = is_max ? -1e30f : 0.0f;
              int count = 0;
              for (int dy = 0; dy < ky; ++dy) {
                int yy = i * sy + dy;
                if (yy >= h) continue;
                for (int dx = 0; dx < kx; ++dx) {
                  int xx = j * sx + dx;
                  if (xx >= w) continue;
                  float v = in.data[
                      ((static_cast<size_t>(b) * h + yy) * w + xx) * c +
                      ci];
                  if (is_max)
                    acc = std::max(acc, v);
                  else
                    acc += v;
                  ++count;
                }
              }
              out->data[((static_cast<size_t>(b) * oh + i) * ow + j) * c +
                        ci] = is_max ? acc : acc / std::max(count, 1);
            }
    });
  }
};

struct Depooling : Unit {
  int kx, ky;

  void Run(const Tensor &in, Tensor *out) override {
    int batch = in.shape[0], h = in.shape[1], w = in.shape[2],
        c = in.shape[3];
    out->Resize({batch, h * ky, w * kx, c});
    for (int b = 0; b < batch; ++b)
      for (int i = 0; i < h * ky; ++i)
        for (int j = 0; j < w * kx; ++j)
          std::memcpy(
              out->data.data() +
                  ((static_cast<size_t>(b) * h * ky + i) * w * kx + j) * c,
              in.data.data() +
                  ((static_cast<size_t>(b) * h + i / ky) * w + j / kx) * c,
              sizeof(float) * c);
  }
};

struct Deconv : Unit {
  int n_channels, kx, ky, sx, sy, pl, pt, pr, pb;

  void Run(const Tensor &in, Tensor *out) override {
    const NpyArray *w = Param("weights");  // (ky, kx, cin, cout)
    const NpyArray *bias = Param("bias");
    int batch = in.shape[0], h = in.shape[1], wd = in.shape[2],
        cin = in.shape[3];
    int oh = (h - 1) * sy + ky - pt - pb;
    int ow = (wd - 1) * sx + kx - pl - pr;
    out->Resize({batch, oh, ow, n_channels});
    std::fill(out->data.begin(), out->data.end(), 0.0f);
    ParallelFor(batch, [&](int blo, int bhi) {
      for (int b = blo; b < bhi; ++b)
        for (int i = 0; i < h; ++i)
          for (int j = 0; j < wd; ++j) {
            const float *x = in.data.data() +
                ((static_cast<size_t>(b) * h + i) * wd + j) * cin;
            for (int dy = 0; dy < ky; ++dy) {
              int yy = i * sy + dy - pt;
              if (yy < 0 || yy >= oh) continue;
              for (int dx = 0; dx < kx; ++dx) {
                int xx = j * sx + dx - pl;
                if (xx < 0 || xx >= ow) continue;
                float *y = out->data.data() +
                    ((static_cast<size_t>(b) * oh + yy) * ow + xx) *
                    n_channels;
                const float *wk = w->data.data() +
                    ((static_cast<size_t>(dy) * kx + dx) * cin) *
                    n_channels;
                for (int ci = 0; ci < cin; ++ci)
                  for (int k = 0; k < n_channels; ++k)
                    y[k] += x[ci] * wk[static_cast<size_t>(ci) *
                                       n_channels + k];
              }
            }
          }
    });
    if (bias)
      for (size_t i = 0; i < out->size(); ++i)
        out->data[i] += bias->data[i % n_channels];
  }
};

struct LRN : Unit {
  double alpha = 1e-4, beta = 0.75, k = 2.0;
  int n = 5;

  void Run(const Tensor &in, Tensor *out) override {
    *out = in;
    int c = in.shape.back();
    size_t rows = in.size() / c;
    int half = n / 2;
    for (size_t r = 0; r < rows; ++r) {
      const float *x = in.data.data() + r * c;
      float *y = out->data.data() + r * c;
      for (int i = 0; i < c; ++i) {
        float win = 0;
        for (int j = std::max(0, i - half);
             j < std::min(c, i + half + 1); ++j)
          win += x[j] * x[j];
        y[i] = x[i] / std::pow(static_cast<float>(k) +
                               static_cast<float>(alpha) * win,
                               static_cast<float>(beta));
      }
    }
  }
};

struct Lstm : Unit {
  int hidden;
  bool return_sequences = false;
  float forget_bias = 1.0f;

  void Run(const Tensor &in, Tensor *out) override {
    const NpyArray *w = Param("weights");  // (d+h, 4h)
    const NpyArray *bias = Param("bias");
    int batch = in.shape[0], t = in.shape[1], d = in.shape[2];
    int h4 = 4 * hidden;
    if (return_sequences)
      out->Resize({batch, t, hidden});
    else
      out->Resize({batch, hidden});
    ParallelFor(batch, [&](int blo, int bhi) {
      std::vector<float> hs(hidden, 0.0f), cs(hidden, 0.0f), z(h4);
      for (int b = blo; b < bhi; ++b) {
        std::fill(hs.begin(), hs.end(), 0.0f);
        std::fill(cs.begin(), cs.end(), 0.0f);
        for (int step = 0; step < t; ++step) {
          const float *x = in.data.data() +
              (static_cast<size_t>(b) * t + step) * d;
          for (int j = 0; j < h4; ++j) z[j] = bias ? bias->data[j] : 0.0f;
          for (int i = 0; i < d; ++i) {
            float xv = x[i];
            const float *wrow = w->data.data() +
                                static_cast<size_t>(i) * h4;
            for (int j = 0; j < h4; ++j) z[j] += xv * wrow[j];
          }
          for (int i = 0; i < hidden; ++i) {
            float hv = hs[i];
            const float *wrow = w->data.data() +
                                static_cast<size_t>(d + i) * h4;
            for (int j = 0; j < h4; ++j) z[j] += hv * wrow[j];
          }
          for (int i = 0; i < hidden; ++i) {
            float ig = Sigmoid(z[i]);
            float fg = Sigmoid(z[hidden + i] + forget_bias);
            float gg = std::tanh(z[2 * hidden + i]);
            float og = Sigmoid(z[3 * hidden + i]);
            cs[i] = fg * cs[i] + ig * gg;
            hs[i] = og * std::tanh(cs[i]);
          }
          if (return_sequences)
            std::memcpy(out->data.data() +
                            (static_cast<size_t>(b) * t + step) * hidden,
                        hs.data(), sizeof(float) * hidden);
        }
        if (!return_sequences)
          std::memcpy(out->data.data() +
                          static_cast<size_t>(b) * hidden,
                      hs.data(), sizeof(float) * hidden);
      }
    });
  }
};

struct Rnn : Unit {
  // vanilla tanh RNN (veles_tpu/nn/rnn.py RNN): h_t = tanh([x,h] W + b)
  int hidden;
  bool return_sequences = false;

  void Run(const Tensor &in, Tensor *out) override {
    const NpyArray *w = Param("weights");  // (d+h, h)
    const NpyArray *bias = Param("bias");
    int batch = in.shape[0], t = in.shape[1], d = in.shape[2];
    if (return_sequences)
      out->Resize({batch, t, hidden});
    else
      out->Resize({batch, hidden});
    ParallelFor(batch, [&](int blo, int bhi) {
      std::vector<float> hs(hidden, 0.0f), z(hidden);
      for (int b = blo; b < bhi; ++b) {
        std::fill(hs.begin(), hs.end(), 0.0f);
        for (int step = 0; step < t; ++step) {
          const float *x = in.data.data() +
              (static_cast<size_t>(b) * t + step) * d;
          for (int j = 0; j < hidden; ++j)
            z[j] = bias ? bias->data[j] : 0.0f;
          for (int i = 0; i < d; ++i) {
            float xv = x[i];
            const float *wrow = w->data.data() +
                                static_cast<size_t>(i) * hidden;
            for (int j = 0; j < hidden; ++j) z[j] += xv * wrow[j];
          }
          for (int i = 0; i < hidden; ++i) {
            float hv = hs[i];
            const float *wrow = w->data.data() +
                                static_cast<size_t>(d + i) * hidden;
            for (int j = 0; j < hidden; ++j) z[j] += hv * wrow[j];
          }
          for (int i = 0; i < hidden; ++i) hs[i] = std::tanh(z[i]);
          if (return_sequences)
            std::memcpy(out->data.data() +
                            (static_cast<size_t>(b) * t + step) * hidden,
                        hs.data(), sizeof(float) * hidden);
        }
        if (!return_sequences)
          std::memcpy(out->data.data() +
                          static_cast<size_t>(b) * hidden,
                      hs.data(), sizeof(float) * hidden);
      }
    });
  }
};

struct Cutter : Unit {
  // static NHWC crop (veles_tpu/nn/cutter.py); padding = (l, t, r, b)
  int pl = 0, pt = 0, pr = 0, pb = 0;

  void Run(const Tensor &in, Tensor *out) override {
    int batch = in.shape[0], h = in.shape[1], w = in.shape[2],
        c = in.shape[3];
    int oh = h - pt - pb, ow = w - pl - pr;
    out->Resize({batch, oh, ow, c});
    // whole output rows are contiguous in both tensors: one memcpy per
    // (b, i), not one per pixel
    size_t row = static_cast<size_t>(ow) * c;
    for (int b = 0; b < batch; ++b)
      for (int i = 0; i < oh; ++i)
        std::memcpy(
            out->data.data() +
                (static_cast<size_t>(b) * oh + i) * row,
            in.data.data() +
                ((static_cast<size_t>(b) * h + (i + pt)) * w + pl) * c,
            sizeof(float) * row);
  }
};

struct KohonenForward : Unit {
  // best-matching-unit lookup (veles_tpu/nn/kohonen.py KohonenForward):
  // argmin_j ||x - w_j||^2 over the flattened sample; emits the winner
  // index as a float scalar per sample (the chain carries one dtype)
  void Run(const Tensor &in, Tensor *out) override {
    const NpyArray *w = Param("weights");  // (neurons, features)
    int batch = in.shape[0];
    size_t features = in.size() / batch;
    int neurons = w->shape[0];
    out->Resize({batch});
    ParallelFor(batch, [&](int blo, int bhi) {
      for (int b = blo; b < bhi; ++b) {
        const float *x = in.data.data() + b * features;
        int best = 0;
        float best_d = 0;
        for (int j = 0; j < neurons; ++j) {
          const float *wj = w->data.data() +
                            static_cast<size_t>(j) * features;
          float d = 0;
          for (size_t i = 0; i < features; ++i) {
            float diff = x[i] - wj[i];
            d += diff * diff;
          }
          if (j == 0 || d < best_d) {
            best_d = d;
            best = j;
          }
        }
        out->data[b] = static_cast<float>(best);
      }
    });
  }
};

struct Rbm : Unit {
  // hidden-unit probabilities sigmoid(x W + hbias)
  // (veles_tpu/nn/rbm.py RBM forward)
  void Run(const Tensor &in, Tensor *out) override {
    const NpyArray *w = Param("weights");  // (n_vis, n_hidden)
    const NpyArray *hb = Param("hbias");
    int batch = in.shape[0];
    size_t n_vis = in.size() / batch;
    int n_hidden = w->shape[1];
    out->Resize({batch, n_hidden});
    ParallelFor(batch, [&](int blo, int bhi) {
      for (int b = blo; b < bhi; ++b) {
        const float *x = in.data.data() + b * n_vis;
        float *y = out->data.data() +
                   static_cast<size_t>(b) * n_hidden;
        for (int j = 0; j < n_hidden; ++j)
          y[j] = hb ? hb->data[j] : 0.0f;
        for (size_t i = 0; i < n_vis; ++i) {
          float xv = x[i];
          if (xv == 0.0f) continue;
          const float *wr = w->data.data() + i * n_hidden;
          for (int j = 0; j < n_hidden; ++j) y[j] += xv * wr[j];
        }
        for (int j = 0; j < n_hidden; ++j) y[j] = Sigmoid(y[j]);
      }
    });
  }
};

// y = x @ w, row-major (n, k) x (k, m) — shared by the attention/MoE
// projections (the skip-zero inner loop mirrors All2All::Run)
void MatMulRM(const float *x, const float *w, float *y, int n, int k,
              int m) {
  for (int r = 0; r < n; ++r) {
    float *yr = y + static_cast<size_t>(r) * m;
    std::fill(yr, yr + m, 0.0f);
    const float *xr = x + static_cast<size_t>(r) * k;
    for (int i = 0; i < k; ++i) {
      float xv = xr[i];
      if (xv == 0.0f) continue;
      const float *wr = w + static_cast<size_t>(i) * m;
      for (int j = 0; j < m; ++j) yr[j] += xv * wr[j];
    }
  }
}

// Per-head scaled-dot-product attention: q/ctx are (t_q, d) planes
// with h heads as contiguous hd slices; k/v are (t, kv_h*hd) planes
// with kv_h heads (GQA twin of the python units: query head `head`
// reads KV head `head / (h / kv_h)`; kv_h == h is classic MHA).
// `scratch` must hold t floats. `t_q`/`q_pos0` generalize to the
// CACHED-decode case: the q plane holds t_q rows at GLOBAL positions
// q_pos0..q_pos0+t_q-1 attending over t cache rows (defaults t_q = t,
// q_pos0 = 0 — the full-window case). ONE implementation shared by
// MultiHeadAttention, TransformerBlock::Run and the KV-cached
// TransformerBlock::Step so masking/stability fixes cannot diverge
// (the python side shares nn/attention.attention_core the same way).
void AttentionHeads(const float *q, const float *k, const float *v,
                    float *ctx, float *scratch, int t, int d, int h,
                    bool causal, int kv_h = 0, int window = 0,
                    int t_q = -1, int q_pos0 = 0) {
  if (kv_h <= 0) kv_h = h;
  if (t_q < 0) t_q = t;
  int hd = d / h;
  int kv_d = kv_h * hd;
  int group = h / kv_h;
  float scale = 1.0f / std::sqrt(static_cast<float>(hd));
  for (int head = 0; head < h; ++head) {
    int off = head * hd;
    int kv_off = (head / group) * hd;
    for (int qi = 0; qi < t_q; ++qi) {
      const float *qv = q + static_cast<size_t>(qi) * d + off;
      int qpos = q_pos0 + qi;
      int kmax = causal ? std::min(qpos + 1, t) : t;
      // sliding window (python twin: q - k < window, causal only)
      int kmin = window > 0 ? std::max(0, qpos - window + 1) : 0;
      float mx = -1e30f;
      for (int ki = kmin; ki < kmax; ++ki) {
        const float *kv = k + static_cast<size_t>(ki) * kv_d + kv_off;
        float dot = 0;
        for (int e = 0; e < hd; ++e) dot += qv[e] * kv[e];
        scratch[ki] = dot * scale;
        mx = std::max(mx, scratch[ki]);
      }
      float sum = 0;
      for (int ki = kmin; ki < kmax; ++ki) {
        scratch[ki] = std::exp(scratch[ki] - mx);
        sum += scratch[ki];
      }
      float *cv = ctx + static_cast<size_t>(qi) * d + off;
      std::fill(cv, cv + hd, 0.0f);
      for (int ki = kmin; ki < kmax; ++ki) {
        float p = scratch[ki] / sum;
        const float *vv = v + static_cast<size_t>(ki) * kv_d + kv_off;
        for (int e = 0; e < hd; ++e) cv[e] += p * vv[e];
      }
    }
  }
}

struct MultiHeadAttention : Unit {
  // inference twin of veles_tpu/nn/attention.py (B, T, D) contract:
  // heads are contiguous hd-slices of the feature axis; n_kv_heads <
  // n_heads is GQA (wk/wv are (d, kv_d))
  int n_heads = 4;
  int n_kv_heads = 0;  // 0 = n_heads
  bool causal = false;

  void Run(const Tensor &in, Tensor *out) override {
    const NpyArray *wq = Param("wq"), *wk = Param("wk"),
                   *wv = Param("wv"), *wo = Param("wo");
    int batch = in.shape[0], t = in.shape[1], d = in.shape[2];
    int kv_h = n_kv_heads > 0 ? n_kv_heads : n_heads;
    int kv_d = (d / n_heads) * kv_h;
    out->Resize({batch, t, d});
    size_t plane = static_cast<size_t>(t) * d;
    size_t kv_plane = static_cast<size_t>(t) * kv_d;
    ParallelFor(batch, [&](int lo, int hi) {
      std::vector<float> q(plane), k(kv_plane), v(kv_plane),
          ctx(plane), s(t);
      for (int b = lo; b < hi; ++b) {
        const float *x = in.data.data() + b * plane;
        MatMulRM(x, wq->data.data(), q.data(), t, d, d);
        MatMulRM(x, wk->data.data(), k.data(), t, d, kv_d);
        MatMulRM(x, wv->data.data(), v.data(), t, d, kv_d);
        AttentionHeads(q.data(), k.data(), v.data(), ctx.data(),
                       s.data(), t, d, n_heads, causal, kv_h);
        MatMulRM(ctx.data(), wo->data.data(),
                 out->data.data() + b * plane, t, d, d);
      }
    });
  }
};

// rotary position embedding on a (t, d) plane with heads as contiguous
// hd slices (transformer.py _rope twin): HALF-SPLIT pairing (GPT-NeoX
// convention, feature j rotates with j+half — not interleaved even/odd).
// `pos0` offsets the rows' global positions (row r sits at pos0 + r) —
// the cached decode rotates single rows at their true position.
void RopeRotate(float *plane, int t, int d, int h,
                float base = 10000.0f, int pos0 = 0) {
  int hd = d / h;
  int half = hd / 2;
  std::vector<float> inv(half), cosv(half), sinv(half);
  for (int j = 0; j < half; ++j)   // position-independent: hoist pow
    inv[j] = std::pow(base, -static_cast<float>(j) / half);
  for (int r = 0; r < t; ++r) {
    int pos = pos0 + r;
    for (int j = 0; j < half; ++j) {
      float ang = pos * inv[j];
      cosv[j] = std::cos(ang);
      sinv[j] = std::sin(ang);
    }
    for (int head = 0; head < h; ++head) {
      float *x = plane + static_cast<size_t>(r) * d + head * hd;
      for (int j = 0; j < half; ++j) {
        float a = x[j], b = x[half + j];
        x[j] = a * cosv[j] - b * sinv[j];
        x[half + j] = a * sinv[j] + b * cosv[j];
      }
    }
  }
}

struct TransformerBlock : Unit {
  // inference twin of veles_tpu/nn/transformer.py: pre-LN residual
  // block — h = x + Wo·attn(LN1 x); y = h + W2·gelu(W1·LN2 h);
  // n_kv_heads < n_heads is GQA (wk/wv are (d, kv_d))
  int n_heads = 4;
  int n_kv_heads = 0;  // 0 = n_heads
  int window = 0;      // sliding-window span; 0 = full attention
  bool causal = true;
  bool rope = false;
  bool rms = false;     // norm="rms": no centering, no bias
  float rope_base = 10000.0f;
  bool swiglu = false;  // ffn="swiglu": W2*(silu(W1 x) . W3 x)

  // b == nullptr selects RMSNorm (no centering, no bias) — the twin of
  // transformer.py block_norm
  static void LayerNorm(const float *x, const float *g, const float *b,
                        float *y, int n, int d) {
    for (int r = 0; r < n; ++r) {
      const float *xr = x + static_cast<size_t>(r) * d;
      float *yr = y + static_cast<size_t>(r) * d;
      if (b == nullptr) {
        float ms = 0;
        for (int i = 0; i < d; ++i) ms += xr[i] * xr[i];
        float inv = 1.0f / std::sqrt(ms / d + 1e-5f);
        for (int i = 0; i < d; ++i) yr[i] = xr[i] * inv * g[i];
        continue;
      }
      float mu = 0;
      for (int i = 0; i < d; ++i) mu += xr[i];
      mu /= d;
      float var = 0;
      for (int i = 0; i < d; ++i) var += (xr[i] - mu) * (xr[i] - mu);
      var /= d;
      float inv = 1.0f / std::sqrt(var + 1e-5f);
      for (int i = 0; i < d; ++i)
        yr[i] = (xr[i] - mu) * inv * g[i] + b[i];
    }
  }

  static float Silu(float x) { return x / (1.0f + std::exp(-x)); }

  static float Gelu(float x) {
    const float c = 0.7978845608028654f;  // sqrt(2/pi)
    return 0.5f * x * (1.0f + std::tanh(c * (x + 0.044715f * x * x * x)));
  }

  void Run(const Tensor &in, Tensor *out) override {
    const NpyArray *wq = Param("wq"), *wk = Param("wk"),
                   *wv = Param("wv"), *wo = Param("wo"),
                   *w1 = Param("w1"), *b1 = Param("b1"),
                   *w2 = Param("w2"), *b2 = Param("b2"),
                   *w3 = Param("w3"),
                   *g1 = Param("ln1_g"), *bb1 = Param("ln1_b"),
                   *g2 = Param("ln2_g"), *bb2 = Param("ln2_b");
    int batch = in.shape[0], t = in.shape[1], d = in.shape[2];
    int f = w1->shape[1];
    int h = n_heads;
    int kv_h = n_kv_heads > 0 ? n_kv_heads : h;
    int kv_d = (d / h) * kv_h;
    *out = in;                         // residual accumulator
    size_t plane = static_cast<size_t>(t) * d;
    size_t kv_plane = static_cast<size_t>(t) * kv_d;
    ParallelFor(batch, [&](int lo, int hi) {
      std::vector<float> ln(plane), q(plane), k(kv_plane),
          v(kv_plane), ctx(plane), proj(plane), s(t), hbuf(f);
      for (int b = lo; b < hi; ++b) {
        float *xb = out->data.data() + b * plane;
        // attention sub-block
        LayerNorm(xb, g1->data.data(),
                  rms ? nullptr : bb1->data.data(), ln.data(), t, d);
        MatMulRM(ln.data(), wq->data.data(), q.data(), t, d, d);
        MatMulRM(ln.data(), wk->data.data(), k.data(), t, d, kv_d);
        MatMulRM(ln.data(), wv->data.data(), v.data(), t, d, kv_d);
        if (rope) {
          RopeRotate(q.data(), t, d, h, rope_base);
          RopeRotate(k.data(), t, kv_d, kv_h, rope_base);
        }
        AttentionHeads(q.data(), k.data(), v.data(), ctx.data(),
                       s.data(), t, d, h, causal, kv_h, window);
        MatMulRM(ctx.data(), wo->data.data(), proj.data(), t, d, d);
        for (size_t i = 0; i < plane; ++i) xb[i] += proj[i];
        // FFN sub-block (gelu: W2*gelu(W1 x + b1) + b2; swiglu:
        // W2*(silu(W1 x) . W3 x), no biases — transformer.py block_ffn)
        LayerNorm(xb, g2->data.data(),
                  rms ? nullptr : bb2->data.data(), ln.data(), t, d);
        std::vector<float> gbuf(swiglu ? f : 0);
        for (int r = 0; r < t; ++r)
          FfnRow(ln.data() + static_cast<size_t>(r) * d,
                 xb + static_cast<size_t>(r) * d,
                 hbuf.data(), gbuf.data(), w1, b1, w2, b2, w3, d, f);
      }
    });
  }

  // FFN for ONE normalized row, ACCUMULATED into the residual `yr`
  // (hbuf: f floats scratch; gbuf: f floats, swiglu only). The one
  // copy Run and the cached Step share.
  void FfnRow(const float *xr, float *yr, float *hbuf, float *gbuf,
              const NpyArray *w1, const NpyArray *b1,
              const NpyArray *w2, const NpyArray *b2,
              const NpyArray *w3, int d, int f) const {
    for (int j = 0; j < f; ++j)
      hbuf[j] = swiglu ? 0.0f : b1->data[j];
    if (swiglu) std::fill(gbuf, gbuf + f, 0.0f);
    for (int i = 0; i < d; ++i) {
      float xv = xr[i];
      if (xv == 0.0f) continue;
      const float *row = w1->data.data() + static_cast<size_t>(i) * f;
      for (int j = 0; j < f; ++j) hbuf[j] += xv * row[j];
      if (swiglu) {
        const float *row3 = w3->data.data() +
                            static_cast<size_t>(i) * f;
        for (int j = 0; j < f; ++j) gbuf[j] += xv * row3[j];
      }
    }
    if (swiglu)
      for (int j = 0; j < f; ++j) hbuf[j] = Silu(hbuf[j]) * gbuf[j];
    else
      for (int j = 0; j < f; ++j) hbuf[j] = Gelu(hbuf[j]);
    if (!swiglu)
      for (int i = 0; i < d; ++i) yr[i] += b2->data[i];
    for (int j = 0; j < f; ++j) {
      float hv = hbuf[j];
      if (hv == 0.0f) continue;
      const float *row = w2->data.data() + static_cast<size_t>(j) * d;
      for (int i = 0; i < d; ++i) yr[i] += hv * row[i];
    }
  }

  // Per-decode state for the cached path: params resolved ONCE and
  // scratch allocated ONCE — Step runs per token per block, so
  // map lookups and heap allocations inside it would dominate the
  // very dispatch cost the cache removes.
  struct StepState {
    const NpyArray *wq, *wk, *wv, *wo, *w1, *b1, *w2, *b2, *w3;
    const NpyArray *g1, *bb1, *g2, *bb2;
    std::vector<float> ln, q, ctx, proj, s, hbuf, gbuf;
    int d, f, h, kv_h, kv_d;
  };

  StepState PrepareStep(int t_max) const {
    StepState st;
    st.wq = Param("wq");
    st.wk = Param("wk");
    st.wv = Param("wv");
    st.wo = Param("wo");
    st.w1 = Param("w1");
    st.b1 = Param("b1");
    st.w2 = Param("w2");
    st.b2 = Param("b2");
    st.w3 = Param("w3");
    st.g1 = Param("ln1_g");
    st.bb1 = Param("ln1_b");
    st.g2 = Param("ln2_g");
    st.bb2 = Param("ln2_b");
    st.d = st.wq->shape[0];
    st.f = st.w1->shape[1];
    st.h = n_heads;
    st.kv_h = n_kv_heads > 0 ? n_kv_heads : st.h;
    st.kv_d = (st.d / st.h) * st.kv_h;
    st.ln.resize(st.d);
    st.q.resize(st.d);
    st.ctx.resize(st.d);
    st.proj.resize(st.d);
    st.s.resize(t_max);
    st.hbuf.resize(st.f);
    st.gbuf.resize(swiglu ? st.f : 0);
    return st;
  }

  // One KV-cached token step: x is this token's (d,) residual stream
  // at GLOBAL position pos; ck/cv are (t_max, kv_d) caches whose rows
  // < pos are filled — row pos is written here, attention reads rows
  // 0..pos (window-clipped) through the SAME AttentionHeads as Run.
  // The incremental twin of veles_tpu/nn/sampling._block_step.
  void Step(StepState &st, float *x, std::vector<float> &ck,
            std::vector<float> &cv, int pos) {
    int d = st.d;
    LayerNorm(x, st.g1->data.data(),
              rms ? nullptr : st.bb1->data.data(), st.ln.data(), 1, d);
    MatMulRM(st.ln.data(), st.wq->data.data(), st.q.data(), 1, d, d);
    float *krow = ck.data() + static_cast<size_t>(pos) * st.kv_d;
    float *vrow = cv.data() + static_cast<size_t>(pos) * st.kv_d;
    MatMulRM(st.ln.data(), st.wk->data.data(), krow, 1, d, st.kv_d);
    MatMulRM(st.ln.data(), st.wv->data.data(), vrow, 1, d, st.kv_d);
    if (rope) {
      RopeRotate(st.q.data(), 1, d, st.h, rope_base, pos);
      RopeRotate(krow, 1, st.kv_d, st.kv_h, rope_base, pos);
    }
    AttentionHeads(st.q.data(), ck.data(), cv.data(), st.ctx.data(),
                   st.s.data(), pos + 1, d, st.h, causal, st.kv_h,
                   window, /*t_q=*/1, /*q_pos0=*/pos);
    MatMulRM(st.ctx.data(), st.wo->data.data(), st.proj.data(), 1, d,
             d);
    for (int i = 0; i < d; ++i) x[i] += st.proj[i];
    LayerNorm(x, st.g2->data.data(),
              rms ? nullptr : st.bb2->data.data(), st.ln.data(), 1, d);
    FfnRow(st.ln.data(), x, st.hbuf.data(), st.gbuf.data(), st.w1,
           st.b1, st.w2, st.b2, st.w3, d, st.f);
  }
};

struct PosEmbedding : Unit {
  // adds the learned (T, D) position table (transformer.py twin)
  void Run(const Tensor &in, Tensor *out) override {
    const NpyArray *table = Param("table");
    int batch = in.shape[0], t = in.shape[1], d = in.shape[2];
    if (table->shape[0] < t || table->shape[1] != d)
      throw std::runtime_error(
          "pos_embedding: input (t=" + std::to_string(t) + ", d=" +
          std::to_string(d) + ") exceeds table (" +
          std::to_string(table->shape[0]) + ", " +
          std::to_string(table->shape[1]) + ")");
    *out = in;
    for (int b = 0; b < batch; ++b) {
      float *y = out->data.data() +
                 static_cast<size_t>(b) * t * d;
      for (int step = 0; step < t; ++step)
        for (int i = 0; i < d; ++i)
          y[static_cast<size_t>(step) * d + i] +=
              table->data[static_cast<size_t>(step) *
                          table->shape[1] + i];
    }
  }
};

struct Embedding : Unit {
  // (B, T) token ids (stored as floats in the runtime's tensors) →
  // (B, T, D) rows of the table (transformer.py Embedding twin)
  void Run(const Tensor &in, Tensor *out) override {
    const NpyArray *table = Param("table");
    int vocab = table->shape[0], d = table->shape[1];
    size_t n = in.size();
    std::vector<int> shape = in.shape;
    shape.push_back(d);
    out->Resize(shape);
    for (size_t i = 0; i < n; ++i) {
      int tok = static_cast<int>(std::lround(in.data[i]));
      // clamp, matching jnp.take(mode="clip") and the numpy oracle —
      // one OOB semantic across every runtime
      tok = std::min(std::max(tok, 0), vocab - 1);
      std::memcpy(out->data.data() + i * d,
                  table->data.data() + static_cast<size_t>(tok) * d,
                  sizeof(float) * d);
    }
  }
};

struct LMHead : Unit {
  // (B, T, D) → (B, T, V) per-position logits (transformer.py twin)
  void Run(const Tensor &in, Tensor *out) override {
    const NpyArray *w = Param("weights");
    const NpyArray *bias = Param("bias");
    int d = w->shape[0], vocab = w->shape[1];
    int rows = static_cast<int>(in.size()) / d;
    std::vector<int> shape(in.shape.begin(), in.shape.end() - 1);
    shape.push_back(vocab);
    out->Resize(shape);
    ParallelFor(rows, [&](int lo, int hi) {
      MatMulRM(in.data.data() + static_cast<size_t>(lo) * d,
               w->data.data(),
               out->data.data() + static_cast<size_t>(lo) * vocab,
               hi - lo, d, vocab);
      if (bias) {
        for (int r = lo; r < hi; ++r) {
          float *y = out->data.data() + static_cast<size_t>(r) * vocab;
          for (int j = 0; j < vocab; ++j) y[j] += bias->data[j];
        }
      }
    });
  }
};

struct InputJoiner : Unit {
  // fan-in concat along features (veles_tpu/input_joiner.py: each
  // input flattened to (batch, -1), joined on axis 1) — the unit that
  // makes DAG execution observable from a package
  void Run(const Tensor &in, Tensor *out) override {
    RunMulti({&in}, out);
  }
  void RunMulti(const std::vector<const Tensor *> &ins,
                Tensor *out) override {
    if (ins.empty())
      throw std::runtime_error(name + ": no inputs to join");
    if (ins[0]->shape.empty())  // validate BEFORE reading shape[0]
      throw std::runtime_error(name + ": rank-0 input");
    int batch = ins[0]->shape[0];
    if (batch <= 0)  // size()/batch below would be a SIGFPE, not catchable
      throw std::runtime_error(name + ": empty batch");
    size_t width = 0;
    for (const Tensor *t : ins) {
      if (t->shape.empty() || t->shape[0] != batch)
        throw std::runtime_error(name + ": input batch mismatch");
      width += t->size() / static_cast<size_t>(batch);
    }
    out->Resize({batch, static_cast<int>(width)});
    size_t off = 0;
    for (const Tensor *t : ins) {
      size_t w = t->size() / static_cast<size_t>(batch);
      for (int b = 0; b < batch; ++b)
        std::memcpy(out->data.data() + b * width + off,
                    t->data.data() + b * w, w * sizeof(float));
      off += w;
    }
  }
};

struct MeanPool : Unit {
  void Run(const Tensor &in, Tensor *out) override {
    int batch = in.shape[0], t = in.shape[1];
    int d = static_cast<int>(in.size()) / (batch * t);
    out->Resize({batch, d});
    for (int b = 0; b < batch; ++b) {
      float *y = out->data.data() + static_cast<size_t>(b) * d;
      std::fill(y, y + d, 0.0f);
      for (int step = 0; step < t; ++step) {
        const float *x = in.data.data() +
                         (static_cast<size_t>(b) * t + step) * d;
        for (int i = 0; i < d; ++i) y[i] += x[i];
      }
      for (int i = 0; i < d; ++i) y[i] /= t;
    }
  }
};

struct MoEFFN : Unit {
  // inference twin of veles_tpu/nn/moe.py: dense softmax mixture, or
  // GShard-style top-k dispatch with the SAME capacity semantics as the
  // python _mix_sparse (top-k renormalized gates; tokens beyond an
  // expert's capacity — assigned in token order — combine with zero
  // weight, the residual path carries them)
  int top_k = 0;
  double capacity_factor = 1.25;

  void Run(const Tensor &in, Tensor *out) override {
    const NpyArray *router = Param("router"), *w1 = Param("w1"),
                   *b1 = Param("b1"), *w2 = Param("w2"),
                   *b2 = Param("b2");
    int d = in.shape.back();
    int n = static_cast<int>(in.size()) / d;   // tokens
    // expert count/width from the weights themselves (a config key that
    // disagreed with the arrays would index out of bounds)
    int e = router->shape[1], f = w1->shape[2];
    if (w1->shape[0] != e)
      throw std::runtime_error("moe_ffn: router/w1 expert mismatch");
    out->Resize(in.shape);
    // pass 1 (serial: capacity slots are claimed in token order) —
    // per-token combine weights after top-k + capacity filtering
    std::vector<float> weights(static_cast<size_t>(n) * e, 0.0f);
    {
      std::vector<float> gates(e);
      std::vector<int> used(e, 0);
      int c = n;  // dense: no capacity pressure
      if (top_k > 0 && top_k < e)
        c = std::max(1, static_cast<int>(std::ceil(
                top_k * static_cast<double>(n) / e * capacity_factor)));
      for (int tok = 0; tok < n; ++tok) {
        const float *x = in.data.data() + static_cast<size_t>(tok) * d;
        for (int ex = 0; ex < e; ++ex) {
          float z = 0;
          for (int i = 0; i < d; ++i)
            z += x[i] * router->data[static_cast<size_t>(i) * e + ex];
          gates[ex] = z;
        }
        float mx = *std::max_element(gates.begin(), gates.end());
        float sum = 0;
        for (int ex = 0; ex < e; ++ex) {
          gates[ex] = std::exp(gates[ex] - mx);
          sum += gates[ex];
        }
        for (int ex = 0; ex < e; ++ex) gates[ex] /= sum;
        if (top_k > 0 && top_k < e) {
          std::vector<float> sorted(gates);
          std::nth_element(sorted.begin(), sorted.end() - top_k,
                           sorted.end());
          float thresh = sorted[e - top_k];
          float kept = 0;
          for (int ex = 0; ex < e; ++ex) {
            if (gates[ex] < thresh) gates[ex] = 0;
            kept += gates[ex];
          }
          for (int ex = 0; ex < e; ++ex) gates[ex] /= kept;
        }
        float *wrow = weights.data() + static_cast<size_t>(tok) * e;
        for (int ex = 0; ex < e; ++ex) {
          if (gates[ex] == 0.0f) continue;
          if (used[ex] >= c) continue;       // over capacity: dropped
          ++used[ex];
          wrow[ex] = gates[ex];
        }
      }
    }
    // pass 2 (parallel): expert FFNs weighted by the kept gates
    ParallelFor(n, [&](int lo, int hi) {
      std::vector<float> hbuf(f), ybuf(d);
      for (int tok = lo; tok < hi; ++tok) {
        const float *x = in.data.data() + static_cast<size_t>(tok) * d;
        float *y = out->data.data() + static_cast<size_t>(tok) * d;
        const float *wrow = weights.data() +
                            static_cast<size_t>(tok) * e;
        std::fill(y, y + d, 0.0f);
        for (int ex = 0; ex < e; ++ex) {
          float g = wrow[ex];
          if (g == 0.0f) continue;
          const float *w1e = w1->data.data() +
                             static_cast<size_t>(ex) * d * f;
          const float *b1e = b1->data.data() +
                             static_cast<size_t>(ex) * f;
          const float *w2e = w2->data.data() +
                             static_cast<size_t>(ex) * f * d;
          const float *b2e = b2->data.data() +
                             static_cast<size_t>(ex) * d;
          for (int j = 0; j < f; ++j) hbuf[j] = b1e[j];
          for (int i = 0; i < d; ++i) {
            float xv = x[i];
            if (xv == 0.0f) continue;
            const float *row = w1e + static_cast<size_t>(i) * f;
            for (int j = 0; j < f; ++j) hbuf[j] += xv * row[j];
          }
          for (int j = 0; j < f; ++j) hbuf[j] = std::tanh(hbuf[j]);
          for (int i = 0; i < d; ++i) ybuf[i] = b2e[i];
          for (int j = 0; j < f; ++j) {
            float hv = hbuf[j];
            if (hv == 0.0f) continue;
            const float *row = w2e + static_cast<size_t>(j) * d;
            for (int i = 0; i < d; ++i) ybuf[i] += hv * row[i];
          }
          for (int i = 0; i < d; ++i) y[i] += g * ybuf[i];
        }
      }
    });
  }
};

// ---------------------------------------------------------------------------
// Factory

std::unique_ptr<Unit> MakeUnit(const std::string &type, const Json &cfg) {
  auto get_pair = [&](const std::string &key, int a, int b) {
    std::pair<int, int> out{a, b};
    if (cfg.Has(key)) {
      auto v = cfg[key].AsIntVector();
      out = {v.at(0), v.at(1)};
    }
    return out;
  };
  auto get4 = [&](const std::string &key) {
    std::vector<int> v = {0, 0, 0, 0};
    if (cfg.Has(key)) v = cfg[key].AsIntVector();
    return v;
  };

  if (type.rfind("all2all", 0) == 0 || type == "softmax") {
    auto u = std::make_unique<All2All>();
    if (type == "all2all_tanh") u->act = Act::kTanhScaled;
    else if (type == "all2all_relu") u->act = Act::kRelu;
    else if (type == "all2all_sigmoid") u->act = Act::kSigmoid;
    else if (type == "softmax") u->act = Act::kSoftmax;
    if (cfg.Has("output_sample_shape"))
      u->out_shape = cfg["output_sample_shape"].AsIntVector();
    return u;
  }
  if (type.rfind("conv", 0) == 0) {
    auto u = std::make_unique<Conv>();
    u->n_kernels = cfg["n_kernels"].AsInt();
    u->kx = cfg["kx"].AsInt();
    u->ky = cfg["ky"].AsInt();
    auto s = get_pair("sliding", 1, 1);
    u->sx = s.first;
    u->sy = s.second;
    auto p = get4("padding");
    u->pl = p[0]; u->pt = p[1]; u->pr = p[2]; u->pb = p[3];
    if (type == "conv_tanh") u->act = Act::kTanhScaled;
    else if (type == "conv_relu") u->act = Act::kRelu;
    else if (type == "conv_sigmoid") u->act = Act::kSigmoid;
    return u;
  }
  if (type == "max_pooling" || type == "avg_pooling" ||
      type == "stochastic_pooling") {
    auto u = std::make_unique<Pooling>();
    u->is_max = (type != "avg_pooling");
    u->kx = cfg.Has("kx") ? cfg["kx"].AsInt() : 2;
    u->ky = cfg.Has("ky") ? cfg["ky"].AsInt() : 2;
    auto s = get_pair("sliding", u->kx, u->ky);
    u->sx = s.first;
    u->sy = s.second;
    return u;
  }
  if (type == "depooling") {
    auto u = std::make_unique<Depooling>();
    u->kx = cfg.Has("kx") ? cfg["kx"].AsInt() : 2;
    u->ky = cfg.Has("ky") ? cfg["ky"].AsInt() : 2;
    return u;
  }
  if (type == "deconv") {
    auto u = std::make_unique<Deconv>();
    u->n_channels = cfg["n_channels"].AsInt();
    u->kx = cfg["kx"].AsInt();
    u->ky = cfg["ky"].AsInt();
    auto s = get_pair("sliding", 1, 1);
    u->sx = s.first;
    u->sy = s.second;
    auto p = get4("padding");
    u->pl = p[0]; u->pt = p[1]; u->pr = p[2]; u->pb = p[3];
    return u;
  }
  if (type == "norm") {
    auto u = std::make_unique<LRN>();
    if (cfg.Has("alpha")) u->alpha = cfg["alpha"].AsDouble();
    if (cfg.Has("beta")) u->beta = cfg["beta"].AsDouble();
    if (cfg.Has("k")) u->k = cfg["k"].AsDouble();
    if (cfg.Has("n")) u->n = cfg["n"].AsInt();
    return u;
  }
  if (type == "lstm") {
    auto u = std::make_unique<Lstm>();
    u->hidden = cfg["hidden_size"].AsInt();
    if (cfg.Has("return_sequences"))
      u->return_sequences = cfg["return_sequences"].AsBool();
    if (cfg.Has("forget_bias"))
      u->forget_bias = static_cast<float>(cfg["forget_bias"].AsDouble());
    return u;
  }
  if (type == "rnn") {
    auto u = std::make_unique<Rnn>();
    u->hidden = cfg["hidden_size"].AsInt();
    if (cfg.Has("return_sequences"))
      u->return_sequences = cfg["return_sequences"].AsBool();
    return u;
  }
  if (type == "cutter") {
    auto u = std::make_unique<Cutter>();
    auto p = get4("padding");
    u->pl = p[0]; u->pt = p[1]; u->pr = p[2]; u->pb = p[3];
    return u;
  }
  if (type == "kohonen_forward") return std::make_unique<KohonenForward>();
  if (type == "rbm") return std::make_unique<Rbm>();
  if (type == "multi_head_attention") {
    auto u = std::make_unique<MultiHeadAttention>();
    if (cfg.Has("n_heads")) u->n_heads = cfg["n_heads"].AsInt();
    if (cfg.Has("n_kv_heads")) u->n_kv_heads = cfg["n_kv_heads"].AsInt();
    if (cfg.Has("causal")) u->causal = cfg["causal"].AsBool();
    return u;
  }
  if (type == "transformer_block") {
    auto u = std::make_unique<TransformerBlock>();
    if (cfg.Has("n_heads")) u->n_heads = cfg["n_heads"].AsInt();
    if (cfg.Has("n_kv_heads")) u->n_kv_heads = cfg["n_kv_heads"].AsInt();
    if (cfg.Has("window")) u->window = cfg["window"].AsInt();
    if (cfg.Has("causal")) u->causal = cfg["causal"].AsBool();
    if (cfg.Has("rope")) u->rope = cfg["rope"].AsBool();
    if (cfg.Has("rope_base"))
      u->rope_base = static_cast<float>(cfg["rope_base"].AsDouble());
    if (cfg.Has("norm")) u->rms = cfg["norm"].AsString() == "rms";
    if (cfg.Has("ffn")) u->swiglu = cfg["ffn"].AsString() == "swiglu";
    return u;
  }
  if (type == "mean_pool") return std::make_unique<MeanPool>();
  if (type == "input_joiner") return std::make_unique<InputJoiner>();
  if (type == "pos_embedding") return std::make_unique<PosEmbedding>();
  if (type == "embedding") return std::make_unique<Embedding>();
  if (type == "lm_head") return std::make_unique<LMHead>();
  if (type == "moe_ffn") {
    auto u = std::make_unique<MoEFFN>();
    if (cfg.Has("top_k")) u->top_k = cfg["top_k"].AsInt();
    if (cfg.Has("capacity_factor"))
      u->capacity_factor = cfg["capacity_factor"].AsDouble();
    return u;
  }
  if (type.rfind("activation", 0) == 0 || type == "dropout") {
    auto u = std::make_unique<Activation>();
    u->kind = type;
    if (cfg.Has("factor")) u->factor = cfg["factor"].AsDouble();
    return u;
  }
  throw std::runtime_error("unit factory: unsupported type " + type);
}

// ---------------------------------------------------------------------------
// DAG executor with liveness-based buffer assignment (the reference's
// MemoryOptimizer capability, libVeles/src/memory_optimizer.h:43-52)

// Resolve each unit's producer indices (-1 = model input). Validates
// topological order: every named input must be a PRECEDING unit.
std::vector<std::vector<int>> ResolveGraph(
    const std::vector<std::unique_ptr<Unit>> &units) {
  std::vector<std::vector<int>> in_idx(units.size());
  std::map<std::string, int> by_name;
  for (size_t i = 0; i < units.size(); ++i) {
    const Unit &u = *units[i];
    if (u.inputs.empty()) {
      in_idx[i].push_back(static_cast<int>(i) - 1);  // chain default
    } else {
      for (const std::string &nm : u.inputs) {
        if (nm == "@input") {
          in_idx[i].push_back(-1);
          continue;
        }
        auto it = by_name.find(nm);
        if (it == by_name.end())
          throw std::runtime_error(
              "unit " + u.name + ": input '" + nm +
              "' is not a preceding unit (package units must be "
              "topologically ordered)");
        in_idx[i].push_back(it->second);
      }
    }
    by_name[u.name] = static_cast<int>(i);
  }
  return in_idx;
}

// Run the whole graph; consumes `input`, fills `*final_out` with the
// LAST unit's output. Buffers are refcounted by consumer count and
// recycled through a pool the moment their last consumer ran.
void ExecuteGraph(const std::vector<std::unique_ptr<Unit>> &units,
                  Tensor input, Tensor *final_out) {
  size_t n = units.size();
  if (n == 0) {
    *final_out = std::move(input);
    return;
  }
  std::vector<std::vector<int>> in_idx = ResolveGraph(units);
  std::vector<int> refs(n, 0);
  int input_refs = 0;
  for (size_t i = 0; i < n; ++i)
    for (int j : in_idx[i]) {
      if (j < 0)
        ++input_refs;
      else
        ++refs[j];
    }
  refs[n - 1] += 1;  // the final output must survive the loop
  std::vector<std::unique_ptr<Tensor>> pool;
  std::vector<std::unique_ptr<Tensor>> live(n);
  for (size_t i = 0; i < n; ++i) {
    std::vector<const Tensor *> ins;
    for (int j : in_idx[i])
      ins.push_back(j < 0 ? &input : live[static_cast<size_t>(j)].get());
    std::unique_ptr<Tensor> out;
    if (pool.empty()) {
      out = std::make_unique<Tensor>();
    } else {
      out = std::move(pool.back());
      pool.pop_back();
    }
    units[i]->RunMulti(ins, out.get());
    live[i] = std::move(out);
    for (int j : in_idx[i]) {
      if (j < 0) {
        if (--input_refs == 0) {
          input.data.clear();
          input.data.shrink_to_fit();
        }
      } else if (--refs[static_cast<size_t>(j)] == 0) {
        pool.push_back(std::move(live[static_cast<size_t>(j)]));
      }
    }
  }
  *final_out = std::move(*live[n - 1]);
}

}  // namespace

}  // namespace veles

// ---------------------------------------------------------------------------
// C ABI

struct vi_model {
  std::vector<std::unique_ptr<veles::Unit>> units;
  std::vector<int> input_shape;
  size_t output_size = 0;
};

extern "C" {

const char *vi_last_error(void) { return veles::g_error.c_str(); }

vi_model *vi_load(const char *package_dir) {
  try {
    std::string dir(package_dir);
    std::ifstream fin(dir + "/contents.json");
    if (!fin) throw std::runtime_error("cannot open contents.json in " +
                                       dir);
    std::stringstream ss;
    ss << fin.rdbuf();
    veles::Json contents = veles::Json::Parse(ss.str());

    auto model = std::make_unique<vi_model>();
    // v2 added per-unit "inputs" (DAG fan-in); a NEWER format may
    // change semantics this reader cannot guess — refuse, don't garble
    if (contents.Has("format_version") &&
        contents["format_version"].AsInt() > 2)
      throw std::runtime_error(
          "package format v" +
          std::to_string(contents["format_version"].AsInt()) +
          " is newer than this runtime (v2)");
    model->input_shape = contents["input_shape"].AsIntVector();
    for (const auto &uj : contents["units"].arr) {
      auto unit = veles::MakeUnit(uj["type"].AsString(), uj["config"]);
      unit->name = uj["name"].AsString();
      unit->type = uj["type"].AsString();
      if (uj.Has("inputs"))
        for (const auto &nm : uj["inputs"].arr)
          unit->inputs.push_back(nm.AsString());
      for (const auto &kv : uj["params"].obj)
        unit->params[kv.first] =
            veles::LoadNpy(dir + "/" + kv.second.AsString());
      model->units.push_back(std::move(unit));
    }
    // probe output size with batch 1 (also validates the graph order)
    veles::Tensor probe, result;
    std::vector<int> shape = model->input_shape;
    shape[0] = 1;
    probe.Resize(shape);
    veles::ExecuteGraph(model->units, std::move(probe), &result);
    model->output_size = result.size();
    return model.release();
  } catch (const std::exception &e) {
    veles::g_error = e.what();
    return nullptr;
  }
}

size_t vi_input_size(const vi_model *m) {
  size_t n = 1;
  for (size_t i = 1; i < m->input_shape.size(); ++i)
    n *= static_cast<size_t>(m->input_shape[i]);
  return n;
}

size_t vi_output_size(const vi_model *m) { return m->output_size; }

size_t vi_unit_count(const vi_model *m) { return m->units.size(); }

const char *vi_unit_name(const vi_model *m, size_t idx) {
  return m->units[idx]->name.c_str();
}

const char *vi_unit_type(const vi_model *m, size_t idx) {
  return m->units[idx]->type.c_str();
}

int vi_generate(vi_model *m, const float *prompt, size_t t_p,
                int n_new, float *out_tokens) {
  // KV-cached greedy decoding with no Python: prefill fills each
  // block's (t_max, kv_d) caches one token at a time, then every new
  // token costs ONE cached step — the native twin of
  // veles_tpu/nn/sampling.generate (the --generate sliding-window
  // re-forward path stays for fixed-window PosEmbedding serving).
  try {
    if (t_p == 0) throw std::runtime_error("vi_generate: empty prompt");
    if (n_new <= 0)
      throw std::runtime_error("vi_generate: n_new must be >= 1");
    veles::Unit *stem = nullptr, *pe = nullptr, *head = nullptr;
    std::vector<veles::TransformerBlock *> blocks;
    for (auto &u : m->units) {
      if (u->type == "embedding" && stem == nullptr)
        stem = u.get();
      else if (u->type == "pos_embedding" && pe == nullptr)
        pe = u.get();
      else if (u->type == "transformer_block")
        blocks.push_back(static_cast<veles::TransformerBlock *>(u.get()));
      else if (u->type == "lm_head" && head == nullptr)
        head = u.get();
      else
        throw std::runtime_error(
            "cached generation supports embedding → [pos_embedding] → "
            "transformer_block* → lm_head chains; found " + u->type);
    }
    if (!stem || !head || blocks.empty())
      throw std::runtime_error(
          "cached generation: not a generation stack (stem/blocks/"
          "head missing)");
    for (auto *blk : blocks)
      if (!blk->causal)
        throw std::runtime_error(
            "cached generation requires causal blocks: one-token "
            "prefill can never let prompt positions see later tokens "
            "(block " + blk->name + " has causal=false — use vi_run)");
    const veles::NpyArray *table = stem->Param("table");
    int vocab = table->shape[0], d = table->shape[1];
    int t_max = static_cast<int>(t_p) + n_new;
    const veles::NpyArray *ptab = pe ? pe->Param("table") : nullptr;
    // highest position ever STEPPED is t_max - 2 (the final generated
    // token is emitted, never fed back), so t_max - 1 table rows
    // suffice — one row fewer than the python scan, which burns a
    // wasted final step (sampling.py _build_sampler)
    if (ptab && ptab->shape[0] < t_max - 1)
      throw std::runtime_error(
          "generation to " + std::to_string(t_max - 1) + " positions "
          "exceeds the pos_embedding table (" +
          std::to_string(ptab->shape[0]) + " rows); RoPE models "
          "generate open-endedly");
    const veles::NpyArray *hw = head->Param("weights");
    const veles::NpyArray *hb = head->Param("bias");
    int hv = hw->shape[1];
    std::vector<std::vector<float>> ck(blocks.size()), cv(blocks.size());
    std::vector<veles::TransformerBlock::StepState> st;
    st.reserve(blocks.size());
    for (size_t b = 0; b < blocks.size(); ++b) {
      auto *blk = blocks[b];
      int kv_h = blk->n_kv_heads > 0 ? blk->n_kv_heads : blk->n_heads;
      size_t kv_d = static_cast<size_t>(d / blk->n_heads) * kv_h;
      ck[b].assign(static_cast<size_t>(t_max) * kv_d, 0.0f);
      cv[b].assign(static_cast<size_t>(t_max) * kv_d, 0.0f);
      st.push_back(blk->PrepareStep(t_max));
    }
    std::vector<float> x(d), logits(hv);
    auto step_all = [&](float tok_id, int pos,
                        bool want_logits) -> int {
      int ti = static_cast<int>(std::lround(tok_id));
      ti = std::max(0, std::min(vocab - 1, ti));   // clip like Run
      const float *row = table->data.data() +
                         static_cast<size_t>(ti) * d;
      std::copy(row, row + d, x.begin());
      if (ptab)
        for (int i = 0; i < d; ++i)
          x[i] += ptab->data[static_cast<size_t>(pos) *
                             ptab->shape[1] + i];
      for (size_t b = 0; b < blocks.size(); ++b)
        blocks[b]->Step(st[b], x.data(), ck[b], cv[b], pos);
      if (!want_logits) return -1;
      veles::MatMulRM(x.data(), hw->data.data(), logits.data(),
                      1, d, hv);
      if (hb)
        for (int c = 0; c < hv; ++c) logits[c] += hb->data[c];
      int best = 0;
      for (int c = 1; c < hv; ++c)
        if (logits[c] > logits[best]) best = c;
      return best;
    };
    int next = -1;
    for (size_t i = 0; i < t_p; ++i)
      next = step_all(prompt[i], static_cast<int>(i), i + 1 == t_p);
    for (int j = 0; j < n_new; ++j) {
      out_tokens[j] = static_cast<float>(next);
      if (j + 1 < n_new)
        next = step_all(out_tokens[j],
                        static_cast<int>(t_p) + j, true);
    }
    return 0;
  } catch (const std::exception &e) {
    veles::g_error = e.what();
    return 1;
  }
}

int vi_run(vi_model *m, const float *in, size_t batch, float *out) {
  try {
    if (batch == 0) throw std::runtime_error("vi_run: empty batch");
    veles::Tensor cur, result;
    std::vector<int> shape = m->input_shape;
    shape[0] = static_cast<int>(batch);
    cur.Resize(shape);
    std::memcpy(cur.data.data(), in, sizeof(float) * cur.size());
    veles::ExecuteGraph(m->units, std::move(cur), &result);
    std::memcpy(out, result.data.data(), sizeof(float) * result.size());
    return 0;
  } catch (const std::exception &e) {
    veles::g_error = e.what();
    return 1;
  }
}

void vi_free(vi_model *m) { delete m; }

}  // extern "C"
