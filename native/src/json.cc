#include "json.h"

namespace veles {
namespace {

struct Parser {
  const std::string &s;
  size_t pos = 0;

  explicit Parser(const std::string &text) : s(text) {}

  void SkipWs() {
    while (pos < s.size() && (s[pos] == ' ' || s[pos] == '\n' ||
                              s[pos] == '\t' || s[pos] == '\r'))
      ++pos;
  }

  char Peek() {
    SkipWs();
    if (pos >= s.size()) throw std::runtime_error("json: eof");
    return s[pos];
  }

  void Expect(char c) {
    if (Peek() != c)
      throw std::runtime_error(std::string("json: expected ") + c);
    ++pos;
  }

  Json Value() {
    char c = Peek();
    if (c == '{') return Object();
    if (c == '[') return Array();
    if (c == '"') return String();
    if (c == 't' || c == 'f') return Boolean();
    if (c == 'n') { pos += 4; return Json(); }
    return Number();
  }

  Json Object() {
    Json j;
    j.type = Json::Type::Object;
    Expect('{');
    if (Peek() == '}') { ++pos; return j; }
    while (true) {
      Json key = String();
      Expect(':');
      j.obj[key.str] = Value();
      char c = Peek();
      ++pos;
      if (c == '}') break;
      if (c != ',') throw std::runtime_error("json: bad object");
    }
    return j;
  }

  Json Array() {
    Json j;
    j.type = Json::Type::Array;
    Expect('[');
    if (Peek() == ']') { ++pos; return j; }
    while (true) {
      j.arr.push_back(Value());
      char c = Peek();
      ++pos;
      if (c == ']') break;
      if (c != ',') throw std::runtime_error("json: bad array");
    }
    return j;
  }

  Json String() {
    Json j;
    j.type = Json::Type::String;
    Expect('"');
    while (pos < s.size() && s[pos] != '"') {
      char c = s[pos++];
      if (c == '\\' && pos < s.size()) {
        char e = s[pos++];
        switch (e) {
          case 'n': c = '\n'; break;
          case 't': c = '\t'; break;
          case 'r': c = '\r'; break;
          case 'u': {  // keep it simple: latin-1 subset
            int code = std::stoi(s.substr(pos, 4), nullptr, 16);
            pos += 4;
            c = static_cast<char>(code);
            break;
          }
          default: c = e;
        }
      }
      j.str.push_back(c);
    }
    ++pos;  // closing quote
    return j;
  }

  Json Boolean() {
    Json j;
    j.type = Json::Type::Bool;
    if (s.compare(pos, 4, "true") == 0) { j.bval = true; pos += 4; }
    else { j.bval = false; pos += 5; }
    return j;
  }

  Json Number() {
    size_t end = pos;
    while (end < s.size() && (isdigit(s[end]) || s[end] == '-' ||
                              s[end] == '+' || s[end] == '.' ||
                              s[end] == 'e' || s[end] == 'E'))
      ++end;
    Json j;
    j.type = Json::Type::Number;
    j.num = std::stod(s.substr(pos, end - pos));
    pos = end;
    return j;
  }
};

}  // namespace

Json Json::Parse(const std::string &text) {
  Parser p(text);
  return p.Value();
}

}  // namespace veles
