// Minimal .npy (v1/v2) reader — the equivalent of the reference's
// NumpyArrayLoader (libVeles/src/numpy_array_loader.cc:1-250): parses the
// header dict (dtype, fortran flag, shape) and yields float32 data.
#ifndef VELES_NPY_H_
#define VELES_NPY_H_

#include <string>
#include <vector>

namespace veles {

struct NpyArray {
  std::vector<int> shape;
  std::vector<float> data;  // always converted to float32, C order

  size_t size() const {
    size_t n = 1;
    for (int d : shape) n *= static_cast<size_t>(d);
    return n;
  }
};

// Throws std::runtime_error on malformed files / unsupported dtypes.
NpyArray LoadNpy(const std::string &path);

}  // namespace veles

#endif  // VELES_NPY_H_
