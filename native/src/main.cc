// veles_infer CLI: run an exported package on a .npy input batch.
// Usage: veles_infer <package_dir> <input.npy> <output.npy>
//        veles_infer --generate N <package_dir> <prompt.npy> <out.npy>
// (the libVeles equivalent of a standalone Workflow::Run driver;
// --generate is native greedy LM decoding: the prompt is one full
// model window of token ids, each step re-forwards the SLIDING window
// and appends the argmax of the last position's logits — serving an
// exported language model with no Python runtime at all)

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <string>
#include <vector>

#include "../include/veles_infer.h"
#include "npy.h"

namespace {

void SaveNpyF32(const std::string &path, const std::vector<int> &shape,
                const float *data, size_t n) {
  std::string header = "{'descr': '<f4', 'fortran_order': False, "
                       "'shape': (";
  for (size_t i = 0; i < shape.size(); ++i) {
    header += std::to_string(shape[i]);
    if (shape.size() == 1 || i + 1 < shape.size()) header += ", ";
  }
  header += "), }";
  size_t total = 10 + header.size() + 1;
  size_t pad = (64 - total % 64) % 64;
  header += std::string(pad, ' ');
  header += '\n';

  std::ofstream fout(path, std::ios::binary);
  fout.write("\x93NUMPY\x01\x00", 8);
  uint16_t len = static_cast<uint16_t>(header.size());
  fout.write(reinterpret_cast<const char *>(&len), 2);
  fout.write(header.data(), header.size());
  fout.write(reinterpret_cast<const char *>(data),
             static_cast<std::streamsize>(sizeof(float) * n));
}

int Generate(int n_new, const char *pkg, const char *prompt_path,
             const char *out_path) {
  vi_model *model = vi_load(pkg);
  if (!model) {
    std::fprintf(stderr, "load failed: %s\n", vi_last_error());
    return 1;
  }
  veles::NpyArray prompt = veles::LoadNpy(prompt_path);
  size_t t = vi_input_size(model);        // window length in token ids
  if (prompt.size() != t) {
    std::fprintf(stderr,
                 "prompt holds %zu ids; the model window is %zu "
                 "(pass one full window)\n",
                 prompt.size(), t);
    vi_free(model);
    return 1;
  }
  if (t == 0 || vi_output_size(model) % t != 0 ||
      vi_output_size(model) / t < 2) {
    std::fprintf(stderr,
                 "--generate needs a per-position LM package "
                 "(output %zu is not vocab x window %zu)\n",
                 vi_output_size(model), t);
    vi_free(model);
    return 1;
  }
  size_t vocab = vi_output_size(model) / t;
  std::vector<float> window(prompt.data.begin(), prompt.data.end());
  std::vector<float> logits(vi_output_size(model));
  std::vector<float> generated;
  generated.reserve(static_cast<size_t>(n_new));
  for (int step = 0; step < n_new; ++step) {
    if (vi_run(model, window.data(), 1, logits.data())) {
      std::fprintf(stderr, "run failed: %s\n", vi_last_error());
      vi_free(model);
      return 1;
    }
    const float *last = logits.data() + (t - 1) * vocab;
    size_t best = 0;
    for (size_t c = 1; c < vocab; ++c)
      if (last[c] > last[best]) best = c;
    // slide: drop the oldest id, append the new one
    window.erase(window.begin());
    window.push_back(static_cast<float>(best));
    generated.push_back(static_cast<float>(best));
  }
  std::vector<int> shape = {n_new};
  SaveNpyF32(out_path, shape, generated.data(), generated.size());
  std::fprintf(stderr, "OK: generated %d tokens (window %zu, vocab %zu)\n",
               n_new, t, vocab);
  vi_free(model);
  return 0;
}

int GenerateCached(int n_new, const char *pkg, const char *prompt_path,
                   const char *out_path) {
  // KV-cached greedy decoding (vi_generate): any prompt length, one
  // cached step per new token — the native twin of the python cached
  // sampler, vs --generate's fixed-window sliding re-forward
  vi_model *model = vi_load(pkg);
  if (!model) {
    std::fprintf(stderr, "load failed: %s\n", vi_last_error());
    return 1;
  }
  veles::NpyArray prompt = veles::LoadNpy(prompt_path);
  std::vector<float> generated(static_cast<size_t>(n_new));
  if (vi_generate(model, prompt.data.data(), prompt.size(), n_new,
                  generated.data())) {
    std::fprintf(stderr, "generate failed: %s\n", vi_last_error());
    vi_free(model);
    return 1;
  }
  std::vector<int> shape = {n_new};
  SaveNpyF32(out_path, shape, generated.data(), generated.size());
  std::fprintf(stderr,
               "OK: generated %d tokens (cached, prompt %zu)\n",
               n_new, prompt.size());
  vi_free(model);
  return 0;
}

}  // namespace

int main(int argc, char **argv) {
  bool cached = argc == 6 &&
                std::strcmp(argv[1], "--generate-cached") == 0;
  if (argc == 6 &&
      (cached || std::strcmp(argv[1], "--generate") == 0)) {
    int n_new = std::atoi(argv[2]);
    if (n_new <= 0) {
      std::fprintf(stderr, "--generate needs a positive token count\n");
      return 2;
    }
    return cached ? GenerateCached(n_new, argv[3], argv[4], argv[5])
                  : Generate(n_new, argv[3], argv[4], argv[5]);
  }
  if (argc != 4) {
    std::fprintf(stderr,
                 "usage: %s <package_dir> <input.npy> <output.npy>\n"
                 "       %s --generate N <package_dir> <prompt.npy> "
                 "<out.npy>   (sliding full-window re-forward)\n"
                 "       %s --generate-cached N <package_dir> "
                 "<prompt.npy> <out.npy>   (KV-cached; any prompt "
                 "length)\n",
                 argv[0], argv[0], argv[0]);
    return 2;
  }
  vi_model *model = vi_load(argv[1]);
  if (!model) {
    std::fprintf(stderr, "load failed: %s\n", vi_last_error());
    return 1;
  }
  veles::NpyArray input = veles::LoadNpy(argv[2]);
  size_t batch = static_cast<size_t>(input.shape[0]);
  size_t per_sample = input.size() / batch;
  if (per_sample != vi_input_size(model)) {
    std::fprintf(stderr, "input size %zu != model input %zu\n",
                 per_sample, vi_input_size(model));
    vi_free(model);
    return 1;
  }
  std::vector<float> out(batch * vi_output_size(model));
  if (vi_run(model, input.data.data(), batch, out.data())) {
    std::fprintf(stderr, "run failed: %s\n", vi_last_error());
    vi_free(model);
    return 1;
  }
  std::vector<int> out_shape = {static_cast<int>(batch),
                                static_cast<int>(vi_output_size(model))};
  SaveNpyF32(argv[3], out_shape, out.data(), out.size());
  std::fprintf(stderr, "OK: %zu samples through %zu units\n", batch,
               vi_unit_count(model));
  vi_free(model);
  return 0;
}
