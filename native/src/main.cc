// veles_infer CLI: run an exported package on a .npy input batch.
// Usage: veles_infer <package_dir> <input.npy> <output.npy>
// (the libVeles equivalent of a standalone Workflow::Run driver)

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <string>
#include <vector>

#include "../include/veles_infer.h"
#include "npy.h"

namespace {

void SaveNpyF32(const std::string &path, const std::vector<int> &shape,
                const float *data, size_t n) {
  std::string header = "{'descr': '<f4', 'fortran_order': False, "
                       "'shape': (";
  for (size_t i = 0; i < shape.size(); ++i) {
    header += std::to_string(shape[i]);
    if (shape.size() == 1 || i + 1 < shape.size()) header += ", ";
  }
  header += "), }";
  size_t total = 10 + header.size() + 1;
  size_t pad = (64 - total % 64) % 64;
  header += std::string(pad, ' ');
  header += '\n';

  std::ofstream fout(path, std::ios::binary);
  fout.write("\x93NUMPY\x01\x00", 8);
  uint16_t len = static_cast<uint16_t>(header.size());
  fout.write(reinterpret_cast<const char *>(&len), 2);
  fout.write(header.data(), header.size());
  fout.write(reinterpret_cast<const char *>(data),
             static_cast<std::streamsize>(sizeof(float) * n));
}

}  // namespace

int main(int argc, char **argv) {
  if (argc != 4) {
    std::fprintf(stderr,
                 "usage: %s <package_dir> <input.npy> <output.npy>\n",
                 argv[0]);
    return 2;
  }
  vi_model *model = vi_load(argv[1]);
  if (!model) {
    std::fprintf(stderr, "load failed: %s\n", vi_last_error());
    return 1;
  }
  veles::NpyArray input = veles::LoadNpy(argv[2]);
  size_t batch = static_cast<size_t>(input.shape[0]);
  size_t per_sample = input.size() / batch;
  if (per_sample != vi_input_size(model)) {
    std::fprintf(stderr, "input size %zu != model input %zu\n",
                 per_sample, vi_input_size(model));
    vi_free(model);
    return 1;
  }
  std::vector<float> out(batch * vi_output_size(model));
  if (vi_run(model, input.data.data(), batch, out.data())) {
    std::fprintf(stderr, "run failed: %s\n", vi_last_error());
    vi_free(model);
    return 1;
  }
  std::vector<int> out_shape = {static_cast<int>(batch),
                                static_cast<int>(vi_output_size(model))};
  SaveNpyF32(argv[3], out_shape, out.data(), out.size());
  std::fprintf(stderr, "OK: %zu samples through %zu units\n", batch,
               vi_unit_count(model));
  vi_free(model);
  return 0;
}
