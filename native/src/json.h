// Minimal JSON parser for contents.json (the reference vendored rapidjson,
// libVeles .gitmodules; this runtime keeps zero external dependencies).
#ifndef VELES_JSON_H_
#define VELES_JSON_H_

#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace veles {

class Json {
 public:
  enum class Type { Null, Bool, Number, String, Array, Object };

  Type type = Type::Null;
  bool bval = false;
  double num = 0;
  std::string str;
  std::vector<Json> arr;
  std::map<std::string, Json> obj;

  static Json Parse(const std::string &text);

  bool Has(const std::string &key) const {
    return type == Type::Object && obj.count(key) > 0;
  }
  const Json &operator[](const std::string &key) const {
    auto it = obj.find(key);
    if (it == obj.end()) throw std::runtime_error("missing key " + key);
    return it->second;
  }
  const Json &operator[](size_t i) const { return arr.at(i); }
  int AsInt() const { return static_cast<int>(num); }
  double AsDouble() const { return num; }
  bool AsBool() const { return type == Type::Bool ? bval : num != 0; }
  const std::string &AsString() const { return str; }
  std::vector<int> AsIntVector() const {
    std::vector<int> out;
    for (const auto &v : arr) out.push_back(v.AsInt());
    return out;
  }
};

}  // namespace veles

#endif  // VELES_JSON_H_
