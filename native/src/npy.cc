#include "npy.h"

#include <cstdint>
#include <cstring>
#include <fstream>
#include <stdexcept>

namespace veles {
namespace {

std::string ReadFile(const std::string &path) {
  std::ifstream fin(path, std::ios::binary);
  if (!fin) throw std::runtime_error("npy: cannot open " + path);
  return std::string(std::istreambuf_iterator<char>(fin),
                     std::istreambuf_iterator<char>());
}

// Extract "'key': value" fields from the python-dict header.
std::string HeaderField(const std::string &header, const std::string &key) {
  size_t k = header.find("'" + key + "'");
  if (k == std::string::npos)
    throw std::runtime_error("npy: header missing " + key);
  size_t colon = header.find(':', k);
  size_t end = colon + 1;
  int depth = 0;
  while (end < header.size()) {
    char c = header[end];
    if (c == '(' || c == '[') ++depth;
    if (c == ')' || c == ']') --depth;
    if ((c == ',' && depth == 0) || c == '}') break;
    ++end;
  }
  return header.substr(colon + 1, end - colon - 1);
}

template <typename T>
void Convert(const char *raw, size_t n, std::vector<float> *out) {
  const T *src = reinterpret_cast<const T *>(raw);
  out->resize(n);
  for (size_t i = 0; i < n; ++i) (*out)[i] = static_cast<float>(src[i]);
}

}  // namespace

NpyArray LoadNpy(const std::string &path) {
  std::string blob = ReadFile(path);
  if (blob.size() < 10 || blob.compare(1, 5, "NUMPY") != 0)
    throw std::runtime_error("npy: bad magic in " + path);
  uint8_t major = static_cast<uint8_t>(blob[6]);
  size_t header_len, header_off;
  if (major == 1) {
    uint16_t len;
    std::memcpy(&len, blob.data() + 8, 2);
    header_len = len;
    header_off = 10;
  } else {
    uint32_t len;
    std::memcpy(&len, blob.data() + 8, 4);
    header_len = len;
    header_off = 12;
  }
  std::string header = blob.substr(header_off, header_len);

  if (HeaderField(header, "fortran_order").find("True") !=
      std::string::npos)
    throw std::runtime_error("npy: fortran order unsupported: " + path);

  NpyArray arr;
  std::string shape = HeaderField(header, "shape");
  for (size_t i = 0; i < shape.size();) {
    if (isdigit(shape[i])) {
      size_t end = i;
      while (end < shape.size() && isdigit(shape[end])) ++end;
      arr.shape.push_back(std::stoi(shape.substr(i, end - i)));
      i = end;
    } else {
      ++i;
    }
  }

  std::string descr = HeaderField(header, "descr");
  const char *data = blob.data() + header_off + header_len;
  size_t n = arr.size();
  size_t avail = blob.size() - header_off - header_len;
  auto need = [&](size_t esz) {
    if (avail < n * esz)
      throw std::runtime_error("npy: truncated " + path);
  };
  if (descr.find("<f4") != std::string::npos ||
      descr.find("|f4") != std::string::npos) {
    need(4);
    Convert<float>(data, n, &arr.data);
  } else if (descr.find("<f8") != std::string::npos) {
    need(8);
    Convert<double>(data, n, &arr.data);
  } else if (descr.find("<i4") != std::string::npos) {
    need(4);
    Convert<int32_t>(data, n, &arr.data);
  } else if (descr.find("<i8") != std::string::npos) {
    need(8);
    Convert<int64_t>(data, n, &arr.data);
  } else if (descr.find("|u1") != std::string::npos) {
    need(1);
    Convert<uint8_t>(data, n, &arr.data);
  } else {
    throw std::runtime_error("npy: unsupported dtype " + descr + " in " +
                             path);
  }
  return arr;
}

}  // namespace veles
