/* veles_infer — standalone C++ inference runtime for veles_tpu packages.
 *
 * The TPU-era equivalent of the reference's libVeles (WorkflowLoader::Load
 * / Workflow::Run / UnitFactory, libVeles/inc/veles/*.h): loads a package
 * directory (contents.json + .npy parameters, written by
 * veles_tpu.export.package_export) and executes the forward chain on the
 * host, no Python required. C ABI so ctypes/cffi can bind it.
 */
#ifndef VELES_INFER_H_
#define VELES_INFER_H_

#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct vi_model vi_model;

/* Load a package directory. Returns NULL on failure (see vi_last_error). */
vi_model *vi_load(const char *package_dir);

/* Input element count per sample (product of input_shape[1:]). */
size_t vi_input_size(const vi_model *m);

/* Output element count per sample, for a given batch of 1. */
size_t vi_output_size(const vi_model *m);

/* Run the forward chain: in = batch*vi_input_size floats, out must hold
 * batch*vi_output_size floats. Returns 0 on success. */
int vi_run(vi_model *m, const float *in, size_t batch, float *out);

/* KV-cached greedy decoding for an LM package (embedding →
 * [pos_embedding] → transformer_block* → lm_head): prompt = t_p token
 * ids (as floats), out_tokens must hold n_new floats. Any prompt
 * length >= 1; RoPE models generate open-endedly, pos_embedding models
 * up to their table length. Each new token costs ONE cached step (the
 * --generate sliding-window re-forward costs a full window). Returns 0
 * on success. */
int vi_generate(vi_model *m, const float *prompt, size_t t_p,
                int n_new, float *out_tokens);

/* Number of units in the chain. */
size_t vi_unit_count(const vi_model *m);

const char *vi_unit_name(const vi_model *m, size_t idx);
const char *vi_unit_type(const vi_model *m, size_t idx);

const char *vi_last_error(void);

void vi_free(vi_model *m);

#ifdef __cplusplus
}
#endif

#endif /* VELES_INFER_H_ */
