file(REMOVE_RECURSE
  "CMakeFiles/veles_infer_cli.dir/src/main.cc.o"
  "CMakeFiles/veles_infer_cli.dir/src/main.cc.o.d"
  "CMakeFiles/veles_infer_cli.dir/src/npy.cc.o"
  "CMakeFiles/veles_infer_cli.dir/src/npy.cc.o.d"
  "veles_infer"
  "veles_infer.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/veles_infer_cli.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
