# Empty compiler generated dependencies file for veles_infer_cli.
# This may be replaced when dependencies are built.
