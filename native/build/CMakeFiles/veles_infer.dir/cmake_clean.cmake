file(REMOVE_RECURSE
  "CMakeFiles/veles_infer.dir/src/json.cc.o"
  "CMakeFiles/veles_infer.dir/src/json.cc.o.d"
  "CMakeFiles/veles_infer.dir/src/model.cc.o"
  "CMakeFiles/veles_infer.dir/src/model.cc.o.d"
  "CMakeFiles/veles_infer.dir/src/npy.cc.o"
  "CMakeFiles/veles_infer.dir/src/npy.cc.o.d"
  "libveles_infer.pdb"
  "libveles_infer.so"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/veles_infer.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
