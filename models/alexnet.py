"""AlexNet — mcdnnic-topology convolutional classifier.

TPU-native rebuild of the VELES "AlexNet" sample (reference zoo,
docs/source/manualrst_veles_algorithms.rst:49: "AlexNet/
imagenet_workflow.py" in the conv-net family). The reference authored
AlexNet-style nets via the ``mcdnnic_topology`` shorthand
(docs/source/manualrst_veles_workflow_parameters.rst) — this model is
the zoo member exercising that authoring path end-to-end: the whole
conv-pool-conv-pool-dense stack comes from one topology string, scaled
by an ``image_size`` knob (default 32 keeps CI affordable; 224 gives
the classic geometry for bench runs).

Data: the imagenet surrogate from veles_tpu.datasets (class-template
images — real ImageNet is absent in-image; BASELINE.md documents the
anchors).

Run: python models/alexnet.py [--epochs N] [--size 64]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy  # noqa: E402

import veles_tpu as vt  # noqa: E402
from veles_tpu import nn  # noqa: E402
from veles_tpu.datasets import load_synthetic  # noqa: E402
from veles_tpu.loader import FullBatchLoader  # noqa: E402

N_CLASSES = 10


class SyntheticImagenet(FullBatchLoader):
    hide_from_registry = True

    def __init__(self, workflow, image_size=32, n_train=1600,
                 n_valid=320, **kwargs):
        super().__init__(workflow, **kwargs)
        self.image_size = image_size
        self.n_train, self.n_valid = n_train, n_valid

    def load_data(self):
        tx, ty, vx, vy = load_synthetic(
            (self.image_size, self.image_size, 3), N_CLASSES,
            self.n_train, self.n_valid, flat=False, key="alexnet")
        self.create_originals(numpy.concatenate([vx, tx]),
                              numpy.concatenate([vy, ty]))
        self.class_lengths = [0, self.n_valid, self.n_train]


def topology(image_size: int) -> str:
    """AlexNet-shaped stack scaled to the input size: two conv+pool
    stages and two dense layers at CI scale, the full five-conv stack
    at >= 96 px."""
    if image_size >= 96:
        return ("3x%dx%d-48C7-MP2-128C5-MP2-192C3-192C3-128C3-MP2-"
                "512N-512N-%dN" % (image_size, image_size, N_CLASSES))
    return ("3x%dx%d-16C5-MP2-32C3-MP2-64N-%dN"
            % (image_size, image_size, N_CLASSES))


def build_workflow(epochs=10, minibatch_size=64, lr=0.001, image_size=32,
                   n_train=1600, n_valid=320):
    loader = SyntheticImagenet(None, image_size=image_size,
                               n_train=n_train, n_valid=n_valid,
                               minibatch_size=minibatch_size,
                               name="imagenet")
    wf = nn.StandardWorkflow(
        name="alexnet",
        mcdnnic_topology=topology(image_size),
        mcdnnic_parameters={"learning_rate": lr, "solver": "adam"},
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=epochs, fail_iterations=40),
    )
    return wf


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--mb", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.001)
    p.add_argument("--size", type=int, default=32)
    p.add_argument("--backend", default="auto")
    args = p.parse_args(argv)

    wf = build_workflow(args.epochs, args.mb, args.lr, args.size)
    wf.initialize(device=vt.Device_for(args.backend))
    t0 = time.time()
    wf.run()
    dt = time.time() - t0
    res = wf.gather_results()
    print("best validation error: %.4f (epoch %d)" %
          (res["best_err"], res["best_epoch"]))
    print("throughput: %.0f samples/sec" %
          (wf.loader.samples_served / dt))
    return res


if __name__ == "__main__":
    main()
