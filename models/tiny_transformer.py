"""Tiny transformer — sequence-classification zoo member.

New-capability showcase beyond the reference zoo (its sequence models
were Znicz RNN/LSTM; SURVEY.md §5.7): a stack of identical fused
pre-LN TransformerBlocks + positional embedding + mean-pool head. The
identical-block shape means the same model pipelines over
``--mesh pipeline=N`` and sequence-shards over ``--mesh sequence=N``
with no changes here.

Task (generated; real anchor like models/lines.py): classify the
ORDER of two marker bursts in the sequence — position-dependent, so
the positional embedding is load-bearing, and attention must relate
the two marker positions.

Run: python models/tiny_transformer.py [--epochs N]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy  # noqa: E402

import veles_tpu as vt  # noqa: E402
from veles_tpu import nn  # noqa: E402
from veles_tpu.loader import FullBatchLoader  # noqa: E402

SEQ_LEN = 16
DIM = 32
N_CLASSES = 2       # marker A before B, or B before A


class OrderLoader(FullBatchLoader):
    hide_from_registry = True

    def __init__(self, workflow, n_train=2560, n_valid=512, **kwargs):
        super().__init__(workflow, **kwargs)
        self.n_train, self.n_valid = n_train, n_valid

    def load_data(self):
        rng = numpy.random.RandomState(23)
        n = self.n_valid + self.n_train
        x = 0.2 * rng.randn(n, SEQ_LEN, DIM).astype(numpy.float32)
        y = rng.randint(0, 2, n).astype(numpy.int32)
        for i in range(n):
            pa, pb = sorted(rng.choice(SEQ_LEN, 2, replace=False))
            first, second = (pa, pb) if y[i] == 0 else (pb, pa)
            x[i, first, :8] += 1.0       # marker A at `first`
            x[i, second, 8:16] += 1.0    # marker B at `second`
        self.create_originals(x, y)
        self.class_lengths = [0, self.n_valid, self.n_train]


def build_workflow(epochs=20, minibatch_size=64, lr=0.003, n_blocks=4,
                   n_train=2560, n_valid=512):
    loader = OrderLoader(None, n_train=n_train, n_valid=n_valid,
                         minibatch_size=minibatch_size, name="order")
    layers = ([{"type": "pos_embedding", "solver": "adam",
                "learning_rate": lr}]
              + [{"type": "transformer_block", "n_heads": 4,
                  "ffn_hidden": 64, "causal": False,
                  "solver": "adam", "learning_rate": lr,
                  "name": "blk%d" % i} for i in range(n_blocks)]
              + [{"type": "mean_pool"},
                 {"type": "softmax", "output_sample_shape": N_CLASSES,
                  "solver": "adam", "learning_rate": lr}])
    wf = nn.StandardWorkflow(
        name="tiny-transformer",
        layers=layers, loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=epochs, fail_iterations=50),
    )
    return wf


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=20)
    p.add_argument("--mb", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.003)
    p.add_argument("--blocks", type=int, default=4)
    p.add_argument("--backend", default="auto")
    args = p.parse_args(argv)

    wf = build_workflow(args.epochs, args.mb, args.lr, args.blocks)
    wf.initialize(device=vt.Device_for(args.backend))
    t0 = time.time()
    wf.run()
    dt = time.time() - t0
    res = wf.gather_results()
    print("best validation error: %.4f (epoch %d)" %
          (res["best_err"], res["best_epoch"]))
    print("throughput: %.0f samples/sec" %
          (wf.loader.samples_served / dt))
    return res


if __name__ == "__main__":
    main()
