"""CIFAR-10 convolutional workflow — BASELINE config #4.

TPU-native rebuild of the Znicz AlexNet/CIFAR sample (reference target:
17.21 % validation error with the caffe config,
docs/source/manualrst_veles_algorithms.rst:50). Layer stack follows the
caffe cifar10_quick recipe the reference shipped; NHWC + MXU convs.

Run: python models/cifar.py [--epochs N] [--mb N] [--data-par N]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy  # noqa: E402

import veles_tpu as vt  # noqa: E402
from veles_tpu import nn, datasets  # noqa: E402
from veles_tpu.loader import FullBatchLoader  # noqa: E402


class CifarLoader(FullBatchLoader):
    """50k train / 10k validation NHWC images, mean-normalized."""

    hide_from_registry = True

    def load_data(self):
        tx, ty, vx, vy = datasets.load_cifar10()
        mean = tx.mean(axis=0)
        data = numpy.concatenate([vx, tx]) - mean
        labels = numpy.concatenate([vy, ty])
        self.create_originals(data, labels)
        self.class_lengths = [0, len(vx), len(tx)]


def caffe_quick_layers(lr):
    """The caffe cifar10_quick stack the reference shipped — shared by
    the CIFAR and STL-10 builders (the reference trained the same
    workflow shape on both)."""
    return [
        {"type": "conv", "n_kernels": 32, "kx": 5, "ky": 5,
         "padding": (2, 2, 2, 2), "learning_rate": lr,
         "weights_decay": 1e-4},
        {"type": "max_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
        {"type": "activation_str"},
        {"type": "conv_relu", "n_kernels": 32, "kx": 5, "ky": 5,
         "padding": (2, 2, 2, 2), "learning_rate": lr,
         "weights_decay": 1e-4},
        {"type": "avg_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
        {"type": "conv_relu", "n_kernels": 64, "kx": 5, "ky": 5,
         "padding": (2, 2, 2, 2), "learning_rate": lr,
         "weights_decay": 1e-4},
        {"type": "avg_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
        {"type": "all2all", "output_sample_shape": 64,
         "learning_rate": lr, "weights_decay": 1e-4},
        {"type": "softmax", "output_sample_shape": 10,
         "learning_rate": lr, "weights_decay": 1e-4},
    ]


def build_workflow(epochs=30, minibatch_size=100, lr=0.001,
                   data_par=1):
    loader = CifarLoader(None, minibatch_size=minibatch_size, name="cifar")
    wf = nn.StandardWorkflow(
        name="cifar-conv",
        layers=caffe_quick_layers(lr), loader_unit=loader,
        loss_function="softmax",
        decision_config=dict(max_epochs=epochs, fail_iterations=100),
        lr_schedule=nn.step_exp(0.5, 20),
    )
    return wf


class Stl10Loader(FullBatchLoader):
    """STL-10 geometry (96×96×3, 10 classes, 5k train / 8k test). Real
    STL-10 is absent in-image, so the class-template surrogate stands in
    (same policy as datasets.load_cifar10's fallback); the reference's
    anchor for the real data is 35.10 % validation error
    (docs/source/manualrst_veles_algorithms.rst:51)."""

    hide_from_registry = True

    def __init__(self, workflow, image_size=96, n_train=5000,
                 n_valid=800, **kwargs):
        super().__init__(workflow, **kwargs)
        self.image_size = image_size
        self.n_train, self.n_valid = n_train, n_valid

    def load_data(self):
        from veles_tpu.datasets import load_synthetic
        tx, ty, vx, vy = load_synthetic(
            (self.image_size, self.image_size, 3), 10, self.n_train,
            self.n_valid, flat=False, key="stl10")
        mean = tx.mean(axis=0)
        self.create_originals(numpy.concatenate([vx, tx]) - mean,
                              numpy.concatenate([vy, ty]))
        self.class_lengths = [0, self.n_valid, self.n_train]


def build_stl10_workflow(epochs=30, minibatch_size=50, lr=0.001,
                         image_size=96, n_train=5000, n_valid=800):
    """The conv family's second dataset (reference trained the same
    workflow shape on CIFAR and STL-10): identical caffe-quick stack,
    STL-10 geometry."""
    loader = Stl10Loader(None, image_size=image_size, n_train=n_train,
                         n_valid=n_valid,
                         minibatch_size=minibatch_size, name="stl10")
    return nn.StandardWorkflow(
        name="stl10-conv",
        layers=caffe_quick_layers(lr), loader_unit=loader,
        loss_function="softmax",
        decision_config=dict(max_epochs=epochs, fail_iterations=100),
        lr_schedule=nn.step_exp(0.5, 20),
    )


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=30)
    p.add_argument("--mb", type=int, default=100)
    p.add_argument("--lr", type=float, default=0.001)
    p.add_argument("--backend", default="auto")
    p.add_argument("--data-par", type=int, default=1,
                   help="size of the mesh 'data' axis")
    args = p.parse_args(argv)

    wf = build_workflow(args.epochs, args.mb, args.lr)
    device = (vt.XLADevice(mesh_axes={"data": args.data_par})
              if args.data_par > 1 else vt.Device_for(args.backend))
    wf.initialize(device=device)
    t0 = time.time()
    wf.run()
    dt = time.time() - t0
    res = wf.gather_results()
    print("dataset: %s CIFAR-10" %
          ("REAL" if datasets.cifar10_is_real() else "synthetic"))
    print("best validation error: %.4f (epoch %d)" %
          (res["best_err"], res["best_epoch"]))
    print("throughput: %.0f samples/sec" %
          (wf.loader.samples_served / dt))
    return res


if __name__ == "__main__":
    main()
