"""Character language model — next-token prediction zoo member.

New capability vs the reference (no language modeling anywhere in 2015
VELES): embedding → RoPE transformer stack → LM head, trained with
``loss_function="softmax_seq"`` (per-token cross-entropy on shifted
targets). The corpus is generated from a small deterministic grammar,
so the next-token structure is real and in-image (anchor like
models/lines.py); swap ``make_corpus`` for a file to train on text.

Identical-block stacks pipeline over ``--mesh pipeline=N`` and the
sequence axis shards over ``--mesh sequence=N`` unchanged.

Run: python models/char_lm.py [--epochs N]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy  # noqa: E402

import veles_tpu as vt  # noqa: E402
from veles_tpu import nn  # noqa: E402
from veles_tpu.loader import FullBatchLoaderMSE  # noqa: E402

SEQ_LEN = 32
VOCAB = 16


def make_corpus(rng, n_chars):
    """Markov-ish grammar: each symbol strongly prefers (s + 1) % 8 or
    a jump into the 8-15 'punctuation' range that returns to 0."""
    out = numpy.empty(n_chars, dtype=numpy.int32)
    s = 0
    for i in range(n_chars):
        out[i] = s
        r = rng.rand()
        if s < 8:
            s = (s + 1) % 8 if r < 0.8 else 8 + rng.randint(0, 8)
        else:
            s = 0 if r < 0.9 else 8 + rng.randint(0, 8)
    return out


class CharLMLoader(FullBatchLoaderMSE):
    hide_from_registry = True

    def __init__(self, workflow, n_train=1536, n_valid=256, **kwargs):
        super().__init__(workflow, **kwargs)
        self.n_train, self.n_valid = n_train, n_valid

    def load_data(self):
        rng = numpy.random.RandomState(41)
        n = self.n_valid + self.n_train
        corpus = make_corpus(rng, n * SEQ_LEN + 1)
        x = corpus[:-1].reshape(n, SEQ_LEN)
        y = corpus[1:].reshape(n, SEQ_LEN)       # next-token targets
        self.create_originals(x, None, targets=y)
        self.class_lengths = [0, self.n_valid, self.n_train]


def build_workflow(epochs=10, minibatch_size=64, lr=0.003, n_blocks=2,
                   dim=32, n_train=1536, n_valid=256, text_file=None,
                   seq_len=SEQ_LEN, arch="transformer"):
    """``text_file``: train on a real text file via TextFileLoader
    (vocab sized to the corpus) instead of the generated grammar.
    ``arch``: "transformer" (RoPE blocks), "lstm" (stacked
    return-sequences LSTMs) or "ssm" (gated linear-attention SSD
    blocks) — the recurrent families ride the same LM surface, so they
    get the same real-data quality gate AND the O(1)-state serving
    lane end-to-end."""
    if text_file:
        from veles_tpu.loader import TextFileLoader
        # one cheap scan for the vocabulary (embedding/head sizes need
        # it BEFORE the loader's load_data runs at initialize); passing
        # it back in pins the loader to the same table
        with open(text_file, "r", encoding="utf-8",
                  errors="replace") as f:
            chars = "".join(sorted(set(f.read())))
        loader = TextFileLoader(None, files=[text_file],
                                seq_len=seq_len, vocab=chars,
                                minibatch_size=minibatch_size,
                                name="chars")
        # vocab_size includes the loader's reserved unk slot — sizing
        # the embedding/head from len(chars) would put unk out of range
        vocab = loader.vocab_size
    else:
        loader = CharLMLoader(None, n_train=n_train, n_valid=n_valid,
                              minibatch_size=minibatch_size,
                              name="chars")
        vocab = VOCAB
    if arch not in ("transformer", "lstm", "ssm"):
        raise ValueError("arch must be 'transformer', 'lstm' or "
                         "'ssm', got %r" % (arch,))
    if arch == "lstm":
        body = [{"type": "lstm", "hidden_size": dim,
                 "return_sequences": True, "solver": "adam",
                 "learning_rate": lr, "name": "lstm%d" % i}
                for i in range(n_blocks)]
    elif arch == "ssm":
        body = [{"type": "ssm_block", "n_heads": 4, "solver": "adam",
                 "learning_rate": lr, "name": "ssm%d" % i}
                for i in range(n_blocks)]
    else:
        body = [{"type": "transformer_block", "n_heads": 4,
                 "ffn_hidden": 2 * dim, "causal": True, "rope": True,
                 "solver": "adam", "learning_rate": lr,
                 "name": "blk%d" % i} for i in range(n_blocks)]
    layers = ([{"type": "embedding", "vocab_size": vocab, "dim": dim,
                "solver": "adam", "learning_rate": lr}]
              + body
              + [{"type": "lm_head", "vocab_size": vocab,
                  "solver": "adam", "learning_rate": lr}])
    wf = nn.StandardWorkflow(
        name="char-lm", layers=layers, loader_unit=loader,
        loss_function="softmax_seq",
        decision_config=dict(max_epochs=epochs, fail_iterations=50),
    )
    return wf


class SyntheticTokenLoader(FullBatchLoaderMSE):
    """Random token streams at arbitrary (seq_len, vocab) — the LM
    throughput-bench surface (content does not affect throughput; the
    tiny int32 upload matters through the tunnel, unlike image data)."""

    hide_from_registry = True

    def __init__(self, workflow, seq_len=512, vocab=256, n_train=1024,
                 n_valid=128, **kwargs):
        super().__init__(workflow, **kwargs)
        self.seq_len, self.vocab = seq_len, vocab
        self.n_train, self.n_valid = n_train, n_valid

    def load_data(self):
        rng = numpy.random.RandomState(2027)
        n = self.n_valid + self.n_train
        stream = rng.randint(0, self.vocab, n * self.seq_len + 1,
                             dtype=numpy.int32)
        self.create_originals(stream[:-1].reshape(n, self.seq_len), None,
                              targets=stream[1:].reshape(n, self.seq_len))
        self.class_lengths = [0, self.n_valid, self.n_train]


def build_bench_workflow(seq_len=512, dim=512, n_blocks=6,
                         ffn_hidden=2048, n_heads=8, vocab=256,
                         minibatch_size=16, n_train=1024, n_valid=128,
                         lr=1e-4, epochs_per_dispatch=1):
    """GPT-style stack at throughput-bench scale (the modern-workload
    counterpart of the AE bench): token embedding → N pre-LN RoPE
    blocks → LM head, per-token CE. Sized so the matmuls dominate
    dispatch latency (~19M matmul params at the defaults)."""
    loader = SyntheticTokenLoader(
        None, seq_len=seq_len, vocab=vocab, n_train=n_train,
        n_valid=n_valid, minibatch_size=minibatch_size, name="lm-bench")
    layers = ([{"type": "embedding", "vocab_size": vocab, "dim": dim,
                "solver": "adam", "learning_rate": lr}]
              + [{"type": "transformer_block", "n_heads": n_heads,
                  "ffn_hidden": ffn_hidden, "causal": True, "rope": True,
                  "solver": "adam", "learning_rate": lr,
                  "name": "blk%d" % i} for i in range(n_blocks)]
              + [{"type": "lm_head", "vocab_size": vocab,
                  "solver": "adam", "learning_rate": lr}])
    return nn.StandardWorkflow(
        name="char-lm-bench", layers=layers, loader_unit=loader,
        loss_function="softmax_seq",
        decision_config=dict(max_epochs=10 ** 9,
                             fail_iterations=10 ** 9),
        steps_per_dispatch=n_train // minibatch_size,
        epochs_per_dispatch=epochs_per_dispatch,
    )


def generate(wf, prompt, n_new, temperature=1.0, seed=0):
    """Sample continuations from the trained causal stack via the
    KV-cached on-device sampler (nn/sampling.py: prefill + one
    lax.scan — a single dispatch end to end)."""
    from veles_tpu.nn import sampling
    return sampling.generate(wf, prompt, n_new, temperature=temperature,
                             seed=seed)


def generate_naive(wf, prompt, n_new, temperature=1.0, seed=0):
    """Reference sampler: re-forward the FULL growing sequence each
    step — O(T^2) per token and one retrace per length; kept as the
    oracle the KV-cached path is tested against
    (tests/test_transformer.py). RoPE has no trained-length cap, so the
    growing context needs no windowing."""
    import jax
    import jax.numpy as jnp
    params = {f.name: {k: v.device_view()
                       for k, v in f.param_arrays().items()}
              for f in wf.forwards if f.PARAMETERIZED}

    @jax.jit
    def logits_fn(tokens):
        x = tokens[None, :]
        for f in wf.forwards:
            x = f.apply(params.get(f.name, {}), x, train=False)
        return x[0, -1]

    key = jax.random.key(seed)
    toks = list(int(t) for t in prompt)
    for _ in range(n_new):
        logits = logits_fn(jnp.asarray(toks, dtype=jnp.int32))
        key, sub = jax.random.split(key)
        if temperature <= 0:
            nxt = int(jnp.argmax(logits))
        else:
            nxt = int(jax.random.categorical(sub, logits / temperature))
        toks.append(nxt)
    return toks[len(prompt):]


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--mb", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.003)
    p.add_argument("--blocks", type=int, default=2)
    p.add_argument("--sample", type=int, default=48,
                   help="tokens to sample after training (0 = skip)")
    p.add_argument("--text", default=None, metavar="FILE",
                   help="train on a real text file (TextFileLoader) "
                        "instead of the generated grammar")
    p.add_argument("--backend", default="auto")
    args = p.parse_args(argv)

    wf = build_workflow(args.epochs, args.mb, args.lr, args.blocks,
                        text_file=args.text)
    wf.initialize(device=vt.Device_for(args.backend))
    t0 = time.time()
    wf.run()
    dt = time.time() - t0
    res = wf.gather_results()
    print("best per-token error: %.4f (epoch %d)" %
          (res["best_err"], res["best_epoch"]))
    print("throughput: %.0f samples/sec" %
          (wf.loader.samples_served / dt))
    if args.sample:
        loader = wf.loader
        if args.text:
            # prompt with text that EXISTS in the corpus vocabulary —
            # encode() maps unknown chars to id 0, which would prompt
            # the model with something other than what we print
            seed_text = loader.decode(
                loader.original_data.mem[0][:8])
            prompt = list(loader.encode(seed_text))
            toks = generate(wf, prompt, args.sample, temperature=0.8)
            print("sample: %r" % loader.decode(prompt + toks))
        else:
            toks = generate(wf, [0, 1, 2], args.sample, temperature=0.8)
            print("sample:", " ".join(str(t) for t in toks))
    return res


if __name__ == "__main__":
    main()
