"""GeneticExample — the GA engine used directly, outside --optimize.

TPU-native rebuild of the reference's ``veles/samples/GeneticExample``
(docs/source/manualrst_veles_algorithms.rst: "Example of using Genetic
Algorithm for other purposes"): the genetics core optimizes an ordinary
function rather than a training config. The demo objective is the
2-D Rosenbrock valley (minimum f(1,1)=0 — deceptive curvature that
random search does not crack), plus an integer-gene knapsack variant
showing the ``ints`` gene mask. Exercises Population/Chromosome as a
public, model-free API; the hyper-parameter path (`--optimize`) is
models/../genetics/optimization.py.

Run: python models/genetic_example.py [--generations N]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy  # noqa: E402

from veles_tpu import prng  # noqa: E402
from veles_tpu.genetics.core import Population  # noqa: E402


def rosenbrock(x, y):
    return (1.0 - x) ** 2 + 100.0 * (y - x * x) ** 2


KNAPSACK_VALUES = numpy.array([6, 5, 8, 9, 6, 7, 3], dtype=float)
KNAPSACK_WEIGHTS = numpy.array([2, 3, 6, 7, 5, 9, 4], dtype=float)
KNAPSACK_CAP = 9.0       # optimum: items {0, 3} -> value 15, weight 9


def solve_rosenbrock(generations=60, size=40, seed=5):
    prng.seed_all(seed)
    pop = Population(mins=[-2.0, -2.0], maxs=[2.0, 2.0], size=size,
                     crossover="arithmetic", mutation_rate=0.3)

    def fitness(ch, _i):
        return -rosenbrock(ch.genes[0], ch.genes[1])

    for _ in range(generations):
        pop.evolve(fitness)
    best = pop.best
    return best.genes, -best.fitness


def solve_knapsack(generations=40, size=30, seed=5):
    """Integer genes in {0, 1}: take/leave per item, capacity penalty."""
    prng.seed_all(seed)
    n = len(KNAPSACK_VALUES)
    pop = Population(mins=[0.0] * n, maxs=[1.0] * n, ints=[True] * n,
                     size=size, crossover="uniform", mutation_rate=0.2)

    def fitness(ch, _i):
        take = numpy.round(ch.genes)
        weight = float(take @ KNAPSACK_WEIGHTS)
        value = float(take @ KNAPSACK_VALUES)
        return value if weight <= KNAPSACK_CAP else -weight

    for _ in range(generations):
        pop.evolve(fitness)
    best = pop.best
    return numpy.round(best.genes).astype(int), best.fitness


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--generations", type=int, default=60)
    args = p.parse_args(argv)

    genes, value = solve_rosenbrock(args.generations)
    print("rosenbrock: best (%.4f, %.4f), f=%.6f (optimum (1,1), 0)"
          % (genes[0], genes[1], value))
    take, fitness = solve_knapsack()
    print("knapsack: take=%s value=%.0f (optimum 15)"
          % (take.tolist(), fitness))
    return value, fitness


if __name__ == "__main__":
    main()
