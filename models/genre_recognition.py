"""LSTM sequence-classification workflow — BASELINE config #5.

TPU-native rebuild of the Znicz LSTM genre_recognition sample (reference:
music-genre classification over audio feature sequences; the audio
front-end used libsndfile, SURVEY.md §2.3). Feature sequences here come
from the datasets module (real features if cached, synthetic
genre-structured sequences otherwise); the model is LSTM → softmax under
one fused jitted step, recurrence via lax.scan.

Run: python models/genre_recognition.py [--epochs N]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy  # noqa: E402

import veles_tpu as vt  # noqa: E402
from veles_tpu import nn  # noqa: E402
from veles_tpu.loader import FullBatchLoader  # noqa: E402


N_GENRES = 6
SEQ_LEN = 64
N_FEATURES = 24


class GenreLoader(FullBatchLoader):
    """Synthetic genre-structured sequences: each genre is a distinct
    frequency/phase signature + noise (deterministic; real dataset drops
    in by overriding load_data)."""

    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(11)
        n_train, n_valid = 1800, 360
        freqs = rng.rand(N_GENRES, N_FEATURES) * 0.5 + 0.05
        phases = rng.rand(N_GENRES, N_FEATURES) * numpy.pi

        def make(n, seed):
            r = numpy.random.RandomState(seed)
            y = r.randint(0, N_GENRES, n).astype(numpy.int32)
            t = numpy.arange(SEQ_LEN)[None, :, None]
            x = numpy.sin(t * freqs[y][:, None, :] + phases[y][:, None, :])
            x = (x + 0.5 * r.randn(n, SEQ_LEN, N_FEATURES)).astype(
                numpy.float32)
            return x, y
        tx, ty = make(n_train, 1)
        vx, vy = make(n_valid, 2)
        self.create_originals(numpy.concatenate([vx, tx]),
                              numpy.concatenate([vy, ty]))
        self.class_lengths = [0, n_valid, n_train]


def build_workflow(epochs=15, minibatch_size=60, lr=0.05, hidden=64):
    loader = GenreLoader(None, minibatch_size=minibatch_size, name="genre")
    wf = nn.StandardWorkflow(
        name="genre-lstm",
        layers=[
            {"type": "lstm", "hidden_size": hidden, "learning_rate": lr},
            {"type": "softmax", "output_sample_shape": N_GENRES,
             "learning_rate": lr},
        ],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=epochs, fail_iterations=50),
    )
    return wf


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=15)
    p.add_argument("--mb", type=int, default=60)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--backend", default="auto")
    args = p.parse_args(argv)

    wf = build_workflow(args.epochs, args.mb, args.lr)
    wf.initialize(device=vt.Device_for(args.backend))
    t0 = time.time()
    wf.run()
    dt = time.time() - t0
    res = wf.gather_results()
    print("best validation error: %.4f (epoch %d)" %
          (res["best_err"], res["best_epoch"]))
    print("throughput: %.0f samples/sec" %
          (wf.loader.samples_served / dt))
    return res


if __name__ == "__main__":
    main()
