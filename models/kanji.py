"""Kanji — MSE net mapping noisy glyph renderings to clean templates.

TPU-native rebuild of the VELES "Kanji" sample (reference zoo,
docs/source/manualrst_veles_algorithms.rst:29: "MSE NN with standard
workflow help: Kanji/kanji.py"): the net sees a distorted rendering of a
glyph and regresses the CLEAN class template — loader-provided targets,
not labels. This is the one zoo member exercising
``target_mode="targets"`` through StandardWorkflow (imagenet_ae
reconstructs its *input*; char_lm's targets are token ids), so the
FullBatchLoaderMSE targets plumbing is load-bearing here.

Glyphs are generated: each class is a fixed set of random strokes on a
grid (kanji-like box/stroke structure), samples are shifted + noised
renderings. Fully synthetic by construction, like lines.py — the RMSE
gate is a real anchor, not a surrogate proxy.

Run: python models/kanji.py [--epochs N]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy  # noqa: E402

import veles_tpu as vt  # noqa: E402
from veles_tpu import nn  # noqa: E402
from veles_tpu.loader import FullBatchLoaderMSE  # noqa: E402

SIZE = 24
N_CLASSES = 12
STROKES_PER_GLYPH = 6


def make_templates(rng, n_classes=N_CLASSES, size=SIZE):
    """Per-class glyph template: horizontal/vertical strokes on a grid
    (the box-and-stroke structure of real kanji), values in [0, 1]."""
    templates = numpy.zeros((n_classes, size, size), dtype=numpy.float32)
    for c in range(n_classes):
        for _ in range(STROKES_PER_GLYPH):
            horizontal = rng.rand() < 0.5
            pos = rng.randint(2, size - 2)
            lo = rng.randint(0, size // 2)
            hi = rng.randint(size // 2, size)
            thickness = rng.randint(1, 3)
            if horizontal:
                templates[c, pos:pos + thickness, lo:hi] = 1.0
            else:
                templates[c, lo:hi, pos:pos + thickness] = 1.0
    return templates


def render(rng, template):
    """One distorted rendering: random shift + speckle noise + contrast
    jitter."""
    dy, dx = rng.randint(-2, 3, size=2)
    img = numpy.roll(numpy.roll(template, dy, axis=0), dx, axis=1)
    img = img * (0.7 + 0.3 * rng.rand()) + 0.25 * rng.rand(*img.shape)
    return numpy.clip(img, 0.0, 1.0).astype(numpy.float32)


class KanjiLoader(FullBatchLoaderMSE):
    hide_from_registry = True

    def __init__(self, workflow, n_train=2400, n_valid=480, **kwargs):
        super().__init__(workflow, **kwargs)
        self.n_train, self.n_valid = n_train, n_valid

    def load_data(self):
        rng = numpy.random.RandomState(43)
        self.templates = make_templates(rng)
        n = self.n_valid + self.n_train
        labels = rng.randint(0, N_CLASSES, n).astype(numpy.int32)
        data = numpy.stack([render(rng, self.templates[c])
                            for c in labels])
        targets = self.templates[labels].reshape(n, -1)
        self.create_originals(data.reshape(n, -1), labels, targets)
        self.class_lengths = [0, self.n_valid, self.n_train]


def build_workflow(epochs=12, minibatch_size=80, lr=0.005,
                   n_train=2400, n_valid=480, hidden=256):
    loader = KanjiLoader(None, n_train=n_train, n_valid=n_valid,
                         minibatch_size=minibatch_size, name="kanji")
    wf = nn.StandardWorkflow(
        name="kanji",
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": hidden,
             "solver": "adam", "learning_rate": lr},
            {"type": "all2all_tanh", "output_sample_shape": SIZE * SIZE,
             "solver": "adam", "learning_rate": lr},
        ],
        loader_unit=loader, loss_function="mse", target_mode="targets",
        decision_config=dict(max_epochs=epochs, fail_iterations=50),
    )
    return wf


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=12)
    p.add_argument("--mb", type=int, default=80)
    p.add_argument("--lr", type=float, default=0.005)
    p.add_argument("--backend", default="auto")
    args = p.parse_args(argv)

    wf = build_workflow(args.epochs, args.mb, args.lr)
    wf.initialize(device=vt.Device_for(args.backend))
    t0 = time.time()
    wf.run()
    dt = time.time() - t0
    res = wf.gather_results()
    print("best validation rmse: %.4f (epoch %d)" %
          (res["best_rmse"], res["best_epoch"]))
    print("throughput: %.0f samples/sec" %
          (wf.loader.samples_served / dt))
    return res


if __name__ == "__main__":
    main()
