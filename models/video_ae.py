"""VideoAE — fully-connected autoencoder over synthetic video frames.

TPU-native rebuild of the VELES "VideoAE" sample (reference zoo,
docs/source/manualrst_veles_algorithms.rst:70: "VideoAE/video_ae.py" in
the Autoencoder family). The reference's task: compress frames of a
video through an FC bottleneck and reconstruct them by MSE. Frames here
are generated — a bright square orbiting over a static background, so
consecutive frames share structure the bottleneck must find. Exercises
the *fully-connected* AE path (imagenet_ae covers the conv/deconv AE;
this is the `all2all` bottleneck with target_mode="input").

Run: python models/video_ae.py [--epochs N]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy  # noqa: E402

import veles_tpu as vt  # noqa: E402
from veles_tpu import nn  # noqa: E402
from veles_tpu.loader import FullBatchLoader  # noqa: E402

SIZE = 16


def make_frames(rng, n, size=SIZE):
    """n frames of a square orbiting a noisy static background."""
    background = 0.2 * rng.rand(size, size).astype(numpy.float32)
    frames = numpy.empty((n, size, size), dtype=numpy.float32)
    for i in range(n):
        t = 2.0 * numpy.pi * (i / 24.0 + rng.rand() / 24.0)
        cy = int(size / 2 + (size / 3) * numpy.sin(t))
        cx = int(size / 2 + (size / 3) * numpy.cos(t))
        f = background + 0.05 * rng.rand(size, size).astype(numpy.float32)
        f[max(cy - 2, 0):cy + 2, max(cx - 2, 0):cx + 2] = \
            0.8 + 0.2 * rng.rand()
        frames[i] = numpy.clip(f, 0.0, 1.0)
    return frames.reshape(n, -1)


class VideoLoader(FullBatchLoader):
    hide_from_registry = True

    def __init__(self, workflow, n_train=1920, n_valid=384, **kwargs):
        super().__init__(workflow, **kwargs)
        self.n_train, self.n_valid = n_train, n_valid

    def load_data(self):
        rng = numpy.random.RandomState(71)
        n = self.n_valid + self.n_train
        self.create_originals(make_frames(rng, n))
        self.class_lengths = [0, self.n_valid, self.n_train]


def build_workflow(epochs=10, minibatch_size=64, lr=0.02,
                   n_train=1920, n_valid=384, bottleneck=24):
    loader = VideoLoader(None, n_train=n_train, n_valid=n_valid,
                         minibatch_size=minibatch_size, name="video")
    wf = nn.StandardWorkflow(
        name="video_ae",
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 96,
             "solver": "adam", "learning_rate": lr},
            {"type": "all2all_tanh", "output_sample_shape": bottleneck,
             "solver": "adam", "learning_rate": lr},
            {"type": "all2all_tanh", "output_sample_shape": 96,
             "solver": "adam", "learning_rate": lr},
            {"type": "all2all_tanh", "output_sample_shape": SIZE * SIZE,
             "solver": "adam", "learning_rate": lr},
        ],
        loader_unit=loader, loss_function="mse", target_mode="input",
        decision_config=dict(max_epochs=epochs, fail_iterations=40),
    )
    return wf


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--mb", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.02)
    p.add_argument("--backend", default="auto")
    args = p.parse_args(argv)

    wf = build_workflow(args.epochs, args.mb, args.lr)
    wf.initialize(device=vt.Device_for(args.backend))
    t0 = time.time()
    wf.run()
    dt = time.time() - t0
    res = wf.gather_results()
    print("best validation rmse: %.4f (epoch %d)" %
          (res["best_rmse"], res["best_epoch"]))
    print("throughput: %.0f samples/sec" %
          (wf.loader.samples_served / dt))
    return res


if __name__ == "__main__":
    main()
