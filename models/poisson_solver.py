"""Poisson solver — the first non-NN model on the platform.

Conjugate gradient on the 5-point 2D Dirichlet Laplacian (n×n interior
grid, n² unknowns), expressed as a Workflow graph of Units
(veles_tpu/linalg/solvers.py): Repeater loop head, CGStep body,
CGDecision gating the back-edge — the same dataflow engine, telemetry
and fault planes every training model runs on (docs/workloads.md).
``--precondition`` arms the 2-level multigrid V-cycle (damped-Jacobi
smoothing around a Galerkin coarse grid factored once with the blocked
Cholesky), cutting the iteration count severalfold.

A finish that claims convergence is re-verified against the trusted
dense operator (``verify_residual``) — the run raises rather than
return a silently-wrong answer.

Run:  python models/poisson_solver.py [--n N] [--tol T] [--precondition]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy  # noqa: E402

from veles_tpu.linalg import (TwoLevelPoisson, build_cg_workflow,  # noqa: E402
                              poisson2d_matvec)


def build_workflow(n=64, tol=1e-6, max_iters=500, precondition=False,
                   rhs=None, seed=0, block=None, mesh=None):
    """CGWorkflow over the n×n Poisson operator. ``rhs=None`` draws a
    seeded random right-hand side (the model-problem default)."""
    if rhs is None:
        rhs = numpy.random.RandomState(seed).standard_normal(
            n * n).astype(numpy.float32)
    kwargs = {}
    if block is not None:
        kwargs["block"] = block
    precond = None
    if precondition:
        precond = TwoLevelPoisson(n, mesh=mesh,
                                  **({"block": block} if block else {}))
    return build_cg_workflow(poisson2d_matvec(n), rhs, tol=tol,
                             max_iters=max_iters, mesh=mesh,
                             preconditioner=precond, **kwargs)


def main():
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--n", type=int, default=64,
                        help="interior grid side (n^2 unknowns)")
    parser.add_argument("--tol", type=float, default=1e-6)
    parser.add_argument("--max-iters", type=int, default=500)
    parser.add_argument("--precondition", action="store_true",
                        help="2-level multigrid V-cycle (even --n)")
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args()
    wf = build_workflow(n=args.n, tol=args.tol,
                        max_iters=args.max_iters,
                        precondition=args.precondition, seed=args.seed)
    wf.initialize()
    wf.run()
    res = wf.cg_decision.get_metric_values()
    print("poisson %dx%d%s: %s in %d iteration(s), residual %.3e, "
          "true residual %s"
          % (args.n, args.n,
             " + multigrid" if args.precondition else "",
             "converged" if res["converged"] else "DID NOT CONVERGE",
             res["iterations"], res["residual"],
             "%.3e" % res["true_residual"]
             if res["true_residual"] is not None else "(unverified)"))
    return 0 if res["converged"] else 1


if __name__ == "__main__":
    sys.exit(main())
