"""Lines — synthetic line-orientation classification.

TPU-native rebuild of the VELES "Lines" demo (reference model zoo,
SURVEY.md §2.8 samples row: "MNIST, CIFAR, AlexNet, ImagenetAE, Lines,
kanji…"): classify which of 4 orientations (horizontal / vertical /
the two diagonals) a noisy line segment has. Fully synthetic by
construction — the one zoo member whose REAL dataset is a generator, so
its accuracy anchor is meaningful in-image. Uses the round-2 knobs:
per-layer adam solver + on-the-fly generated data.

Run: python models/lines.py [--epochs N]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy  # noqa: E402

import veles_tpu as vt  # noqa: E402
from veles_tpu import nn  # noqa: E402
from veles_tpu.config import root  # noqa: E402
from veles_tpu.genetics import Range  # noqa: E402
from veles_tpu.genetics.config import resolve as _cfg  # noqa: E402
from veles_tpu.loader import FullBatchLoader  # noqa: E402

SIZE = 16
N_CLASSES = 4       # horizontal, vertical, diag, anti-diag

# hyper-parameters live in the config tree so ``--optimize`` can search
# them through Range markers (the reference samples carried the same
# optimize-ready configs, e.g. veles/znicz samples' *_config.py).
# Plain runs collapse markers to defaults (materialize_defaults); the
# CLI re-applies root.lines.* overrides after this import.
root.lines.lr = Range(0.002, 0.0005, 0.01)
root.lines.mb = 80
root.lines.epochs = 10
root.lines.n_train = 2400
root.lines.n_valid = 480


def draw_line(rng, angle_class, size=SIZE):
    """One noisy line image (H, W, 1) in [0, 1]."""
    img = rng.rand(size, size).astype(numpy.float32) * 0.3
    c = size // 2 + rng.randint(-2, 3)
    thickness = rng.randint(1, 3)
    for t in range(-size, size):
        if angle_class == 0:
            y, x, dy, dx = c, c + t, 1, 0          # horizontal
        elif angle_class == 1:
            y, x, dy, dx = c + t, c, 0, 1          # vertical
        elif angle_class == 2:
            y, x, dy, dx = c + t, c + t, 0, 1      # diagonal
        else:
            y, x, dy, dx = c + t, c - t, 0, 1      # anti-diagonal
        # thickness grows PERPENDICULAR to the line (an offset along it
        # would redraw the same pixels, making thickness class-dependent
        # — a spurious intensity cue)
        for d in range(thickness):
            yy, xx = y + d * dy, x + d * dx
            if 0 <= yy < size and 0 <= xx < size:
                img[yy, xx] = 0.7 + 0.3 * rng.rand()
    return img[:, :, None]


class LinesLoader(FullBatchLoader):
    hide_from_registry = True

    def __init__(self, workflow, n_train=2400, n_valid=480, **kwargs):
        super().__init__(workflow, **kwargs)
        self.n_train, self.n_valid = n_train, n_valid

    def load_data(self):
        rng = numpy.random.RandomState(17)
        n = self.n_valid + self.n_train
        labels = rng.randint(0, N_CLASSES, n).astype(numpy.int32)
        data = numpy.stack([draw_line(rng, c) for c in labels])
        self.create_originals(data, labels)
        self.class_lengths = [0, self.n_valid, self.n_train]


def build_workflow(epochs=None, minibatch_size=None, lr=None,
                   n_train=None, n_valid=None):
    """Explicit arguments win; anything left None resolves from
    ``root.lines`` (where --optimize writes each candidate's genes)."""
    c = root.lines
    epochs = int(_cfg(c.epochs)) if epochs is None else epochs
    minibatch_size = (int(_cfg(c.mb)) if minibatch_size is None
                      else minibatch_size)
    lr = float(_cfg(c.lr)) if lr is None else lr
    n_train = int(_cfg(c.n_train)) if n_train is None else n_train
    n_valid = int(_cfg(c.n_valid)) if n_valid is None else n_valid
    loader = LinesLoader(None, n_train=n_train, n_valid=n_valid,
                         minibatch_size=minibatch_size, name="lines")
    wf = nn.StandardWorkflow(
        name="lines",
        layers=[
            {"type": "conv_relu", "n_kernels": 8, "kx": 5, "ky": 5,
             "padding": (2, 2, 2, 2), "solver": "adam",
             "learning_rate": lr},
            {"type": "max_pooling", "kx": 2, "ky": 2},
            {"type": "all2all_tanh", "output_sample_shape": 32,
             "solver": "adam", "learning_rate": lr},
            {"type": "softmax", "output_sample_shape": N_CLASSES,
             "solver": "adam", "learning_rate": lr},
        ],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=epochs, fail_iterations=50),
    )
    return wf


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--mb", type=int, default=None)
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--backend", default="auto")
    args = p.parse_args(argv)

    wf = build_workflow(args.epochs, args.mb, args.lr)
    wf.initialize(device=vt.Device_for(args.backend))
    t0 = time.time()
    wf.run()
    dt = time.time() - t0
    res = wf.gather_results()
    print("best validation error: %.4f (epoch %d)" %
          (res["best_err"], res["best_epoch"]))
    print("throughput: %.0f samples/sec" %
          (wf.loader.samples_served / dt))
    return res


if __name__ == "__main__":
    main()
