"""Convolutional autoencoder workflow — BASELINE config #3 (ImagenetAE).

TPU-native rebuild of the Znicz ImagenetAE sample (reference: conv+pool
encoder, deconv+depool decoder, MSE reconstruction; exercised the GEMM
path, SURVEY.md §2.8/§6). Trains to reconstruct its input
(target_mode="input"), reports RMSE like the reference's 0.5478 anchor
for the MNIST AE variant.

Run: python models/imagenet_ae.py [--epochs N] [--size N]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy  # noqa: E402

import veles_tpu as vt  # noqa: E402
from veles_tpu import nn, datasets  # noqa: E402
from veles_tpu.loader import FullBatchLoader  # noqa: E402


class AELoader(FullBatchLoader):
    hide_from_registry = True

    def __init__(self, workflow, image_size=32, n_train=4000, n_valid=800,
                 **kwargs):
        super().__init__(workflow, **kwargs)
        self.image_size = image_size
        self.n_train, self.n_valid = n_train, n_valid

    def load_data(self):
        tx, ty, vx, vy = datasets.load_cifar10(
            n_train=self.n_train, n_test=self.n_valid)
        data = numpy.concatenate([vx, tx])
        self.create_originals(data, None)
        self.class_lengths = [0, len(vx), len(tx)]


def build_workflow(epochs=20, minibatch_size=50, lr=0.01):
    loader = AELoader(None, minibatch_size=minibatch_size, name="ae")
    layers = [
        # encoder
        {"type": "conv_tanh", "n_kernels": 16, "kx": 5, "ky": 5,
         "padding": (2, 2, 2, 2), "learning_rate": lr},
        {"type": "avg_pooling", "kx": 2, "ky": 2},
        {"type": "conv_tanh", "n_kernels": 8, "kx": 3, "ky": 3,
         "padding": (1, 1, 1, 1), "learning_rate": lr},
        # decoder
        {"type": "depooling", "kx": 2, "ky": 2},
        {"type": "deconv", "n_channels": 3, "kx": 5, "ky": 5,
         "padding": (2, 2, 2, 2), "learning_rate": lr},
    ]
    wf = nn.StandardWorkflow(
        name="imagenet-ae",
        layers=layers, loader_unit=loader, loss_function="mse",
        decision_config=dict(max_epochs=epochs, fail_iterations=50),
    )
    return wf


class SyntheticImageLoader(FullBatchLoader):
    """Deterministic synthetic RGB images at an arbitrary size — the
    compute-bound bench surface (provenance 'synthetic' is stamped into the
    bench JSON; throughput/MFU do not depend on pixel content)."""

    hide_from_registry = True

    def __init__(self, workflow, image_size=128, n_train=1024, n_valid=128,
                 **kwargs):
        super().__init__(workflow, **kwargs)
        self.image_size = image_size
        self.n_train, self.n_valid = n_train, n_valid

    def load_data(self):
        rng = numpy.random.RandomState(123456)
        s = self.image_size
        data = rng.uniform(
            -1.0, 1.0, (self.n_valid + self.n_train, s, s, 3)
        ).astype(numpy.float32)
        self.create_originals(data, None)
        self.class_lengths = [0, self.n_valid, self.n_train]


def build_bench_workflow(image_size=128, minibatch_size=64, n_train=1024,
                         n_valid=128, lr=1e-4, remat=False):
    """MXU-weighted AE: most FLOPs sit in 64→128 and 128→128 3×3 convs
    (contraction dims ≥64 tile cleanly onto the 128×128 systolic array);
    only the unavoidable RGB stem is narrow. This is the compute-bound
    counterpart of :func:`build_workflow` — same layer vocabulary, sized so
    arithmetic dominates the tunnel's dispatch latency."""
    loader = SyntheticImageLoader(
        None, image_size=image_size, n_train=n_train, n_valid=n_valid,
        minibatch_size=minibatch_size, name="ae-bench")
    layers = [
        # encoder
        {"type": "conv_relu", "n_kernels": 64, "kx": 5, "ky": 5,
         "padding": (2, 2, 2, 2), "learning_rate": lr},
        {"type": "avg_pooling", "kx": 2, "ky": 2},
        {"type": "conv_relu", "n_kernels": 128, "kx": 3, "ky": 3,
         "padding": (1, 1, 1, 1), "learning_rate": lr},
        {"type": "avg_pooling", "kx": 2, "ky": 2},
        {"type": "conv_relu", "n_kernels": 128, "kx": 3, "ky": 3,
         "padding": (1, 1, 1, 1), "learning_rate": lr},
        # decoder
        {"type": "depooling", "kx": 2, "ky": 2},
        {"type": "deconv", "n_channels": 64, "kx": 3, "ky": 3,
         "padding": (1, 1, 1, 1), "learning_rate": lr},
        {"type": "depooling", "kx": 2, "ky": 2},
        {"type": "deconv", "n_channels": 3, "kx": 5, "ky": 5,
         "padding": (2, 2, 2, 2), "learning_rate": lr},
    ]
    wf = nn.StandardWorkflow(
        name="imagenet-ae-bench",
        layers=layers, loader_unit=loader, loss_function="mse",
        decision_config=dict(max_epochs=10 ** 9, fail_iterations=10 ** 9),
        remat=remat,
    )
    return wf


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=20)
    p.add_argument("--mb", type=int, default=50)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--backend", default="auto")
    args = p.parse_args(argv)

    wf = build_workflow(args.epochs, args.mb, args.lr)
    wf.initialize(device=vt.Device_for(args.backend))
    t0 = time.time()
    wf.run()
    dt = time.time() - t0
    res = wf.gather_results()
    print("best validation rmse: %.4f (epoch %d)" %
          (res["best_rmse"], res["best_epoch"]))
    print("throughput: %.0f samples/sec" %
          (wf.loader.samples_served / dt))
    return res


if __name__ == "__main__":
    main()
