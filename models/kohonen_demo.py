"""DemoKohonen — self-organizing map on 2-D point clusters.

TPU-native rebuild of the VELES "DemoKohonen" sample (reference zoo,
docs/source/manualrst_veles_algorithms.rst:89: "DemoKohonen/kohonen.py";
SpamKohonen is the same workflow over hashed text features). Unlike the
gradient-descent zoo members this one wires its own workflow loop —
Repeater → Loader → KohonenTrainer → decision — because SOM training is
not a StandardWorkflow loss graph; it mirrors the reference's custom
kohonen workflow shape. The trainer's batch-SOM update is a single
jitted function per minibatch (veles_tpu/nn/kohonen.py).

Convergence anchor: the quantization error on the generated clusters
must fall below the cluster noise radius — a real anchor, not a
surrogate proxy, like lines.py.

Run: python models/kohonen_demo.py [--epochs N]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy  # noqa: E402

import veles_tpu as vt  # noqa: E402
from veles_tpu import nn  # noqa: E402
from veles_tpu.loader import FullBatchLoader  # noqa: E402
from veles_tpu.mutable import Bool  # noqa: E402
from veles_tpu.plumbing import Repeater  # noqa: E402
from veles_tpu.units import Unit  # noqa: E402

N_CLUSTERS = 5


def make_clusters(rng, n, n_clusters=N_CLUSTERS, noise=0.25):
    centers = 4.0 * rng.rand(n_clusters, 2).astype(numpy.float32)
    labels = rng.randint(0, n_clusters, n).astype(numpy.int32)
    x = centers[labels] + noise * rng.randn(n, 2).astype(numpy.float32)
    return x, labels


class ClusterLoader(FullBatchLoader):
    hide_from_registry = True

    def __init__(self, workflow, n_train=1500, **kwargs):
        super().__init__(workflow, **kwargs)
        self.n_train = n_train

    def load_data(self):
        rng = numpy.random.RandomState(29)
        x, labels = make_clusters(rng, self.n_train)
        self.create_originals(x, labels)
        self.class_lengths = [0, 0, self.n_train]


class SOMDecision(Unit):
    """Epoch bookkeeping for the SOM loop: records the trainer's
    quantization error per epoch, raises ``complete`` at max_epochs."""

    hide_from_registry = True

    def __init__(self, workflow, max_epochs=10, **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "TRAINER"
        self.max_epochs = max_epochs
        self.complete = Bool(False)
        self.epoch_number = 0
        self.qerr_history = []
        self.demand("loader", "trainer")
        self.loader = None
        self.trainer = None

    def run(self) -> None:
        if not bool(self.loader.epoch_ended):
            return
        self.epoch_number += 1
        qerr = self.trainer.quantization_error
        self.qerr_history.append(qerr)
        self.info("epoch %d  som_qerr=%.4f", self.epoch_number, qerr)
        if self.epoch_number >= self.max_epochs:
            self.complete <<= True

    def get_metric_values(self):
        return {"epochs": self.epoch_number,
                "final_qerr": (self.qerr_history[-1]
                               if self.qerr_history else None),
                "qerr_history": list(self.qerr_history)}


class KohonenDemoWorkflow(vt.Workflow):
    """Repeater loop around loader → KohonenTrainer, the reference's
    custom-workflow shape for non-GD training."""

    hide_from_registry = True

    def __init__(self, shape=(6, 6), epochs=10, minibatch_size=100,
                 n_train=1500, lr0=0.5, decay=120.0, **kwargs):
        super().__init__(**kwargs)
        self.loader = ClusterLoader(self, n_train=n_train,
                                    minibatch_size=minibatch_size,
                                    name="clusters")
        self.trainer = nn.KohonenTrainer(self, shape=shape, lr0=lr0,
                                         decay=decay, name="som")
        self.trainer.link_attrs(self.loader, ("input", "minibatch_data"))
        self.decision = SOMDecision(self, max_epochs=epochs,
                                    name="som_decision")
        self.decision.loader = self.loader
        self.decision.trainer = self.trainer
        self.repeater = Repeater(self)
        self.repeater.link_from(self.start_point)
        self.loader.link_from(self.repeater)
        self.trainer.link_from(self.loader)
        self.decision.link_from(self.trainer)
        self.repeater.link_from(self.decision)
        self.repeater.gate_block = self.decision.complete
        self.end_point.link_from(self.decision)
        self.end_point.gate_block = ~self.decision.complete


def build_workflow(epochs=10, minibatch_size=100, n_train=1500,
                   shape=(6, 6), lr0=0.5, decay=None):
    if decay is None:
        # schedule the decay clock to the actual run length so shrunken
        # CI runs anneal the same way the full demo does
        decay = max(epochs * max(n_train // minibatch_size, 1) / 2.0, 10.0)
    return KohonenDemoWorkflow(shape=shape, epochs=epochs,
                               minibatch_size=minibatch_size,
                               n_train=n_train, lr0=lr0, decay=decay,
                               name="kohonen_demo")


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--mb", type=int, default=100)
    p.add_argument("--backend", default="auto")
    args = p.parse_args(argv)

    wf = build_workflow(args.epochs, args.mb)
    wf.initialize(device=vt.Device_for(args.backend))
    t0 = time.time()
    wf.run()
    dt = time.time() - t0
    res = wf.gather_results()
    print("final quantization error: %.4f after %d epochs" %
          (res["final_qerr"], res["epochs"]))
    print("throughput: %.0f samples/sec" %
          (wf.loader.samples_served / dt))
    return res


if __name__ == "__main__":
    main()
