"""MNIST-784 fully-connected workflow — BASELINE config #1.

The TPU-native rebuild of the Znicz MNIST sample (reference target: 1.48 %
validation error, docs/source/manualrst_veles_algorithms.rst:31; topology
784 → 100 tanh → 10 softmax, the classic Znicz mnist 784-100-10 config).

Run:  python models/mnist.py [--epochs N] [--mb N] [--backend xla|numpy]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy  # noqa: E402

import veles_tpu as vt  # noqa: E402
from veles_tpu import nn, datasets  # noqa: E402
from veles_tpu.config import root  # noqa: E402
from veles_tpu.genetics import Range  # noqa: E402
from veles_tpu.genetics.config import resolve as _cfg  # noqa: E402
from veles_tpu.loader import FullBatchLoader  # noqa: E402

# optimize-ready config (the reference shipped mnist_config.py with the
# same markers): --optimize searches these; plain runs collapse them
root.mnist.lr = Range(0.03, 0.001, 0.3)
root.mnist.hidden = Range(100, 25, 400)


class MnistLoader(FullBatchLoader):
    """60k train / 10k validation, flattened 784-vectors (reference: Znicz
    loader_mnist, SURVEY.md §2.8)."""

    hide_from_registry = True

    def load_data(self):
        tx, ty, vx, vy = datasets.load_mnist(flat=True)
        data = numpy.concatenate([vx, tx])
        labels = numpy.concatenate([vy, ty])
        self.create_originals(data, labels)
        self.class_lengths = [0, len(vx), len(tx)]


def build_workflow(epochs=10, minibatch_size=100, lr=None, hidden=None,
                   snapshot_dir=None, epochs_per_dispatch=1):
    """Explicit arguments win; ``lr``/``hidden`` left None resolve from
    ``root.mnist.*`` (where --optimize writes each candidate's
    genes)."""
    lr = float(_cfg(root.mnist.lr)) if lr is None else lr
    hidden = int(_cfg(root.mnist.hidden)) if hidden is None else hidden
    loader = MnistLoader(None, minibatch_size=minibatch_size, name="mnist")
    snap = (vt.Snapshotter(None, prefix="mnist", directory=snapshot_dir)
            if snapshot_dir else None)
    wf = nn.StandardWorkflow(
        name="mnist-784",
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": hidden,
             "learning_rate": lr},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": lr},
        ],
        loader_unit=loader,
        loss_function="softmax",
        decision_config=dict(max_epochs=epochs, fail_iterations=50),
        lr_schedule=nn.exp_decay(0.98),
        snapshotter_unit=snap,
        epochs_per_dispatch=epochs_per_dispatch,
    )
    return wf


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--mb", type=int, default=100)
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--backend", default="auto")
    p.add_argument("--snapshot-dir", default=None)
    p.add_argument("--resume", default=None,
                   help="snapshot file to resume from")
    args = p.parse_args(argv)

    wf = build_workflow(args.epochs, args.mb, args.lr,
                        snapshot_dir=args.snapshot_dir)
    device = vt.Device_for(args.backend)
    wf.initialize(device=device)
    if args.resume:
        vt.resume(wf, args.resume)
        wf.decision.complete <<= False
        print("resumed from %s at epoch %d" %
              (args.resume, wf.decision.epoch_number))
    t0 = time.time()
    wf.run()
    dt = time.time() - t0
    res = wf.gather_results()
    served = wf.loader.samples_served
    print("dataset: %s MNIST" %
          ("REAL" if datasets.mnist_is_real() else "synthetic"))
    print("best validation error: %.4f (epoch %d)" %
          (res["best_err"], res["best_epoch"]))
    print("throughput: %.0f samples/sec" % (served / dt))
    return res


if __name__ == "__main__":
    main()
