"""End-to-end training through StandardWorkflow: the full fused-step loop
(Repeater → Loader → TrainStep → Decision) must converge on synthetic
separable data. Mirrors the reference's model-convergence tests (the Znicz
MNIST regression tests, SURVEY.md §4)."""
import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn
from veles_tpu.loader import FullBatchLoader, TRAIN, VALID, TEST


class BlobsLoader(FullBatchLoader):
    """3-class Gaussian blobs: 600 train / 150 valid / 90 test."""

    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(7)
        n_per, d, k = 280, 10, 3
        centers = rng.randn(k, d) * 3
        data, labels = [], []
        for c in range(k):
            data.append(centers[c] + rng.randn(n_per, d))
            labels.append(numpy.full(n_per, c))
        data = numpy.concatenate(data).astype(numpy.float32)
        labels = numpy.concatenate(labels).astype(numpy.int32)
        perm = rng.permutation(len(data))
        data, labels = data[perm], labels[perm]
        self.create_originals(data, labels)
        self.class_lengths = [90, 150, 600]


def make_workflow(minibatch_size=50, **decision_kw):
    loader = BlobsLoader(None, minibatch_size=minibatch_size, name="blobs")
    wf = nn.StandardWorkflow(
        name="blobs-train",
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 16},
            {"type": "softmax", "output_sample_shape": 3},
        ],
        loader_unit=loader,
        loss_function="softmax",
        decision_config=dict(max_epochs=12, fail_iterations=50,
                             **decision_kw),
    )
    return wf


def test_training_converges():
    wf = make_workflow()
    dev = vt.XLADevice(mesh_axes={"data": 1})
    wf.initialize(device=dev)
    wf.run()
    assert bool(wf.stopped)
    d = wf.decision
    assert d.epoch_number == 12
    # separable blobs: validation error should collapse under 5%
    assert d.best_metric is not None
    assert d.best_metric < 0.05, d.epoch_metrics
    # all three sets were evaluated
    for s in (TEST, VALID, TRAIN):
        assert len(d.epoch_metrics[s]) == 12


def test_metrics_and_results():
    wf = make_workflow()
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    res = wf.gather_results()
    assert "best_err" in res and res["best_err"] < 0.05
    assert res["epochs"] == 12


def test_trained_params_reach_arrays():
    """After stop, TrainStep must sync device params back into the forward
    units' Arrays (snapshot coherence)."""
    wf = make_workflow()
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    w_before = wf.forwards[0].weights.map_read().copy()
    wf.run()
    w_after = wf.forwards[0].weights.map_read()
    assert not numpy.allclose(w_before, w_after)


def test_data_parallel_8dev_matches_semantics():
    """Same workflow on an 8-device data mesh: XLA SPMD partitioning of the
    fused step (batch sharded over 'data') must still converge — the psum
    equivalent of the reference's master-slave averaging."""
    wf = make_workflow(minibatch_size=48)
    dev = vt.XLADevice(mesh_axes={"data": 8})
    assert dev.mesh.devices.size == 8
    wf.initialize(device=dev)
    step = wf.train_step
    assert step._shardings is not None
    wf.run()
    assert wf.decision.best_metric < 0.05
    # params replicated over all 8 devices; minibatch indices sharded
    w = step.params[wf.forwards[0].name]["weights"]
    assert len(w.sharding.device_set) == 8
    idx = wf.loader.minibatch_indices.devmem
    assert len(idx.sharding.device_set) == 8
    assert not idx.sharding.is_fully_replicated


def test_data_parallel_requires_divisible_minibatch():
    wf = make_workflow(minibatch_size=50)
    dev = vt.XLADevice(mesh_axes={"data": 8})
    with pytest.raises(vt.Bug):
        wf.initialize(device=dev)


def test_extract_forward_workflow_inference():
    """Inference extraction: trained forwards chained, fed a real batch
    (must NOT see the never-filled fused minibatch zeros)."""
    wf = make_workflow()
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    fwf = wf.extract_forward_workflow()
    x = wf.loader.original_data.mem[:20]
    y_true = wf.loader.original_labels.mem[:20]
    from veles_tpu.memory import Array
    wf.forwards[0].input = Array(x, name="x")
    fwf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    fwf.run()
    probs = wf.forwards[-1].output.map_read()
    assert probs.shape == (20, 3)
    acc = (probs.argmax(1) == y_true).mean()
    assert acc > 0.9, acc


def test_layer_config_reaches_gd_units():
    """Per-layer learning_rate/weights_decay must reach the GD units."""
    loader = BlobsLoader(None, minibatch_size=50, name="blobs")
    wf = nn.StandardWorkflow(
        name="lr-check",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 4,
                 "learning_rate": 0.05, "weights_decay": 1e-3,
                 "gradient_moment": 0.9}],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=1))
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    gd = wf.train_step.gds[0]
    assert gd.learning_rate == 0.05
    assert gd.weight_decay == 1e-3
    assert gd.momentum == 0.9


def test_mixed_precision_converges():
    """AMP knob (root.common.engine.mixed_precision): forward/backward on
    a bf16 cast of params+batch (activation storage halves — the HBM
    lever for image-scale conv nets), f32 masters/loss. Must converge
    like the f32 run and leave master params f32."""
    import jax.numpy as jnp
    from veles_tpu.config import root
    root.common.engine.mixed_precision = True
    try:
        wf = make_workflow()
        wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        assert wf.train_step.mixed_precision
        wf.run()
    finally:
        root.common.engine.mixed_precision = False
    d = wf.decision
    assert d.best_metric is not None
    assert d.best_metric < 0.05, d.epoch_metrics
    for tree in wf.train_step.params.values():
        for leaf in tree.values():
            assert leaf.dtype == jnp.float32


def test_evaluation_mode_downgrades_block_dispatch():
    """--test of a config trained with epochs_per_dispatch>1 is a
    capability, not an error: entering evaluation mode downgrades the
    loader's block serving to the classic per-epoch loop (a fused
    H-epoch block would re-evaluate the same sets H times). Mirrors
    launcher._enter_test_mode's sequence. Params must not move."""
    import jax
    from veles_tpu import prng
    prng.seed_all(123)
    loader = BlobsLoader(None, minibatch_size=50, name="blobs-evb")
    wf = nn.StandardWorkflow(
        name="evb",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 16},
                {"type": "softmax", "output_sample_shape": 3}],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=1, fail_iterations=50),
        epochs_per_dispatch=4)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    assert loader.block_epochs == 4
    step = wf.train_step
    step.evaluation_mode = True
    assert loader.block_epochs == 1
    before = jax.device_get(step.params)
    wf.run()
    assert wf.decision.epoch_number == 1
    after = jax.device_get(step.params)
    for name, tree in before.items():
        for k, v in tree.items():
            numpy.testing.assert_array_equal(numpy.asarray(after[name][k]),
                                             numpy.asarray(v))


def test_epoch_block_matches_classic():
    """epochs_per_dispatch=H fuses H whole epochs (eval+train) into ONE
    device dispatch; the Decision replays per-epoch bookkeeping from the
    stacked accums. Same seed → the trajectory and final weights must
    match the classic per-epoch loop."""
    import jax
    from veles_tpu import prng

    def run(h):
        prng.seed_all(99)
        loader = BlobsLoader(None, minibatch_size=50, name="blobs-blk")
        wf = nn.StandardWorkflow(
            name="blk-%d" % h,
            layers=[
                {"type": "all2all_tanh", "output_sample_shape": 16},
                {"type": "softmax", "output_sample_shape": 3},
            ],
            loader_unit=loader, loss_function="softmax",
            decision_config=dict(max_epochs=12, fail_iterations=50),
            lr_schedule=nn.exp_decay(0.95),
            epochs_per_dispatch=h,
        )
        wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        wf.run()
        d = wf.decision
        return {
            "train": numpy.asarray(d.epoch_metrics[TRAIN]),
            "valid": numpy.asarray(d.epoch_metrics[VALID]),
            "test": numpy.asarray(d.epoch_metrics[TEST]),
            "epochs": d.epoch_number,
            "w": numpy.asarray(jax.device_get(
                wf.train_step.params[wf.forwards[0].name]["weights"])),
        }

    classic = run(1)
    for h in (4, 5):
        # h=5 does NOT divide max_epochs=12: the final block clamps to
        # the 2 remaining epochs, so the weights stop exactly at the cap
        block = run(h)
        assert classic["epochs"] == block["epochs"] == 12
        for k in ("train", "valid", "test"):
            assert classic[k].shape == block[k].shape == (12,)
            numpy.testing.assert_allclose(block[k], classic[k],
                                          atol=0.02)
        numpy.testing.assert_allclose(block["w"], classic["w"],
                                      rtol=2e-3, atol=2e-4)


def test_plan_height_clamps_to_class_ceiling():
    """The plan height is static and fully scanned, so rows past the
    class ceiling are mask-zero dead compute. A large minibatch
    (ceil(600/200)=3 < the default 16 steps) must clamp plan_steps at
    initialize — and the clamped run must trace the SAME trajectory as
    an explicit steps_per_dispatch=3 config (the clamp removes only
    dead rows). Found on chip: the mb=256 conv-AE burned 12/16 plan
    rows masked, quadrupling the work per served sample."""
    import jax
    from veles_tpu import prng

    def run(steps):
        prng.seed_all(123)
        loader = BlobsLoader(None, minibatch_size=200, name="blobs-big")
        wf = nn.StandardWorkflow(
            name="clamp-%s" % steps,
            layers=[
                {"type": "all2all_tanh", "output_sample_shape": 16},
                {"type": "softmax", "output_sample_shape": 3},
            ],
            loader_unit=loader, loss_function="softmax",
            decision_config=dict(max_epochs=6, fail_iterations=50),
            steps_per_dispatch=steps,
        )
        wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        assert wf.loader.plan_steps == 3        # clamped (or explicit)
        wf.run()
        return {
            "valid": numpy.asarray(wf.decision.epoch_metrics[VALID]),
            "w": numpy.asarray(jax.device_get(
                wf.train_step.params[wf.forwards[0].name]["weights"])),
        }

    clamped = run(16)       # default-style config, clamp kicks in
    explicit = run(3)       # exactly-sized plan, no dead rows either
    numpy.testing.assert_array_equal(clamped["valid"],
                                     explicit["valid"])
    numpy.testing.assert_array_equal(clamped["w"], explicit["w"])


def test_epoch_block_with_data_axis():
    """Block dispatch composes with data parallelism: plans shard over
    the minibatch axis, trajectory still converges."""
    from veles_tpu import prng
    prng.seed_all(99)
    loader = BlobsLoader(None, minibatch_size=48, name="blobs-blk8")
    wf = nn.StandardWorkflow(
        name="blk-dp",
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 16},
            {"type": "softmax", "output_sample_shape": 3},
        ],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=8, fail_iterations=50),
        epochs_per_dispatch=4,
    )
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 8}))
    wf.run()
    d = wf.decision
    assert d.epoch_number == 8
    assert d.best_metric is not None and d.best_metric < 0.05, \
        d.epoch_metrics


def test_block_drain_improved_flag_ors_over_epochs():
    """The snapshot gate reads `improved` once per drain: improvement at
    an INTERIOR epoch of a block must leave it True even if the final
    epochs plateau (else best models never snapshot under long blocks)."""
    from veles_tpu.nn.decision import DecisionGD
    from veles_tpu.mutable import Bool

    class FakeLoader:
        epoch_ended = Bool(True)

    class FakeStep:
        def __init__(self, blocks):
            self.blocks = blocks

        def drain_epoch_blocks(self):
            return self.blocks

    wf = vt.Workflow(name="t")
    d = DecisionGD(wf, max_epochs=10)
    d.loader = FakeLoader()
    # err improves at epoch 2 of 4, then plateaus
    d.step_unit = FakeStep([
        {TRAIN: {"n_err": 50.0, "n_samples": 100.0}},
        {TRAIN: {"n_err": 10.0, "n_samples": 100.0}},
        {TRAIN: {"n_err": 30.0, "n_samples": 100.0}},
        {TRAIN: {"n_err": 30.0, "n_samples": 100.0}},
    ])
    d.run()
    assert d.epoch_number == 4
    assert d.best_metric == 0.1 and d.best_epoch == 2
    assert bool(d.improved)      # interior improvement kept visible


def test_mixed_precision_composes_with_remat():
    """AMP + remat: jax.checkpoint wraps the bf16 forward — both knobs
    on together must still converge with f32 masters."""
    import jax.numpy as jnp
    from veles_tpu.config import root
    from veles_tpu import prng
    prng.seed_all(5)
    root.common.engine.mixed_precision = True
    try:
        loader = BlobsLoader(None, minibatch_size=50, name="blobs-ar")
        wf = nn.StandardWorkflow(
            name="amp-remat",
            layers=[
                {"type": "all2all_tanh", "output_sample_shape": 16},
                {"type": "softmax", "output_sample_shape": 3},
            ],
            loader_unit=loader, loss_function="softmax",
            decision_config=dict(max_epochs=8, fail_iterations=50),
            remat=True,
        )
        wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        assert wf.train_step.mixed_precision and wf.train_step.remat
        wf.run()
    finally:
        root.common.engine.mixed_precision = False
    d = wf.decision
    assert d.best_metric is not None and d.best_metric < 0.05, \
        d.epoch_metrics
    for tree in wf.train_step.params.values():
        for leaf in tree.values():
            assert leaf.dtype == jnp.float32


def test_bf16_dataset_storage_converges():
    """engine.dataset_dtype='bfloat16': dataset stored/staged at half
    width (the tunnel/HBM lever for image data); training on the bf16
    dataset must still converge."""
    from veles_tpu.config import root
    from veles_tpu import prng
    import jax.numpy as jnp
    prng.seed_all(6)
    root.common.engine.dataset_dtype = "bfloat16"
    try:
        wf = make_workflow()
        wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        assert wf.loader.original_data.mem.dtype == jnp.bfloat16
        wf.run()
    finally:
        root.common.engine.dataset_dtype = None
    assert wf.decision.best_metric is not None
    assert wf.decision.best_metric < 0.06, wf.decision.epoch_metrics


def test_grad_accumulation_matches_direct_step():
    """grad_accumulation=G: G sequential chunk backwards + ONE update
    from the valid-weighted mean gradient must reproduce the direct
    full-minibatch step (no dropout in this net, so the only
    difference is reduction order)."""
    import jax
    from veles_tpu import prng

    def run(ga):
        prng.seed_all(321)
        loader = BlobsLoader(None, minibatch_size=50, name="blobs-ga")
        wf = nn.StandardWorkflow(
            name="ga-%d" % ga,
            layers=[{"type": "all2all_tanh", "output_sample_shape": 16},
                    {"type": "softmax", "output_sample_shape": 3}],
            loader_unit=loader, loss_function="softmax",
            decision_config=dict(max_epochs=6, fail_iterations=50),
            grad_accumulation=ga)
        wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        assert wf.train_step.grad_accumulation == ga
        wf.run()
        return (numpy.asarray(wf.decision.epoch_metrics[VALID]),
                numpy.asarray(jax.device_get(
                    wf.train_step.params["a2a0"]["weights"])
                    if "a2a0" in wf.train_step.params else
                    jax.device_get(list(
                        wf.train_step.params.values())[0]["weights"])))

    e1, w1 = run(1)
    e2, w2 = run(5)
    numpy.testing.assert_allclose(e2, e1, atol=0.025)
    numpy.testing.assert_allclose(w2, w1, rtol=2e-3, atol=2e-4)


def test_grad_accumulation_refuses_pipeline():
    from veles_tpu import prng
    prng.seed_all(5)
    loader = BlobsLoader(None, minibatch_size=48, name="blobs-gap")
    wf = nn.StandardWorkflow(
        name="ga-pp",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 16,
                 "name": "b%d" % i} for i in range(4)]
        + [{"type": "softmax", "output_sample_shape": 3}],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=1), grad_accumulation=2)
    with pytest.raises(vt.Bug, match="grad_accumulation"):
        wf.initialize(device=vt.XLADevice(mesh_axes={"pipeline": 4}))


def test_grad_accumulation_composes_with_data_axis():
    from veles_tpu import prng
    prng.seed_all(77)
    loader = BlobsLoader(None, minibatch_size=48, name="blobs-gad")
    wf = nn.StandardWorkflow(
        name="ga-dp",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 16},
                {"type": "softmax", "output_sample_shape": 3}],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=6, fail_iterations=50),
        grad_accumulation=2)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 2}))
    wf.run()
    assert wf.decision.best_metric < 0.06, wf.decision.epoch_metrics


def test_label_smoothing_trains_and_changes_loss():
    """EvaluatorSoftmax(label_smoothing=eps): CE against the eps-mixed
    target. Still converges; the loss genuinely differs from the hard-
    target CE (floor is the smoothed entropy, not 0); oracle agrees."""
    from veles_tpu import prng
    prng.seed_all(31)
    loader = BlobsLoader(None, minibatch_size=50, name="blobs-ls")
    wf = nn.StandardWorkflow(
        name="ls",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 16},
                {"type": "softmax", "output_sample_shape": 3}],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=8, fail_iterations=50),
        evaluator_config=dict(label_smoothing=0.1))
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    assert wf.evaluator.label_smoothing == 0.1
    wf.run()
    assert wf.decision.best_metric < 0.05, wf.decision.epoch_metrics
    # jax loss vs numpy oracle on a small batch
    import jax.numpy as jnp
    logits = numpy.random.RandomState(0).randn(6, 3).astype("float32")
    labels = numpy.array([0, 1, 2, 0, 1, 2], numpy.int32)
    mask = numpy.ones(6, numpy.float32)
    l_jax = float(wf.evaluator.loss(jnp.asarray(logits),
                                    jnp.asarray(labels),
                                    jnp.asarray(mask)))
    l_np = wf.evaluator.numpy_loss(logits, labels, mask)
    numpy.testing.assert_allclose(l_jax, l_np, rtol=1e-5)
    # and it differs from the unsmoothed loss
    wf.evaluator.label_smoothing = 0.0
    l_hard = float(wf.evaluator.loss(jnp.asarray(logits),
                                     jnp.asarray(labels),
                                     jnp.asarray(mask)))
    assert abs(l_jax - l_hard) > 1e-4
