"""Model-health observability (veles_tpu/telemetry/tensormon.py +
recorder.py): in-graph tensor taps on the fused train step and the
flight-recorder crash black box.

The load-bearing locks:
- monitoring OFF (the default) is BIT-IDENTICAL to a build without the
  feature — same state trees, same per-program dispatch counts, zero
  tensormon counters (the PR-1 scan-lock discipline applied here);
- a seeded NaN batch trips each sentinel policy: warn counts, halt
  marks health unready + raises ModelHealthError, snapshot_and_halt
  additionally commits a forensic snapshot through the checkpoint
  chain; every halt dumps the flight recorder;
- the flight-recorder ring keeps the newest events in order, crash
  dumps land next to the snapshots, and `veles-tpu blackbox inspect`
  round-trips them;
- scripts/check_counters.py (the static registration pass) stays green.
"""
import glob
import os
import subprocess
import sys

import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn, prng
from veles_tpu.config import root
from veles_tpu.loader import FullBatchLoader
from veles_tpu.resilience import health
from veles_tpu.telemetry import ModelHealthError, monitor
from veles_tpu.telemetry import spans
from veles_tpu.telemetry.counters import counters
from veles_tpu.telemetry.recorder import (FlightRecorder, flight,
                                          inspect, read_blackbox)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _reset_model_health(tmp_path):
    """Every test starts from the shipped defaults and leaves no
    monitoring/health residue (or stray black boxes in the real
    snapshot directory) for the rest of the suite."""
    flight.clear()
    monitor.reset()
    prev_snapdir = root.common.dirs.snapshots
    root.common.dirs.snapshots = str(tmp_path / "snapdir")
    yield
    root.common.dirs.snapshots = prev_snapdir
    root.common.telemetry.tensormon.enabled = False
    root.common.telemetry.tensormon.nan_policy = "warn"
    root.common.telemetry.tensormon.every = 1
    root.common.telemetry.recorder.autodump = False
    monitor.reset()
    health.forget("model_health")


class BlobsLoader(FullBatchLoader):
    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(7)
        data = rng.randn(120, 10).astype(numpy.float32)
        labels = (data.sum(axis=1) > 0).astype(numpy.int32)
        self.create_originals(data, labels)
        self.class_lengths = [0, 40, 80]


def _run(enabled=False, policy="warn", poison=False, snapshot_dir=None,
         epochs=3, every=1):
    root.common.telemetry.tensormon.enabled = enabled
    root.common.telemetry.tensormon.nan_policy = policy
    root.common.telemetry.tensormon.every = every
    prng.seed_all(1234)
    loader = BlobsLoader(None, minibatch_size=40, name="mon-blobs")
    snap = None
    if snapshot_dir is not None:
        snap = vt.Snapshotter(None, prefix="mon",
                              directory=str(snapshot_dir), interval=1)
    wf = nn.StandardWorkflow(
        name="mon-wf",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 8},
                {"type": "softmax", "output_sample_shape": 2}],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=epochs, fail_iterations=100),
        snapshotter_unit=snap)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    if poison:
        # seeded NaN injection: the whole dataset is poisoned BEFORE
        # the first device upload, so the first train batch already
        # carries non-finite values into loss and gradients
        loader.original_data.mem[:, :] = numpy.nan
    wf.run()
    return wf


# -- off-mode bit-identity + dispatch lock ------------------------------------

def test_off_mode_bit_identical_and_dispatch_count_locked():
    """THE off-mode contract: enabling the taps must not change a
    single bit of the training trajectory or add a single dispatch —
    so the DISABLED default is exactly a build without the feature."""
    import jax
    before_a = counters.snapshot()
    wf_a = _run(enabled=False)
    d_a = counters.delta(before_a)
    before_b = counters.snapshot()
    wf_b = _run(enabled=True)
    d_b = counters.delta(before_b)
    # same per-program dispatch counts AND same global dispatch total
    assert wf_a.train_step._dispatch_counts == \
        wf_b.train_step._dispatch_counts
    assert d_a.get("veles_dispatches_total") == \
        d_b.get("veles_dispatches_total")
    # identical state trees, bit for bit
    leaves_a = jax.tree_util.tree_leaves(
        jax.device_get((wf_a.train_step.params,
                        wf_a.train_step.opt_state)))
    leaves_b = jax.tree_util.tree_leaves(
        jax.device_get((wf_b.train_step.params,
                        wf_b.train_step.opt_state)))
    assert len(leaves_a) == len(leaves_b)
    for a, b in zip(leaves_a, leaves_b):
        numpy.testing.assert_array_equal(numpy.asarray(a),
                                         numpy.asarray(b))
    # zero tensormon counter leakage in the off run, samples in the on
    assert not d_a.get("veles_tensormon_samples_total")
    assert d_b.get("veles_tensormon_samples_total") == 3  # one/epoch
    # the off run's accumulators carry the classic key set only
    assert not any(k.startswith("mon_")
                   for k in wf_a.train_step._make_zero_accum())


def test_enabled_serves_gauges_spans_and_every_throttle():
    spans.recorder.clear()
    _run(enabled=True, epochs=4, every=2)
    gauges = monitor.gauges()
    assert "veles_model_grad_norm" in gauges
    assert "veles_model_act_saturation" in gauges
    assert any(k.startswith("veles_model_update_ratio_")
               for k in gauges)
    value, help_text = gauges["veles_model_grad_norm"]
    assert value > 0 and "norm" in help_text
    # every=2: 4 samples observed, every 2nd emits a span + ring event
    assert len(spans.recorder.records("tensormon.sample")) == 2
    assert len(flight.records("tensormon")) == 2


# -- NaN sentinel -------------------------------------------------------------

def test_nan_warn_policy_counts_and_serves_metrics():
    import urllib.request
    before = counters.snapshot()
    _run(enabled=True, poison=True, policy="warn")   # completes
    delta = counters.delta(before)
    assert delta.get("veles_model_nan_total", 0) > 0
    assert not delta.get("veles_model_health_errors_total")
    # acceptance: veles_model_nan_total > 0 on /metrics
    from veles_tpu.web_status import WebStatusServer
    server = WebStatusServer(port=0).start()
    try:
        url = "http://127.0.0.1:%d/metrics" % server.port
        with urllib.request.urlopen(url, timeout=30) as r:
            body = r.read().decode()
    finally:
        server.stop()
    line = next(ln for ln in body.splitlines()
                if ln.startswith("veles_model_nan_total "))
    assert float(line.split()[1]) > 0
    assert "veles_model_grad_norm" in body


def test_nan_halt_policy_raises_and_beats_health_unready():
    with pytest.raises(ModelHealthError):
        _run(enabled=True, poison=True, policy="halt")
    code, payload = health.readyz()
    assert code == 503
    assert payload["components"]["model_health"] is False
    assert counters.get("veles_model_health_errors_total") >= 1


def test_nan_snapshot_and_halt_commits_snapshot_and_blackbox(tmp_path):
    prev_dir = root.common.dirs.snapshots
    root.common.dirs.snapshots = str(tmp_path)
    try:
        with pytest.raises(ModelHealthError) as excinfo:
            _run(enabled=True, poison=True, policy="snapshot_and_halt",
                 snapshot_dir=tmp_path)
    finally:
        root.common.dirs.snapshots = prev_dir
    # the forensic snapshot went through the crash-safe chain:
    # committed file + verifying manifest
    from veles_tpu.resilience import checkpoint_chain
    snaps = checkpoint_chain.chain(str(tmp_path), "mon")
    assert len(snaps) == 1
    assert checkpoint_chain.verify(snaps[0]) is True
    assert "forensic snapshot" in str(excinfo.value)
    # the black box landed next to it and holds the triggering
    # step's events (the tensormon.nan record among them)
    dumps = glob.glob(str(tmp_path / "blackbox-*.jsonl"))
    assert len(dumps) == 1
    header, events = read_blackbox(dumps[0])
    assert header["reason"].startswith("nan sentinel")
    kinds = {e.get("kind") for e in events}
    assert "tensormon.nan" in kinds
    assert "span" in kinds          # the final seconds' span closes


# -- flight recorder ----------------------------------------------------------

def test_ring_buffer_overwrite_order():
    rec = FlightRecorder(capacity=4)
    for i in range(6):
        rec.note("t", i=i)
    kept = [r["i"] for r in rec.records()]
    assert kept == [2, 3, 4, 5]       # newest 4, oldest first
    assert rec.stats() == {"recorded": 6, "buffered": 4, "capacity": 4}


def test_crash_dump_and_blackbox_inspect_roundtrip(tmp_path, capsys):
    class Boom(vt.Unit):
        hide_from_registry = True

        def run(self):
            raise RuntimeError("boom")

    root.common.telemetry.recorder.autodump = True
    prev_dir = root.common.dirs.snapshots
    root.common.dirs.snapshots = str(tmp_path)
    try:
        wf = vt.Workflow(name="crash-wf")
        u = Boom(wf, name="boom")
        u.link_from(wf.start_point)
        wf.end_point.link_from(u)
        wf.initialize()
        with pytest.raises(RuntimeError):
            wf.run()
    finally:
        root.common.dirs.snapshots = prev_dir
    dumps = glob.glob(str(tmp_path / "blackbox-*.jsonl"))
    assert len(dumps) == 1
    header, events = read_blackbox(dumps[0])
    assert header["reason"].startswith("workflow.run crash-wf")
    assert header["events"] == len(events)
    summary = inspect(dumps[0])
    assert summary["events"] == len(events)
    assert sum(summary["by_kind"].values()) == len(events)
    # CLI round trip
    from veles_tpu.__main__ import main
    assert main(["blackbox", "inspect", dumps[0]]) == 0
    out = capsys.readouterr().out
    assert "workflow.run crash-wf" in out
    assert "events:" in out


def test_blackbox_dump_cli_writes_current_ring(tmp_path, capsys):
    flight.note("marker", detail="cli-test")
    path = str(tmp_path / "bb.jsonl")
    from veles_tpu.__main__ import main
    assert main(["blackbox", "dump", "--out", path,
                 "--reason", "unit test"]) == 0
    header, events = read_blackbox(path)
    assert header["reason"] == "unit test"
    assert any(e.get("kind") == "marker" for e in events)
    assert main(["blackbox", "inspect", path]) == 0


def test_watchdog_trip_notes_and_dumps(tmp_path):
    from veles_tpu.parallel.distributed import step_watchdog
    root.common.telemetry.recorder.autodump = True
    prev_dir = root.common.dirs.snapshots
    root.common.dirs.snapshots = str(tmp_path)
    try:
        history = [0.0] * 8        # mean+3σ == 0: any duration trips
        before = counters.get("veles_watchdog_trips_total")
        with step_watchdog("trip-test", history=history):
            pass
    finally:
        root.common.dirs.snapshots = prev_dir
    assert counters.get("veles_watchdog_trips_total") == before + 1
    assert flight.records("watchdog.trip")
    dumps = glob.glob(str(tmp_path / "blackbox-*.jsonl"))
    assert len(dumps) == 1
    header, _ = read_blackbox(dumps[0])
    assert "watchdog trip" in header["reason"]


def test_recorder_dump_fault_point_corrupts_dump(tmp_path, monkeypatch):
    from veles_tpu.resilience import faults
    assert "recorder.dump" in faults.list_points()
    before = counters.get("veles_faults_injected_total")
    monkeypatch.setenv("VELES_FAULTS", "recorder.dump:corrupt:times=1")
    faults.plane.configure()
    try:
        flight.note("pre-corrupt")
        path = flight.dump("corruption test",
                           path=str(tmp_path / "bb.jsonl"))
    finally:
        monkeypatch.delenv("VELES_FAULTS")
        faults.plane.configure("")
    assert counters.get("veles_faults_injected_total") == before + 1
    # the damaged dump must still read back without raising — bitrot
    # in the black box cannot be allowed to break the forensics tool
    header, events = read_blackbox(path)
    assert isinstance(events, list)
    from veles_tpu.__main__ import main
    assert main(["blackbox", "inspect", path]) == 0


# -- static counter-registration pass (scripts/check_counters.py) -------------

def _load_checker():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "veles_check_counters",
        os.path.join(REPO, "scripts", "check_counters.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_check_counters_static_pass(tmp_path):
    mod = _load_checker()
    # the tree itself is clean — this is the tier-1 hook the satellite
    # asks for: any counter inc'd anywhere without a DESCRIPTIONS entry
    # fails here
    assert mod.find_unregistered() == []
    r = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "scripts", "check_counters.py")],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "counter registration OK" in r.stdout
    # the detector actually detects: a fabricated tree with an
    # unregistered name is flagged
    (tmp_path / "veles_tpu").mkdir()
    (tmp_path / "veles_tpu" / "x.py").write_text(
        'inc("veles_bogus_total")\ncounters.get("veles_bogus2_total")\n')
    uses = mod.used_counters(str(tmp_path))
    assert set(uses) == {"veles_bogus_total", "veles_bogus2_total"}
