"""Aux compute units (BASELINE config #2: mean_disp_normalizer +
fullbatch pipeline vs numpy oracle) + normalizer registry."""
import numpy
import pytest

import veles_tpu as vt
from veles_tpu.memory import Array
from veles_tpu import normalization


def dev():
    return vt.XLADevice(mesh_axes={"data": 1})


def test_mean_disp_normalizer_oracle():
    rng = numpy.random.RandomState(0)
    data = (rng.rand(50, 7, 3) * 255).astype(numpy.uint8)
    mean, rdisp = vt.MeanDispNormalizer.compute_mean_rdisp(
        data.astype(numpy.float32))
    wf = vt.Workflow(name="t")
    u = vt.MeanDispNormalizer(wf)
    u.input = Array(data.astype(numpy.float32))
    u.mean, u.rdisp = Array(mean), Array(rdisp)
    u.initialize(device=dev())
    u.xla_run()
    y_dev = numpy.asarray(u.output.map_read())
    u.numpy_run()
    y_np = u.output.map_read()
    numpy.testing.assert_allclose(y_dev, y_np, rtol=1e-5, atol=1e-6)
    # normalized data spans about [-1, 1]
    assert abs(y_np).max() <= 1.0 + 1e-5


def test_input_joiner():
    wf = vt.Workflow(name="t")
    a = Array(numpy.ones((4, 3), dtype=numpy.float32))
    b = Array(numpy.full((4, 2, 2), 2.0, dtype=numpy.float32))
    u = vt.InputJoiner(wf, inputs=[a, b])
    u.initialize(device=dev())
    u.xla_run()
    y = numpy.asarray(u.output.map_read())
    assert y.shape == (4, 7)
    numpy.testing.assert_allclose(y[:, :3], 1.0)
    numpy.testing.assert_allclose(y[:, 3:], 2.0)
    u.numpy_run()
    numpy.testing.assert_allclose(u.output.map_read(), y)


def test_avatar_clones_and_isolates():
    wf = vt.Workflow(name="t")

    class Src(vt.Unit):
        hide_from_registry = True
    src = Src(wf, name="src")
    src.output = Array(numpy.arange(6, dtype=numpy.float32))
    av = vt.Avatar(wf, source=src)
    av.initialize(device=dev())
    av.xla_run()
    numpy.testing.assert_allclose(
        numpy.asarray(av.output.map_read()), numpy.arange(6))
    # producer overwrites; avatar keeps the old copy until next run
    src.output.map_write()[...] = 99.0
    numpy.testing.assert_allclose(
        numpy.asarray(av.output.map_read()), numpy.arange(6))


@pytest.mark.parametrize("name", sorted(normalization.NORMALIZERS))
def test_normalizer_roundtrip(name):
    rng = numpy.random.RandomState(3)
    data = (rng.rand(20, 5) * 10 - 3).astype(numpy.float32)
    kwargs = {}
    if name == "external_mean":
        kwargs["mean_source"] = data.mean(axis=0)
    n = normalization.get_normalizer(name, **kwargs)
    n.analyze(data)
    out = n.normalize(data.copy())
    assert out.shape == data.shape
    if name in ("range", "mean_disp", "external_mean", "pointwise", "exp"):
        back = n.denormalize(out)
        numpy.testing.assert_allclose(back, data, rtol=1e-4, atol=1e-4)
    if name in ("linear", "range", "pointwise"):
        assert out.min() >= -1 - 1e-5 and out.max() <= 1 + 1e-5


def test_normalizer_state_roundtrip():
    n = normalization.get_normalizer("range")
    n.analyze(numpy.array([0.0, 10.0]))
    sd = n.state_dict()
    n2 = normalization.get_normalizer("range")
    n2.load_state_dict(sd)
    numpy.testing.assert_allclose(
        n2.normalize(numpy.array([5.0])), [0.0])


def test_unknown_normalizer():
    with pytest.raises(KeyError):
        normalization.get_normalizer("nope")
