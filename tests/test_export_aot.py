"""AOT serving artifacts (export/serve_artifact.py) + package format
v3 (export/package.py quant blocks).

The contracts under test: an exported artifact serves id-exact greedy
(and sampled) tokens vs the live-jit engine with ZERO jit compiles at
initialize+serve; a corrupt / injected-fault / mismatched artifact
falls back to live jit with a counted warning and the API keeps
serving; plain packages still stamp format_version 2 and import/run
everywhere; quantized packages stamp v3, round-trip their quant
metadata and dequantize on import."""
import json
import os
import urllib.request

import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn, prng
from veles_tpu.export import package_export, package_import, run_package
from veles_tpu.export.serve_artifact import (export_serve_artifact,
                                             load_serve_programs)
from veles_tpu.error import VelesError
from veles_tpu.loader import FullBatchLoader
from veles_tpu.serving import ContinuousEngine
from veles_tpu.serving.engine import make_request
from veles_tpu.telemetry.counters import counters

from conftest import import_model

KNOBS = dict(max_slots=3, buckets=(8, 16), max_context=48)


@pytest.fixture(scope="module")
def served_artifact(tmp_path_factory):
    """Trained LM + a serve-artifact exported with the same knobs the
    engines under test boot with."""
    lm = import_model("char_lm")
    prng.seed_all(971)
    wf = lm.build_workflow(epochs=1, minibatch_size=64, n_blocks=2,
                           dim=32, n_train=256, n_valid=64)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    art = str(tmp_path_factory.mktemp("aot") / "artifact")
    export_serve_artifact(wf, art, **KNOBS)
    return lm, wf, art


def _prompt(lm, seed, length=10):
    return [int(t) for t in
            lm.make_corpus(numpy.random.RandomState(seed), length)]


def _reqs(lm):
    return [make_request(_prompt(lm, 80 + s, 5 + s % 6), 6,
                         temperature=0.7 if s % 2 else 0.0,
                         seed=80 + s)
            for s in range(4)]


# -- artifact contents ---------------------------------------------------------

def test_artifact_is_a_v3_package_with_serving_block(served_artifact):
    lm, wf, art = served_artifact
    with open(os.path.join(art, "contents.json")) as fin:
        contents = json.load(fin)
    assert contents["format_version"] == 3
    serving = contents["serving"]
    # v5: the tensor-parallel mesh geometry ("tp"/"mesh") joined the
    # signature; unsharded artifacts are unchanged otherwise
    assert serving["artifact_version"] == 5
    assert sorted(serving["programs"]) == ["decode", "prefill_16",
                                           "prefill_8"]
    for fname in serving["programs"].values():
        assert os.path.getsize(os.path.join(art, fname)) > 0
    sig = serving["signature"]
    assert sig["buckets"] == [8, 16]
    assert sig["max_slots"] == 3
    assert sig["quant_weights"] is False
    # the artifact is still a readable package (program-only: the
    # params stay runtime inputs, so it survives further training)
    assert package_import(art)["contents"]["units"] == []


# -- artifact serving: id-exact, zero compiles ---------------------------------

def test_artifact_serves_id_exact_with_zero_compiles(served_artifact):
    lm, wf, art = served_artifact
    from veles_tpu.nn import sampling
    reqs = _reqs(lm)
    live = ContinuousEngine(wf, name="aot_live", **KNOBS).start()
    try:
        ref = live.serve(list(reqs))
        assert live.compiled_live >= 2          # the cost AOT deletes
    finally:
        live.stop()
    loads0 = counters.get("veles_artifact_loads_total")
    compiles0 = counters.get("veles_compiles_total")
    compile_s0 = counters.get("veles_serving_compile_seconds_total")
    engine = ContinuousEngine(wf, artifact=art, name="aot_eng",
                              **KNOBS).start()
    try:
        assert engine.artifact_mode
        out = engine.serve(list(reqs))
        # greedy AND sampled answers equal the live engine AND the
        # scan decoder — the artifact is the same program, serialized
        assert out == ref
        for r, toks in zip(reqs, out):
            assert toks == sampling.generate(
                wf, r["prompt"], r["n_new"],
                temperature=r["temperature"], seed=r["seed"])
        st = engine.stats()
        assert st["artifact_mode"] == 1
        assert st["compiled_live"] == 0
        assert engine.programs_built <= len(engine.buckets) + 1
    finally:
        engine.stop()
    assert counters.get("veles_artifact_loads_total") == loads0 + 1
    assert counters.get("veles_compiles_total") == compiles0
    assert counters.get("veles_serving_compile_seconds_total") == \
        compile_s0


# -- fallback paths ------------------------------------------------------------

def _fallback_engine(wf, art, name):
    fails0 = counters.get("veles_artifact_load_failures_total")
    engine = ContinuousEngine(wf, artifact=art, name=name,
                              **KNOBS).start()
    assert not engine.artifact_mode
    assert counters.get("veles_artifact_load_failures_total") == \
        fails0 + 1
    return engine


def test_corrupt_artifact_falls_back_to_live_jit(served_artifact,
                                                 tmp_path):
    import shutil
    lm, wf, art = served_artifact
    from veles_tpu.nn import sampling
    bad = str(tmp_path / "bad_art")
    shutil.copytree(art, bad)
    blob = os.path.join(bad, "serve_decode.bin")
    with open(blob, "rb") as fin:
        raw = fin.read()
    with open(blob, "wb") as fout:
        fout.write(raw[: len(raw) // 2])
    engine = _fallback_engine(wf, bad, "aot_corrupt")
    try:
        req = make_request(_prompt(lm, 90), 5)
        assert engine.serve([req])[0] == sampling.generate(
            wf, req["prompt"], req["n_new"], temperature=0)
    finally:
        engine.stop()


def test_missing_and_mismatched_artifacts_fall_back(served_artifact,
                                                    tmp_path):
    lm, wf, art = served_artifact
    engine = _fallback_engine(wf, str(tmp_path / "nowhere"),
                              "aot_missing")
    engine.stop()
    # geometry mismatch: an engine with different buckets must refuse
    # the shape-committed programs, not run them on reinterpreted pools
    fails0 = counters.get("veles_artifact_load_failures_total")
    engine = ContinuousEngine(wf, artifact=art, max_slots=3,
                              buckets=(8, 32), max_context=48,
                              name="aot_geom").start()
    try:
        assert not engine.artifact_mode
        assert counters.get("veles_artifact_load_failures_total") == \
            fails0 + 1
    finally:
        engine.stop()
    with pytest.raises(VelesError, match="different"):
        load_serve_programs(art, {"buckets": [8, 32]})


def test_v4_artifact_refused_with_counted_live_fallback(
        served_artifact, tmp_path):
    """Format-migration contract (v4 -> v5): a v4 artifact — exported
    before the mesh geometry ("tp"/"mesh") joined the signature — is
    REFUSED, counted in veles_artifact_load_failures_total, and the
    engine serves correct tokens via live jit instead of running
    programs whose sharding commitments are unknown."""
    import shutil
    lm, wf, art = served_artifact
    from veles_tpu.nn import sampling
    old = str(tmp_path / "v4_art")
    shutil.copytree(art, old)
    cpath = os.path.join(old, "contents.json")
    with open(cpath) as fin:
        contents = json.load(fin)
    contents["serving"]["artifact_version"] = 4
    for key in ("tp", "mesh"):
        contents["serving"]["signature"].pop(key, None)
    with open(cpath, "w") as fout:
        json.dump(contents, fout)
    with pytest.raises(VelesError, match="different"):
        load_serve_programs(old, ContinuousEngine(
            wf, name="aot_v4_sig", **KNOBS).stack_signature())
    engine = _fallback_engine(wf, old, "aot_v4")
    try:
        req = make_request(_prompt(lm, 93), 5)
        assert engine.serve([req])[0] == sampling.generate(
            wf, req["prompt"], req["n_new"], temperature=0)
    finally:
        engine.stop()


def test_injected_artifact_load_fault_falls_back(served_artifact,
                                                 monkeypatch):
    lm, wf, art = served_artifact
    monkeypatch.setenv("VELES_FAULTS", "artifact.load:raise:times=1")
    engine = _fallback_engine(wf, art, "aot_fault")
    engine.stop()
    monkeypatch.setenv("VELES_FAULTS", "")


def test_api_survives_corrupt_artifact_over_http(served_artifact,
                                                 tmp_path):
    """The operator-facing guarantee: a server booted with a corrupt
    artifact WARNS and serves correct answers via live jit — 200s,
    not a crash loop."""
    import shutil
    lm, wf, art = served_artifact
    from veles_tpu.nn import sampling
    bad = str(tmp_path / "bad_api_art")
    shutil.copytree(art, bad)
    with open(os.path.join(bad, "contents.json"), "w") as fout:
        fout.write("{ not json")
    api = vt.GenerationAPI(wf, port=0, engine="continuous",
                           artifact=bad, name="aot_api", **KNOBS)
    api.initialize()
    try:
        p = _prompt(lm, 91, 9)
        body = json.dumps({"prompt": p, "n_new": 5}).encode()
        req = urllib.request.Request(
            "http://127.0.0.1:%d/generate" % api.port, data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            assert r.status == 200
            out = json.loads(r.read())
        assert out["tokens"] == sampling.generate(wf, p, 5,
                                                  temperature=0)
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/metrics" % api.port,
                timeout=30) as r:
            text = r.read().decode()
        assert "veles_serving_artifact_mode 0" in text
        assert "veles_artifact_load_failures_total" in text
    finally:
        api.stop()


def test_quantized_artifact_round_trip(served_artifact, tmp_path):
    """Artifact exported under the int8 policy: the signature pins the
    quant flags, a quant-matched engine loads it and serves the same
    tokens as the live int8 engine."""
    lm, wf, art = served_artifact
    qart = str(tmp_path / "q_art")
    export_serve_artifact(wf, qart, quant_weights=True, quant_kv=True,
                          **KNOBS)
    reqs = _reqs(lm)
    live = ContinuousEngine(wf, quant_weights=True, quant_kv=True,
                            name="aot_qlive", **KNOBS).start()
    try:
        ref = live.serve(list(reqs))
    finally:
        live.stop()
    engine = ContinuousEngine(wf, artifact=qart, quant_weights=True,
                              quant_kv=True, name="aot_qeng",
                              **KNOBS).start()
    try:
        assert engine.artifact_mode
        assert engine.serve(list(reqs)) == ref
    finally:
        engine.stop()
    # a float engine must NOT load the int8 artifact
    engine = _fallback_engine(wf, qart, "aot_qmismatch")
    engine.stop()


# -- package format: v2 back-compat + v3 quant blocks --------------------------

class _SmallVecs(FullBatchLoader):
    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(2)
        n = 64
        self.create_originals(
            rng.rand(n, 12).astype(numpy.float32),
            rng.randint(0, 4, n).astype(numpy.int32))
        self.class_lengths = [0, 16, 48]


@pytest.fixture(scope="module")
def dense_wf():
    wf = nn.StandardWorkflow(
        name="quant-pkg-net",
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 24},
            {"type": "softmax", "output_sample_shape": 4},
        ],
        loader_unit=_SmallVecs(None, minibatch_size=16, name="vecs"),
        loss_function="softmax",
        decision_config=dict(max_epochs=1), steps_per_dispatch=2)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    return wf


def test_plain_package_still_v2_and_runs(dense_wf, tmp_path):
    pkg = str(tmp_path / "plain")
    package_export(dense_wf, pkg, with_stablehlo=False)
    loaded = package_import(pkg)
    assert loaded["contents"]["format_version"] == 2
    assert all("quant" not in u for u in loaded["contents"]["units"])
    batch = dense_wf.loader.original_data.mem[:6].copy()
    out = run_package(pkg, batch)
    assert out.shape == (6, 4)


def test_quant_package_v3_round_trips_metadata(dense_wf, tmp_path):
    pkg_fp = str(tmp_path / "fp")
    pkg_q = str(tmp_path / "q")
    package_export(dense_wf, pkg_fp, with_stablehlo=False)
    package_export(dense_wf, pkg_q, with_stablehlo=False, quant=True)
    contents = package_import(pkg_q)["contents"]
    assert contents["format_version"] == 3
    assert contents["quant"]["granularity"] == "per_channel"
    assert contents["quant"]["params"] >= 1
    # the eligible 2-D weight is int8 on disk with a scale sidecar...
    unit0 = contents["units"][0]
    assert unit0["quant"]["weights"]["scheme"] == "int8"
    raw = numpy.load(os.path.join(
        pkg_q, unit0["params"]["weights"]))
    assert raw.dtype == numpy.int8
    assert os.path.exists(os.path.join(
        pkg_q, unit0["quant"]["weights"]["scale"]))
    # ...but import dequantizes: consumers see float tensors within
    # the per-channel rounding bound of the plain export
    params_fp = package_import(pkg_fp)["params"]
    params_q = package_import(pkg_q)["params"]
    w_fp = params_fp["all2all_tanh0"]["weights"]
    w_q = params_q["all2all_tanh0"]["weights"]
    assert w_q.dtype == w_fp.dtype
    bound = numpy.abs(w_fp).max(axis=0) / (2 * 127) + 1e-6
    assert (numpy.abs(w_q - w_fp) <= bound[None, :]).all()
    # small tensors (softmax head here) stay float and bit-identical
    assert (params_q["softmax1"]["weights"]
            == params_fp["softmax1"]["weights"]).all()
    batch = dense_wf.loader.original_data.mem[:6].copy()
    numpy.testing.assert_allclose(
        run_package(pkg_q, batch), run_package(pkg_fp, batch),
        rtol=0.1, atol=0.05)
