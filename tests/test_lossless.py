"""Lossless request plane (ISSUE 13): durable router journal,
token-level failover resume, drain-by-handoff.

The contract under test: (1) every request the router ACCEPTS is on
disk (fsync'd, per-record hashed) before its first dispatch and
marked terminal on answer — a router SIGKILL loses zero accepted
requests, replay is idempotent by request_id and sheds expired
entries with the id, and a torn/corrupt record is quarantined with a
counted warning, never a refused start; (2) a decode killed at token
k (injected ``serve.replica_death`` / ``serve.decode_step``) hands
its emitted-token prefix back through the first-terminal ``fail()``,
and the failover retry RESUMES — prompt+prefix re-prefilled in one
bucketed pass, the per-slot PRNG stream advanced k splits — producing
token-for-token the uninterrupted solo decode for greedy AND sampled
modes; (3) a draining replica hands its in-flight tickets back at
the next step boundary (bounded by a handoff, not the longest
generation), with the ``serve.handoff`` fault degrading one ticket
to a plain shed, never blocking the drain. All chaos rides the
registered fault points — no monkeypatched internals.

Budget discipline: the journal/Ticket/gate tests are jax-free; the
identity drills share one tiny char_lm workflow module-wide.
"""
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy
import pytest

import veles_tpu as vt
from veles_tpu.resilience.faults import FaultInjected
from veles_tpu.serving import (ContinuousEngine, RequestJournal,
                               Ticket, fold_resume)
from veles_tpu.serving.engine import advanced_prng_key, make_request
from veles_tpu.serving.router import FleetRouter
from veles_tpu.telemetry.counters import counters, histograms

from conftest import import_model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _post(url, payload, timeout=120.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


# -- the journal (no jax) ------------------------------------------------------

def test_journal_append_pending_done_order(tmp_path):
    j = RequestJournal(str(tmp_path), fsync=False)
    j.admit("req-b", {"prompt": [1]}, 200.0)
    j.admit("req-a", {"prompt": [2]}, 100.0)
    j.admit("req-c", {"prompt": [3]}, 300.0)
    j.done("req-b", 200)
    # unanswered only, ordered by enqueued_at — the replay order
    assert [r["request_id"] for r in j.pending()] == ["req-a", "req-c"]
    j.done("req-a", 503, "expired")
    j.done("req-c", 200)
    assert j.pending() == []


def test_journal_duplicate_admits_are_idempotent(tmp_path):
    # a crash-looped router may re-journal the same id: replay must
    # yield it once (first admit wins)
    j = RequestJournal(str(tmp_path), fsync=False)
    j.admit("req-1", {"n": 1}, 100.0)
    j.admit("req-1", {"n": 2}, 150.0)
    pending = j.pending()
    assert len(pending) == 1
    assert pending[0]["body"] == {"n": 1}


def test_journal_torn_tail_salvaged_counted(tmp_path):
    j = RequestJournal(str(tmp_path), fsync=False)
    j.admit("req-1", {"prompt": [1]}, 100.0)
    j.admit("req-2", {"prompt": [2]}, 101.0)
    # a power cut mid-append leaves a torn tail line
    with open(j._active_path(), "a") as f:
        f.write('{"op": "admit", "request_id": "req-torn", "enq')
    before = counters.get("veles_journal_salvaged_total")
    pending = j.pending()
    assert [r["request_id"] for r in pending] == ["req-1", "req-2"]
    assert counters.get("veles_journal_salvaged_total") - before == 1


def test_journal_bitrot_fails_record_hash(tmp_path):
    j = RequestJournal(str(tmp_path), fsync=False)
    j.admit("req-1", {"prompt": [1]}, 100.0)
    j.admit("req-2", {"prompt": [2]}, 101.0)
    path = j._active_path()
    with open(path) as f:
        lines = f.readlines()
    # valid JSON, silently flipped payload: the per-record hash is
    # what catches it (a plain JSON parse would accept it)
    rotted = lines[0].replace('"prompt": [1]', '"prompt": [9]')
    assert rotted != lines[0]
    with open(path, "w") as f:
        f.writelines([rotted, lines[1]])
    before = counters.get("veles_journal_salvaged_total")
    assert [r["request_id"] for r in j.pending()] == ["req-2"]
    assert counters.get("veles_journal_salvaged_total") - before == 1


def test_journal_injected_corruption_salvaged(tmp_path, monkeypatch):
    """The router.journal fault point, append side: an armed corrupt
    clause damages the written bytes — replay quarantines the torn
    record with a counted warning instead of refusing to start."""
    j = RequestJournal(str(tmp_path), fsync=False)
    monkeypatch.setenv("VELES_FAULTS",
                       "router.journal:corrupt:times=1")
    inj = counters.get("veles_faults_injected_total")
    j.admit("req-corrupt", {"prompt": [1]}, 100.0)
    assert counters.get("veles_faults_injected_total") - inj == 1
    monkeypatch.delenv("VELES_FAULTS")
    j.admit("req-clean", {"prompt": [2]}, 101.0)
    before = counters.get("veles_journal_salvaged_total")
    pending = j.pending()        # the salvage pass IS the start path
    assert [r["request_id"] for r in pending] == ["req-clean"]
    assert counters.get("veles_journal_salvaged_total") - before == 1


def test_journal_append_raise_propagates(tmp_path, monkeypatch):
    # raise at append = the admission must be REFUSED (the router
    # sheds it), never acknowledged un-journaled
    j = RequestJournal(str(tmp_path), fsync=False)
    monkeypatch.setenv("VELES_FAULTS", "router.journal:raise:times=1")
    with pytest.raises(FaultInjected):
        j.admit("req-1", {"prompt": [1]}, 100.0)
    monkeypatch.delenv("VELES_FAULTS")
    assert j.pending() == []


def test_journal_compaction_keeps_live_only(tmp_path):
    j = RequestJournal(str(tmp_path), rotate_every=16, fsync=False)
    before = counters.get("veles_journal_compactions_total")
    for i in range(10):
        j.admit("req-%d" % i, {"i": i}, 100.0 + i)
        if i % 2 == 0:
            j.done("req-%d" % i, 200)
    j.compact()
    assert counters.get("veles_journal_compactions_total") \
        - before >= 1
    segs = j.segments()
    assert len(segs) == 1
    # the compacted segment carries the checkpoint-chain manifest
    assert os.path.exists(segs[0] + ".manifest.json")
    live = [r["request_id"] for r in j.pending()]
    assert live == ["req-%d" % i for i in range(10) if i % 2]
    # appends continue into the fresh segment; terminals still land
    j.done("req-1", 200)
    assert "req-1" not in [r["request_id"] for r in j.pending()]


def test_journal_auto_rotates_past_rotate_every(tmp_path):
    j = RequestJournal(str(tmp_path), rotate_every=16, fsync=False)
    before = counters.get("veles_journal_compactions_total")
    for i in range(10):
        j.admit("req-%d" % i, {"i": i}, 100.0 + i)
        j.done("req-%d" % i, 200)
    assert counters.get("veles_journal_compactions_total") \
        - before >= 1
    assert j.pending() == []


# -- ticket progress + resume payload (no jax) --------------------------------

def test_error_payload_carries_resume():
    t = Ticket(mode="sample")
    t.set_progress([5, 6, 7])
    assert t.fail("died mid-decode", code=503, retry_after=1.0)
    body = t.error_payload()
    assert body["resume"] == {"tokens": [5, 6, 7], "tokens_done": 3}
    assert body["request_id"] == t.request_id


def test_progress_only_for_step_modes_and_pre_terminal():
    spec = Ticket(mode="speculative")
    spec.set_progress([1, 2])
    assert spec.progress is None        # spec/beam retry from scratch
    t = Ticket(mode="greedy")
    t.fail("gone", code=503)
    t.set_progress([1])                 # after terminal: no-op
    assert t.progress is None
    assert "resume" not in t.error_payload()


def test_fold_resume_arithmetic():
    req = make_request([1, 2, 3], 8, temperature=0.7, seed=4,
                       mode="sample")
    folded = fold_resume(req, [9, 8])
    assert folded["prompt"] == [1, 2, 3, 9, 8]
    assert folded["n_new"] == 6 and folded["resume_k"] == 2
    assert fold_resume(req, [])["resume_k"] == 0
    with pytest.raises(ValueError):
        fold_resume(make_request([1], 2), [7, 7])


def test_advanced_prng_key_matches_split_chain():
    import jax
    key = jax.random.PRNGKey(11)
    for _ in range(5):
        key = jax.random.split(key)[0]
    assert numpy.array_equal(numpy.asarray(key),
                             numpy.asarray(advanced_prng_key(11, 5)))


# -- gate arithmetic (live proof stubbed; the drills below ARE live) ----------

def _bench():
    sys.path.insert(0, REPO)
    sys.path.insert(0, os.path.join(REPO, "models"))
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    return bench


def test_gate_lossless_doc_checks(monkeypatch):
    bench = _bench()
    monkeypatch.setattr(bench, "_lossless_resume_proof", lambda: [])
    sec = bench._lossless_section()
    assert set(sec) == {"journal_appends", "journal_replayed",
                        "journal_salvaged", "journal_compactions",
                        "resume_attempts", "resume_tokens",
                        "handoff_requests"}
    clean = {"lossless": {k: 0 for k in sec}}
    leaked = {"lossless": dict(clean["lossless"], resume_attempts=2)}
    failures = bench.gate_lossless(clean, leaked)
    assert any("leaked" in f for f in failures)
    assert not bench.gate_lossless(clean, clean)


# -- the identity drills (one tiny LM, module-scoped) -------------------------

@pytest.fixture(scope="module")
def lm_wf():
    lm = import_model("char_lm")
    from veles_tpu import prng
    prng.seed_all(2025)
    wf = lm.build_workflow(epochs=1, minibatch_size=32, n_blocks=1,
                           dim=32, n_train=64, n_valid=32)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    return lm, wf


@pytest.mark.parametrize("temperature,seed",
                         [(0.0, 0), (0.9, 41)],
                         ids=["greedy", "sampled"])
def test_engine_resume_is_id_exact(lm_wf, monkeypatch, temperature,
                                   seed):
    """THE resume identity, engine-level and deterministic: a decode
    killed at token k (injected serve.decode_step fault) hands back
    its emitted prefix; a SECOND engine serves the folded resume and
    the concatenation equals the uninterrupted solo decode
    token-for-token — greedy AND sampled."""
    from veles_tpu.nn import sampling
    lm, wf = lm_wf
    mode = "sample" if temperature > 0 else "greedy"
    prompt = [1, 5, 3, 2, 4]
    n_new = 12
    solo = sampling.generate(wf, prompt, n_new,
                             temperature=temperature, seed=seed)
    req = make_request(prompt, n_new, temperature=temperature,
                       seed=seed, mode=mode)
    e1 = ContinuousEngine(wf, max_slots=2, buckets=(8, 16, 32),
                          max_context=48, decode_block=1,
                          name="resume_a_" + mode).start()
    try:
        t1 = Ticket(mode=mode)
        monkeypatch.setenv("VELES_FAULTS",
                           "serve.decode_step:raise:after=4,times=1")
        assert e1.submit(req, t1)
        assert t1.event.wait(60)
        monkeypatch.delenv("VELES_FAULTS")
        assert t1.code == 503 and t1.progress
        k = len(t1.progress)
        assert 0 < k < n_new
        assert t1.progress == solo[:k]
        assert t1.error_payload()["resume"]["tokens_done"] == k
    finally:
        e1.stop()
    rt = counters.get("veles_resume_tokens_total")
    e2 = ContinuousEngine(wf, max_slots=2, buckets=(8, 16, 32),
                          max_context=48, decode_block=1,
                          name="resume_b_" + mode).start()
    try:
        t2 = Ticket(mode=mode)
        assert e2.submit(fold_resume(req, t1.progress), t2)
        assert t2.event.wait(60)
        assert t2.error is None, t2.error
        assert t1.progress + t2.result["tokens"] == solo
        assert counters.get("veles_resume_tokens_total") - rt == k
    finally:
        e2.stop()


def test_fleet_death_resume_id_exact_sampled(lm_wf, monkeypatch):
    """THE acceptance drill, HTTP end-to-end with a SAMPLED decode: a
    2-replica fleet, serve.replica_death armed to fire a few decode
    ticks in — the dying replica's gasp (503 + resume) makes the
    router RESUME on the survivor, and the stitched answer equals the
    uninterrupted solo decode exactly, counted and exactly-once."""
    from veles_tpu.nn import sampling
    lm, wf = lm_wf
    prompt = [2, 4, 1, 3, 5]
    n_new = 12
    solo = sampling.generate(wf, prompt, n_new, temperature=0.8,
                             seed=17)
    apis = [vt.GenerationAPI(wf, port=0, engine="continuous",
                             max_slots=2, buckets=(8, 16, 32),
                             max_context=48,
                             name="gasp_%d" % i) for i in range(2)]
    for api in apis:
        api.initialize()
    router = None
    try:
        router = FleetRouter(
            ["127.0.0.1:%d" % api.port for api in apis],
            probe_interval=0.2, failure_threshold=1, retry_budget=2,
            attempt_timeout=60.0, request_timeout=120.0,
            name="gasp_router").start()
        url = "http://127.0.0.1:%d/generate" % router.port
        # warm both replicas' programs outside the armed window
        for api in apis:
            code, _b, _h = _post(
                "http://127.0.0.1:%d/generate" % api.port,
                {"prompt": prompt, "n_new": 2, "mode": "sample",
                 "temperature": 0.8, "seed": 17})
            assert code == 200
        ra = counters.get("veles_resume_attempts_total")
        fo = counters.get("veles_router_failovers_total")
        monkeypatch.setenv(
            "VELES_FAULTS", "serve.replica_death:raise:after=4,times=1")
        code, body, _ = _post(url, {"prompt": prompt, "n_new": n_new,
                                    "mode": "sample",
                                    "temperature": 0.8, "seed": 17})
        monkeypatch.delenv("VELES_FAULTS")
        assert code == 200, body
        assert body["tokens"] == solo          # id-exact across death
        k = body.get("resumed_from", 0)
        assert k >= 1                          # it RESUMED, not redid
        assert counters.get("veles_resume_attempts_total") - ra >= 1
        assert counters.get("veles_router_failovers_total") - fo >= 1
    finally:
        if router is not None:
            router.stop()
        for api in apis:
            api.stop()


def test_window_plane_greedy_resume_and_sampled_409(lm_wf):
    """The window-plane exclusions: a greedy resume MAY ride the
    window worker (deterministic — the folded prompt continues
    exactly); a sampled resume is answered 409 (the PRNG stream lives
    on the slot pool only), which tells a router to retry from
    scratch."""
    from veles_tpu.nn import sampling
    lm, wf = lm_wf
    prompt = [1, 2, 3, 4]
    solo = sampling.generate(wf, prompt, 8, temperature=0)
    api = vt.GenerationAPI(wf, port=0, engine="window",
                           name="window_resume")
    api.initialize()
    base = "http://127.0.0.1:%d/generate" % api.port
    try:
        code, body, _ = _post(base, {
            "prompt": prompt, "n_new": 5, "mode": "greedy",
            "resume_tokens": solo[:3]})
        assert code == 200
        assert solo[:3] + body["tokens"] == solo
        code, body, _ = _post(base, {
            "prompt": prompt, "n_new": 5, "mode": "sample",
            "temperature": 0.8, "seed": 3,
            "resume_tokens": solo[:3]})
        assert code == 409
        assert "resume" in body["error"] and "request_id" in body
    finally:
        api.stop()


def test_router_409_drops_resume_and_retries_from_scratch():
    """A replica that answers 409 to a resume attempt is healthy: the
    router drops the prefix, gives the replica its roster slot back,
    retries from scratch and delivers — without advancing the 409
    replica's breaker."""
    state = {"a_posts": [], "b_posts": []}

    def handler(key, resume_answer):
        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/readyz":
                    self._reply(200, {"status": "ok"})
                else:
                    self.send_error(404)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                state[key].append(req)
                self._reply(*resume_answer(req))

            def _reply(self, code, payload):
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
        return H

    def a_answer(req):
        # A always dies with a gasp carrying progress
        return 503, {"error": "dying", "request_id":
                     req.get("request_id"),
                     "resume": {"tokens": [7, 8], "tokens_done": 2}}

    def b_answer(req):
        if req.get("resume_tokens"):
            return 409, {"error": "resume not servable here",
                         "request_id": req.get("request_id")}
        return 200, {"tokens": [7, 8, 9, 10],
                     "request_id": req.get("request_id")}

    srv_a = ThreadingHTTPServer(("127.0.0.1", 0),
                                handler("a_posts", a_answer))
    srv_b = ThreadingHTTPServer(("127.0.0.1", 0),
                                handler("b_posts", b_answer))
    for srv in (srv_a, srv_b):
        threading.Thread(target=srv.serve_forever,
                         daemon=True).start()
    router = None
    try:
        router = FleetRouter(
            ["127.0.0.1:%d" % srv_a.server_port,
             "127.0.0.1:%d" % srv_b.server_port],
            probe_interval=30.0, failure_threshold=3,
            retry_budget=2, attempt_timeout=10.0,
            request_timeout=30.0, name="r409").start()
        # rank A first so the gasp precedes the 409
        for r in router.replicas:
            r.ready = True
            r.slots, r.slots_busy = (
                (4, 0) if str(srv_a.server_port) in r.url else (4, 3))
        answered = router.route({"prompt": [1], "n_new": 4,
                                 "mode": "greedy"})
        assert answered.done and answered.status == 200
        assert answered.body["tokens"] == [7, 8, 9, 10]
        # B saw the resume attempt, then the from-scratch retry
        assert state["b_posts"][0].get("resume_tokens") == [7, 8]
        assert "resume_tokens" not in state["b_posts"][1]
        assert state["b_posts"][1]["n_new"] == 4
        b = [r for r in router.replicas
             if str(srv_b.server_port) in r.url][0]
        assert b.breaker.failures == 0        # 409 is not a failure
    finally:
        if router is not None:
            router.stop()
        srv_a.shutdown()
        srv_b.shutdown()


# -- drain-by-handoff ---------------------------------------------------------

def test_drain_handoff_bounded_by_handoff_not_generation(lm_wf):
    """THE drain acceptance leg: a replica with a LONG in-flight
    generation drains within handoff time, not generation time — the
    ticket comes back 503 + resume progress, and through a router the
    request finishes on the other replica, id-exact."""
    from veles_tpu.nn import sampling
    lm, wf = lm_wf
    prompt = [3, 1, 4, 1, 5]
    n_new = 80
    solo = sampling.generate(wf, prompt, n_new, temperature=0)
    apis = [vt.GenerationAPI(wf, port=0, engine="continuous",
                             max_slots=2, buckets=(8, 16, 32, 48),
                             max_context=96,
                             name="handoff_%d" % i) for i in range(2)]
    for api in apis:
        api.initialize()
    router = None
    try:
        router = FleetRouter(
            ["127.0.0.1:%d" % api.port for api in apis],
            probe_interval=0.2, failure_threshold=2, retry_budget=2,
            attempt_timeout=120.0, request_timeout=180.0,
            name="handoff_router").start()
        url = "http://127.0.0.1:%d/generate" % router.port
        # warm both replicas + measure the uninterrupted decode time
        t0 = time.time()
        for api in apis:
            code, _b, _h = _post(
                "http://127.0.0.1:%d/generate" % api.port,
                {"prompt": prompt, "n_new": n_new})
            assert code == 200
        uninterrupted = (time.time() - t0) / 2
        results = {}

        def long_post():
            results["r"] = _post(url, {"prompt": prompt,
                                       "n_new": n_new})

        t = threading.Thread(target=long_post)
        t.start()
        # catch the request MID-DECODE: poll the engines' slot
        # occupancy (not just the HTTP in-flight count — a request
        # still queued, or already retired, has nothing to hand off)
        busy = None
        deadline = time.time() + 15
        while busy is None and time.time() < deadline:
            busy = next(
                (api for api in apis
                 if api._engine is not None
                 and api._engine.scheduler.busy_count()), None)
            if busy is None:
                time.sleep(0.001)
        assert busy is not None
        ho = counters.get("veles_handoff_requests_total")
        drain_t0 = time.time()
        assert busy.drain(grace=60) is True
        drain_elapsed = time.time() - drain_t0
        assert counters.get("veles_handoff_requests_total") - ho == 1
        # bounded by a handoff, not by the generation: the drained
        # replica never rode out the remaining decode
        assert drain_elapsed < max(1.0, 0.75 * uninterrupted), \
            (drain_elapsed, uninterrupted)
        t.join(timeout=120)
        code, body, _ = results["r"]
        assert code == 200
        assert body["tokens"] == solo          # finished elsewhere
        assert body.get("resumed_from", 0) >= 1
    finally:
        if router is not None:
            router.stop()
        for api in apis:
            api.stop()


def test_handoff_snapshot_fault_degrades_to_plain_shed(lm_wf,
                                                       monkeypatch):
    """serve.handoff chaos: a failed progress snapshot mid-drain
    degrades that ticket to a plain 503 (no resume record) — the
    drain still completes and the caller still gets its terminal."""
    lm, wf = lm_wf
    api = vt.GenerationAPI(wf, port=0, engine="continuous",
                           max_slots=2, buckets=(8, 16, 32),
                           max_context=64, name="handoff_fault")
    api.initialize()
    base = "http://127.0.0.1:%d" % api.port
    try:
        code, _b, _h = _post(base + "/generate",
                             {"prompt": [1, 2, 3], "n_new": 2})
        assert code == 200                     # warm
        results = {}

        def long_post():
            results["r"] = _post(base + "/generate",
                                 {"prompt": [1, 2, 3, 4],
                                  "n_new": 48})

        t = threading.Thread(target=long_post)
        t.start()
        deadline = time.time() + 15
        while not (api._engine is not None
                   and api._engine.scheduler.busy_count()) \
                and time.time() < deadline:
            time.sleep(0.001)
        ho = counters.get("veles_handoff_requests_total")
        monkeypatch.setenv("VELES_FAULTS", "serve.handoff:raise")
        assert api.drain(grace=60) is True
        monkeypatch.delenv("VELES_FAULTS")
        assert counters.get("veles_handoff_requests_total") == ho
        t.join(timeout=30)
        code, body, _ = results["r"]
        assert code == 503
        assert "resume" not in body            # degraded, not blocked
        assert "request_id" in body
    finally:
        api.stop()


# -- the drain/stop abort path: one terminal per ticket -----------------------

def test_double_drain_stop_records_one_terminal(lm_wf):
    """Satellite regression: stragglers aborted by drain()/stop()
    settle via the first-terminal fail() — histogram sample and
    terminal exactly once however many sweeps run."""
    lm, wf = lm_wf
    engine = ContinuousEngine(wf, max_slots=2, buckets=(8,),
                              max_context=48, decode_block=1,
                              name="double_stop").start()
    req = make_request([1, 2, 3], 32)
    ticket = Ticket(mode="greedy")
    assert engine.submit(req, ticket)
    deadline = time.time() + 15
    while ticket.admitted is None and time.time() < deadline:
        time.sleep(0.005)
    assert ticket.admitted is not None
    qw = histograms.count("veles_serving_queue_wait_seconds")
    engine.stop()
    assert ticket.event.is_set() and ticket.code == 503
    assert ticket.progress                     # abort handed progress
    # the double sweep: a second stop + explicit abort re-run
    engine.stop()
    engine._abort_active("late sweep", code=503)
    engine.scheduler.drain("late sweep")
    assert ticket.fail("third sweep", code=503) is False
    assert histograms.count("veles_serving_queue_wait_seconds") \
        - qw == 1
    assert ticket.outcome == "shed"


def test_restful_stop_sweep_settles_outstanding_once():
    wf = vt.Workflow(name="sweep_wf")
    api = vt.RESTfulAPI(wf, loader=None, port=0)
    ticket = Ticket(mode="greedy")
    api._outstanding.add(ticket)
    qw = histograms.count("veles_serving_queue_wait_seconds")
    api.stop()
    assert ticket.event.is_set() and ticket.code == 503
    assert ticket.retry_after == 5.0
    body = ticket.error_payload()
    assert body["request_id"] == ticket.request_id
    api.stop()                                  # double sweep: no-op
    assert histograms.count("veles_serving_queue_wait_seconds") \
        - qw == 1


# -- journal replay after SIGKILL, end to end on the route CLI ----------------

def _fake_replica(state=None):
    state = dict({"delay": 0.0, "served": []}, **(state or {}))

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.path == "/readyz":
                body = json.dumps({"status": "ok"}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_error(404)

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(n) or b"{}")
            if state["delay"]:
                time.sleep(state["delay"])
            state["served"].append(req.get("request_id"))
            body = json.dumps({"tokens": [1, 2, 3],
                               "request_id":
                               req.get("request_id")}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, state


def _start_route_cli(endpoints_file, journal_dir):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "veles_tpu", "route",
         "--endpoints-file", str(endpoints_file), "--port", "0",
         "--probe-interval", "0.2", "--journal", str(journal_dir),
         "--request-timeout", "60"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=REPO)
    line = proc.stdout.readline()
    assert line.startswith("ROUTING port="), line
    return proc, int(line.split("port=")[1].split()[0])


@pytest.mark.skipif(sys.platform.startswith("win"),
                    reason="SIGKILL semantics")
def test_journal_replay_after_sigkill_answers_every_request(tmp_path):
    """THE durability drill: a journaled route CLI is SIGKILLed with
    requests accepted-but-unanswered; the restarted router replays
    them — every journaled request reaches the replica and EXACTLY
    one terminal record, none lost, none double-terminal."""
    srv, state = _fake_replica({"delay": 1.0})
    endpoints = tmp_path / "fleet.txt"
    endpoints.write_text("127.0.0.1:%d\n" % srv.server_port)
    journal_dir = tmp_path / "journal"
    proc, port = _start_route_cli(endpoints, journal_dir)
    url = "http://127.0.0.1:%d/generate" % port
    rids = ["req-kill-%d" % i for i in range(3)]
    try:
        # one request completes before the kill...
        code, body, _ = _post(url, {"prompt": [1], "n_new": 2,
                                    "request_id": "req-done-0"})
        assert code == 200
        # ...three more are accepted (journaled) and in flight when
        # the router is SIGKILLed mid-load
        threads = [threading.Thread(
            target=lambda r=r: _post(url, {"prompt": [1], "n_new": 2,
                                           "request_id": r}),
            daemon=True) for r in rids]
        for t in threads:
            t.start()
        time.sleep(0.4)                 # admitted, not yet answered
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    journal = RequestJournal(str(journal_dir), fsync=False)
    pending = [r["request_id"] for r in journal.pending()]
    assert set(rids) & set(pending), \
        "SIGKILL left nothing pending — the drill never armed"
    assert "req-done-0" not in pending  # terminal before the kill
    # restart: the replay must answer every journaled request
    state["delay"] = 0.0
    before = counters.get("veles_journal_replayed_total")
    proc2, _port2 = _start_route_cli(endpoints, journal_dir)
    try:
        deadline = time.time() + 30
        while journal.pending() and time.time() < deadline:
            time.sleep(0.1)
        assert journal.pending() == [], "replay left entries pending"
        admits, terminals = journal.replay()
        # exactly one terminal per accepted request, every replayed
        # request actually reached the replica
        for rid in rids + ["req-done-0"]:
            assert rid in terminals, rid
        for rid in pending:
            assert rid in state["served"], rid
    finally:
        proc2.send_signal(signal.SIGTERM)
        try:
            assert proc2.wait(timeout=30) == 0
        finally:
            if proc2.poll() is None:
                proc2.kill()
                proc2.wait()
        srv.shutdown()
    # the test-process journal reads never count replays — only the
    # restarted router's own process did the replaying
    assert counters.get("veles_journal_replayed_total") == before
