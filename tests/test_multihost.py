"""Multi-host training: two REAL processes, one logical 2-device mesh.

The reference tested its distributed plane by running a real master and
real slaves in-process against loopback sockets (veles/tests/
test_network.py, test_launcher.py — SURVEY.md §4 "the real stack is
considered cheap enough to spin up"). The equivalent here: two OS
processes join through the jax distributed coordinator (gloo over
loopback), train the same workflow SPMD over the spanned mesh, and the
coordinator alone writes results.
"""
import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = textwrap.dedent("""
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("XLA_FLAGS", None)      # exactly 1 device per process
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, %(repo)r)
    import numpy
    import veles_tpu as vt
    from veles_tpu import nn
    from veles_tpu.launcher import Launcher
    from veles_tpu.loader import FullBatchLoader

    class Toy(FullBatchLoader):
        hide_from_registry = True
        def load_data(self):
            rng = numpy.random.RandomState(0)
            x = rng.rand(128, 6).astype(numpy.float32)
            y = (x[:, 0] > 0.5).astype(numpy.int32)
            self.create_originals(x, y)
            self.class_lengths = [0, 32, 96]

    pid = int(sys.argv[1])
    launcher = Launcher(coordinator="127.0.0.1:%(port)d",
                        num_processes=2, process_id=pid,
                        mesh={"data": 2}, random_seed=11)
    wf = nn.StandardWorkflow(
        name="mh",
        layers=[{"type": "softmax", "output_sample_shape": 2,
                 "learning_rate": 0.2}],
        loader_unit=Toy(None, minibatch_size=32),
        loss_function="softmax",
        decision_config=dict(max_epochs=4))
    launcher.initialize(wf)
    assert launcher.device.mesh.devices.size == 2
    results = launcher.run()
    launcher.write_results(results, %(out)r + str(pid) + ".json")
    print("RANK%%d DONE err=%%.4f" %% (pid, results["best_err"]))
""")


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_training(tmp_path):
    port = free_port()
    script = tmp_path / "child.py"
    out = str(tmp_path / "results_rank")
    script.write_text(CHILD % {"repo": REPO, "port": port, "out": out})
    procs = [subprocess.Popen([sys.executable, str(script), str(i)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True,
                              cwd=REPO)
             for i in range(2)]
    outs = []
    for p in procs:
        stdout, _ = p.communicate(timeout=300)
        outs.append(stdout)
    for i, (p, stdout) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, "rank %d:\n%s" % (i, stdout[-3000:])
        assert "RANK%d DONE" % i in stdout
    # coordinator-only results write (reference: master-only snapshots)
    assert os.path.exists(out + "0.json")
    assert not os.path.exists(out + "1.json")
    with open(out + "0.json") as fin:
        res = json.load(fin)
    assert res["epochs"] >= 4 and res["best_err"] < 0.5
