"""Multi-host training: two REAL processes, one logical 2-device mesh.

The reference tested its distributed plane by running a real master and
real slaves in-process against loopback sockets (veles/tests/
test_network.py, test_launcher.py — SURVEY.md §4 "the real stack is
considered cheap enough to spin up"). The equivalent here: two OS
processes join through the jax distributed coordinator (gloo over
loopback), train the same workflow SPMD over the spanned mesh, and the
coordinator alone writes results.
"""
import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = textwrap.dedent("""
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("XLA_FLAGS", None)      # exactly 1 device per process
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, %(repo)r)
    import numpy
    import veles_tpu as vt
    from veles_tpu import nn
    from veles_tpu.launcher import Launcher
    from veles_tpu.loader import FullBatchLoader

    class Toy(FullBatchLoader):
        hide_from_registry = True
        def load_data(self):
            rng = numpy.random.RandomState(0)
            x = rng.rand(128, 6).astype(numpy.float32)
            y = (x[:, 0] > 0.5).astype(numpy.int32)
            self.create_originals(x, y)
            self.class_lengths = [0, 32, 96]

    pid = int(sys.argv[1])
    launcher = Launcher(coordinator="127.0.0.1:%(port)d",
                        num_processes=%(nproc)d, process_id=pid,
                        mesh={"data": %(nproc)d}, random_seed=11)
    wf = nn.StandardWorkflow(
        name="mh",
        layers=[{"type": "softmax", "output_sample_shape": 2,
                 "learning_rate": 0.2}],
        loader_unit=Toy(None, minibatch_size=32),
        loss_function="softmax",
        decision_config=dict(max_epochs=%(max_epochs)d))
    launcher.initialize(wf)
    assert launcher.device.mesh.devices.size == %(nproc)d
    results = launcher.run()
    launcher.write_results(results, %(out)r + str(pid) + ".json")
    print("RANK%%d DONE err=%%.4f" %% (pid, results["best_err"]))
""")


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_training(tmp_path):
    port = free_port()
    script = tmp_path / "child.py"
    out = str(tmp_path / "results_rank")
    script.write_text(CHILD % {"repo": REPO, "port": port, "out": out,
                               "nproc": 2, "max_epochs": 4})
    procs = [subprocess.Popen([sys.executable, str(script), str(i)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True,
                              cwd=REPO)
             for i in range(2)]
    outs = []
    for p in procs:
        stdout, _ = p.communicate(timeout=300)
        outs.append(stdout)
    for i, (p, stdout) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, "rank %d:\n%s" % (i, stdout[-3000:])
        assert "RANK%d DONE" % i in stdout
    # coordinator-only results write (reference: master-only snapshots)
    assert os.path.exists(out + "0.json")
    assert not os.path.exists(out + "1.json")
    with open(out + "0.json") as fin:
        res = json.load(fin)
    assert res["epochs"] >= 4 and res["best_err"] < 0.5


def test_four_process_training(tmp_path):
    """SPMD over a 4-process × 1-device logical mesh (VERDICT r2 #9:
    multihost depth beyond the 2-process pair)."""
    port = free_port()
    script = tmp_path / "child4.py"
    out = str(tmp_path / "r4_rank")
    script.write_text(CHILD % {"repo": REPO, "port": port, "out": out,
                               "nproc": 4, "max_epochs": 2})
    procs = [subprocess.Popen([sys.executable, str(script), str(i)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True,
                              cwd=REPO)
             for i in range(4)]
    outs = []
    for p in procs:
        stdout, _ = p.communicate(timeout=600)
        outs.append(stdout)
    for i, (p, stdout) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, "rank %d:\n%s" % (i, stdout[-3000:])
        assert "RANK%d DONE" % i in stdout
    assert os.path.exists(out + "0.json")
    # coordinator-only writes hold at every non-zero rank
    for i in (1, 2, 3):
        assert not os.path.exists(out + "%d.json" % i)


DRILL_CHILD = textwrap.dedent("""
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("XLA_FLAGS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, %(repo)r)
    import numpy
    import veles_tpu as vt
    from veles_tpu import nn
    from veles_tpu.launcher import Launcher
    from veles_tpu.loader import FullBatchLoader

    class Toy(FullBatchLoader):
        hide_from_registry = True
        def load_data(self):
            rng = numpy.random.RandomState(0)
            centers = rng.randn(3, 8) * 3
            y = rng.randint(0, 3, 192).astype(numpy.int32)
            x = (centers[y] + rng.randn(192, 8)).astype(numpy.float32)
            self.create_originals(x, y)
            self.class_lengths = [0, 32, 160]

    pid = int(sys.argv[1])
    port = int(sys.argv[2])
    max_epochs = int(sys.argv[3])
    launcher = Launcher(coordinator="127.0.0.1:%%d" %% port,
                        num_processes=2, process_id=pid,
                        mesh={"data": 2}, random_seed=11)
    snap = vt.Snapshotter(None, prefix="mhdrill",
                          directory=%(snapdir)r, interval=1)
    wf = nn.StandardWorkflow(
        name="mh-drill",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 8,
                 "learning_rate": 0.1},
                {"type": "softmax", "output_sample_shape": 3,
                 "learning_rate": 0.1}],
        loader_unit=Toy(None, minibatch_size=32),
        loss_function="softmax",
        decision_config=dict(max_epochs=max_epochs,
                             fail_iterations=100),
        snapshotter_unit=snap)
    launcher.initialize(wf)
    resumed = launcher.try_restore_latest()
    print("RANK%%d RESUMED=%%s epoch=%%d" %% (
        pid, resumed, wf.decision.epoch_number), flush=True)
    results = launcher.run()
    launcher.write_results(results, %(out)r + str(pid) + ".json")
    print("RANK%%d DONE epochs=%%d err=%%.4f" %% (
        pid, results["epochs"], results["best_err"]), flush=True)
""")


def test_coordinator_kill_and_resume(tmp_path):
    """The SPMD analog of the reference's slave-death story
    (veles/server.py:315-338): the COORDINATOR process is SIGKILLed
    mid-training; a fresh 2-process job over the same snapshot dir
    auto-resumes from the newest coordinator-written snapshot and
    completes."""
    import glob
    import signal
    import time
    snapdir = str(tmp_path / "snaps")
    os.makedirs(snapdir)
    out = str(tmp_path / "drill_rank")
    script = tmp_path / "drill.py"
    script.write_text(DRILL_CHILD % {
        "repo": REPO, "snapdir": snapdir, "out": out})

    # phase 1: effectively-unbounded epochs; killed once snapshots land
    port = free_port()
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), str(port), "1000"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=REPO) for i in range(2)]
    def real_snaps():
        # COMPLETE snapshots only: counting the '_current' link or an
        # in-flight '.tmp' partial would green-light the SIGKILL while
        # the coordinator is mid-write — exactly the race this drill
        # must not inject artificially
        return [p for p in glob.glob(os.path.join(snapdir, "*.pickle*"))
                if not p.endswith(".tmp") and "_current" not in p]

    deadline = time.time() + 240
    try:
        while time.time() < deadline:
            if len(real_snaps()) >= 2:
                break
            if any(p.poll() is not None for p in procs):
                break
            time.sleep(0.5)
        # liveness first: a startup crash must surface the child's
        # output, not a bare "no snapshot" message
        assert all(p.poll() is None for p in procs), \
            "phase-1 died early:\n" + "\n".join(
                p.communicate()[0][-2000:] for p in procs
                if p.poll() is not None)
        assert real_snaps(), "no snapshot before deadline"
        os.kill(procs[0].pid, signal.SIGKILL)      # the coordinator
    finally:
        for p in procs:
            try:
                p.kill()
            except OSError:
                pass
            p.communicate()

    # phase 2: fresh job, same dir — must resume past epoch 0 and finish
    port = free_port()
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), str(port), "6"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=REPO) for i in range(2)]
    outs = [p.communicate(timeout=300)[0] for p in procs]
    for i, (p, stdout) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, "rank %d:\n%s" % (i, stdout[-3000:])
        assert "RANK%d RESUMED=True" % i in stdout, stdout[-2000:]
        assert "RANK%d DONE" % i in stdout
    # resumed mid-trajectory, not from scratch
    import re
    epoch = int(re.search(r"RANK0 RESUMED=True epoch=(\d+)",
                          outs[0]).group(1))
    assert epoch >= 1, outs[0][-1000:]
    assert os.path.exists(out + "0.json")
    assert not os.path.exists(out + "1.json")
    with open(out + "0.json") as fin:
        res = json.load(fin)
    assert res["best_err"] < 0.35, res


SHARDED_CHILD = textwrap.dedent("""
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("XLA_FLAGS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, %(repo)r)
    import numpy
    import veles_tpu as vt
    from veles_tpu import nn
    from veles_tpu.launcher import Launcher
    from veles_tpu.loader import FullBatchLoader

    class Toy(FullBatchLoader):
        hide_from_registry = True
        def load_data(self):
            rng = numpy.random.RandomState(7)
            centers = rng.randn(3, 8) * 3
            y = rng.randint(0, 3, 192).astype(numpy.int32)
            x = (centers[y] + rng.randn(192, 8)).astype(numpy.float32)
            self.create_originals(x, y)
            self.class_lengths = [0, 32, 160]

    pid = int(sys.argv[1]); port = int(sys.argv[2])
    max_epochs = int(sys.argv[3]); snapdir = sys.argv[4]
    wout = sys.argv[5]; resume = sys.argv[6] == "resume"
    nproc = int(sys.argv[7])
    mesh = {k: int(v) for k, v in
            (kv.split(":") for kv in sys.argv[8].split(","))}
    launcher = Launcher(coordinator="127.0.0.1:%%d" %% port,
                        num_processes=nproc, process_id=pid,
                        mesh=mesh, random_seed=23)
    snap = (vt.Snapshotter(None, prefix="shck", directory=snapdir,
                           interval=1) if snapdir != "-" else None)
    wf = nn.StandardWorkflow(
        name="shck",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 8,
                 "solver": "adam", "learning_rate": 0.05,
                 "name": "fc0"},
                {"type": "softmax", "output_sample_shape": 3,
                 "solver": "adam", "learning_rate": 0.05,
                 "name": "head"}],
        loader_unit=Toy(None, minibatch_size=32),
        loss_function="softmax",
        decision_config=dict(max_epochs=max_epochs,
                             fail_iterations=100),
        snapshotter_unit=snap)
    launcher.initialize(wf)
    # the point of this drill: params genuinely span the processes
    w = wf.train_step.params["fc0"]["weights"]
    for ax in mesh:
        if ax in ("fsdp", "tensor"):
            assert any(ax == s or (isinstance(s, tuple) and ax in s)
                       for s in w.sharding.spec if s is not None), \
                (ax, w.sharding)
    assert not w.is_fully_addressable, "not cross-process sharded"
    if resume:
        assert launcher.try_restore_latest(), "nothing to resume"
        assert wf.decision.epoch_number >= 1
        wf.decision.complete <<= False
        print("RANK%%d RESUMED epoch=%%d" %% (
            pid, wf.decision.epoch_number), flush=True)
    launcher.run()
    # workflow stop already synced trained params to the host Arrays
    # on every rank (TrainStep.stop runs the gather in lockstep)
    if pid == 0:
        numpy.savez(wout,
                    w=numpy.asarray(wf.forwards[0].weights.map_read()))
    print("RANK%%d DONE epoch=%%d" %% (pid, wf.decision.epoch_number),
          flush=True)
""")


def _run_procs(script, argv, n=2, timeout=300):
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i)] + [str(a) for a in argv],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=REPO) for i in range(n)]
    try:
        outs = [p.communicate(timeout=timeout)[0] for p in procs]
    finally:
        # a rank deadlocked in a collective must not orphan live
        # children holding gloo/coordinator sockets for the whole run
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for i, (p, stdout) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, "rank %d:\n%s" % (i, stdout[-3000:])
        assert "RANK%d DONE" % i in stdout
    return outs


def _run_pair(script, argv, timeout=300):
    return _run_procs(script, argv, n=2, timeout=timeout)


def test_sharded_param_checkpoint_roundtrip(tmp_path):
    """fsdp-sharded params across TWO real processes: snapshot collection
    all-gathers the non-addressable shards (every rank participates,
    coordinator writes), resume device_puts them back onto the sharded
    mesh, and 2+2 epochs across the snapshot boundary reproduce 4
    straight epochs bit-for-bit (VERDICT r3 weak #7 — the one untested
    leg of checkpoint/resume)."""
    import numpy
    script = tmp_path / "shck.py"
    script.write_text(SHARDED_CHILD % {"repo": REPO})
    snapdir = str(tmp_path / "snaps")
    os.makedirs(snapdir)

    # A: 4 straight epochs, no snapshots
    wa = str(tmp_path / "wa.npz")
    _run_pair(script, [free_port(), 4, "-", wa, "straight", 2,
                       "fsdp:2"])
    # B1: 2 epochs, snapshot every epoch (coordinator-only files)
    wb1 = str(tmp_path / "wb1.npz")
    _run_pair(script, [free_port(), 2, snapdir, wb1, "straight", 2,
                       "fsdp:2"])
    import glob as _glob
    assert _glob.glob(os.path.join(snapdir, "shck_*.pickle.gz"))
    # B2: fresh pair resumes the sharded snapshot, continues to 4
    wb2 = str(tmp_path / "wb2.npz")
    outs = _run_pair(script, [free_port(), 4, snapdir, wb2, "resume",
                              2, "fsdp:2"])
    assert "RESUMED" in outs[0] and "RESUMED" in outs[1]

    a = numpy.load(wa)["w"]
    b = numpy.load(wb2)["w"]
    numpy.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_sharded_checkpoint_roundtrip_width4(tmp_path):
    """VERDICT r4 item 5: the sharded-checkpoint roundtrip at FOUR
    processes on an fsdp=4 mesh — collection all-gathers four
    non-addressable shards, resume re-shards them onto the 4-way mesh,
    and 2+2 epochs across the boundary reproduce 4 straight epochs
    bit-for-bit. The largest correctness surface previously proven
    only at width 2."""
    import numpy
    script = tmp_path / "shck4.py"
    script.write_text(SHARDED_CHILD % {"repo": REPO})
    snapdir = str(tmp_path / "snaps4")
    os.makedirs(snapdir)

    wa = str(tmp_path / "wa4.npz")
    _run_procs(script, [free_port(), 4, "-", wa, "straight", 4,
                        "fsdp:4"], n=4, timeout=420)
    wb1 = str(tmp_path / "wb14.npz")
    _run_procs(script, [free_port(), 2, snapdir, wb1, "straight", 4,
                        "fsdp:4"], n=4, timeout=420)
    wb2 = str(tmp_path / "wb24.npz")
    outs = _run_procs(script, [free_port(), 4, snapdir, wb2, "resume",
                               4, "fsdp:4"], n=4, timeout=420)
    for i in range(4):
        assert "RESUMED" in outs[i], outs[i][-2000:]

    a = numpy.load(wa)["w"]
    b = numpy.load(wb2)["w"]
    numpy.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_fsdp_tensor_composition_width4(tmp_path):
    """fsdp=2 × tensor=2 across four REAL processes (VERDICT r4 weak
    #7: the composition was only ever proven at width 2 / in-process):
    fc0's kernel carries BOTH mesh axes in its sharding spec, params
    are non-addressable, training runs to completion."""
    script = tmp_path / "shtp.py"
    script.write_text(SHARDED_CHILD % {"repo": REPO})
    wout = str(tmp_path / "wtp.npz")
    _run_procs(script, [free_port(), 2, "-", wout, "straight", 4,
                        "fsdp:2,tensor:2"], n=4, timeout=420)
