"""Real-data quality anchor (VERDICT r2 missing #2, adapted to this
rig's constraints: MNIST/CIFAR bytes do not exist anywhere on this disk
and egress is zero — `veles_tpu.datasets` stands ready to load real
MNIST from idx/npz the moment bytes appear, `mnist_is_real()` stays
honest. The one REAL dataset shipped in-image is sklearn's bundled UCI
handwritten digits (1797 x 8x8, Alpaydin/Kaynak) — small, but real
pixels with real label noise, unlike every synthetic-surrogate CI gate
(tests/test_models_ci.py admits those prove wiring, not quality).

Anchor: the MNIST-style FC stack at digits scale must reach <= 5% test
error on a fixed held-out split. Chance is 90%; a wiring-only 'learns
at all' gate would pass at 60% — this one fails unless the full
train/eval stack genuinely works on real data."""
import numpy

import veles_tpu as vt
from veles_tpu import nn, prng
from veles_tpu.loader import FullBatchLoader


def _split_originals(loader, x, y, n_valid, seed):
    """Shared anchor convention: seeded permutation, then loader row
    order [test | valid | train] — the first n_valid permuted rows ARE
    the held-out set, the rest train."""
    rng = numpy.random.RandomState(seed)
    perm = rng.permutation(len(x))
    x, y = x[perm], y[perm]
    loader.create_originals(x, y)
    loader.class_lengths = [0, n_valid, len(x) - n_valid]
    return x


class DigitsLoader(FullBatchLoader):
    """Real UCI digits, deterministic 80/20 split, [0,1] scaling."""

    hide_from_registry = True

    def load_data(self):
        from sklearn.datasets import load_digits
        d = load_digits()
        _split_originals(self, (d.data / 16.0).astype(numpy.float32),
                         d.target.astype(numpy.int32), 360, seed=0)


def test_digits_real_data_anchor():
    prng.seed_all(42)
    loader = DigitsLoader(None, minibatch_size=72, name="digits")
    wf = nn.StandardWorkflow(
        name="digits-fc",
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 100,
             "solver": "adam", "learning_rate": 0.002},
            {"type": "softmax", "output_sample_shape": 10,
             "solver": "adam", "learning_rate": 0.002},
        ],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=40, fail_iterations=20))
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    res = wf.gather_results()
    # typical MLP literature figure for this dataset is ~2-4% test
    # error; 5% is the regression gate, chance is 90%
    assert res["best_err"] <= 0.05, res
    assert loader.class_lengths[1] == 360   # evaluated on the real split


class DigitsConvLoader(DigitsLoader):
    """Real UCI digits as 8x8x1 images for the conv family — the SAME
    rows/permutation/split as DigitsLoader, only reshaped."""

    hide_from_registry = True

    def load_data(self):
        super().load_data()
        self.original_data.mem = self.original_data.mem.reshape(
            -1, 8, 8, 1)


def test_digits_conv_real_data_anchor():
    """Conv-family anchor on real pixels (VERDICT r3 weak #8: no conv
    stack had a real-data gate): conv→relu→pool ×2 → fc → softmax must
    reach <= 2% held-out error — BELOW the FC anchor's measured 2.5%,
    so the conv/pooling/GD-conv path has to genuinely add value over
    flattening, not just wire up."""
    prng.seed_all(42)
    loader = DigitsConvLoader(None, minibatch_size=72, name="digits-c")
    wf = nn.StandardWorkflow(
        name="digits-conv",
        layers=[
            {"type": "conv", "n_kernels": 16, "kx": 3, "ky": 3,
             "padding": (1, 1, 1, 1), "solver": "adam",
             "learning_rate": 0.002, "name": "c0"},
            {"type": "activation_str", "name": "a0"},
            {"type": "max_pooling", "kx": 2, "ky": 2, "name": "p0"},
            {"type": "conv", "n_kernels": 32, "kx": 3, "ky": 3,
             "padding": (1, 1, 1, 1), "solver": "adam",
             "learning_rate": 0.002, "name": "c1"},
            {"type": "activation_str", "name": "a1"},
            {"type": "max_pooling", "kx": 2, "ky": 2, "name": "p1"},
            {"type": "all2all_tanh", "output_sample_shape": 64,
             "solver": "adam", "learning_rate": 0.002, "name": "fc"},
            {"type": "softmax", "output_sample_shape": 10,
             "solver": "adam", "learning_rate": 0.002, "name": "sm"},
        ],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=40, fail_iterations=20))
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    res = wf.gather_results()
    # measured 0.83% on this split/seed (2026-07-31); 2% is the
    # regression gate (< the FC stack's 2.5%), chance is 90%
    assert res["best_err"] <= 0.02, res
    assert loader.class_lengths[1] == 360


class BreastCancerLoader(FullBatchLoader):
    """Real WDBC tabular data (569 x 30, 2 classes), z-scored,
    deterministic 80/20 split."""

    hide_from_registry = True

    def load_data(self):
        from sklearn.datasets import load_breast_cancer
        d = load_breast_cancer()
        n_valid = 114
        x = _split_originals(self, d.data.astype(numpy.float32),
                             d.target.astype(numpy.int32), n_valid,
                             seed=1)
        # z-score with TRAIN-rows statistics only: whole-dataset stats
        # would leak held-out information into the anchor
        mu = x[n_valid:].mean(0)
        sd = x[n_valid:].std(0) + 1e-6
        self.original_data.mem[...] = (x - mu) / sd


def test_breast_cancer_real_data_anchor():
    """Second in-image real dataset (WDBC): a small FC stack must reach
    <= 8% held-out error (literature MLP figures ~2-5%; majority-class
    baseline is ~37%)."""
    prng.seed_all(7)
    loader = BreastCancerLoader(None, minibatch_size=65, name="wdbc")
    wf = nn.StandardWorkflow(
        name="wdbc-fc",
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "solver": "adam", "learning_rate": 0.003},
            {"type": "softmax", "output_sample_shape": 2,
             "solver": "adam", "learning_rate": 0.003},
        ],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=60, fail_iterations=30))
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    res = wf.gather_results()
    assert res["best_err"] <= 0.08, res
    assert loader.class_lengths[1] == 114


class DiabetesLoader(FullBatchLoader):
    """Real diabetes progression data (442 x 10, continuous target)
    through the regression/MSE stack — a path neither classification
    anchor exercises. Features AND target z-scored with TRAIN-row
    statistics only."""

    hide_from_registry = True

    def load_data(self):
        from sklearn.datasets import load_diabetes
        from veles_tpu.loader import FullBatchLoaderMSE  # noqa: F401
        d = load_diabetes()
        rng = numpy.random.RandomState(3)
        perm = rng.permutation(len(d.data))
        x = d.data.astype(numpy.float32)[perm]
        y = d.target.astype(numpy.float32)[perm]
        n_valid = 89
        mu, sd = x[n_valid:].mean(0), x[n_valid:].std(0) + 1e-6
        tmu, tsd = y[n_valid:].mean(), y[n_valid:].std()
        x = (x - mu) / sd
        y = ((y - tmu) / tsd).reshape(-1, 1)
        self.create_originals(x, None, y)
        self.class_lengths = [0, n_valid, len(x) - n_valid]


def test_diabetes_regression_real_data_anchor():
    """Third in-image real dataset, REGRESSION: held-out RMSE (in
    target-std units) must beat 0.80 — the train-mean predictor scores
    0.95 on this split and ridge regression 0.72, so the gate fails
    unless the MSE evaluator/decision stack genuinely fits real data."""
    from veles_tpu.loader import FullBatchLoaderMSE

    class L(FullBatchLoaderMSE, DiabetesLoader):
        hide_from_registry = True

    prng.seed_all(17)
    loader = L(None, minibatch_size=51, name="diab")
    wf = nn.StandardWorkflow(
        name="diab-fc",
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "solver": "adam", "learning_rate": 0.003,
             "weights_decay": 1e-4},
            {"type": "all2all", "output_sample_shape": 1,
             "solver": "adam", "learning_rate": 0.003},
        ],
        loader_unit=loader, loss_function="mse", target_mode="targets",
        decision_config=dict(max_epochs=80, fail_iterations=30))
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    res = wf.gather_results()
    assert res["best_rmse"] <= 0.80, res
    assert loader.class_lengths[1] == 89


def _pydoc_corpus_and_trigram(tmp_path):
    """The ONE copy of the LM anchors' corpus + baseline convention
    (transformer AND lstm gates must stay on the identical split):
    CPython's pydoc topics, 120k chars, and the trigram-argmax error
    on the SAME leak-free tail split the model sees —
    TextFileLoader's default validation_ratio is 0.1, so the baseline
    trains on the first 90% of chars and scores on the last 10%
    exactly like the model. Returns (corpus_path, tri_err)."""
    from collections import Counter, defaultdict
    import pydoc_data.topics as topics
    text = "".join(v for _, v in sorted(topics.topics.items()))[:120_000]
    path = tmp_path / "pydoc_corpus.txt"
    path.write_text(text)
    cut = int(len(text) * 0.9)
    train, valid = text[:cut], text[cut:]
    tri = defaultdict(Counter)
    for a, b, c in zip(train, train[1:], train[2:]):
        tri[a + b][c] += 1
    hits = sum(1 for a, b, c in zip(valid, valid[1:], valid[2:])
               if tri[a + b] and tri[a + b].most_common(1)[0][0] == c)
    return path, 1.0 - hits / (len(valid) - 2)


def test_char_lm_real_text_anchor(tmp_path):
    """Attention-family anchor on REAL text (VERDICT r3 weak #8: no
    attention stack had a real-data gate): a 2-block char transformer
    trained on CPython's own pydoc topics (real English prose shipped
    in every interpreter — deterministic in-image bytes) must beat
    0.48 held-out next-char error AND the trigram-argmax baseline on
    the same split (measured 2026-07-31: model 0.428, trigram ~0.57)."""
    from conftest import import_model
    lm = import_model("char_lm")
    path, tri_err = _pydoc_corpus_and_trigram(tmp_path)

    prng.seed_all(11)
    wf = lm.build_workflow(epochs=24, minibatch_size=64, n_blocks=2,
                           dim=48, text_file=str(path))
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    res = wf.gather_results()
    assert res["best_err"] <= 0.48, res
    assert res["best_err"] < tri_err - 0.05, (res["best_err"], tri_err)


def test_char_lstm_real_text_anchor(tmp_path):
    """Recurrent-family anchor on REAL text (VERDICT r4 item 7: the
    LSTM/RNN family was the last without a non-synthetic quality
    gate): a 2-layer char-LSTM on the same CPython pydoc corpus and
    leak-free 90/10 tail split as the transformer anchor must beat
    0.51 held-out next-char error AND the trigram-argmax baseline by
    >= 4 points (measured 2026-08-01: model 0.469 @ 40 epochs,
    trigram 0.5653)."""
    from conftest import import_model
    lm = import_model("char_lm")
    path, tri_err = _pydoc_corpus_and_trigram(tmp_path)

    prng.seed_all(13)
    wf = lm.build_workflow(epochs=40, minibatch_size=64, n_blocks=2,
                           dim=64, text_file=str(path), arch="lstm")
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    res = wf.gather_results()
    assert res["best_err"] <= 0.51, res
    assert res["best_err"] < tri_err - 0.04, (res["best_err"], tri_err)
