"""Forward-unit correctness: XLA result vs numpy oracle — the reference's
@multi_device pattern (veles/tests/accelerated_test.py:41-61) adapted: each
unit's jitted apply() must agree with its numpy_apply()."""
import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn
from veles_tpu.memory import Array


@pytest.fixture(autouse=True)
def f32_compute():
    """Oracle agreement is an exactness check: pin float32 compute; bf16
    (the TPU production dtype) gets its own loose-tolerance test."""
    prev = vt.root.common.engine.compute_dtype
    vt.root.common.engine.compute_dtype = "float32"
    yield
    vt.root.common.engine.compute_dtype = prev


def run_both(unit_cls, input_shape, seed=3, rtol=1e-4, atol=1e-5, **kwargs):
    wf = vt.Workflow(name="t")
    u = unit_cls(wf, **kwargs)
    rng = numpy.random.RandomState(seed)
    x = rng.randn(*input_shape).astype(numpy.float32)
    u.input = Array(x, name="x")
    u.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    # xla path
    u.xla_run()
    y_xla = numpy.asarray(u.output.map_read(), dtype=numpy.float32)
    # oracle
    y_np = u.numpy_apply(u.params_np(), x).astype(numpy.float32)
    assert y_xla.shape == tuple(u.output_shape_for(x.shape))
    numpy.testing.assert_allclose(y_xla, y_np, rtol=rtol, atol=atol)
    return u, y_np


def test_all2all_linear():
    run_both(nn.All2All, (8, 12), output_sample_shape=7)


def test_all2all_tanh():
    run_both(nn.All2AllTanh, (8, 12), output_sample_shape=(5,))


def test_all2all_relu():
    run_both(nn.All2AllRelu, (4, 6), output_sample_shape=3)


def test_all2all_sigmoid():
    run_both(nn.All2AllSigmoid, (4, 6), output_sample_shape=3)


def test_all2all_softmax():
    u, y = run_both(nn.All2AllSoftmax, (6, 10), output_sample_shape=4)
    numpy.testing.assert_allclose(y.sum(axis=1), 1.0, rtol=1e-5)


def test_all2all_flattens_nd_input():
    run_both(nn.All2All, (5, 4, 3, 2), output_sample_shape=6)


def test_conv_basic():
    run_both(nn.Conv, (2, 8, 8, 3), n_kernels=4, kx=3, ky=3,
             rtol=2e-2, atol=2e-3)


def test_bf16_compute_path():
    """The precision knob must map bf16 → MXU DEFAULT, f32 → HIGHEST, and
    mixed-dtype operands must be promoted rather than rejected."""
    import jax
    import jax.lax
    import jax.numpy as jnp
    from veles_tpu.ops import matmul_precision
    from veles_tpu.ops.precision import promote_operands
    vt.root.common.engine.compute_dtype = "bfloat16"
    try:
        assert matmul_precision() == jax.lax.Precision.DEFAULT
    finally:
        vt.root.common.engine.compute_dtype = "float32"
    assert matmul_precision() == jax.lax.Precision.HIGHEST
    x = jnp.ones((2, 3), jnp.float32)
    w = jnp.ones((3, 4), jnp.bfloat16)
    xx, ww, ct = promote_operands(x, w)
    assert xx.dtype == ww.dtype == ct == jnp.float32
    # a bf16-param FC layer must still run (promoted, not rejected)
    wf = vt.Workflow(name="t")
    u = nn.All2All(wf, output_sample_shape=4)
    y = u.apply({"weights": w, "bias": jnp.zeros(4, jnp.bfloat16)}, x)
    assert y.shape == (2, 4)


def test_conv_stride_padding():
    run_both(nn.Conv, (2, 9, 9, 2), n_kernels=3, kx=3, ky=3,
             sliding=(2, 2), padding=(1, 1, 1, 1), rtol=2e-2, atol=2e-3)


def test_conv_tanh():
    run_both(nn.ConvTanh, (2, 6, 6, 2), n_kernels=2, kx=3, ky=3,
             rtol=2e-2, atol=2e-3)


def test_max_pooling():
    run_both(nn.MaxPooling, (2, 8, 8, 3), kx=2, ky=2)


def test_max_pooling_ceil_mode():
    # 7x7 with 2x2/stride2 → ceil → 4x4, edge windows partial
    run_both(nn.MaxPooling, (2, 7, 7, 2), kx=2, ky=2)


def test_avg_pooling():
    run_both(nn.AvgPooling, (2, 8, 8, 3), kx=2, ky=2)


def test_avg_pooling_ceil_mode():
    run_both(nn.AvgPooling, (1, 5, 5, 1), kx=2, ky=2)


def test_deconv():
    run_both(nn.Deconv, (2, 4, 4, 3), n_channels=2, kx=3, ky=3,
             rtol=2e-2, atol=2e-3)


def test_deconv_stride():
    run_both(nn.Deconv, (1, 3, 3, 2), n_channels=1, kx=2, ky=2,
             sliding=(2, 2), rtol=2e-2, atol=2e-3)


def test_depooling():
    run_both(nn.Depooling, (2, 3, 3, 4), kx=2, ky=2)


def test_activations():
    for cls in (nn.ForwardTanh, nn.ForwardRelu, nn.ForwardStrictRelu,
                nn.ForwardSigmoid, nn.ForwardLog):
        run_both(cls, (4, 7))


def test_activation_mul():
    run_both(nn.ForwardMul, (3, 5), factor=2.5)


def test_lrn():
    run_both(nn.LRNormalizerForward, (2, 4, 4, 8), rtol=1e-3)


def test_dropout_eval_identity():
    u, y = run_both(nn.DropoutForward, (4, 9), dropout_ratio=0.5)
    # eval mode: identity


def test_dropout_train_masks():
    import jax
    wf = vt.Workflow(name="t")
    u = nn.DropoutForward(wf, dropout_ratio=0.5)
    x = numpy.ones((100, 50), dtype=numpy.float32)
    y = numpy.asarray(u.apply({}, x, train=True,
                              rng=jax.random.key(0)))
    kept = (y > 0).mean()
    assert 0.3 < kept < 0.7          # ~50% kept
    numpy.testing.assert_allclose(y[y > 0], 2.0, rtol=1e-5)  # 1/keep scale


def test_gd_unit_standalone_updates_weights():
    """GradientDescentBase.run: vjp backward + SGD update moves weights."""
    wf = vt.Workflow(name="t")
    fwd = nn.All2All(wf, output_sample_shape=3, name="fc")
    x = numpy.random.RandomState(0).randn(4, 5).astype(numpy.float32)
    fwd.input = Array(x, name="x")
    dev = vt.XLADevice(mesh_axes={"data": 1})
    fwd.initialize(device=dev)
    w_before = fwd.weights.map_read().copy()
    gd = nn.nn_units.MATCHING[nn.All2All](wf, learning_rate=0.1)
    gd.forward = fwd
    gd.initialize(device=dev)
    gd.err_output = Array(numpy.ones((4, 3), dtype=numpy.float32))
    gd.xla_run()
    w_after = fwd.weights.map_read()
    assert not numpy.allclose(w_before, w_after)
    assert gd.err_input.shape == (4, 5)


def test_relu_softplus_oracle_large_inputs():
    u = nn.ForwardRelu(vt.Workflow(name="t"))
    x = numpy.array([[-100.0, -1.0, 0.0, 1.0, 60.0, 500.0]],
                    dtype=numpy.float32)
    import jax
    y_dev = numpy.asarray(jax.jit(lambda z: u.apply({}, z))(x))
    y_np = u.numpy_apply({}, x)
    numpy.testing.assert_allclose(y_dev, y_np, rtol=1e-5, atol=1e-6)


def test_lstm_oracle():
    run_both(nn.LSTM, (3, 7, 5), hidden_size=6, rtol=1e-4, atol=1e-5)


def test_lstm_sequences_oracle():
    run_both(nn.LSTM, (2, 5, 4), hidden_size=3, return_sequences=True,
             rtol=1e-4, atol=1e-5)


def test_rnn_oracle():
    run_both(nn.RNN, (3, 6, 4), hidden_size=5, rtol=1e-4, atol=1e-5)


def test_rnn_sequences_oracle():
    run_both(nn.RNN, (2, 4, 3), hidden_size=2, return_sequences=True,
             rtol=1e-4, atol=1e-5)
