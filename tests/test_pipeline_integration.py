"""Pipeline parallelism as a WORKFLOW capability (VERDICT r1 item 5).

A user says ``mesh={"pipeline": N}`` on a StandardWorkflow whose forward
chain contains a run of identical shape-preserving layers; TrainStep
stage-groups the run, stacks its parameters with a leading layer axis
sharded over 'pipeline', and runs the gpipe microbatch schedule inside
the fused jitted step. These tests assert:
- the plan forms (pre/block/post split, stacked params, shardings);
- training through the pipelined step CONVERGES and matches a plain
  1-device run of the same seed/model (the equivalence claim);
- snapshots stay per-layer (portable between pipeline topologies);
- a chain with no viable block fails loudly.
"""
import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn, prng
from veles_tpu.error import Bug
from veles_tpu.loader import FullBatchLoader, TRAIN, VALID
from veles_tpu.parallel.sharding import PP_BLOCK


class BlobsLoader(FullBatchLoader):
    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(7)
        n_per, d, k = 120, 12, 3
        centers = rng.randn(k, d) * 3
        data = numpy.concatenate(
            [centers[c] + rng.randn(n_per, d) for c in range(k)])
        labels = numpy.concatenate(
            [numpy.full(n_per, c) for c in range(k)])
        perm = rng.permutation(len(data))
        self.create_originals(data[perm].astype(numpy.float32),
                              labels[perm].astype(numpy.int32))
        self.class_lengths = [0, 90, 270]


def make_workflow(epochs=6, n_blocks=4, microbatches=None):
    """Stem → n_blocks identical 16-wide tanh blocks → softmax head."""
    loader = BlobsLoader(None, minibatch_size=24, name="blobs-pp")
    layers = ([{"type": "all2all_tanh", "output_sample_shape": 16,
                "name": "stem"}]
              + [{"type": "all2all_tanh", "output_sample_shape": 16,
                  "name": "block%d" % i} for i in range(n_blocks)]
              + [{"type": "softmax", "output_sample_shape": 3,
                  "name": "head"}])
    return nn.StandardWorkflow(
        name="pp-train", layers=layers, loader_unit=loader,
        loss_function="softmax",
        decision_config=dict(max_epochs=epochs, fail_iterations=100),
        pipeline_microbatches=microbatches)


def _run(mesh_axes, epochs=6, **kw):
    prng.seed_all(4242)
    wf = make_workflow(epochs=epochs, **kw)
    wf.initialize(device=vt.XLADevice(mesh_axes=mesh_axes))
    wf.run()
    return wf


def test_pipeline_plan_forms():
    prng.seed_all(4242)
    wf = make_workflow()
    wf.initialize(device=vt.XLADevice(mesh_axes={"pipeline": 4}))
    step = wf.train_step
    assert step._pp is not None
    assert [f.name for f in step._pp["pre"]] == ["stem"]
    assert step._pp["names"] == ["block0", "block1", "block2", "block3"]
    assert [f.name for f in step._pp["post"]] == ["head"]
    blk = step.params[PP_BLOCK]
    assert blk["weights"].shape == (4, 16, 16)
    # stacked block sharded over the pipeline axis
    spec = blk["weights"].sharding.spec
    assert spec[0] == "pipeline"
    # per-layer entries replaced by the block
    assert "block0" not in step.params


def test_pipeline_matches_plain_run():
    """Same seed: {'pipeline': 4} training must track the 1-device run
    (gpipe composes the same functions; only reduction order differs).
    Microbatching changes nothing: plain SGD sums per-sample grads."""
    import jax
    plain = _run({"data": 1})
    pp = _run({"pipeline": 4})
    e1 = numpy.asarray(plain.decision.epoch_metrics[VALID])
    e2 = numpy.asarray(pp.decision.epoch_metrics[VALID])
    assert e1.shape == e2.shape == (6,)
    numpy.testing.assert_allclose(e2, e1, atol=0.023)  # ≤2 sample flips
    assert pp.decision.best_metric < 0.1
    w1 = plain.train_step.params["block2"]["weights"]
    w2 = pp.train_step.params[PP_BLOCK]["weights"][2]
    numpy.testing.assert_allclose(
        numpy.asarray(jax.device_get(w2)),
        numpy.asarray(jax.device_get(w1)), rtol=2e-3, atol=2e-4)


def test_pipeline_with_data_axis():
    """pp x dp composed mesh: microbatches additionally batch-sharded."""
    wf = _run({"pipeline": 2, "data": 2}, epochs=4)
    assert wf.train_step._pp is not None
    assert wf.decision.best_metric < 0.15


def test_pipeline_snapshot_roundtrip(tmp_path):
    """Snapshots are per-layer: a pipeline run's checkpoint resumes into
    a DIFFERENT topology (plain mesh) and continues identically."""
    wf = _run({"pipeline": 4}, epochs=3)
    snap = vt.Snapshotter(None, prefix="pp", directory=str(tmp_path))
    snap.workflow = wf
    path = snap.export()
    assert path
    prng.seed_all(999)  # resume must restore streams from the snapshot
    wf2 = make_workflow(epochs=6)
    wf2.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    vt.resume(wf2, path)
    assert wf2.decision.epoch_number == 3
    w_pp = wf.train_step.params[PP_BLOCK]["weights"][1]
    w_plain = wf2.train_step.params["block1"]["weights"]
    numpy.testing.assert_allclose(numpy.asarray(w_plain),
                                  numpy.asarray(w_pp), rtol=1e-6)
    # and the reverse: plain snapshot into a pipeline mesh
    wf2.decision.complete <<= False
    snap2 = vt.Snapshotter(None, prefix="pp2", directory=str(tmp_path))
    snap2.workflow = wf2
    path2 = snap2.export()
    wf3 = make_workflow(epochs=6)
    wf3.initialize(device=vt.XLADevice(mesh_axes={"pipeline": 4}))
    vt.resume(wf3, path2)
    w3 = wf3.train_step.params[PP_BLOCK]["weights"][1]
    import jax
    numpy.testing.assert_allclose(
        numpy.asarray(jax.device_get(w3)), numpy.asarray(w_plain),
        rtol=1e-6)


def test_pipeline_rejects_heterogeneous_chain():
    loader = BlobsLoader(None, minibatch_size=30, name="blobs-bad")
    wf = nn.StandardWorkflow(
        name="pp-bad",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 20},
                {"type": "softmax", "output_sample_shape": 3}],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=1))
    with pytest.raises(Bug, match="pipeline"):
        wf.initialize(device=vt.XLADevice(mesh_axes={"pipeline": 4}))


def test_pipeline_microbatch_divisibility():
    with pytest.raises(Bug, match="microbatch"):
        _run({"pipeline": 4}, microbatches=7)


def test_expert_parallel_through_workflow():
    """EP as a workflow capability: a {"type": "moe_ffn"} layer under a
    {'data': D, 'expert': E} mesh gets its expert-leading params sharded
    over 'expert' by the rule table, inside the fused step."""
    loader = BlobsLoader(None, minibatch_size=24, name="blobs-ep")
    wf = nn.StandardWorkflow(
        name="ep-train",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 16},
                {"type": "moe_ffn", "n_experts": 4, "hidden": 8},
                {"type": "softmax", "output_sample_shape": 3}],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=6, fail_iterations=100))
    prng.seed_all(4242)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 2, "expert": 2}))
    step = wf.train_step
    w1 = step.params["moe_ffn1"]["w1"]
    assert w1.sharding.spec[0] == "expert", w1.sharding
    assert not w1.sharding.is_fully_replicated
    wf.run()
    assert wf.decision.best_metric < 0.1, wf.decision.epoch_metrics


def test_pipeline_mixed_config_blocks_take_hetero_path():
    """Same class + same shapes but different semantic config (rope
    on/off): the uniform planner refuses (grouping would silently run
    block 0's settings on every stage) and the heterogeneous schedule
    picks the chain up instead — each stage applies its own unit, so
    per-block config is honored."""
    layers = ([{"type": "transformer_block", "n_heads": 2,
                "ffn_hidden": 8, "rope": bool(i % 2),
                "name": "tb%d" % i} for i in range(4)]
              + [{"type": "mean_pool"},
                 {"type": "softmax", "output_sample_shape": 3}])
    import numpy as _np

    class SeqL(FullBatchLoader):
        hide_from_registry = True

        def load_data(self):
            rng = _np.random.RandomState(1)
            self.create_originals(
                rng.rand(96, 6, 16).astype(_np.float32),
                rng.randint(0, 3, 96).astype(_np.int32))
            self.class_lengths = [0, 24, 72]

    wf = nn.StandardWorkflow(
        name="pp-mixed", layers=layers,
        loader_unit=SeqL(None, minibatch_size=24, name="seql"),
        loss_function="softmax", decision_config=dict(max_epochs=1))
    wf.initialize(device=vt.XLADevice(mesh_axes={"pipeline": 4}))
    step = wf.train_step
    assert step._pp is None
    assert step._pp_hetero is not None
    wf.run()
    assert wf.decision.epoch_number == 1


def test_pipeline_clip_norm_matches_plain():
    """gradient_clip_norm under stacking clips per layer slice — the
    pipelined run must match the plain run exactly like the unclipped
    equivalence test does."""
    def run(mesh_axes):
        prng.seed_all(4242)
        loader = BlobsLoader(None, minibatch_size=24, name="b-ppclip")
        layers = ([{"type": "all2all_tanh", "output_sample_shape": 16,
                    "name": "stem", "learning_rate": 0.5,
                    "gradient_clip_norm": 0.1}]
                  + [{"type": "all2all_tanh", "output_sample_shape": 16,
                      "name": "blk%d" % i, "learning_rate": 0.5,
                      "gradient_clip_norm": 0.1} for i in range(4)]
                  + [{"type": "softmax", "output_sample_shape": 3,
                      "name": "head", "learning_rate": 0.5,
                      "gradient_clip_norm": 0.1}])
        wf = nn.StandardWorkflow(
            name="ppclip", layers=layers, loader_unit=loader,
            loss_function="softmax",
            decision_config=dict(max_epochs=4, fail_iterations=100))
        wf.initialize(device=vt.XLADevice(mesh_axes=mesh_axes))
        wf.run()
        import jax
        if wf.train_step._pp is not None:
            w = wf.train_step.params[PP_BLOCK]["weights"][1]
        else:
            w = wf.train_step.params["blk1"]["weights"]
        return numpy.asarray(jax.device_get(w))

    w_plain = run({"data": 1})
    w_pp = run({"pipeline": 4})
    numpy.testing.assert_allclose(w_pp, w_plain, rtol=2e-3, atol=2e-4)


def test_pipeline_with_mixed_precision():
    """AMP composes with the pipeline axis: the gpipe stage scan's
    carry runs bf16 (cast params + cast microbatches keep the carry
    dtype consistent through ppermute) while masters stay f32."""
    import jax.numpy as jnp
    from veles_tpu.config import root
    from veles_tpu.parallel.sharding import PP_BLOCK
    root.common.engine.mixed_precision = True
    try:
        wf = _run({"pipeline": 4}, epochs=4)
    finally:
        root.common.engine.mixed_precision = False
    assert wf.train_step._pp is not None
    assert wf.train_step.mixed_precision
    d = wf.decision
    assert d.best_metric is not None and d.best_metric < 0.35, \
        d.epoch_metrics
    for leaf in wf.train_step.params[PP_BLOCK].values():
        assert leaf.dtype == jnp.float32


def test_pipeline_with_epoch_block():
    """epochs_per_dispatch composes with the pipeline axis: the epoch
    scan wraps the gpipe step; Decision replays per-epoch entries."""
    prng.seed_all(4242)
    wf = make_workflow(epochs=4)
    wf.train_step.epochs_per_dispatch = 2
    wf.loader.block_epochs = 2
    wf.loader.block_epochs_cap = 4
    wf.initialize(device=vt.XLADevice(mesh_axes={"pipeline": 4}))
    wf.run()
    d = wf.decision
    assert wf.train_step._pp is not None
    assert d.epoch_number == 4
    assert d.best_metric is not None and d.best_metric < 0.35, \
        d.epoch_metrics


def test_pipeline_with_fsdp_axis():
    """pp x fsdp composed mesh (ZeRO-3 over the stacked block): the
    stage axis shards over 'pipeline' AND the largest free param axis
    shards over 'fsdp'; training still tracks the plain run."""
    import jax
    wf = _run({"pipeline": 2, "fsdp": 2}, epochs=4)
    step = wf.train_step
    assert step._pp is not None
    spec = step.params[PP_BLOCK]["weights"].sharding.spec
    assert spec[0] == "pipeline" and "fsdp" in tuple(spec), spec
    assert not step.params[PP_BLOCK]["weights"] \
        .sharding.is_fully_replicated
    assert wf.decision.best_metric < 0.15
    # optimizer state inherited the composed sharding (ZeRO's point):
    # the buffer with the WEIGHTS' shape carries the weights' spec
    # (bias buffers legitimately stay ('pipeline', None))
    w_shape = step.params[PP_BLOCK]["weights"].shape
    bufs = [b for b in jax.tree_util.tree_leaves(step.opt_state[PP_BLOCK])
            if getattr(b, "shape", None) == w_shape]
    assert bufs, "no weights-shaped optimizer buffer"
    for b in bufs:
        assert b.sharding.spec == spec, b.sharding
