"""Mirrors reference veles/tests/test_memory.py scope: Array coherence
protocol + Watcher accounting, adapted to the host-newer/dev-newer model."""
import pickle

import numpy
import pytest

from veles_tpu.memory import Array, Watcher


def test_array_host_basics():
    a = Array(numpy.arange(6, dtype=numpy.float32).reshape(2, 3), name="t")
    assert a.shape == (2, 3)
    assert a.dtype == numpy.float32
    assert bool(a)
    assert len(a) == 2
    assert a[0, 1] == 1.0
    a[0, 1] = 9.0
    assert a.mem[0, 1] == 9.0


def test_array_device_roundtrip():
    a = Array(numpy.ones((4, 4), dtype=numpy.float32), name="rt")
    dv = a.device_view()
    assert dv.shape == (4, 4)
    # simulate a jitted step producing a new device array
    import jax.numpy as jnp
    a.assign_devmem(dv * 2)
    host = a.map_read()
    numpy.testing.assert_allclose(host, 2 * numpy.ones((4, 4)))


def test_array_host_newer_pushes():
    a = Array(numpy.zeros(3, dtype=numpy.float32), name="hn")
    a.device_view()
    a.map_write()[...] = 5.0
    dv = a.device_view()
    numpy.testing.assert_allclose(numpy.asarray(dv), 5.0)


def test_array_map_invalidate_skips_sync():
    a = Array(numpy.zeros(2, dtype=numpy.float32), name="mi")
    dv = a.device_view()
    a.assign_devmem(dv + 1)          # device newer
    mem = a.map_invalidate()         # host claims full overwrite
    mem[...] = 7.0
    numpy.testing.assert_allclose(numpy.asarray(a.device_view()), 7.0)


def test_array_pickle_syncs_device_first():
    a = Array(numpy.zeros(2, dtype=numpy.float32), name="pk")
    a.assign_devmem(a.device_view() + 3)
    b = pickle.loads(pickle.dumps(a))
    numpy.testing.assert_allclose(b.mem, 3.0)
    assert b.devmem is None


def test_watcher_accounting():
    Watcher.reset()
    a = Array(numpy.zeros((10, 10), dtype=numpy.float32), name="w")
    a.device_view()
    assert Watcher.total >= 400
    assert Watcher.peak >= Watcher.total
    a.reset(numpy.zeros(1, dtype=numpy.float32))
    assert Watcher.total == 0


def test_empty_array_falsey():
    a = Array()
    assert not bool(a)
    with pytest.raises(Exception):
        a.device_view()


def test_map_write_after_device_adoption_is_writeable():
    a = Array(numpy.zeros(4, dtype=numpy.float32), name="wr")
    a.assign_devmem(a.device_view() + 1)   # device newer
    mem = a.map_write()
    mem[0] = 42.0                          # must not raise read-only
    numpy.testing.assert_allclose(numpy.asarray(a.device_view())[0], 42.0)


def test_device_view_dtype_staleness():
    a = Array(numpy.ones(3, dtype=numpy.float32), name="dt")
    d1 = a.device_view(dtype="bfloat16")
    assert str(d1.dtype) == "bfloat16"
    d2 = a.device_view(dtype="float32")
    assert str(d2.dtype) == "float32"
