"""Fleet watchtower (veles_tpu/telemetry/timeseries.py + alerts.py):
the in-process metrics time-series, the SLO burn-rate alert engine,
and the watch/alerts surfaces.

The load-bearing locks:
- the watchtower OFF (the default) is BIT-IDENTICAL to a build
  without the feature: no sampler thread, no store, no engine, empty
  ``veles_alert_firing`` exposition, a single ``enabled: false``
  header line from the history pull, and NOT ONE ``veles_watch_*`` /
  ``veles_alert_*`` counter moves (the tensormon scan-lock
  discipline);
- the SeriesStore ring is seq-cursored exactly like the span ring:
  bounded capacity ``retention/period + 1``, eviction keeps the
  newest, a cursor older than the tail silently skips evicted
  records, and a torn JSONL pull salvages per line;
- windowed derivations are restart-safe (negative counter deltas
  clamp to growth-from-zero) and DIVERGE from the
  cumulative-since-start ``_p99`` gauges by design — an hour of good
  traffic must not bury a brownout;
- burn-rate and threshold rules ride a fire_for/resolve_for
  hysteresis machine whose streaks HOLD on no-data evaluations; a
  critical rule's firing edge marks the process unready (the router
  probe loop routes around it) and its resolve edge readmits;
- rule construction is FAIL-CLOSED: unknown series / type / op /
  source / severity / field raise at parse, never at 3am;
- ``veles-tpu watch`` / ``veles-tpu alerts`` drive a real 2-replica
  fleet through the same ``/metrics/history`` + ``/alerts`` pages a
  remote operator would scrape.

Budget discipline: everything above the live-fleet test is jax-free
(fake clocks, hand-fed stores); the live test uses one tiny char_lm
workflow shared by both replicas.
"""
import io
import json
import time
import urllib.error
import urllib.request
from contextlib import redirect_stdout

import numpy
import pytest

import veles_tpu as vt
from veles_tpu.config import root
from veles_tpu.loadgen.harness import aggregate, verdict
from veles_tpu.resilience import health
from veles_tpu.telemetry import WATCH_COUNTERS, alerts, timeseries
from veles_tpu.telemetry import fleet
from veles_tpu.telemetry.counters import counters, histogram_quantile
from veles_tpu.telemetry.recorder import flight
from veles_tpu.telemetry.timeseries import (SeriesStore, parse_history,
                                            pull_payload)

from conftest import import_model

TTFT = "veles_serving_ttft_seconds"


@pytest.fixture(autouse=True)
def _reset_watchtower():
    """Every test starts with the watchtower down and the shipped
    knob defaults, and leaves no sampler thread / health residue for
    the rest of the suite."""
    timeseries.stop_watch()
    flight.clear()
    yield
    timeseries.stop_watch()
    node = root.common.telemetry.watch
    node.enabled = False
    node.period = 1.0
    node.retention = 300.0
    node.rules = None
    node.slo_ttft_ms = 500.0
    node.slo_e2e_ms = 5000.0
    node.objective = 0.99
    node.fast_window = 30.0
    node.slow_window = 120.0
    node.burn_factor = 6.0
    node.queue_depth_limit = 64
    node.shed_rate_limit = 5.0
    for rule in ("brownout_shedding",):
        health.forget("alert.watch.%s" % rule)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def tick(self, dt=1.0):
        self.t += dt
        return self.t


def _hist(bounds, counts):
    """Registry-snapshot histogram form: counts = per-bucket +
    overflow (len(bounds) + 1)."""
    assert len(counts) == len(bounds) + 1
    return {"bounds": list(bounds), "counts": list(counts),
            "sum": float(sum(counts)), "count": float(sum(counts))}


def _feed(store_, clock, counter_values=None, hists=None, gauges=None,
          dt=1.0):
    clock.tick(dt)
    return store_.ingest(dict(counter_values or {}),
                         dict(hists or {}), dict(gauges or {}))


# -- ring math (no jax, fake clock) -------------------------------------------

def test_ring_capacity_eviction_and_cursor_pull():
    fk = FakeClock()
    st = SeriesStore(period=1.0, retention=5.0, clock=fk,
                     count_samples=False)
    # capacity = retention/period + 1 so a full window has both ends
    for i in range(10):
        _feed(st, fk, {"c": float(i)})
    recs = st.samples()
    assert len(recs) == 6
    assert [r["seq"] for r in recs] == [5, 6, 7, 8, 9, 10]
    # a cursor older than the ring's tail silently skips the evicted
    pulled, cur = st.records_since(0)
    assert [r["seq"] for r in pulled] == [5, 6, 7, 8, 9, 10]
    assert cur == st.cursor() == 10
    # incremental pull: only what was appended after the cursor
    pulled, cur2 = st.records_since(cur)
    assert pulled == [] and cur2 == 10
    _feed(st, fk, {"c": 10.0})
    pulled, cur3 = st.records_since(cur)
    assert len(pulled) == 1 and pulled[0]["seq"] == 11 and cur3 == 11
    # non-sample events ride the same ring, in order
    st.note_event("watch.alert", rule="r", state="firing")
    pulled, _ = st.records_since(cur3)
    assert pulled[0]["kind"] == "watch.alert"
    assert pulled[0]["seq"] == 12


def test_delta_rate_window_selection_and_restart_clamp():
    fk = FakeClock()
    st = SeriesStore(period=1.0, retention=60.0, clock=fk,
                     count_samples=False)
    assert st.delta("c") is None and st.rate("c") is None
    for i in range(6):                       # ts 1001..1006, c = 10*i
        _feed(st, fk, {"c": 10.0 * i})
    # window=None → the latest adjacent pair
    assert st.delta("c") == 10.0
    assert st.rate("c") == pytest.approx(10.0)
    # window picks the newest sample at least `window` older
    assert st.delta("c", window=2.5) == 30.0
    assert st.rate("c", window=2.5) == pytest.approx(10.0)
    # a window outrunning retention spans the whole ring
    assert st.delta("c", window=1e9) == 50.0
    # a restarted remote process: negative delta clamps to the newest
    # absolute value — growth from zero, not negative traffic
    _feed(st, fk, {"c": 3.0})
    assert st.delta("c") == 3.0
    assert st.rate("c") == pytest.approx(3.0)


def test_windowed_quantile_diverges_from_cumulative():
    fk = FakeClock()
    st = SeriesStore(period=1.0, retention=60.0, clock=fk,
                     count_samples=False)
    bounds = [0.1, 1.0]
    # an hour of fast traffic ...
    _feed(st, fk, hists={TTFT: _hist(bounds, [1000, 0, 0])})
    # ... then a brownout: 50 slow requests land in (0.1, 1.0]
    _feed(st, fk, hists={TTFT: _hist(bounds, [1000, 50, 0])})
    windowed = st.quantile(TTFT, 0.5)
    cumulative = histogram_quantile(tuple(bounds), (1000, 50, 0), 0.5)
    assert windowed is not None and windowed > 0.1
    assert cumulative is not None and cumulative <= 0.1
    # error_fraction errs toward alerting: an SLO target between
    # bounds counts the whole straddling bucket as bad
    assert st.error_fraction(TTFT, 0.5) == pytest.approx(1.0)
    assert st.error_fraction(TTFT, 1.0) == pytest.approx(0.0)
    # no growth in the window → no verdict (None, not 0.0)
    _feed(st, fk, hists={TTFT: _hist(bounds, [1000, 50, 0])})
    assert st.quantile(TTFT, 0.5) is None
    assert st.error_fraction(TTFT, 0.5) is None


def test_hist_delta_bounds_mismatch_falls_back_to_absolute():
    fk = FakeClock()
    st = SeriesStore(period=1.0, retention=60.0, clock=fk,
                     count_samples=False)
    _feed(st, fk, hists={TTFT: _hist([0.1, 1.0], [10, 0, 0])})
    # remote restart re-registered with different buckets
    _feed(st, fk, hists={TTFT: _hist([0.5], [7, 2])})
    h = st.hist_delta(TTFT)
    assert h["bounds"] == [0.5] and h["counts"] == [7, 2]
    # a histogram absent from the older sample deltas as absolute
    _feed(st, fk, hists={TTFT: _hist([0.5], [8, 2]),
                         "veles_serving_e2e_seconds":
                         _hist([1.0], [4, 1])})
    h = st.hist_delta("veles_serving_e2e_seconds")
    assert h["counts"] == [4, 1] and h["count"] == 5


def test_gauge_providers_feed_sample_and_broken_provider_skipped():
    fk = FakeClock()
    st = SeriesStore(period=1.0, retention=60.0, clock=fk,
                     count_samples=False)
    timeseries.add_gauge_provider(
        "wt_test", lambda: {"wt_g": (3.0, "help text"),
                            "wt_bad": "not-a-number"})
    timeseries.add_gauge_provider(
        "wt_boom", lambda: (_ for _ in ()).throw(RuntimeError("x")))
    try:
        rec = st.sample()
    finally:
        timeseries.remove_gauge_provider("wt_test")
        timeseries.remove_gauge_provider("wt_boom")
    assert rec["gauges"]["wt_g"] == 3.0
    assert "wt_bad" not in rec["gauges"]
    assert st.gauge("wt_g") == 3.0


def test_parse_history_salvages_torn_lines():
    header = {"kind": "watch.header", "enabled": True, "cursor": 2}
    rec = {"kind": "watch.sample", "seq": 2, "ts": 1.0,
           "counters": {}, "hist": {}, "gauges": {}}
    text = (json.dumps(header) + "\n" + json.dumps(rec)
            + "\n" + '{"kind": "watch.sam')      # torn mid-record
    got_header, got_records = parse_history(text)
    assert got_header["cursor"] == 2
    assert [r["seq"] for r in got_records] == [2]


# -- the alert rule engine (no jax, fake clock) -------------------------------

def test_burn_rate_hysteresis_fires_holds_and_resolves():
    fk = FakeClock()
    st = SeriesStore(period=1.0, retention=60.0, clock=fk,
                     count_samples=False)
    rule = alerts.BurnRateRule(
        "ttft_burn", TTFT, slo_seconds=0.1, objective=0.9,
        fast_window=1.0, slow_window=1.0, factor=2.0,
        fire_for=2, resolve_for=2)
    eng = alerts.AlertEngine(st, [rule], clock=fk,
                             health_name="wt_unit",
                             dump_on_critical=False)
    ev0 = counters.get("veles_alert_evals_total")
    tr0 = counters.get("veles_alert_transitions_total")
    bounds = [0.1]
    # no samples yet → observe None, streaks hold, state ok
    assert eng.evaluate() == [] and rule.state == "ok"
    _feed(st, fk, hists={TTFT: _hist(bounds, [100, 0])})
    # breach #1 (all 50 new requests blow the SLO): streak 1, no fire
    _feed(st, fk, hists={TTFT: _hist(bounds, [100, 50])})
    assert eng.evaluate() == [] and rule.state == "ok"
    # a no-growth window → observe None → the streak HOLDS at 1
    _feed(st, fk, hists={TTFT: _hist(bounds, [100, 50])})
    assert eng.evaluate() == [] and rule.state == "ok"
    # breach #2 → firing (fire_for=2 satisfied across the hold)
    _feed(st, fk, hists={TTFT: _hist(bounds, [100, 90])})
    trans = eng.evaluate()
    assert [(t["rule"], t["state"]) for t in trans] \
        == [("ttft_burn", "firing")]
    assert rule.state == "firing" and rule.since == fk.t
    assert rule.status()["type"] == "burn_rate"
    # the firing edge rides the ring next to its samples
    edges = st.records("watch.alert")
    assert [(e["rule"], e["state"]) for e in edges] \
        == [("ttft_burn", "firing")]
    # exposition flips the labeled gauge
    assert 'veles_alert_firing{rule="ttft_burn"} 1' \
        in eng.render_firing()
    # heal: two clean windows → resolved (resolve_for=2)
    _feed(st, fk, hists={TTFT: _hist(bounds, [200, 90])})
    assert eng.evaluate() == [] and rule.state == "firing"
    _feed(st, fk, hists={TTFT: _hist(bounds, [300, 90])})
    trans = eng.evaluate()
    assert [(t["rule"], t["state"]) for t in trans] \
        == [("ttft_burn", "resolved")]
    assert 'veles_alert_firing{rule="ttft_burn"} 0' \
        in eng.render_firing()
    assert counters.get("veles_alert_evals_total") - ev0 == 6
    assert counters.get("veles_alert_transitions_total") - tr0 == 2
    if flight.enabled():
        noted = [(e["rule"], e["state"])
                 for e in flight.records("alert")]
        assert ("ttft_burn", "firing") in noted
        assert ("ttft_burn", "resolved") in noted


def test_critical_rule_marks_process_unready_and_readmits():
    fk = FakeClock()
    st = SeriesStore(period=1.0, retention=60.0, clock=fk,
                     count_samples=False)
    rule = alerts.ThresholdRule(
        "brown", "veles_qos_brownout_level", threshold=2.0, op=">=",
        source="gauge", severity="critical", fire_for=1,
        resolve_for=1)
    eng = alerts.AlertEngine(st, [rule], clock=fk,
                             health_name="wt_crit",
                             dump_on_critical=False)
    cu0 = counters.get("veles_alert_critical_unready_total")
    try:
        _feed(st, fk, gauges={"veles_qos_brownout_level": 3.0})
        trans = eng.evaluate()
        assert [(t["rule"], t["state"]) for t in trans] \
            == [("brown", "firing")]
        assert health.readiness().get("alert.wt_crit.brown") is False
        assert counters.get("veles_alert_critical_unready_total") \
            - cu0 == 1
        _feed(st, fk, gauges={"veles_qos_brownout_level": 0.0})
        trans = eng.evaluate()
        assert [(t["rule"], t["state"]) for t in trans] \
            == [("brown", "resolved")]
        assert health.readiness().get("alert.wt_crit.brown") is True
    finally:
        health.forget("alert.wt_crit.brown")


def test_threshold_rule_rate_source_and_one_bad_rule_isolated():
    fk = FakeClock()
    st = SeriesStore(period=1.0, retention=60.0, clock=fk,
                     count_samples=False)

    class Boom(alerts.Rule):
        def observe(self, store):
            raise RuntimeError("bad rule")

        def describe(self):
            return {}

    shed = alerts.ThresholdRule(
        "shed_fast", "veles_shed_requests_total", threshold=5.0,
        op=">", source="rate", window=10.0, fire_for=1,
        resolve_for=1)
    eng = alerts.AlertEngine(st, [Boom("boom"), shed], clock=fk,
                             dump_on_critical=False)
    _feed(st, fk, {"veles_shed_requests_total": 0.0})
    _feed(st, fk, {"veles_shed_requests_total": 20.0})
    # the raising rule must not take the sweep down
    trans = eng.evaluate()
    assert [(t["rule"], t["state"]) for t in trans] \
        == [("shed_fast", "firing")]
    assert shed.value == pytest.approx(20.0)


def test_rule_validation_fails_closed():
    with pytest.raises(ValueError, match="unregistered series"):
        alerts.ThresholdRule("x", "nope_total", 1.0)
    with pytest.raises(ValueError, match="unregistered series"):
        # a counter is not a gauge: source picks the registry
        alerts.ThresholdRule("x", "veles_shed_requests_total", 1.0,
                             source="gauge")
    with pytest.raises(ValueError, match="unknown op"):
        alerts.ThresholdRule("x", "veles_serving_queue_depth", 1.0,
                             op="!=")
    with pytest.raises(ValueError, match="unknown source"):
        alerts.ThresholdRule("x", "veles_serving_queue_depth", 1.0,
                             source="avg")
    with pytest.raises(ValueError, match="unknown severity"):
        alerts.ThresholdRule("x", "veles_serving_queue_depth", 1.0,
                             severity="page")
    with pytest.raises(ValueError, match="objective"):
        alerts.BurnRateRule("x", TTFT, 0.5, objective=1.5)
    with pytest.raises(ValueError, match="slow_window"):
        alerts.BurnRateRule("x", TTFT, 0.5, fast_window=60.0,
                            slow_window=30.0)
    with pytest.raises(ValueError, match="unknown type"):
        alerts.parse_rule({"name": "x", "type": "anomaly"})
    with pytest.raises(ValueError):                 # unexpected field
        alerts.parse_rule({"name": "x", "type": "threshold",
                           "series": "veles_serving_queue_depth",
                           "threshold": 1.0, "frobnicate": True})
    with pytest.raises(ValueError, match="duplicate"):
        alerts.AlertEngine(None, [
            alerts.ThresholdRule("a", "veles_serving_queue_depth", 1),
            alerts.ThresholdRule("a", "veles_serving_queue_depth", 2),
        ])


def test_default_rules_and_operator_overrides_from_config():
    names = {r.name for r in alerts.default_rules()}
    assert names == {"slo_ttft_burn", "slo_e2e_burn",
                     "queue_depth_high", "shed_rate_high",
                     "brownout_shedding"}
    by_name = {r.name: r for r in alerts.default_rules()}
    assert by_name["brownout_shedding"].severity == "critical"
    # the knob block retargets the shipped rules without redefining
    node = root.common.telemetry.watch
    node.slo_ttft_ms = 250.0
    node.fast_window = 2.0
    node.slow_window = 6.0
    node.burn_factor = 2.0
    by_name = {r.name: r for r in alerts.default_rules()}
    assert by_name["slo_ttft_burn"].slo_seconds \
        == pytest.approx(0.25)
    assert by_name["slo_ttft_burn"].fast_window == 2.0
    assert by_name["slo_ttft_burn"].factor == 2.0
    # operator rules append, and a duplicate name REPLACES the default
    node.rules = [
        {"name": "gpu_queue", "type": "threshold",
         "series": "veles_serving_queue_depth", "threshold": 5.0},
        {"name": "queue_depth_high", "type": "threshold",
         "series": "veles_serving_queue_depth", "threshold": 7.0},
    ]
    by_name = {r.name: r for r in alerts.rules_from_config()}
    assert by_name["gpu_queue"].threshold == 5.0
    assert by_name["queue_depth_high"].threshold == 7.0
    assert len(by_name) == 6
    # a malformed operator rule refuses to start the engine
    node.rules = [{"name": "bad", "type": "threshold",
                   "series": "not_registered", "threshold": 1.0}]
    with pytest.raises(ValueError, match="unregistered series"):
        alerts.rules_from_config()


# -- off is OFF (the bit-identical contract) ----------------------------------

def test_watch_off_is_bit_identical_off():
    before = {name: counters.get(name) for name in WATCH_COUNTERS}
    assert timeseries.enabled() is False
    assert timeseries.maybe_start() is None
    assert timeseries.store() is None
    assert timeseries.alert_engine() is None
    assert alerts.render_firing() == ""
    assert timeseries.alerts_payload() == {"enabled": False,
                                           "rules": []}
    body = pull_payload(0)
    lines = body.strip().splitlines()
    assert len(lines) == 1
    header = json.loads(lines[0])
    assert header["enabled"] is False
    assert header["cursor"] == 0 and header["records"] == 0
    # not one watch counter moved through any of the reads above
    after = {name: counters.get(name) for name in WATCH_COUNTERS}
    assert after == before


def test_maybe_start_samples_and_stop_watch_tears_down():
    node = root.common.telemetry.watch
    node.enabled = True
    node.period = 0.02
    node.retention = 10.0
    s0 = counters.get("veles_watch_samples_total")
    st = timeseries.maybe_start()
    assert st is not None
    assert timeseries.maybe_start() is st          # idempotent
    assert timeseries.alert_engine() is not None
    deadline = time.time() + 10
    while len(st.samples()) < 2 and time.time() < deadline:
        time.sleep(0.02)
    assert len(st.samples()) >= 2
    assert counters.get("veles_watch_samples_total") > s0
    # a live pull: header + records, counted
    p0 = counters.get("veles_watch_pulls_total")
    header, records = parse_history(pull_payload(0))
    assert header["enabled"] is True and header["cursor"] > 0
    assert header["records"] == len(records) >= 2
    assert isinstance(header["alerts"], list) and header["alerts"]
    assert counters.get("veles_watch_pulls_total") == p0 + 1
    payload = timeseries.alerts_payload()
    assert payload["enabled"] is True
    assert {r["rule"] for r in payload["rules"]} \
        >= {"slo_ttft_burn", "brownout_shedding"}
    timeseries.stop_watch()
    assert timeseries.store() is None
    assert timeseries.alert_engine() is None
    frozen = counters.get("veles_watch_samples_total")
    time.sleep(0.1)
    assert counters.get("veles_watch_samples_total") == frozen


# -- the client-side fleet helpers (veles-tpu watch internals) ----------------

def _fake_agg(retired, ttft_cum, up=(True, True)):
    """One fleet.aggregate()-shaped result: merged exposition-form
    registries + per-endpoint up flags."""
    count = float(sum(ttft_cum.values()))
    return {
        "merged": {
            "counters": {"veles_serving_retired_total": retired,
                         "veles_serving_tokens_total": retired * 4.0},
            "histograms": {TTFT: {
                "buckets": dict(ttft_cum, **{"+Inf": count}),
                "count": count, "sum": count * 0.05}},
            "gauges": {"veles_serving_slots": 4.0,
                       "veles_serving_slots_busy": 1.0,
                       "veles_serving_queue_depth": 0.0},
        },
        "endpoints": [{"up": u} for u in up],
    }


def test_hist_to_snapshot_uncumulates_exposition_buckets():
    snap = fleet.hist_to_snapshot(
        {"buckets": {"0.1": 5.0, "1.0": 8.0, "+Inf": 10.0},
         "count": 10.0, "sum": 3.5})
    assert snap["bounds"] == [0.1, 1.0]
    assert snap["counts"] == [5.0, 3.0, 2.0]       # + overflow bucket
    assert snap["count"] == 10.0 and snap["sum"] == 3.5
    qs = fleet.quantiles({"buckets": {"0.1": 5.0, "1.0": 8.0,
                                      "+Inf": 10.0},
                          "count": 10.0, "sum": 3.5}, qs=(0.5,))
    assert qs[0.5] is not None and qs[0.5] <= 1.0


def test_ingest_aggregate_and_interval_report_windowed_rates():
    fk = FakeClock()
    st = SeriesStore(period=1.0, retention=60.0, clock=fk,
                     count_samples=False)
    rep = fleet.interval_report(st)
    assert rep["qps"] is None and rep["up"] is None
    fleet.ingest_aggregate(st, _fake_agg(100.0, {"0.1": 50.0}),
                           ts=fk.tick())
    fleet.ingest_aggregate(st, _fake_agg(130.0, {"0.1": 70.0},
                                         up=(True, False)),
                           ts=fk.tick(2.0))
    rep = fleet.interval_report(st)
    assert rep["endpoints"] == 2.0 and rep["up"] == 1.0
    assert rep["qps"] == pytest.approx(15.0)       # 30 retired / 2 s
    assert rep["tok_s"] == pytest.approx(60.0)
    assert rep["ttft_p50"] is not None
    assert rep["slots"] == 4.0 and rep["slots_busy"] == 1.0
    line = fleet.format_interval(rep)
    assert "up 1/2" in line and "qps 15" in line


def test_loadgen_verdict_fails_on_alert_abort():
    report = {"wall_seconds": 1.0, "offered": 5, "dispatched": 3,
              "answered": 3, "records": [],
              "aggregates": aggregate([], 1.0)}
    # park the TTFT bound: aggregate() folds in the PROCESS-global
    # server histogram, which earlier suites legitimately filled
    assert verdict(report, slo_ttft_ms=1e9)["pass"] is True
    report["aborted_on_alert"] = {"rules": ["slo_ttft_burn"],
                                  "after_requests": 3}
    v = verdict(report, slo_ttft_ms=1e9)
    assert v["pass"] is False
    check = {c["name"]: c for c in v["checks"]}["aborted_on_alert"]
    assert check["ok"] is False
    assert check["observed"] == "slo_ttft_burn"


# -- the live fleet: watch/alerts CLIs over real replicas ---------------------

@pytest.fixture(scope="module")
def lm_wf():
    lm = import_model("char_lm")
    from veles_tpu import prng
    prng.seed_all(2025)
    wf = lm.build_workflow(epochs=1, minibatch_size=32, n_blocks=1,
                           dim=32, n_train=64, n_valid=32)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    return lm, wf


def _get_text(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def test_watch_and_alerts_clis_over_live_fleet(lm_wf):
    """A 2-replica fleet behind the router with the watchtower ON:
    the sampler comes up with the first HTTP surface, the history
    cursor pull round-trips over HTTP, /alerts lists the shipped
    rules, /metrics carries the veles_alert_firing rows, and the
    watch / alerts CLIs read it all like a remote operator."""
    from veles_tpu.__main__ import main
    from veles_tpu.serving.router import FleetRouter
    lm, wf = lm_wf
    node = root.common.telemetry.watch
    node.enabled = True
    node.period = 0.05
    node.retention = 60.0
    # park the latency SLOs out of range: compile-heavy first
    # requests on a CI host would legitimately burn the shipped
    # 500 ms budget, and this test wants a QUIET fleet (the firing
    # path is locked by the engine tests above and bench gate_watch)
    node.slo_ttft_ms = 600000.0
    node.slo_e2e_ms = 600000.0
    apis = [vt.GenerationAPI(wf, port=0, engine="continuous",
                             max_slots=2, buckets=(8,),
                             max_context=24,
                             name="watchtest_%d" % i)
            for i in range(2)]
    router = None
    try:
        for api in apis:
            api.initialize()
        st = timeseries.store()
        assert st is not None        # the first surface started it
        router = FleetRouter(
            ["127.0.0.1:%d" % api.port for api in apis],
            probe_interval=0.2, failure_threshold=3, retry_budget=2,
            attempt_timeout=60.0, request_timeout=120.0,
            name="watchtest.router").start()
        base = "http://127.0.0.1:%d" % router.port
        rng = numpy.random.RandomState(41)
        for i in range(3):
            prompt = [int(t) for t in rng.randint(0, lm.VOCAB, 5)]
            req = urllib.request.Request(
                base + "/generate",
                data=json.dumps({"prompt": prompt,
                                 "n_new": 3}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as r:
                assert r.status == 200
                assert json.loads(r.read())["tokens"]
        deadline = time.time() + 10
        while len(st.samples()) < 3 and time.time() < deadline:
            time.sleep(0.05)
        assert len(st.samples()) >= 3
        # the HTTP cursor pull, full then incremental
        header, records = parse_history(
            _get_text(base + "/metrics/history?since=0"))
        assert header["enabled"] is True and header["cursor"] > 0
        assert any(r["kind"] == "watch.sample" for r in records)
        cursor = header["cursor"]
        h2, recs2 = parse_history(
            _get_text(base + "/metrics/history?since=%d" % cursor))
        assert all(r["seq"] > cursor for r in recs2)
        assert h2["cursor"] >= cursor
        # /alerts lists the shipped rule set; nothing firing at idle
        payload = json.loads(_get_text(base + "/alerts"))
        assert payload["enabled"] is True
        assert {r["rule"] for r in payload["rules"]} \
            >= {"slo_ttft_burn", "queue_depth_high",
                "brownout_shedding"}
        assert payload["firing"] == []
        # the firing gauge rows ride every live /metrics page
        text = _get_text(base + "/metrics")
        assert 'veles_alert_firing{rule="slo_ttft_burn"} 0' in text
        # the dispatch-count lock: the sampler only READS registries
        # — watching an idle fleet must not move the dispatch plane
        d0 = counters.get("veles_dispatches_total")
        n0 = len(st.samples())
        deadline = time.time() + 10
        while len(st.samples()) < n0 + 3 and time.time() < deadline:
            time.sleep(0.05)
        assert len(st.samples()) >= n0 + 3
        assert counters.get("veles_dispatches_total") == d0
        # veles-tpu watch --once: one frame, exit 0
        out = io.StringIO()
        with redirect_stdout(out):
            rc = main(["watch", base, "--once", "--no-clear",
                       "--period", "0.2", "--window", "10"])
        frame = out.getvalue()
        assert rc == 0
        assert "veles-tpu watch" in frame and "alerts:" in frame
        assert "1/1 endpoint(s) up" in frame
        # --json frames are machine-readable and carry the alerts
        out = io.StringIO()
        with redirect_stdout(out):
            rc = main(["watch", base, "--once", "--json",
                       "--period", "0.2"])
        assert rc == 0
        rep = json.loads(out.getvalue().strip().splitlines()[-1])
        assert rep["alerts"]["enabled"] is True
        assert "qps" in rep and "ttft_p99" in rep
        # metrics aggregate --watch: one interval line per scrape
        out = io.StringIO()
        with redirect_stdout(out):
            rc = main(["metrics", "aggregate", base, "--watch",
                       "0.2", "--iterations", "2"])
        assert rc == 0
        lines = [ln for ln in out.getvalue().splitlines()
                 if ln.strip()]
        assert len(lines) == 2
        assert "up 1/1" in lines[-1] and "qps" in lines[-1]
        # veles-tpu alerts: 0 with nothing firing, 2 with nobody home
        out = io.StringIO()
        with redirect_stdout(out):
            rc = main(["alerts", base])
        assert rc == 0
        assert "rule(s), 0 firing" in out.getvalue()
        assert main(["alerts", "127.0.0.1:9", "--timeout", "1"]) == 2
    finally:
        if router is not None:
            router.stop()
        for api in apis:
            api.stop()
        timeseries.stop_watch()
