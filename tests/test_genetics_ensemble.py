"""Meta-learning: GA hyper-parameter optimization + ensembles
(SURVEY.md §2.6). Mirrors the reference's genetics/ensemble surface:
gray coding, crossover families, Range markers ⇄ config mapping,
optimizer driving real training runs, ensemble train/soft-vote-test."""
import json
import os

import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn
from veles_tpu.config import Config
from veles_tpu.genetics import (GeneticsOptimizer, Population, Range,
                                find_tuneables, fix_config)
from veles_tpu.genetics.core import gray_decode, gray_encode
from veles_tpu.ensemble import EnsembleTrainer, EnsembleTester
from veles_tpu.loader import FullBatchLoader


# -- GA core -----------------------------------------------------------------

def test_gray_code_roundtrip():
    for n in (0, 1, 5, 255, 1000, 65535):
        assert gray_decode(gray_encode(n)) == n
    # adjacent values differ by one bit in gray space
    for n in range(200):
        assert bin(gray_encode(n) ^ gray_encode(n + 1)).count("1") == 1


@pytest.mark.parametrize("crossover",
                         ["uniform", "arithmetic", "geometric", "pointed"])
def test_population_optimizes_quadratic(crossover):
    pop = Population(mins=[0.0, 0.0], maxs=[1.0, 1.0], size=16,
                     crossover=crossover)

    def fitness(chromo, _):
        x, y = chromo.genes
        return -((x - 0.3) ** 2 + (y - 0.7) ** 2)

    for _ in range(15):
        pop.evolve(fitness)
    best = pop.best
    assert best.fitness > -0.02, (crossover, best.genes)
    # bounds respected everywhere
    for c in pop.chromosomes:
        assert (c.genes >= 0.0).all() and (c.genes <= 1.0).all()


def test_integer_genes_stay_integer():
    pop = Population(mins=[1], maxs=[64], ints=[True], size=8)
    pop.evolve(lambda c, i: -abs(c.genes[0] - 17))
    for c in pop.chromosomes:
        assert c.genes[0] == round(c.genes[0])
    assert isinstance(pop.best.values()[0], int)


# -- Range markers ⇄ config --------------------------------------------------

def test_find_and_fix_tuneables():
    cfg = Config("root")
    cfg.model.lr = Range(0.03, 0.001, 0.1)
    cfg.model.hidden = Range(100, 10, 500)
    cfg.other.fixed = 42
    tuns = find_tuneables(cfg)
    assert [t[0] for t in tuns] == ["root.model.lr", "root.model.hidden"]
    assert tuns[1][3].is_int and not tuns[0][3].is_int
    fix_config(tuns, [0.05, 200.3])
    assert cfg.model.lr == 0.05
    assert cfg.model.hidden == 200 and isinstance(cfg.model.hidden, int)


def test_range_validates_default():
    with pytest.raises(ValueError):
        Range(5.0, 0.0, 1.0)


def test_materialize_defaults_for_plain_runs():
    """A config written for --optimize must run plainly: markers collapse
    to their default values."""
    from veles_tpu.genetics import materialize_defaults
    cfg = Config("plain")
    cfg.m.lr = Range(0.03, 0.001, 0.1)
    cfg.m.hidden = Range(100, 10, 500)
    assert materialize_defaults(cfg) == 2
    assert cfg.m.lr == 0.03 and cfg.m.hidden == 100
    assert materialize_defaults(cfg) == 0


def test_optimizer_plumbing_with_fake_workflow():
    """GeneticsOptimizer end to end against a stub workflow: fitness must
    drive the config toward the known optimum."""
    cfg = Config("opt")
    cfg.m.x = Range(0.5, 0.0, 1.0)

    class FakeWF:
        def initialize(self, device=None):
            pass

        def run(self):
            pass

        def gather_results(self):
            return {"best_err": abs(cfg.m.x - 0.25)}

    opt = GeneticsOptimizer(build_workflow=FakeWF, config_node=cfg,
                            size=10, generations=8)
    res = opt.run()
    assert abs(res["best_config"]["opt.m.x"] - 0.25) < 0.05
    assert res["evaluations"] >= 10
    # markers restored for subsequent runs
    assert isinstance(cfg.m.x, Range)


# -- real-training integration ----------------------------------------------

class TinyBlobsLoader(FullBatchLoader):
    """2-class blobs, small enough for many short trainings."""

    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(3)
        n, d = 120, 6
        x0 = rng.randn(n, d).astype(numpy.float32) + 2.0
        x1 = rng.randn(n, d).astype(numpy.float32) - 2.0
        data = numpy.concatenate([x0, x1])
        labels = numpy.concatenate(
            [numpy.zeros(n), numpy.ones(n)]).astype(numpy.int32)
        perm = rng.permutation(len(data))
        self.create_originals(data[perm], labels[perm])
        self.class_lengths = [0, 60, 180]


def _tiny_workflow(epochs=3, lr=0.05):
    loader = TinyBlobsLoader(None, minibatch_size=30, name="tinyblobs")
    return nn.StandardWorkflow(
        name="tiny", layers=[
            {"type": "all2all_tanh", "output_sample_shape": 8,
             "learning_rate": lr},
            {"type": "softmax", "output_sample_shape": 2,
             "learning_rate": lr},
        ], loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=epochs, fail_iterations=20))


def test_ensemble_train_and_soft_vote(tmp_path):
    dev = vt.XLADevice(mesh_axes={"data": 1})
    manifest_file = str(tmp_path / "ens.json")
    trainer = EnsembleTrainer(
        _tiny_workflow, n_models=3, train_ratio=0.8, device=dev,
        out_file=manifest_file, directory=str(tmp_path), base_seed=99)
    manifest = trainer.run()
    assert len(manifest["models"]) == 3
    assert os.path.exists(manifest_file)
    # distinct seeds, snapshots on disk
    seeds = {m["seed"] for m in manifest["models"]}
    assert seeds == {99, 100, 101}
    for m in manifest["models"]:
        assert os.path.exists(m["snapshot"])
        assert m["results"]["best_err"] < 0.2

    tester = EnsembleTester(_tiny_workflow, manifest_file, device=dev)
    out = tester.run()
    assert out["n_models"] == 3
    assert out["ensemble_err"] <= 0.2
    # soft vote can't be (much) worse than the worst member on this data
    assert out["ensemble_err"] <= max(out["member_errs"]) + 1e-9


def test_optimizer_subprocess_mode(tmp_path):
    """Subprocess evaluation: candidate overrides must beat import-time
    Range markers in the child (re-applied post-import)."""
    model = tmp_path / "m.py"
    model.write_text("""
import os, sys
sys.path.insert(0, %r)
from veles_tpu.config import root
from veles_tpu.genetics import Range

root.subm.x = Range(0.5, 0.0, 1.0)


class _WF:
    loader = None

    def initialize(self, device=None):
        pass

    def run(self):
        pass

    def gather_results(self):
        return {"best_err": abs(float(root.subm.x) - 0.25)}


def build_workflow():
    return _WF()
""" % os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from veles_tpu.config import root as cfg_root
    from veles_tpu.genetics import Range as R
    cfg_root.subm.x = R(0.5, 0.0, 1.0)
    try:
        opt = GeneticsOptimizer(
            model_path=str(model), config_node=cfg_root.subm,
            size=4, generations=1, subprocess_mode=True)
        res = opt.run()
        # fitness must VARY across candidates (not stuck at the marker
        # default, which would mean overrides lost to import-time config)
        fits = {round(f, 6) for _, f in opt.history}
        assert len(fits) > 1, opt.history
        assert 0.0 <= res["best_config"]["root.subm.x"] <= 1.0
    finally:
        delattr(cfg_root, "subm")


def test_train_ratio_subsamples_train_class():
    loader = TinyBlobsLoader(None, minibatch_size=30, name="sub")
    loader.train_ratio = 0.5
    wf = nn.StandardWorkflow(
        name="sub", layers=[{"type": "softmax", "output_sample_shape": 2}],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=1))
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    assert loader.class_lengths == [0, 60, 90]
    # indices stay valid rows of the original data
    assert loader._shuffled_indices.max() < 240
    assert len(loader._shuffled_indices) == 150


# -- operator census parity (reference veles/genetics/core.py) ---------------

def test_operator_census_matches_reference():
    """Reference census: mutations binary_point/gaussian/uniform/altering
    (core.py:205-211), selections roulette/random/tournament (:573-616),
    crossovers uniform/arithmetic/geometric/pointed (:633-747)."""
    assert set(Population.MUTATIONS) == {
        "binary", "gaussian", "uniform", "altering"}
    assert set(Population.SELECTIONS) == {
        "roulette", "random", "tournament"}


@pytest.mark.parametrize("op", list(Population.MUTATIONS))
def test_mutation_preserves_bounds_and_intness(op):
    """Property gate: every mutation operator keeps genes inside their
    per-gene bounds and integer genes integral, under heterogeneous
    ranges (the altering swap crosses ranges deliberately)."""
    from veles_tpu.genetics.core import Chromosome
    rng = numpy.random.RandomState(7)
    mins = numpy.array([0.0, -5.0, 1.0])
    maxs = numpy.array([1.0, 5.0, 64.0])
    ints = [False, False, True]
    for _ in range(200):
        genes = mins + (maxs - mins) * rng.rand(3)
        c = Chromosome(genes, mins, maxs, ints)
        if op == "binary":
            c.mutate_binary(2, rng)
        elif op == "gaussian":
            c.mutate_gaussian(2, 0.3, rng)
        elif op == "uniform":
            c.mutate_uniform(2, rng)
        else:
            c.mutate_altering(2, rng)
        assert (c.genes >= mins).all() and (c.genes <= maxs).all(), \
            (op, c.genes)
        assert c.genes[2] == round(c.genes[2]), (op, c.genes)


def test_altering_mutation_swaps_and_single_gene_noop():
    from veles_tpu.genetics.core import Chromosome
    rng = numpy.random.RandomState(1)
    mins = numpy.array([0.0, 0.0])
    maxs = numpy.array([10.0, 10.0])
    c = Chromosome(numpy.array([2.0, 9.0]), mins, maxs, [False, False])
    before = set(c.genes)
    c.mutate_altering(1, rng)
    assert set(c.genes) == before          # values permuted, not altered
    solo = Chromosome(numpy.array([3.0]), numpy.array([0.0]),
                      numpy.array([10.0]), [False])
    solo.mutate_altering(5, rng)
    assert solo.genes[0] == 3.0


@pytest.mark.parametrize("selection", ["roulette", "tournament", "random"])
def test_selection_procedures_converge(selection):
    pop = Population(mins=[0.0, 0.0], maxs=[1.0, 1.0], size=16,
                     selection=selection)

    def fitness(chromo, _):
        x, y = chromo.genes
        return -((x - 0.3) ** 2 + (y - 0.7) ** 2)

    for _ in range(15):
        pop.evolve(fitness)
    # random selection leans on elitism alone — looser gate
    gate = -0.25 if selection == "random" else -0.02
    assert pop.best.fitness > gate, (selection, pop.best.genes)
    with pytest.raises(ValueError):
        Population(mins=[0.0], maxs=[1.0], selection="nope")


def test_batch_evaluator_scores_a_generation_at_once():
    calls = []

    def batch(chromos):
        calls.append(len(chromos))
        return [-abs(c.genes[0] - 0.5) for c in chromos]

    pop = Population(mins=[0.0], maxs=[1.0], size=8)
    pop.evolve(batch_evaluator=batch)
    pop.evolve(batch_evaluator=batch)
    assert calls[0] == 8                 # whole first generation at once
    # second generation: elite keeps its score, only children re-scored
    assert 0 < calls[1] <= 8 - 1
    with pytest.raises(ValueError):
        pop.evolve(batch_evaluator=lambda cs: [0.0] * (len(cs) + 1))
    with pytest.raises(ValueError):
        Population(mins=[0.0], maxs=[1.0]).evolve()


# -- parallel trial evaluation (VERDICT r2 missing #3) -----------------------

FAKE_MODEL = """
import os, sys
sys.path.insert(0, %r)
from veles_tpu.config import root
from veles_tpu.genetics import Range

root.par.x = Range(0.5, 0.0, 1.0)


class _WF:
    loader = None

    def initialize(self, device=None):
        pass

    def run(self):
        pass

    def gather_results(self):
        return {"best_err": abs(float(root.par.x) - 0.25)}


def build_workflow():
    return _WF()
"""


def test_optimizer_parallel_workers(tmp_path):
    """n_workers > 1 farms a generation of candidates through the trial
    scheduler (subprocess isolation implied); fitness mapping, history
    and bounds behave exactly as in serial mode."""
    model = tmp_path / "m.py"
    model.write_text(FAKE_MODEL % os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from veles_tpu.config import root as cfg_root
    from veles_tpu.genetics import Range as R
    cfg_root.par.x = R(0.5, 0.0, 1.0)
    try:
        opt = GeneticsOptimizer(
            model_path=str(model), config_node=cfg_root.par,
            size=4, generations=1, n_workers=4)
        assert opt.subprocess_mode          # implied by n_workers
        res = opt.run()
        assert res["evaluations"] == 4
        assert len(opt.history) == 4
        fits = {round(f, 6) for _, f in opt.history}
        assert len(fits) > 1, opt.history   # candidates really varied
        assert 0.0 <= res["best_config"]["root.par.x"] <= 1.0
    finally:
        delattr(cfg_root, "par")


ENSEMBLE_MODEL = """
import sys
sys.path.insert(0, %r)
import numpy
from veles_tpu import nn
from veles_tpu.loader import FullBatchLoader


class Blobs(FullBatchLoader):
    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(3)
        n, d = 120, 6
        x0 = rng.randn(n, d).astype(numpy.float32) + 2.0
        x1 = rng.randn(n, d).astype(numpy.float32) - 2.0
        data = numpy.concatenate([x0, x1])
        labels = numpy.concatenate(
            [numpy.zeros(n), numpy.ones(n)]).astype(numpy.int32)
        perm = rng.permutation(len(data))
        self.create_originals(data[perm], labels[perm])
        self.class_lengths = [0, 60, 180]


def build_workflow(**kw):
    loader = Blobs(None, minibatch_size=30, name="blobs")
    return nn.StandardWorkflow(
        name="tiny", layers=[
            {"type": "all2all_tanh", "output_sample_shape": 8,
             "learning_rate": 0.05},
            {"type": "softmax", "output_sample_shape": 2,
             "learning_rate": 0.05},
        ], loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=3, fail_iterations=20))
"""


def test_ensemble_parallel_workers(tmp_path):
    """Members farmed out as --ensemble-member CLI children through the
    scheduler: same manifest contract as sequential mode (distinct
    seeds, snapshots on disk, results), consumable by EnsembleTester."""
    model = tmp_path / "blobs_model.py"
    model.write_text(ENSEMBLE_MODEL % os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    manifest_file = str(tmp_path / "ens.json")
    trainer = EnsembleTrainer(
        None, n_models=3, train_ratio=0.8, out_file=manifest_file,
        directory=str(tmp_path), base_seed=99, n_workers=3,
        model_path=str(model))
    manifest = trainer.run()
    assert len(manifest["models"]) == 3
    assert "failed_members" not in manifest
    assert {m["seed"] for m in manifest["models"]} == {99, 100, 101}
    for m in manifest["models"]:
        assert os.path.exists(m["snapshot"])
        assert m["results"]["best_err"] < 0.2
    # the parallel-trained manifest feeds the tester unchanged
    import importlib.util
    spec = importlib.util.spec_from_file_location("blobs_model",
                                                  str(model))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    dev = vt.XLADevice(mesh_axes={"data": 1})
    out = EnsembleTester(mod.build_workflow, manifest_file,
                         device=dev).run()
    assert out["n_models"] == 3
    assert out["ensemble_err"] <= 0.2


def test_ensemble_parallel_needs_model_path():
    from veles_tpu.error import VelesError
    with pytest.raises(VelesError):
        EnsembleTrainer(None, n_models=2, n_workers=2)
