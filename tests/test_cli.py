"""CLI surface: mirrors reference veles/tests/test_velescli.py scope."""
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(*argv, timeout=240):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    # --backend cpu: the TPU plugin ignores JAX_PLATFORMS, and tests must
    # not contend for the real chip
    return subprocess.run(
        [sys.executable, "-m", "veles_tpu", *argv, "--backend", "cpu"],
        cwd=REPO, capture_output=True, text=True, timeout=timeout, env=env)


@pytest.fixture(scope="module")
def tiny_model(tmp_path_factory):
    path = tmp_path_factory.mktemp("m") / "tiny_model.py"
    path.write_text(textwrap.dedent("""
        import numpy
        from veles_tpu import nn
        from veles_tpu.loader import FullBatchLoader

        class L(FullBatchLoader):
            hide_from_registry = True
            def load_data(self):
                rng = numpy.random.RandomState(0)
                self.create_originals(
                    rng.rand(120, 6).astype(numpy.float32),
                    rng.randint(0, 3, 120).astype(numpy.int32))
                self.class_lengths = [0, 24, 96]

        def build_workflow():
            return nn.StandardWorkflow(
                name="tiny",
                layers=[{"type": "softmax", "output_sample_shape": 3}],
                loader_unit=L(None, minibatch_size=24, name="l"),
                loss_function="softmax",
                decision_config=dict(max_epochs=2))
    """))
    return str(path)


def test_cli_dry_run(tiny_model):
    r = run_cli(tiny_model, "--dry-run", "-v")
    assert r.returncode == 0, r.stderr
    assert "dry run: initialize OK" in r.stderr + r.stdout


def test_cli_full_run_with_results(tiny_model, tmp_path):
    res = tmp_path / "r.json"
    r = run_cli(tiny_model, "--result-file", str(res), "-v")
    assert r.returncode == 0, r.stderr
    data = json.loads(res.read_text())
    assert data["epochs"] == 2
    assert "best_err" in data


def test_cli_workflow_graph(tiny_model, tmp_path):
    dot = tmp_path / "g.dot"
    r = run_cli(tiny_model, "--workflow-graph", str(dot))
    assert r.returncode == 0, r.stderr
    assert "digraph" in dot.read_text()


def test_cli_config_override(tiny_model):
    r = run_cli(tiny_model, "root.common.trace.run=true", "--dry-run",
                "-v")
    assert r.returncode == 0, r.stderr


def test_cli_dump_config(tiny_model):
    r = run_cli(tiny_model, "--dump-config")
    assert r.returncode == 0
    assert "engine:" in r.stdout


def test_cli_bad_model(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("x = 1\n")
    r = run_cli(str(bad), "--dry-run")
    assert r.returncode != 0
    assert "build_workflow" in r.stderr


def test_cli_test_mode_no_updates(tiny_model, tmp_path):
    """--test runs one evaluation pass without changing params."""
    res = tmp_path / "t.json"
    r = run_cli(tiny_model, "--test", "--result-file", str(res))
    assert r.returncode == 0, r.stderr
    data = json.loads(res.read_text())
    assert data["epochs"] == 1


def test_import_file_does_not_clobber_stdlib(tmp_path):
    from veles_tpu.import_file import import_file_as_module
    p = tmp_path / "json.py"
    p.write_text("VALUE = 42\n")
    m = import_file_as_module(str(p))
    assert m.VALUE == 42
    import json as real_json
    assert hasattr(real_json, "dumps")


def test_import_file_error_cleans_sys_modules(tmp_path):
    import sys as _sys
    from veles_tpu.import_file import import_file_as_module
    p = tmp_path / "broken_model.py"
    p.write_text("raise RuntimeError('boom')\n")
    with pytest.raises(RuntimeError):
        import_file_as_module(str(p))
    assert "veles_model_broken_model" not in _sys.modules


def test_debug_flag_and_rss(tiny_model):
    """--debug Class enables that logger; max RSS logged at exit."""
    from veles_tpu.logger import enable_debug
    import logging
    enable_debug("SomeUnitClass")
    assert logging.getLogger("SomeUnitClass").level == logging.DEBUG
    out = run_cli(str(tiny_model), "--debug", "Launcher")
    assert "max RSS" in out.stderr + out.stdout


def test_cli_optimize_zoo_model_with_workers(tmp_path):
    """VERDICT r2: Range-marked config end-to-end through --optimize on
    a ZOO model, with the parallel trial scheduler (--optimize-workers).
    models/lines.py carries root.lines.lr = Range(...); candidates are
    CLI subprocesses placed on private CPU devices."""
    rf = str(tmp_path / "opt.json")
    r = run_cli(os.path.join(REPO, "models", "lines.py"),
                "--optimize", "3:1", "--optimize-workers", "3",
                "--optimize-selection", "tournament",
                "--result-file", rf,
                "root.lines.epochs=2", "root.lines.n_train=240",
                "root.lines.n_valid=80", "root.lines.mb=40",
                timeout=900)
    assert r.returncode == 0, r.stderr[-2000:]
    with open(rf) as f:
        res = json.load(f)
    assert res["evaluations"] == 3
    assert 0.0005 <= res["best_config"]["root.lines.lr"] <= 0.01
    # candidates actually trained: a working lines run at 2 epochs gets
    # well under chance (0.75); -inf would mean every child failed
    assert res["best_fitness"] > -0.75, res


def test_cli_ensemble_train_with_workers(tiny_model, tmp_path):
    """--ensemble-workers farms members out as --ensemble-member CLI
    children; the manifest matches the sequential contract."""
    ens = str(tmp_path / "ens.json")
    r = run_cli(tiny_model, "--ensemble-train", "2:0.9",
                "--ensemble-workers", "2", "--ensemble-file", ens,
                "--snapshot-dir", str(tmp_path), "--random-seed", "5",
                timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    with open(ens) as f:
        manifest = json.load(f)
    assert manifest["n_models"] == 2
    assert len(manifest["models"]) == 2
    assert {m["seed"] for m in manifest["models"]} == {5, 6}
    for m in manifest["models"]:
        assert os.path.exists(m["snapshot"])


def test_cli_config_file_survives_model_import(tmp_path):
    """A config FILE must win over the model's import-time defaults,
    exactly like inline overrides do — the model import runs after
    update_from_file and used to clobber it silently."""
    model = tmp_path / "m.py"
    model.write_text(textwrap.dedent("""
        from veles_tpu.config import root
        root.t.x = 1                     # import-time default

        class _WF:
            loader = None
            def initialize(self, device=None): pass
            def run(self): pass
            def gather_results(self):
                return {"x": int(root.t.x)}

        def build_workflow():
            return _WF()
    """))
    conf = tmp_path / "conf.py"
    conf.write_text("root.t.x = 2\n")
    rf = str(tmp_path / "res.json")
    r = run_cli(str(model), str(conf), "--result-file", rf)
    assert r.returncode == 0, r.stderr[-2000:]
    with open(rf) as f:
        assert json.load(f)["x"] == 2


def test_cli_optimize_mnist_integer_gene(tmp_path):
    """Second optimize-ready zoo model (mnist): an INTEGER gene (hidden
    width) changes traced shapes per candidate — recompile-per-candidate
    must work through the parallel CLI path."""
    rf = str(tmp_path / "opt.json")
    r = run_cli(os.path.join(REPO, "models", "mnist.py"),
                "--optimize", "3:1", "--optimize-workers", "3",
                "--result-file", rf,
                timeout=900)
    assert r.returncode == 0, r.stderr[-2000:]
    with open(rf) as f:
        res = json.load(f)
    assert res["evaluations"] == 3
    cfg = res["best_config"]
    assert 0.001 <= cfg["root.mnist.lr"] <= 0.3
    assert isinstance(cfg["root.mnist.hidden"], int)
    assert 25 <= cfg["root.mnist.hidden"] <= 400
    assert res["best_fitness"] > -0.5, res      # really trained


def test_cli_optimize_workers_with_trial_devices(tmp_path):
    """--trial-devices D routes --optimize-workers through
    mesh_slice_placement: every candidate child trains on its own
    disjoint D-chip slice (VERDICT r3 weak #6 — the CLI leg). On this
    CPU host each child materializes D virtual devices from its
    TPU_VISIBLE_CHIPS slice, so a passing run proves the placement
    plumbing end-to-end."""
    rf = str(tmp_path / "opt.json")
    r = run_cli(os.path.join(REPO, "models", "lines.py"),
                "--optimize", "2:1", "--optimize-workers", "2",
                "--trial-devices", "2",
                "--result-file", rf,
                "root.lines.epochs=2", "root.lines.n_train=240",
                "root.lines.n_valid=80", "root.lines.mb=40",
                timeout=900)
    assert r.returncode == 0, r.stderr[-2000:]
    with open(rf) as f:
        res = json.load(f)
    assert res["evaluations"] == 2
    # children actually trained on their slices, not silently failed
    assert res["best_fitness"] > -0.75, res


_SERVE_LM_MODEL = textwrap.dedent("""
    import numpy
    from veles_tpu import nn
    from veles_tpu.loader import FullBatchLoaderMSE

    class L(FullBatchLoaderMSE):
        hide_from_registry = True
        def load_data(self):
            rng = numpy.random.RandomState(0)
            stream = rng.randint(0, 8, 64 * 16 + 1).astype(
                numpy.int32)
            self.create_originals(
                stream[:-1].reshape(64, 16), None,
                targets=stream[1:].reshape(64, 16))
            self.class_lengths = [0, 16, 48]

    def build_workflow():
        return nn.StandardWorkflow(
            name="srv-lm-%(tag)s",
            layers=[{"type": "embedding", "vocab_size": 8,
                     "dim": %(dim)d},
                    {"type": "transformer_block", "n_heads": 2,
                     "ffn_hidden": %(ffn)d, "causal": True,
                     "rope": True}] * %(blocks)d
                   + [{"type": "lm_head", "vocab_size": 8}],
            loader_unit=L(None, minibatch_size=16, name="l"),
            loss_function="softmax_seq",
            decision_config=dict(max_epochs=1))
""")


def _serve_and_post(argv, payload, tmp_path):
    """Start `python -m veles_tpu ... --serve-generate 0`, learn the
    port from the scriptable SERVING line (no bind-then-close port
    race), POST once, SIGINT, return (response, stdout, returncode)."""
    import json as _json
    import signal
    import time
    import urllib.request
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    proc = subprocess.Popen(
        [sys.executable, "-m", "veles_tpu", *argv,
         "--serve-generate", "0", "--backend", "cpu"],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env)
    try:
        import select
        port = None
        deadline = time.time() + 180
        buf = ""
        while time.time() < deadline and port is None:
            # select makes the deadline REAL: a wedged server that
            # prints nothing must fail the test, not hang readline()
            ready, _, _ = select.select([proc.stdout], [], [], 1.0)
            if not ready:
                if proc.poll() is not None:
                    raise AssertionError(
                        "server died:\n" + proc.communicate()[0][-3000:])
                continue
            chunk = os.read(proc.stdout.fileno(), 4096).decode(
                errors="replace")
            if not chunk:
                raise AssertionError(
                    "server died:\n" + proc.communicate()[0][-3000:])
            buf += chunk
            # only parse COMPLETE lines — os.read can split mid-line,
            # and 'SERVING port=80' from 'port=8080' must not parse
            *lines, buf = buf.split("\n")
            for line in lines:
                if line.startswith("SERVING port="):
                    port = int(line.strip().split("=", 1)[1])
                    break
        assert port is not None, "no SERVING line before deadline"
        req = urllib.request.Request(
            "http://127.0.0.1:%d/generate" % port,
            data=_json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            out = _json.loads(r.read())
        proc.send_signal(signal.SIGINT)
        stdout, _ = proc.communicate(timeout=60)
        return out, stdout, proc.returncode
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()


def test_cli_serve_generate(tmp_path):
    """--serve-generate: the CLI face of GenerationAPI — train briefly,
    serve, answer a greedy request over real HTTP, stop on SIGINT."""
    model = tmp_path / "lm_model.py"
    model.write_text(_SERVE_LM_MODEL
                     % {"tag": "t", "dim": 16, "ffn": 32, "blocks": 1})
    out, stdout, rc = _serve_and_post(
        [str(model)], {"prompt": [1, 2, 3], "n_new": 5}, tmp_path)
    assert len(out["tokens"]) == 5, out
    assert rc == 0, stdout[-2000:]


def test_cli_serve_generate_with_draft(tmp_path):
    """--serve-draft wires a second model so mode=speculative works
    end-to-end from the CLI (without it, speculative is a 400)."""
    target = tmp_path / "target.py"
    target.write_text(_SERVE_LM_MODEL
                      % {"tag": "tg", "dim": 16, "ffn": 32,
                         "blocks": 2})
    draft = tmp_path / "draft.py"
    draft.write_text(_SERVE_LM_MODEL
                     % {"tag": "dr", "dim": 8, "ffn": 16, "blocks": 1})
    out, stdout, rc = _serve_and_post(
        [str(target), "--serve-draft", str(draft)],
        {"prompt": [1, 2, 3], "n_new": 6, "mode": "speculative",
         "gamma": 2}, tmp_path)
    assert len(out["tokens"]) == 6, out
    assert 0.0 <= out["acceptance"] <= 1.0, out
    assert rc == 0, stdout[-2000:]


def test_cli_serve_generate_rejects_non_lm(tiny_model):
    r = run_cli(tiny_model, "--serve-generate", "0")
    assert r.returncode != 0
    # split_stack's reason, raised at startup — not a 500 per request
    assert "cached sampling supports" in (r.stderr + r.stdout)


def test_cli_serve_draft_snapshot_requires_draft(tiny_model):
    r = run_cli(tiny_model, "--serve-generate", "0",
                "--serve-draft-snapshot", "x.pickle.gz")
    assert r.returncode != 0
    assert "--serve-draft" in (r.stderr + r.stdout)
