"""Fleet-wide distributed tracing (ISSUE 14): trace propagation,
router spans, cross-process timeline assembly.

The contract under test: (1) the router mints a ``trace_id`` per
accepted request (request_id family) and forwards it — with the
1-based ``attempt`` number — on every attempt; the replica Ticket
adopts both and every request lifecycle span / flight event / journal
record carries them; (2) the router's own decisions are spans —
``route.request`` (root), ``route.attempt`` (endpoint, outcome,
status, resume ``tokens_done``), ``route.backoff`` (scheduled breaker
hold), ``route.probe`` (half-open recovery try), ``route.replay``
(journal tail) — gated by ``root.common.trace.requests`` exactly like
the replica spans; (3) every request-plane HTTP surface serves its
bounded span ring at ``GET /trace/spans?since=CURSOR`` (JSONL, torn
reads salvage per record), and ``veles-tpu trace fleet`` pulls
router + replicas, estimates per-process clock offsets by bracketing
alignment (route.attempt ⊇ replica request, matched on
trace_id+attempt), and merges ONE Chrome trace with one lane per
process; (4) the JSONL ``--trace-file`` rotates past
``root.common.trace.rotate_bytes`` with a counted rotation; (5) a
black-box dump filters by request (``blackbox inspect --request``);
(6) ``scripts/check_counters.py --docs`` fails on any registered
counter/histogram missing from docs/observability.md.

THE acceptance drill: a journaled 2-replica fleet with an injected
mid-decode replica death yields, via the ``trace fleet`` CLI, a
single Chrome trace containing the router's route.request/
route.attempt spans and BOTH replicas' request spans under one
trace_id, the resume attempt's tokens_done visible — with decode
dispatch counts proven bit-identical tracing on/off THROUGH THE
ROUTER (the PR 11 lock extended to the fleet path).

Budget discipline: everything above the drill is jax-free (fake HTTP
replicas, fake span payloads, fake clocks); the drill shares one tiny
char_lm workflow.
"""
import json
import os
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

import veles_tpu as vt
from veles_tpu.config import root
from veles_tpu.serving.router import CircuitBreaker, FleetRouter
from veles_tpu.serving.scheduler import Ticket, new_trace_id
from veles_tpu.resilience.retry import RetryPolicy
from veles_tpu.telemetry import fleet
from veles_tpu.telemetry.counters import counters
from veles_tpu.telemetry.spans import (pull_payload,
                                       recorder as span_recorder)

from conftest import import_model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _post(url, payload, timeout=120.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _new_spans(cursor, *name_prefixes):
    recs, nxt = span_recorder.records_since(cursor)
    if name_prefixes:
        recs = [r for r in recs
                if str(r.get("name", "")).startswith(name_prefixes)]
    return recs, nxt


# -- trace_id propagation (no jax, no HTTP) -----------------------------------

def test_new_trace_id_family_and_ticket_defaults():
    tid = new_trace_id()
    assert tid.startswith("trace-%d-" % os.getpid())
    t = Ticket()
    # no router involved: the request's own id IS its trace
    assert t.trace_id == t.request_id
    assert t.attempt == 1
    t2 = Ticket(trace_id="trace-9-9", attempt=3)
    assert t2.trace_id == "trace-9-9" and t2.attempt == 3


def test_ticket_lifecycle_spans_carry_trace_id_and_attempt():
    from veles_tpu.telemetry.recorder import flight
    prev = root.common.trace.get("requests", True)
    root.common.trace.requests = True
    cursor = span_recorder.cursor()
    try:
        t = Ticket(trace_id="trace-7-7", attempt=2, mode="greedy")
        t.mark_admitted()
        t.mark_prefill_done()
        t.mark_first_token()
        assert t.succeed({"tokens": [1, 2, 3]})
    finally:
        root.common.trace.requests = prev
    recs, _ = _new_spans(cursor, "request")
    mine = [r for r in recs if r.get("request_id") == t.request_id]
    names = {r["name"] for r in mine}
    assert {"request", "request.queue", "request.prefill",
            "request.decode"} <= names
    for r in mine:
        assert r["trace_id"] == "trace-7-7"
        assert r["attempt"] == 2
    done = [r for r in flight.records(kind="request")
            if r.get("request_id") == t.request_id]
    assert done
    assert all(r.get("trace_id") == "trace-7-7"
               and r.get("attempt") == 2 for r in done)


def test_generation_api_parse_adopts_trace_and_attempt():
    wf = vt.Workflow(name="parse_wf")
    api = vt.GenerationAPI(wf, engine="window", name="parse_api")
    req = api._parse({"prompt": [1, 2], "n_new": 2,
                      "trace_id": "trace-1-5", "attempt": 2,
                      "request_id": "req-1-5"})
    assert req["trace_id"] == "trace-1-5" and req["attempt"] == 2
    with pytest.raises(ValueError):
        api._parse({"prompt": [1], "n_new": 2, "trace_id": ""})
    with pytest.raises(ValueError):
        api._parse({"prompt": [1], "n_new": 2, "attempt": 0})
    with pytest.raises(ValueError):
        api._parse({"prompt": [1], "n_new": 2, "attempt": True})


def test_journal_records_carry_trace_id(tmp_path):
    from veles_tpu.serving.journal import RequestJournal
    j = RequestJournal(str(tmp_path), fsync=False)
    j.admit("req-a", {"prompt": [1], "trace_id": "trace-a"}, 100.0,
            trace_id="trace-a")
    j.done("req-a", 200, trace_id="trace-a", attempts=2)
    admits, terminals = j.replay()
    assert admits["req-a"]["trace_id"] == "trace-a"
    assert terminals["req-a"]["trace_id"] == "trace-a"
    assert terminals["req-a"]["attempts"] == 2


# -- span ring: cursor pulls, rotation (no jax) -------------------------------

def test_span_ring_cursor_and_pull_payload():
    from veles_tpu.telemetry.spans import emit
    cursor = span_recorder.cursor()
    pulls = counters.get("veles_trace_span_pulls_total")
    emit("t.alpha", 100.0, 0.5, request_id="req-c-1")
    emit("t.beta", 101.0, 0.5, request_id="req-c-2")
    recs, nxt = span_recorder.records_since(cursor)
    assert [r["name"] for r in recs] == ["t.alpha", "t.beta"]
    assert nxt == recs[-1]["seq"]
    # incremental: the new cursor yields nothing until more appends
    assert span_recorder.records_since(nxt)[0] == []
    payload = pull_payload(cursor, name="unit")
    assert counters.get("veles_trace_span_pulls_total") - pulls == 1
    parsed = fleet.parse_span_payload(payload)
    assert parsed["header"]["pid"] == os.getpid()
    assert parsed["header"]["name"] == "unit"
    assert parsed["header"]["cursor"] == nxt
    assert [r["name"] for r in parsed["spans"]] \
        == ["t.alpha", "t.beta"]


def test_torn_span_payload_salvages_with_warning(caplog):
    from veles_tpu.telemetry.spans import emit
    cursor = span_recorder.cursor()
    for i in range(4):
        emit("t.torn", 100.0 + i, 0.1, i=i)
    payload = pull_payload(cursor)
    # cut mid-way through the LAST record: the complete prefix lives
    torn = payload[:payload.rindex('{"')] + '{"name": "t.to'
    import logging
    with caplog.at_level(logging.WARNING, "veles_tpu.telemetry"):
        parsed = fleet.parse_span_payload(torn)
    assert parsed["bad"] == 1
    assert [r["i"] for r in parsed["spans"]] == [0, 1, 2]
    assert any("torn" in rec.message or "skipped" in rec.message
               for rec in caplog.records)


def test_trace_file_rotation_counted(tmp_path):
    from veles_tpu.telemetry.spans import emit
    prev = root.common.trace.get("rotate_bytes", None)
    root.common.trace.rotate_bytes = 1500
    path = str(tmp_path / "run.jsonl")
    rotations = counters.get("veles_trace_rotations_total")
    try:
        span_recorder.set_sink(path)
        for i in range(40):
            emit("t.rot", 100.0 + i, 0.1, i=i,
                 padding="x" * 40)
    finally:
        span_recorder.set_sink(None)
        if prev is None:
            root.common.trace.rotate_bytes = 64 << 20
        else:
            root.common.trace.rotate_bytes = prev
    assert counters.get("veles_trace_rotations_total") - rotations >= 1
    assert os.path.exists(path + ".1")       # segment-drop pattern
    assert os.path.getsize(path) < 3000      # bounded, not history


# -- /trace/spans HTTP endpoint (router; no replicas needed) ------------------

def test_router_serves_trace_spans_endpoint():
    from veles_tpu.telemetry.spans import emit
    router = FleetRouter(["127.0.0.1:9"], probe_interval=5.0,
                         probe_timeout=0.2,
                         name="spans_ep_router").start()
    try:
        emit("t.http", 100.0, 0.5, request_id="req-h-1")
        base = "http://127.0.0.1:%d" % router.port
        with urllib.request.urlopen(base + "/trace/spans?since=0",
                                    timeout=10) as r:
            assert r.status == 200
            assert "ndjson" in r.headers.get("Content-Type", "")
            parsed = fleet.parse_span_payload(
                r.read().decode())
        assert parsed["header"]["name"].startswith("router.")
        assert any(s["name"] == "t.http" for s in parsed["spans"])
        cursor = parsed["header"]["cursor"]
        # incremental pull from the returned cursor is empty
        with urllib.request.urlopen(
                base + "/trace/spans?since=%d" % cursor,
                timeout=10) as r:
            parsed2 = fleet.parse_span_payload(r.read().decode())
        assert parsed2["spans"] == []
        # a bad cursor is a 400, not a traceback
        try:
            urllib.request.urlopen(base + "/trace/spans?since=xyz",
                                   timeout=10)
            assert False, "expected 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        router.stop()


# -- clock-offset estimation on fake clocks -----------------------------------

def _fake_payload(url, pid, name, spans):
    for i, rec in enumerate(spans, 1):
        rec.setdefault("seq", i)
        rec.setdefault("dur", 0.0)
    return {"url": url, "spans": spans, "bad": 0,
            "header": {"kind": "spans.header", "pid": pid,
                       "name": name, "cursor": len(spans),
                       "wall": 0.0}}


def test_offset_estimation_recovers_known_skew_within_bound():
    skew = 3.7          # replica clock AHEAD of the router's
    router_spans = [
        {"name": "route.request", "ts": 99.9, "dur": 6.0,
         "trace_id": "t1", "request_id": "r1"},
        {"name": "route.attempt", "ts": 100.0, "dur": 2.0,
         "trace_id": "t1", "attempt": 1, "endpoint": "http://a"},
        {"name": "route.attempt", "ts": 103.0, "dur": 2.4,
         "trace_id": "t1", "attempt": 2, "endpoint": "http://b"},
    ]
    replica_spans = [
        {"name": "request", "ts": 100.4 + skew, "dur": 1.4,
         "trace_id": "t1", "attempt": 1, "request_id": "r1"},
        {"name": "request", "ts": 103.5 + skew, "dur": 1.6,
         "trace_id": "t1", "attempt": 2, "request_id": "r1"},
    ]
    payloads = [_fake_payload("router", 11, "router.r",
                              router_spans),
                _fake_payload("replica", 22, "serve.a",
                              replica_spans)]
    procs = fleet._group_processes(payloads)
    offsets = fleet.estimate_offsets(procs)
    assert offsets[11]["reference"] is True
    est = offsets[22]
    assert est["pairs"] == 2
    # within the bracketing-span bound: the estimate can only be as
    # tight as the attempt-minus-request slack
    assert abs(est["offset"] - skew) <= est["bound"] / 2 + 1e-9
    # assembly shifts the replica lane onto the router clock
    doc, summary = fleet.assemble_fleet_trace(payloads)
    assert summary["processes"] == 2
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    req = [e for e in evs if e["name"] == "request"
           and e["args"].get("attempt") == 1][0]
    att = [e for e in evs if e["name"] == "route.attempt"
           and e["args"].get("attempt") == 1][0]
    # corrected: the replica request event sits INSIDE its bracket
    assert att["ts"] - 1e3 <= req["ts"] \
        <= att["ts"] + att["dur"] + 1e3      # µs, 1ms slack
    lanes = {e["pid"] for e in evs}
    assert len(lanes) == 2                   # one lane per process


def test_assembly_dedupes_shared_ring_and_filters_request():
    spans = [
        {"name": "route.request", "ts": 10.0, "dur": 1.0, "seq": 1,
         "trace_id": "tA", "request_id": "rA"},
        {"name": "request", "ts": 10.2, "dur": 0.5, "seq": 2,
         "trace_id": "tA", "attempt": 1, "request_id": "rA"},
        {"name": "request", "ts": 12.0, "dur": 0.5, "seq": 3,
         "trace_id": "tB", "attempt": 1, "request_id": "rB"},
    ]
    # an in-process fleet: three endpoints, ONE process-global ring
    payloads = [_fake_payload(u, 33, u, [dict(s) for s in spans])
                for u in ("router", "rep0", "rep1")]
    doc, summary = fleet.assemble_fleet_trace(payloads, request="rA")
    assert summary["processes"] == 1         # deduped to one lane
    assert summary["trace_ids"] == ["tA"]
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert len(evs) == 2                     # rB filtered out, no dups
    assert {e["args"].get("trace_id") for e in evs} == {"tA"}
    with pytest.raises(ValueError):
        fleet.assemble_fleet_trace(payloads, request="nope")


# -- router spans over fake replicas (no jax) ---------------------------------

def _fake_replica(state=None):
    state = dict({"dead": False, "bodies": []}, **(state or {}))

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.path == "/readyz":
                body = json.dumps({"status": "ok"}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_error(404)

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(n) or b"{}")
            state["bodies"].append(req)
            if state["dead"]:
                self.close_connection = True
                return
            body = json.dumps(
                {"tokens": [1, 2, 3],
                 "request_id": req.get("request_id")}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, state


@pytest.fixture
def fake_fleet():
    a_srv, a = _fake_replica()
    b_srv, b = _fake_replica()
    router = None
    try:
        router = FleetRouter(
            ["127.0.0.1:%d" % a_srv.server_port,
             "127.0.0.1:%d" % b_srv.server_port],
            probe_interval=0.1, probe_timeout=2.0,
            failure_threshold=1, retry_budget=2,
            attempt_timeout=5.0, request_timeout=20.0,
            name="trace_router").start()
        yield router, (a_srv, a), (b_srv, b)
    finally:
        if router is not None:
            router.stop()
        a_srv.shutdown()
        b_srv.shutdown()


def test_router_emits_route_spans_on_failover(fake_fleet):
    router, (a_srv, a), (b_srv, b) = fake_fleet
    # pin a deterministic fast breaker on the dying replica
    policy = RetryPolicy(base_delay=0.2, max_delay=0.4, jitter=False,
                         name="t")
    for rep in router.replicas:
        rep.breaker = CircuitBreaker(failure_threshold=1,
                                     backoff=policy)
    # kill whichever replica the ranking picks FIRST (both idle →
    # URL sort), so attempt 1 deterministically dies and fails over
    first = min(r.url for r in router.replicas)
    dead = a if first.endswith(str(a_srv.server_port)) else b
    live = b if dead is a else a
    dead["dead"] = True
    url = "http://127.0.0.1:%d/generate" % router.port
    cursor = span_recorder.cursor()
    code, body = _post(url, {"prompt": [1], "n_new": 2})
    assert code == 200
    tid = body.get("trace_id")
    assert tid and tid.startswith("trace-")
    # both replicas saw the SAME trace_id with climbing attempts
    routed = dead["bodies"] + live["bodies"]
    assert all(r.get("trace_id") == tid for r in routed)
    assert sorted(r.get("attempt") for r in routed) == [1, 2]
    recs, cursor = _new_spans(cursor, "route.")
    by_name = {}
    for r in recs:
        by_name.setdefault(r["name"], []).append(r)
    root_span = by_name["route.request"][-1]
    assert root_span["trace_id"] == tid
    assert root_span["outcome"] == "answered"
    assert root_span["status"] == 200
    assert root_span["attempts"] == 2
    attempts = [r for r in by_name["route.attempt"]
                if r["trace_id"] == tid]
    assert len(attempts) == 2
    assert attempts[0]["outcome"] == "failed"
    assert attempts[1]["outcome"] == "answered"
    assert attempts[1]["status"] == 200
    # the failure opened the breaker -> the scheduled hold is a span
    backoffs = [r for r in by_name.get("route.backoff", ())
                if r["trace_id"] == tid]
    assert backoffs and backoffs[0]["dur"] > 0
    # half-open after the hold: the next attempt doubles as the probe
    time.sleep(0.45)
    code, _ = _post(url, {"prompt": [1], "n_new": 2})
    assert code == 200
    probes, cursor = _new_spans(cursor, "route.probe")
    assert probes and probes[0]["outcome"] == "failed"
    # route.request spans parent the whole timeline on one clock
    assert root_span["ts"] <= attempts[0]["ts"]
    assert root_span["ts"] + root_span["dur"] \
        >= attempts[1]["ts"] + attempts[1]["dur"] - 1e-3


def test_router_spans_gated_by_trace_requests(fake_fleet):
    router, (a_srv, a), (b_srv, b) = fake_fleet
    url = "http://127.0.0.1:%d/generate" % router.port
    prev = root.common.trace.get("requests", True)
    cursor = span_recorder.cursor()
    try:
        root.common.trace.requests = False
        code, body = _post(url, {"prompt": [1], "n_new": 2})
        assert code == 200
        assert body.get("trace_id")       # propagation stays on
    finally:
        root.common.trace.requests = prev
    leaked, _ = _new_spans(cursor, "route.", "request")
    assert leaked == []


def test_route_replay_span_covers_journal_tail(tmp_path, fake_fleet):
    router, (a_srv, a), (b_srv, b) = fake_fleet
    router.stop()
    from veles_tpu.serving.journal import RequestJournal
    jdir = str(tmp_path / "journal")
    j = RequestJournal(jdir, fsync=False)
    j.admit("req-replay-1", {"prompt": [1], "n_new": 2,
                             "trace_id": "trace-rp-1"},
            time.time(), trace_id="trace-rp-1")
    j.close()
    cursor = span_recorder.cursor()
    router2 = FleetRouter(
        ["127.0.0.1:%d" % a_srv.server_port],
        probe_interval=0.1, failure_threshold=2, retry_budget=1,
        attempt_timeout=5.0, request_timeout=20.0,
        journal_dir=jdir, journal_fsync=False,
        name="replay_router").start()
    try:
        deadline = time.time() + 15
        while j.__class__(jdir, fsync=False).pending_count() \
                and time.time() < deadline:
            time.sleep(0.05)
        replays, _ = _new_spans(cursor, "route.replay")
        assert replays and replays[-1]["replayed"] == 1
        # the replayed request routed under its ORIGINAL trace_id
        routed, _ = _new_spans(cursor, "route.request")
        mine = [r for r in routed
                if r.get("trace_id") == "trace-rp-1"]
        assert mine and mine[0]["request_id"] == "req-replay-1"
    finally:
        router2.stop()


# -- blackbox --request filtering ---------------------------------------------

def test_blackbox_inspect_filters_by_request(tmp_path):
    from veles_tpu.telemetry.recorder import flight, inspect
    flight.note("request", request_id="req-bb-1",
                trace_id="trace-bb-1", attempt=1, phase="admitted")
    flight.note("request", request_id="req-bb-1",
                trace_id="trace-bb-1", attempt=1, phase="done",
                outcome="shed")
    flight.note("request", request_id="req-bb-2",
                trace_id="trace-bb-2", attempt=1, phase="done")
    path = str(tmp_path / "bb.jsonl")
    flight.dump("test", path=path)
    full = inspect(path)
    one = inspect(path, request="trace-bb-1")
    assert one["events"] == 2
    assert one["events_total"] == full["events"]
    assert one["request"] == "trace-bb-1"
    # by request_id too
    assert inspect(path, request="req-bb-2")["events"] == 1
    from veles_tpu.__main__ import main
    assert main(["blackbox", "inspect", path,
                 "--request", "trace-bb-1"]) == 0


# -- check_counters --docs pass ------------------------------------------------

def test_check_counters_docs_pass_green_and_fails_on_drift(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import check_counters
    finally:
        sys.path.pop(0)
    # the shipped docs cover every registered name (tier-1 hook for
    # the --docs pass, like test_tensormon hooks the base pass)
    assert check_counters.main(["--docs"]) == 0
    # and the pass actually bites: a doc missing one registered name
    with open(check_counters.DOCS_MD, errors="replace") as fin:
        text = fin.read()
    crippled = tmp_path / "observability.md"
    crippled.write_text(
        text.replace("veles_trace_fleet_merges_total", "veles_gone"))
    missing = check_counters.find_undocumented(str(crippled))
    assert ("veles_trace_fleet_merges_total", "counter") in missing
    # brace families in prose count as documented
    docs = check_counters.documented_names()
    assert "veles_journal_appends_total" in docs


# -- THE acceptance drill: one merged trace across a replica death ------------

@pytest.fixture(scope="module")
def lm_wf():
    lm = import_model("char_lm")
    from veles_tpu import prng
    prng.seed_all(2026)
    wf = lm.build_workflow(epochs=1, minibatch_size=32, n_blocks=1,
                           dim=32, n_train=64, n_valid=32)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    return lm, wf


def test_fleet_trace_cli_one_merged_trace_across_death(
        lm_wf, tmp_path, monkeypatch):
    """The acceptance criterion end to end: journaled 2-replica
    fleet, injected mid-decode replica death, `veles-tpu trace fleet
    --request ID` → ONE Chrome trace with the router's
    route.request/route.attempt spans and both replicas' request
    spans under the same trace_id, the resume attempt's tokens_done
    visible — and decode dispatch counts bit-identical tracing
    on/off through the router path."""
    from veles_tpu.nn import sampling
    lm, wf = lm_wf
    prompt = [1, 5, 3, 2, 4]
    n_new = 12
    solo = sampling.generate(wf, prompt, n_new, temperature=0)
    apis = [vt.GenerationAPI(wf, port=0, engine="continuous",
                             max_slots=2, buckets=(8, 16, 32),
                             max_context=48,
                             name="trace_drill_%d" % i)
            for i in range(2)]
    for api in apis:
        api.initialize()
    jdir = str(tmp_path / "journal")
    router = None
    prev = root.common.trace.get("requests", True)
    try:
        router = FleetRouter(
            ["127.0.0.1:%d" % api.port for api in apis],
            probe_interval=0.2, failure_threshold=1, retry_budget=2,
            attempt_timeout=60.0, request_timeout=120.0,
            journal_dir=jdir, journal_fsync=False,
            name="drill_router").start()
        url = "http://127.0.0.1:%d/generate" % router.port
        # warm both replicas' programs outside any measured window
        for api in apis:
            code, _b = _post(
                "http://127.0.0.1:%d/generate" % api.port,
                {"prompt": prompt, "n_new": 4})
            assert code == 200

        # -- dispatch lock, extended to the router path --------------
        keys = ("veles_serving_decode_dispatches_total",
                "veles_serving_prefill_dispatches_total",
                "veles_decode_dispatches_total")

        def load():
            out = []
            for _ in range(3):
                code, body = _post(url, {"prompt": prompt,
                                         "n_new": 4})
                out.append((code, tuple(body.get("tokens") or ())))
            return out

        def measured():
            before = {k: counters.get(k) for k in keys}
            out = load()
            return out, {k: counters.get(k) - before[k]
                         for k in keys}

        root.common.trace.requests = True
        out_on, d_on = measured()
        cursor = span_recorder.cursor()
        root.common.trace.requests = False
        out_off, d_off = measured()
        leaked, _ = _new_spans(cursor, "request", "route.")
        root.common.trace.requests = True
        assert out_on == out_off
        assert d_on == d_off, (d_on, d_off)
        assert leaked == []

        # -- the death drill ----------------------------------------
        monkeypatch.setenv(
            "VELES_FAULTS",
            "serve.replica_death:raise:after=4,times=1")
        code, body = _post(url, {"prompt": prompt, "n_new": n_new})
        monkeypatch.delenv("VELES_FAULTS")
        assert code == 200, body
        assert body["tokens"] == solo          # id-exact across death
        assert int(body.get("resumed_from", 0)) >= 1
        tid = body["trace_id"]
        rid = body["request_id"]

        # roster file (the saved GET /roster page) + router URL =
        # the documented trace fleet invocation
        roster = tmp_path / "roster.json"
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/roster" % router.port,
                timeout=10) as r:
            roster.write_text(r.read().decode())
        out_path = str(tmp_path / "fleet_trace.json")
        from veles_tpu.__main__ import main
        rc = main(["trace", "fleet",
                   "127.0.0.1:%d" % router.port,
                   "--endpoints-file", str(roster),
                   "--request", rid,          # request_id resolves
                   "--out", out_path])        # to its trace_id
        assert rc == 0
        with open(out_path) as fin:
            doc = json.load(fin)
        from veles_tpu.telemetry import chrome_trace
        assert chrome_trace.validate(doc) == []
        evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        names = [e["name"] for e in evs]
        assert "route.request" in names
        attempts = [e for e in evs if e["name"] == "route.attempt"]
        assert len(attempts) >= 2              # death + failover
        # the resume attempt's tokens_done is visible in the trace
        assert any(int(e["args"].get("tokens_done", 0)) >= 1
                   for e in attempts)
        req_spans = [e for e in evs if e["name"] == "request"]
        span_attempts = {int(e["args"].get("attempt", 0))
                         for e in req_spans}
        assert {1, 2} <= span_attempts         # BOTH replicas' spans
        # every event belongs to the one trace
        tagged = [e for e in evs if "trace_id" in e["args"]]
        assert tagged
        assert {e["args"]["trace_id"] for e in tagged} == {tid}
        # the journal's records carry the same trace
        admits, terminals = router.journal.replay()
        assert admits[rid]["trace_id"] == tid
        assert terminals[rid]["trace_id"] == tid
        assert terminals[rid]["attempts"] >= 2
        assert router.journal.pending() == []
    finally:
        root.common.trace.requests = prev
        if router is not None:
            router.stop()
        for api in apis:
            api.stop()
