"""Distributed linear-algebra family (veles_tpu/linalg/) — tier-1.

The family's contract, each clause locked here:

- **blocked == dense**: the block-cyclic SUMMA matmul, the
  right-looking blocked Cholesky and the blocked triangular solves
  match ``numpy.linalg`` within the stated 100·eps tolerance — on
  deliberately awkward shapes (odd sizes, blocks that do not divide
  the dims) and on the 8-virtual-device mesh path as well as the
  single-device path.
- **solvers converge and verify**: CG on the SPD Poisson model problem
  reaches < 1e-5; the multigrid-preconditioned run needs fewer
  iterations; a finish claiming convergence is re-verified through the
  trusted dense path, so a corrupt block op can NEVER yield a
  silently-wrong answer (chaos-tested via ``linalg.block_op``).
- **telemetry + gate plumbing**: every veles_linalg_* counter is
  registered, ``bench.py``'s linalg section reads them absolutely, and
  ``gate_linalg`` fails leakage, tolerates pre-family legacy documents
  (counted, never crashing) and exempts ``linalg_bench`` documents.
- **dtype-correct peaks**: f32 work is graded against the f32 peak
  table (half the bf16 entry), and the stamped source label says so.
"""
import json
import os
import sys

import numpy
import pytest

from conftest import import_model
from veles_tpu.linalg import (LINALG_COUNTERS, LinalgError,
                              TwoLevelPoisson, blocked_cholesky,
                              blocked_matmul, blocked_triangular_solve,
                              build_cg_workflow, cholesky_solve,
                              cyclic_permutation, default_tolerance,
                              linalg_mesh, poisson2d_dense,
                              poisson2d_matvec, predict_summa_time,
                              verify_residual)
from veles_tpu.resilience.faults import FaultInjected
from veles_tpu.telemetry.counters import DESCRIPTIONS, counters

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

F32_TOL = default_tolerance(numpy.float32)


def _import_bench():
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    return bench


def _spd(n, seed=3, dtype=numpy.float32):
    g = numpy.random.RandomState(seed).standard_normal((n, n))
    return (g @ g.T + n * numpy.eye(n)).astype(dtype)


# -- layout helpers ----------------------------------------------------------

def test_cyclic_permutation_roundtrip():
    for n_pad, slabs, p in ((24, 4, 2), (48, 8, 4), (16, 4, 4)):
        perm, inv = cyclic_permutation(n_pad, slabs, p)
        x = numpy.arange(n_pad)
        assert (x[perm][inv] == x).all()
        assert (x[inv][perm] == x).all()


def test_linalg_mesh_squarest_and_explicit():
    mesh = linalg_mesh()
    assert tuple(mesh.devices.shape) == (2, 4)      # 8 virtual devices
    assert mesh.axis_names == ("rows", "cols")
    sub = linalg_mesh((1, 2))
    assert tuple(sub.devices.shape) == (1, 2)


# -- blocked kernels vs the dense reference ----------------------------------

def test_blocked_matmul_matches_dense_single():
    rng = numpy.random.RandomState(0)
    for m, k, n, block in ((37, 23, 41, 8), (16, 16, 16, 64),
                           (5, 7, 3, 4)):
        a = rng.standard_normal((m, k)).astype(numpy.float32)
        b = rng.standard_normal((k, n)).astype(numpy.float32)
        c = numpy.asarray(blocked_matmul(a, b, block=block, mesh=None))
        ref = a.astype(numpy.float64) @ b.astype(numpy.float64)
        rel = numpy.linalg.norm(c - ref) / numpy.linalg.norm(ref)
        assert rel < F32_TOL, (m, k, n, block, rel)


def test_blocked_matmul_matches_dense_on_mesh():
    """SUMMA over the 2x4 mesh == dense == single-device, on shapes
    the G=4 panel padding must handle (nothing divides evenly)."""
    rng = numpy.random.RandomState(1)
    mesh = linalg_mesh()
    a = rng.standard_normal((50, 30)).astype(numpy.float32)
    b = rng.standard_normal((30, 70)).astype(numpy.float32)
    ref = a.astype(numpy.float64) @ b.astype(numpy.float64)
    single = numpy.asarray(blocked_matmul(a, b, block=16, mesh=None))
    for cyclic in (True, False):
        c = numpy.asarray(blocked_matmul(a, b, block=16, mesh=mesh,
                                         cyclic=cyclic))
        rel = numpy.linalg.norm(c - ref) / numpy.linalg.norm(ref)
        assert rel < F32_TOL, (cyclic, rel)
        drift = (numpy.linalg.norm(c - single)
                 / numpy.linalg.norm(single))
        assert drift < F32_TOL, (cyclic, drift)


def test_blocked_cholesky_matches_dense():
    spd = _spd(45)
    ref = numpy.linalg.cholesky(spd.astype(numpy.float64))
    for mesh in (None, linalg_mesh()):
        l = numpy.asarray(blocked_cholesky(spd, block=16, mesh=mesh,
                                           mesh_min=8))
        rel = numpy.linalg.norm(l - ref) / numpy.linalg.norm(ref)
        assert rel < F32_TOL, rel
        assert numpy.allclose(l, numpy.tril(l))


def test_blocked_cholesky_rejects_indefinite():
    bad = numpy.eye(12, dtype=numpy.float32)
    bad[5, 5] = -1.0
    with pytest.raises(LinalgError):
        blocked_cholesky(bad, block=4)


def test_triangular_and_cholesky_solve():
    rng = numpy.random.RandomState(5)
    spd = _spd(33)
    b = rng.standard_normal((33, 2)).astype(numpy.float32)
    l = numpy.asarray(blocked_cholesky(spd, block=8))
    y = numpy.asarray(blocked_triangular_solve(l, b, lower=True,
                                               block=8))
    assert numpy.linalg.norm(l @ y - b) / numpy.linalg.norm(b) < F32_TOL
    x = numpy.asarray(cholesky_solve(spd, b, block=8, check=True))
    ref = numpy.linalg.solve(spd.astype(numpy.float64),
                             b.astype(numpy.float64))
    assert (numpy.linalg.norm(x - ref)
            / numpy.linalg.norm(ref)) < F32_TOL


def test_verify_residual_fails_loud():
    spd = _spd(16)
    b = numpy.ones((16,), dtype=numpy.float32)
    x = numpy.linalg.solve(spd, b)
    before = counters.snapshot()
    verify_residual(spd, x, b)                       # good x passes
    with pytest.raises(LinalgError):
        verify_residual(spd, x + 1.0, b)             # bad x raises
    delta = counters.delta(before)
    assert delta.get("veles_linalg_residual_checks_total") == 2
    assert delta.get("veles_linalg_residual_failures_total") == 1


# -- solvers on the Workflow graph -------------------------------------------

def test_cg_poisson_converges_and_verifies():
    n = 12
    rhs = numpy.random.RandomState(7).standard_normal(
        n * n).astype(numpy.float32)
    before = counters.snapshot()
    wf = build_cg_workflow(poisson2d_matvec(n), rhs, tol=1e-6,
                           max_iters=400)
    wf.initialize()
    wf.run()
    res = wf.cg_decision.get_metric_values()
    assert res["converged"]
    assert res["residual"] < 1e-5
    assert res["true_residual"] is not None
    assert res["true_residual"] < 1e-4
    # per-iteration telemetry: one history entry per step + seed
    assert len(res["residual_history"]) == res["iterations"] + 1
    delta = counters.delta(before)
    assert delta.get("veles_linalg_iterations_total") == \
        res["iterations"]
    assert delta.get("veles_linalg_solves_total") == 1


def test_cg_dense_operator_routes_through_blocked_matmul():
    n = 8
    dense = poisson2d_dense(n)
    rhs = numpy.random.RandomState(8).standard_normal(
        n * n).astype(numpy.float32)
    before = counters.snapshot()
    wf = build_cg_workflow(dense, rhs, tol=1e-6, max_iters=200,
                           mesh=linalg_mesh((1, 2)), block=16)
    wf.initialize()
    wf.run()
    res = wf.cg_decision.get_metric_values()
    assert res["converged"] and res["residual"] < 1e-5
    # the matvec went through the blocked (faultable) path
    assert counters.delta(before).get("veles_linalg_matmuls_total")


def test_pcg_multigrid_beats_plain_cg():
    n = 12
    rhs = numpy.random.RandomState(9).standard_normal(
        n * n).astype(numpy.float32)
    runs = {}
    for label, precond in (("cg", None),
                           ("pcg", TwoLevelPoisson(n, block=16))):
        wf = build_cg_workflow(poisson2d_matvec(n), rhs, tol=1e-6,
                               max_iters=400, preconditioner=precond)
        wf.initialize()
        wf.run()
        runs[label] = wf.cg_decision.get_metric_values()
        assert runs[label]["converged"]
    assert runs["pcg"]["iterations"] < runs["cg"]["iterations"]


def test_cg_rejects_non_spd_operator():
    n = 4
    rhs = numpy.ones(n, dtype=numpy.float32)
    wf = build_cg_workflow(lambda v: -v, rhs, tol=1e-6, max_iters=10)
    wf.initialize()
    with pytest.raises(LinalgError):
        wf.run()


def test_twolevel_poisson_needs_even_n():
    with pytest.raises(LinalgError):
        TwoLevelPoisson(7)


def test_poisson_solver_model():
    mod = import_model("poisson_solver")
    wf = mod.build_workflow(n=8, tol=1e-6, max_iters=200)
    wf.initialize()
    wf.run()
    res = wf.cg_decision.get_metric_values()
    assert res["converged"] and res["residual"] < 1e-5


# -- chaos: linalg.block_op --------------------------------------------------

def test_chaos_corrupt_block_fails_loud_never_silent(monkeypatch):
    """THE satellite lock: a corrupted block op must surface as a
    LinalgError from the residual check — never as a returned
    silently-wrong x."""
    spd = _spd(24)
    b = numpy.ones((24, 1), dtype=numpy.float32)
    monkeypatch.setenv("VELES_FAULTS", "linalg.block_op:corrupt")
    with pytest.raises(LinalgError):
        cholesky_solve(spd, b, block=8, check=True)
    monkeypatch.setenv("VELES_FAULTS", "")


def test_chaos_corrupt_cg_reports_nonconvergence(monkeypatch):
    """Persistent corruption inside the CG matvec: the solve must end
    in an explicit non-answer (converged=False or a raise) — the
    convergence claim is what the trusted re-verification guards."""
    n = 6
    dense = poisson2d_dense(n)
    rhs = numpy.ones(n * n, dtype=numpy.float32)
    monkeypatch.setenv("VELES_FAULTS", "linalg.block_op:corrupt")
    wf = build_cg_workflow(dense, rhs, tol=1e-8, max_iters=25,
                           block=8)
    wf.initialize()
    try:
        wf.run()
        res = wf.cg_decision.get_metric_values()
        assert not res["converged"] or res["true_residual"] < 1e-6
    except LinalgError:
        pass                     # loud failure is equally acceptable
    finally:
        monkeypatch.setenv("VELES_FAULTS", "")


def test_chaos_raise_propagates(monkeypatch):
    rng = numpy.random.RandomState(2)
    a = rng.standard_normal((8, 8)).astype(numpy.float32)
    monkeypatch.setenv("VELES_FAULTS", "linalg.block_op:raise:times=1")
    with pytest.raises(FaultInjected):
        blocked_matmul(a, a, block=8)
    monkeypatch.setenv("VELES_FAULTS", "")
    numpy.asarray(blocked_matmul(a, a, block=8))     # healed


# -- telemetry + gate plumbing -----------------------------------------------

def test_linalg_counters_registered():
    for name in LINALG_COUNTERS:
        assert name in DESCRIPTIONS, name
    before = counters.snapshot()
    a = numpy.eye(4, dtype=numpy.float32)
    numpy.asarray(blocked_matmul(a, a, block=4))
    delta = counters.delta(before)
    assert delta.get("veles_linalg_matmuls_total") == 1
    assert delta.get("veles_linalg_block_ops_total")


def test_bench_linalg_section_shape():
    bench = _import_bench()
    sec = bench._linalg_section()
    assert sec["linalg_bench"] is False
    short = [n[len("veles_linalg_"):-len("_total")]
             for n in LINALG_COUNTERS]
    for key in short:
        assert isinstance(sec[key], int)


def test_gate_linalg_doc_arithmetic(monkeypatch):
    """Doc arithmetic in isolation (the live proof is stubbed out):
    leakage fails, linalg_bench documents are exempt, and legacy
    documents lacking the section entirely are counted on
    veles_bench_legacy_sections_total — never a crash (PR 8 rule)."""
    bench = _import_bench()
    monkeypatch.setattr(bench, "_linalg_proof", lambda: ([], {}))
    clean = {"linalg": {"linalg_bench": False, "matmuls": 0,
                        "solves": 0}}
    assert bench.gate_linalg(clean, clean) == []
    leaked = {"linalg": {"linalg_bench": False, "matmuls": 3,
                         "solves": 0}}
    failures = bench.gate_linalg(clean, leaked)
    assert failures and "leaked" in failures[0]
    marked = {"linalg": {"linalg_bench": True, "matmuls": 3}}
    assert bench.gate_linalg(clean, marked) == []
    # pre-family legacy document: tolerated + counted, no crash
    legacy = {"value": 1.0, "extras": []}
    before = counters.snapshot()
    assert bench.gate_linalg(legacy, clean) == []
    assert counters.delta(before).get(
        "veles_bench_legacy_sections_total") == 1


# -- dtype-correct peak table ------------------------------------------------

def test_peak_flops_f32_is_half_bf16():
    from veles_tpu.telemetry.cost import (DEFAULT_PEAK,
                                          DEFAULT_PEAK_F32, PEAK_BF16,
                                          PEAK_F32, peak_flops_entry)
    assert DEFAULT_PEAK_F32 == DEFAULT_PEAK / 2
    bf16 = dict(PEAK_BF16)
    for kind, peak in PEAK_F32:
        assert peak == bf16[kind] / 2, kind
    src32, p32 = peak_flops_entry("float32")
    srcbf, pbf = peak_flops_entry("bfloat16")
    assert "PEAK_F32" in src32 and "F32" not in srcbf
    assert p32 == pbf / 2
    # device-kind substring match routes to the named entry
    src, p = peak_flops_entry(numpy.float32, device_kind="TPU v4")
    assert src == "telemetry.cost.PEAK_F32[v4]" and p == 137.5e12
    # f64 has no separate table: graded against the f32 ceiling
    assert peak_flops_entry("float64")[1] == p32


def test_predict_summa_time_states_every_input():
    pred = predict_summa_time(384, 384, 384, (2, 4), t1_step_s=1.0)
    inputs = pred["inputs"]
    for field in ("t1_step_s", "grid", "panels",
                  "block_bytes_a_panel", "block_bytes_b_panel",
                  "psum_bytes_per_device",
                  "ici_bw_assumed_bytes_per_s", "ici_bw_source"):
        assert field in inputs, field
    assert pred["predicted_step_s"] == pytest.approx(
        pred["compute_s"] + pred["comm_s"])
    assert pred["comm_s"] > 0 and inputs["psum_bytes_per_device"] > 0
    # a 1x1 grid broadcasts nothing
    solo = predict_summa_time(384, 384, 384, (1, 1), t1_step_s=1.0)
    assert solo["comm_s"] == 0
    assert solo["predicted_step_s"] == pytest.approx(1.0)


def test_scaling_json_carries_linalg_row():
    with open(os.path.join(REPO, "SCALING.json")) as fin:
        doc = json.load(fin)
    block = doc["linalg"]
    assert "formula" in block and "per_width" in block
    assert block["inputs"]["ici_bw_assumed_bytes_per_s"] > 0
    for row in block["per_width"]:
        assert row["matches_dense"]
        assert row["predicted_step_s"] > 0
        assert row["psum_bytes_per_device"] >= 0
