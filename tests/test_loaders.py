"""Loader family (SURVEY.md §2.3): file scanning, images+augmentation,
pickles, HDF5, minibatch record/replay, streaming (interactive/zmq),
downloader, ensemble outputs loader."""
import gzip
import json
import os
import pickle
import tarfile
import threading

import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn
from veles_tpu.downloader import Downloader
from veles_tpu.error import VelesError
from veles_tpu.loader import (FileFilter, FileListScanner, auto_label,
                              ImageLoader, PicklesLoader, HDF5Loader,
                              MinibatchesSaver, MinibatchesLoader,
                              InteractiveLoader, ZeroMQLoader,
                              EnsembleLoader, TEST, VALID, TRAIN)
from veles_tpu.loader.image import decode_image, augment


# -- file scanning -----------------------------------------------------------

def _make_tree(tmp_path, spec):
    for rel, content in spec.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_bytes(content)
    return tmp_path


def test_file_filter_and_scanner(tmp_path):
    _make_tree(tmp_path, {
        "train/cat/a.png": b"x", "train/cat/b.jpg": b"x",
        "train/dog/c.png": b"x", "train/dog/skip.txt": b"x",
        "valid/cat/d.png": b"x"})
    f = FileFilter(include=("*.png", "*.jpg"), exclude=("b.*",))
    files = f.scan(str(tmp_path / "train"))
    names = [os.path.basename(p) for p in files]
    assert names == ["a.png", "c.png"]
    scanner = FileListScanner([str(tmp_path / "train")],
                              [str(tmp_path / "valid")],
                              file_filter=FileFilter(include=("*.png",)))
    test_f, valid_f, train_f = scanner.scan()
    assert len(train_f) == 2 and len(valid_f) == 1 and not test_f
    assert auto_label(train_f[0]) == "cat"
    with pytest.raises(VelesError):
        FileListScanner(["/nonexistent/xyz"]).scan()


# -- images ------------------------------------------------------------------

def _write_png(path, color, size=(12, 10)):
    from PIL import Image
    os.makedirs(os.path.dirname(path), exist_ok=True)
    Image.new("RGB", size, color).save(path)


def test_decode_and_augment(tmp_path):
    p = str(tmp_path / "img.png")
    _write_png(p, (255, 0, 0), size=(10, 8))
    arr = decode_image(p)                    # HWC in [0,1]
    assert arr.shape == (8, 10, 3)
    assert arr[..., 0].max() == 1.0 and arr[..., 1].max() == 0.0
    arr = decode_image(p, size=(4, 6))
    assert arr.shape == (4, 6, 3)
    gray = decode_image(p, color="L")
    assert gray.shape == (8, 10, 1)
    variants = augment(arr, mirror=True, rotations=(0, 180))
    assert len(variants) == 4
    crops = augment(arr, crop=(2, 2), crop_number=3,
                    rand=numpy.random.RandomState(0))
    assert len(crops) == 3 and crops[0].shape == (2, 2, 3)


def test_image_loader_end_to_end(tmp_path):
    for i in range(4):
        _write_png(str(tmp_path / ("train/red/r%d.png" % i)), (255, 0, 0))
        _write_png(str(tmp_path / ("train/blue/b%d.png" % i)), (0, 0, 255))
    _write_png(str(tmp_path / "valid/red/v0.png"), (255, 0, 0))
    _write_png(str(tmp_path / "valid/blue/v1.png"), (0, 0, 255))
    loader = ImageLoader(
        None, train_paths=[str(tmp_path / "train")],
        validation_paths=[str(tmp_path / "valid")],
        size=(8, 8), mirror=True, minibatch_size=4, name="imgs")
    loader.initialize(device=None)
    # 8 train images ×2 (mirror) = 16 train samples, 2 validation
    assert loader.class_lengths == [0, 2, 16]
    assert loader.labels_mapping == {"blue": 0, "red": 1}
    assert loader.original_data.shape == (18, 8, 8, 3)
    # labels match pixel content: red channel high ⇒ label "red"
    data, labels = loader.original_data.mem, loader.original_labels.mem
    for row, lab in zip(data, labels):
        assert lab == (1 if row[..., 0].mean() > 0.5 else 0)


def test_image_loader_shape_mismatch(tmp_path):
    _write_png(str(tmp_path / "train/a/x.png"), (1, 2, 3), size=(5, 5))
    _write_png(str(tmp_path / "train/b/y.png"), (1, 2, 3), size=(7, 7))
    loader = ImageLoader(None, train_paths=[str(tmp_path / "train")],
                         name="bad")
    with pytest.raises(VelesError, match="differing shapes"):
        loader.initialize(device=None)


# -- pickles / hdf5 ----------------------------------------------------------

def _blob(n, d=4, seed=0):
    rng = numpy.random.RandomState(seed)
    return (rng.randn(n, d).astype(numpy.float32),
            rng.randint(0, 3, n).astype(numpy.int32))


def test_pickles_loader(tmp_path):
    tr, trl = _blob(30)
    va, val = _blob(10, seed=1)
    ptr, pva = str(tmp_path / "tr.pickle"), str(tmp_path / "va.pickle")
    pickle.dump((tr, trl), open(ptr, "wb"))
    pickle.dump({"data": va, "labels": val}, open(pva, "wb"))
    loader = PicklesLoader(None, files=(None, pva, ptr),
                           minibatch_size=10, name="pk")
    loader.initialize(device=None)
    assert loader.class_lengths == [0, 10, 30]
    numpy.testing.assert_allclose(loader.original_data.mem[:10], va,
                                  rtol=1e-6)
    assert (loader.original_labels.mem[10:] == trl).all()


def test_hdf5_loader(tmp_path):
    import h5py
    tr, trl = _blob(20)
    path = str(tmp_path / "d.h5")
    with h5py.File(path, "w") as f:
        f["data"] = tr
        f["labels"] = trl
    loader = HDF5Loader(None, files=(None, None, path),
                        validation_ratio=0.25, minibatch_size=5, name="h5")
    loader.initialize(device=None)
    assert loader.class_lengths == [0, 5, 15]
    assert loader.total_samples == 20


# -- minibatch record / replay ----------------------------------------------

class _TinyLoader(vt.loader.FullBatchLoader):
    hide_from_registry = True

    def load_data(self):
        data, labels = _blob(24, seed=2)
        self.create_originals(data, labels)
        self.class_lengths = [0, 8, 16]


def test_minibatches_saver_roundtrip(tmp_path):
    fname = str(tmp_path / "mb.vtmb")
    loader = _TinyLoader(None, minibatch_size=8, name="src")
    saver = MinibatchesSaver(None, file_name=fname, name="saver")
    saver.loader = loader
    loader.initialize(device=None)
    saver.initialize(device=None)
    served = []
    for _ in range(3):   # one epoch: 1 valid + 2 train minibatches
        loader.run()
        saver.run()
        served.append(numpy.array(loader.minibatch_data.mem))
    saver.stop()
    replay = MinibatchesLoader(None, file_name=fname, minibatch_size=8,
                               name="replay")
    replay.initialize(device=None)
    assert replay.class_lengths == [0, 8, 16]
    # recorded sample set equals the source dataset (order may differ)
    src = numpy.sort(numpy.concatenate(served), axis=0)
    rec = numpy.sort(replay.original_data.mem, axis=0)
    numpy.testing.assert_allclose(src, rec, rtol=1e-6)


def test_minibatches_saver_fused_loader(tmp_path):
    """The default training path (fused TrainStep) never fills
    minibatch_data on host — the saver must gather from the originals."""
    fname = str(tmp_path / "fused.vtmb")
    loader = _TinyLoader(None, minibatch_size=8, name="fsrc")
    saver = MinibatchesSaver(None, file_name=fname, name="fsaver")
    saver.loader = loader
    loader.fused = True
    loader.initialize(device=None)
    saver.initialize(device=None)
    for _ in range(3):
        loader.run()
        saver.run()
    saver.stop()
    replay = MinibatchesLoader(None, file_name=fname, minibatch_size=8,
                               name="freplay")
    replay.initialize(device=None)
    assert replay.total_samples == 24
    assert numpy.abs(replay.original_data.mem).sum() > 0   # not zeros
    src = numpy.sort(loader.original_data.mem, axis=0)
    rec = numpy.sort(replay.original_data.mem, axis=0)
    numpy.testing.assert_allclose(src, rec, rtol=1e-6)


def test_hdf5_inconsistent_labels_rejected(tmp_path):
    import h5py
    tr, trl = _blob(20)
    va, _ = _blob(6, seed=1)
    p_tr, p_va = str(tmp_path / "tr.h5"), str(tmp_path / "va.h5")
    with h5py.File(p_tr, "w") as f:
        f["data"], f["labels"] = tr, trl
    with h5py.File(p_va, "w") as f:
        f["data"] = va          # no labels
    loader = HDF5Loader(None, files=(None, p_va, p_tr), name="badh5")
    with pytest.raises(VelesError, match="inconsistent"):
        loader.initialize(device=None)


def test_validation_carve_is_class_balanced():
    """resize_validation must not slice a class-sorted head (would yield a
    single-class validation set)."""

    class SortedLoader(vt.loader.FullBatchLoader):
        hide_from_registry = True

        def load_data(self):
            # class-sorted: first 50 rows label 0, next 50 label 1
            data = numpy.arange(100, dtype=numpy.float32)[:, None]
            labels = numpy.repeat([0, 1], 50).astype(numpy.int32)
            self.create_originals(data, labels)
            self.class_lengths = [0, 0, 100]
            self.resize_validation(0.3)

    loader = SortedLoader(None, minibatch_size=10, name="sorted")
    loader.initialize(device=None)
    assert loader.class_lengths == [0, 30, 70]
    valid_labels = loader.original_labels.mem[:30]
    assert 0 < valid_labels.mean() < 1   # both classes present


# -- streaming ---------------------------------------------------------------

def test_interactive_loader_feed_and_close():
    wf = vt.Workflow(name="stream-wf")
    loader = InteractiveLoader(wf, sample_shape=(4,), timeout=5.0,
                               name="inter")
    loader.initialize(device=None)
    loader.feed(numpy.ones(4), label=2, ticket="t1")
    loader.run()
    assert loader.minibatch_size == 1
    assert loader.minibatch_class == TEST
    assert loader.current_tickets == ["t1"]
    assert (loader.minibatch_data.mem[0] == 1).all()
    assert loader.minibatch_labels.mem[0] == 2
    loader.close()
    loader.run()
    assert bool(wf.stopped)
    with pytest.raises(VelesError):
        loader.feed(numpy.zeros(4))


def test_zeromq_loader_roundtrip():
    import zmq
    wf = vt.Workflow(name="zmq-wf")
    loader = ZeroMQLoader(wf, sample_shape=(3,), timeout=10.0, name="zl")
    loader.initialize(device=None)
    ctx = zmq.Context.instance()
    sock = ctx.socket(zmq.DEALER)
    sock.connect(loader.bound_endpoint)
    sock.send(pickle.dumps((numpy.arange(3.0), 1)))
    assert sock.recv() == b"ok"
    loader.run()
    assert loader.minibatch_size == 1
    numpy.testing.assert_allclose(loader.minibatch_data.mem[0],
                                  [0, 1, 2])
    sock.send(b"")           # close the stream
    assert sock.recv() == b"bye"
    loader.run()
    assert bool(wf.stopped)
    sock.close(0)


# -- downloader --------------------------------------------------------------

def test_downloader_unpack_and_idempotence(tmp_path):
    src = tmp_path / "src"
    src.mkdir()
    (src / "data.npy").write_bytes(b"hello")
    tar_path = tmp_path / "bundle.tar.gz"
    with tarfile.open(tar_path, "w:gz") as t:
        t.add(src / "data.npy", arcname="data.npy")
    dest = tmp_path / "dest"
    d = Downloader(None, url="file://" + str(tar_path),
                   directory=str(dest), files=["data.npy"], name="dl")
    d.initialize(device=None)
    assert (dest / "data.npy").read_bytes() == b"hello"
    # second run: nothing re-downloaded (delete archive to prove skip)
    (dest / "bundle.tar.gz").unlink()
    d2 = Downloader(None, url="file://" + str(tar_path),
                    directory=str(dest), files=["data.npy"], name="dl2")
    d2.initialize(device=None)
    assert not (dest / "bundle.tar.gz").exists()
    miss = Downloader(None, directory=str(dest), files=["nope.npy"],
                      name="dl3")
    with pytest.raises(VelesError):
        miss.initialize(device=None)


# -- ensemble outputs loader -------------------------------------------------

def test_ensemble_loader_stacks_member_outputs(tmp_path):
    n, k = 12, 3
    rng = numpy.random.RandomState(0)
    outputs = []
    for i in range(2):
        p = str(tmp_path / ("m%d.npy" % i))
        numpy.save(p, rng.rand(n, k).astype(numpy.float32))
        outputs.append(p)
    labels_path = str(tmp_path / "labels.npy")
    numpy.save(labels_path, rng.randint(0, k, n).astype(numpy.int32))
    man = str(tmp_path / "outputs.json")
    json.dump({"outputs": outputs, "labels": labels_path}, open(man, "w"))
    loader = EnsembleLoader(None, manifest=man, minibatch_size=4,
                            name="ens")
    loader.initialize(device=None)
    assert loader.original_data.shape == (n, 2 * k)
    assert loader.class_lengths == [0, 0, n]


def test_label_diversity_check():
    """χ² homogeneity of validation vs train labels (reference:
    veles/loader/base.py:1007)."""
    from veles_tpu.loader import FullBatchLoader

    class Balanced(FullBatchLoader):
        hide_from_registry = True

        def load_data(self):
            rng = numpy.random.RandomState(0)
            labels = rng.randint(0, 4, 400).astype(numpy.int32)
            self.create_originals(
                rng.rand(400, 3).astype(numpy.float32), labels)
            self.class_lengths = [0, 100, 300]

    loader = Balanced(None, minibatch_size=10)
    loader.initialize()
    p = loader.check_label_diversity()
    assert p is not None and p > 0.01

    class Skewed(Balanced):
        def load_data(self):
            rng = numpy.random.RandomState(0)
            labels = numpy.concatenate([
                numpy.zeros(100, numpy.int32),           # valid: class 0
                rng.randint(0, 4, 300).astype(numpy.int32)])
            self.create_originals(
                rng.rand(400, 3).astype(numpy.float32), labels)
            self.class_lengths = [0, 100, 300]
            self.shuffle_limit = 0

    sk = Skewed(None, minibatch_size=10)
    sk.load_data()
    assert sk.check_label_diversity() < 0.01


def test_label_check_runs_before_train_ratio_subset():
    """The χ² check must see the full train block, not the post-subset
    head (which could be class-ordered and falsely skewed)."""
    from veles_tpu.loader import FullBatchLoader

    class Ordered(FullBatchLoader):
        hide_from_registry = True

        def load_data(self):
            rng = numpy.random.RandomState(0)
            valid = rng.randint(0, 2, 100).astype(numpy.int32)
            train = numpy.sort(rng.randint(0, 2, 300)).astype(numpy.int32)
            labels = numpy.concatenate([valid, train])
            self.create_originals(
                rng.rand(400, 3).astype(numpy.float32), labels)
            self.class_lengths = [0, 100, 300]

    seen = {}
    loader = Ordered(None, minibatch_size=10)
    loader.train_ratio = 0.25
    real = loader.check_label_diversity

    def spy():
        seen["train_len"] = loader.class_lengths[2]
        return real()

    loader.check_label_diversity = spy
    loader.initialize()
    # called with the FULL train block (300), not the 75-sample subset
    assert seen["train_len"] == 300
    assert loader.class_lengths[2] == 75


def test_lmdb_record_codec():
    """LMDB records are data-only npy+label bytes decodable with
    allow_pickle=False (the untrusted-database posture; pickle_records=True
    is the documented legacy opt-in)."""
    from veles_tpu.loader.kv_store import encode_record, decode_record
    sample = numpy.random.RandomState(7).rand(4, 3).astype(numpy.float32)
    rec = encode_record(sample, -12)
    out, label = decode_record(rec)
    assert label == -12
    numpy.testing.assert_array_equal(out, sample)


def test_text_file_loader_char_lm(tmp_path):
    """TextFileLoader: real text file → char-id windows with shifted
    targets; a char-LM stack trains on it unchanged and beats the
    uniform-vocab entropy (the text is highly repetitive)."""
    import jax
    from veles_tpu.loader import TextFileLoader
    from veles_tpu import nn, prng
    text = ("the quick brown fox jumps over the lazy dog. " * 120)
    p = tmp_path / "corpus.txt"
    p.write_text(text)
    prng.seed_all(11)
    loader = TextFileLoader(None, files=[str(p)], seq_len=32,
                            minibatch_size=16, name="text")
    wf = nn.StandardWorkflow(
        name="text-lm",
        layers=[{"type": "embedding", "vocab_size": 64, "dim": 24,
                 "solver": "adam", "learning_rate": 0.01},
                {"type": "transformer_block", "n_heads": 4,
                 "ffn_hidden": 48, "causal": True, "rope": True,
                 "solver": "adam", "learning_rate": 0.01},
                {"type": "lm_head", "vocab_size": 64,
                 "solver": "adam", "learning_rate": 0.01}],
        loader_unit=loader, loss_function="softmax_seq",
        decision_config=dict(max_epochs=4, fail_iterations=50))
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    assert loader.vocab_size < 32       # a-z + punctuation + space
    # round-trip encode/decode
    assert loader.decode(loader.encode("the fox")) == "the fox"
    wf.run()
    res = wf.gather_results()
    # per-token error: the corpus is a repeated sentence — a working
    # LM path gets far below the ~0.96 uniform-chance error
    assert res["best_err"] < 0.35, res
    # validation windows came from the tail, train from the head
    assert loader.class_lengths[1] > 0


def test_text_file_loader_guards(tmp_path):
    from veles_tpu.error import VelesError
    from veles_tpu.loader import TextFileLoader
    with pytest.raises(VelesError):
        TextFileLoader(None, files=[], minibatch_size=4)
    p = tmp_path / "tiny.txt"
    p.write_text("abc")
    loader = TextFileLoader(None, files=[str(p)], seq_len=128,
                            minibatch_size=4, name="tiny")
    with pytest.raises(VelesError):
        loader.load_data()
    with pytest.raises(VelesError):
        TextFileLoader(None, files=[str(tmp_path / "missing.txt")],
                       minibatch_size=4, name="m").load_data()


def test_text_loader_window_accounting(tmp_path):
    """Exactly the right windows: the last valid start is included, and
    an overlapping-stride split drops the straddling windows so train
    and validation never share text."""
    from veles_tpu.loader import TextFileLoader
    p = tmp_path / "t.txt"
    p.write_text("abcdefghi")            # 9 chars
    ld = TextFileLoader(None, files=[str(p)], seq_len=4, stride=4,
                        validation_ratio=0.0, minibatch_size=2,
                        name="w")
    ld.load_data()
    assert ld.class_lengths == [0, 0, 2]   # starts 0 AND 4 both served

    # oversampling mode: distinct chars let us read window offsets
    # back out of the ids and assert the no-shared-text invariant
    p2 = tmp_path / "t2.txt"
    alphabet = "".join(chr(33 + (i % 90)) for i in range(400))
    p2.write_text(alphabet)
    ld2 = TextFileLoader(None, files=[str(p2)], seq_len=32, stride=8,
                         validation_ratio=0.2, minibatch_size=4,
                         name="w2")
    ld2.load_data()
    n_train, n_valid = ld2.class_lengths[2], ld2.class_lengths[1]
    assert n_valid > 0
    seq = ld2.original_data.mem
    starts = numpy.arange(0, 400 - 32, 8)
    # rows are [valid (corpus tail) | train]; recover each row's corpus
    # offset via its first char id (vocab is sorted, corpus cycles with
    # period 90 > nothing here exceeds 400 distinct positions? period
    # 90 repeats — instead recover offsets from the window id pattern)
    # train windows are starts[:n_train]; the first valid window must
    # begin AFTER the last train window's final (target) character:
    last_train_end = starts[n_train - 1] + 32 + 1     # exclusive
    first_valid_start = int(
        starts[n_train:][len(starts) - n_train - n_valid])
    assert first_valid_start >= last_train_end, (
        first_valid_start, last_train_end)


def test_text_loader_oov_maps_to_reserved_unk(tmp_path, caplog):
    """ADVICE r2: with a user-restricted vocab, OOV characters must NOT
    alias onto id 0 (a real character) — they get the reserved unk id
    (one past the vocab), decode renders them distinctly, and load
    warns with a count."""
    import logging
    from veles_tpu.loader import TextFileLoader
    p = tmp_path / "t.txt"
    p.write_text("abcabcabzQQ" * 8)          # z/Q outside the vocab
    ld = TextFileLoader(None, files=[str(p)], seq_len=8, stride=8,
                        vocab="abc", validation_ratio=0.0,
                        minibatch_size=2, name="oov")
    with caplog.at_level(logging.WARNING):
        ld.load_data()
    assert ld.unk_id == 3                      # one PAST 'abc'
    assert ld.vocab_size == 4                  # unk is id space
    ids = ld.encode("azbQ")
    assert ids.tolist() == [0, 3, 1, 3]
    assert ld.decode(ids) == "a" + ld.UNK_CHAR + "b" + ld.UNK_CHAR
    # id 0 kept its real meaning: only genuine 'a's decode to 'a'
    assert ld.decode(ld.encode("aaa")) == "aaa"
    assert any("unk" in r.message for r in caplog.records), \
        [r.message for r in caplog.records]
    # the served windows contain unk ids, never silent zeros for OOV
    assert (ld.original_data.mem == 3).any()


def test_zeromq_loader_batches_queued_items():
    """Dynamic batching reaches the ZMQ path too: items queued before
    the next run() share one dispatch, in arrival order."""
    zmq = pytest.importorskip("zmq")
    wf = vt.Workflow(name="zmq-batch-wf")
    loader = ZeroMQLoader(wf, sample_shape=(3,), timeout=10.0,
                          minibatch_size=4, name="zb")
    loader.initialize(device=None)
    ctx = zmq.Context.instance()
    sock = ctx.socket(zmq.DEALER)
    sock.RCVTIMEO = 10000       # a dead drain thread must FAIL, not hang
    sock.connect(loader.bound_endpoint)
    for i in range(3):
        sock.send(pickle.dumps((numpy.full(3, float(i)), i)))
        assert sock.recv() == b"ok"
    loader.run()
    assert loader.minibatch_size == 3        # one dispatch, three items
    for i in range(3):
        numpy.testing.assert_allclose(loader.minibatch_data.mem[i],
                                      [i, i, i])
        assert loader.minibatch_labels.mem[i] == i
    assert loader.samples_served == 3
    sock.send(b"")
    assert sock.recv() == b"bye"
    loader.run()
    assert bool(wf.stopped)
    sock.close(0)
