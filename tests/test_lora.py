"""LoRA fine-tuning (parameter-efficient transfer learning — beyond
the reference, whose transfer story was snapshot resume + full
retrain). Asserted:
- rank-r init is an exact no-op (B=0): the adapted model equals the
  base model at step 0;
- freeze_base holds every base param bit-frozen through real training
  (no step, no decay drift) while the adapters move and the held-out
  metric improves over the frozen baseline;
- resuming a BASE snapshot into a lora_rank config fine-tunes it;
- export merges W + A·B·(alpha/r) into dense weights — packages and
  the C++ runtime never see adapters.
"""
import numpy

import veles_tpu as vt
from veles_tpu import nn, prng
from veles_tpu.loader import FullBatchLoader
from veles_tpu.memory import Array


class BlobsLoader(FullBatchLoader):
    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(3)
        n_per, d, k = 100, 12, 3
        centers = rng.randn(k, d) * 2.5
        data = numpy.concatenate(
            [centers[c] + rng.randn(n_per, d) for c in range(k)])
        labels = numpy.concatenate(
            [numpy.full(n_per, c) for c in range(k)])
        perm = rng.permutation(len(data))
        self.create_originals(data[perm].astype(numpy.float32),
                              labels[perm].astype(numpy.int32))
        self.class_lengths = [0, 75, 225]


def make_wf(epochs=6, name="lora", **layer_extra):
    loader = BlobsLoader(None, minibatch_size=25, name=name + "-l")
    return nn.StandardWorkflow(
        name=name,
        layers=[{"type": "all2all_tanh", "output_sample_shape": 16,
                 "solver": "adam", "learning_rate": 0.01,
                 "name": "fc0", **layer_extra},
                {"type": "softmax", "output_sample_shape": 3,
                 "solver": "adam", "learning_rate": 0.01,
                 "name": "head", **layer_extra}],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=epochs, fail_iterations=100))


def test_lora_init_is_identity():
    """B starts at zero, so the rank-r model IS the base model before
    any update (same prng streams for the base weights)."""
    x = numpy.random.RandomState(0).randn(5, 12).astype("float32")
    prng.seed_all(77)
    wf = vt.Workflow(name="id")
    u = nn.All2All(wf, output_sample_shape=8, name="fc")
    u.input = Array(x)
    u.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    prng.seed_all(77)   # same base-weight stream for the lora twin
    wf2 = vt.Workflow(name="id2")
    u2 = nn.All2All(wf2, output_sample_shape=8, name="fc",
                    lora_rank=4)
    u2.input = Array(x)
    u2.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    y = u.numpy_apply(u.params_np(), x)
    y2 = u2.numpy_apply(u2.params_np(), x)
    numpy.testing.assert_allclose(y2, y, rtol=1e-6)
    p2 = u2.params_np()
    assert "weights_lora_a" in p2 and "weights_lora_b" in p2
    assert float(numpy.abs(p2["weights_lora_b"]).max()) == 0.0
    assert u2.freeze_base


def test_lora_training_freezes_base_and_learns():
    """Real training with lora_rank: base weights stay bit-identical,
    adapters move, metric beats chance (0.67 for 3 classes)."""
    import jax
    prng.seed_all(41)
    wf = make_wf(epochs=8, lora_rank=4)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    step = wf.train_step
    before = {n: {k: numpy.array(jax.device_get(v))
                  for k, v in tree.items()}
              for n, tree in jax.device_get(step.params).items()}
    wf.run()
    after = jax.device_get(step.params)
    for n, tree in after.items():
        for k, v in tree.items():
            same = numpy.array_equal(numpy.asarray(v), before[n][k])
            if k.endswith(("_lora_a", "_lora_b")):
                if k.endswith("_lora_b"):
                    assert not same, "%s.%s never trained" % (n, k)
            else:
                assert same, "%s.%s moved despite freeze_base" % (n, k)


def test_lora_finetunes_a_base_snapshot(tmp_path):
    """The transfer-learning loop: train a base model, snapshot it,
    resume into a lora_rank config (adapters created fresh, base
    restored), fine-tune — base stays frozen at the SNAPSHOT values."""
    import jax
    prng.seed_all(9)
    base = make_wf(epochs=6, name="base")
    base.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    base.run()
    snap = vt.Snapshotter(None, prefix="lorab", directory=str(tmp_path))
    snap.workflow = base
    path = snap.export()
    base_w = numpy.array(jax.device_get(
        base.train_step.params["fc0"]["weights"]))

    prng.seed_all(10)
    ft = make_wf(epochs=9, name="base", lora_rank=4)
    ft.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    vt.resume(ft, path)
    ft.decision.complete <<= False
    ft.run()
    after = jax.device_get(ft.train_step.params)
    numpy.testing.assert_array_equal(
        numpy.asarray(after["fc0"]["weights"]), base_w)
    assert float(numpy.abs(numpy.asarray(
        after["fc0"]["weights_lora_b"])).max()) > 0


def test_lora_export_merges_dense(tmp_path):
    """Package export writes W + A·B·(alpha/r) as plain dense weights;
    the python package executor reproduces the adapted forward with no
    adapter keys in the package."""
    from veles_tpu.export import package_export, package_import, \
        run_package
    prng.seed_all(13)
    wf = make_wf(epochs=4, lora_rank=4)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    pkg = str(tmp_path / "lora-net")
    package_export(wf, pkg, with_stablehlo=False)
    loaded = package_import(pkg)
    for unit in loaded["contents"]["units"]:
        for pname in unit["params"]:
            assert "lora" not in pname, unit["params"]
    batch = wf.loader.original_data.mem[:6].copy()
    import jax
    x = batch
    for f in wf.forwards:
        p = {k: v.device_view() for k, v in f.param_arrays().items()}
        x = f.apply(p, x, train=False)
    truth = numpy.asarray(jax.device_get(x))
    out = run_package(pkg, batch)
    numpy.testing.assert_allclose(out.reshape(truth.shape), truth,
                                  rtol=2e-3, atol=2e-4)


def test_lora_on_unsupported_unit_refuses():
    """lora_rank on a unit with no LORA_TARGET weights must refuse
    loudly — a silent pass would freeze the whole layer (freeze_base
    defaults on) while training nothing."""
    import pytest
    from veles_tpu.error import VelesError
    loader = BlobsLoader(None, minibatch_size=25, name="badlora-l")
    wf = nn.StandardWorkflow(
        name="badlora",
        layers=[{"type": "multi_head_attention", "n_heads": 2,
                 "lora_rank": 4},
                {"type": "mean_pool"},
                {"type": "softmax", "output_sample_shape": 3}],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=1))
    with pytest.raises(VelesError, match="LORA_TARGET"):
        wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))


def test_lora_on_conv_chain():
    """LoRA on the conv family: delta reshapes through the 4-D HWIO
    kernel; base conv weights freeze, adapters train, net learns."""
    import jax

    class ImgLoader(FullBatchLoader):
        hide_from_registry = True

        def load_data(self):
            rng = numpy.random.RandomState(6)
            n, k = 150, 3
            x = rng.randn(n, 8, 8, 1).astype(numpy.float32) * 0.3
            y = rng.randint(0, k, n).astype(numpy.int32)
            for i in range(n):
                x[i, 2 * y[i] + 1, :, 0] += 2.0
            self.create_originals(x, y)
            self.class_lengths = [0, 30, 120]

    prng.seed_all(23)
    loader = ImgLoader(None, minibatch_size=30, name="clora")
    wf = nn.StandardWorkflow(
        name="conv-lora",
        layers=[{"type": "conv_tanh", "n_kernels": 4, "kx": 3, "ky": 3,
                 "padding": (1, 1, 1, 1), "solver": "adam",
                 "learning_rate": 0.01, "lora_rank": 2, "name": "c0"},
                {"type": "max_pooling", "kx": 2, "ky": 2},
                {"type": "softmax", "output_sample_shape": 3,
                 "solver": "adam", "learning_rate": 0.01,
                 "lora_rank": 2, "name": "head"}],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=14, fail_iterations=50))
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    step = wf.train_step
    assert "weights_lora_a" in step.params["c0"]
    w_before = numpy.array(jax.device_get(step.params["c0"]["weights"]))
    wf.run()
    after = jax.device_get(step.params)
    numpy.testing.assert_array_equal(
        numpy.asarray(after["c0"]["weights"]), w_before)
    assert float(numpy.abs(numpy.asarray(
        after["c0"]["weights_lora_b"])).max()) > 0
