"""Request-plane SLO observability (ISSUE 11): per-request tracing,
latency histograms, fleet /metrics aggregation.

The contract under test: every serving request gets a process-unique
``request_id`` threaded through its Ticket lifecycle; TTFT / TPOT /
queue-wait / end-to-end land in fixed-bucket Prometheus histograms
(p50/p90/p99 derived from buckets, rendered as gauges by the shared
``metrics_text`` path); terminal accounting is EXACTLY once even when
two sweeps see the same ticket; tracing stays off the hot path
(decode-step dispatch counts bit-identical tracing on vs off); and
``veles-tpu metrics aggregate`` merges N live /metrics endpoints
(counters summed, histogram buckets summed, quantiles recomputed,
per-endpoint up/down stamped)."""
import http.server
import io
import json
import os
import sys
import threading
import time
from contextlib import redirect_stdout

import numpy
import pytest

import veles_tpu as vt
from veles_tpu import prng
from veles_tpu.serving import (SERVING_HISTOGRAMS, ContinuousEngine,
                               Ticket)
from veles_tpu.serving.engine import make_request
from veles_tpu.serving.scheduler import (SlotScheduler, shed_expired,
                                         split_expired)
from veles_tpu.telemetry import fleet
from veles_tpu.telemetry.counters import (HISTOGRAMS,
                                          HistogramRegistry, counters,
                                          histogram_quantile,
                                          histograms, metrics_text,
                                          observe)
from veles_tpu.telemetry.recorder import flight
from veles_tpu.telemetry.spans import recorder as span_recorder

from conftest import import_model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- histogram registry (no jax) ----------------------------------------------

def test_histogram_observe_and_quantiles():
    reg = HistogramRegistry()
    name = "veles_serving_ttft_seconds"
    for v in (0.003, 0.02, 0.02, 0.07, 0.2, 4.0):
        reg.observe(name, v)
    assert reg.count(name) == 6
    assert abs(reg.sum(name) - 4.313) < 1e-9
    snap = reg.snapshot()[name]
    assert snap["bounds"] == HISTOGRAMS[name]["buckets"]
    assert sum(snap["counts"]) == 6
    p50 = reg.quantile(name, 0.5)
    # rank 3 lands in the (0.01, 0.025] bucket (two samples there)
    assert 0.01 < p50 <= 0.025
    p99 = reg.quantile(name, 0.99)
    assert p99 >= p50
    # empty histogram answers None, not 0 (0 is a real latency)
    assert reg.quantile("veles_serving_tpot_seconds", 0.5) is None


def test_histogram_overflow_bucket_reports_last_bound():
    bounds = (0.1, 1.0)
    # every sample beyond the last bound: quantile cannot see past it
    assert histogram_quantile(bounds, (0, 0, 5), 0.5) == 1.0
    assert histogram_quantile(bounds, (0, 0, 0), 0.5) is None
    # exact boundary value belongs to its bucket (Prometheus `le`)
    reg = HistogramRegistry()
    reg.observe("veles_serving_tpot_seconds", 0.0005)
    assert reg.snapshot()["veles_serving_tpot_seconds"]["counts"][0] == 1


def test_histogram_prometheus_exposition_format():
    reg = HistogramRegistry()
    reg.observe("veles_serving_e2e_seconds", 0.3)
    reg.observe("veles_serving_e2e_seconds", 7.0)
    text = reg.prometheus_text()
    assert "# TYPE veles_serving_e2e_seconds histogram" in text
    assert 'veles_serving_e2e_seconds_bucket{le="0.5"} 1' in text
    assert 'veles_serving_e2e_seconds_bucket{le="+Inf"} 2' in text
    assert "veles_serving_e2e_seconds_count 2" in text
    assert "veles_serving_e2e_seconds_sum 7.3" in text
    # cumulative monotonicity across every rendered bucket
    cums = [int(line.rsplit(None, 1)[1]) for line in text.splitlines()
            if "_bucket{" in line]
    assert cums == sorted(cums)


def test_metrics_text_renders_quantile_gauges_and_histograms():
    histograms.reset()
    try:
        for v in (0.01, 0.02, 0.03, 0.4):
            observe("veles_serving_ttft_seconds", v)
        text = metrics_text()
        assert "# TYPE veles_serving_ttft_seconds histogram" in text
        assert "# TYPE veles_serving_ttft_seconds_p50 gauge" in text
        assert "veles_serving_ttft_seconds_p99" in text
        # no samples -> no rows at all (non-serving pages unchanged)
        assert "veles_serving_tpot_seconds" not in text
    finally:
        histograms.reset()


def test_metrics_text_collision_guard_drops_shadowing_gauge():
    histograms.reset()
    counters.inc("veles_dispatches_total", 0)
    observe("veles_serving_ttft_seconds", 0.02)
    before = counters.get("veles_metrics_name_collisions_total")
    try:
        text = metrics_text({
            "veles_dispatches_total": 123.0,          # shadows counter
            "veles_serving_ttft_seconds_p50": 9.9,    # shadows quantile
            "veles_fine_gauge": (7, "a fine gauge")})
        grown = counters.get("veles_metrics_name_collisions_total") \
            - before
        assert grown == 2
        assert "veles_fine_gauge 7" in text
        assert "# TYPE veles_dispatches_total gauge" not in text
        # the page never renders a duplicate metric name with two TYPEs
        names = {}
        for line in text.splitlines():
            if line.startswith("# TYPE "):
                _h, _t, name, kind = line.split()
                assert names.setdefault(name, kind) == kind, name
    finally:
        histograms.reset()


# -- exactly-once terminal accounting (no jax) --------------------------------

def test_ticket_terminal_is_exactly_once():
    histograms.reset()
    t = Ticket()
    assert t.fail("boom", code=503) is True
    assert t.fail("boom again", code=500) is False
    assert t.code == 503            # the first answer stands
    assert t.succeed({"tokens": [1]}) is False
    assert t.result is None
    histograms.reset()


def test_deadline_shed_accounted_exactly_once():
    """A ticket expired by shed_expired must record its queue-wait
    histogram sample, its expiry counters and its terminal flight
    event exactly once — also when the tick sweep AND the failure-path
    sweep both hand it to shed_expired."""
    histograms.reset()
    sched = SlotScheduler(1, (8,), 16)
    busy, old = Ticket(), Ticket(deadline=time.time() - 1)
    sched.push(make_request([1, 2], 4), busy)
    sched.take_admissions()
    sched.push(make_request([1, 2], 4), old)
    exp_before = counters.get("veles_serving_expired_total")
    shed_before = counters.get("veles_shed_requests_total")
    # the tick sweep sees it...
    _adm, expired = sched.take_admissions()
    assert expired == [old]
    shed_expired(expired)
    # ...and the failure-path sweep hands the SAME ticket over again
    shed_expired(expired)
    shed_expired(sched.expire_queued())
    assert counters.get("veles_serving_expired_total") \
        - exp_before == 1
    assert counters.get("veles_shed_requests_total") \
        - shed_before == 1
    assert histograms.count("veles_serving_queue_wait_seconds") == 1
    assert old.outcome == "expired"
    done = [r for r in flight.records(kind="request")
            if r.get("request_id") == old.request_id
            and r.get("phase") == "done"]
    assert len(done) == 1 and done[0]["outcome"] == "expired"
    histograms.reset()


def test_split_expired_unchanged_by_tracing_fields():
    live = Ticket(deadline=time.time() + 60)
    dead = Ticket(deadline=time.time() - 60)
    keep, gone = split_expired([({}, live), ({}, dead)])
    assert [t for _r, t in keep] == [live] and gone == [dead]


# -- fleet aggregation (no jax) -----------------------------------------------

_PAGE_A = """\
# HELP veles_serving_admitted_total x
# TYPE veles_serving_admitted_total counter
veles_serving_admitted_total 10
# HELP veles_serving_ttft_seconds ttft
# TYPE veles_serving_ttft_seconds histogram
veles_serving_ttft_seconds_bucket{le="0.1"} 8
veles_serving_ttft_seconds_bucket{le="1"} 10
veles_serving_ttft_seconds_bucket{le="+Inf"} 10
veles_serving_ttft_seconds_sum 0.9
veles_serving_ttft_seconds_count 10
# TYPE veles_serving_ttft_seconds_p50 gauge
veles_serving_ttft_seconds_p50 0.0625
# TYPE veles_serving_slots_busy gauge
veles_serving_slots_busy 3
"""

_PAGE_B = """\
# TYPE veles_serving_admitted_total counter
veles_serving_admitted_total 4
# TYPE veles_serving_ttft_seconds histogram
veles_serving_ttft_seconds_bucket{le="0.1"} 0
veles_serving_ttft_seconds_bucket{le="1"} 2
veles_serving_ttft_seconds_bucket{le="+Inf"} 4
veles_serving_ttft_seconds_sum 9.5
veles_serving_ttft_seconds_count 4
# TYPE veles_serving_slots_busy gauge
veles_serving_slots_busy 5
"""


def test_fleet_parse_and_merge_math():
    pa = fleet.parse_metrics_text(_PAGE_A)
    pb = fleet.parse_metrics_text(_PAGE_B)
    assert pa["counters"]["veles_serving_admitted_total"] == 10
    assert pa["histograms"]["veles_serving_ttft_seconds"]["count"] == 10
    # the endpoint-local quantile gauge parses as a gauge...
    assert "veles_serving_ttft_seconds_p50" in pa["gauges"]
    merged = fleet.merge([pa, pb])
    assert merged["counters"]["veles_serving_admitted_total"] == 14
    h = merged["histograms"]["veles_serving_ttft_seconds"]
    assert h["buckets"]["0.1"] == 8 and h["buckets"]["1"] == 12
    assert h["buckets"]["+Inf"] == 14 and h["count"] == 14
    assert abs(h["sum"] - 10.4) < 1e-9
    assert merged["gauges"]["veles_serving_slots_busy"] == 8
    # ...but is DROPPED from the merge: fleet quantiles are
    # recomputed from the merged buckets, never averaged
    assert "veles_serving_ttft_seconds_p50" not in merged["gauges"]
    qs = fleet.quantiles(h)
    # rank(0.5) = 7 of 14 sits inside the (0, 0.1] bucket (8 samples)
    assert 0.0 < qs[0.5] <= 0.1
    assert qs[0.99] > qs[0.5]


def test_fleet_merge_step_function_handles_unequal_grids():
    pa = fleet.parse_metrics_text(
        "# TYPE h histogram\n"
        'h_bucket{le="1"} 2\nh_bucket{le="+Inf"} 2\n'
        "h_sum 1.0\nh_count 2\n")
    pb = fleet.parse_metrics_text(
        "# TYPE h histogram\n"
        'h_bucket{le="0.5"} 1\nh_bucket{le="1"} 1\n'
        'h_bucket{le="+Inf"} 3\nh_sum 4.0\nh_count 3\n')
    h = fleet.merge([pa, pb])["histograms"]["h"]
    # at le=0.5 endpoint A contributes its cumulative at <=0.5 (0)
    assert h["buckets"]["0.5"] == 1
    assert h["buckets"]["1"] == 3
    assert h["buckets"]["+Inf"] == 5


class _Static(http.server.BaseHTTPRequestHandler):
    page = ""

    def log_message(self, *a):
        pass

    def do_GET(self):
        if self.path != "/metrics":
            self.send_error(404)
            return
        body = self.page.encode()
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def _serve_page(page):
    handler = type("H", (_Static,), {"page": page})
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def test_metrics_aggregate_cli_merges_two_live_endpoints():
    """The acceptance e2e: `veles-tpu metrics aggregate` over two live
    HTTP endpoints — counters summed, buckets summed, quantiles
    recomputed, per-endpoint up/down stamped (a dead third endpoint
    reports up=0 without failing the aggregation)."""
    a, b = _serve_page(_PAGE_A), _serve_page(_PAGE_B)
    dead = "http://127.0.0.1:1/metrics"
    try:
        urls = ["http://127.0.0.1:%d/metrics" % a.server_address[1],
                "http://127.0.0.1:%d" % b.server_address[1], dead]
        from veles_tpu.__main__ import main
        out = io.StringIO()
        with redirect_stdout(out):
            rc = main(["metrics", "aggregate"] + urls)
        assert rc == 0
        text = out.getvalue()
        assert "veles_serving_admitted_total 14" in text
        assert 'veles_serving_ttft_seconds_bucket{le="1"} 12' in text
        assert "veles_serving_ttft_seconds_count 14" in text
        assert "veles_serving_slots_busy 8" in text
        assert 'veles_fleet_endpoint_up{endpoint="%s"} 0' % dead \
            in text
        assert text.count("veles_fleet_endpoint_up{") == 3
        assert "veles_serving_ttft_seconds_p50" in text
        assert "veles_serving_ttft_seconds_p99" in text
        # --json form carries the structured aggregation
        out = io.StringIO()
        with redirect_stdout(out):
            rc = main(["metrics", "aggregate", "--json"] + urls)
        assert rc == 0
        agg = json.loads(out.getvalue())
        assert [ep["up"] for ep in agg["endpoints"]] \
            == [True, True, False]
        # the whole fleet down = exit 2 (an alert, not a report)
        out = io.StringIO()
        with redirect_stdout(out):
            rc = main(["metrics", "aggregate", dead])
        assert rc == 2
    finally:
        a.shutdown()
        b.shutdown()


# -- static registration pass (scripts/check_counters.py) ---------------------

def _load_checker():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "veles_check_counters_hist",
        os.path.join(REPO, "scripts", "check_counters.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_check_counters_verifies_histograms(tmp_path):
    mod = _load_checker()
    # the tree itself is clean — every observe()d veles_* histogram
    # carries help + bucket bounds
    assert mod.find_unregistered_histograms() == []
    regs = mod.registered_histograms()
    for name in SERVING_HISTOGRAMS:
        assert regs.get(name) is True, name
    # the detector detects: an unregistered observe is flagged
    (tmp_path / "veles_tpu").mkdir()
    (tmp_path / "veles_tpu" / "x.py").write_text(
        'observe("veles_bogus_seconds", 1.0)\n'
        'histograms.quantile("veles_bogus2_seconds", 0.5)\n')
    uses = mod.used_histograms(str(tmp_path))
    assert set(uses) == {"veles_bogus_seconds",
                         "veles_bogus2_seconds"}


# -- gate arithmetic (bench.py, no live proof) --------------------------------

def _bench():
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    return bench


def test_gate_serving_doc_checks(monkeypatch):
    """The REAL gate_serving, with only its (minutes-long) live proof
    stubbed out: histogram leakage in a non-serving doc fails, a
    TTFT-p99 regression beyond tolerance fails, an in-tolerance doc
    pair contributes no latency/leakage failures."""
    bench = _bench()
    monkeypatch.setattr(bench, "_serving_throughput_proof",
                        lambda: [])
    histograms.reset()
    clean = {"serving": {"admitted": 0, "histogram_samples": 0,
                         "ttft_p99": None, "queue_wait_p99": None}}
    leaked = {"serving": {"admitted": 0, "histogram_samples": 3}}
    failures = bench.gate_serving(clean, leaked)
    assert any("histogram_samples" in f for f in failures)
    # serving-mode docs (serving_bench: true) skip the leakage checks
    # — their serving activity IS the measurement — and are gated on
    # the latency quantiles instead
    slow_base = {"serving": {"serving_bench": True, "admitted": 40,
                             "histogram_samples": 160,
                             "ttft_p99": 0.1, "queue_wait_p99": 0.05}}
    slow_cur = {"serving": {"serving_bench": True, "admitted": 40,
                            "histogram_samples": 160,
                            "ttft_p99": 0.5, "queue_wait_p99": 0.04}}
    failures = bench.gate_serving(slow_base, slow_cur)
    assert any("ttft_p99 regressed" in f for f in failures)
    assert not any("leaked" in f for f in failures)
    ok_cur = {"serving": {"serving_bench": True, "admitted": 40,
                          "histogram_samples": 160,
                          "ttft_p99": 0.2, "queue_wait_p99": 0.05}}
    failures = bench.gate_serving(slow_base, ok_cur)
    assert not any("regressed" in f or "leaked" in f
                   for f in failures)


def test_bench_serving_section_stamps_slo_quantiles():
    bench = _bench()
    histograms.reset()
    try:
        sec = bench._serving_section()
        assert sec["histogram_samples"] == 0
        assert sec["ttft_p50"] is None and sec["ttft_p99"] is None
        assert sec["tpot_p50"] is None
        assert sec["queue_wait_p99"] is None
        observe("veles_serving_ttft_seconds", 0.02)
        observe("veles_serving_tpot_seconds", 0.004)
        observe("veles_serving_queue_wait_seconds", 0.001)
        sec = bench._serving_section()
        assert sec["histogram_samples"] == 3
        assert 0.0 < sec["ttft_p50"] <= sec["ttft_p99"]
        assert sec["tpot_p50"] > 0 and sec["queue_wait_p99"] > 0
    finally:
        histograms.reset()


# -- engine e2e: ids, histograms, spans, dispatch lock ------------------------

@pytest.fixture(scope="module")
def served():
    lm = import_model("char_lm")
    prng.seed_all(1311)
    wf = lm.build_workflow(epochs=1, minibatch_size=64, n_blocks=1,
                           dim=32, n_train=128, n_valid=64)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    engine = ContinuousEngine(wf, max_slots=3, buckets=(8, 16),
                              max_context=48, decode_block=4,
                              name="eng_trace").start()
    yield lm, wf, engine
    engine.stop()


def _reqs(lm, n=4):
    rng = numpy.random.RandomState(7)
    return [make_request(
        [int(t) for t in rng.randint(0, lm.VOCAB, 5 + i)], 4 + i,
        temperature=0.0 if i % 2 else 0.8, seed=50 + i)
        for i in range(n)]


def test_request_ids_histograms_spans_and_flight(served):
    lm, _wf, engine = served
    histograms.reset()
    span_recorder.clear()
    reqs = _reqs(lm)
    tickets = [Ticket() for _ in reqs]
    for req, ticket in zip(reqs, tickets):
        assert engine.submit(req, ticket)
    for ticket in tickets:
        assert ticket.event.wait(120), ticket.error
    rids = [t.result["request_id"] for t in tickets]
    assert len(set(rids)) == len(rids)
    assert all(t.request_id == rid
               for t, rid in zip(tickets, rids))
    # per-request SLO samples: one TTFT + one queue-wait + one e2e
    # per request; TPOT for every multi-token request
    assert histograms.count("veles_serving_ttft_seconds") == len(reqs)
    assert histograms.count("veles_serving_queue_wait_seconds") \
        == len(reqs)
    assert histograms.count("veles_serving_e2e_seconds") == len(reqs)
    assert histograms.count("veles_serving_tpot_seconds") == len(reqs)
    assert histograms.quantile("veles_serving_ttft_seconds", 0.5) > 0
    # lifecycle spans tagged with the id, exportable per request
    recs = span_recorder.records()
    for rid in rids:
        mine = [r for r in recs if r.get("request_id") == rid]
        names = {r["name"] for r in mine}
        assert {"request", "request.queue", "request.prefill",
                "request.decode"} <= names, names
        total = [r for r in mine if r["name"] == "request"][0]
        assert total["outcome"] == "retired"
        assert total["tokens"] == len(
            [t for t in tickets
             if t.request_id == rid][0].result["tokens"])
    # terminal flight events: one done event per request
    done = [r for r in flight.records(kind="request")
            if r.get("phase") == "done"
            and r.get("request_id") in rids]
    assert len(done) == len(rids)
    # the engine prefill span carries the id too
    prefills = [r for r in recs if r["name"] == "serving.prefill"]
    assert prefills and all("request_id" in r for r in prefills)
    histograms.reset()


def test_trace_export_filters_one_request(served, tmp_path):
    lm, _wf, engine = served
    span_recorder.clear()
    reqs = _reqs(lm, n=2)
    out = engine.serve(reqs)
    assert len(out) == 2
    recs = span_recorder.records("request")
    rid = recs[-1]["request_id"]
    jsonl = str(tmp_path / "run.jsonl")
    span_recorder.to_jsonl(jsonl)
    trace = str(tmp_path / "trace.json")
    from veles_tpu.__main__ import main
    assert main(["trace", "export", jsonl, trace,
                 "--request", rid]) == 0
    doc = json.load(open(trace))
    named = [ev for ev in doc["traceEvents"] if ev["ph"] == "X"]
    assert named, "no spans exported"
    assert all(ev["args"].get("request_id") == rid for ev in named)
    # an unknown id refuses instead of writing a blank page
    assert main(["trace", "export", jsonl, trace,
                 "--request", "req-0-0"]) == 1


def test_tracing_off_hot_path_dispatches_bit_identical(served):
    """The CI lock the satellite asks for: decode-step dispatch count
    (and tokens) with request tracing enabled is bit-identical to
    tracing off — tracing is host-side timestamps at step boundaries
    only, never device work."""
    lm, _wf, engine = served
    from veles_tpu.config import root
    reqs = _reqs(lm)
    engine.serve(list(reqs))          # warm every program

    def measure():
        before = {k: counters.get(k) for k in (
            "veles_serving_decode_dispatches_total",
            "veles_serving_prefill_dispatches_total",
            "veles_decode_dispatches_total",
            "veles_compiles_total")}
        # solo, sequential: admission timing cannot reshuffle chunk
        # boundaries between the two measured passes
        out = [engine.serve([r])[0] for r in reqs]
        return out, {k: counters.get(k) - v
                     for k, v in before.items()}

    prev = root.common.trace.get("requests", True)
    try:
        root.common.trace.requests = True
        out_on, d_on = measure()
        root.common.trace.requests = False
        out_off, d_off = measure()
    finally:
        root.common.trace.requests = prev
    assert out_on == out_off
    assert d_on == d_off, (d_on, d_off)
    assert d_on["veles_compiles_total"] == 0


def test_tracing_disabled_emits_no_request_spans(served):
    lm, _wf, engine = served
    from veles_tpu.config import root
    histograms.reset()
    prev = root.common.trace.get("requests", True)
    span_recorder.clear()
    try:
        root.common.trace.requests = False
        engine.serve(_reqs(lm, n=2))
        assert not span_recorder.records("request")
        # the SLO histograms record regardless — p99 TTFT must be
        # answerable on a fleet running with tracing off
        assert histograms.count("veles_serving_ttft_seconds") == 2
    finally:
        root.common.trace.requests = prev
        histograms.reset()


def test_live_metrics_page_exposes_request_slos(served):
    """Both HTTP surfaces render through metrics_text — one rendered
    page after real serving carries the histogram series and the
    quantile gauges."""
    lm, _wf, engine = served
    histograms.reset()
    try:
        engine.serve(_reqs(lm, n=2))
        text = metrics_text()
        assert "# TYPE veles_serving_ttft_seconds histogram" in text
        assert 'veles_serving_ttft_seconds_bucket{le="+Inf"} 2' \
            in text
        assert "veles_serving_ttft_seconds_p99" in text
        assert "veles_serving_queue_wait_seconds_p50" in text
        # and the fleet parser round-trips the live page
        parsed = fleet.parse_metrics_text(text)
        assert parsed["histograms"][
            "veles_serving_ttft_seconds"]["count"] == 2
    finally:
        histograms.reset()
