"""Overlap engine (veles_tpu/overlap/, docs/overlap.md).

The contract under test: overlapping host I/O with device compute —
async side-plane for side-effect units, non-blocking checkpoints,
data-plane prefetch — changes WHEN host work happens, never WHAT is
computed. Train results are bit-identical with overlap on vs. off;
lane FIFO and drain barriers preserve the checkpoint chain's
crash-safety invariants; no thread outlives its owner.
"""
import glob
import os
import threading
import time

import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn, prng
from veles_tpu.config import root
from veles_tpu.loader import FullBatchLoader
from veles_tpu.overlap import (OVERLAP_COUNTERS, Prefetcher, SidePlane,
                               SidePlaneError)
from veles_tpu.resilience import checkpoint_chain, faults
from veles_tpu.snapshotter import collect_state
from veles_tpu.telemetry.counters import DESCRIPTIONS, counters
from veles_tpu.units import Unit
from veles_tpu.workflow import Workflow


def fresh_prng(seed=1234):
    with prng._lock:
        prng._generators.clear()
    prng.seed_all(seed)


@pytest.fixture(autouse=True)
def _overlap_off_after():
    """Every test leaves the engine the way tier-1 expects it: off."""
    yield
    root.common.overlap.enabled = False
    root.common.overlap.async_snapshots = False
    root.common.overlap.prefetch_depth = 0


def assert_trees_equal(a, b, path="root"):
    assert type(a) is type(b), (path, type(a), type(b))
    if isinstance(a, dict):
        assert sorted(a) == sorted(b), (path, sorted(a), sorted(b))
        for k in a:
            assert_trees_equal(a[k], b[k], "%s.%s" % (path, k))
    elif isinstance(a, (list, tuple)):
        assert len(a) == len(b), path
        for i, (x, y) in enumerate(zip(a, b)):
            assert_trees_equal(x, y, "%s[%d]" % (path, i))
    elif isinstance(a, numpy.ndarray):
        numpy.testing.assert_array_equal(a, b, err_msg=path)
    else:
        assert a == b, (path, a, b)


# ---------------------------------------------------------------------------
# side-plane executor
# ---------------------------------------------------------------------------

def test_lane_fifo_ordering_under_concurrency():
    """Tasks in one lane run FIFO even while several lanes execute
    concurrently; drain is a true barrier."""
    sp = SidePlane(name="fifo", queue_depth=8)
    seen = {"a": [], "b": [], "c": []}
    try:
        for i in range(60):
            lane = "abc"[i % 3]
            # uneven task durations shuffle cross-lane completion order
            # — per-lane order must survive anyway
            def task(lane=lane, i=i):
                if i % 7 == 0:
                    time.sleep(0.002)
                seen[lane].append(i)
            sp.submit(lane, task)
        sp.drain()
        for lane in "abc":
            assert seen[lane] == sorted(seen[lane]), lane
            assert len(seen[lane]) == 20
    finally:
        sp.shutdown()
    assert not any(t.name.startswith("fifo:")
                   for t in threading.enumerate())


def test_sideplane_backpressure_counts_stall():
    """A full lane blocks the submitter (bounded memory) and the wait
    is counted in the stall counter."""
    sp = SidePlane(name="bp", queue_depth=1)
    before = counters.get("veles_sideplane_stall_seconds_total")
    try:
        for _ in range(6):
            sp.submit("slow", time.sleep, 0.01)
        sp.drain()
    finally:
        sp.shutdown()
    assert counters.get("veles_sideplane_stall_seconds_total") > before


def test_sideplane_errors_route_to_drain_and_counters():
    sp = SidePlane(name="err", queue_depth=4)
    before = counters.get("veles_sideplane_errors_total")
    try:
        sp.submit("x", lambda: 1 / 0)
        sp.submit("x", lambda: None)     # lane keeps running after error
        with pytest.raises(SidePlaneError) as excinfo:
            sp.drain()
        assert isinstance(excinfo.value.errors[0], ZeroDivisionError)
        assert counters.get("veles_sideplane_errors_total") == before + 1
        # errors were popped: the next drain is clean
        assert sp.drain() == []
    finally:
        sp.shutdown()


def test_sideplane_chaos_delay_survives_drain(monkeypatch):
    """Satellite: the sideplane.task fault point can delay lane
    workers; drain still barriers and FIFO holds."""
    monkeypatch.setenv("VELES_FAULTS", "sideplane.task:delay:delay=0.01")
    faults.plane.configure()
    sp = SidePlane(name="chaos", queue_depth=4)
    out = []
    try:
        for i in range(5):
            sp.submit("l", out.append, i)
        sp.drain()
        assert out == list(range(5))
    finally:
        sp.shutdown()
        monkeypatch.delenv("VELES_FAULTS")
        faults.plane.configure()


def test_overlap_counters_registered():
    for name in OVERLAP_COUNTERS:
        assert name in DESCRIPTIONS, name
    for point in ("sideplane.task", "prefetch.batch"):
        assert point in faults.list_points(), point


# ---------------------------------------------------------------------------
# prefetcher
# ---------------------------------------------------------------------------

def test_prefetcher_backpressure():
    """The producer never runs more than ``depth`` batches ahead."""
    produced = []

    def gen():
        for i in range(50):
            produced.append(i)
            yield i

    with Prefetcher(gen(), depth=3, name="bp") as pf:
        time.sleep(0.1)                 # producer runs free…
        # …but depth + the one item in flight bound its lead
        assert len(produced) <= 3 + 1, produced
        assert [pf.get(timeout=10) for _ in range(50)] == list(range(50))
        with pytest.raises(StopIteration):
            pf.get(timeout=10)


def test_prefetcher_shutdown_without_orphan_threads():
    """close() while the producer is BLOCKED on a full queue must still
    join the thread."""
    def gen():
        i = 0
        while True:
            yield i
            i += 1

    pf = Prefetcher(gen(), depth=2, name="orphan")
    assert pf.get(timeout=10) == 0
    time.sleep(0.05)                    # producer now stuck in put()
    pf.close()
    assert pf.closed
    assert not any(t.name.startswith("prefetch:orphan")
                   for t in threading.enumerate())


def test_prefetcher_get_timeout_raises_timeout_error():
    """A wedged producer fails the consumer loudly (TimeoutError, not
    a leaked queue.Empty), and the wait still lands in the stall
    counter."""
    def gen():
        # close() cannot interrupt a producer blocked INSIDE its own
        # source (only one blocked on the queue) — keep the wedge
        # short so the daemon thread dies with the test, not 30s later
        time.sleep(1.0)
        yield 0

    before = counters.get("veles_prefetch_stall_seconds_total")
    pf = Prefetcher(gen(), depth=2, name="wedge")
    with pytest.raises(TimeoutError):
        pf.get(timeout=0.05)
    pf.close()
    assert counters.get("veles_prefetch_stall_seconds_total") > before


def test_prefetcher_producer_error_surfaces_at_get():
    def gen():
        yield 1
        raise RuntimeError("producer died")

    with Prefetcher(gen(), depth=2, name="err") as pf:
        assert pf.get(timeout=10) == 1
        with pytest.raises(RuntimeError, match="producer died"):
            pf.get(timeout=10)
        with pytest.raises(RuntimeError):   # stays broken, never hangs
            pf.get(timeout=10)


def test_prefetch_fault_point_chaos(monkeypatch):
    monkeypatch.setenv("VELES_FAULTS", "prefetch.batch:raise:after=2")
    faults.plane.configure()
    try:
        with Prefetcher(iter(range(10)), depth=2, name="chaos") as pf:
            assert pf.get(timeout=10) == 0
            assert pf.get(timeout=10) == 1
            with pytest.raises(faults.FaultInjected):
                for _ in range(8):
                    pf.get(timeout=10)
    finally:
        monkeypatch.delenv("VELES_FAULTS")
        faults.plane.configure()


# ---------------------------------------------------------------------------
# loader prefetch: bit-identical serving
# ---------------------------------------------------------------------------

class ServingLoader(FullBatchLoader):
    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(0)
        self.create_originals(rng.rand(105, 4).astype(numpy.float32),
                              rng.randint(0, 3, 105).astype(numpy.int32))
        self.class_lengths = [0, 25, 80]


def _serve_trace(depth, steps=18):
    """Same name + same seed ⇒ the serial and prefetched runs consume
    identical PRNG streams; the trace captures everything a training
    consumer could observe."""
    fresh_prng(7)
    loader = ServingLoader(None, minibatch_size=20, name="serve",
                           prefetch_depth=depth)
    loader.initialize()
    trace = []
    for _ in range(steps):
        loader.run()
        trace.append((numpy.array(loader.minibatch_data.mem),
                      numpy.array(loader.minibatch_labels.mem),
                      numpy.array(loader.minibatch_indices.mem),
                      loader.minibatch_offset, loader.minibatch_class,
                      loader.minibatch_size, bool(loader.epoch_ended),
                      bool(loader.train_ended)))
    state = loader.state_dict()
    loader.stop()
    return trace, state


def test_loader_prefetch_bit_identical_serving():
    serial_trace, serial_state = _serve_trace(0)
    over_trace, over_state = _serve_trace(3)
    assert_trees_equal(serial_trace, over_trace)
    assert_trees_equal(serial_state, over_state)
    assert counters.get("veles_prefetch_hits_total") > 0
    # THIS loader's producers are all joined (other tests' short-lived
    # daemon threads may still be winding down — scope the assert)
    assert not any(t.name.startswith("prefetch:serve")
                   for t in threading.enumerate())


def test_loader_prefetch_resume_desync_falls_back():
    """A mid-epoch restore invalidates staged batches; serving must
    continue correctly (inline fallback + re-arm), not serve stale
    data."""
    serial_trace, _ = _serve_trace(0, steps=12)
    fresh_prng(7)
    loader = ServingLoader(None, minibatch_size=20, name="serve",
                           prefetch_depth=2)
    loader.initialize()
    for _ in range(4):
        loader.run()
    mid_state = loader.state_dict()
    for _ in range(2):
        loader.run()
    loader.load_state_dict(mid_state)       # rewind 2 minibatches
    loader.run()
    numpy.testing.assert_array_equal(
        loader.minibatch_data.mem, serial_trace[4][0])
    loader.stop()


# ---------------------------------------------------------------------------
# workflow: side-effect offload + end-to-end bit-identical state tree
# ---------------------------------------------------------------------------

class SideFx(Unit):
    hide_from_registry = True
    side_effect_only = True

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.threads = []

    def run(self):
        self.threads.append(threading.get_ident())


def _fx_workflow():
    wf = Workflow(None, name="fxwf")
    fx = SideFx(wf, name="fx")
    fx.link_from(wf.start_point)
    wf.end_point.link_from(fx)
    wf.initialize()
    return wf, fx


def test_side_effect_unit_runs_off_thread_with_overlap_on():
    root.common.overlap.enabled = True
    wf, fx = _fx_workflow()
    wf.run()
    # drained at EndPoint/run end: the task completed before run()
    # returned, on a side-plane worker, with timers/counters kept
    assert fx.threads and fx.threads[0] != threading.get_ident()
    assert fx.run_count == 1


def test_side_effect_unit_runs_inline_with_overlap_off():
    root.common.overlap.enabled = False
    wf, fx = _fx_workflow()
    wf.run()
    assert fx.threads == [threading.get_ident()]


def test_side_effect_task_error_surfaces_from_run():
    class Boom(SideFx):
        hide_from_registry = True

        def run(self):
            raise RuntimeError("async boom")

    root.common.overlap.enabled = True
    wf = Workflow(None, name="boomwf")
    fx = Boom(wf, name="boom")
    fx.link_from(wf.start_point)
    wf.end_point.link_from(fx)
    wf.initialize()
    with pytest.raises(SidePlaneError):
        wf.run()


class TrainLoader(FullBatchLoader):
    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(5)
        self.create_originals(rng.rand(240, 8).astype(numpy.float32),
                              rng.randint(0, 3, 240).astype(numpy.int32))
        self.class_lengths = [0, 40, 200]


def _train(tmpdir, overlap):
    fresh_prng()
    root.common.overlap.enabled = overlap
    root.common.overlap.async_snapshots = overlap
    if overlap:
        root.common.overlap.prefetch_depth = 2
    snap = vt.Snapshotter(None, prefix="ov", directory=str(tmpdir),
                          interval=1)
    wf = nn.StandardWorkflow(
        name="ov-wf",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 8},
                {"type": "softmax", "output_sample_shape": 3}],
        loader_unit=TrainLoader(None, minibatch_size=20, name="tiny"),
        loss_function="softmax",
        decision_config=dict(max_epochs=3, fail_iterations=99),
        snapshotter_unit=snap)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    state = collect_state(wf)
    root.common.overlap.enabled = False
    root.common.overlap.async_snapshots = False
    root.common.overlap.prefetch_depth = 0
    return wf, state


def test_train_state_tree_bit_identical_overlap_on_off(tmp_path):
    """ISSUE acceptance: async snapshotting + side-plane + prefetch
    enabled produces a state tree bit-identical to the fully serial
    run — parameters, optimizer state, loader position, PRNG streams,
    decision bests, everything."""
    serial_dir = tmp_path / "serial"
    over_dir = tmp_path / "overlap"
    serial_dir.mkdir()
    over_dir.mkdir()
    _, serial_state = _train(serial_dir, overlap=False)
    wf, over_state = _train(over_dir, overlap=True)
    assert wf[wf.units[0].name] is not None  # workflow intact
    assert_trees_equal(serial_state["__units__"],
                       over_state["__units__"])
    assert_trees_equal(serial_state["__prng__"], over_state["__prng__"])
    # the async chain is complete and loads to the same tree
    found = checkpoint_chain.load_latest(str(over_dir), "ov")
    assert found is not None
    assert_trees_equal(found[1]["__units__"], over_state["__units__"])
    # one snapshot per epoch + the forced one on stop, all verified
    snaps = checkpoint_chain.chain(str(over_dir), "ov")
    assert len(snaps) == len(checkpoint_chain.chain(str(serial_dir),
                                                    "ov"))
    for path in snaps:
        assert checkpoint_chain.verify(path) is True, path


# ---------------------------------------------------------------------------
# non-blocking checkpoints: crash/corrupt mid-commit
# ---------------------------------------------------------------------------

def _snap_workflow(tmpdir, async_mode):
    fresh_prng()
    snap = vt.Snapshotter(None, prefix="nb", directory=str(tmpdir),
                          interval=1, async_mode=async_mode)
    wf = nn.StandardWorkflow(
        name="nb-wf",
        layers=[{"type": "softmax", "output_sample_shape": 3}],
        loader_unit=TrainLoader(None, minibatch_size=40, name="nb-l"),
        loss_function="softmax",
        decision_config=dict(max_epochs=2, fail_iterations=99),
        snapshotter_unit=snap)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    return wf, snap


def _interrupted_chain(tmp_path, async_mode, monkeypatch, tag):
    """Run 2 epochs with the SECOND snapshot commit dying mid-write;
    returns (snapshot dir, state restored by restore_latest)."""
    d = tmp_path / tag
    d.mkdir()
    wf, snap = _snap_workflow(d, async_mode)
    monkeypatch.setenv("VELES_FAULTS",
                       "snapshot.write:raise:after=1")
    faults.plane.configure()
    try:
        if async_mode:
            wf.run()                    # error lands at the drain…
    except SidePlaneError:
        pass
    if not async_mode:
        try:
            wf.run()                    # …or inline at the 2nd export
        except faults.FaultInjected:
            pass
    monkeypatch.delenv("VELES_FAULTS")
    faults.plane.configure()
    # exactly the first commit survived; no torn final file
    chain = checkpoint_chain.chain(str(d), "nb")
    assert len(chain) == 1, chain
    assert checkpoint_chain.verify(chain[0]) is True
    fresh2 = _snap_workflow(tmp_path / (tag + "_r"), False)[0]
    restored = checkpoint_chain.restore_latest(fresh2, str(d), "nb")
    assert restored == chain[0]
    return d, collect_state(fresh2)


def test_async_snapshot_crash_mid_commit_restores_like_sync(
        tmp_path, monkeypatch):
    """ISSUE acceptance: a crash between state collection and commit
    must leave the previous snapshot intact, and ``restore_latest``
    must restore EXACTLY what the sync path would have."""
    _, sync_state = _interrupted_chain(tmp_path, False, monkeypatch,
                                       "sync")
    _, async_state = _interrupted_chain(tmp_path, True, monkeypatch,
                                        "async")
    assert_trees_equal(sync_state["__units__"],
                       async_state["__units__"])


def test_async_stop_commit_failure_surfaces_from_run(tmp_path,
                                                     monkeypatch):
    """A failed async commit — including the forced stop-time one —
    must surface from Workflow.run like a sync export failure would,
    not vanish into a silently-drained lane (even with the side-plane
    off: async_mode works standalone)."""
    d = tmp_path / "stopfail"
    d.mkdir()
    wf, snap = _snap_workflow(d, True)
    monkeypatch.setenv("VELES_FAULTS", "snapshot.write:raise")
    faults.plane.configure()
    try:
        with pytest.raises(SidePlaneError) as excinfo:
            wf.run()
        assert isinstance(excinfo.value.errors[0], faults.FaultInjected)
    finally:
        monkeypatch.delenv("VELES_FAULTS")
        faults.plane.configure()


def test_async_snapshot_corrupt_commit_quarantines(tmp_path,
                                                   monkeypatch):
    """Bitrot injected into an ASYNC commit is caught at restore: the
    damaged newest snapshot is quarantined and the chain falls back."""
    d = tmp_path / "rot"
    d.mkdir()
    wf, snap = _snap_workflow(d, True)
    wf.run()
    chain = checkpoint_chain.chain(str(d), "nb")
    assert len(chain) >= 2
    monkeypatch.setenv("VELES_FAULTS", "snapshot.write:corrupt:times=1")
    faults.plane.configure()
    snap.export()
    snap.drain()
    monkeypatch.delenv("VELES_FAULTS")
    faults.plane.configure()
    newest = checkpoint_chain.chain(str(d), "nb")[0]
    assert checkpoint_chain.verify(newest) is False
    fresh2 = _snap_workflow(tmp_path / "rot_r", False)[0]
    restored = checkpoint_chain.restore_latest(fresh2, str(d), "nb")
    assert restored is not None and restored != newest
    assert os.path.exists(newest + ".corrupt")


def test_async_commit_order_is_fifo(tmp_path):
    """Checkpoint-lane ordering: N queued commits land newest-last, and
    the _current symlink points at the final one."""
    d = tmp_path / "order"
    d.mkdir()
    wf, snap = _snap_workflow(d, True)
    wf.run()
    snaps = sorted(glob.glob(str(d / "nb_*.pickle.gz")))
    assert len(snaps) >= 2
    mtimes = [os.path.getmtime(p) for p in snaps]
    assert mtimes == sorted(mtimes)
    cur = d / "nb_current.pickle.gz"
    assert os.path.realpath(cur) == os.path.realpath(snaps[-1])
