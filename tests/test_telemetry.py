"""Telemetry subsystem (veles_tpu/telemetry/): deterministic
accounting — counters, spans, cost model, Chrome-trace export, and the
counter-based perf gate. The regression locks here are the ones
wall-clock gates cannot hold through relay weather: cached decode is
ONE dispatch per lax.scan (the round-5 speculative finding was a
dispatch-count story), and an injected extra dispatch fails the gate
deterministically."""
import json
import threading
import urllib.request

import numpy
import pytest

import veles_tpu as vt
from veles_tpu.telemetry import (Cost, CostModel, gate_counters,
                                 peak_bf16_flops)
from veles_tpu.telemetry import chrome_trace, spans
from veles_tpu.telemetry.counters import counters
from veles_tpu.telemetry.cost import cost_of_fn

from conftest import import_model


# -- counters ----------------------------------------------------------------

def test_counter_registry_thread_safety():
    counters.reset()
    n_threads, n_incs = 8, 2000

    def worker():
        for _ in range(n_incs):
            counters.inc("t_threads_total")
            counters.inc("t_bytes_total", 3)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert counters.get("t_threads_total") == n_threads * n_incs
    assert counters.get("t_bytes_total") == 3 * n_threads * n_incs


def test_counter_delta_and_prometheus_text():
    counters.reset()
    before = counters.snapshot()
    counters.inc("veles_dispatches_total", 4)
    delta = counters.delta(before)
    assert delta == {"veles_dispatches_total": 4}
    text = counters.prometheus_text()
    assert "# HELP veles_dispatches_total" in text
    assert "# TYPE veles_dispatches_total counter" in text
    assert "veles_dispatches_total 4" in text
    assert text.endswith("\n")


# -- spans -------------------------------------------------------------------

def test_span_nesting_counters_and_jsonl_roundtrip(tmp_path):
    spans.recorder.clear()
    counters.reset()
    with spans.span("outer", who="test"):
        with spans.span("inner"):
            counters.inc("veles_dispatches_total", 2)
    recs = spans.recorder.records()
    inner = next(r for r in recs if r["name"] == "inner")
    outer = next(r for r in recs if r["name"] == "outer")
    assert inner["depth"] == 1 and outer["depth"] == 0
    assert inner["parent"] == outer["sid"]
    assert outer["parent"] is None
    # counter deltas ride the span (both levels see the incs)
    assert inner["counters"]["veles_dispatches_total"] == 2
    assert outer["counters"]["veles_dispatches_total"] == 2
    assert outer["who"] == "test"
    assert outer["dur"] >= inner["dur"] >= 0
    # JSONL round trip
    path = str(tmp_path / "spans.jsonl")
    assert spans.recorder.to_jsonl(path) == len(recs)
    loaded = spans.read_jsonl(path)
    assert [r["name"] for r in loaded] == [r["name"] for r in recs]
    roots = spans.tree(loaded)
    assert [r["name"] for r in roots] == ["outer"]
    assert [c["name"] for c in roots[0]["children"]] == ["inner"]


def test_span_decorator_and_exception_close():
    spans.recorder.clear()

    @spans.spanned("decorated")
    def boom():
        raise RuntimeError("x")

    with pytest.raises(RuntimeError):
        boom()
    rec = spans.recorder.records("decorated")[0]
    assert rec["error"] is True
    # nesting stack recovered: a fresh span lands at depth 0
    with spans.span("after"):
        pass
    assert spans.recorder.records("after")[0]["depth"] == 0


def test_spans_config_switch_disables_all_recording():
    """root.common.trace.spans = False must silence EVERY span site
    (the recorder gates centrally), not just Unit.run."""
    from veles_tpu.config import root
    spans.recorder.clear()
    prev = root.common.trace.get("spans", True)
    root.common.trace.spans = False
    try:
        with spans.span("direct"):
            pass
        wf = _chain_workflow()
        wf.initialize()
        wf.run()
        assert spans.recorder.records() == []
    finally:
        root.common.trace.spans = prev
    with spans.span("after_reenable"):
        pass
    assert [r["name"] for r in spans.recorder.records()] == \
        ["after_reenable"]


def test_span_sink_streams_jsonl(tmp_path):
    path = str(tmp_path / "stream.jsonl")
    spans.recorder.set_sink(path)
    try:
        with spans.span("streamed"):
            pass
    finally:
        spans.recorder.set_sink(None)
    loaded = spans.read_jsonl(path)
    assert [r["name"] for r in loaded] == ["streamed"]


# -- cost model --------------------------------------------------------------

def test_cost_model_mfu_on_known_matmul():
    import jax.numpy as jnp
    m, k, n = 128, 256, 64
    c = cost_of_fn(lambda a, b: a @ b,
                   jnp.ones((m, k), jnp.float32),
                   jnp.ones((k, n), jnp.float32))
    assert c.source == "xla"
    assert c.flops == 2.0 * m * k * n          # the textbook number
    assert c.bytes_accessed >= 4 * (m * k + k * n + m * n)
    cm = CostModel(peak_flops=1e12)
    cm.record("mm", c, executions=10)
    # 10 executions of 4.19 MFLOP in 42µs on a 1 TFLOP/s chip = 100 %
    seconds = 10 * c.flops / 1e12
    assert cm.mfu("mm", seconds) == pytest.approx(1.0)
    assert cm.mfu("mm", seconds * 2) == pytest.approx(0.5)
    rep = cm.report({"mm": seconds})
    assert rep["mm"]["mfu"] == pytest.approx(1.0)
    assert rep["mm"]["executions"] == 10
    assert rep["mm"]["flops"] == pytest.approx(10 * c.flops)


def test_cost_arithmetic_and_peak_lookup():
    a = Cost(100.0, 50.0, 7.0)
    b = Cost(10.0, 2.0, 9.0)
    s = a + b
    assert (s.flops, s.bytes_accessed, s.peak_memory) == (110.0, 52.0, 9.0)
    assert a.scaled(3).flops == 300.0
    assert a.scaled(3).peak_memory == 7.0      # per-execution, not summed
    assert a.arithmetic_intensity == 2.0
    assert peak_bf16_flops("TPU v5 lite") == 197e12
    assert peak_bf16_flops("TPU v5p") == 459e12
    assert peak_bf16_flops("weird") == 275e12


def test_pallas_analytic_fallbacks():
    from veles_tpu.ops.flash_attention import analytic_cost as flash_cost
    from veles_tpu.ops.fused_fc import analytic_cost as fc_cost
    full = flash_cost(2, 1024, 8, 64)
    causal = flash_cost(2, 1024, 8, 64, causal=True)
    train = flash_cost(2, 1024, 8, 64, causal=True, train=True)
    assert full.flops == 4.0 * 2 * 8 * 1024 * 1024 * 64
    assert causal.flops == pytest.approx(full.flops / 2)
    assert train.flops == pytest.approx(causal.flops * 3.5)
    assert full.source == "analytic"
    fc = fc_cost([(784, 100), (100, 10)], mb=100, steps=600)
    mm = 784 * 100 + 100 * 10
    assert fc.flops >= 600 * 3 * 2 * 100 * mm
    assert fc.bytes_accessed > 600 * 100 * 784 * 4   # the batch stream
    assert fc.peak_memory > 0


def test_kernel_cost_collector():
    """Pallas kernels note analytic costs at trace time; program_cost
    collects them during its re-lower (the custom call is opaque to
    XLA's cost model). flash_attention's entry calls note_kernel_cost
    — here the collector contract is exercised directly since the
    kernel itself cannot lower in this environment."""
    from veles_tpu.telemetry.cost import (collecting_kernel_costs,
                                          note_kernel_cost)
    note_kernel_cost(Cost(1.0))          # no active collector: no-op
    with collecting_kernel_costs() as notes:
        note_kernel_cost(Cost(10.0, 5.0))
        note_kernel_cost(Cost(2.0, 1.0))
    assert [c.flops for c in notes] == [10.0, 2.0]
    with collecting_kernel_costs() as notes2:
        pass
    assert notes2 == []


# -- workflow integration ----------------------------------------------------

class _Rec(vt.Unit):
    hide_from_registry = True

    def run(self):
        counters.inc("veles_dispatches_total")


def _chain_workflow(n=3):
    wf = vt.Workflow(name="telemetry_wf")
    prev = wf.start_point
    for i in range(n):
        u = _Rec(wf, name="u%d" % i)
        u.link_from(prev)
        prev = u
    wf.end_point.link_from(prev)
    return wf


def test_unit_runs_record_spans():
    spans.recorder.clear()
    wf = _chain_workflow()
    wf.initialize()
    wf.run()
    unit_spans = spans.recorder.records("unit.run")
    names = {r["unit"] for r in unit_spans}
    assert {"u0", "u1", "u2"} <= names
    # each unit.run span nests under the workflow.run span
    run_span = spans.recorder.records("workflow.run")[-1]
    u0 = next(r for r in unit_spans if r["unit"] == "u0")
    assert u0["parent"] == run_span["sid"]
    assert u0["counters"]["veles_dispatches_total"] == 1
    assert run_span["steps"] >= 3


def test_trace_export_cli_from_real_workflow_run(tmp_path):
    """Acceptance gate: `veles-tpu trace export` on a real workflow
    run's span JSONL produces schema-valid Chrome trace_event JSON."""
    spans.recorder.clear()
    wf = _chain_workflow()
    wf.initialize()
    wf.run()
    jsonl = str(tmp_path / "run.jsonl")
    assert spans.recorder.to_jsonl(jsonl) > 0
    out = str(tmp_path / "trace.json")
    from veles_tpu.__main__ import main
    assert main(["trace", "export", jsonl, out]) == 0
    with open(out) as f:
        doc = json.load(f)
    assert chrome_trace.validate(doc) == []
    events = doc["traceEvents"]
    x_names = [e["name"] for e in events if e["ph"] == "X"]
    assert "unit.run" in x_names and "workflow.run" in x_names
    # counter tracks emitted for the dispatch counter
    assert any(e["ph"] == "C" and
               e["name"] == "veles_dispatches_total" for e in events)
    # span args survive into the trace
    unit_ev = next(e for e in events
                   if e["ph"] == "X" and e["name"] == "unit.run")
    assert "unit" in unit_ev["args"]


def test_trace_export_cli_rejects_empty_input(tmp_path):
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    from veles_tpu.__main__ import main
    assert main(["trace", "export", str(empty),
                 str(tmp_path / "o.json")]) == 1


def test_trace_export_tolerates_truncated_lines(tmp_path, caplog):
    """A --trace-file killed mid-append ends in half a JSON record;
    `trace export` must skip the bad line with a counted warning and
    export the readable spans instead of raising on json.loads."""
    import logging
    spans.recorder.clear()
    with spans.span("kept_a"):
        pass
    with spans.span("kept_b"):
        pass
    jsonl = tmp_path / "torn.jsonl"
    assert spans.recorder.to_jsonl(str(jsonl)) == 2
    with open(jsonl, "a") as f:
        f.write('{"name": "torn", "ts": 123.0, "du')   # mid-write cut
    with caplog.at_level(logging.WARNING,
                         logger="veles_tpu.telemetry"):
        recs = spans.read_jsonl(str(jsonl))
    assert [r["name"] for r in recs] == ["kept_a", "kept_b"]
    assert any("skipped 1 malformed" in rec.message
               for rec in caplog.records)
    out = tmp_path / "trace.json"
    from veles_tpu.__main__ import main
    assert main(["trace", "export", str(jsonl), str(out)]) == 0
    with open(out) as f:
        doc = json.load(f)
    assert chrome_trace.validate(doc) == []
    names = [e["name"] for e in doc["traceEvents"] if e["ph"] == "X"]
    assert names == ["kept_a", "kept_b"]


def test_chrome_trace_validator_catches_violations():
    assert chrome_trace.validate([]) != []
    assert chrome_trace.validate({"traceEvents": "nope"}) != []
    bad = {"traceEvents": [{"name": "x", "ph": "Z", "ts": 0, "dur": 0}]}
    assert any("phase" in e for e in chrome_trace.validate(bad))
    bad = {"traceEvents": [{"name": "x", "ph": "X", "ts": -1, "dur": 0}]}
    assert any("ts" in e for e in chrome_trace.validate(bad))
    good = {"traceEvents": [{"name": "x", "ph": "X", "ts": 0.0,
                             "dur": 1.0, "pid": 1, "tid": 2,
                             "args": {}}]}
    assert chrome_trace.validate(good) == []


# -- decode dispatch accounting (round-5 regression lock) --------------------

@pytest.fixture(scope="module")
def tiny_lm():
    lm = import_model("char_lm")
    from veles_tpu import prng
    prng.seed_all(1234)
    wf = lm.build_workflow(epochs=1, minibatch_size=64, n_blocks=1,
                           dim=16, n_train=256, n_valid=64)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    return lm, wf


def test_cached_decode_is_one_dispatch_per_scan(tiny_lm):
    """The cached sampler (prefill + lax.scan) is ONE device program:
    decoding N tokens must cost exactly one decode dispatch, not one
    per token — the dispatch-count discipline behind the round-5
    speculative finding, now framework-observable."""
    lm, wf = tiny_lm
    rng = numpy.random.RandomState(7)
    prompt = list(lm.make_corpus(rng, lm.SEQ_LEN // 2))
    for n_new in (8, 24):
        before = counters.snapshot()
        lm.generate(wf, prompt, n_new, temperature=0)
        delta = counters.delta(before)
        assert delta.get("veles_decode_dispatches_total") == 1, delta
        assert delta.get("veles_decode_tokens_total") == n_new
    # dispatches per token stays well under the 1.0 gate ceiling
    before = counters.snapshot()
    lm.generate(wf, prompt, 24, temperature=0)
    delta = counters.delta(before)
    dpt = (delta["veles_decode_dispatches_total"]
           / delta["veles_decode_tokens_total"])
    assert dpt <= 1.0 / 24 + 1e-9


def test_train_step_cost_report(tiny_lm):
    """The TrainStep's own program cost (the CostModel source bench.py
    reads): real FLOPs from Compiled.cost_analysis at the recorded arg
    shapes."""
    _, wf = tiny_lm
    rep = wf.train_step.cost_report()
    assert rep is not None
    cost = rep["cost"]
    assert cost.flops > 0
    assert cost.bytes_accessed > 0
    assert cost.source == "xla"
    # MFU math composes: tiny model for 1 s on a full chip is ~0
    assert 0 <= cost.mfu(1.0, peak_flops=197e12) < 1e-3


# -- counter gate ------------------------------------------------------------

def test_gate_passes_on_equal_and_fails_on_extra_dispatch():
    """The gate reads window-independent rates only (raw totals scale
    with how many epochs fit a time-boxed window)."""
    base = {"dispatches": 120, "dispatches_per_epoch": 3.0,
            "compiles": 0, "flops_per_dispatch": 1e9,
            "bytes_per_dispatch": 5e6}
    assert gate_counters(dict(base), dict(base)) == []
    # an extra dispatch per epoch = a real program regression
    worse = dict(base, dispatches_per_epoch=4.0)
    failures = gate_counters(worse, base)
    assert len(failures) == 1 and "dispatches_per_epoch" in failures[0]
    # raw total growth alone (longer/faster window) does NOT fail
    assert gate_counters(dict(base, dispatches=900), base) == []
    # recompile where the baseline had none
    assert gate_counters({"compiles": 1}, {"compiles": 0}) != []
    # tolerated growth under the ratio rules
    assert gate_counters(dict(base, flops_per_dispatch=1.04e9),
                         base) == []


def test_gate_decode_dispatches_per_token_ceiling():
    failures = gate_counters({"dispatches_per_token": 2.0}, {},
                             max_dispatches_per_token=1.0)
    assert failures and "dispatches_per_token" in failures[0]
    assert gate_counters({"dispatches_per_token": 0.04}, {},
                         max_dispatches_per_token=1.0) == []


def test_bench_gate_docs_fails_on_injected_regression():
    """Acceptance gate: bench.py's counter-gate mode fails on an
    injected extra-dispatch regression (and passes unchanged docs)."""
    import os
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    try:
        import bench
    finally:
        sys.path.remove(repo)
    baseline = {
        "counters": {"dispatches": 100, "dispatches_per_epoch": 1.0,
                     "compiles": 0, "flops_per_dispatch": 1e10,
                     "bytes_per_dispatch": 2e7},
        "extras": [{"metric": "lm",
                    "counters": {"dispatches_per_epoch": 1.0,
                                 "compiles": 0}}],
    }
    same = json.loads(json.dumps(baseline))
    assert bench.gate_docs(baseline, same) == []
    worse = json.loads(json.dumps(baseline))
    # injected extra-dispatch regression (per epoch, so it cannot be
    # explained away by window length)
    worse["counters"]["dispatches_per_epoch"] = 2.0
    failures = bench.gate_docs(baseline, worse)
    assert failures and "headline" in failures[0]
    worse2 = json.loads(json.dumps(baseline))
    worse2["extras"][0]["counters"]["compiles"] = 3
    failures = bench.gate_docs(baseline, worse2)
    assert failures and failures[0].startswith("lm:")
    # a decode section above the per-token ceiling fails absolutely
    worse3 = json.loads(json.dumps(baseline))
    worse3["counters"]["dispatches_per_token"] = 1.5
    baseline3 = json.loads(json.dumps(baseline))
    baseline3["counters"]["dispatches_per_token"] = 0.05
    assert bench.gate_docs(baseline3, worse3) != []
    # sections without counters (legacy baselines) are ignored
    assert bench.gate_docs({}, worse) == []


def test_bench_gate_cli(tmp_path):
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base = {"counters": {"dispatches_per_epoch": 1.0, "compiles": 0},
            "extras": []}
    cur = {"counters": {"dispatches_per_epoch": 1.2, "compiles": 0},
           "extras": []}
    bp, cp = tmp_path / "b.json", tmp_path / "c.json"
    bp.write_text(json.dumps(base))
    cp.write_text(json.dumps(cur))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # the gate runs every live drill (fleet failover, overload burst,
    # watchtower storm, ...) — budget for the whole acceptance suite,
    # not just the doc comparison
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "gate",
         str(bp), str(cp)], capture_output=True, text=True, env=env,
        timeout=420)
    assert r.returncode == 1
    assert "GATE FAIL" in r.stderr
    cp.write_text(json.dumps(base))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "gate",
         str(bp), str(cp)], capture_output=True, text=True, env=env,
        timeout=420)
    assert r.returncode == 0, r.stderr


# -- /metrics endpoints ------------------------------------------------------

def test_web_status_metrics_endpoint():
    from veles_tpu.web_status import WebStatusServer
    counters.inc("veles_dispatches_total")
    server = WebStatusServer(port=0).start()
    try:
        url = "http://127.0.0.1:%d/metrics" % server.port
        with urllib.request.urlopen(url, timeout=30) as r:
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            body = r.read().decode()
        assert "veles_dispatches_total" in body
        assert "veles_status_workflows 0" in body
    finally:
        server.stop()
