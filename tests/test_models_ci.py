"""Model-zoo CI: the four BASELINE configs under convergence gates.

The reference CI ran Znicz model regression tests (SURVEY.md §4,
veles/tests/jenkins.xml); these tests are that net for the TPU build:
every model in models/ is imported, built through its public
build_workflow(), trained on a shrunken surrogate dataset, and held to a
convergence threshold — so a regression in any model (layer wiring, loss,
decision plumbing, loader contract) fails CI instead of shipping silently.

Thresholds are calibrated against the deterministic synthetic surrogates
(veles_tpu/datasets.py:_synthetic_images — class-template data that simple
models genuinely learn). They are intentionally loose: the gate is
"learns at all", not "matches the published anchor" (which needs the real
datasets, absent in-image; BASELINE.md documents the anchors).
"""
import os

import numpy
import pytest

import veles_tpu as vt
from veles_tpu import datasets, prng
from veles_tpu.datasets import _synthetic_images

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


from conftest import import_model as _import_model  # noqa: E402


def _dev():
    return vt.XLADevice(mesh_axes={"data": 1})


def test_mnist_converges(monkeypatch):
    prng.seed_all(1234)
    """BASELINE config #1 (MNIST-784 FC). Real anchor: 1.48 % val error."""
    monkeypatch.setattr(
        datasets, "load_mnist",
        lambda flat=True: _synthetic_images((28, 28), 10, 3000, 600,
                                            flat, key="mnist"))
    mnist = _import_model("mnist")
    wf = mnist.build_workflow(epochs=4, minibatch_size=100)
    wf.initialize(device=_dev())
    wf.run()
    res = wf.gather_results()
    assert res["epochs"] == 4
    assert res["best_err"] < 0.12, res


def test_cifar_converges(monkeypatch):
    prng.seed_all(1234)
    """BASELINE config #4 (CIFAR conv net). Real anchor: 17.21 % val
    error. The surrogate shrinks to 16x16 so the conv stack stays CI-
    affordable on the CPU mesh; the gate is "clearly beats chance"
    (90 % error for 10 classes), which catches any wiring/loss/GD
    regression in the conv path."""
    monkeypatch.setattr(
        datasets, "load_cifar10",
        lambda n_train=50000, n_test=10000: _synthetic_images(
            (16, 16, 3), 10, 960, 120, flat=False, key="cifar10"))
    cifar = _import_model("cifar")
    wf = cifar.build_workflow(epochs=10, minibatch_size=60, lr=0.05)
    wf.initialize(device=_dev())
    wf.run()
    res = wf.gather_results()
    assert res["epochs"] == 10
    # chance is 0.9; a broken conv/gd path stays there (calibrated best
    # on this surrogate: ~0.62 at epoch 8)
    assert res["best_err"] < 0.7, res


def test_imagenet_ae_converges(monkeypatch):
    prng.seed_all(1234)
    """BASELINE config #3 (conv autoencoder). Real anchor: 0.5478 RMSE on
    the MNIST AE variant. Gate: reconstruction RMSE drops below the
    do-nothing bound (std of the surrogate pixels ~0.29) and improves
    across epochs."""
    monkeypatch.setattr(
        datasets, "load_cifar10",
        lambda n_train=50000, n_test=10000: _synthetic_images(
            (32, 32, 3), 10, 1000, 200, flat=False, key="cifar10"))
    ae = _import_model("imagenet_ae")
    wf = ae.build_workflow(epochs=3, minibatch_size=50, lr=0.02)
    wf.initialize(device=_dev())
    wf.run()
    res = wf.gather_results()
    assert res["epochs"] == 3
    assert res["best_rmse"] < 0.25, res


def test_genre_lstm_converges():
    prng.seed_all(1234)
    """BASELINE config #5 (LSTM genre recognition). The loader is already
    synthetic-by-design (frequency/phase signatures per genre)."""
    genre = _import_model("genre_recognition")
    wf = genre.build_workflow(epochs=3, minibatch_size=60, lr=0.05,
                              hidden=32)
    wf.initialize(device=_dev())
    wf.run()
    res = wf.gather_results()
    assert res["epochs"] == 3
    assert res["best_err"] < 0.35, res


def test_lines_converges():
    prng.seed_all(1234)
    """Lines demo (reference zoo member; generator-backed, so its
    accuracy is a REAL anchor, not a surrogate proxy). Exercises the
    per-layer adam solver in CI."""
    lines = _import_model("lines")
    wf = lines.build_workflow(epochs=5, minibatch_size=80,
                              n_train=960, n_valid=240)
    wf.initialize(device=_dev())
    wf.run()
    res = wf.gather_results()
    assert res["best_err"] < 0.1, res


def test_tiny_transformer_converges():
    prng.seed_all(1234)
    """Transformer zoo member (generated order-classification task —
    position-dependent, so pos_embedding + attention are load-bearing;
    a real anchor like lines)."""
    tt = _import_model("tiny_transformer")
    wf = tt.build_workflow(epochs=15, minibatch_size=64, n_blocks=2,
                           n_train=2048, n_valid=512)
    wf.initialize(device=_dev())
    wf.run()
    res = wf.gather_results()
    # chance is 0.5; calibrated best on this task: ~0.27 at epoch 14
    assert res["best_err"] < 0.35, res


def test_kanji_converges():
    prng.seed_all(1234)
    """Kanji zoo member (reference: "MSE NN with standard workflow",
    algorithms doc :29): the ONE model exercising loader-provided
    regression targets (target_mode='targets' / FullBatchLoaderMSE)
    through StandardWorkflow. Generator-backed — a real anchor.
    Do-nothing bound: predicting 0 gives RMSE ~0.5 on the stroke
    templates; calibrated best on 6 epochs: ~0.12."""
    kanji = _import_model("kanji")
    wf = kanji.build_workflow(epochs=6, minibatch_size=80,
                              n_train=960, n_valid=240)
    wf.initialize(device=_dev())
    wf.run()
    res = wf.gather_results()
    assert res["best_rmse"] < 0.3, res


def test_video_ae_converges():
    prng.seed_all(1234)
    """VideoAE zoo member (reference AE family, algorithms doc :70):
    the fully-connected bottleneck AE (imagenet_ae covers conv/deconv).
    Do-nothing bound: frame std ~0.22; calibrated best on 6 epochs:
    ~0.13."""
    vae = _import_model("video_ae")
    wf = vae.build_workflow(epochs=6, minibatch_size=64,
                            n_train=768, n_valid=192)
    wf.initialize(device=_dev())
    wf.run()
    res = wf.gather_results()
    assert res["best_rmse"] < 0.19, res


def test_kohonen_demo_organizes():
    prng.seed_all(1234)
    """DemoKohonen zoo member (algorithms doc :89): custom (non-GD)
    workflow loop around the batch-SOM trainer. The map must organize:
    final quantization error below the cluster noise radius (0.25)."""
    kd = _import_model("kohonen_demo")
    wf = kd.build_workflow(epochs=8, minibatch_size=100, n_train=600)
    wf.initialize(device=_dev())
    wf.run()
    res = wf.gather_results()
    assert res["epochs"] == 8
    assert res["final_qerr"] < 0.25, res
    # error actually fell as the map organized
    assert res["qerr_history"][-1] < res["qerr_history"][0]


def test_alexnet_converges():
    prng.seed_all(1234)
    """AlexNet zoo member (algorithms doc :49), authored via the
    mcdnnic_topology shorthand — this gate covers that authoring path
    end-to-end. Calibrated: 0 % by epoch 3 on the surrogate."""
    m = _import_model("alexnet")
    wf = m.build_workflow(epochs=5, minibatch_size=64,
                          n_train=960, n_valid=240)
    wf.initialize(device=_dev())
    wf.run()
    res = wf.gather_results()
    assert res["best_err"] < 0.2, res


def test_stl10_converges():
    prng.seed_all(1234)
    """STL-10 variant of the conv family (anchor: 35.10 % on real data,
    algorithms doc :51): same caffe-quick stack, STL geometry. CI
    shrinks to 32 px; the gate is "clearly beats chance"."""
    cifar = _import_model("cifar")
    wf = cifar.build_stl10_workflow(epochs=10, minibatch_size=60, lr=0.05,
                                    image_size=32, n_train=960,
                                    n_valid=120)
    wf.initialize(device=_dev())
    wf.run()
    res = wf.gather_results()
    assert res["best_err"] < 0.7, res


def test_bench_workflow_builds(monkeypatch):
    """The compute-bound bench surface (bench.py extras[0]) must keep
    building and running a WHOLE epoch under the exact bench knobs
    (mixed_precision + bf16 dataset). One dispatch is not enough: the
    epoch's first dispatch is the VALID eval — an AMP regression in the
    conv/deconv TRAIN grad shipped invisibly behind a single-dispatch
    gate once (preferred_element_type f32 broke the conv transpose rule
    on bf16 operands)."""
    from veles_tpu.config import root
    root.common.engine.mixed_precision = True
    root.common.engine.dataset_dtype = "bfloat16"
    try:
        ae = _import_model("imagenet_ae")
        wf = ae.build_bench_workflow(image_size=16, minibatch_size=8,
                                     n_train=32, n_valid=8)
        wf.initialize(device=_dev())
        loader = wf.loader
        assert loader.total_samples == 40
        assert wf.train_step.mixed_precision
        # a full epoch: the valid-eval dispatch AND the train dispatch
        # — through bench.py's own epoch_runner, the exact surface this
        # gate protects
        import bench
        served = bench.epoch_runner(wf)()
        assert served == 40
        import jax
        jax.block_until_ready(wf.train_step.params)
    finally:
        root.common.engine.mixed_precision = False
        root.common.engine.dataset_dtype = None
    assert wf.train_step.params


def test_char_lm_converges():
    """Language-model zoo member (new capability: per-token CE via
    loss_function='softmax_seq'). The grammar's optimal per-token error
    is ~0.2-0.3 (stochastic branches); a broken LM path sits near
    1 - 1/16 = 0.94."""
    prng.seed_all(1234)
    lm = _import_model("char_lm")
    wf = lm.build_workflow(epochs=6, minibatch_size=64, n_blocks=1,
                           dim=32, n_train=768, n_valid=128)
    wf.initialize(device=_dev())
    wf.run()
    res = wf.gather_results()
    assert res["best_err"] < 0.45, res


def test_char_lm_generates_grammar():
    """Sampling: a briefly trained LM's GREEDY continuations follow the
    grammar's dominant transition (s -> s+1 mod 8 within 0..7)."""
    prng.seed_all(1234)
    lm = _import_model("char_lm")
    wf = lm.build_workflow(epochs=6, minibatch_size=64, n_blocks=1,
                           dim=32, n_train=768, n_valid=128)
    wf.initialize(device=_dev())
    wf.run()
    rng = numpy.random.RandomState(3)
    prompt = list(lm.make_corpus(rng, lm.SEQ_LEN))
    toks = lm.generate(wf, prompt, 64, temperature=0)
    seq = prompt[-1:] + toks
    follow = sum(1 for a, b in zip(seq, seq[1:])
                 if (a < 8 and b == (a + 1) % 8) or (a >= 8 and b == 0))
    # dominant transitions fire ~80-90% in the grammar; chance ~1/16
    assert follow / (len(seq) - 1) > 0.5, (follow, seq)


def test_genetic_example_solves():
    """GeneticExample zoo member (reference samples/GeneticExample —
    the GA engine used directly on plain objectives): the integer-gene
    knapsack must reach its known optimum; continuous Rosenbrock must
    get into the valley (random init scatters f across ~1e2-1e3)."""
    ge = _import_model("genetic_example")
    take, value = ge.solve_knapsack()
    assert value == 15.0, (take, value)
    _genes, f = ge.solve_rosenbrock(generations=60)
    assert f < 0.5, f


def test_lm_bench_workflow_builds():
    """The LM throughput-bench surface (bench.py extras[1]) must keep
    building and running one block dispatch."""
    lm = _import_model("char_lm")
    wf = lm.build_bench_workflow(seq_len=32, dim=32, n_blocks=2,
                                 ffn_hidden=64, n_heads=4, vocab=32,
                                 minibatch_size=8, n_train=32, n_valid=8,
                                 epochs_per_dispatch=2)
    wf.initialize(device=_dev())
    wf.loader.run()
    wf.train_step.run()
    assert wf.loader.block_length == 2
    assert wf.train_step.params
