"""Operator tooling: interaction shell, compare_snapshots,
generate_frontend, sound loader, numpy JSON encoder (reference:
veles/scripts/, veles/interaction.py, veles/tests/test_snd_file_loader.py)."""
import json
import os
import wave

import numpy
import pytest

import veles_tpu as vt
from veles_tpu.json_encoders import NumpyJSONEncoder, dumps
from veles_tpu.loader.sound import SoundFileLoader, decode_audio
from veles_tpu.scripts import compare_snapshots, generate_frontend


# -- interaction shell -------------------------------------------------------

class RecordingShell(vt.Shell):
    hide_from_registry = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.opened = []

    def open_console(self, ns, banner):
        self.opened.append((sorted(ns), banner))


def test_shell_activation_paths(tmp_path):
    wf = vt.Workflow(name="t")
    shell = RecordingShell(wf, name="shell")
    shell.run()
    assert not shell.opened                 # idle by default
    shell.activate()
    shell.run()
    assert len(shell.opened) == 1
    names, banner = shell.opened[0]
    assert "workflow" in names and "np" in names
    shell.run()
    assert len(shell.opened) == 1           # one-shot
    trigger = tmp_path / "poke"
    shell.trigger_file = str(trigger)
    trigger.touch()
    shell.run()
    assert len(shell.opened) == 2
    assert not trigger.exists()             # consumed


def test_shell_every_n():
    wf = vt.Workflow(name="t")
    shell = RecordingShell(wf, every=3)
    for _ in range(7):
        shell.process()                     # increments run_count after run
    assert len(shell.opened) == 2           # at run_count 3 and 6


def test_shell_namespace_has_units():
    wf = vt.Workflow(name="t")
    vt.TrivialUnit(wf, name="my unit")
    shell = RecordingShell(wf)
    ns = shell.namespace()
    assert ns["my_unit"] is wf["my unit"]


# -- compare_snapshots -------------------------------------------------------

def test_compare_snapshots_logic():
    a = {"__units__": {"fc": {"weights": numpy.ones((2, 2)),
                              "bias": numpy.zeros(2)}},
         "__meta__": {"checksum": "abc"}}
    b = {"__units__": {"fc": {"weights": numpy.ones((2, 2)) + 1e-9,
                              "bias": numpy.zeros(3)}},
         "__meta__": {"checksum": "xyz"}}
    rows = {r["path"]: r for r in compare_snapshots.compare(a, b)}
    assert rows["/__units__/fc/weights"]["status"] == "close"
    assert rows["/__units__/fc/bias"]["status"] == "shape"
    assert rows["/__meta__/checksum"]["status"] == "differs"


def test_compare_snapshots_cli(tmp_path):
    """End to end over real snapshot files."""
    from veles_tpu.snapshotter import Snapshotter
    from veles_tpu import nn
    from veles_tpu.memory import Array

    def make(seed, directory):
        wf = vt.Workflow(name="w")
        fc = nn.All2All(wf, output_sample_shape=3, name="fc")
        rng = numpy.random.RandomState(seed)
        fc.input = Array(rng.rand(4, 5).astype(numpy.float32))
        fc.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        snap = Snapshotter(wf, prefix="s", directory=str(directory),
                           interval=1)
        snap.run()
        return snap.destination

    pa = make(0, tmp_path / "a")
    pb = make(1, tmp_path / "b")
    assert compare_snapshots.main([pa, pb]) == 1        # differ
    assert compare_snapshots.main([pa, pa]) == 0        # identical


# -- generate_frontend -------------------------------------------------------

def test_generate_frontend(tmp_path):
    out = str(tmp_path / "frontend.html")
    assert generate_frontend.main(["-o", out]) == 0
    page = open(out).read()
    assert "--backend" in page and "--mesh" in page
    assert "command composer" in page.lower()


# -- sound loader ------------------------------------------------------------

def make_wav(path, seconds=0.5, rate=8000, freq=440.0):
    t = numpy.arange(int(seconds * rate)) / rate
    samples = (numpy.sin(2 * numpy.pi * freq * t) * 32000).astype("<i2")
    with wave.open(str(path), "wb") as f:
        f.setnchannels(1)
        f.setsampwidth(2)
        f.setframerate(rate)
        f.writeframes(samples.tobytes())


def test_decode_audio_wav(tmp_path):
    p = tmp_path / "tone.wav"
    make_wav(p)
    data, rate = decode_audio(str(p))
    assert rate == 8000 and data.shape == (4000, 1)
    assert abs(data).max() <= 1.0
    # dominant frequency ≈ 440 Hz
    spec = numpy.abs(numpy.fft.rfft(data[:, 0]))
    peak_hz = spec.argmax() * rate / len(data)
    assert abs(peak_hz - 440.0) < 5


def test_sound_file_loader(tmp_path):
    wavs = []
    for i, freq in enumerate((220.0, 880.0)):
        p = tmp_path / ("f%d.wav" % i)
        make_wav(p, freq=freq)
        wavs.append(str(p))
    loader = SoundFileLoader(None, files=wavs, labels=[0, 1],
                             window=256, stride=256, minibatch_size=16)
    loader.load_data()
    n = loader.total_samples
    assert n > 0
    assert loader.original_data.shape == (n, 256)
    assert set(numpy.unique(loader.original_labels)) == {0, 1}
    assert loader.class_lengths[1] > 0          # validation split present
    assert loader.sample_rate == 8000


# -- JSON encoder ------------------------------------------------------------

def test_numpy_json_encoder():
    blob = dumps({"a": numpy.float32(1.5), "b": numpy.arange(3),
                  "c": numpy.bool_(True), "d": {numpy.int64(3)},
                  "e": b"bytes"})
    back = json.loads(blob)
    assert back == {"a": 1.5, "b": [0, 1, 2], "c": True, "d": [3],
                    "e": "bytes"}
    assert json.loads(json.dumps({"x": numpy.int32(7)},
                                 cls=NumpyJSONEncoder)) == {"x": 7}


def test_compare_snapshots_missing_unit_fails():
    """Structural asymmetry (only_a/only_b) must exit nonzero."""
    a = {"__units__": {"fc": {"w": numpy.ones(2)}}}
    b = {"__units__": {}}
    rows = compare_snapshots.compare(a, b)
    assert any(r["status"] == "only_a" for r in rows)


def test_sound_loader_rejects_mixed_rates(tmp_path):
    from veles_tpu.error import VelesError
    a, b = tmp_path / "a.wav", tmp_path / "b.wav"
    make_wav(a, rate=8000)
    make_wav(b, rate=16000)
    loader = SoundFileLoader(None, files=[str(a), str(b)], labels=[0, 1],
                             window=256, minibatch_size=4)
    with pytest.raises(VelesError):
        loader.load_data()


def test_frontend_js_safe_embedding(tmp_path):
    """Help strings with < > & must reach the page JS-escaped, without
    HTML entities."""
    out = str(tmp_path / "f.html")
    generate_frontend.main(["-o", out])
    page = open(out).read()
    assert "&lt;" not in page.split("<script>")[1].split("</script>")[0]


def test_generate_docs_manual():
    """The generated unit-reference manual (reference analogue:
    docs/generate_units_args.py): every registry entry appears, every
    entry carries a description (own docstring, variant-of pointer, or
    module blurb)."""
    import re
    from veles_tpu.scripts import generate_docs
    from veles_tpu.units import UnitRegistry
    text = generate_docs.generate()
    assert "# Unit reference" in text
    for mapping in UnitRegistry.mapping:
        assert "### `%s`" % mapping in text, mapping
    entries = re.findall(
        r"### `[^`]+` — \w+\n\n(.*?)(?=\n### |\n## |\Z)", text, re.S)
    assert len(entries) >= len(UnitRegistry.mapping) - 15
    for e in entries:
        first = e.strip().splitlines()[0]
        assert not first.startswith("Arguments:"), first[:60]
    # the committed manual must BE the generator's output — that is the
    # whole no-drift claim (regenerate with
    # `python -m veles_tpu.scripts.generate_docs` after registry edits)
    committed = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "units_reference.md")
    with open(committed) as fin:
        assert fin.read() == text, \
            "docs/units_reference.md is stale — regenerate it"


def test_profile_step_produces_trace(tmp_path):
    """scripts/profile_step.py: the per-op profiling tool (reference had
    only wall-clock unit timers, SURVEY §5.1) must emit an XPlane dir."""
    import subprocess
    import sys
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "profile_step.py")
    out = str(tmp_path / "trace")
    r = subprocess.run(
        [sys.executable, script, "--model", "lines",
         "--dispatches", "1", "--out", out],
        capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    produced = [f for _r, _d, fs in os.walk(out) for f in fs]
    assert any(f.endswith(".xplane.pb") for f in produced), produced


def test_frontend_wizard_serving_round_trip():
    """The live wizard (reference veles/__main__.py:258-332 tornado
    composer): GET the page and options, POST a state dict, get back
    the assembled command VALIDATED by the real parser."""
    import json as _json
    import urllib.request
    httpd = generate_frontend.serve(port=0)
    import threading
    th = threading.Thread(target=httpd.serve_forever, daemon=True)
    th.start()
    base = "http://127.0.0.1:%d" % httpd.server_address[1]
    try:
        page = urllib.request.urlopen(base + "/").read().decode()
        assert "command composer" in page
        opts = _json.loads(urllib.request.urlopen(
            base + "/options").read())
        assert any(o["flag"] == "--optimize" for o in opts)

        def post(state):
            req = urllib.request.Request(
                base + "/compose", data=_json.dumps(state).encode(),
                headers={"Content-Type": "application/json"})
            return _json.loads(urllib.request.urlopen(req).read())

        out = post({"model": "models/lines.py", "optimize": "4:2",
                    "optimize_workers": 4, "backend": "cpu",
                    "config_list": ["root.lines.epochs=2"]})
        assert out["valid"], out
        assert "--optimize 4:2" in out["cmd"]
        assert "--optimize-workers 4" in out["cmd"]
        assert out["argv"][0] == "models/lines.py"   # positionals first
        assert "root.lines.epochs=2" in out["argv"][1]
        # a bad value must come back as a parser error, not a 500
        bad = post({"model": "m.py", "optimize_workers": "lots"})
        assert not bad["valid"]
        assert "lots" in bad["error"] or "invalid" in bad["error"]
        # zero is a VALUE (rank 0 is the coordinator), not "unset"
        zero = post({"model": "m.py", "process_id": 0,
                     "num_processes": 2,
                     "coordinator": "127.0.0.1:5000"})
        assert "--process-id 0" in zero["cmd"], zero
        # positionals bind in PARSER order regardless of JSON key order
        swapped = post({"config_list": ["root.x=1"], "model": "m.py"})
        assert swapped["argv"][0] == "m.py", swapped
        # cmd is shell-safe: spaces survive as one token
        spacey = post({"model": "my models/m.py"})
        assert "'my models/m.py'" in spacey["cmd"], spacey
        assert spacey["argv"][0] == "my models/m.py"
    finally:
        httpd.shutdown()
        httpd.server_close()
