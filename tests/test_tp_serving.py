"""Tensor-parallel sharded decode over the mesh (serving/engine.py
``tp=`` + parallel/compat.py shard_map): N chips serve as ONE logical
replica — attention heads and K/V pages shard over the head axis,
FC/embedding weights go column/row-parallel with one psum per block,
and every request-plane structure (page tables, shared mask, slot
metadata, PrefixCache) stays replicated host data indexing LOGICAL
pages.

The contract proven here: sharding is a pure execution detail —
every decode mode (greedy / sample / speculative / beam), chunked
prefill, prefix adoption AND the copy-on-write path return tokens
id-EXACT vs the unsharded engine, on 2- and 4-device CPU virtual
meshes (the TPU_VISIBLE_CHIPS seam from veles_tpu/__init__.py; the
mesh children run in subprocesses because the seam must be set before
jax initializes). Admission math and page gauges count logical pages
once per slice (kv_pool_bytes shard-agnostic, kv_pool_bytes_per_shard
= pool / tp), the ``veles_tp_*`` counters move only for sharded
engines, and the ``serve.replica_death`` journaled failover drill
stays token-level lossless when the survivor is a mesh slice.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable


def _run_child(code, chips, timeout=480):
    """Run ``code`` in a fresh interpreter with the TPU_VISIBLE_CHIPS
    seam pinned BEFORE veles_tpu/jax import — the only way a pytest
    process (whose jax already materialized 1 CPU device) can drive a
    multi-device mesh. The child prints ONE json line on stdout."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", TPU_VISIBLE_CHIPS=chips,
               VELES_REPO=REPO)
    env.pop("XLA_FLAGS", None)          # the seam owns device count
    env.pop("VELES_FAULTS", None)
    proc = subprocess.run([PY, "-c", code], env=env, timeout=timeout,
                          capture_output=True, text=True)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-4000:])
    return json.loads(proc.stdout.strip().splitlines()[-1])


# -- the mesh child: every serving mode, solo vs sharded -----------------------

MESH_CHILD = r"""
import json, os, sys
sys.path.insert(0, os.environ["VELES_REPO"])
sys.path.insert(0, os.path.join(os.environ["VELES_REPO"], "tests"))

import numpy
import veles_tpu as vt
from veles_tpu import prng
from veles_tpu.serving import ContinuousEngine
from veles_tpu.serving.engine import make_request
from veles_tpu.telemetry.counters import counters
from conftest import import_model

lm = import_model("char_lm")
prng.seed_all(971)
wf = lm.build_workflow(epochs=1, minibatch_size=64, n_blocks=2,
                       dim=32, n_train=256, n_valid=64)
wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
wf.run()
prng.seed_all(437)
draft = lm.build_workflow(epochs=1, minibatch_size=64, n_blocks=1,
                          dim=16, n_train=256, n_valid=64)
draft.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
draft.run()

import jax
tp = jax.device_count()


def prompt(seed, length=10):
    return [int(t) for t in
            lm.make_corpus(numpy.random.RandomState(seed), length)]


# all four decode modes; every prompt prefills CHUNKED
# (prefill_chunk=8), the last two adopt the cached `shared` prefix
shared = prompt(9, 16)
reqs = [make_request(prompt(1, 10), 8),
        make_request(prompt(2, 7), 8, temperature=0.8, seed=5,
                     mode="sample"),
        make_request(prompt(3, 7), 9, mode="speculative", gamma=3),
        make_request(prompt(4, 6), 8, mode="beam", beam=2),
        make_request(shared + prompt(5, 4), 6),
        make_request(shared + prompt(6, 3), 6)]
# COW trigger: a FULL-prompt match on the cached (page-aligned)
# 16-token `shared` prefix — at least one token must re-prefill, and
# the engine must recompute that last position into a COPY of the
# final shared page (copy-on-write), never into the shared page
cow_req = make_request(list(shared), 6)


def run(tp_n):
    d0 = counters.get("veles_tp_dispatches_total")
    e0 = counters.get("veles_tp_engines_total")
    c0 = counters.get("veles_prefix_cow_copies_total")
    eng = ContinuousEngine(wf, max_slots=5, buckets=(8, 16, 32),
                           max_context=64, page_size=8, spec_gamma=3,
                           beam_width=2, draft=draft,
                           prefix_cache=True, prefill_chunk=8,
                           tp=tp_n, name="eng_tp%d" % tp_n).start()
    try:
        out = eng.serve([dict(r) for r in reqs])
        out += eng.serve([dict(cow_req)])
        st = eng.stats()
    finally:
        eng.stop()
    return out, st, {
        "dispatches": counters.get("veles_tp_dispatches_total") - d0,
        "engines": counters.get("veles_tp_engines_total") - e0,
        "cow": counters.get("veles_prefix_cow_copies_total") - c0}


out_solo, st_solo, mv_solo = run(1)
out_tp, st_tp, mv_tp = run(tp)

print(json.dumps({
    "devices": tp,
    "equal": out_solo == out_tp,
    "n_out": len(out_tp),
    "tp_stat": st_tp["tp"], "solo_tp_stat": st_solo["tp"],
    "prefix_requests": st_tp["prefix_requests"],
    "chunk_dispatches": st_tp["chunk_dispatches"],
    "solo_moved": mv_solo, "tp_moved": mv_tp,
    "kv_solo": st_solo["kv_pool_bytes"],
    "kv_tp": st_tp["kv_pool_bytes"],
    "kv_shard_solo": st_solo["kv_pool_bytes_per_shard"],
    "kv_shard_tp": st_tp["kv_pool_bytes_per_shard"],
}))
"""


def _assert_mesh_doc(doc, devices):
    assert doc["devices"] == devices, doc
    assert doc["equal"] is True, doc
    assert doc["n_out"] == 7
    assert doc["tp_stat"] == devices and doc["solo_tp_stat"] == 1
    # every prompt prefilled chunked; the shared-prefix pair adopted;
    # the shorter-prompt request took the copy-on-write path — in the
    # SOLO run and the SHARDED run alike (same logical request plane)
    assert doc["chunk_dispatches"] > 0
    assert doc["prefix_requests"] >= 2
    assert doc["solo_moved"]["cow"] >= 1
    assert doc["tp_moved"]["cow"] >= 1
    # tp counters: one engine, live sharded dispatches — and ZERO
    # leakage into the unsharded run
    assert doc["solo_moved"]["engines"] == 0
    assert doc["solo_moved"]["dispatches"] == 0
    assert doc["tp_moved"]["engines"] == 1
    assert doc["tp_moved"]["dispatches"] > 0
    # page gauges are LOGICAL (shard-agnostic admission math): the
    # sharded pool reports the same logical bytes, and the per-shard
    # gauge is exactly the slice's cut of it
    assert doc["kv_solo"] == doc["kv_tp"]
    assert doc["kv_shard_solo"] == doc["kv_solo"]
    assert doc["kv_shard_tp"] == doc["kv_tp"] // devices


def test_tp2_mesh_id_exact_all_modes():
    """THE acceptance drill (2-chip virtual mesh): greedy, sampled,
    speculative, beam, chunked prefill, prefix adoption and prefix-COW
    all return tokens id-exact vs the unsharded engine; page gauges
    stay logical; veles_tp_* counters move only for the slice."""
    _assert_mesh_doc(_run_child(MESH_CHILD, "0,1"), 2)


@pytest.mark.slow
def test_tp4_mesh_id_exact_all_modes():
    """Same drill at tp=4 — the mesh width the satellite names; slow
    lane (a second ~30 s training + double-serve child)."""
    _assert_mesh_doc(_run_child(MESH_CHILD, "0,1,2,3"), 4)


# -- sharded failover: the survivor is a mesh slice ----------------------------

FAILOVER_CHILD = r"""
import json, os, sys, urllib.error, urllib.request
sys.path.insert(0, os.environ["VELES_REPO"])
sys.path.insert(0, os.path.join(os.environ["VELES_REPO"], "tests"))

import veles_tpu as vt
from veles_tpu import prng
from veles_tpu.config import root
from veles_tpu.nn import sampling
from veles_tpu.serving.router import FleetRouter
from veles_tpu.telemetry.counters import counters
from conftest import import_model


def post(url, payload, timeout=120.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


lm = import_model("char_lm")
prng.seed_all(2025)
wf = lm.build_workflow(epochs=1, minibatch_size=32, n_blocks=1,
                       dim=32, n_train=64, n_valid=32)
wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))

prompt = [2, 4, 1, 3, 5]
n_new = 12
solo = sampling.generate(wf, prompt, n_new, temperature=0.8, seed=17)

# both replicas are tp=2 mesh slices (the CLI path: --serve-tp)
root.common.serving.tp = 2
apis = [vt.GenerationAPI(wf, port=0, engine="continuous", max_slots=2,
                         buckets=(8, 16, 32), max_context=48,
                         name="tpgasp_%d" % i) for i in range(2)]
for api in apis:
    api.initialize()
router = FleetRouter(["127.0.0.1:%d" % api.port for api in apis],
                     probe_interval=0.2, failure_threshold=1,
                     retry_budget=2, attempt_timeout=60.0,
                     request_timeout=120.0, name="tpgasp_router").start()
try:
    # replica = mesh slice on the probe surface: /readyz carries the
    # slice shape, the roster counts chips once per slice
    with urllib.request.urlopen(
            "http://127.0.0.1:%d/readyz" % apis[0].port,
            timeout=30) as r:
        ready = json.loads(r.read())
    # warm both replicas' programs outside the armed window
    for api in apis:
        code, _ = post("http://127.0.0.1:%d/generate" % api.port,
                       {"prompt": prompt, "n_new": 2, "mode": "sample",
                        "temperature": 0.8, "seed": 17})
        assert code == 200, code
    import time
    deadline = time.time() + 15
    while time.time() < deadline:
        eps = router.roster()["endpoints"]
        if all(e.get("tp_devices") == 2 for e in eps):
            break
        time.sleep(0.25)
    roster = router.roster()["endpoints"]
    gauges = router.gauges()
    ra = counters.get("veles_resume_attempts_total")
    fo = counters.get("veles_router_failovers_total")
    os.environ["VELES_FAULTS"] = \
        "serve.replica_death:raise:after=4,times=1"
    code, body = post(
        "http://127.0.0.1:%d/generate" % router.port,
        {"prompt": prompt, "n_new": n_new, "mode": "sample",
         "temperature": 0.8, "seed": 17})
    os.environ.pop("VELES_FAULTS", None)
    print(json.dumps({
        "code": code,
        "tokens_equal": body.get("tokens") == solo,
        "resumed_from": body.get("resumed_from", 0),
        "resume_attempts": counters.get(
            "veles_resume_attempts_total") - ra,
        "failovers": counters.get("veles_router_failovers_total") - fo,
        "readyz_tp": ready.get("tp"),
        "roster_tp": [e.get("tp_devices") for e in roster],
        "router_chips": gauges["veles_router_chips"][0],
        "router_replicas": gauges["veles_router_replicas"][0],
    }))
finally:
    router.stop()
    for api in apis:
        api.stop()
"""


@pytest.mark.slow
def test_sharded_failover_resume_token_level_lossless():
    """The serve.replica_death journal proof with mesh-slice replicas:
    the dying tp=2 replica's 503 gasp makes the router RESUME on the
    surviving tp=2 slice, and the stitched answer equals the
    uninterrupted (unsharded!) solo decode exactly. The probe surface
    reports replica = mesh slice: /readyz rides {"tp": {devices, axis}}
    and the roster counts each slice once (2 replicas) while
    veles_router_chips says 4."""
    doc = _run_child(FAILOVER_CHILD, "0,1", timeout=480)
    assert doc["code"] == 200, doc
    assert doc["tokens_equal"] is True, doc
    assert doc["resumed_from"] >= 1            # resumed, not redone
    assert doc["resume_attempts"] >= 1
    assert doc["failovers"] >= 1
    assert doc["readyz_tp"] == {"devices": 2, "axis": "model"}
    assert doc["roster_tp"] == [2, 2]
    assert doc["router_replicas"] == 2         # slices, not chips
    assert doc["router_chips"] == 4


# -- in-process units: the replicated-host-data arithmetic ---------------------

def test_per_shard_kv_heads():
    """Per-chip K/V pool geometry: heads divide exactly or the engine
    must refuse (a ragged shard cannot serve id-exact)."""
    from veles_tpu.serving.pages import per_shard_kv_heads
    assert per_shard_kv_heads(8) == 8
    assert per_shard_kv_heads(8, 2) == 4
    assert per_shard_kv_heads(8, 8) == 1
    with pytest.raises(ValueError, match="ragged"):
        per_shard_kv_heads(6, 4)


def test_fleet_merge_folds_slice_width_into_chips():
    """fleet.aggregate must NOT read a tp=4 slice as 4 replicas: the
    veles_serving_tp gauge folds into veles_fleet_chips instead of the
    generic sum, and replica-count gauges stay per-endpoint."""
    from veles_tpu.telemetry import fleet
    a = {"counters": {"veles_requests_total": 3.0},
         "gauges": {"veles_serving_tp": 4.0,
                    "veles_serving_slots": 2.0}}
    b = {"counters": {"veles_requests_total": 2.0},
         "gauges": {"veles_serving_tp": 1.0,
                    "veles_serving_slots": 2.0}}
    merged = fleet.merge([a, b])
    assert merged["counters"]["veles_requests_total"] == 5.0
    assert merged["gauges"]["veles_fleet_chips"] == 5.0
    assert merged["gauges"]["veles_serving_slots"] == 4.0
    # the raw width gauge never leaks into the merged view (a summed
    # "tp" across a fleet is the meaningless number this guards)
    assert "veles_serving_tp" not in merged["gauges"]
    # an old replica without the gauge still counts one chip? No —
    # chips are only counted where the gauge is exported; a fleet of
    # pre-tp replicas simply has no chip gauge
    assert fleet.merge([{"counters": {}, "gauges": {}}])[
        "gauges"] == {}


def test_router_counts_slice_once_and_chips_gauge():
    """Roster arithmetic without HTTP: each Replica defaults to one
    chip, a probed slice width lands in snapshot()["tp_devices"], and
    gauges() sums chips while replicas stay slice-count."""
    from veles_tpu.serving.router import FleetRouter
    router = FleetRouter(["127.0.0.1:1", "127.0.0.1:2"],
                         name="tp_roster_unit")
    try:
        assert [r.tp_devices for r in router.replicas] == [1, 1]
        router.replicas[0].tp_devices = 4
        snap = router.replicas[0].snapshot()
        assert snap["tp_devices"] == 4
        g = router.gauges()
        assert g["veles_router_replicas"][0] == 2
        assert g["veles_router_chips"][0] == 5
    finally:
        router.stop()


def test_health_info_rides_readyz_without_shadowing():
    """set_info publishes discovery facts on /readyz (the router probe
    learns the slice shape for free), retracts on None, and can never
    shadow the probe's own status/components keys."""
    from veles_tpu.resilience import health
    health.mark_ready("tp_info_unit")
    try:
        health.set_info("tp", {"devices": 2, "axis": "model"})
        health.set_info("status", "evil")      # must NOT shadow
        code, payload = health.readyz()
        assert code == 200
        assert payload["tp"] == {"devices": 2, "axis": "model"}
        assert payload["status"] == "ok"
        health.set_info("tp")                  # retract
        health.set_info("status")
        _code, payload = health.readyz()
        assert "tp" not in payload
    finally:
        health.set_info("tp")
        health.set_info("status")
        health.forget("tp_info_unit")
