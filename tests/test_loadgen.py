"""The load/chaos harness (ISSUE 17): seeded workload synthesis,
timed chaos storms over the fault plane, and the SLO verdict.

Everything here is deterministic and fleet-free: the workload is a
seeded program (same knobs + seed -> same arrivals, same bodies), a
storm is a ``window=T0:T1`` fault clause armed via ``VELES_FAULTS``
and ALWAYS restored, and the verdict folds explicit aggregates into
explicit pass/fail checks. The one live :class:`LoadGen` run targets
a dead port — a refused connection is data (the errors lane), and it
exercises the whole open-loop dispatch/join machinery in
milliseconds. The full fleet-under-storm drill lives in bench.py's
``gate_overload``.
"""
import os

import pytest

from veles_tpu.error import VelesError
from veles_tpu.loadgen import (ChaosStorm, LoadGen, StormPlan,
                               Workload, aggregate, parse_storm,
                               percentile, verdict)
from veles_tpu.resilience.faults import plane
from veles_tpu.telemetry.counters import counters


# -- workload synthesis: seeded, bounded, labeled ----------------------------

def test_workload_is_deterministic_per_seed():
    knobs = dict(n_requests=40, rate=50.0, shape="diurnal",
                 min_prompt=4, max_prompt=32, sample_fraction=0.5,
                 stream_fraction=0.5, seed=7)
    a, b = Workload(**knobs), Workload(**knobs)
    assert a.arrivals() == b.arrivals()
    assert a.requests() == b.requests()
    c = Workload(**{**knobs, "seed": 8})
    assert c.requests() != a.requests()


def test_workload_shape_changes_arrivals_not_bodies():
    base = dict(n_requests=30, rate=50.0, seed=3)
    steady = Workload(shape="steady", **base)
    burst = Workload(shape="burst", **base)
    assert steady.requests() == burst.requests()
    assert steady.arrivals() != burst.arrivals()


def test_workload_prompt_lengths_and_labels_bounded():
    wl = Workload(n_requests=200, min_prompt=4, max_prompt=16,
                  batch_fraction=0.5, sample_fraction=0.5,
                  deadline_ms=250.0, vocab=64, seed=5)
    arrivals, bodies = wl.arrivals(), wl.requests()
    assert len(arrivals) == len(bodies) == 200
    assert arrivals == sorted(arrivals) and arrivals[0] > 0.0
    seen = {"interactive": 0, "batch": 0}
    for body in bodies:
        assert 4 <= len(body["prompt"]) <= 16
        assert all(0 < t < 64 for t in body["prompt"])
        assert body["mode"] in ("greedy", "sample")
        if body["mode"] == "sample":
            assert body["temperature"] > 0 and body["seed"] >= 1
        seen[body["priority"]] += 1
        # deadline_ms rides ONLY the protected class
        if body["priority"] == "interactive":
            assert body["deadline_ms"] == 250.0
        else:
            assert "deadline_ms" not in body
    assert seen["interactive"] and seen["batch"]


def test_workload_shared_prefixes():
    wl = Workload(n_requests=50, min_prompt=8, max_prompt=24,
                  shared_fraction=1.0, prefix_len=6, n_prefixes=2,
                  seed=9)
    bodies = wl.requests()
    openings = {tuple(b["prompt"][:6]) for b in bodies}
    assert len(openings) == 2        # every prompt opens with one of
    # the n_prefixes fixed system prompts


def test_workload_rejects_bad_knobs():
    with pytest.raises(ValueError):
        Workload(shape="tsunami")
    with pytest.raises(ValueError):
        Workload(rate=0.0)
    with pytest.raises(ValueError):
        Workload(min_prompt=8, max_prompt=4)


# -- storms: window-clause round trip + arm/restore --------------------------

def test_parse_storm_round_trip():
    storm = parse_storm("dispatch:raise:window=3:7,p=0.5")
    assert (storm.point, storm.action) == ("dispatch", "raise")
    assert storm.window == (3, 7) and storm.p == 0.5
    assert parse_storm(storm.spec()).spec() == storm.spec()


@pytest.mark.parametrize("text", [
    "dispatch:raise",                      # no window: not a storm
    "dispatch:raise:window=1:3;download:raise:window=1:3",   # two
    "nosuchpoint:raise:window=1:3",        # unknown injection point
])
def test_parse_storm_rejects(text):
    with pytest.raises((ValueError, VelesError)):
        parse_storm(text)


def test_chaos_storm_validates_eagerly():
    with pytest.raises(VelesError):
        ChaosStorm("nosuchpoint")


def test_storm_plan_arms_and_restores_env():
    far = ChaosStorm("dispatch", window=(10 ** 6, 10 ** 6 + 1))
    prior_env = os.environ.get("VELES_FAULTS")
    try:
        os.environ.pop("VELES_FAULTS", None)
        plane.configure()
        before = counters.get("veles_loadgen_storms_total")
        with StormPlan([far]):
            assert os.environ["VELES_FAULTS"] == far.spec()
            assert far.spec() in plane.current_spec()
        assert "VELES_FAULTS" not in os.environ
        assert counters.get("veles_loadgen_storms_total") \
            - before == 1
        # a pre-existing spec is COMBINED for the run, then restored
        os.environ["VELES_FAULTS"] = \
            "download:raise:window=10000000:10000001"
        plane.configure()
        with StormPlan([far]):
            armed = os.environ["VELES_FAULTS"]
            assert armed.startswith("download:") \
                and armed.endswith(far.spec())
        assert os.environ["VELES_FAULTS"].startswith("download:")
    finally:
        if prior_env is None:
            os.environ.pop("VELES_FAULTS", None)
        else:
            os.environ["VELES_FAULTS"] = prior_env
        plane.configure()


def test_storm_plan_empty_is_a_noop():
    prior = os.environ.get("VELES_FAULTS")
    with StormPlan([]):
        assert os.environ.get("VELES_FAULTS") == prior


# -- aggregates + verdict: pure folds ----------------------------------------

def test_percentile_nearest_rank():
    assert percentile([], 0.99) is None
    assert percentile([7.0], 0.5) == 7.0
    vals = list(range(1, 101))
    assert percentile(vals, 0.0) == 1
    assert percentile(vals, 0.5) == 51   # nearest rank on 100 points
    assert percentile(vals, 1.0) == 100


def _rec(priority="interactive", status=200, error=None, shed=False,
         ttft_s=None, e2e_s=0.1, tokens=8, stream=False):
    return {"priority": priority, "status": status, "error": error,
            "shed": shed, "ttft_s": ttft_s, "e2e_s": e2e_s,
            "tokens": tokens, "stream": stream}


def test_aggregate_classifies_ok_shed_error():
    records = [
        _rec(ttft_s=0.02),
        _rec(ttft_s=0.04),
        _rec(status=503, error="shed", shed=True, tokens=0),
        _rec(status=None, error="URLError: refused", tokens=0),
        _rec(priority="batch", tokens=16),
        _rec(priority="batch", status=503, error="shed", shed=True,
             tokens=0),
    ]
    agg = aggregate(records, wall=2.0)
    inter, batch = agg["interactive"], agg["batch"]
    assert (inter["offered"], inter["ok"], inter["shed"],
            inter["errors"]) == (4, 2, 1, 1)
    assert (batch["offered"], batch["ok"], batch["shed"],
            batch["errors"]) == (2, 1, 1, 0)
    # tokens (and goodput) count ONLY answered-200 work
    assert inter["tokens"] == 16 and batch["tokens"] == 16
    assert agg["goodput_tokens_per_s"] == pytest.approx(16.0)
    assert inter["ttft_p50_ms"] == pytest.approx(20.0)
    assert inter["ttft_p99_ms"] == pytest.approx(40.0)


def _report(server_ttft=None, client_ttft=None, offered=10, shed=0,
            errors=0, goodput=100.0):
    inter = {"offered": offered, "ok": offered - shed - errors,
             "shed": shed, "errors": errors, "tokens": 0,
             "ttft_p50_ms": client_ttft, "ttft_p99_ms": client_ttft,
             "e2e_p50_ms": 1.0, "e2e_p99_ms": 1.0}
    return {"aggregates": {
        "interactive": inter,
        "batch": dict(inter, offered=0, ok=0),
        "goodput_tokens_per_s": goodput,
        "server_ttft_p99_ms": server_ttft,
        "server_queue_wait_p99_ms": None,
    }}


def test_verdict_prefers_server_ttft_and_bounds():
    # server histogram wins over the (worse) client observation
    v = verdict(_report(server_ttft=100.0, client_ttft=5000.0),
                slo_ttft_ms=1000.0)
    assert v["pass"] is True
    # no server signal: judged on the client-side number
    v = verdict(_report(server_ttft=None, client_ttft=5000.0),
                slo_ttft_ms=1000.0)
    assert v["pass"] is False
    names = {c["name"]: c for c in v["checks"]}
    assert names["interactive_ttft_p99_ms"]["ok"] is False


def test_verdict_interactive_loss_and_goodput_bounds():
    v = verdict(_report(offered=20, shed=1, errors=1),
                max_interactive_loss=0.05)
    names = {c["name"]: c for c in v["checks"]}
    assert names["interactive_loss_fraction"]["observed"] == 0.1
    assert v["pass"] is False
    assert verdict(_report(offered=20, shed=1),
                   max_interactive_loss=0.05)["pass"] is True
    v = verdict(_report(goodput=3.0), min_goodput_tokens_per_s=5.0)
    names = {c["name"]: c for c in v["checks"]}
    assert names["goodput_tokens_per_s"]["ok"] is False


# -- the driver itself: open loop against a dead port ------------------------

def test_loadgen_records_a_dead_fleet_as_errors():
    """A refused connection is DATA: every offered request answers as
    an error (not a shed), the report stays whole, and the counters
    move — the machinery the live drill (bench.py gate_overload)
    builds on."""
    wl = Workload(n_requests=4, rate=1000.0, min_prompt=4,
                  max_prompt=4, n_new=1, seed=2)
    gen = LoadGen("http://127.0.0.1:9", wl, timeout=5.0)
    off0 = counters.get("veles_loadgen_requests_total")
    err0 = counters.get("veles_loadgen_errors_total")
    report = gen.run()
    assert report["offered"] == report["answered"] == 4
    agg = report["aggregates"]
    total = (agg["interactive"]["errors"] + agg["batch"]["errors"])
    assert total == 4
    assert agg["interactive"]["shed"] == agg["batch"]["shed"] == 0
    assert counters.get("veles_loadgen_requests_total") - off0 == 4
    assert counters.get("veles_loadgen_errors_total") - err0 == 4
    assert verdict(report, max_interactive_loss=0.0)["pass"] is False
