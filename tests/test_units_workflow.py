"""Mirrors reference veles/tests/test_units.py + test_workflow.py scope:
gates, links, scheduler order, loops via Repeater, initialize re-queue."""
import pytest

from veles_tpu import Bool, Bug, Repeater, TrivialUnit, Unit, Workflow


class Recorder(Unit):
    hide_from_registry = True

    def __init__(self, workflow, log, **kwargs):
        super().__init__(workflow, **kwargs)
        self.log = log

    def run(self):
        self.log.append(self.name)


def build_chain(names):
    wf = Workflow(name="wf")
    log = []
    units = [Recorder(wf, log, name=n) for n in names]
    prev = wf.start_point
    for u in units:
        u.link_from(prev)
        prev = u
    wf.end_point.link_from(prev)
    return wf, log, units


def test_linear_chain_runs_in_order():
    wf, log, _ = build_chain("abc")
    wf.initialize()
    wf.run()
    assert log == ["a", "b", "c"]
    assert bool(wf.stopped)


def test_diamond_gate_waits_for_all():
    wf = Workflow(name="wf")
    log = []
    a = Recorder(wf, log, name="a")
    b = Recorder(wf, log, name="b")
    c = Recorder(wf, log, name="c")
    j = Recorder(wf, log, name="join")
    a.link_from(wf.start_point)
    b.link_from(a)
    c.link_from(a)
    j.link_from(b)
    j.link_from(c)
    wf.end_point.link_from(j)
    wf.initialize()
    wf.run()
    assert log.index("join") > max(log.index("b"), log.index("c"))
    assert log.count("join") == 1


def test_gate_block_stops_propagation():
    wf, log, units = build_chain("abc")
    units[1].gate_block <<= True
    wf.initialize()
    wf.run()
    assert log == ["a"]
    assert not bool(wf.stopped)  # EndPoint never reached


def test_gate_skip_propagates_without_running():
    wf, log, units = build_chain("abc")
    units[1].gate_skip <<= True
    wf.initialize()
    wf.run()
    assert log == ["a", "c"]
    assert bool(wf.stopped)


def test_repeater_loop_with_decision():
    wf = Workflow(name="loop")
    log = []
    rep = Repeater(wf)

    class Counter(Recorder):
        def __init__(self, workflow, log, **kw):
            super().__init__(workflow, log, **kw)
            self.complete = Bool(False)
            self.n = 0

        def run(self):
            super().run()
            self.n += 1
            if self.n >= 3:
                self.complete <<= True

    cnt = Counter(wf, log, name="cnt")
    rep.link_from(wf.start_point)
    cnt.link_from(rep)
    rep.link_from(cnt)               # back edge
    rep.gate_block = cnt.complete    # stop looping when complete
    wf.end_point.link_from(cnt)
    wf.end_point.gate_block = ~cnt.complete
    wf.initialize()
    wf.run()
    assert log == ["cnt"] * 3
    assert bool(wf.stopped)


def test_demand_initialize_requeue():
    wf = Workflow(name="wf")

    class Producer(TrivialUnit):
        def initialize(self, **kw):
            res = super().initialize(**kw)
            self.out = 5
            return res

    class Consumer(TrivialUnit):
        def __init__(self, workflow, **kw):
            super().__init__(workflow, **kw)
            self.demand("inp")

    p = Producer(wf, name="p")
    c = Consumer(wf, name="c")
    # deliberately link c earlier in dependency order than p
    c.link_from(wf.start_point)
    p.link_from(c)
    wf.end_point.link_from(p)
    c.link_attrs(p, ("inp", "out"))
    wf.initialize()
    assert c.inp == 5


def test_initialize_deadlock_detected():
    wf = Workflow(name="wf")

    class Needy(TrivialUnit):
        def __init__(self, workflow, **kw):
            super().__init__(workflow, **kw)
            self.demand("never_set")

    n = Needy(wf, name="n")
    n.link_from(wf.start_point)
    wf.end_point.link_from(n)
    with pytest.raises(Bug):
        wf.initialize()


def test_max_steps_guard():
    wf = Workflow(name="wf", max_steps=10)
    rep = Repeater(wf)
    rep.link_from(wf.start_point)
    a = TrivialUnit(wf, name="a")
    a.link_from(rep)
    rep.link_from(a)
    wf.initialize()
    with pytest.raises(Bug):
        wf.run()


def test_graph_and_results_and_stats():
    wf, log, units = build_chain("ab")
    units[0].get_metric_values = lambda: {"m": 1}
    wf.initialize()
    wf.run()
    dot = wf.generate_graph()
    assert '"a" -> "b"' in dot
    assert wf.gather_results() == {"m": 1}
    assert wf.checksum()
    stats = wf.print_stats()
    assert any(name == "a" for _, name, _ in stats)


def test_workflow_getitem_and_container():
    wf, _, units = build_chain("ab")
    assert wf["a"] is units[0]
    assert len(wf) == 4  # a, b + start + end
    wf.del_ref(units[0])
    assert len(wf) == 3
