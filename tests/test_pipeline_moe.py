"""Pipeline parallelism (gpipe over the 'pipeline' axis) and MoE
(expert-axis sharding) on the 8-device virtual mesh."""
import jax
import jax.numpy as jnp
import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn
from veles_tpu.memory import Array
from veles_tpu.parallel.pipeline import gpipe, microbatch, unmicrobatch


def pipe_mesh(n):
    from jax.sharding import Mesh
    return Mesh(numpy.asarray(jax.devices()[:n]).reshape(n),
                ("pipeline",))


def stage(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def make_params(n, d, seed=0):
    rng = numpy.random.RandomState(seed)
    return {"w": jnp.asarray(rng.randn(n, d, d).astype("float32") * 0.3),
            "b": jnp.asarray(rng.randn(n, d).astype("float32") * 0.1)}


def sequential(params, x, n):
    r = x
    for i in range(n):
        r = stage({"w": params["w"][i], "b": params["b"][i]}, r)
    return r


def test_gpipe_matches_sequential():
    n, d = 4, 8
    params = make_params(n, d)
    x = jnp.asarray(numpy.random.RandomState(1)
                    .randn(16, d).astype("float32"))
    y = unmicrobatch(gpipe(stage, params, microbatch(x, 8),
                           pipe_mesh(n)))
    ref = sequential(params, x, n)
    numpy.testing.assert_allclose(numpy.asarray(y), numpy.asarray(ref),
                                  rtol=1e-5, atol=1e-6)


def test_gpipe_differentiable():
    n, d = 4, 6
    params = make_params(n, d, seed=2)
    x = jnp.asarray(numpy.random.RandomState(3)
                    .randn(8, d).astype("float32"))
    mesh = pipe_mesh(n)

    def loss(p):
        return (unmicrobatch(gpipe(stage, p, microbatch(x, 4),
                                   mesh)) ** 2).sum()

    def loss_ref(p):
        return (sequential(p, x, n) ** 2).sum()

    g = jax.grad(loss)(params)
    gr = jax.grad(loss_ref)(params)
    for k in ("w", "b"):
        numpy.testing.assert_allclose(numpy.asarray(g[k]),
                                      numpy.asarray(gr[k]),
                                      rtol=1e-4, atol=1e-5)


def test_microbatch_validation():
    with pytest.raises(ValueError):
        microbatch(jnp.zeros((10, 3)), 4)


def test_moe_oracle_agreement():
    prev = vt.root.common.engine.compute_dtype
    vt.root.common.engine.compute_dtype = "float32"
    try:
        wf = vt.Workflow(name="t")
        u = nn.MoEFFN(wf, n_experts=4, hidden=16)
        x = numpy.random.RandomState(0).randn(6, 8).astype("float32")
        u.input = Array(x)
        u.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        u.xla_run()
        y = numpy.asarray(u.output.map_read())
        y_np = u.numpy_apply(u.params_np(), x)
        numpy.testing.assert_allclose(y, y_np, rtol=1e-4, atol=1e-5)
        assert y.shape == x.shape
    finally:
        vt.root.common.engine.compute_dtype = prev


def test_moe_trains_in_standard_workflow_on_expert_mesh():
    """dp×ep mesh: MoE params shard over 'expert', training converges."""
    from veles_tpu.loader import FullBatchLoader

    class Toy(FullBatchLoader):
        hide_from_registry = True

        def load_data(self):
            rng = numpy.random.RandomState(0)
            x = rng.rand(256, 8).astype("float32")
            y = (x[:, 0] > x[:, 4]).astype("int32")
            self.create_originals(x, y)
            self.class_lengths = [0, 64, 192]

    wf = nn.StandardWorkflow(
        name="moe",
        layers=[{"type": "moe_ffn", "n_experts": 4, "hidden": 16,
                 "learning_rate": 0.1},
                {"type": "softmax", "output_sample_shape": 2,
                 "learning_rate": 0.1}],
        loader_unit=Toy(None, minibatch_size=32), loss_function="softmax",
        decision_config=dict(max_epochs=12))
    wf.initialize(device=vt.XLADevice(
        mesh_axes={"data": 2, "expert": 4}))
    w1 = wf.train_step.params["moe_ffn0"]["w1"]
    assert not w1.sharding.is_fully_replicated      # expert-sharded
    wf.run()
    assert wf.gather_results()["best_err"] < 0.4


def test_gpipe_rejects_wrong_stage_count():
    params = make_params(8, 4)      # 8 stages on a 4-device mesh
    x = jnp.zeros((8, 4))
    with pytest.raises(ValueError):
        gpipe(stage, params, microbatch(x, 4), pipe_mesh(4))


def test_sparse_moe_oracle_agreement():
    """top_k sparse dispatch: jnp path vs numpy oracle."""
    prev = vt.root.common.engine.compute_dtype
    vt.root.common.engine.compute_dtype = "float32"
    try:
        wf = vt.Workflow(name="ts")
        u = nn.MoEFFN(wf, n_experts=4, hidden=16, top_k=2,
                      capacity_factor=2.0)
        x = numpy.random.RandomState(1).randn(10, 8).astype("float32")
        u.input = Array(x)
        u.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        u.xla_run()
        y = numpy.asarray(u.output.map_read())
        y_np = u.numpy_apply(u.params_np(), x)
        numpy.testing.assert_allclose(y, y_np, rtol=1e-4, atol=1e-5)
    finally:
        vt.root.common.engine.compute_dtype = prev


def test_sparse_topk_full_matches_dense():
    """top_k == n_experts with ample capacity selects every expert with
    the full softmax weights — must equal the dense mixture."""
    prev = vt.root.common.engine.compute_dtype
    vt.root.common.engine.compute_dtype = "float32"
    try:
        wf = vt.Workflow(name="teq")
        u = nn.MoEFFN(wf, n_experts=3, hidden=8, top_k=3,
                      capacity_factor=4.0)
        x = numpy.random.RandomState(2).randn(12, 6).astype("float32")
        u.input = Array(x)
        u.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        params = u.params_np()
        y_sparse = u.numpy_apply(params, x)
        u.top_k = 0
        y_dense = u.numpy_apply(params, x)
        numpy.testing.assert_allclose(y_sparse, y_dense, rtol=1e-5,
                                      atol=1e-6)
    finally:
        vt.root.common.engine.compute_dtype = prev


def test_sparse_moe_capacity_drops_tokens():
    """Overflowing tokens get zero combine weight (residual carries
    them) — outputs stay finite, dropped rows are exactly zero."""
    wf = vt.Workflow(name="tc")
    u = nn.MoEFFN(wf, n_experts=2, hidden=8, top_k=1,
                  capacity_factor=0.25)
    x = numpy.zeros((8, 6), "float32")      # all tokens identical
    x[:] = numpy.random.RandomState(3).randn(6)
    u.input = Array(x)
    u.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    y = u.numpy_apply(u.params_np(), x)
    assert numpy.isfinite(y).all()
    # identical tokens all route to one expert; capacity 1 → first kept
    nonzero_rows = (numpy.abs(y).sum(-1) > 1e-9).sum()
    assert nonzero_rows == 1, nonzero_rows


def test_sparse_moe_trains():
    from veles_tpu.loader import FullBatchLoader

    class L(FullBatchLoader):
        hide_from_registry = True

        def load_data(self):
            rng = numpy.random.RandomState(5)
            centers = rng.randn(3, 8) * 3
            y = rng.randint(0, 3, 240).astype(numpy.int32)
            xx = (centers[y] + rng.randn(240, 8)).astype(numpy.float32)
            self.create_originals(xx, y)
            self.class_lengths = [0, 48, 192]

    wf = nn.StandardWorkflow(
        name="sparse-moe",
        layers=[{"type": "moe_ffn", "n_experts": 4, "hidden": 16,
                 "top_k": 2, "learning_rate": 0.05},
                {"type": "softmax", "output_sample_shape": 3}],
        loader_unit=L(None, minibatch_size=24, name="l"),
        loss_function="softmax",
        decision_config=dict(max_epochs=8, fail_iterations=100))
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 2,
                                                 "expert": 2}))
    w1 = wf.train_step.params["moe_ffn0"]["w1"]
    assert w1.sharding.spec[0] == "expert"
    wf.run()
    assert wf.decision.best_metric < 0.15, wf.decision.epoch_metrics


def test_moe_topk_validation():
    wf = vt.Workflow(name="tv")
    with pytest.raises(vt.Bug, match="top_k"):
        nn.MoEFFN(wf, n_experts=4, top_k=5)
    with pytest.raises(vt.Bug, match="top_k"):
        nn.MoEFFN(wf, n_experts=4, top_k=-1)
