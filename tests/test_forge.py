"""Forge model hub (reference: veles/tests/test_forge_client.py,
test_forge_server.py — real in-process server, no transport mocks)."""
import json
import os

import numpy
import pytest

import veles_tpu as vt
from veles_tpu.error import VelesError
from veles_tpu import forge


def make_src(tmp_path, content=b"weights"):
    src = tmp_path / "src"
    src.mkdir(exist_ok=True)
    (src / "model.npy").write_bytes(content)
    (src / "workflow.py").write_text("# model source\n")
    return str(src)


def manifest(**over):
    m = {"name": "mnist-fc", "version": "1.0", "author": "test",
         "description": "MNIST 784-100-10"}
    m.update(over)
    return m


def test_pack_and_read_manifest(tmp_path):
    pkg = forge.make_package(make_src(tmp_path), manifest(),
                             str(tmp_path / "p.tar.gz"))
    m = forge.read_package_manifest(pkg)
    assert m["name"] == "mnist-fc"
    dest = tmp_path / "out"
    forge.extract_package(pkg, str(dest))
    assert (dest / "model.npy").read_bytes() == b"weights"
    assert (dest / "workflow.py").exists()


def test_manifest_validation(tmp_path):
    with pytest.raises(VelesError):
        forge.make_package(make_src(tmp_path), manifest(name=""))
    with pytest.raises(VelesError):
        forge.make_package(make_src(tmp_path),
                           manifest(name="../escape"))


def test_server_roundtrip(tmp_path):
    server = forge.ForgeServer(str(tmp_path / "store"), port=0,
                               upload_tokens=["sekrit"]).start()
    client = forge.ForgeClient("http://127.0.0.1:%d" % server.port)
    pkg = forge.make_package(make_src(tmp_path), manifest(),
                             str(tmp_path / "p.tar.gz"))
    # bad token rejected
    with pytest.raises(VelesError):
        client.upload(pkg, token="wrong")
    out = client.upload(pkg, token="sekrit")
    assert out == {"ok": True, "name": "mnist-fc", "version": "1.0"}
    # second version
    pkg2 = forge.make_package(make_src(tmp_path, b"w2"),
                              manifest(version="1.1"),
                              str(tmp_path / "p2.tar.gz"))
    client.upload(pkg2, token="sekrit")
    lst = client.list()
    assert len(lst) == 1 and lst[0]["versions"] == ["1.0", "1.1"]
    det = client.details("mnist-fc")
    assert det["version"] == "1.1"      # latest
    dest = tmp_path / "fetched"
    m = client.fetch("mnist-fc", str(dest))
    assert m["version"] == "1.1"
    assert (dest / "model.npy").read_bytes() == b"w2"
    m = client.fetch("mnist-fc", str(tmp_path / "f10"), version="1.0")
    assert m["version"] == "1.0"
    server.stop()


def test_server_rejects_garbage_and_unknown(tmp_path):
    server = forge.ForgeServer(str(tmp_path / "store"), port=0).start()
    client = forge.ForgeClient("http://127.0.0.1:%d" % server.port)
    assert client.list() == []
    with pytest.raises(Exception):
        client.details("nope")
    with pytest.raises(Exception):
        client.fetch("nope", str(tmp_path / "x"))
    # garbage upload (no token list → open upload)
    import urllib.request
    req = urllib.request.Request(
        "http://127.0.0.1:%d/upload" % server.port, data=b"not a tarball")
    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(req, timeout=10)
    server.stop()


def test_forge_roundtrip_of_exported_workflow(tmp_path):
    """The canonical flow: package_export → forge upload → fetch →
    run_package gives identical outputs."""
    from veles_tpu import nn
    from veles_tpu.export.package import package_export, run_package
    wf = vt.Workflow(name="exp")
    f1 = nn.All2AllTanh(wf, output_sample_shape=6, name="fc1")
    x = numpy.random.RandomState(0).rand(3, 5).astype(numpy.float32)
    f1.input = vt.Array(x)
    f2 = nn.All2AllSoftmax(wf, output_sample_shape=4, name="fc2")
    dev = vt.XLADevice(mesh_axes={"data": 1})
    f1.initialize(device=dev)
    f2.input = vt.Array(f1.numpy_apply(f1.params_np(), x))
    f2.initialize(device=dev)
    wf.forwards = [f1, f2]
    pkg_dir = str(tmp_path / "pkg")
    package_export(wf, pkg_dir, with_stablehlo=False)
    expected = run_package(pkg_dir, x)

    pkg = forge.make_package(pkg_dir, manifest(name="exp"),
                             str(tmp_path / "exp.tar.gz"))
    server = forge.ForgeServer(str(tmp_path / "store"), port=0).start()
    client = forge.ForgeClient("http://127.0.0.1:%d" % server.port)
    client.upload(pkg)
    dest = str(tmp_path / "fetched")
    client.fetch("exp", dest)
    got = run_package(dest, x)
    numpy.testing.assert_allclose(got, expected, rtol=1e-6)
    server.stop()


def test_version_ordering(tmp_path):
    """1.10 beats 1.9 (lexicographic sort would invert this)."""
    server = forge.ForgeServer(str(tmp_path / "store"), port=0).start()
    client = forge.ForgeClient("http://127.0.0.1:%d" % server.port)
    for v in ("1.9", "1.10", "1.2"):
        client.upload(forge.make_package(
            make_src(tmp_path, v.encode()), manifest(version=v),
            str(tmp_path / ("p%s.tar.gz" % v))))
    assert client.details("mnist-fc")["version"] == "1.10"
    assert client.list()[0]["versions"] == ["1.2", "1.9", "1.10"]
    m = client.fetch("mnist-fc", str(tmp_path / "latest"))
    assert m["version"] == "1.10"
    server.stop()


def test_tarball_without_manifest_rejected(tmp_path):
    """Missing manifest.json must raise VelesError (HTTP 400), never
    KeyError (HTTP 500)."""
    import tarfile
    bad = tmp_path / "bad.tar.gz"
    with tarfile.open(bad, "w:gz") as tar:
        tar.add(make_src(tmp_path), arcname="payload")
    with pytest.raises(VelesError):
        forge.read_package_manifest(str(bad))
    server = forge.ForgeServer(str(tmp_path / "store"), port=0).start()
    import urllib.request
    req = urllib.request.Request(
        "http://127.0.0.1:%d/upload" % server.port,
        data=bad.read_bytes())
    with pytest.raises(urllib.error.HTTPError) as err:
        urllib.request.urlopen(req, timeout=10)
    assert err.value.code == 400
    server.stop()


def test_stray_file_in_store_ignored(tmp_path):
    store = tmp_path / "store"
    store.mkdir()
    (store / ".DS_Store").write_bytes(b"junk")
    server = forge.ForgeServer(str(store), port=0).start()
    client = forge.ForgeClient("http://127.0.0.1:%d" % server.port)
    assert client.list() == []
    server.stop()


def test_stop_before_start_does_not_hang(tmp_path):
    server = forge.ForgeServer(str(tmp_path / "store"), port=0)
    server.stop()       # never started; must return, not deadlock


def test_serve_cli_requires_token_off_loopback(tmp_path, capsys):
    with pytest.raises(SystemExit):
        forge.main(["serve", str(tmp_path / "s"), "--host", "0.0.0.0"])
    err = capsys.readouterr().err
    assert "--token" in err


def test_registration_and_ownership(tmp_path):
    """Author registration + package ownership (reference:
    veles/forge/forge_server.py:462 token/registration machinery; the
    confirmation-mail loop is replaced by returning the token — no
    egress here)."""
    server = forge.ForgeServer(str(tmp_path / "store"), port=0,
                               registration_open=True).start()
    try:
        client = forge.ForgeClient("http://127.0.0.1:%d" % server.port)
        alice = client.register("alice", "alice@example.com")
        bob = client.register("bob")
        assert alice != bob
        pkg = forge.make_package(make_src(tmp_path), manifest(),
                                 str(tmp_path / "p.tar.gz"))
        # no token once tokens exist → rejected
        with pytest.raises(VelesError, match="403"):
            client.upload(pkg)
        assert client.upload(pkg, token=alice)["ok"]
        # bob cannot publish over alice's package
        pkg2 = forge.make_package(make_src(tmp_path),
                                  manifest(version="1.1"),
                                  str(tmp_path / "p2.tar.gz"))
        with pytest.raises(VelesError, match="owned by"):
            client.upload(pkg2, token=bob)
        # alice can ship the new version
        assert client.upload(pkg2, token=alice)["version"] == "1.1"
        # the token store and ownership survive a server restart
        server.stop()
        server2 = forge.ForgeServer(str(tmp_path / "store"),
                                    port=0).start()
        try:
            client2 = forge.ForgeClient(
                "http://127.0.0.1:%d" % server2.port)
            pkg3 = forge.make_package(make_src(tmp_path),
                                      manifest(version="1.2"),
                                      str(tmp_path / "p3.tar.gz"))
            with pytest.raises(VelesError, match="owned by"):
                client2.upload(pkg3, token=bob)
            assert client2.upload(pkg3, token=alice)["ok"]
            # the listing is not confused by _tokens.json/_owner entries
            (entry,) = client2.list()
            assert entry["versions"] == ["1.0", "1.1", "1.2"]
        finally:
            server2.stop()
    finally:
        server.stop()


def test_registration_closed_by_default(tmp_path):
    server = forge.ForgeServer(str(tmp_path / "store"), port=0).start()
    try:
        client = forge.ForgeClient("http://127.0.0.1:%d" % server.port)
        with pytest.raises(VelesError, match="registration"):
            client.register("mallory")
    finally:
        server.stop()


def test_operator_token_is_admin(tmp_path):
    """--token operator tokens bypass ownership (hub admin)."""
    server = forge.ForgeServer(str(tmp_path / "store"), port=0,
                               upload_tokens=["admin-t"],
                               registration_open=True).start()
    try:
        client = forge.ForgeClient("http://127.0.0.1:%d" % server.port)
        carol = client.register("carol")
        pkg = forge.make_package(make_src(tmp_path), manifest(),
                                 str(tmp_path / "p.tar.gz"))
        assert client.upload(pkg, token=carol)["ok"]
        pkg2 = forge.make_package(make_src(tmp_path),
                                  manifest(version="2.0"),
                                  str(tmp_path / "p2.tar.gz"))
        assert client.upload(pkg2, token="admin-t")["ok"]
    finally:
        server.stop()
