"""Whole-epoch fused FC SGD kernel (ops/fused_fc.py): kernel↔oracle
equivalence, TrainStep fast-path trajectory parity vs the general scan
path, and strict eligibility gating."""
import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn, prng
from veles_tpu.config import root
from veles_tpu.loader import FullBatchLoader, TRAIN, VALID


def test_kernel_matches_oracle():
    import jax.numpy as jnp
    from veles_tpu.ops.fused_fc import (fused_fc_oracle,
                                        fused_fc_sgd_epoch)
    rng = numpy.random.RandomState(0)
    fin, hid, nout, n, mb = 20, 12, 3, 60, 10
    w1 = jnp.asarray(rng.randn(fin, hid) * 0.1, jnp.float32)
    b1 = jnp.asarray(rng.randn(hid) * 0.01, jnp.float32)
    w2 = jnp.asarray(rng.randn(hid, nout) * 0.1, jnp.float32)
    b2 = jnp.zeros((nout,), jnp.float32)
    ds = jnp.asarray(rng.rand(n, fin), jnp.float32)
    lb = jnp.asarray(rng.randint(0, nout, n), jnp.int32)
    plan = jnp.asarray(rng.permutation(n).reshape(-1, mb), jnp.int32)
    for a, b in ((1.0, 1.0), (1.7159, 0.6666)):
        out_k = fused_fc_sgd_epoch(w1, b1, w2, b2, ds, lb, plan, 0.05,
                                   act_a=a, act_b=b)
        out_o = fused_fc_oracle(w1, b1, w2, b2, ds, lb, plan, 0.05,
                                act_a=a, act_b=b)
        for name, kk, oo in zip(("w1", "b1", "w2", "b2", "loss", "err"),
                                out_k, out_o):
            numpy.testing.assert_allclose(
                numpy.asarray(kk), numpy.asarray(oo), rtol=2e-5,
                atol=2e-6, err_msg="%s (A=%s)" % (name, a))


class Blobs(FullBatchLoader):
    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(9)
        n_per, d, k = 50, 16, 3
        centers = rng.randn(k, d) * 2.5
        x = numpy.concatenate(
            [centers[c] + rng.randn(n_per, d) for c in range(k)])
        y = numpy.concatenate([numpy.full(n_per, c) for c in range(k)])
        perm = rng.permutation(len(x))
        self.create_originals(x[perm].astype(numpy.float32),
                              y[perm].astype(numpy.int32))
        self.class_lengths = [0, 30, 120]


def _run(fused, epochs=4, solver="sgd", mb=20):
    prev = root.common.engine.get("fused_fc_scan", False)
    root.common.engine.fused_fc_scan = fused
    try:
        prng.seed_all(777)
        wf = nn.StandardWorkflow(
            name="ffc-%s" % fused,
            layers=[{"type": "all2all_tanh", "output_sample_shape": 8,
                     "learning_rate": 0.05, "solver": solver},
                    {"type": "softmax", "output_sample_shape": 3,
                     "learning_rate": 0.05, "solver": solver}],
            loader_unit=Blobs(None, minibatch_size=mb, name="bl"),
            loss_function="softmax",
            decision_config=dict(max_epochs=epochs,
                                 fail_iterations=100),
            epochs_per_dispatch=2)
        wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        wf.run()
        return wf
    finally:
        root.common.engine.fused_fc_scan = prev


def test_workflow_trajectory_parity():
    """engine.fused_fc_scan must reproduce the general epoch-block
    path: identical per-epoch valid metrics and matching final
    weights (same seed → same shuffle plans → same SGD math)."""
    import jax
    wf_g = _run(False)
    wf_f = _run(True)
    assert wf_f.train_step._fused_fc is not None
    assert wf_f.train_step._fused_fc_active
    assert wf_g.train_step._fused_fc is None
    ev_g = numpy.asarray(wf_g.decision.epoch_metrics[VALID])
    ev_f = numpy.asarray(wf_f.decision.epoch_metrics[VALID])
    numpy.testing.assert_allclose(ev_f, ev_g, atol=1e-6)
    tr_g = numpy.asarray(wf_g.decision.epoch_metrics[TRAIN])
    tr_f = numpy.asarray(wf_f.decision.epoch_metrics[TRAIN])
    numpy.testing.assert_allclose(tr_f, tr_g, atol=1e-5)
    names = sorted(wf_g.train_step.params)
    assert names == sorted(wf_f.train_step.params) and len(names) == 2
    for name in names:
        wg = jax.device_get(wf_g.train_step.params[name]["weights"])
        wf_ = jax.device_get(wf_f.train_step.params[name]["weights"])
        numpy.testing.assert_allclose(wf_, wg, rtol=2e-4, atol=2e-5)


def test_eligibility_rejects_adam():
    wf = _run(True, epochs=2, solver="adam")
    assert wf.train_step._fused_fc is None          # fell back loudly
    assert wf.decision.best_metric is not None


def test_eligibility_rejects_partial_batches():
    """mb that does not divide the train length leaves padded plan
    rows — the kernel path must yield to the masked general path."""
    wf = _run(True, epochs=2, mb=25)    # 120 % 25 != 0
    assert wf.train_step._fused_fc is not None
    assert not wf.train_step._fused_fc_active
    assert wf.decision.best_metric is not None


def test_eligibility_rejects_freeze_base():
    """Frozen layers must not be updated by the unconditional kernel."""
    prev = root.common.engine.get("fused_fc_scan", False)
    root.common.engine.fused_fc_scan = True
    try:
        prng.seed_all(3)
        wf = nn.StandardWorkflow(
            name="ffc-frozen",
            layers=[{"type": "all2all_tanh", "output_sample_shape": 8,
                     "learning_rate": 0.05, "freeze_base": True},
                    {"type": "softmax", "output_sample_shape": 3,
                     "learning_rate": 0.05}],
            loader_unit=Blobs(None, minibatch_size=20, name="bl2"),
            loss_function="softmax",
            decision_config=dict(max_epochs=1, fail_iterations=100),
            epochs_per_dispatch=2)
        wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        assert wf.train_step._fused_fc is None
    finally:
        root.common.engine.fused_fc_scan = prev
