"""Whole-epoch fused FC SGD kernel (ops/fused_fc.py): kernel↔oracle
equivalence, TrainStep fast-path trajectory parity vs the general scan
path, and strict eligibility gating."""
import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn, prng
from veles_tpu.config import root
from veles_tpu.loader import FullBatchLoader, TRAIN, VALID


def _rand_net(rng, dims):
    import jax.numpy as jnp
    ws = [jnp.asarray(rng.randn(a, b) * 0.1, jnp.float32)
          for a, b in zip(dims, dims[1:])]
    bs = [jnp.asarray(rng.randn(b) * 0.01, jnp.float32)
          for b in dims[1:]]
    zw = [jnp.zeros_like(w) for w in ws]
    zb = [jnp.zeros_like(b) for b in bs]
    return ws, bs, zw, zb


def test_kernel_matches_oracle():
    """Kernel == jnp oracle across depth (2- and 3-layer chains),
    LeCun tanh scaling, momentum, coupled weight decay, and a bias-lr
    ratio — including the returned delta-recurrence state."""
    import jax.numpy as jnp
    from veles_tpu.ops.fused_fc import (fused_fc_oracle,
                                        fused_fc_sgd_epoch)
    rng = numpy.random.RandomState(0)
    n, mb, nout = 60, 10, 3
    ds = jnp.asarray(rng.rand(n, 20), jnp.float32)
    lb = jnp.asarray(rng.randint(0, nout, n), jnp.int32)
    plan = jnp.asarray(rng.permutation(n).reshape(-1, mb), jnp.int32)
    cases = (
        ((20, 12, 3), dict(act_a=1.0, act_b=1.0)),
        ((20, 12, 3), dict(act_a=1.7159, act_b=0.6666)),
        ((20, 12, 3), dict(momentum=0.9, wd=1e-3, wd_bias=1e-4,
                           lr_bias_ratio=0.5)),
        ((20, 16, 8, 3), dict(act_a=1.7159, act_b=0.6666,
                              momentum=0.5)),
    )
    for dims, kw in cases:
        ws, bs, zw, zb = _rand_net(rng, dims)
        out_k = fused_fc_sgd_epoch(ws, bs, zw, zb, ds, lb, plan, 0.05,
                                   **kw)
        out_o = fused_fc_oracle(ws, bs, zw, zb, ds, lb, plan, 0.05,
                                **kw)
        for name, kk, oo in zip(("w", "b", "vw", "vb"), out_k[:4],
                                out_o[:4]):
            for li, (k1, o1) in enumerate(zip(kk, oo)):
                numpy.testing.assert_allclose(
                    numpy.asarray(k1), numpy.asarray(o1), rtol=2e-5,
                    atol=2e-6, err_msg="%s[%d] %s %s" % (name, li,
                                                         dims, kw))
        for name, kk, oo in zip(("loss", "err"), out_k[4:], out_o[4:]):
            numpy.testing.assert_allclose(
                numpy.asarray(kk), numpy.asarray(oo), rtol=2e-5,
                atol=2e-6, err_msg=name)
        # a SECOND epoch continues from the returned state (the delta
        # recurrence survives the kernel boundary)
        k2 = fused_fc_sgd_epoch(out_k[0], out_k[1], out_k[2], out_k[3],
                                ds, lb, plan, 0.05, **kw)
        o2 = fused_fc_oracle(out_o[0], out_o[1], out_o[2], out_o[3],
                             ds, lb, plan, 0.05, **kw)
        numpy.testing.assert_allclose(
            numpy.asarray(k2[0][0]), numpy.asarray(o2[0][0]),
            rtol=5e-5, atol=5e-6)


class Blobs(FullBatchLoader):
    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(9)
        n_per, d, k = 50, 16, 3
        centers = rng.randn(k, d) * 2.5
        x = numpy.concatenate(
            [centers[c] + rng.randn(n_per, d) for c in range(k)])
        y = numpy.concatenate([numpy.full(n_per, c) for c in range(k)])
        perm = rng.permutation(len(x))
        self.create_originals(x[perm].astype(numpy.float32),
                              y[perm].astype(numpy.int32))
        self.class_lengths = [0, 30, 120]


def _run(fused, epochs=4, solver="sgd", mb=20, **layer_extra):
    prev = root.common.engine.get("fused_fc_scan", False)
    root.common.engine.fused_fc_scan = fused
    try:
        prng.seed_all(777)
        wf = nn.StandardWorkflow(
            name="ffc-%s" % fused,
            layers=[{"type": "all2all_tanh", "output_sample_shape": 8,
                     "learning_rate": 0.05, "solver": solver,
                     **layer_extra},
                    {"type": "softmax", "output_sample_shape": 3,
                     "learning_rate": 0.05, "solver": solver,
                     **layer_extra}],
            loader_unit=Blobs(None, minibatch_size=mb, name="bl"),
            loss_function="softmax",
            decision_config=dict(max_epochs=epochs,
                                 fail_iterations=100),
            epochs_per_dispatch=2)
        wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        wf.run()
        return wf
    finally:
        root.common.engine.fused_fc_scan = prev


def test_workflow_trajectory_parity():
    """engine.fused_fc_scan must reproduce the general epoch-block
    path: identical per-epoch valid metrics and matching final
    weights (same seed → same shuffle plans → same SGD math)."""
    import jax
    wf_g = _run(False)
    wf_f = _run(True)
    assert wf_f.train_step._fused_fc is not None
    assert wf_f.train_step._fused_fc_active
    assert wf_g.train_step._fused_fc is None
    ev_g = numpy.asarray(wf_g.decision.epoch_metrics[VALID])
    ev_f = numpy.asarray(wf_f.decision.epoch_metrics[VALID])
    numpy.testing.assert_allclose(ev_f, ev_g, atol=1e-6)
    tr_g = numpy.asarray(wf_g.decision.epoch_metrics[TRAIN])
    tr_f = numpy.asarray(wf_f.decision.epoch_metrics[TRAIN])
    numpy.testing.assert_allclose(tr_f, tr_g, atol=1e-5)
    names = sorted(wf_g.train_step.params)
    assert names == sorted(wf_f.train_step.params) and len(names) == 2
    for name in names:
        wg = jax.device_get(wf_g.train_step.params[name]["weights"])
        wf_ = jax.device_get(wf_f.train_step.params[name]["weights"])
        numpy.testing.assert_allclose(wf_, wg, rtol=2e-4, atol=2e-5)


def test_workflow_trajectory_parity_momentum_decay():
    """The Znicz SGD recurrence (momentum + coupled L2) through the
    kernel: VALID metrics identical, weights AND the opt_state delta
    recurrence match the general path across dispatch boundaries."""
    import jax
    kw = dict(momentum=0.9, weights_decay=1e-3)
    wf_g = _run(False, **kw)
    wf_f = _run(True, **kw)
    assert wf_f.train_step._fused_fc_active
    ev_g = numpy.asarray(wf_g.decision.epoch_metrics[VALID])
    ev_f = numpy.asarray(wf_f.decision.epoch_metrics[VALID])
    numpy.testing.assert_allclose(ev_f, ev_g, atol=1e-6)
    for name in sorted(wf_g.train_step.params):
        for k in ("weights", "bias"):
            pg = jax.device_get(wf_g.train_step.params[name][k])
            pf = jax.device_get(wf_f.train_step.params[name][k])
            numpy.testing.assert_allclose(pf, pg, rtol=2e-4,
                                          atol=2e-5, err_msg=name)
            sg = jax.device_get(wf_g.train_step.opt_state[name][k])
            sf = jax.device_get(wf_f.train_step.opt_state[name][k])
            numpy.testing.assert_allclose(sf, sg, rtol=2e-3,
                                          atol=2e-6, err_msg=name)


def test_workflow_three_layer_chain():
    """Depth generality: tanh→tanh→softmax engages and learns."""
    prev = root.common.engine.get("fused_fc_scan", False)
    root.common.engine.fused_fc_scan = True
    try:
        prng.seed_all(5)
        wf = nn.StandardWorkflow(
            name="ffc3",
            layers=[{"type": "all2all_tanh", "output_sample_shape": 12,
                     "learning_rate": 0.05},
                    {"type": "all2all_tanh", "output_sample_shape": 8,
                     "learning_rate": 0.05},
                    {"type": "softmax", "output_sample_shape": 3,
                     "learning_rate": 0.05}],
            loader_unit=Blobs(None, minibatch_size=20, name="bl3"),
            loss_function="softmax",
            decision_config=dict(max_epochs=8, fail_iterations=100),
            epochs_per_dispatch=2)
        wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        wf.run()
        assert wf.train_step._fused_fc is not None
        assert wf.train_step._fused_fc_active
        assert len(wf.train_step._fused_fc["names"]) == 3
        assert wf.decision.best_metric < 0.15, \
            wf.decision.epoch_metrics
    finally:
        root.common.engine.fused_fc_scan = prev


def test_eligibility_rejects_adam():
    wf = _run(True, epochs=2, solver="adam")
    assert wf.train_step._fused_fc is None          # fell back loudly
    assert wf.decision.best_metric is not None


def test_eligibility_rejects_partial_batches():
    """mb that does not divide the train length leaves padded plan
    rows — the kernel path must yield to the masked general path."""
    wf = _run(True, epochs=2, mb=25)    # 120 % 25 != 0
    assert wf.train_step._fused_fc is not None
    assert not wf.train_step._fused_fc_active
    assert wf.decision.best_metric is not None


def test_eligibility_rejects_freeze_base():
    """Frozen layers must not be updated by the unconditional kernel."""
    prev = root.common.engine.get("fused_fc_scan", False)
    root.common.engine.fused_fc_scan = True
    try:
        prng.seed_all(3)
        wf = nn.StandardWorkflow(
            name="ffc-frozen",
            layers=[{"type": "all2all_tanh", "output_sample_shape": 8,
                     "learning_rate": 0.05, "freeze_base": True},
                    {"type": "softmax", "output_sample_shape": 3,
                     "learning_rate": 0.05}],
            loader_unit=Blobs(None, minibatch_size=20, name="bl2"),
            loss_function="softmax",
            decision_config=dict(max_epochs=1, fail_iterations=100),
            epochs_per_dispatch=2)
        wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        assert wf.train_step._fused_fc is None
    finally:
        root.common.engine.fused_fc_scan = prev


def test_eligibility_rejects_vmem_oversized_chain():
    """A chain whose VMEM-resident state would blow the kernel budget
    must fall back to the general path instead of dying in Mosaic."""
    prev = root.common.engine.get("fused_fc_scan", False)
    root.common.engine.fused_fc_scan = True
    try:
        prng.seed_all(2)
        wf = nn.StandardWorkflow(
            name="ffc-big",
            layers=[{"type": "all2all_tanh",
                     "output_sample_shape": 2048,
                     "learning_rate": 0.05},
                    {"type": "all2all_tanh",
                     "output_sample_shape": 2048,
                     "learning_rate": 0.05},
                    {"type": "softmax", "output_sample_shape": 3,
                     "learning_rate": 0.05}],
            loader_unit=Blobs(None, minibatch_size=20, name="blb"),
            loss_function="softmax",
            decision_config=dict(max_epochs=1, fail_iterations=100),
            epochs_per_dispatch=2)
        wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        assert wf.train_step._fused_fc is None
    finally:
        root.common.engine.fused_fc_scan = prev


def test_eligibility_rejects_per_layer_act_scales():
    """A per-instance (A, B) override on one tanh layer must fall back:
    the kernel bakes ONE scaling for the whole chain (ADVICE r4)."""
    from veles_tpu.nn.all2all import All2AllTanh
    prev = root.common.engine.get("fused_fc_scan", False)
    root.common.engine.fused_fc_scan = True
    try:
        prng.seed_all(7)
        wf = nn.StandardWorkflow(
            name="ffc-actscale",
            layers=[{"type": "all2all_tanh", "output_sample_shape": 12,
                     "learning_rate": 0.05},
                    {"type": "all2all_tanh", "output_sample_shape": 8,
                     "learning_rate": 0.05},
                    {"type": "softmax", "output_sample_shape": 3,
                     "learning_rate": 0.05}],
            loader_unit=Blobs(None, minibatch_size=20, name="blact"),
            loss_function="softmax",
            decision_config=dict(max_epochs=1, fail_iterations=100),
            epochs_per_dispatch=2)
        tanhs = [f for f in wf.forwards if isinstance(f, All2AllTanh)]
        tanhs[1].A = 1.0            # instance override shadows class A
        wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        assert wf.train_step._fused_fc is None
    finally:
        root.common.engine.fused_fc_scan = prev
