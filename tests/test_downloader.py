"""Downloader fetch+unpack against a loopback HTTP stub (VERDICT r2
missing #5: the network path had never run — egress is zero, so the
proof is a local server, the same pattern that validated WebHDFS).
Reference behavior: veles/downloader.py:56-131 — ensure files exist
under directory, downloading + unpacking the archive when missing,
skipping entirely when present."""
import io
import os
import tarfile
import threading
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy
import pytest

from veles_tpu.downloader import Downloader
from veles_tpu.error import VelesError


class _ArchiveServer:
    """Serves in-memory archives; counts hits per path; can redirect."""

    def __init__(self):
        self.payloads = {}
        self.hits = {}
        self.redirects = {}
        srv = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                srv.hits[self.path] = srv.hits.get(self.path, 0) + 1
                if self.path in srv.redirects:
                    self.send_response(307)
                    self.send_header("Location",
                                     srv.redirects[self.path])
                    self.end_headers()
                    return
                body = srv.payloads.get(self.path)
                if body is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        self.thread = threading.Thread(target=self.httpd.serve_forever,
                                       daemon=True)
        self.thread.start()

    def url(self, path):
        return "http://127.0.0.1:%d%s" % (self.port, path)

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture
def server():
    s = _ArchiveServer()
    yield s
    s.stop()


def _tgz(files):
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as t:
        for name, data in files.items():
            info = tarfile.TarInfo(name)
            info.size = len(data)
            t.addfile(info, io.BytesIO(data))
    return buf.getvalue()


def _npy_bytes(arr):
    buf = io.BytesIO()
    numpy.save(buf, arr)
    return buf.getvalue()


def test_download_unpack_load_and_idempotence(server, tmp_path):
    """The full chain: fetch tar.gz → unpack → the extracted .npy loads
    — then a second initialize must NOT re-download (files present)."""
    arr = numpy.arange(12, dtype=numpy.float32).reshape(3, 4)
    server.payloads["/blob.tar.gz"] = _tgz(
        {"data/x.npy": _npy_bytes(arr), "data/labels.txt": b"a\nb\nc\n"})
    d = Downloader(None, url=server.url("/blob.tar.gz"),
                   directory=str(tmp_path),
                   files=["data/x.npy", "data/labels.txt"], name="dl")
    d.initialize()
    loaded = numpy.load(tmp_path / "data" / "x.npy")
    numpy.testing.assert_array_equal(loaded, arr)
    assert (tmp_path / "data" / "labels.txt").read_text() == "a\nb\nc\n"
    assert server.hits["/blob.tar.gz"] == 1
    # idempotent: all files present → no network traffic at all
    Downloader(None, url=server.url("/blob.tar.gz"),
               directory=str(tmp_path),
               files=["data/x.npy", "data/labels.txt"],
               name="dl2").initialize()
    assert server.hits["/blob.tar.gz"] == 1


def test_download_zip_and_redirect(server, tmp_path):
    """Zip unpack; and the fetch must follow an HTTP 307 (the WebHDFS
    two-step every real data host uses)."""
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("inner/readme.txt", "hello")
    server.payloads["/real.zip"] = buf.getvalue()
    server.redirects["/alias.zip"] = server.url("/real.zip")
    d = Downloader(None, url=server.url("/alias.zip"),
                   directory=str(tmp_path),
                   files=["inner/readme.txt"], name="dlz")
    d.initialize()
    assert (tmp_path / "inner" / "readme.txt").read_text() == "hello"
    assert server.hits["/real.zip"] == 1


def test_missing_files_after_unpack_is_loud(server, tmp_path):
    server.payloads["/t.tar.gz"] = _tgz({"only.npy": b"x"})
    d = Downloader(None, url=server.url("/t.tar.gz"),
                   directory=str(tmp_path),
                   files=["never_in_archive.npy"], name="dm")
    with pytest.raises(VelesError, match="still missing"):
        d.initialize()


def test_no_url_and_missing_files_is_loud(tmp_path):
    d = Downloader(None, directory=str(tmp_path), files=["x.npy"],
                   name="dn")
    with pytest.raises(VelesError, match="no url"):
        d.initialize()


def test_hostile_archive_member_is_rejected(server, tmp_path):
    """Path-traversal members must not escape the dataset directory
    (extraction uses the stdlib 'data' filter)."""
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as t:
        info = tarfile.TarInfo("../escape.txt")
        info.size = 3
        t.addfile(info, io.BytesIO(b"owx"))
    server.payloads["/evil.tar.gz"] = buf.getvalue()
    d = Downloader(None, url=server.url("/evil.tar.gz"),
                   directory=str(tmp_path / "inside"),
                   files=[], name="de")
    # specifically the extraction filter's rejection — a broad
    # Exception would let an environmental error (dead stub, no
    # loopback) pass this security gate vacuously
    with pytest.raises(tarfile.TarError):
        d.initialize()
    assert not (tmp_path / "escape.txt").exists()
