"""Per-layer solvers (GD-unit update rules): sgd (Znicz semantics),
adam, adamw (decoupled decay), adagrad, rmsprop, adadelta — routed
from the layer dict like the lr knobs, running inside the fused step,
sharded state, snapshot-portable."""
import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn, prng
from veles_tpu.error import Bug
from veles_tpu.loader import FullBatchLoader, VALID


class BlobsLoader(FullBatchLoader):
    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(7)
        n_per, d, k = 120, 10, 3
        centers = rng.randn(k, d) * 3
        data = numpy.concatenate(
            [centers[c] + rng.randn(n_per, d) for c in range(k)])
        labels = numpy.concatenate(
            [numpy.full(n_per, c) for c in range(k)])
        perm = rng.permutation(len(data))
        self.create_originals(data[perm].astype(numpy.float32),
                              labels[perm].astype(numpy.int32))
        self.class_lengths = [0, 90, 270]


def make_wf(solver, lr, epochs=6, **extra):
    loader = BlobsLoader(None, minibatch_size=24, name="blobs-" + solver)
    return nn.StandardWorkflow(
        name="solver-" + solver,
        layers=[{"type": "all2all_tanh", "output_sample_shape": 16,
                 "solver": solver, "learning_rate": lr, **extra},
                {"type": "softmax", "output_sample_shape": 3,
                 "solver": solver, "learning_rate": lr, **extra}],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=epochs, fail_iterations=100))


@pytest.mark.parametrize("solver,lr", [("adam", 0.01),
                                       ("adamw", 0.01),
                                       ("adagrad", 0.05),
                                       ("rmsprop", 0.005),
                                       ("adadelta", 1.0)])
def test_solver_converges(solver, lr):
    prng.seed_all(99)
    # adadelta's unit-correcting deltas ramp from ~sqrt(eps), so it
    # needs more epochs to reach the shared gate
    wf = make_wf(solver, lr, epochs=20 if solver == "adadelta" else 6)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    gd = wf.train_step.gds[0]
    assert gd.solver == solver
    wf.run()
    assert wf.decision.best_metric < 0.1, wf.decision.epoch_metrics


def test_adam_state_shards_on_data_mesh():
    """Nested Adam state (m/v trees + scalar step counter) must survive
    multi-device placement — state leaves inherit the matching param's
    sharding, the counter replicates."""
    prng.seed_all(99)
    wf = make_wf("adam", 0.01, epochs=4)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 8}))
    st = wf.train_step.opt_state["all2all_tanh0"]
    assert set(st) == {"m", "v", "t"}
    wf.run()
    assert wf.decision.best_metric < 0.1
    # re-read: dispatch donates the state buffers (old refs are deleted)
    st = wf.train_step.opt_state["all2all_tanh0"]
    assert int(st["t"]) > 0  # counter device-resident and advancing


def test_adam_snapshot_roundtrip(tmp_path):
    prng.seed_all(99)
    wf = make_wf("adam", 0.01, epochs=3)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    snap = vt.Snapshotter(None, prefix="adam", directory=str(tmp_path))
    snap.workflow = wf
    path = snap.export()
    import jax
    t_before = int(jax.device_get(
        wf.train_step.opt_state["all2all_tanh0"]["t"]))
    assert t_before > 0

    prng.seed_all(99)
    wf2 = make_wf("adam", 0.01, epochs=6)
    wf2.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    vt.resume(wf2, path)
    t_after = int(jax.device_get(
        wf2.train_step.opt_state["all2all_tanh0"]["t"]))
    assert t_after == t_before
    wf2.decision.complete <<= False     # reopen (launcher.resume does)
    wf2.run()          # continues training with restored moments
    assert wf2.decision.epoch_number == 6


def test_adam_through_pipeline(tmp_path):
    """Adam + {'pipeline': 2}: stacked m/v, shared step counter; the
    per-layer snapshot moves to a plain mesh."""
    prng.seed_all(99)
    loader = BlobsLoader(None, minibatch_size=24, name="blobs-ppadam")
    layers = ([{"type": "all2all_tanh", "output_sample_shape": 16,
                "name": "stem", "solver": "adam",
                "learning_rate": 0.01}]
              + [{"type": "all2all_tanh", "output_sample_shape": 16,
                  "name": "blk%d" % i, "solver": "adam",
                  "learning_rate": 0.01} for i in range(2)]
              + [{"type": "softmax", "output_sample_shape": 3,
                  "solver": "adam", "learning_rate": 0.01}])
    wf = nn.StandardWorkflow(
        name="pp-adam", layers=layers, loader_unit=loader,
        loss_function="softmax",
        decision_config=dict(max_epochs=3, fail_iterations=100))
    wf.initialize(device=vt.XLADevice(mesh_axes={"pipeline": 2}))
    from veles_tpu.parallel.sharding import PP_BLOCK
    st = wf.train_step.opt_state[PP_BLOCK]
    assert st["m"]["weights"].shape[0] == 2    # stacked moments
    wf.run()
    snap = vt.Snapshotter(None, prefix="ppa", directory=str(tmp_path))
    snap.workflow = wf
    path = snap.export()

    wf2 = nn.StandardWorkflow(
        name="pp-adam", layers=layers,
        loader_unit=BlobsLoader(None, minibatch_size=24, name="b2"),
        loss_function="softmax",
        decision_config=dict(max_epochs=3, fail_iterations=100))
    wf2.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    vt.resume(wf2, path)
    assert wf2.decision.epoch_number == 3
    assert set(wf2.train_step.opt_state["blk1"]) == {"m", "v", "t"}


def test_unknown_solver_rejected():
    wf = make_wf("lion", 0.01)         # GD units are created lazily
    with pytest.raises(Bug, match="solver"):
        wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))


def test_remat_identical_numerics():
    """remat=True recomputes activations in the backward (jax.checkpoint)
    — memory knob only, trajectories must match exactly."""
    def run(remat):
        prng.seed_all(99)
        loader = BlobsLoader(None, minibatch_size=24, name="b-remat")
        wf = nn.StandardWorkflow(
            name="remat-%s" % remat,
            layers=[{"type": "all2all_tanh", "output_sample_shape": 16},
                    {"type": "softmax", "output_sample_shape": 3}],
            loader_unit=loader, loss_function="softmax",
            decision_config=dict(max_epochs=4, fail_iterations=100),
            remat=remat)
        wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        wf.run()
        return numpy.asarray(wf.decision.epoch_metrics[VALID])

    numpy.testing.assert_array_equal(run(True), run(False))


def test_gradient_clip_norm():
    """gradient_clip_norm clips the layer's joint grad L2; training
    stays stable at an lr that diverges unclipped."""
    def run(clip):
        prng.seed_all(77)
        loader = BlobsLoader(None, minibatch_size=24, name="b-clip")
        wf = nn.StandardWorkflow(
            name="clip-%s" % clip,
            layers=[{"type": "all2all_tanh", "output_sample_shape": 16,
                     "learning_rate": 2.0, "gradient_clip_norm": clip},
                    {"type": "softmax", "output_sample_shape": 3,
                     "learning_rate": 2.0, "gradient_clip_norm": clip}],
            loader_unit=loader, loss_function="softmax",
            decision_config=dict(max_epochs=5, fail_iterations=100))
        wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        wf.run()
        return wf.decision.epoch_metrics[VALID]

    unclipped = run(0.0)
    clipped = run(0.05)
    # lr=2.0 unclipped: oscillates near/above chance; clipped: converges
    assert min(clipped) < 0.15, clipped
    assert min(clipped) < min(unclipped) - 0.05, (clipped, unclipped)


def test_warmup_cosine_schedule_unit():
    sched = nn.warmup_cosine(2, 8, floor=0.1)
    assert sched(0) == 0.5 and sched(1) == 1.0
    assert abs(sched(8) - 0.1) < 1e-9
    vals = [sched(e) for e in range(9)]
    assert all(a >= b for a, b in zip(vals[1:], vals[2:]))  # decays


def test_adamw_decay_is_decoupled():
    """The defining AdamW property: with zero gradients, weights still
    shrink by lr*wd per step (decay outside the moments), while plain
    adam with wd folded into g moves them through the moment machinery
    instead. Assert the exact decoupled shrink."""
    import jax.numpy as jnp
    wf = vt.Workflow(name="adamw-pin")
    fwd = nn.All2All(wf, output_sample_shape=4, name="fc",
                     solver="adamw", learning_rate=0.1,
                     weight_decay=0.5)
    from veles_tpu.nn.all2all import GradientDescent
    gd = GradientDescent(wf, name="gd")
    gd.forward = fwd
    for k, v in fwd.gd_config.items():
        setattr(gd, k, v)
    gd.solver = "adamw"
    gd.learning_rate = 0.1
    gd.weight_decay = 0.5
    params = {"weights": jnp.ones((3, 4))}
    state = gd.init_state(params)
    grads = {"weights": jnp.zeros((3, 4))}
    new_p, _ = gd.update(params, grads, state)
    numpy.testing.assert_allclose(
        numpy.asarray(new_p["weights"]),
        numpy.ones((3, 4)) * (1 - 0.1 * 0.5), rtol=1e-6)
