"""TrialScheduler: the job farm under parallel GA / ensemble search
(SURVEY.md §2.4 "trial scheduler over TPU slices"; reference analog:
master–slave job distribution, veles/server.py)."""
import os
import sys
import time

import pytest

from veles_tpu.parallel.trials import (Trial, TrialResult, TrialScheduler,
                                       cpu_placement, mesh_slice_placement)

PY = sys.executable

# -S skips site initialization: on this rig a bare `python -c pass`
# costs ~2s of CPU processing site-packages .pth hooks, and the CI box
# has ONE core — without -S every concurrent spawn would serialize on
# startup CPU and time-based assertions would measure the site hooks,
# not the scheduler. Sleep-dominated -S trials isolate scheduler
# concurrency from host core count.
NOSITE = [PY, "-S"]


def test_results_keep_submission_order_and_tags():
    sched = TrialScheduler(n_workers=3)
    trials = [Trial(NOSITE + ["-c", "pass"], tag=i) for i in range(7)]
    results = sched.run(trials)
    assert [r.tag for r in results] == list(range(7))
    assert all(r.ok for r in results)


def test_wallclock_sublinear_in_trials():
    """The whole point (VERDICT r2 missing #3): N trials on W workers
    must cost ~N/W serial time, not N. Six 1-second sleeps on three
    workers: serial is 6s+spawn; the gate at 4.5s only passes with
    genuine concurrency."""
    sched = TrialScheduler(n_workers=3)
    trials = [Trial(NOSITE + ["-c", "import time; time.sleep(1.0)"],
                    tag=i) for i in range(6)]
    t0 = time.time()
    results = sched.run(trials)
    elapsed = time.time() - t0
    assert all(r.ok for r in results)
    assert elapsed < 4.5, elapsed
    # slots actually rotated across workers
    assert len({r.slot for r in results}) == 3


def test_failure_is_reported_not_raised():
    sched = TrialScheduler(n_workers=2)
    results = sched.run([
        Trial(NOSITE + ["-c", "pass"], tag="ok"),
        Trial(NOSITE + ["-c", "import sys; sys.exit(3)"], tag="bad"),
        Trial(NOSITE + ["-c", "raise RuntimeError('boom')"], tag="boom"),
    ])
    assert results[0].ok
    assert not results[1].ok and results[1].returncode == 3
    assert not results[2].ok and "boom" in results[2].stderr_tail


def test_overrunning_trial_is_killed_by_group():
    """A hung candidate (the TPU-tunnel failure mode) must be killed —
    including any grandchildren — and reported as timed_out."""
    sched = TrialScheduler(n_workers=2)
    t0 = time.time()
    results = sched.run([
        Trial(NOSITE + ["-c",
                        "import subprocess, sys, time;"
                        "subprocess.Popen([sys.executable, '-S', '-c',"
                        " 'import time; time.sleep(60)']);"
                        "time.sleep(60)"], tag="hang", timeout=2.0),
        Trial(NOSITE + ["-c", "pass"], tag="ok"),
    ])
    assert time.time() - t0 < 30
    assert results[0].timed_out and not results[0].ok
    assert results[1].ok


def test_placement_env_reaches_the_trial(tmp_path):
    """Each worker slot's placement env must be visible inside the
    trial process — that is the device-isolation mechanism."""
    out = tmp_path / "envs"
    out.mkdir()
    sched = TrialScheduler(
        n_workers=2,
        placement=lambda slot: {"TRIAL_SLOT": str(slot),
                                "JAX_PLATFORMS": "cpu"})
    script = ("import os; open(%r + '/' + os.environ['TRIAL_SLOT'], 'a')"
              ".write(os.environ['JAX_PLATFORMS'] + '\\n')" % str(out))
    results = sched.run([Trial(NOSITE + ["-c", script], tag=i)
                         for i in range(6)])
    assert all(r.ok for r in results), [r.stderr_tail for r in results]
    seen = sorted(os.listdir(out))
    assert seen == ["0", "1"]
    assert (out / "0").read_text().strip().splitlines()[0] == "cpu"


def test_cpu_placement_strips_forced_device_count(monkeypatch):
    monkeypatch.setenv(
        "XLA_FLAGS",
        "--xla_force_host_platform_device_count=8 --xla_foo=1")
    env = cpu_placement(0)
    assert env["JAX_PLATFORMS"] == "cpu"
    assert "device_count" not in env["XLA_FLAGS"]
    assert "--xla_foo=1" in env["XLA_FLAGS"]


def test_mesh_slice_placement_disjoint_slices():
    place = mesh_slice_placement(devices_per_trial=2, total_devices=8)
    assert place(0)["TPU_VISIBLE_CHIPS"] == "0,1"
    assert place(3)["TPU_VISIBLE_CHIPS"] == "6,7"
    with pytest.raises(ValueError):
        place(4)


def test_worker_count_validation():
    with pytest.raises(ValueError):
        TrialScheduler(n_workers=0)
    assert isinstance(
        TrialScheduler(n_workers=2).run([]), list)


def test_mesh_slice_child_sees_exact_device_set(tmp_path):
    """Placement correctness end-to-end (VERDICT r3 weak #6): a REAL
    veles_tpu trial placed by mesh_slice_placement must materialize
    EXACTLY its slice as its jax device set — slot i ↔ chips
    [2i, 2i+1], 2 devices, disjoint between slots. On this CPU host
    the package init maps TPU_VISIBLE_CHIPS to that many virtual
    devices (veles_tpu/__init__.py), so the env-var contract is
    provable without multi-chip hardware."""
    import json
    outdir = tmp_path / "docs"
    outdir.mkdir()
    child = (
        "import json, os, sys\n"
        "import jax\n"
        "import veles_tpu as vt\n"
        "devs = jax.devices()\n"
        "mesh = vt.make_mesh(devs, {'data': len(devs)})\n"
        "import jax.numpy as jnp\n"
        "from jax.sharding import NamedSharding, PartitionSpec as P\n"
        "x = jax.device_put(jnp.arange(8.0),\n"
        "                   NamedSharding(mesh, P('data')))\n"
        "s = float(jax.jit(lambda v: v.sum())(x))\n"
        "json.dump({'chips': os.environ.get('TPU_VISIBLE_CHIPS'),\n"
        "           'bounds': os.environ.get("
        "'TPU_CHIPS_PER_PROCESS_BOUNDS'),\n"
        "           'n_devices': len(devs), 'sum': s},\n"
        "          open(sys.argv[1], 'w'))\n")
    sched = TrialScheduler(
        n_workers=2,
        placement=mesh_slice_placement(devices_per_trial=2,
                                       total_devices=4))
    results = sched.run([
        Trial([PY, "-c", child, str(outdir / ("t%d.json" % i))], tag=i)
        for i in range(4)])
    assert all(r.ok for r in results), [r.stderr_tail for r in results]
    import json as _json
    by_slot = {0: "0,1", 1: "2,3"}
    for i, res in enumerate(results):
        doc = _json.load(open(outdir / ("t%d.json" % i)))
        # the child's device set IS its slice: width and identity
        assert doc["n_devices"] == 2, doc
        assert doc["chips"] == by_slot[res.slot], (doc, res.slot)
        assert doc["bounds"] == "2,1,1"
        assert doc["sum"] == 28.0
    # both slots actually hosted trials (true fan-out, not serial)
    assert {r.slot for r in results} == {0, 1}
