"""Service layer: RESTful serving unit + web status server
(reference: veles/tests/test_restful.py, test_web_status.py)."""
import json
import time
import threading
import urllib.request

import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn
from veles_tpu.loader.stream import RestfulLoader
from veles_tpu.plumbing import Repeater
from veles_tpu.web_status import StatusReporter, WebStatusServer


def _post(url, payload, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        body = e.read()
        try:
            return e.code, json.loads(body)
        except ValueError:
            return e.code, body.decode(errors="replace")


def build_serving_workflow():
    wf = vt.Workflow(name="serve")
    rep = Repeater(wf)
    loader = RestfulLoader(wf, sample_shape=(4,), timeout=30.0,
                           name="rest_loader")
    fwd = nn.All2AllSoftmax(wf, output_sample_shape=3, name="fwd")
    api = vt.RESTfulAPI(wf, loader=loader, port=0, request_timeout=30.0)
    rep.link_from(wf.start_point)
    loader.link_from(rep)
    fwd.link_from(loader)
    fwd.link_attrs(loader, ("input", "minibatch_data"))
    api.link_from(fwd)
    api.link_attrs(fwd, ("input", "output"))
    rep.link_from(api)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    return wf, loader, fwd, api


def test_restful_api_roundtrip():
    wf, loader, fwd, api = build_serving_workflow()
    t = threading.Thread(target=wf.run, daemon=True)
    t.start()
    url = "http://127.0.0.1:%d/api" % api.port
    x = [0.1, -0.2, 0.3, 0.4]
    status, body = _post(url, {"input": x})
    assert status == 200, body
    got = numpy.asarray(body["result"])
    expect = fwd.numpy_apply(fwd.params_np(),
                             numpy.asarray([x], dtype=numpy.float32))[0]
    numpy.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)
    assert abs(got.sum() - 1.0) < 1e-4
    # malformed request does not kill the service
    status, body = _post(url, {"wrong": 1})
    assert status == 400
    status, body = _post(url, {"input": x})
    assert status == 200
    assert api.requests_served == 2
    loader.close()
    t.join(timeout=10)
    assert not t.is_alive()
    api.stop()


def test_restful_api_rejects_unknown_path():
    wf, loader, fwd, api = build_serving_workflow()
    status, _ = _post("http://127.0.0.1:%d/nope" % api.port, {"input": []},
                      timeout=5)
    assert status == 404
    loader.close()
    api.stop()


def test_web_status_update_and_snapshot():
    server = WebStatusServer(port=0).start()
    base = "http://127.0.0.1:%d" % server.port
    reporter = StatusReporter(base)
    assert reporter.send({"id": "wf@1", "name": "mnist", "device": "tpu",
                          "epoch": 3, "metric": 0.02, "elapsed_sec": 12.5})
    with urllib.request.urlopen(base + "/status.json", timeout=5) as resp:
        snap = json.loads(resp.read())
    assert snap["wf@1"]["name"] == "mnist"
    assert snap["wf@1"]["epoch"] == 3
    with urllib.request.urlopen(base + "/", timeout=5) as resp:
        page = resp.read().decode()
    assert "veles_tpu" in page and "status.json" in page
    status, body = _post(base + "/update", {"no_id": True}, timeout=5)
    assert status == 400
    server.stop()


def test_web_status_stale_eviction():
    server = WebStatusServer(port=0, stale_after=0.0).start()
    server.update("w", {"name": "x"})
    assert server.snapshot() == {}      # immediately stale
    server.stop()


def test_launcher_payload_shape():
    from veles_tpu.launcher import Launcher
    launcher = Launcher(backend="numpy")
    wf = vt.Workflow(name="w")
    launcher.workflow = wf
    payload = launcher._status_payload()
    assert payload["name"] == "w" and "elapsed_sec" in payload


def test_non_object_json_bodies_get_400():
    """Valid-JSON non-dict bodies must 400, not kill the handler thread."""
    server = WebStatusServer(port=0).start()
    status, _ = _post("http://127.0.0.1:%d/update" % server.port, [1, 2],
                      timeout=5)
    assert status == 400
    server.stop()
    wf, loader, fwd, api = build_serving_workflow()
    status, _ = _post("http://127.0.0.1:%d/api" % api.port, "just a string",
                      timeout=5)
    assert status == 400
    loader.close()
    api.stop()


def test_restful_image_serving_roundtrip(tmp_path):
    """Image serving (reference RestfulImageLoader,
    veles/loader/restful.py:133): POST a base64-encoded PNG; the loader
    decodes it with the training-time size/color policy and the
    forward chain answers — the 'input' numeric path keeps working."""
    import base64
    import io
    from PIL import Image
    from veles_tpu.loader import RestfulImageLoader

    wf = vt.Workflow(name="serve-img")
    rep = Repeater(wf)
    loader = RestfulImageLoader(wf, sample_shape=(4, 4, 3),
                                size=(4, 4), color="RGB", timeout=30.0,
                                name="img_loader")
    fwd = nn.All2AllSoftmax(wf, output_sample_shape=2, name="fwd")
    api = vt.RESTfulAPI(wf, loader=loader, port=0, request_timeout=30.0)
    rep.link_from(wf.start_point)
    loader.link_from(rep)
    fwd.link_from(loader)
    fwd.link_attrs(loader, ("input", "minibatch_data"))
    api.link_from(fwd)
    api.link_attrs(fwd, ("input", "output"))
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    rep.link_from(api)
    t = threading.Thread(target=wf.run, daemon=True)
    t.start()
    url = "http://127.0.0.1:%d/api" % api.port
    rng = numpy.random.RandomState(0)
    img = (rng.rand(8, 8, 3) * 255).astype(numpy.uint8)   # resized 8→4
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, format="PNG")
    payload = base64.b64encode(buf.getvalue()).decode()
    status, body = _post(url, {"image": payload})
    assert status == 200, body
    got = numpy.asarray(body["result"])
    assert got.shape == (2,) and abs(got.sum() - 1.0) < 1e-4
    # undecodable image → 400, service stays alive
    status, _ = _post(url, {"image": base64.b64encode(b"junk").decode()})
    assert status == 400
    status, body = _post(url, {"image": payload})
    assert status == 200
    loader.close()
    t.join(timeout=10)
    assert not t.is_alive()
    api.stop()


def test_restful_bad_shape_does_not_kill_service():
    """A wrong-shaped (but well-formed) sample must fail THAT request
    with 400 — not raise later on the workflow thread and 504 every
    subsequent request (producer-side validation in feed)."""
    wf, loader, fwd, api = build_serving_workflow()
    t = threading.Thread(target=wf.run, daemon=True)
    t.start()
    url = "http://127.0.0.1:%d/api" % api.port
    status, body = _post(url, {"input": [1.0, 2.0]})     # declared (4,)
    assert status == 400, body
    # the loop survived: a good request still works
    status, body = _post(url, {"input": [0.1, 0.2, 0.3, 0.4]})
    assert status == 200, body
    loader.close()
    t.join(timeout=10)
    assert not t.is_alive()
    api.stop()


def test_dynamic_batching_serves_concurrent_requests():
    """minibatch_size > 1 enables dynamic batching: requests queued
    while a dispatch runs are answered TOGETHER by the next one, each
    client getting its own row — TPU-first serving (the reference ran
    one request per workflow iteration)."""
    wf = vt.Workflow(name="serve-batch")
    rep = Repeater(wf)
    loader = RestfulLoader(wf, sample_shape=(4,), timeout=30.0,
                           minibatch_size=8, name="rest_loader")
    fwd = nn.All2AllSoftmax(wf, output_sample_shape=3, name="fwd")
    api = vt.RESTfulAPI(wf, loader=loader, port=0, request_timeout=30.0)
    rep.link_from(wf.start_point)
    loader.link_from(rep)
    fwd.link_from(loader)
    fwd.link_attrs(loader, ("input", "minibatch_data"))
    api.link_from(fwd)
    api.link_attrs(fwd, ("input", "output"))
    rep.link_from(api)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    url = "http://127.0.0.1:%d/api" % api.port
    rng = numpy.random.RandomState(0)
    xs = rng.rand(6, 4).astype(numpy.float32)
    results = [None] * 6

    def client(i):
        status, body = _post(url, {"input": xs[i].tolist()}, timeout=30)
        results[i] = (status, body)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(6)]
    # clients FIRST, workflow after the queue provably holds several
    # requests — otherwise fast dispatches could legally drain one
    # request each and the batching assertion would be timing luck
    for th in threads:
        th.start()
    deadline = time.time() + 20
    while loader._queue.qsize() < 4 and time.time() < deadline:
        time.sleep(0.02)
    assert loader._queue.qsize() >= 4, loader._queue.qsize()
    t = threading.Thread(target=wf.run, daemon=True)
    t.start()
    for th in threads:
        th.join(timeout=30)
    params = fwd.params_np()
    for i, (status, body) in enumerate(results):
        assert status == 200, (i, body)
        expect = fwd.numpy_apply(params, xs[i:i + 1])[0]
        numpy.testing.assert_allclose(numpy.asarray(body["result"]),
                                      expect, rtol=1e-4, atol=1e-5)
    assert api.requests_served == 6
    # fewer dispatches than requests = batching actually happened
    # (loader.run calls == workflow iterations that served samples)
    assert loader.samples_served == 6
    assert loader.run_count < 6, loader.run_count
    loader.close()
    t.join(timeout=10)
    assert not t.is_alive()
    api.stop()


def test_web_status_metric_history_sparkline():
    """The dashboard accumulates per-workflow metric history server-side
    (the beacon stays a stateless POST) and the page renders it as an
    inline SVG sparkline."""
    server = WebStatusServer(port=0).start()
    base = "http://127.0.0.1:%d" % server.port
    reporter = StatusReporter(base)
    for epoch, m in enumerate([0.9, 0.5, 0.3, 0.2]):
        assert reporter.send({"id": "w1", "name": "m", "epoch": epoch,
                              "metric": m})
    with urllib.request.urlopen(base + "/status.json", timeout=5) as r:
        snap = json.loads(r.read())
    assert snap["w1"]["_history"] == [0.9, 0.5, 0.3, 0.2]
    # non-numeric / non-finite / bool metrics don't poison the series
    # (a bare inf in history would render as invalid JSON 'Infinity'
    # and freeze the dashboard poll for every workflow)
    for bad in ("n/a", float("inf"), float("-inf"), float("nan"), True):
        assert reporter.send({"id": "w1", "name": "m", "metric": bad})
    with urllib.request.urlopen(base + "/status.json", timeout=5) as r:
        raw = r.read().decode()
    snap = json.loads(raw)
    assert snap["w1"]["_history"] == [0.9, 0.5, 0.3, 0.2]
    # the stored payload is sanitized too: bare Infinity/NaN is invalid
    # JSON for the browser's JSON.parse (python json accepts it, so the
    # check must be on the TEXT)
    for tok in ("Infinity", "NaN"):
        assert tok not in raw, raw
    with urllib.request.urlopen(base + "/", timeout=5) as r:
        page = r.read().decode()
    assert "spark" in page and "svg" in page
    # history is bounded
    from veles_tpu.web_status import HISTORY_LEN
    for i in range(HISTORY_LEN + 20):
        server.update("w2", {"metric": float(i)})
    assert len(server.snapshot()["w2"]["_history"]) == HISTORY_LEN
    server.stop()


def test_web_status_drilldown_pages():
    """Per-run drill-down (VERDICT r3 missing #3): the beacon's detail
    payload (unit table, event spans, plot gallery) is served at
    /run.json + /run.html, while the index's status.json stays a
    summary that never re-ships the heavy keys."""
    import base64
    server = WebStatusServer(port=0).start()
    base = "http://127.0.0.1:%d" % server.port
    png = base64.b64encode(b"\x89PNG fake").decode()
    assert StatusReporter(base).send({
        "id": "wf@9", "name": "conv", "device": "tpu", "epoch": 5,
        "metric": 0.11,
        "units": [{"name": "train_step", "cls": "TrainStep",
                   "runs": 40, "time_s": 1.25}],
        "events": [{"name": "snapshot", "type": "single",
                    "time": 1700000000.0, "who": "Snapshotter"}],
        "plots": [{"name": "err.png", "png_b64": png}]})
    with urllib.request.urlopen(base + "/status.json", timeout=5) as r:
        snap = json.loads(r.read())
    assert snap["wf@9"]["epoch"] == 5
    for heavy in ("units", "events", "plots"):
        assert heavy not in snap["wf@9"], heavy
    with urllib.request.urlopen(base + "/run.json?id=wf%409",
                                timeout=5) as r:
        run = json.loads(r.read())
    assert run["units"][0]["name"] == "train_step"
    assert run["events"][0]["who"] == "Snapshotter"
    assert run["plots"][0]["png_b64"] == png
    assert run["_history"] == [0.11]
    with urllib.request.urlopen(base + "/run.html", timeout=5) as r:
        page = r.read().decode()
    assert "metric history" in page and "run.json" in page
    try:
        urllib.request.urlopen(base + "/run.json?id=nope", timeout=5)
        raise AssertionError("unknown id must 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404
    server.stop()


def test_launcher_payload_carries_drilldown_detail():
    """The real beacon body includes the drill-down keys: per-unit
    timing rows and recent event spans (plots only when a graphics
    client is attached)."""
    from veles_tpu.launcher import Launcher
    launcher = Launcher(backend="numpy")
    wf = vt.Workflow(name="wd")
    u = vt.TrivialUnit(wf, name="noop")
    u.event("probe", "single")
    launcher.workflow = wf
    payload = launcher._status_payload()
    assert any(row["name"] == "noop" for row in payload["units"])
    row = next(r for r in payload["units"] if r["name"] == "noop")
    assert set(row) == {"name", "cls", "runs", "time_s"}
    assert any(e["name"] == "probe" for e in payload["events"])
    assert payload["plots"] == []      # no graphics client attached


def test_web_status_detail_carry_forward_and_nested_nan():
    """A beacon that omits a detail key declares it unchanged (the
    launcher skips re-shipping identical plot galleries); non-finite
    floats NESTED in drill-down rows are stringified like top-level
    ones."""
    server = WebStatusServer(port=0).start()
    server.update("w", {"name": "x", "metric": 0.5,
                        "plots": [{"name": "a.png", "png_b64": "QQ=="}],
                        "units": [{"name": "u", "time_s":
                                   float("inf")}]})
    server.update("w", {"name": "x", "metric": 0.4})   # no detail keys
    run = server.entry("w")
    assert run["plots"] == [{"name": "a.png", "png_b64": "QQ=="}]
    assert run["units"][0]["time_s"] == "inf"          # stringified
    assert run["_history"] == [0.5, 0.4]
    # the summary endpoint never leaks the carried-forward detail
    assert "plots" not in server.snapshot()["w"]
    server.stop()


def test_launcher_plot_payload_omits_unchanged(tmp_path):
    """_plot_payload returns the gallery once, then None (key omitted)
    until a PNG's mtime or the file set changes."""
    from veles_tpu.launcher import Launcher

    class FakeGS:
        out_dir = str(tmp_path)

    (tmp_path / "err.png").write_bytes(b"\x89PNG x")
    launcher = Launcher(backend="numpy")
    launcher.graphics_server = FakeGS()
    first = launcher._plot_payload()
    assert [p["name"] for p in first] == ["err.png"]
    assert launcher._plot_payload() is None            # unchanged
    import os
    os.utime(tmp_path / "err.png", (1, 1))             # touched
    assert launcher._plot_payload() is not None
