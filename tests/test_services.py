"""Service layer: RESTful serving unit + web status server
(reference: veles/tests/test_restful.py, test_web_status.py)."""
import json
import time
import threading
import urllib.request

import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn
from veles_tpu.loader.stream import RestfulLoader
from veles_tpu.plumbing import Repeater
from veles_tpu.web_status import StatusReporter, WebStatusServer


def _post(url, payload, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        body = e.read()
        try:
            return e.code, json.loads(body)
        except ValueError:
            return e.code, body.decode(errors="replace")


def build_serving_workflow():
    wf = vt.Workflow(name="serve")
    rep = Repeater(wf)
    loader = RestfulLoader(wf, sample_shape=(4,), timeout=30.0,
                           name="rest_loader")
    fwd = nn.All2AllSoftmax(wf, output_sample_shape=3, name="fwd")
    api = vt.RESTfulAPI(wf, loader=loader, port=0, request_timeout=30.0)
    rep.link_from(wf.start_point)
    loader.link_from(rep)
    fwd.link_from(loader)
    fwd.link_attrs(loader, ("input", "minibatch_data"))
    api.link_from(fwd)
    api.link_attrs(fwd, ("input", "output"))
    rep.link_from(api)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    return wf, loader, fwd, api


def test_restful_api_roundtrip():
    wf, loader, fwd, api = build_serving_workflow()
    t = threading.Thread(target=wf.run, daemon=True)
    t.start()
    url = "http://127.0.0.1:%d/api" % api.port
    x = [0.1, -0.2, 0.3, 0.4]
    status, body = _post(url, {"input": x})
    assert status == 200, body
    got = numpy.asarray(body["result"])
    expect = fwd.numpy_apply(fwd.params_np(),
                             numpy.asarray([x], dtype=numpy.float32))[0]
    numpy.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)
    assert abs(got.sum() - 1.0) < 1e-4
    # malformed request does not kill the service
    status, body = _post(url, {"wrong": 1})
    assert status == 400
    status, body = _post(url, {"input": x})
    assert status == 200
    assert api.requests_served == 2
    loader.close()
    t.join(timeout=10)
    assert not t.is_alive()
    api.stop()


def test_restful_api_rejects_unknown_path():
    wf, loader, fwd, api = build_serving_workflow()
    status, _ = _post("http://127.0.0.1:%d/nope" % api.port, {"input": []},
                      timeout=5)
    assert status == 404
    loader.close()
    api.stop()


def test_web_status_update_and_snapshot():
    server = WebStatusServer(port=0).start()
    base = "http://127.0.0.1:%d" % server.port
    reporter = StatusReporter(base)
    assert reporter.send({"id": "wf@1", "name": "mnist", "device": "tpu",
                          "epoch": 3, "metric": 0.02, "elapsed_sec": 12.5})
    with urllib.request.urlopen(base + "/status.json", timeout=5) as resp:
        snap = json.loads(resp.read())
    assert snap["wf@1"]["name"] == "mnist"
    assert snap["wf@1"]["epoch"] == 3
    with urllib.request.urlopen(base + "/", timeout=5) as resp:
        page = resp.read().decode()
    assert "veles_tpu" in page and "status.json" in page
    status, body = _post(base + "/update", {"no_id": True}, timeout=5)
    assert status == 400
    server.stop()


def test_web_status_stale_eviction():
    server = WebStatusServer(port=0, stale_after=0.0).start()
    server.update("w", {"name": "x"})
    assert server.snapshot() == {}      # immediately stale
    server.stop()


def test_launcher_payload_shape():
    from veles_tpu.launcher import Launcher
    launcher = Launcher(backend="numpy")
    wf = vt.Workflow(name="w")
    launcher.workflow = wf
    payload = launcher._status_payload()
    assert payload["name"] == "w" and "elapsed_sec" in payload


def test_non_object_json_bodies_get_400():
    """Valid-JSON non-dict bodies must 400, not kill the handler thread."""
    server = WebStatusServer(port=0).start()
    status, _ = _post("http://127.0.0.1:%d/update" % server.port, [1, 2],
                      timeout=5)
    assert status == 400
    server.stop()
    wf, loader, fwd, api = build_serving_workflow()
    status, _ = _post("http://127.0.0.1:%d/api" % api.port, "just a string",
                      timeout=5)
    assert status == 400
    loader.close()
    api.stop()


def test_restful_image_serving_roundtrip(tmp_path):
    """Image serving (reference RestfulImageLoader,
    veles/loader/restful.py:133): POST a base64-encoded PNG; the loader
    decodes it with the training-time size/color policy and the
    forward chain answers — the 'input' numeric path keeps working."""
    import base64
    import io
    from PIL import Image
    from veles_tpu.loader import RestfulImageLoader

    wf = vt.Workflow(name="serve-img")
    rep = Repeater(wf)
    loader = RestfulImageLoader(wf, sample_shape=(4, 4, 3),
                                size=(4, 4), color="RGB", timeout=30.0,
                                name="img_loader")
    fwd = nn.All2AllSoftmax(wf, output_sample_shape=2, name="fwd")
    api = vt.RESTfulAPI(wf, loader=loader, port=0, request_timeout=30.0)
    rep.link_from(wf.start_point)
    loader.link_from(rep)
    fwd.link_from(loader)
    fwd.link_attrs(loader, ("input", "minibatch_data"))
    api.link_from(fwd)
    api.link_attrs(fwd, ("input", "output"))
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    rep.link_from(api)
    t = threading.Thread(target=wf.run, daemon=True)
    t.start()
    url = "http://127.0.0.1:%d/api" % api.port
    rng = numpy.random.RandomState(0)
    img = (rng.rand(8, 8, 3) * 255).astype(numpy.uint8)   # resized 8→4
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, format="PNG")
    payload = base64.b64encode(buf.getvalue()).decode()
    status, body = _post(url, {"image": payload})
    assert status == 200, body
    got = numpy.asarray(body["result"])
    assert got.shape == (2,) and abs(got.sum() - 1.0) < 1e-4
    # undecodable image → 400, service stays alive
    status, _ = _post(url, {"image": base64.b64encode(b"junk").decode()})
    assert status == 400
    status, body = _post(url, {"image": payload})
    assert status == 200
    loader.close()
    t.join(timeout=10)
    assert not t.is_alive()
    api.stop()


def test_restful_bad_shape_does_not_kill_service():
    """A wrong-shaped (but well-formed) sample must fail THAT request
    with 400 — not raise later on the workflow thread and 504 every
    subsequent request (producer-side validation in feed)."""
    wf, loader, fwd, api = build_serving_workflow()
    t = threading.Thread(target=wf.run, daemon=True)
    t.start()
    url = "http://127.0.0.1:%d/api" % api.port
    status, body = _post(url, {"input": [1.0, 2.0]})     # declared (4,)
    assert status == 400, body
    # the loop survived: a good request still works
    status, body = _post(url, {"input": [0.1, 0.2, 0.3, 0.4]})
    assert status == 200, body
    loader.close()
    t.join(timeout=10)
    assert not t.is_alive()
    api.stop()


def test_dynamic_batching_serves_concurrent_requests():
    """minibatch_size > 1 enables dynamic batching: requests queued
    while a dispatch runs are answered TOGETHER by the next one, each
    client getting its own row — TPU-first serving (the reference ran
    one request per workflow iteration)."""
    wf = vt.Workflow(name="serve-batch")
    rep = Repeater(wf)
    loader = RestfulLoader(wf, sample_shape=(4,), timeout=30.0,
                           minibatch_size=8, name="rest_loader")
    fwd = nn.All2AllSoftmax(wf, output_sample_shape=3, name="fwd")
    api = vt.RESTfulAPI(wf, loader=loader, port=0, request_timeout=30.0)
    rep.link_from(wf.start_point)
    loader.link_from(rep)
    fwd.link_from(loader)
    fwd.link_attrs(loader, ("input", "minibatch_data"))
    api.link_from(fwd)
    api.link_attrs(fwd, ("input", "output"))
    rep.link_from(api)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    url = "http://127.0.0.1:%d/api" % api.port
    rng = numpy.random.RandomState(0)
    xs = rng.rand(6, 4).astype(numpy.float32)
    results = [None] * 6

    def client(i):
        status, body = _post(url, {"input": xs[i].tolist()}, timeout=30)
        results[i] = (status, body)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(6)]
    # clients FIRST, workflow after the queue provably holds several
    # requests — otherwise fast dispatches could legally drain one
    # request each and the batching assertion would be timing luck
    for th in threads:
        th.start()
    deadline = time.time() + 20
    while loader._queue.qsize() < 4 and time.time() < deadline:
        time.sleep(0.02)
    assert loader._queue.qsize() >= 4, loader._queue.qsize()
    t = threading.Thread(target=wf.run, daemon=True)
    t.start()
    for th in threads:
        th.join(timeout=30)
    params = fwd.params_np()
    for i, (status, body) in enumerate(results):
        assert status == 200, (i, body)
        expect = fwd.numpy_apply(params, xs[i:i + 1])[0]
        numpy.testing.assert_allclose(numpy.asarray(body["result"]),
                                      expect, rtol=1e-4, atol=1e-5)
    assert api.requests_served == 6
    # fewer dispatches than requests = batching actually happened
    # (loader.run calls == workflow iterations that served samples)
    assert loader.samples_served == 6
    assert loader.run_count < 6, loader.run_count
    loader.close()
    t.join(timeout=10)
    assert not t.is_alive()
    api.stop()


def test_web_status_metric_history_sparkline():
    """The dashboard accumulates per-workflow metric history server-side
    (the beacon stays a stateless POST) and the page renders it as an
    inline SVG sparkline."""
    server = WebStatusServer(port=0).start()
    base = "http://127.0.0.1:%d" % server.port
    reporter = StatusReporter(base)
    for epoch, m in enumerate([0.9, 0.5, 0.3, 0.2]):
        assert reporter.send({"id": "w1", "name": "m", "epoch": epoch,
                              "metric": m})
    with urllib.request.urlopen(base + "/status.json", timeout=5) as r:
        snap = json.loads(r.read())
    assert snap["w1"]["_history"] == [0.9, 0.5, 0.3, 0.2]
    # non-numeric / non-finite / bool metrics don't poison the series
    # (a bare inf in history would render as invalid JSON 'Infinity'
    # and freeze the dashboard poll for every workflow)
    for bad in ("n/a", float("inf"), float("-inf"), float("nan"), True):
        assert reporter.send({"id": "w1", "name": "m", "metric": bad})
    with urllib.request.urlopen(base + "/status.json", timeout=5) as r:
        raw = r.read().decode()
    snap = json.loads(raw)
    assert snap["w1"]["_history"] == [0.9, 0.5, 0.3, 0.2]
    # the stored payload is sanitized too: bare Infinity/NaN is invalid
    # JSON for the browser's JSON.parse (python json accepts it, so the
    # check must be on the TEXT)
    for tok in ("Infinity", "NaN"):
        assert tok not in raw, raw
    with urllib.request.urlopen(base + "/", timeout=5) as r:
        page = r.read().decode()
    assert "spark" in page and "svg" in page
    # history is bounded
    from veles_tpu.web_status import HISTORY_LEN
    for i in range(HISTORY_LEN + 20):
        server.update("w2", {"metric": float(i)})
    assert len(server.snapshot()["w2"]["_history"]) == HISTORY_LEN
    server.stop()
