"""Pallas flash-attention kernel vs the exact reference attention
(forward + custom-VJP backward), and its wiring into MultiHeadAttention.
Runs in pallas interpret mode on the CPU test harness; the same kernel
compiles via Mosaic on TPU (verified in bench/verify drives)."""
import jax
import jax.numpy as jnp
import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn
from veles_tpu.memory import Array
from veles_tpu.ops.flash_attention import flash_attention, supported
from veles_tpu.parallel.ring_attention import attention_reference


def qkv(b=2, t=256, h=2, d=64, seed=0):
    rng = numpy.random.RandomState(seed)
    return [jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
            for _ in range(3)]


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference(causal):
    q, k, v = qkv()
    o = flash_attention(q, k, v, causal=causal)
    ref = attention_reference(q, k, v, causal=causal)
    numpy.testing.assert_allclose(numpy.asarray(o), numpy.asarray(ref),
                                  rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_reference(causal):
    q, k, v = qkv(b=1, t=128, h=2, d=32)

    def loss(f):
        return lambda q, k, v: (f(q, k, v, causal=causal) ** 2).sum()

    gf = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(attention_reference), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        scale = float(jnp.abs(b).max())
        numpy.testing.assert_allclose(numpy.asarray(a) / scale,
                                      numpy.asarray(b) / scale,
                                      rtol=1e-4, atol=1e-5)


def test_head_dim_padding():
    """D=32 < 128 lanes: zero padding must not change the result."""
    q, k, v = qkv(t=128, d=32, seed=3)
    o = flash_attention(q, k, v)
    ref = attention_reference(q, k, v)
    numpy.testing.assert_allclose(numpy.asarray(o), numpy.asarray(ref),
                                  rtol=1e-4, atol=1e-5)


def test_multi_lane_head_dim():
    """D=256 > one 128-lane group: runs with multi-lane blocks."""
    q, k, v = qkv(b=1, t=128, h=1, d=256, seed=5)
    o = flash_attention(q, k, v, causal=True)
    ref = attention_reference(q, k, v, causal=True)
    numpy.testing.assert_allclose(numpy.asarray(o), numpy.asarray(ref),
                                  rtol=1e-4, atol=1e-5)


def test_supported_predicate():
    assert supported(256, 64)
    assert not supported(200, 64)       # T not divisible by block
    assert supported(256, 256)          # multi-lane head dim
    assert not supported(256, 1024)     # beyond the VMEM budget bound


def test_mha_unit_routes_through_flash():
    prev = vt.root.common.engine.compute_dtype
    prev_flash = vt.root.common.engine.flash_attention
    vt.root.common.engine.compute_dtype = "float32"
    # CPU harness: production gating skips flash off-TPU; force interpret
    vt.root.common.engine.flash_attention = "force"
    try:
        wf = vt.Workflow(name="t")
        u = nn.MultiHeadAttention(wf, n_heads=2, causal=True)
        x = numpy.random.RandomState(0).randn(2, 128, 64).astype(
            numpy.float32)
        u.input = Array(x)
        u.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        assert u.mesh is None           # single chip → flash eligible
        u.xla_run()
        y_flash = numpy.asarray(u.output.map_read())
        vt.root.common.engine.flash_attention = False
        u._jit_cache.clear()
        u.xla_run()
        y_ref = numpy.asarray(u.output.map_read())
        numpy.testing.assert_allclose(y_flash, y_ref, rtol=1e-4,
                                      atol=1e-5)
        y_np = u.numpy_apply(u.params_np(), x)
        numpy.testing.assert_allclose(y_flash, y_np, rtol=1e-3, atol=1e-4)
    finally:
        vt.root.common.engine.flash_attention = prev_flash
        vt.root.common.engine.compute_dtype = prev


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_bwd_matches_jnp_bwd(causal):
    """The Pallas backward twins the jnp blockwise oracle exactly
    (same math, same f32 accumulation) and the config switch selects
    between them."""
    from veles_tpu.config import root
    rng = numpy.random.RandomState(9)
    q, k, v = (jnp.asarray(rng.randn(2, 256, 2, 64), jnp.float32)
               for _ in range(3))

    def loss_fn(qq, kk, vv):
        return (flash_attention(qq, kk, vv, causal=causal)
                .astype(jnp.float32) ** 2).sum()

    def g(qq, kk, vv):
        return jax.grad(loss_fn, argnums=(0, 1, 2))(qq, kk, vv)

    assert root.common.engine.get("flash_attention_pallas_bwd",
                                  True) is True
    g_pallas = g(q, k, v)
    root.common.engine.flash_attention_pallas_bwd = False
    try:
        jax.clear_caches()      # the switch lives outside the trace
        g_jnp = g(q, k, v)
    finally:
        root.common.engine.flash_attention_pallas_bwd = True
        jax.clear_caches()
    for a, b in zip(g_pallas, g_jnp):
        numpy.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("window", [128, 200, 256])
def test_windowed_forward_matches_reference(window):
    """Sliding window: flash (with dead-block skipping) vs the windowed
    reference mask. Windows chosen to hit block-aligned (128), block-
    straddling (200), and multi-block (256) horizons at bq=bk=128."""
    q, k, v = qkv(t=512, seed=3)
    o = flash_attention(q, k, v, causal=True, window=window)
    ref = attention_reference(q, k, v, causal=True, window=window)
    numpy.testing.assert_allclose(numpy.asarray(o), numpy.asarray(ref),
                                  rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("pallas_bwd", [True, False])
def test_windowed_grads_match_reference(pallas_bwd):
    """Window masking through BOTH backwards (pallas kernels and the
    jnp blockwise fallback) vs autodiff of the windowed reference."""
    prev = vt.root.common.engine.get("flash_attention_pallas_bwd", True)
    vt.root.common.engine.flash_attention_pallas_bwd = pallas_bwd
    try:
        q, k, v = qkv(b=1, t=256, h=2, d=32, seed=4)
        win = 160

        def loss_fl(q, k, v):
            return (flash_attention(q, k, v, causal=True,
                                    window=win) ** 2).sum()

        def loss_ref(q, k, v):
            return (attention_reference(q, k, v, causal=True,
                                        window=win) ** 2).sum()

        g_fl = jax.grad(loss_fl, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_fl, g_ref):
            numpy.testing.assert_allclose(numpy.asarray(a),
                                          numpy.asarray(b),
                                          rtol=2e-4, atol=2e-4)
    finally:
        vt.root.common.engine.flash_attention_pallas_bwd = prev


def test_window_requires_causal():
    q, k, v = qkv(t=256)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, causal=False, window=64)


def test_window_covering_everything_equals_full():
    """window >= T degenerates to full causal attention exactly."""
    q, k, v = qkv(t=256, seed=5)
    o_w = flash_attention(q, k, v, causal=True, window=4096)
    o_f = flash_attention(q, k, v, causal=True)
    numpy.testing.assert_allclose(numpy.asarray(o_w),
                                  numpy.asarray(o_f), rtol=1e-6)


def gqa_qkv(b=2, t=256, h=4, kv=2, d=64, seed=11):
    rng = numpy.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
    k = jnp.asarray(rng.randn(b, t, kv, d).astype(numpy.float32))
    v = jnp.asarray(rng.randn(b, t, kv, d).astype(numpy.float32))
    return q, k, v


def _expand(x, h):
    b, t, kv, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :],
                            (b, t, kv, h // kv, d)).reshape(b, t, h, d)


@pytest.mark.parametrize("causal", [False, True])
def test_grouped_forward_matches_expanded(causal):
    """GQA-native kernel (grouped k/v consumed via index-map head
    remapping, never expanded into operands) vs the same attention on
    pre-expanded K/V."""
    q, k, v = gqa_qkv()
    o = flash_attention(q, k, v, causal=causal)
    ref = attention_reference(q, _expand(k, 4), _expand(v, 4),
                              causal=causal)
    numpy.testing.assert_allclose(numpy.asarray(o), numpy.asarray(ref),
                                  rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("pallas_bwd", [True, False])
def test_grouped_grads_match_expanded(pallas_bwd):
    """GQA grads through BOTH backwards. The pallas dkv grid folds
    (query-head-in-group, q-block) into its sequential dim so each kv
    head accumulates all its query heads' contributions; dk/dv must
    equal the group-summed expanded gradients."""
    prev = vt.root.common.engine.get("flash_attention_pallas_bwd", True)
    vt.root.common.engine.flash_attention_pallas_bwd = pallas_bwd
    try:
        q, k, v = gqa_qkv(b=1, t=128, h=4, kv=2, d=32, seed=12)

        def loss_fl(q, k, v):
            return (flash_attention(q, k, v, causal=True) ** 2).sum()

        def loss_ref(q, k, v):
            return (attention_reference(
                q, _expand(k, 4), _expand(v, 4), causal=True) ** 2).sum()

        g_fl = jax.grad(loss_fl, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_fl, g_ref):
            assert a.shape == b.shape
            numpy.testing.assert_allclose(numpy.asarray(a),
                                          numpy.asarray(b),
                                          rtol=2e-4, atol=2e-4)
    finally:
        vt.root.common.engine.flash_attention_pallas_bwd = prev


def test_grouped_windowed_forward():
    """GQA x sliding window in one kernel call."""
    q, k, v = gqa_qkv(t=512, seed=13)
    o = flash_attention(q, k, v, causal=True, window=200)
    ref = attention_reference(q, _expand(k, 4), _expand(v, 4),
                              causal=True, window=200)
    numpy.testing.assert_allclose(numpy.asarray(o), numpy.asarray(ref),
                                  rtol=1e-4, atol=1e-5)


def test_mqa_extreme_grouping():
    """kv=1 (multi-query): every query head reads the single KV head."""
    q, k, v = gqa_qkv(h=8, kv=1, seed=14)
    o = flash_attention(q, k, v, causal=True)
    ref = attention_reference(q, _expand(k, 8), _expand(v, 8),
                              causal=True)
    numpy.testing.assert_allclose(numpy.asarray(o), numpy.asarray(ref),
                                  rtol=1e-4, atol=1e-5)


def test_mismatched_kv_heads_refused():
    q, k, v = gqa_qkv(h=4, kv=2)
    with pytest.raises(ValueError, match="head counts"):
        flash_attention(q, k, v[:, :, :1], causal=True)
    q2 = jnp.zeros((1, 256, 3, 64), jnp.float32)
    with pytest.raises(ValueError, match="head counts"):
        flash_attention(q2, jnp.zeros((1, 256, 2, 64), jnp.float32),
                        jnp.zeros((1, 256, 2, 64), jnp.float32),
                        causal=True)
