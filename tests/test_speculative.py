"""Speculative decoding (nn/speculative.py): greedy output must be
IDENTICAL to the target model's own cached greedy decode, across
acceptance regimes; stats sane; misuse rejected."""
import numpy
import pytest

import veles_tpu as vt
from veles_tpu import prng
from veles_tpu.error import VelesError
from veles_tpu.nn.speculative import generate_speculative

from conftest import import_model


@pytest.fixture(scope="module")
def lms():
    lm = import_model("char_lm")
    prng.seed_all(4321)
    target = lm.build_workflow(epochs=3, minibatch_size=64, n_blocks=2,
                               dim=32, n_train=512, n_valid=128)
    target.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    target.run()
    prng.seed_all(99)
    draft = lm.build_workflow(epochs=2, minibatch_size=64, n_blocks=1,
                              dim=16, n_train=512, n_valid=128)
    draft.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    draft.run()
    return lm, target, draft


def test_speculative_matches_target_greedy(lms):
    lm, target, draft = lms
    rng = numpy.random.RandomState(5)
    prompt = list(lm.make_corpus(rng, lm.SEQ_LEN // 2))
    want = lm.generate(target, prompt, 24, temperature=0)
    for gamma in (1, 3, 4):
        got, stats = generate_speculative(target, draft, prompt, 24,
                                          gamma=gamma)
        assert got == want, (gamma, got, want)
        assert stats["rounds"] >= 1
        assert 0.0 <= stats["acceptance"] <= 1.0


def test_speculative_self_draft_accepts_everything(lms):
    """Draft == target: every draft token verifies, so rounds ~
    n_new/gamma and acceptance == 1 — the accept plumbing's sharpest
    self-check (output still exactly greedy)."""
    lm, target, _ = lms
    rng = numpy.random.RandomState(6)
    prompt = list(lm.make_corpus(rng, lm.SEQ_LEN // 2))
    want = lm.generate(target, prompt, 20, temperature=0)
    got, stats = generate_speculative(target, target, prompt, 20,
                                      gamma=4)
    assert got == want
    assert stats["acceptance"] == 1.0
    assert stats["rounds"] <= (20 // 4) + 1, stats


def test_speculative_batched_rows_match_solo_decodes(lms):
    """Batched speculation with per-row accept-length divergence:
    EVERY row of the batch must equal its own solo greedy decode
    (VERDICT r4 item 4's CI gate). Different prompts force different
    per-row acceptance trajectories."""
    lm, target, draft = lms
    prompts = [list(lm.make_corpus(numpy.random.RandomState(s),
                                   lm.SEQ_LEN // 2))
               for s in (7, 8, 9)]
    got, stats = generate_speculative(target, draft, prompts, 20,
                                      gamma=3)
    assert len(got) == 3
    for row, prompt in zip(got, prompts):
        solo, _ = generate_speculative(target, draft, prompt, 20,
                                       gamma=3)
        assert row == solo, (prompt, row, solo)
        # and solo greedy speculation ≡ the target's own greedy decode
        assert row == lm.generate(target, prompt, 20, temperature=0)
    assert len(stats["acceptance"]) == 3
    assert all(0.0 <= a <= 1.0 for a in stats["acceptance"])
    assert all(r >= 1 for r in stats["rounds"])
    assert 0.0 <= stats["mean_acceptance"] <= 1.0


def test_speculative_batched_self_draft(lms):
    """Self-draft rows accept everything; rounds hit the floor."""
    lm, target, _ = lms
    prompts = [list(lm.make_corpus(numpy.random.RandomState(s),
                                   lm.SEQ_LEN // 2)) for s in (11, 12)]
    got, stats = generate_speculative(target, target, prompts, 16,
                                      gamma=4)
    for row, prompt in zip(got, prompts):
        assert row == lm.generate(target, prompt, 16, temperature=0)
    assert stats["mean_acceptance"] == 1.0
    assert max(stats["rounds"]) <= (16 // 4) + 1


def test_speculative_rejects_ragged_batch(lms):
    lm, target, draft = lms
    with pytest.raises(VelesError, match="EQUAL-length"):
        generate_speculative(target, draft, [[1, 2], [3, 4, 5]], 8)
    with pytest.raises(VelesError, match="flat id list"):
        generate_speculative(
            target, draft, [[[1, 2]], [[3, 4]]], 8)


def test_speculative_rejects_bad_gamma(lms):
    lm, target, draft = lms
    with pytest.raises(ValueError, match="gamma"):
        generate_speculative(target, draft, [1, 2, 3], 8, gamma=0)


def test_stochastic_accept_preserves_target_distribution():
    """The Leviathan accept/resample rule, Monte-Carlo: with proposals
    drawn from p_d, the emitted token's marginal must be EXACTLY p_t —
    for an adversarially different draft. 20k trials, 5-sigma gate."""
    import jax
    import jax.numpy as jnp
    from veles_tpu.nn.speculative import _stochastic_accept
    rng = numpy.random.RandomState(0)
    v = 8
    pt = rng.dirichlet(numpy.ones(v)).astype(numpy.float32)
    pd = rng.dirichlet(numpy.ones(v) * 0.3).astype(numpy.float32)
    ptj = jnp.asarray(pt)[None, :]
    pdj = jnp.asarray(pd)[None, :]

    def one(key):
        kd, ka = jax.random.split(key)
        d = jax.random.categorical(kd, jnp.log(pdj[0]))[None]
        a, fix = _stochastic_accept(ka, ptj, pdj, d.astype(jnp.int32))
        return jnp.where(a >= 1, d[0], fix)

    n = 20000
    toks = jax.vmap(one)(jax.random.split(jax.random.PRNGKey(7), n))
    counts = numpy.bincount(numpy.asarray(toks), minlength=v)
    emp = counts / n
    sigma = numpy.sqrt(pt * (1 - pt) / n)
    assert (numpy.abs(emp - pt) < 5 * sigma + 1e-3).all(), (emp, pt)


def test_speculative_stochastic_end_to_end(lms):
    """temperature > 0: runs, stays in-vocab, seeds decorrelate, and
    the greedy path is untouched by the new plumbing."""
    lm, target, draft = lms
    rng = numpy.random.RandomState(8)
    prompt = list(lm.make_corpus(rng, lm.SEQ_LEN // 2))
    got1, stats = generate_speculative(target, draft, prompt, 24,
                                       gamma=3, temperature=0.9,
                                       seed=1)
    got2, _ = generate_speculative(target, draft, prompt, 24,
                                   gamma=3, temperature=0.9, seed=2)
    assert len(got1) == len(got2) == 24
    assert all(0 <= t < lm.VOCAB for t in got1 + got2)
    assert got1 != got2            # stochastic paths decorrelate
    assert 0.0 <= stats["acceptance"] <= 1.0
    # greedy regression guard after the stochastic refactor
    want = lm.generate(target, prompt, 24, temperature=0)
    got, _ = generate_speculative(target, draft, prompt, 24, gamma=3)
    assert got == want
