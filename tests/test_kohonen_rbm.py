"""Kohonen SOM + RBM units: XLA-vs-numpy oracle agreement and small
convergence checks (reference test strategy SURVEY.md §4)."""
import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn
from veles_tpu.memory import Array
from veles_tpu.nn.kohonen import som_step
from veles_tpu.nn.rbm import cd1_step


def dev():
    return vt.XLADevice(mesh_axes={"data": 1})


@pytest.fixture(autouse=True)
def f32_compute():
    prev = vt.root.common.engine.compute_dtype
    vt.root.common.engine.compute_dtype = "float32"
    yield
    vt.root.common.engine.compute_dtype = prev


def clusters(n=96, seed=0):
    rng = numpy.random.RandomState(seed)
    centers = numpy.array([[0.0, 0.0], [4.0, 4.0], [0.0, 4.0]],
                          dtype=numpy.float32)
    x = numpy.concatenate([
        c + 0.3 * rng.randn(n // 3, 2).astype(numpy.float32)
        for c in centers])
    rng.shuffle(x)
    return x


def test_kohonen_forward_oracle():
    wf = vt.Workflow(name="t")
    u = nn.KohonenForward(wf, shape=(4, 4))
    x = clusters()
    u.input = Array(x)
    u.initialize(device=dev())
    u.xla_run()
    y_xla = numpy.asarray(u.output.map_read())
    y_np = u.numpy_apply(u.params_np(), x)
    numpy.testing.assert_array_equal(y_xla, y_np)
    assert y_xla.dtype == numpy.int32
    assert (0 <= y_xla).all() and (y_xla < 16).all()


def test_kohonen_trainer_oracle_step():
    """One batch-SOM step agrees between jitted path and numpy."""
    wf = vt.Workflow(name="t")
    u = nn.KohonenTrainer(wf, shape=(3, 3))
    x = clusters(30)
    u.input = Array(x)
    u.initialize(device=dev())
    w0 = numpy.array(u.weights.map_read())
    w_np, win_np, qerr_np = som_step(w0.copy(), u.grid, x, 0.4, 1.2,
                                     numpy)
    u.xla_run()
    # re-run the same step from the same start on the oracle path
    u2 = nn.KohonenTrainer(wf, shape=(3, 3), name="t2")
    u2.input = Array(x)
    u2.initialize(device=dev())
    u2.weights.reset(w0.copy())
    u2.numpy_run()
    # both used schedule() at time 0 — same lr/sigma
    numpy.testing.assert_allclose(numpy.asarray(u.weights.map_read()),
                                  u2.weights.map_read(), rtol=1e-4,
                                  atol=1e-5)
    numpy.testing.assert_array_equal(u.winners, u2.winners)


def test_kohonen_convergence():
    """Quantization error falls as the map organizes."""
    wf = vt.Workflow(name="t")
    u = nn.KohonenTrainer(wf, shape=(5, 5), lr0=0.6, decay=60.0)
    x = clusters(150)
    u.input = Array(x)
    u.initialize(device=dev())
    u.xla_run()
    first = u.quantization_error
    for _ in range(40):
        u.xla_run()
    assert u.quantization_error < first * 0.5, (first,
                                                u.quantization_error)
    m = u.get_metric_values()
    assert m["som_steps"] == 41


def test_kohonen_state_roundtrip():
    wf = vt.Workflow(name="t")
    u = nn.KohonenTrainer(wf, shape=(3, 3))
    u.input = Array(clusters(30))
    u.initialize(device=dev())
    u.xla_run()
    sd = u.state_dict()
    u2 = nn.KohonenTrainer(wf, shape=(3, 3), name="u2")
    u2.input = Array(clusters(30))
    u2.initialize(device=dev())
    u2.load_state_dict(sd)
    assert u2.time == u.time
    numpy.testing.assert_allclose(u2.weights.map_read(),
                                  numpy.asarray(u.weights.map_read()))


def bars(n=64, side=4, seed=1):
    """Bars dataset: each sample lights up full rows/columns."""
    rng = numpy.random.RandomState(seed)
    x = numpy.zeros((n, side, side), dtype=numpy.float32)
    for i in range(n):
        for r in range(side):
            if rng.rand() < 0.3:
                x[i, r, :] = 1.0
    return x.reshape(n, side * side)


def test_rbm_forward_oracle():
    wf = vt.Workflow(name="t")
    u = nn.RBM(wf, n_hidden=12)
    x = bars()
    u.input = Array(x)
    u.initialize(device=dev())
    u.xla_run()
    y_xla = numpy.asarray(u.output.map_read())
    y_np = u.numpy_apply(u.params_np(), x)
    numpy.testing.assert_allclose(y_xla, y_np, rtol=1e-4, atol=1e-5)
    assert ((0 < y_xla) & (y_xla < 1)).all()


def test_rbm_cd1_oracle_same_noise():
    """With identical sampling uniforms, the jitted CD-1 update equals the
    numpy oracle update."""
    wf = vt.Workflow(name="t")
    u = nn.RBMTrainer(wf, n_hidden=8, learning_rate=0.2)
    x = bars(16)
    u.input = Array(x)
    u.initialize(device=dev())
    params = {k: numpy.array(v.map_read())
              for k, v in u.param_arrays().items()}
    uni = numpy.random.RandomState(7).rand(16, 8).astype(numpy.float32)
    new_np, err_np = cd1_step(params, x, uni, 0.2, numpy)
    import jax.numpy as jnp
    new_x, err_x = cd1_step({k: jnp.asarray(v) for k, v in params.items()},
                            jnp.asarray(x), jnp.asarray(uni), 0.2, jnp)
    for k in params:
        numpy.testing.assert_allclose(numpy.asarray(new_x[k]), new_np[k],
                                      rtol=1e-4, atol=1e-5)
    assert abs(float(err_x) - float(err_np)) < 1e-5


def test_rbm_training_reduces_reconstruction_error():
    wf = vt.Workflow(name="t")
    u = nn.RBMTrainer(wf, n_hidden=16, learning_rate=0.5)
    x = bars(64)
    u.input = Array(x)
    u.initialize(device=dev())
    u.xla_run()
    first = u.reconstruction_error
    for _ in range(200):
        u.xla_run()
    assert u.reconstruction_error < first * 0.7, (
        first, u.reconstruction_error)
    # mean-field reconstruction resembles the data
    vhat = u.reconstruct_np({k: numpy.array(v.map_read())
                             for k, v in u.param_arrays().items()}, x)
    assert ((vhat - x) ** 2).mean() < 0.1
