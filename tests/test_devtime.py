"""Device-time measurement plane (veles_tpu/telemetry/devtime.py) and
the ISSUE-9 roofline features it gates.

The load-bearing locks:
- trace-event parsing math: device streams identified, envelope lanes
  ("XLA Modules") and host processes excluded, nested/overlapping
  events interval-unioned (never double counted), torn traces
  salvaged like ``spans.read_jsonl``;
- span attribution: device intervals clip onto telemetry span windows
  under an explicit or estimated clock offset;
- the host-sync fallback path: counted, wall ≥ device, stamped
  ``source="host_sync"``;
- gate arithmetic: device-time medians compare at the stated
  tolerance, CPU/smoke documents prove harness invariants instead,
  legacy documents (no ``device_time_s``) fall back to wall-clock
  with a counted ``veles_bench_legacy_sections_total`` warning — and
  never crash;
- the fused scale-bias-activation epilogue and bf16 activation
  storage are BIT-IDENTICAL off, and the epilogue removes (not just
  renames) standalone-chain dispatches — the dispatch-count lock;
- the epilogue composes with TensorMonitor taps (monitoring on keeps
  the plan active — no silent unfused fallback);
- ``veles-tpu trace self-time`` summarizes real and torn traces.
"""
import gzip
import json
import os
import sys

import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn, prng
from veles_tpu.config import root
from veles_tpu.loader import FullBatchLoader
from veles_tpu.memory import Array
from veles_tpu.ops.fused_fc import install_epilogues, plan_epilogues
from veles_tpu.telemetry import devtime
from veles_tpu.telemetry.counters import counters

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _reset_knobs():
    """Every test starts from the shipped defaults (all ISSUE-9 knobs
    OFF, profiler capture OFF so no test pays trace overhead) and
    leaves no residue."""
    prev_prof = root.common.telemetry.devtime.get("profiler", "auto")
    root.common.telemetry.devtime.profiler = "off"
    yield
    root.common.telemetry.devtime.profiler = prev_prof
    root.common.engine.fused_epilogue = False
    root.common.engine.bf16_activations = False
    root.common.engine.conv_lane_pad = False
    root.common.engine.mixed_precision = False
    root.common.telemetry.tensormon.enabled = False


def _fake_trace(extra=()):
    """A minimal XLA-shaped trace: one TPU device process with an
    "XLA Ops" stream (two overlapping events covering 150 us) and an
    enveloping "XLA Modules" lane, plus a busy host process that must
    not count."""
    return [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "process_name", "pid": 2,
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "name": "thread_name", "pid": 1, "tid": 10,
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "name": "thread_name", "pid": 1, "tid": 11,
         "args": {"name": "XLA Modules"}},
        {"ph": "X", "pid": 1, "tid": 10, "ts": 0.0, "dur": 100.0,
         "name": "fusion.1"},
        {"ph": "X", "pid": 1, "tid": 10, "ts": 50.0, "dur": 100.0,
         "name": "fusion.2"},
        {"ph": "X", "pid": 1, "tid": 11, "ts": 0.0, "dur": 1000.0,
         "name": "jit_epoch_block"},
        {"ph": "X", "pid": 2, "tid": 1, "ts": 0.0, "dur": 99999.0,
         "name": "python"},
    ] + list(extra)


# -- parsing math -------------------------------------------------------------

def test_interval_union_never_double_counts():
    union = devtime._interval_union_us
    assert union([]) == 0.0
    assert union([(0, 10)]) == 10.0
    assert union([(0, 10), (5, 15)]) == 15.0        # overlap merges
    assert union([(0, 10), (2, 5)]) == 10.0         # nested absorbs
    assert union([(0, 10), (20, 30)]) == 20.0       # disjoint sums
    assert union([(20, 30), (0, 10), (5, 12)]) == 22.0  # unsorted ok


def test_device_self_time_filters_streams():
    st = devtime.device_self_time(_fake_trace())
    # 150 us on the ops stream; the Modules envelope and the host
    # process are excluded (they would triple the number)
    assert st["device_time_s"] == pytest.approx(150e-6)
    assert st["n_events"] == 2
    assert list(st["by_stream"]) == ["/device:TPU:0/XLA Ops"]


def test_device_self_time_without_ops_thread_uses_all_device_lanes():
    evs = [e for e in _fake_trace()
           if not (e.get("ph") == "M" and e.get("tid") == 10
                   and e.get("name") == "thread_name")
           and not (e.get("ph") == "M" and e.get("tid") == 11
                    and e.get("name") == "thread_name")]
    st = devtime.device_self_time(evs)
    # no named "XLA Ops" lane: every device-pid thread counts,
    # per-thread unions summed (two streams here)
    assert st["device_time_s"] == pytest.approx(150e-6 + 1000e-6)
    assert st["n_events"] == 3


def test_attribute_spans_clips_and_aggregates():
    evs = _fake_trace()
    spans = [
        {"name": "train_step.epoch_block", "ts": 0.0, "dur": 75e-6},
        {"name": "train_step.epoch_block", "ts": 100e-6, "dur": 50e-6},
        {"name": "unit.loader", "ts": 200e-6, "dur": 50e-6},
    ]
    per = devtime.attribute_spans(evs, spans, offset_us=0.0)
    blk = per["train_step.epoch_block"]
    # window 1 covers [0, 75) of the 150 us union; window 2 [100, 150)
    assert blk["device_time_s"] == pytest.approx(125e-6)
    assert blk["spans"] == 2
    assert per["unit.loader"]["device_time_s"] == 0.0
    # default offset aligns earliest device event to earliest span:
    # shifting every span by a constant changes nothing
    shifted = [dict(s, ts=s["ts"] + 1000.0) for s in spans]
    per2 = devtime.attribute_spans(evs, shifted)
    assert per2["train_step.epoch_block"]["device_time_s"] == \
        pytest.approx(125e-6)


# -- trace loading + salvage --------------------------------------------------

def test_load_trace_events_plain_gz_and_bare_list(tmp_path):
    doc = {"displayTimeUnit": "ns", "traceEvents": _fake_trace()}
    plain = tmp_path / "t.json"
    plain.write_text(json.dumps(doc))
    assert len(devtime.load_trace_events(str(plain))) == 8
    gz = tmp_path / "t.json.gz"
    with gzip.open(str(gz), "wb") as f:
        f.write(json.dumps(doc).encode())
    assert len(devtime.load_trace_events(str(gz))) == 8
    bare = tmp_path / "bare.json"
    bare.write_text(json.dumps(_fake_trace()))
    assert len(devtime.load_trace_events(str(bare))) == 8


def test_torn_trace_salvaged_with_warning(tmp_path, caplog):
    """A capture killed mid-write must still summarize — the
    spans.read_jsonl hardening, mirrored: complete event prefix
    parsed, ONE counted warning, no raise."""
    raw = json.dumps({"traceEvents": _fake_trace()})
    torn = tmp_path / "torn.json"
    # tear inside the LAST event object: 7 complete events survive
    torn.write_text(raw[:raw.rindex('{"ph": "X", "pid": 2') + 10])
    import logging
    with caplog.at_level(logging.WARNING, "veles_tpu.telemetry"):
        evs = devtime.load_trace_events(str(torn))
    assert len(evs) == 7
    assert any("salvaged" in r.message for r in caplog.records)
    st = devtime.device_self_time(evs)
    assert st["device_time_s"] == pytest.approx(150e-6)


def test_self_time_cli(tmp_path, capsys):
    from veles_tpu.__main__ import main
    trace = tmp_path / "trace.json"
    trace.write_text(json.dumps({"traceEvents": _fake_trace()}))
    spans = tmp_path / "run.jsonl"
    spans.write_text(json.dumps(
        {"name": "train_step.epoch_block", "ts": 0.0, "dur": 150e-6,
         "sid": 1, "tid": 1}) + "\n")
    rc = main(["trace", "self-time", str(trace),
               "--spans", str(spans)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "/device:TPU:0/XLA Ops" in out
    assert "train_step.epoch_block" in out
    # a missing file is a clean rc=1, not a traceback
    assert main(["trace", "self-time",
                 str(tmp_path / "nope.json")]) == 1


# -- capture fallback ---------------------------------------------------------

def test_measure_fallback_counts_and_brackets_with_sync():
    calls = {"fn": 0, "sync": 0}

    def fn():
        calls["fn"] += 1

    def sync():
        calls["sync"] += 1

    before = counters.snapshot()
    rec = devtime.measure(fn, sync, calls=3)
    delta = counters.delta(before)
    assert rec["source"] == "host_sync"
    assert rec["calls"] == 3 and calls["fn"] == 3
    assert calls["sync"] == 2            # leading + trailing bracket
    assert rec["wall_time_s"] >= rec["device_time_s"] > 0
    assert rec["device_time_per_call"] == \
        pytest.approx(rec["device_time_s"] / 3)
    assert delta.get("veles_devtime_fallbacks_total") == 1
    assert not delta.get("veles_devtime_captures_total")


def test_measure_windows_stamps_devtimes():
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    import itertools
    ticks = itertools.count()

    def run_epoch():
        next(ticks)
        return 10

    rates, eps, durs, devs = bench.measure_windows(
        run_epoch, lambda: None, n_windows=2, secs=0.01, min_epochs=1)
    assert len(rates) == len(eps) == len(durs) == len(devs) == 2
    for d, win in zip(durs, devs):
        assert win["source"] == "host_sync"
        assert win["wall_time_s"] == win["device_time_s"] == d


# -- gate arithmetic ----------------------------------------------------------

def _sec(per_epoch=0.5, source="profiler", **over):
    out = {"device_time_s": per_epoch * 4, "wall_time_s": per_epoch * 5,
           "device_time_per_epoch": per_epoch, "source": source}
    out.update(over)
    return out


def test_compare_sections_tolerance_arithmetic():
    ok = devtime.compare_sections("ae", _sec(0.5), _sec(0.6))
    assert ok == []                       # 1.2x < 1.25x tolerance
    bad = devtime.compare_sections("ae", _sec(0.5), _sec(0.7))
    assert bad and "device_time_per_epoch regressed" in bad[0]
    # invariants-only mode (CPU CI): the same regression passes
    assert devtime.compare_sections("ae", _sec(0.5), _sec(0.7),
                                    timing=False) == []
    # a looser tolerance (host-sync sources) passes it too
    assert devtime.compare_sections(
        "ae", _sec(0.5), _sec(0.7),
        tolerance=devtime.LEGACY_TOLERANCE) == []


def test_compare_sections_invariants():
    bad = devtime.compare_sections("ae", _sec(), _sec(0.0))
    assert any("must be > 0" in f for f in bad)
    wall = _sec()
    wall["wall_time_s"] = wall["device_time_s"] / 2
    bad = devtime.compare_sections("ae", _sec(), wall)
    assert any("cannot exceed the synced wall window" in f
               for f in bad)
    bad = devtime.compare_sections("ae", _sec(),
                                   _sec(source="guesswork"))
    assert any("unknown devtime source" in f for f in bad)
    missing = _sec()
    del missing["device_time_per_epoch"]
    bad = devtime.compare_sections("ae", _sec(), missing)
    assert any("lacks device_time_per_epoch" in f for f in bad)


def test_compare_sections_legacy_wallclock_fallback():
    """Satellite lock: old BENCH_*.json without device_time_s fields
    must not crash the gate — wall-clock comparison with a counted
    veles_bench_legacy_sections_total warning."""
    before = counters.snapshot()
    # legacy baseline, modern current: counted, rate compared loosely
    assert devtime.compare_sections("mnist", None, _sec(),
                                    base_rate=100.0,
                                    cur_rate=50.0) == []
    delta = counters.delta(before)
    assert delta.get("veles_bench_legacy_sections_total") == 1
    # total collapse beyond even relay weather still fails
    bad = devtime.compare_sections("mnist", None, _sec(),
                                   base_rate=100.0, cur_rate=1.0)
    assert any("collapsed" in f for f in bad)
    # losing the record relative to the baseline is a format
    # regression and fails outright
    bad = devtime.compare_sections("mnist", _sec(), None,
                                   base_rate=1.0, cur_rate=1.0)
    assert any("lost its devtime record" in f for f in bad)


def test_gate_devtime_on_documents():
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    modern = {"platform": "tpu", "smoke": False,
              "value": 100.0, "devtime": _sec(0.5),
              "extras": [{"metric": "lm",
                          "tokens_per_sec_per_chip": 10.0,
                          "devtime": _sec(0.2)}]}
    same = json.loads(json.dumps(modern))
    assert bench.gate_devtime(modern, same) == []
    worse = json.loads(json.dumps(modern))
    worse["devtime"]["device_time_per_epoch"] = 1.0
    failures = bench.gate_devtime(modern, worse)
    assert failures and "headline" in failures[0]
    # CPU/smoke documents prove invariants instead of timing ratios
    cpu_doc = json.loads(json.dumps(worse))
    cpu_doc["platform"] = "cpu"
    assert bench.gate_devtime(modern, cpu_doc) == []
    broken = json.loads(json.dumps(cpu_doc))
    del broken["devtime"]["source"]
    assert bench.gate_devtime(modern, broken)
    # legacy baseline never crashes and is counted
    before = counters.snapshot()
    legacy = {"value": 90.0, "extras": []}
    assert bench.gate_devtime(legacy, modern) == []
    assert counters.delta(before).get(
        "veles_bench_legacy_sections_total") == 1
    # skipped extras (no devtime, no rate) are ignored silently
    skipped = json.loads(json.dumps(modern))
    skipped["extras"] = [{"metric": "lm",
                          "skipped": "cpu fallback"}]
    before = counters.snapshot()
    assert bench.gate_devtime(modern, skipped) == []
    assert not counters.delta(before).get(
        "veles_bench_legacy_sections_total")


# -- roofline features: bit-identical off, fewer dispatches on ---------------

class BlobsLoader(FullBatchLoader):
    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(7)
        data = rng.randn(120, 10).astype(numpy.float32)
        labels = (data.sum(axis=1) > 0).astype(numpy.int32)
        self.create_originals(data, labels)
        self.class_lengths = [0, 40, 80]


def _train(epilogue=False, bf16=False, amp=False, tensormon=False,
           epochs=2):
    """A tiny chain WITH a standalone activation unit (the epilogue's
    fold target) trained for two epochs; returns the workflow."""
    root.common.engine.fused_epilogue = epilogue
    root.common.engine.bf16_activations = bf16
    root.common.engine.mixed_precision = amp
    root.common.telemetry.tensormon.enabled = tensormon
    prng.seed_all(1234)
    loader = BlobsLoader(None, minibatch_size=40, name="dv-blobs")
    wf = nn.StandardWorkflow(
        name="dv-wf",
        layers=[{"type": "all2all", "output_sample_shape": 8},
                {"type": "activation_tanh"},
                {"type": "softmax", "output_sample_shape": 2}],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=epochs, fail_iterations=100))
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    return wf


def _state_leaves(wf):
    import jax
    return jax.tree_util.tree_leaves(jax.device_get(
        (wf.train_step.params, wf.train_step.opt_state)))


def _assert_bit_identical(wf_a, wf_b):
    la, lb = _state_leaves(wf_a), _state_leaves(wf_b)
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        numpy.testing.assert_array_equal(numpy.asarray(a),
                                         numpy.asarray(b))


def test_epilogue_train_step_bit_identical_and_plan_active():
    wf_off = _train(epilogue=False)
    wf_on = _train(epilogue=True)
    assert wf_off.train_step._epilogue is None
    assert wf_on.train_step._epilogue       # the plan engaged
    _assert_bit_identical(wf_off, wf_on)
    assert wf_off.train_step._dispatch_counts == \
        wf_on.train_step._dispatch_counts


def test_epilogue_composes_with_tensormon_no_silent_fallback():
    """Satellite lock: monitoring ON must keep the epilogue plan
    active (the taps read the post-epilogue head output) — never a
    silent fall-back to the unfused chain."""
    wf = _train(epilogue=True, tensormon=True)
    assert wf.train_step._epilogue          # still fused
    assert wf.train_step._tensormon is not None
    wf_ref = _train(epilogue=False, tensormon=True)
    _assert_bit_identical(wf_ref, wf)


def test_fused_fc_reject_message_mentions_epilogue_path():
    """Satellite lock: the fused_fc_scan tensormon rejection names the
    epilogue path as what the general scan keeps."""
    root.common.engine.fused_fc_scan = True
    root.common.telemetry.tensormon.enabled = True
    msgs = []
    prng.seed_all(99)
    loader = BlobsLoader(None, minibatch_size=40, name="rj-blobs")
    wf = nn.StandardWorkflow(
        name="rj-wf",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 8},
                {"type": "softmax", "output_sample_shape": 2}],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=1, fail_iterations=100))
    orig = wf.train_step.info
    wf.train_step.info = lambda fmt, *a: msgs.append(fmt % a if a
                                                    else fmt)
    try:
        wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    finally:
        wf.train_step.info = orig
        root.common.engine.fused_fc_scan = False
        root.common.telemetry.tensormon.enabled = False
    assert wf.train_step._fused_fc is None
    rejected = [m for m in msgs if "ineligible" in m]
    assert rejected and "fused scale-bias-activation epilogue" in \
        rejected[0]


def test_standalone_epilogue_removes_dispatches_bit_exact():
    """THE dispatch-count lock: on the standalone forward path the
    fused epilogue must REMOVE the activation unit's dispatch (2 → 1
    per batch) while producing bit-identical output."""
    root.common.engine.compute_dtype = "float32"

    def chain(fold):
        prng.seed_all(77)
        wf = vt.Workflow(name="c")
        a = nn.All2All(wf, name="fc", output_sample_shape=6)
        t = nn.ForwardTanh(wf, name="act")
        rngs = numpy.random.RandomState(3)
        x = rngs.randn(4, 5).astype(numpy.float32)
        a.input = Array(x, name="x")
        dev = vt.XLADevice(mesh_axes={"data": 1})
        a.initialize(device=dev)
        t.input = a.output
        t.initialize(device=dev)
        plan = install_epilogues([a, t], force=fold)
        assert bool(plan) == fold
        before = counters.get("veles_dispatches_total")
        a.run()
        t.run()
        n = int(counters.get("veles_dispatches_total") - before)
        return numpy.asarray(t.output.map_read()), n

    y_off, n_off = chain(False)
    y_on, n_on = chain(True)
    numpy.testing.assert_array_equal(y_off, y_on)
    assert (n_off, n_on) == (2, 1)      # removed, not renamed


def test_epilogue_keeps_every_stage_output_fresh_and_uninstalls():
    """Review hardening: (1) the fused program assigns EVERY stage's
    output array — a non-chain consumer linked to the producer's
    output must read exactly what the unfused path wrote, not stale
    device memory; (2) re-installing with the knob off restores the
    unfused dispatch layout (no sticky fold flags, no stale jitted
    closure)."""
    root.common.engine.compute_dtype = "float32"
    prng.seed_all(77)
    wf = vt.Workflow(name="c2")
    a = nn.All2All(wf, name="fc", output_sample_shape=6)
    t = nn.ForwardTanh(wf, name="act")
    rngs = numpy.random.RandomState(3)
    x = rngs.randn(4, 5).astype(numpy.float32)
    a.input = Array(x, name="x")
    dev = vt.XLADevice(mesh_axes={"data": 1})
    a.initialize(device=dev)
    t.input = a.output
    t.initialize(device=dev)

    def run_chain():
        before = counters.get("veles_dispatches_total")
        a.run()
        t.run()
        return (numpy.asarray(a.output.map_read()).copy(),
                numpy.asarray(t.output.map_read()).copy(),
                int(counters.get("veles_dispatches_total") - before))

    mm_off, act_off, n_off = run_chain()        # unfused reference
    install_epilogues([a, t], force=True)
    mm_on, act_on, n_on = run_chain()
    assert (n_off, n_on) == (2, 1)
    # the PRODUCER's output array (pre-activation) is fresh too
    numpy.testing.assert_array_equal(mm_off, mm_on)
    numpy.testing.assert_array_equal(act_off, act_on)
    # uninstall: knob off → re-install clears flags + cached closure
    root.common.engine.fused_epilogue = False
    assert install_epilogues([a, t]) == {}
    assert a._epilogue_tails is None and not t._epilogue_folded
    mm_back, act_back, n_back = run_chain()
    assert n_back == 2                          # unfused layout back
    numpy.testing.assert_array_equal(act_back, act_off)


def test_plan_epilogues_geometry():
    wf = vt.Workflow(name="p")
    t0 = nn.ForwardTanh(wf, name="t0")
    a = nn.All2All(wf, name="fc1", output_sample_shape=4)
    t1 = nn.ForwardTanh(wf, name="t1")
    m = nn.ForwardMul(wf, name="scale", factor=0.5)
    d = nn.DropoutForward(wf, name="drop", dropout_ratio=0.5)
    b = nn.All2All(wf, name="fc2", output_sample_shape=4)
    t2 = nn.ForwardTanh(wf, name="t2")
    # leading activation has no producer: never folded; the tanh+mul
    # run folds into fc1; dropout (rng- and train-dependent) is never
    # an epilogue and fc2's run restarts after it
    plan = plan_epilogues([t0, a, t1, m, d, b, t2])
    assert [(p.name, [t.name for t in ts]) for p, ts in plan] == \
        [("fc1", ["t1", "scale"]), ("fc2", ["t2"])]


def test_bf16_activations_off_bit_identical_on_stores_bf16():
    wf_amp = _train(amp=True)
    wf_off = _train(amp=True, bf16=False)
    _assert_bit_identical(wf_amp, wf_off)
    # ON: interlayer activations that would leave a unit f32 are
    # stored bfloat16; masters stay f32 and training stays finite
    seen = {}
    wf_on = _train(amp=True, bf16=True)
    assert wf_on.train_step._bf16_acts
    import jax
    import jax.numpy as jnp

    ts = wf_on.train_step

    class Probe:
        def __init__(self, inner):
            self.inner = inner
            self.name = inner.name
            self.PARAMETERIZED = inner.PARAMETERIZED

        def apply(self, p, x, *, train=False, rng=None):
            seen["dtype"] = x.dtype
            return self.inner.apply(p, x, train=train, rng=rng)

        def __getattr__(self, k):
            return getattr(self.inner, k)

    # force an f32 interlayer value: a probe wrapping the activation
    # unit records what dtype the NEXT layer receives after the cast
    orig = ts.forwards[1]
    f32_out = Probe(orig)
    f32_out.apply = lambda p, x, train=False, rng=None: \
        orig.apply(p, x, train=train, rng=rng).astype(jnp.float32)
    probe = Probe(ts.forwards[2])
    ts.forwards = [ts.forwards[0], f32_out, probe]
    x = jnp.asarray(numpy.random.RandomState(0).randn(4, 10),
                    jnp.bfloat16)
    ts._forward_pure({k: jax.device_get(v)
                      for k, v in ts.params.items()}, x, False, None)
    assert seen["dtype"] == jnp.bfloat16    # the knob's cast fired
    for leaf in jax.tree_util.tree_leaves(ts.params):
        assert leaf.dtype == jnp.float32    # masters stay f32


def test_bf16_activations_without_amp_is_inert():
    wf = _train(bf16=True, amp=False)
    assert not wf.train_step._bf16_acts
    wf_base = _train()
    _assert_bit_identical(wf_base, wf)


def test_conv_lane_padding_off_identical_on_equal():
    from veles_tpu.nn.conv import lane_padded_channels
    assert lane_padded_channels(96) == 128      # 1.33x: worth it
    assert lane_padded_channels(100) == 128
    assert lane_padded_channels(3) == 3         # 42x: never
    assert lane_padded_channels(64) == 64       # 2x: beyond headroom
    assert lane_padded_channels(128) == 128     # aligned already
    assert lane_padded_channels(130) == 130     # 1.97x: beyond

    prev = root.common.engine.compute_dtype
    root.common.engine.compute_dtype = "float32"
    try:
        def conv_out(pad, cls=nn.Conv, c=96, **kw):
            root.common.engine.conv_lane_pad = pad
            prng.seed_all(42)
            wf = vt.Workflow(name="cl")
            u = cls(wf, name="u", **kw)
            rng = numpy.random.RandomState(5)
            x = rng.randn(2, 6, 6, c).astype(numpy.float32)
            u.input = Array(x, name="x")
            u.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
            u.xla_run()
            return numpy.asarray(u.output.map_read())

        a = conv_out(False, n_kernels=4, kx=3, ky=3)
        b = conv_out(True, n_kernels=4, kx=3, ky=3)
        # zero channels contribute exact-zero partial products
        numpy.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)
        a = conv_out(False, cls=nn.Deconv, n_channels=4, kx=3, ky=3)
        b = conv_out(True, cls=nn.Deconv, n_channels=4, kx=3, ky=3)
        numpy.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)
    finally:
        root.common.engine.compute_dtype = prev
        root.common.engine.conv_lane_pad = False


def test_check_counters_still_green():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import check_counters
    finally:
        sys.path.remove(os.path.join(REPO, "scripts"))
    assert check_counters.find_unregistered() == []
    for name in devtime.DEVTIME_COUNTERS:
        assert name in check_counters.registered_counters()
