"""Quantization subsystem (veles_tpu/quant/): int8 weights with
dequant-on-read serving, int8 KV-cache slot pool, and the offline
``veles-tpu quantize`` snapshot CLI.

The contracts under test: quant-OFF is bit-identical to a build
without the feature (and leaks zero quant counters), quantized greedy
serving is TOKEN-EXACT vs float on the bench model, the int8 pool
halves its HBM at the same ``max_slots``, a quantized snapshot resumes
anywhere a plain one does, and an injected ``quant.calibrate`` fault
degrades instead of wedging the serving plane."""
import os

import numpy
import pytest

import veles_tpu as vt
from veles_tpu import prng
from veles_tpu.error import VelesError
from veles_tpu.ops.precision import (INT8_QMAX, dequantize_int8,
                                     dequantize_rows_int8,
                                     quantize_int8, quantize_rows_int8)
from veles_tpu.quant import (QUANT_COUNTERS, dequantize_params,
                             is_quantized_params, quantize_params,
                             quantize_state, dequantize_state)
from veles_tpu.serving import ContinuousEngine
from veles_tpu.serving.engine import make_request
from veles_tpu.telemetry.counters import counters

from conftest import import_model


@pytest.fixture(scope="module")
def trained():
    """The serving-engine test model (same geometry + seed as
    tests/test_serving_engine.py, where the float contracts live)."""
    lm = import_model("char_lm")
    prng.seed_all(971)
    wf = lm.build_workflow(epochs=1, minibatch_size=64, n_blocks=2,
                           dim=32, n_train=256, n_valid=64)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    return lm, wf


def _prompt(lm, seed, length=10):
    return [int(t) for t in
            lm.make_corpus(numpy.random.RandomState(seed), length)]


def _requests(lm):
    """Mixed greedy/sampled load — the set the id-exactness bar is
    measured on (greedy rows must match float exactly; sampled rows
    must at least be deterministic, and DO match on this model)."""
    return [make_request(_prompt(lm, 40 + s, 5 + s % 6), 6,
                         temperature=0.8 if s % 2 else 0.0,
                         seed=40 + s)
            for s in range(5)]


# -- numeric core (ops/precision.py) -----------------------------------------

def test_per_channel_round_trip_error_bounded():
    w = numpy.random.RandomState(0).randn(64, 24).astype(numpy.float32)
    q, scale = quantize_int8(w, axis=-1)
    q, scale = numpy.asarray(q), numpy.asarray(scale)
    assert q.dtype == numpy.int8
    assert scale.shape == (1, 24)
    err = numpy.abs(numpy.asarray(dequantize_int8(q, scale)) - w)
    # symmetric rounding error is at most half an lsb per column
    assert (err <= scale / 2 + 1e-7).all()
    # per-channel beats per-tensor on spread columns
    w[:, 3] *= 100.0
    qt, st = quantize_int8(w, axis=None)
    assert numpy.asarray(st).shape == ()
    qc, sc = quantize_int8(w, axis=-1)
    err_t = numpy.abs(numpy.asarray(dequantize_int8(qt, st)) - w)[:, 0]
    err_c = numpy.abs(numpy.asarray(dequantize_int8(qc, sc)) - w)[:, 0]
    assert err_c.max() < err_t.max()


def test_zero_and_extreme_groups_are_safe():
    w = numpy.zeros((8, 40), numpy.float32)
    w[:, 1] = 3.0
    q, scale = quantize_int8(w, axis=-1)
    out = numpy.asarray(dequantize_int8(q, scale))
    assert (out[:, 0] == 0).all() and (out[:, 1] == 3.0).all()
    assert numpy.asarray(q).max() <= INT8_QMAX


def test_kv_row_quant_is_per_position_and_requant_stable():
    x = numpy.random.RandomState(1).randn(6, 4, 8).astype(numpy.float32)
    q, s = quantize_rows_int8(x)
    assert numpy.asarray(q).shape == x.shape
    assert numpy.asarray(s).shape == (6,)
    back = numpy.asarray(dequantize_rows_int8(q, s))
    assert numpy.abs(back - x).max() <= numpy.asarray(s).max() / 2 + 1e-7
    # re-quantizing a dequantized row with its own scale is bit-exact —
    # the no-error-accumulation property the decode step relies on
    q2, s2 = quantize_rows_int8(back)
    assert (numpy.asarray(q2) == numpy.asarray(q)).all()
    assert numpy.allclose(numpy.asarray(s2), numpy.asarray(s))


# -- parameter trees ----------------------------------------------------------

def test_quantize_params_eligibility_and_round_trip(trained):
    from veles_tpu.nn.sampling import params_of
    lm, wf = trained
    params = params_of(wf)
    qp, report = quantize_params(params)
    assert is_quantized_params(qp) and not is_quantized_params(params)
    assert report["params"] > 0
    assert report["bytes_after"] < report["bytes_before"] / 3
    for uname, uparams in qp.items():
        # embedding tables and 1-D tensors ride through untouched
        for pname, val in uparams.items():
            if pname == "table" or getattr(
                    params[uname][pname], "ndim", 0) != 2:
                assert not isinstance(val, dict), (uname, pname)
    dp = dequantize_params(qp)
    for uname, uparams in params.items():
        for pname, arr in uparams.items():
            a, b = numpy.asarray(arr), numpy.asarray(dp[uname][pname])
            assert a.shape == b.shape
            if not isinstance(qp[uname][pname], dict):
                assert (a == b).all()


def test_bad_granularity_rejected(trained):
    from veles_tpu.nn.sampling import params_of
    _lm, wf = trained
    with pytest.raises(VelesError, match="granularity"):
        quantize_params(params_of(wf), granularity="per_banana")


# -- serving engine: off = leak-free, on = token-exact ------------------------

def test_quant_off_engine_leaks_no_quant_counters(trained):
    lm, wf = trained
    before = counters.snapshot()
    engine = ContinuousEngine(wf, max_slots=2, buckets=(8, 16),
                              max_context=48, name="qoff").start()
    try:
        from veles_tpu.nn import sampling
        req = make_request(_prompt(lm, 7), 5)
        assert engine.serve([req])[0] == sampling.generate(
            wf, req["prompt"], req["n_new"], temperature=0)
        assert engine.stats()["quant_weights"] == 0
        assert engine.stats()["artifact_mode"] == 0
    finally:
        engine.stop()
    delta = counters.delta(before, names=QUANT_COUNTERS)
    assert delta == {}, "quant counters leaked into a quant-off run"


@pytest.mark.parametrize("qw,qkv", [(True, False), (False, True),
                                    (True, True)])
def test_quantized_greedy_and_sampled_token_exact(trained, qw, qkv):
    """The headline quality bar: int8 serving (weights, KV cache, or
    both) answers the bench model's requests with the exact tokens the
    float plane produces — greedy rows by contract, sampled rows
    measured-and-locked on this model."""
    lm, wf = trained
    from veles_tpu.nn import sampling
    reqs = _requests(lm)
    ref = [sampling.generate(wf, r["prompt"], r["n_new"],
                             temperature=r["temperature"],
                             seed=r["seed"]) for r in reqs]
    engine = ContinuousEngine(wf, max_slots=3, buckets=(8, 16),
                              max_context=48, quant_weights=qw,
                              quant_kv=qkv,
                              name="q_%d%d" % (qw, qkv)).start()
    try:
        assert engine.serve(list(reqs)) == ref
        # concurrent == solo (per-slot PRNG independence survives
        # quantization — the noise derives from seeds, not weights)
        solo = [engine.serve([r])[0] for r in reqs]
        assert solo == ref
        assert engine.programs_built <= len(engine.buckets) + 1
    finally:
        engine.stop()


def test_int8_pool_halves_hbm(trained):
    lm, wf = trained
    sizes = {}
    for qkv in (False, True):
        engine = ContinuousEngine(wf, max_slots=3, buckets=(8, 16),
                                  max_context=48, quant_kv=qkv,
                                  name="pool_%d" % qkv).start()
        try:
            engine.serve([make_request(_prompt(lm, 9), 3)])
            sizes[qkv] = engine.stats()["kv_pool_bytes"]
        finally:
            engine.stop()
    # int8 payload + f32 per-position scales vs f32 payload: < 0.5x
    assert sizes[True] < sizes[False] / 2


def test_quant_calibrate_fault_degrades_then_recovers(trained,
                                                     monkeypatch):
    """An injected calibration fault fails the serving tick; the
    queued request survives the failed tick and is answered correctly
    once the (times=1) fault is spent — degrade, don't wedge."""
    lm, wf = trained
    from veles_tpu.nn import sampling
    from veles_tpu.resilience.faults import FaultInjected
    from veles_tpu.resilience import faults
    monkeypatch.setenv("VELES_FAULTS", "quant.calibrate:raise:times=1")
    with pytest.raises(FaultInjected):
        from veles_tpu.nn.sampling import params_of
        quantize_params(params_of(wf))
    # the times=1 clause is spent; re-arm it for the engine phase (an
    # unchanged spec string never re-arms by itself)
    faults.plane.configure()
    before = counters.get("veles_faults_injected_total")
    engine = ContinuousEngine(wf, max_slots=2, buckets=(8, 16),
                              max_context=48, quant_weights=True,
                              name="qfault").start()
    try:
        req = make_request(_prompt(lm, 11), 4)
        assert engine.serve([req], timeout=60)[0] == \
            sampling.generate(wf, req["prompt"], req["n_new"],
                              temperature=0)
    finally:
        engine.stop()
        monkeypatch.setenv("VELES_FAULTS", "")
    assert counters.get("veles_faults_injected_total") > before


# -- snapshot plane (veles-tpu quantize) --------------------------------------

def test_quantize_state_round_trip_bounds(trained):
    from veles_tpu.snapshotter import collect_state
    _lm, wf = trained
    state = collect_state(wf)
    qstate, report = quantize_state(state)
    assert report["params"] > 0
    assert qstate["__meta__"]["quant"]["params"] == report["params"]
    # input state is not mutated
    assert not any(isinstance(v, dict) and "__quant__" in v
                   for sd in state["__units__"].values()
                   if isinstance(sd, dict) for v in sd.values())
    ds = dequantize_state(qstate)
    for uname, sd in state["__units__"].items():
        for pname, arr in sd.items():
            if not isinstance(arr, numpy.ndarray):
                continue
            back = ds["__units__"][uname][pname]
            assert back.dtype == arr.dtype
            if isinstance(qstate["__units__"][uname][pname], dict):
                col_max = numpy.abs(arr).max(axis=0)
                assert numpy.abs(back - arr).max() <= \
                    col_max.max() / (2 * 127) + 1e-6
            else:
                assert (back == arr).all()


def test_quantize_cli_snapshot_resumes_and_serves(trained, tmp_path):
    """End to end: snapshot → ``veles-tpu quantize`` → resume → the
    resumed model's greedy decode equals the LIVE engine serving the
    original weights under ``quant_weights`` — both paths apply the
    same int8 scheme, so they must agree token for token."""
    from veles_tpu.__main__ import main as cli_main
    from veles_tpu.nn import sampling
    from veles_tpu.snapshotter import Snapshotter, resume
    lm, wf = trained
    snap = Snapshotter(wf, prefix="qt", directory=str(tmp_path),
                       compression="gz", async_mode=False)
    snap._runs = 1
    path = snap.export()
    assert cli_main(["quantize", path]) == 0
    qpath = path.replace(".pickle", ".int8.pickle")
    assert os.path.exists(qpath)
    assert os.path.getsize(qpath) < os.path.getsize(path)
    lm2 = import_model("char_lm")
    prng.seed_all(971)
    wf2 = lm2.build_workflow(epochs=1, minibatch_size=64, n_blocks=2,
                             dim=32, n_train=256, n_valid=64)
    wf2.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    resume(wf2, qpath)
    prompt = _prompt(lm, 13)
    engine = ContinuousEngine(wf, max_slots=2, buckets=(8, 16),
                              max_context=48, quant_weights=True,
                              name="qsnap").start()
    try:
        served = engine.serve([make_request(prompt, 6)])[0]
    finally:
        engine.stop()
    assert served == sampling.generate(wf2, prompt, 6, temperature=0)


def test_quantize_cli_rejects_unquantizable_path(tmp_path, capsys):
    from veles_tpu.__main__ import main as cli_main
    missing = str(tmp_path / "nope.pickle.gz")
    assert cli_main(["quantize", missing]) == 1
    assert "quantize failed" in capsys.readouterr().err
