"""Continuous-batching serving engine (veles_tpu/serving/): persistent
slot-pool KV cache, bucketed prefill, iteration-level scheduling.

The contract under test: a request's tokens are a pure function of the
request (id-exact vs its solo decode, greedy AND sampled — per-slot
PRNG streams), short requests retire the moment they finish instead of
riding out long co-tenants, the jit cache is bounded by
``len(buckets) + 1`` programs, and tickets older than their deadline
are answered 503 + Retry-After instead of rotting in the queue."""
import json
import time
import urllib.error
import urllib.request

import numpy
import pytest

import veles_tpu as vt
from veles_tpu import prng
from veles_tpu.serving import ContinuousEngine, parse_buckets
from veles_tpu.serving.engine import make_request
from veles_tpu.serving.scheduler import SlotScheduler, Ticket
from veles_tpu.telemetry.counters import counters

from conftest import import_model


def _post(url, payload, timeout=60.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


@pytest.fixture(scope="module")
def served():
    lm = import_model("char_lm")
    prng.seed_all(971)
    wf = lm.build_workflow(epochs=1, minibatch_size=64, n_blocks=2,
                           dim=32, n_train=256, n_valid=64)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    engine = ContinuousEngine(wf, max_slots=3, buckets=(8, 16),
                              max_context=48, name="eng_t").start()
    yield lm, wf, engine
    engine.stop()


def _prompt(lm, seed, length=12):
    return [int(t) for t in
            lm.make_corpus(numpy.random.RandomState(seed), length)]


# -- scheduler geometry (no jax) ---------------------------------------------

def test_bucket_selection_and_rejection():
    sched = SlotScheduler(2, (8, 16), 32)
    assert sched.bucket_for(3) == 8
    assert sched.bucket_for(8) == 8
    assert sched.bucket_for(9) == 16
    assert sched.bucket_for(17) is None
    assert sched.reject_reason(5, 10) is None
    assert "bucket" in sched.reject_reason(20, 4)
    assert "max_context" in sched.reject_reason(16, 30)
    with pytest.raises(ValueError):
        SlotScheduler(2, (8, 64), 32)     # bucket beyond max_context


def test_parse_buckets_forms():
    assert parse_buckets("16, 8,8") == (8, 16)
    assert parse_buckets([32, 16]) == (16, 32)
    from veles_tpu.error import VelesError
    with pytest.raises(VelesError):
        parse_buckets("")


def test_expired_ticket_purged_even_when_pool_full():
    sched = SlotScheduler(1, (8,), 16)
    t_busy, t_old = Ticket(), Ticket(deadline=time.time() - 1)
    sched.push(make_request([1, 2], 4), t_busy)
    admitted, expired = sched.take_admissions()
    assert len(admitted) == 1 and not expired
    sched.push(make_request([1, 2], 4), t_old)
    # pool is full — the expired HEAD must still be answered
    admitted, expired = sched.take_admissions()
    assert not admitted and expired == [t_old]
    # ... and so must an expired ticket BEHIND a live head
    t_live = Ticket(deadline=time.time() + 60)
    t_mid = Ticket(deadline=time.time() - 1)
    sched.push(make_request([1, 2], 4), t_live)
    sched.push(make_request([1, 2], 4), t_mid)
    admitted, expired = sched.take_admissions()
    assert not admitted and expired == [t_mid]
    assert sched.queue_depth() == 1               # t_live kept, FIFO


def test_poisoned_head_answered_400_not_crash_loop():
    """A queued request that fits no bucket (a checked=True submit
    bypassing accepts(), or a raw push) must be popped and answered
    400 — not crash take_admissions pre-pop tick after tick while the
    whole pool starves behind it."""
    sched = SlotScheduler(2, (8,), 16)
    bad, good = Ticket(), Ticket()
    sched.push(make_request([1] * 20, 2), bad)
    sched.push(make_request([1, 2], 2), good)
    admitted, expired = sched.take_admissions()
    assert bad.event.is_set() and bad.code == 400
    assert "bucket" in bad.error
    assert len(admitted) == 1          # the pool kept serving
    assert not expired


def test_retire_is_idempotent():
    # a shutdown abort racing a wedged worker's late _finish retires
    # the same slot twice — the free list must not hold an index twice
    sched = SlotScheduler(2, (8,), 16)
    sched.push(make_request([1, 2], 4), Ticket())
    (slot,), _ = sched.take_admissions()
    sched.retire(slot)
    sched.retire(slot)
    assert sorted(sched._free) == [0, 1]


# -- engine: lifecycle + id-exactness ----------------------------------------

def test_slot_lifecycle_admit_bucket_retire_reuse(served):
    """admit → prefill-bucket selection → retirement → slot reuse by a
    later request: 6 mixed-length requests through a 3-slot pool."""
    lm, wf, engine = served
    before = counters.snapshot()
    admitted0, retired0 = engine.admitted, engine.retired
    reqs = [make_request(_prompt(lm, s, length=ln), n, seed=s)
            for s, ln, n in ((1, 6, 8), (2, 12, 5), (3, 9, 10),
                             (4, 16, 6), (5, 5, 7), (6, 11, 9))]
    out = engine.serve(list(reqs))
    for req, toks in zip(reqs, out):
        assert len(toks) == req["n_new"]
        assert all(0 <= t < lm.VOCAB for t in toks)
    # every request owned a slot at some point; the pool has 3 rows,
    # so slots were REUSED (6 admissions through 3 slots)
    assert engine.admitted - admitted0 == 6
    assert engine.retired - retired0 == 6
    assert engine.scheduler.busy_count() == 0
    delta = counters.delta(before)
    assert delta["veles_serving_admitted_total"] == 6
    assert delta["veles_serving_retired_total"] == 6
    assert delta["veles_serving_tokens_total"] == \
        sum(r["n_new"] for r in reqs)
    assert delta["veles_serving_prefill_dispatches_total"] == 6
    assert delta["veles_serving_decode_dispatches_total"] >= 1


def test_concurrent_rows_id_exact_vs_solo_greedy_and_sampled(served):
    """The continuous-batching determinism bar: every row — greedy AND
    stochastic — equals its solo decode exactly, whatever strangers
    share the pool (per-slot PRNG streams derive noise purely from the
    request's seed)."""
    lm, wf, engine = served
    reqs = [make_request(_prompt(lm, 10 + i, length=5 + i), 6 + i % 3,
                         temperature=0.8 if i % 2 else 0.0,
                         seed=50 + i)
            for i in range(6)]
    solo = [engine.serve([r])[0] for r in reqs]
    conc = engine.serve(list(reqs))
    assert conc == solo
    # and the greedy/sampled rows also match the legacy scan decoder
    # (same _block_prefill/_block_step math, same per-row streams)
    from veles_tpu.nn import sampling
    for r, toks in zip(reqs, solo):
        assert toks == sampling.generate(
            wf, r["prompt"], r["n_new"],
            temperature=r["temperature"], seed=r["seed"])


def test_jit_program_cache_bounded_by_buckets(served):
    """After everything this module served, the engine holds at most
    len(buckets)+1 jitted programs (the bucketed prefills + the ONE
    fixed-shape decode step) — never one per distinct prompt length."""
    lm, wf, engine = served
    assert engine.programs_built <= len(engine.buckets) + 1
    # and the dispatch counter rides _count_decode_dispatches, so the
    # decode plane stays visible to the round-5 regression lock
    before = counters.get("veles_decode_dispatches_total")
    engine.serve([make_request(_prompt(lm, 30, 7), 4)])
    assert counters.get("veles_decode_dispatches_total") > before
    assert engine.programs_built <= len(engine.buckets) + 1


def test_early_eos_retirement_frees_slot_for_queue(served):
    """A row emitting eos_id retires immediately — its tokens stop at
    the stop token and its slot is reused while longer co-tenants keep
    decoding."""
    lm, wf, engine = served
    p = _prompt(lm, 40, length=10)
    full = engine.serve([make_request(p, 12)])[0]
    eos = full[4]
    first = full.index(eos)
    retired0 = engine.admitted
    # 4 requests into 3 slots: the eos row must retire early and hand
    # its slot to the queued 4th request
    reqs = [make_request(p, 12, eos_id=eos),
            make_request(_prompt(lm, 41, 9), 12),
            make_request(_prompt(lm, 42, 13), 12),
            make_request(_prompt(lm, 43, 7), 12)]
    out = engine.serve(reqs)
    assert out[0] == full[:first + 1]
    assert out[0][-1] == eos
    assert len(out[0]) < 12                # retired before its n_new
    for toks in out[1:]:
        assert len(toks) == 12
    assert engine.admitted - retired0 == 4


def test_queued_past_deadline_answered_503(served):
    lm, wf, engine = served
    before = counters.get("veles_serving_expired_total")
    ticket = Ticket(deadline=time.time() - 0.5)
    assert engine.submit(make_request(_prompt(lm, 50, 6), 4), ticket)
    assert ticket.event.wait(30)
    assert ticket.error is not None and ticket.code == 503
    assert ticket.retry_after
    assert counters.get("veles_serving_expired_total") == before + 1


def test_injected_decode_fault_sheds_then_recovers(served, monkeypatch):
    lm, wf, engine = served
    from veles_tpu.error import VelesError
    monkeypatch.setenv("VELES_FAULTS", "serve.decode_step:raise:times=1")
    req = make_request(_prompt(lm, 60, 6), 6)
    with pytest.raises(VelesError, match="injected"):
        engine.serve([req])
    monkeypatch.setenv("VELES_FAULTS", "")
    # the pool stayed consistent: the very next request serves fine
    from veles_tpu.nn import sampling
    assert engine.serve([req])[0] == sampling.generate(
        wf, req["prompt"], req["n_new"], temperature=0)


def test_non_lm_workflow_degrades_to_window_worker():
    wf = vt.Workflow(None, name="w")
    api = vt.GenerationAPI(wf, port=0, engine="continuous",
                           name="deg_g")
    api.initialize()
    try:
        assert api._engine is None         # graceful fallback, no raise
    finally:
        api.stop()


def test_bad_knob_geometry_raises_not_degrades(served):
    # an operator who ASKED for continuous batching must not silently
    # get the window worker because of a knob mistake
    lm, wf, _engine = served
    api = vt.GenerationAPI(wf, port=0, engine="continuous",
                           buckets=(8, 128), max_context=48,
                           name="bad_g")
    with pytest.raises(ValueError):
        api.initialize()


# -- GenerationAPI over HTTP --------------------------------------------------

@pytest.fixture(scope="module")
def api_served(served):
    lm, wf, _engine = served
    api = vt.GenerationAPI(wf, port=0, engine="continuous", max_slots=3,
                           buckets=(8, 16), max_context=48,
                           name="capi")
    api.initialize()
    url = "http://127.0.0.1:%d/generate" % api.port
    yield lm, wf, api, url
    api.stop()


def test_http_greedy_and_sample_ride_the_engine(served, api_served):
    lm, wf, api, url = api_served
    from veles_tpu.nn import sampling
    p = _prompt(lm, 70, 9)
    code, out, _ = _post(url, {"prompt": p, "n_new": 8})
    assert code == 200, out
    assert out["engine"] == "continuous"
    assert out["tokens"] == sampling.generate(wf, p, 8, temperature=0)
    code, out, _ = _post(url, {"prompt": p, "n_new": 6,
                               "mode": "sample", "temperature": 0.7,
                               "seed": 11})
    assert code == 200 and out["engine"] == "continuous"
    assert out["tokens"] == sampling.generate(wf, p, 6,
                                              temperature=0.7, seed=11)


def test_http_oversized_request_falls_back_to_window(served,
                                                     api_served):
    """A prompt longer than the largest bucket (or a context overflow)
    still gets served — through the legacy shape-keyed worker."""
    lm, wf, api, url = api_served
    from veles_tpu.nn import sampling
    long_p = (_prompt(lm, 71, 12) * 2)[:20]     # > largest bucket 16
    code, out, _ = _post(url, {"prompt": long_p, "n_new": 5})
    assert code == 200, out
    assert "engine" not in out                  # window worker answered
    assert out["tokens"] == sampling.generate(wf, long_p, 5,
                                              temperature=0)


def test_http_expired_in_queue_gets_503_retry_after(served,
                                                    api_served):
    """request_timeout holds while QUEUED: with a zero timeout the
    ticket's deadline passes before any decode, and the scheduler
    answers 503 + Retry-After (not a silent 504)."""
    lm, wf, api, url = api_served
    prev = api.request_timeout
    api.request_timeout = 0.0
    try:
        code, out, headers = _post(url, {"prompt": _prompt(lm, 72, 6),
                                         "n_new": 4})
    finally:
        api.request_timeout = prev
    assert code == 503, out
    assert "expired" in out["error"]
    assert int(headers.get("Retry-After")) >= 1


def test_http_metrics_and_stats_expose_occupancy(served, api_served):
    lm, wf, api, url = api_served
    code, _, _ = _post(url, {"prompt": _prompt(lm, 73, 6), "n_new": 4})
    assert code == 200
    with urllib.request.urlopen(url + "/stats", timeout=30) as r:
        stats = json.loads(r.read())
    assert stats["engine"] == "continuous"
    assert stats["continuous"]["slots"] == 3
    assert stats["continuous"]["retired"] >= 1
    assert stats["continuous"]["programs"] <= 3
    with urllib.request.urlopen(
            "http://127.0.0.1:%d/metrics" % api.port, timeout=30) as r:
        text = r.read().decode()
    assert "veles_serving_slots 3" in text
    # same gauge names as web_status, just unsuffixed (one engine here)
    assert "veles_serving_queue_depth" in text
    assert "veles_serving_admitted_total" in text


def test_web_status_metrics_render_engine_gauges(served):
    lm, wf, engine = served
    from veles_tpu.web_status import WebStatusServer
    server = WebStatusServer(port=0)
    server._service.start_serving()
    try:
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/metrics" % server.port,
                timeout=30) as r:
            text = r.read().decode()
        assert "veles_serving_slots_busy_eng_t" in text
        assert "veles_serving_queue_depth_eng_t" in text
        # paged-pool occupancy gauges (serving/pages.py) — the rows an
        # operator sizes pages/page_size with
        assert "veles_serving_pages_total_eng_t" in text
        assert "veles_serving_pages_in_use_eng_t" in text
        assert "veles_serving_page_fragmentation_eng_t" in text
    finally:
        server.stop()


# -- the paged pool ------------------------------------------------------------

def test_paged_admission_beats_dense_at_same_hbm():
    """THE paged-pool win, at the ledger: 16 pages x 8 positions is the
    HBM a dense pool spends on 128/32 = 4 slots of max_context=32. The
    paged scheduler admits on each request's OWN footprint, so the same
    HBM holds 8 concurrent short requests — strictly more than dense
    ever could."""
    from veles_tpu.serving.pages import PagePool
    pool = PagePool(16, 8)
    sched = SlotScheduler(8, (8,), 32, page_pool=pool)
    for s in range(8):
        sched.push(make_request([1, 2, 3, 4], 4, seed=s), Ticket())
    admitted, expired = sched.take_admissions()
    assert not expired
    assert len(admitted) == 8          # dense tops out at 4
    # each row reserved its own worst case (8 positions = 1 page)
    assert pool.in_use() == 8
    for slot in admitted:
        sched.retire(slot)
    assert pool.in_use() == 0
    assert pool.free_count() == 16


def test_admission_waits_for_pages_then_proceeds():
    """Real exhaustion at admission keeps FIFO order and waits for
    retirements (no shed): pages freed by a retiring row admit the
    waiting head on the next boundary."""
    from veles_tpu.serving.pages import PagePool
    pool = PagePool(2, 8)
    sched = SlotScheduler(4, (8,), 16, page_pool=pool)
    t1, t2 = Ticket(), Ticket()
    sched.push(make_request([1] * 6, 8), t1)     # worst 14 -> 2 pages
    sched.push(make_request([1] * 6, 8), t2)
    admitted, _ = sched.take_admissions()
    assert len(admitted) == 1                    # pool can hold one
    again, _ = sched.take_admissions()
    assert not again                             # starved, not shed
    assert not t2.event.is_set()
    sched.retire(admitted[0])
    admitted, _ = sched.take_admissions()
    assert len(admitted) == 1                    # head admitted now
    assert pool.in_use() == 2


def test_page_alloc_fault_sheds_503_and_ledger_stays_consistent(
        served, monkeypatch):
    """Chaos for satellite `serve.page_alloc`: an injected allocation
    fault sheds the admitting request 503 + Retry-After; the page
    ledger balances back to empty and the very next request decodes
    id-exact — recovery, not an outage."""
    lm, wf, engine = served
    shed0 = counters.get("veles_shed_requests_total")
    monkeypatch.setenv("VELES_FAULTS", "serve.page_alloc:raise:times=1")
    req = make_request(_prompt(lm, 80, 6), 6)
    ticket = Ticket()
    assert engine.submit(req, ticket)
    assert ticket.event.wait(60)
    assert ticket.error is not None and ticket.code == 503
    assert ticket.retry_after
    assert counters.get("veles_shed_requests_total") == shed0 + 1
    monkeypatch.setenv("VELES_FAULTS", "")
    from veles_tpu.nn import sampling
    assert engine.serve([req])[0] == sampling.generate(
        wf, req["prompt"], req["n_new"], temperature=0)
    # the ledger balanced: nothing leaked across the shed + recovery
    assert engine.page_pool.in_use() == 0
    assert engine.page_pool.free_count() == engine.pages


def test_unknown_mode_rejected_400_not_leaked(served):
    """accepts() must fail CLOSED on a mode string no tick path
    advances — admitting it would strand the ticket to timeout and
    leak the slot + its reserved pages forever."""
    lm, wf, engine = served
    ticket = Ticket()
    assert engine.submit(
        make_request(_prompt(lm, 140, 5), 4, mode="gredy"), ticket)
    assert ticket.event.wait(30)
    assert ticket.code == 400
    assert "mode" in ticket.error
    assert engine.page_pool.in_use() == 0


def test_page_reuse_after_retire_not_poisoned(served):
    """Pages freed by retired rows are immediately re-issued to new
    admissions; a page-constrained pool forces heavy reuse across
    waves, and every wave must stay id-exact — a stale row bleeding
    through a reused page would show up here."""
    lm, wf, _ = served
    engine = ContinuousEngine(wf, max_slots=3, buckets=(8,),
                              max_context=32, page_size=8, pages=6,
                              name="eng_tight").start()
    try:
        reqs_a = [make_request(_prompt(lm, 90 + i, 5), 6,
                               temperature=0.6 if i == 1 else 0.0,
                               seed=90 + i) for i in range(3)]
        reqs_b = [make_request(_prompt(lm, 95 + i, 6), 7, seed=95 + i)
                  for i in range(3)]
        ref_a = [engine.serve([r])[0] for r in reqs_a]
        for _wave in range(3):
            engine.serve(list(reqs_b))           # dirty every page
            assert engine.serve(list(reqs_a)) == ref_a
        assert engine.page_pool.in_use() == 0
        assert engine.page_pool.free_count() == engine.pages
    finally:
        engine.stop()


def test_quant_cache_invalidation_recalibrates(served):
    """Satellite regression: the int8 twin is cached on device-view
    leaf IDENTITY, so an in-place device mutation (same jax.Array,
    new bytes) would serve stale scales forever — an explicit
    :meth:`invalidate_quant_cache` must force recalibration at the
    next param refresh, while unchanged weights keep reusing the
    cached twin."""
    lm, wf, _ = served
    engine = ContinuousEngine(wf, max_slots=2, buckets=(8,),
                              max_context=32, quant_weights=True,
                              name="eng_q")
    cal = lambda: counters.get("veles_quant_calibrations_total")  # noqa: E731
    c0 = cal()
    p1 = engine._prepare_params()
    assert cal() == c0 + 1
    p2 = engine._prepare_params()                # identity-cached
    assert cal() == c0 + 1
    assert p2 is p1
    engine.invalidate_quant_cache()
    p3 = engine._prepare_params()
    assert cal() == c0 + 2                       # recalibrated
    assert p3 is not p1
    # and through the serving path: idle-boundary refresh reuses the
    # twin until invalidated
    engine.start()
    try:
        req = make_request(_prompt(lm, 85, 6), 4)
        engine.serve([req])
        served_cal = cal()
        engine.serve([req])
        assert cal() == served_cal               # cache held
        engine.invalidate_quant_cache()
        engine.serve([req])
        assert cal() == served_cal + 1           # refresh recalibrated
    finally:
        engine.stop()


# -- speculative + beam on the pool -------------------------------------------

@pytest.fixture(scope="module")
def pooled(served):
    """Target + draft + an engine serving ALL four decode modes on one
    paged pool: 5 slots so greedy + sample + spec + one beam-width-2
    group can co-tenant a single step boundary."""
    lm, wf, _ = served
    prng.seed_all(437)
    draft = lm.build_workflow(epochs=1, minibatch_size=64, n_blocks=1,
                              dim=16, n_train=256, n_valid=64)
    draft.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    draft.run()
    engine = ContinuousEngine(wf, max_slots=5, buckets=(8, 16),
                              max_context=48, page_size=8,
                              spec_gamma=3, beam_width=2,
                              draft=draft, name="eng_pool").start()
    yield lm, wf, draft, engine
    engine.stop()


def test_speculative_id_exact_on_pool_vs_solo(pooled):
    """Pooled speculation (on-device draft/verify rounds over the page
    tables) emits the same tokens as the host-loop
    ``generate_speculative`` — greedy AND stochastic — and the greedy
    rows equal plain greedy decode (the speculation invariant)."""
    from veles_tpu.nn import sampling
    from veles_tpu.nn.speculative import generate_speculative
    lm, wf, draft, engine = pooled
    for temp, seed in ((0.0, 0), (0.7, 21)):
        p = _prompt(lm, 100 + seed, 7)
        req = make_request(p, 9, temperature=temp, seed=seed,
                           mode="speculative", gamma=3)
        toks = engine.serve([req])[0]
        solo, _stats = generate_speculative(
            wf, draft, p, 9, gamma=3, temperature=temp, seed=seed)
        assert toks == solo, "temp=%s" % temp
        if temp == 0.0:
            assert toks == sampling.generate(wf, p, 9, temperature=0)


def test_beam_id_exact_on_pool_vs_solo(pooled):
    """A pooled beam request (hypothesis rows on the page tables, the
    group top-k step, page-granular cache reorder) returns exactly
    ``beam_generate``'s best tokens and hypothesis scores."""
    from veles_tpu.nn.beam import beam_generate
    lm, wf, draft, engine = pooled
    from veles_tpu.serving.scheduler import Ticket as STicket
    for seed in (110, 111):
        p = _prompt(lm, seed, 6)
        req = make_request(p, 8, mode="beam", beam=2)
        ticket = STicket()
        assert engine.submit(req, ticket)
        assert ticket.event.wait(120)
        assert ticket.error is None, ticket.error
        solo, stats = beam_generate(wf, p, 8, beam=2)
        assert ticket.result["tokens"] == [int(t) for t in solo]
        assert numpy.allclose(ticket.result["scores"],
                              stats["scores"], atol=1e-4)


def test_mixed_mode_cotenancy_all_id_exact(pooled):
    """The full-stack co-tenancy bar: greedy + sampled + speculative +
    beam rows sharing ONE step boundary, every answer id-exact vs its
    own solo baseline — no mode perturbs another's tokens."""
    from veles_tpu.nn import sampling
    from veles_tpu.nn.beam import beam_generate
    from veles_tpu.nn.speculative import generate_speculative
    from veles_tpu.serving.scheduler import Ticket as STicket
    lm, wf, draft, engine = pooled
    pg = _prompt(lm, 120, 6)
    ps = _prompt(lm, 121, 9)
    pv = _prompt(lm, 122, 7)
    pb = _prompt(lm, 123, 5)
    reqs = [make_request(pg, 8),
            make_request(ps, 7, temperature=0.8, seed=7, mode="sample"),
            make_request(pv, 8, mode="speculative", gamma=3),
            make_request(pb, 6, mode="beam", beam=2)]
    tickets = [STicket() for _ in reqs]
    for r, t in zip(reqs, tickets):
        assert engine.submit(r, t)
    for t in tickets:
        assert t.event.wait(180)
        assert t.error is None, t.error
    # co-tenancy really happened: the beam pair plus at least two of
    # the single-row modes shared a step boundary (== 5 when all four
    # admissions land on the same tick, >= 4 when the first admission
    # races one boundary ahead)
    assert engine.peak_slots >= 4
    assert tickets[0].result["tokens"] == sampling.generate(
        wf, pg, 8, temperature=0)
    assert tickets[1].result["tokens"] == sampling.generate(
        wf, ps, 7, temperature=0.8, seed=7)
    spec_solo, _ = generate_speculative(wf, draft, pv, 8, gamma=3)
    assert tickets[2].result["tokens"] == spec_solo
    beam_solo, _ = beam_generate(wf, pb, 6, beam=2)
    assert tickets[3].result["tokens"] == [int(t) for t in beam_solo]
    # mode stats survive the pool: speculation reports its rounds
    assert tickets[2].result["rounds"] >= 1
    assert 0.0 <= tickets[2].result["acceptance"] <= 1.0


def test_scheduler_reserves_engine_gamma_for_gammaless_requests():
    """A speculative request that omits ``gamma`` must be page-
    reserved for the ENGINE's round width, not a literal default —
    under-reservation would resurrect mid-decode exhaustion for the
    exact rows the reservation policy promises it cannot happen to."""
    from veles_tpu.serving.pages import PagePool
    pool = PagePool(8, 8)
    sched = SlotScheduler(2, (8,), 40, page_pool=pool, spec_gamma=8)
    req = {"prompt": [1] * 6, "n_new": 10, "mode": "speculative"}
    sched.push(req, Ticket())
    (slot,), _ = sched.take_admissions()
    # worst = 6 + 10 + 8 + 1 = 25 positions -> 4 pages (a gamma=4
    # default would reserve only 3)
    assert len(slot.pages) == 4


def test_beam_n_new_1_finishes_at_admission(pooled):
    """An n_new=1 beam group is answered by its first hypothesis's
    prefill expansion; the dead sibling rows must not dispatch
    prefills of their own or leave pages behind."""
    from veles_tpu.nn.beam import beam_generate
    lm, wf, draft, engine = pooled
    p = _prompt(lm, 130, 6)
    before = counters.get("veles_serving_prefill_dispatches_total")
    toks = engine.serve([make_request(p, 1, mode="beam", beam=2)])[0]
    solo, _ = beam_generate(wf, p, 1, beam=2)
    assert toks == [int(t) for t in solo]
    assert counters.get("veles_serving_prefill_dispatches_total") \
        == before + 1
    assert engine.page_pool.in_use() == 0


def test_program_count_bounded_with_spec_and_beam(pooled):
    """After serving every decode mode, the jit cache holds at most
    ``programs_bound()`` programs — base prefills + decode step, draft
    prefills + the spec round, the beam step; a CONSTANT, never a
    function of traffic."""
    lm, wf, draft, engine = pooled
    assert engine.programs_built <= engine.programs_bound()
    # the base greedy/sample plane alone stays within len(buckets)+1
    base = [k for k in engine._progs
            if k[0] in ("prefill", "step")]
    assert len(base) <= len(engine.buckets) + 1
