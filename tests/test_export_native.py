"""Export pipeline + C++ runtime parity: a trained workflow exported to a
package must produce (near-)identical outputs through (a) the python
package executor, (b) the C++ engine via ctypes, and (c) the C++ CLI —
mirroring the reference's libVeles tests (libVeles/tests/)."""
import os
import subprocess

import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn
from veles_tpu.export import package_export, package_import, run_package
from veles_tpu.export.native import NativeModel, find_library
from veles_tpu.loader import FullBatchLoader

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BIN = os.path.join(REPO, "native", "build", "veles_infer")


def _ensure_native_built() -> bool:
    """Build the C++ runtime on demand: native/build is untracked, so a
    fresh checkout would otherwise silently SKIP the 16 parity tests
    (which happened — round-3 session 2). ~20 s with ninja; returns
    False only when no toolchain is available."""
    if find_library() is not None:
        return True
    import shutil
    if shutil.which("cmake") is None:
        return False
    gen = ["-G", "Ninja"] if shutil.which("ninja") else []
    try:
        subprocess.run(["cmake", "-S", os.path.join(REPO, "native"),
                        "-B", os.path.join(REPO, "native", "build"),
                        "-DCMAKE_BUILD_TYPE=Release"] + gen,
                       check=True, capture_output=True, timeout=300)
        subprocess.run(["cmake", "--build",
                        os.path.join(REPO, "native", "build"), "-j"],
                       check=True, capture_output=True, timeout=600)
    except (subprocess.SubprocessError, OSError):
        return False
    return find_library() is not None


needs_native = pytest.mark.skipif(
    not _ensure_native_built(), reason="native runtime not built "
    "and no toolchain to build it")


class SmallImages(FullBatchLoader):
    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(9)
        n = 96
        self.create_originals(
            rng.rand(n, 8, 8, 3).astype(numpy.float32),
            rng.randint(0, 4, n).astype(numpy.int32))
        self.class_lengths = [0, 16, 80]


@pytest.fixture(scope="module")
def trained_pkg(tmp_path_factory):
    loader = SmallImages(None, minibatch_size=16, name="imgs")
    wf = nn.StandardWorkflow(
        name="export-net",
        layers=[
            {"type": "conv_tanh", "n_kernels": 4, "kx": 3, "ky": 3,
             "padding": (1, 1, 1, 1)},
            {"type": "max_pooling", "kx": 2, "ky": 2},
            {"type": "norm"},
            {"type": "all2all_relu", "output_sample_shape": 10},
            {"type": "softmax", "output_sample_shape": 4},
        ],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=2), steps_per_dispatch=2)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    pkg = str(tmp_path_factory.mktemp("pkg") / "export-net")
    package_export(wf, pkg)
    batch = loader.original_data.mem[:8].copy()
    # ground truth: the jitted forward chain
    import jax
    x = batch
    for f in wf.forwards:
        p = {k: v.device_view() for k, v in f.param_arrays().items()}
        x = f.apply(p, x, train=False)
    truth = numpy.asarray(jax.device_get(x))
    return pkg, batch, truth


def test_package_contents(trained_pkg):
    pkg, _, _ = trained_pkg
    loaded = package_import(pkg)
    c = loaded["contents"]
    assert c["format_version"] == 2       # v2: per-unit "inputs" DAG
    assert len(c["units"]) == 5
    assert c["units"][0]["type"] == "conv_tanh"
    assert "weights" in loaded["params"]["conv_tanh0"]
    assert os.path.exists(os.path.join(pkg, "forward.stablehlo"))


def test_python_executor_parity(trained_pkg):
    pkg, batch, truth = trained_pkg
    out = run_package(pkg, batch)
    numpy.testing.assert_allclose(out, truth, rtol=2e-3, atol=2e-4)


@needs_native
def test_native_ctypes_parity(trained_pkg):
    pkg, batch, truth = trained_pkg
    model = NativeModel(pkg)
    assert model.unit_count == 5
    out = model(batch).reshape(truth.shape)
    numpy.testing.assert_allclose(out, truth, rtol=2e-3, atol=2e-4)
    model.close()


@needs_native
def test_native_cli_parity(trained_pkg, tmp_path):
    pkg, batch, truth = trained_pkg
    inp = str(tmp_path / "in.npy")
    outp = str(tmp_path / "out.npy")
    numpy.save(inp, batch)
    r = subprocess.run([BIN, pkg, inp, outp], capture_output=True,
                      text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    out = numpy.load(outp).reshape(truth.shape)
    numpy.testing.assert_allclose(out, truth, rtol=2e-3, atol=2e-4)


@needs_native
def test_native_bad_package(tmp_path):
    from veles_tpu.error import VelesError
    with pytest.raises(VelesError):
        NativeModel(str(tmp_path))


def test_stablehlo_roundtrip(trained_pkg):
    """The embedded StableHLO artifact must deserialize and run (static
    batch = the export-time input shape)."""
    pkg, batch, truth = trained_pkg
    from jax import export as jexport
    with open(os.path.join(pkg, "forward.stablehlo"), "rb") as fin:
        exported = jexport.deserialize(fin.read())
    loaded = package_import(pkg)
    params = [loaded["params"][u["name"]]
              for u in loaded["contents"]["units"]]
    n = loaded["contents"]["input_shape"][0]
    full = numpy.tile(batch, (n // len(batch) + 1, 1, 1, 1))[:n]
    out = numpy.asarray(exported.call(params, full))
    numpy.testing.assert_allclose(out[:len(batch)], truth,
                                  rtol=2e-3, atol=2e-4)


@pytest.fixture(scope="module")
def attention_moe_pkg(tmp_path_factory):
    """Sequence model with the round-2 layer types: attention + sparse
    MoE + lstm head — exported and compared against the jitted chain."""
    class Seqs(FullBatchLoader):
        hide_from_registry = True

        def load_data(self):
            rng = numpy.random.RandomState(4)
            n = 64
            self.create_originals(
                rng.rand(n, 6, 8).astype(numpy.float32),
                rng.randint(0, 3, n).astype(numpy.int32))
            self.class_lengths = [0, 16, 48]

    wf = nn.StandardWorkflow(
        name="attn-moe-net",
        layers=[
            {"type": "multi_head_attention", "n_heads": 2,
             "causal": True},
            {"type": "moe_ffn", "n_experts": 4, "hidden": 16,
             "top_k": 2, "capacity_factor": 1.0},
            {"type": "lstm", "hidden_size": 8},
            {"type": "softmax", "output_sample_shape": 3},
        ],
        loader_unit=Seqs(None, minibatch_size=16, name="seqs"),
        loss_function="softmax",
        decision_config=dict(max_epochs=1), steps_per_dispatch=2)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    pkg = str(tmp_path_factory.mktemp("pkg2") / "attn-moe-net")
    package_export(wf, pkg, with_stablehlo=False)
    batch = wf.loader.original_data.mem[:5].copy()
    import jax
    x = batch
    for f in wf.forwards:
        p = {k: v.device_view() for k, v in f.param_arrays().items()}
        x = f.apply(p, x, train=False)
    return pkg, batch, numpy.asarray(jax.device_get(x))


@needs_native
def test_native_attention_moe_parity(attention_moe_pkg):
    """C++ engine vs jitted chain on attention + sparse MoE — tight
    capacity_factor, so token drops must match the GShard dispatch
    exactly, not just the top-k-renorm weights."""
    pkg, batch, truth = attention_moe_pkg
    model = NativeModel(pkg)
    out = model(batch).reshape(truth.shape)
    numpy.testing.assert_allclose(out, truth, rtol=2e-3, atol=2e-4)
    model.close()


def test_python_executor_attention_moe(attention_moe_pkg):
    pkg, batch, truth = attention_moe_pkg
    out = run_package(pkg, batch)
    numpy.testing.assert_allclose(out, truth, rtol=2e-3, atol=2e-4)


@pytest.fixture(scope="module")
def transformer_pkg(tmp_path_factory):
    """Transformer stack export: block + mean_pool + softmax."""
    class Seqs2(FullBatchLoader):
        hide_from_registry = True

        def load_data(self):
            rng = numpy.random.RandomState(8)
            n = 48
            self.create_originals(
                rng.rand(n, 6, 8).astype(numpy.float32),
                rng.randint(0, 3, n).astype(numpy.int32))
            self.class_lengths = [0, 12, 36]

    wf = nn.StandardWorkflow(
        name="tf-net",
        layers=[
            {"type": "pos_embedding"},
            {"type": "transformer_block", "n_heads": 2,
             "ffn_hidden": 16, "causal": True},
            {"type": "transformer_block", "n_heads": 2,
             "ffn_hidden": 16, "causal": True, "rope": True},
            {"type": "transformer_block", "n_heads": 4,
             "n_kv_heads": 2, "ffn_hidden": 16, "causal": True,
             "rope": True},      # GQA: C++ AttentionHeads kv mapping
            {"type": "transformer_block", "n_heads": 2,
             "ffn_hidden": 16, "causal": True,
             "window": 3},       # sliding window: C++ kmin horizon
            {"type": "transformer_block", "n_heads": 2,
             "ffn_hidden": 16, "causal": True, "norm": "rms",
             "ffn": "swiglu"},   # llama-style: C++ rms/silu-gate twin
            {"type": "mean_pool"},
            {"type": "softmax", "output_sample_shape": 3},
        ],
        loader_unit=Seqs2(None, minibatch_size=12, name="s2"),
        loss_function="softmax",
        decision_config=dict(max_epochs=1), steps_per_dispatch=2)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    pkg = str(tmp_path_factory.mktemp("pkg3") / "tf-net")
    package_export(wf, pkg, with_stablehlo=False)
    batch = wf.loader.original_data.mem[:5].copy()
    import jax
    x = batch
    for f in wf.forwards:
        p = {k: v.device_view() for k, v in f.param_arrays().items()}
        x = f.apply(p, x, train=False)
    return pkg, batch, numpy.asarray(jax.device_get(x))


@needs_native
def test_native_transformer_parity(transformer_pkg):
    pkg, batch, truth = transformer_pkg
    model = NativeModel(pkg)
    out = model(batch).reshape(truth.shape)
    numpy.testing.assert_allclose(out, truth, rtol=2e-3, atol=2e-4)
    model.close()


def test_python_executor_transformer(transformer_pkg):
    pkg, batch, truth = transformer_pkg
    out = run_package(pkg, batch)
    numpy.testing.assert_allclose(out, truth, rtol=2e-3, atol=2e-4)


@needs_native
def test_native_embedding_parity(tmp_path):
    """Token stem through the C++ engine (ids travel as floats in the
    runtime tensors; the unit rounds + bounds-checks)."""
    class Toks(FullBatchLoader):
        hide_from_registry = True

        def load_data(self):
            rng = numpy.random.RandomState(3)
            self.create_originals(
                rng.randint(0, 11, (48, 6)).astype(numpy.int32),
                rng.randint(0, 3, 48).astype(numpy.int32))
            self.class_lengths = [0, 12, 36]

    wf = nn.StandardWorkflow(
        name="tok-net",
        layers=[{"type": "embedding", "vocab_size": 11, "dim": 8},
                {"type": "transformer_block", "n_heads": 2,
                 "ffn_hidden": 16, "rope": True},
                {"type": "mean_pool"},
                {"type": "softmax", "output_sample_shape": 3}],
        loader_unit=Toks(None, minibatch_size=12, name="tk"),
        loss_function="softmax",
        decision_config=dict(max_epochs=1), steps_per_dispatch=2)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    pkg = str(tmp_path / "tok-net")
    package_export(wf, pkg, with_stablehlo=False)
    batch = wf.loader.original_data.mem[:5].copy()
    import jax
    x = batch
    for f in wf.forwards:
        p = {k: v.device_view() for k, v in f.param_arrays().items()}
        x = f.apply(p, x, train=False)
    truth = numpy.asarray(jax.device_get(x))
    model = NativeModel(pkg)
    out = model(batch.astype(numpy.float32)).reshape(truth.shape)
    numpy.testing.assert_allclose(out, truth, rtol=2e-3, atol=2e-4)
    model.close()


@needs_native
def test_native_char_lm_parity():
    """Full LM net (embedding → rope block → lm_head) through the C++
    engine vs the jitted chain."""
    from conftest import import_model
    lm = import_model("char_lm")
    import tempfile
    wf = lm.build_workflow(epochs=1, minibatch_size=32, n_blocks=1,
                           dim=16, n_train=128, n_valid=32)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    with tempfile.TemporaryDirectory() as tmp:
        pkg = os.path.join(tmp, "lm")
        from veles_tpu.export import package_export
        package_export(wf, pkg, with_stablehlo=False)
        batch = wf.loader.original_data.mem[:4].copy()
        import jax
        x = batch
        for f in wf.forwards:
            p = {k: v.device_view()
                 for k, v in f.param_arrays().items()}
            x = f.apply(p, x, train=False)
        truth = numpy.asarray(jax.device_get(x))
        model = NativeModel(pkg)
        out = model(batch.astype(numpy.float32)).reshape(truth.shape)
        numpy.testing.assert_allclose(out, truth, rtol=2e-3, atol=2e-4)
        model.close()


@needs_native
def test_native_rnn_cutter_parity(tmp_path):
    """Round-2 native additions: plain RNN, Cutter crop. A cutter→rnn
    chain exported and compared against the python oracle."""
    wf = vt.Workflow(name="rc")
    cut = nn.Cutter(wf, padding=(1, 1, 1, 1), name="cut")
    rng = numpy.random.RandomState(3)
    x = rng.rand(6, 5, 8, 3).astype(numpy.float32)
    cut.input = vt.Array(x)
    cut.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    cropped = cut.numpy_apply({}, x)            # (6, 3, 6, 3)
    seq = cropped.reshape(6, 3, 18)
    rnn = nn.RNN(wf, hidden_size=7, return_sequences=True, name="r")
    rnn.input = vt.Array(seq)
    rnn.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    truth = rnn.numpy_apply(rnn.params_np(), seq)

    pkg = str(tmp_path / "rnn-pkg")
    wf.forwards = [rnn]
    package_export(wf, pkg, input_shape=[6, 3, 18],
                   with_stablehlo=False)
    model = NativeModel(pkg)
    out = model(seq).reshape(truth.shape)
    numpy.testing.assert_allclose(out, truth, rtol=2e-3, atol=2e-4)
    model.close()

    wf2 = vt.Workflow(name="cut-wf")
    wf2.forwards = [cut]
    pkg2 = str(tmp_path / "cut-pkg")
    package_export(wf2, pkg2, input_shape=list(x.shape),
                   with_stablehlo=False)
    m2 = NativeModel(pkg2)
    out2 = m2(x).reshape(cropped.shape)
    numpy.testing.assert_allclose(out2, cropped, rtol=1e-5, atol=1e-6)
    m2.close()


@needs_native
def test_native_kohonen_rbm_parity(tmp_path):
    """Round-2 native additions: Kohonen BMU lookup + RBM hidden
    probabilities, vs their python oracles."""
    rng = numpy.random.RandomState(5)
    x = rng.rand(12, 6).astype(numpy.float32)

    wf = vt.Workflow(name="kf")
    kf = nn.KohonenForward(wf, shape=(3, 3), name="k")
    kf.input = vt.Array(x)
    kf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    winners = kf.numpy_apply(kf.params_np(), x)
    pkg = str(tmp_path / "kf-pkg")
    wf.forwards = [kf]
    package_export(wf, pkg, input_shape=list(x.shape),
                   with_stablehlo=False)
    m = NativeModel(pkg)
    out = m(x).reshape(winners.shape)
    numpy.testing.assert_array_equal(out.astype(numpy.int32), winners)
    m.close()

    wf2 = vt.Workflow(name="rb")
    rbm = nn.RBM(wf2, n_hidden=5, name="rbm")
    rbm.input = vt.Array(x)
    rbm.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    truth = rbm.numpy_apply(rbm.params_np(), x)
    pkg2 = str(tmp_path / "rbm-pkg")
    wf2.forwards = [rbm]
    package_export(wf2, pkg2, input_shape=list(x.shape),
                   with_stablehlo=False)
    m2 = NativeModel(pkg2)
    out2 = m2(x).reshape(truth.shape)
    numpy.testing.assert_allclose(out2, truth, rtol=2e-3, atol=2e-4)
    m2.close()


def test_package_tgz_roundtrip(tmp_path):
    """The reference exported zip OR tgz (Workflow.package_export,
    veles/workflow.py:868): the WRITER's .tgz branch must produce an
    archive the executor round-trips."""
    from veles_tpu.memory import Array
    wf = vt.Workflow(name="tgz-wf")
    fc = nn.All2AllTanh(wf, output_sample_shape=6, name="fc")
    x = numpy.random.RandomState(0).rand(5, 9).astype(numpy.float32)
    fc.input = Array(x)
    fc.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.forwards = [fc]
    tgz = package_export(wf, str(tmp_path / "net.tgz"),
                         input_shape=[5, 9], with_stablehlo=False)
    assert tgz.endswith(".tgz") and os.path.exists(tgz)
    assert not (tmp_path / "net").exists()      # staging dir cleaned
    out = run_package(tgz, x)
    oracle = fc.numpy_apply(fc.params_np(), x)
    numpy.testing.assert_allclose(out, oracle, rtol=1e-5, atol=1e-6)


@needs_native
def test_native_cli_greedy_generation(tmp_path):
    """veles_infer --generate: native greedy LM decoding over an
    exported package (sliding full-window re-forward, argmax of the
    last position) — serving a language model with zero Python. Oracle:
    the same sliding-window decode through the python numpy chain."""
    from conftest import import_model
    lm = import_model("char_lm")
    from veles_tpu import prng
    prng.seed_all(11)
    wf = lm.build_workflow(epochs=2, minibatch_size=32, n_blocks=1,
                           dim=16, n_train=128, n_valid=32)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    pkg = str(tmp_path / "lm_pkg")
    from veles_tpu.export import package_export
    package_export(wf, pkg, with_stablehlo=False)

    t = lm.SEQ_LEN
    rng = numpy.random.RandomState(5)
    prompt = numpy.asarray(list(lm.make_corpus(rng, t)),
                           dtype=numpy.float32)
    n_new = 12
    inp = str(tmp_path / "prompt.npy")
    outp = str(tmp_path / "gen.npy")
    numpy.save(inp, prompt)
    r = subprocess.run([BIN, "--generate", str(n_new), pkg, inp, outp],
                       capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, r.stderr
    got = numpy.load(outp).astype(numpy.int32).tolist()

    # python oracle: identical sliding-window semantics via numpy chain
    params = [(f, f.params_np()) for f in wf.forwards]

    def forward(window):
        x = numpy.asarray(window, dtype=numpy.float32)[None]
        for f, p in params:
            x = f.numpy_apply(p, x)
        return x[0]                      # (T, vocab)

    window = prompt.tolist()
    expect = []
    for _ in range(n_new):
        logits = forward(window)
        nxt = int(numpy.argmax(logits[-1]))
        expect.append(nxt)
        window = window[1:] + [nxt]
    assert got == expect, (got, expect)
    # wrong-length prompt refused loudly
    short = str(tmp_path / "short.npy")
    numpy.save(short, prompt[: t // 2])
    r = subprocess.run([BIN, "--generate", "4", pkg, short, outp],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode != 0 and "window" in r.stderr


def _build_fanin(tmp_path):
    """input → (tanh fa | relu fb) → InputJoiner → softmax head: the
    smallest graph a chain executor cannot run (VERDICT r4 item 6).
    Returns (pkg_dir, x, truth)."""
    dev = vt.XLADevice(mesh_axes={"data": 1})
    wf = vt.Workflow(name="fanin")
    rng = numpy.random.RandomState(11)
    x = rng.rand(6, 10).astype(numpy.float32)
    fa = nn.All2AllTanh(wf, output_sample_shape=7, name="fa")
    fa.input = vt.Array(x)
    fa.initialize(device=dev)
    fb = nn.All2AllRelu(wf, output_sample_shape=5, name="fb")
    fb.input = vt.Array(x)
    fb.initialize(device=dev)
    ya = fa.numpy_apply(fa.params_np(), x)
    yb = fb.numpy_apply(fb.params_np(), x)
    join = vt.InputJoiner(wf, inputs=[vt.Array(ya), vt.Array(yb)],
                          name="join")
    yj = join.numpy_apply({}, ya, yb)
    head = nn.All2AllSoftmax(wf, output_sample_shape=3, name="head")
    head.input = vt.Array(yj)
    head.initialize(device=dev)
    truth = head.numpy_apply(head.params_np(), yj)

    wf.forwards = [fa, fb, join, head]
    pkg = str(tmp_path / "fanin-pkg")
    package_export(wf, pkg, input_shape=list(x.shape),
                   with_stablehlo=False,
                   graph=[["@input"], ["@input"], ["fa", "fb"],
                          ["join"]])
    return pkg, x, truth


def test_python_executor_fanin_dag(tmp_path):
    pkg, x, truth = _build_fanin(tmp_path)
    out = run_package(pkg, x)
    numpy.testing.assert_allclose(out, truth, rtol=2e-3, atol=2e-4)


@needs_native
def test_native_fanin_dag_parity(tmp_path):
    """The C++ executor runs the fan-in DAG with liveness-pooled
    buffers and matches the python oracle (replaces the two-buffer
    ping-pong chain limitation)."""
    pkg, x, truth = _build_fanin(tmp_path)
    model = NativeModel(pkg)
    assert model.unit_count == 4
    out = model(x).reshape(truth.shape)
    numpy.testing.assert_allclose(out, truth, rtol=2e-3, atol=2e-4)
    model.close()


@needs_native
def test_native_rejects_forward_reference(tmp_path):
    """A package whose unit names a LATER unit as input must fail to
    load with a topological-order error, not crash."""
    import json
    pkg, x, _ = _build_fanin(tmp_path)
    cpath = os.path.join(pkg, "contents.json")
    with open(cpath) as fin:
        contents = json.load(fin)
    contents["units"][0]["inputs"] = ["head"]      # forward reference
    with open(cpath, "w") as fout:
        json.dump(contents, fout)
    from veles_tpu.error import VelesError
    with pytest.raises(VelesError, match="topologically"):
        NativeModel(pkg)


def test_export_rejects_forward_reference_graph(tmp_path):
    from veles_tpu.error import VelesError
    dev = vt.XLADevice(mesh_axes={"data": 1})
    wf = vt.Workflow(name="badg")
    rng = numpy.random.RandomState(1)
    x = rng.rand(4, 6).astype(numpy.float32)
    fa = nn.All2AllTanh(wf, output_sample_shape=4, name="a")
    fa.input = vt.Array(x)
    fa.initialize(device=dev)
    fb = nn.All2AllTanh(wf, output_sample_shape=4, name="b")
    fb.input = vt.Array(x)
    fb.initialize(device=dev)
    wf.forwards = [fa, fb]
    with pytest.raises(VelesError, match="preceding"):
        package_export(wf, str(tmp_path / "bad"),
                       input_shape=list(x.shape), with_stablehlo=False,
                       graph=[["b"], ["a"]])


@needs_native
def test_native_legacy_chain_package(trained_pkg, tmp_path):
    """Packages written before the "inputs" key (format v1 chains)
    must keep executing: absent inputs default to the previous unit."""
    import json
    import shutil
    pkg, batch, truth = trained_pkg
    legacy = str(tmp_path / "legacy")
    shutil.copytree(pkg, legacy)
    cpath = os.path.join(legacy, "contents.json")
    with open(cpath) as fin:
        contents = json.load(fin)
    for u in contents["units"]:
        u.pop("inputs", None)
    with open(cpath, "w") as fout:
        json.dump(contents, fout)
    model = NativeModel(legacy)
    out = model(batch).reshape(truth.shape)
    numpy.testing.assert_allclose(out, truth, rtol=2e-3, atol=2e-4)
    model.close()


@needs_native
def test_future_format_rejected(trained_pkg, tmp_path):
    """A format_version newer than the readers must be refused by BOTH
    executors, not silently half-executed."""
    import json
    import shutil
    pkg, batch, _ = trained_pkg
    future = str(tmp_path / "future")
    shutil.copytree(pkg, future)
    cpath = os.path.join(future, "contents.json")
    with open(cpath) as fin:
        contents = json.load(fin)
    contents["format_version"] = 99
    with open(cpath, "w") as fout:
        json.dump(contents, fout)
    from veles_tpu.error import VelesError
    with pytest.raises(VelesError, match="newer"):
        NativeModel(future)
    with pytest.raises(VelesError, match="newer"):
        package_import(future)


@needs_native
def test_native_empty_batch_is_clean_error(trained_pkg):
    pkg, batch, _ = trained_pkg
    from veles_tpu.error import VelesError
    model = NativeModel(pkg)
    try:
        with pytest.raises((VelesError, ValueError)):
            model(numpy.empty((0, batch[0].size), dtype=numpy.float32))
    finally:
        model.close()


@needs_native
def test_native_cached_generation_matches_python_sampler(tmp_path):
    """vi_generate / --generate-cached: KV-cached native greedy
    decoding — one cached step per token, any prompt length — must
    emit the SAME ids as (a) the python cached sampler
    (nn.sampling.generate, temperature 0) and (b) a growing-context
    numpy-chain re-forward at the same positions."""
    from conftest import import_model
    lm = import_model("char_lm")
    from veles_tpu import prng
    from veles_tpu.nn import sampling
    prng.seed_all(21)
    wf = lm.build_workflow(epochs=2, minibatch_size=32, n_blocks=2,
                           dim=16, n_train=128, n_valid=32)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    pkg = str(tmp_path / "lm_pkg")
    from veles_tpu.export import package_export
    package_export(wf, pkg, with_stablehlo=False)

    rng = numpy.random.RandomState(3)
    prompt = [int(t) for t in lm.make_corpus(rng, 11)]  # != SEQ_LEN
    n_new = 10
    want = sampling.generate(wf, prompt, n_new, temperature=0)

    # numpy growing-context oracle (same positions as the cache)
    params = [(f, f.params_np()) for f in wf.forwards]

    def argmax_next(ctx):
        x = numpy.asarray(ctx, dtype=numpy.float32)[None]
        for f, p in params:
            x = f.numpy_apply(p, x)
        return int(numpy.argmax(x[0, -1]))

    ctx = list(prompt)
    oracle = []
    for _ in range(n_new):
        nxt = argmax_next(ctx)
        oracle.append(nxt)
        ctx.append(nxt)
    assert want == oracle, (want, oracle)   # python cached == numpy

    model = NativeModel(pkg)
    got = model.generate(prompt, n_new)
    model.close()
    assert got == oracle, (got, oracle)

    # CLI twin
    inp = str(tmp_path / "prompt.npy")
    outp = str(tmp_path / "gen.npy")
    numpy.save(inp, numpy.asarray(prompt, dtype=numpy.float32))
    r = subprocess.run([BIN, "--generate-cached", str(n_new), pkg,
                        inp, outp], capture_output=True, text=True,
                       timeout=240)
    assert r.returncode == 0, r.stderr
    assert numpy.load(outp).astype(int).tolist() == oracle


@needs_native
def test_native_cached_generation_rejects_non_lm(trained_pkg):
    pkg, _, _ = trained_pkg
    from veles_tpu.error import VelesError
    model = NativeModel(pkg)
    try:
        with pytest.raises(VelesError, match="generation"):
            model.generate([1, 2], 4)
    finally:
        model.close()


@needs_native
def test_native_cached_generation_gqa_window(tmp_path):
    """The native cache stores UNREPEATED kv heads and clips the
    window exactly like the python cache: a GQA (n_kv_heads=2 of 4)
    sliding-window block stack decodes id-exact vs the growing-context
    numpy oracle."""
    from veles_tpu.loader import FullBatchLoaderMSE

    class Toks(FullBatchLoaderMSE):
        hide_from_registry = True

        def load_data(self):
            rng = numpy.random.RandomState(8)
            stream = rng.randint(0, 8, 48 * 12 + 1).astype(numpy.int32)
            self.create_originals(stream[:-1].reshape(48, 12), None,
                                  targets=stream[1:].reshape(48, 12))
            self.class_lengths = [0, 12, 36]

    wf = nn.StandardWorkflow(
        name="gqa-lm",
        layers=[{"type": "embedding", "vocab_size": 8, "dim": 16},
                {"type": "transformer_block", "n_heads": 4,
                 "n_kv_heads": 2, "window": 6, "causal": True,
                 "rope": True, "ffn_hidden": 32, "norm": "rms",
                 "ffn": "swiglu"},
                {"type": "lm_head", "vocab_size": 8}],
        loader_unit=Toks(None, minibatch_size=12, name="tk"),
        loss_function="softmax_seq",
        decision_config=dict(max_epochs=1), steps_per_dispatch=2)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    pkg = str(tmp_path / "gqa_pkg")
    package_export(wf, pkg, with_stablehlo=False)

    params = [(f, f.params_np()) for f in wf.forwards]

    def argmax_next(ctx):
        x = numpy.asarray(ctx, dtype=numpy.float32)[None]
        for f, p in params:
            x = f.numpy_apply(p, x)
        return int(numpy.argmax(x[0, -1]))

    prompt = [1, 5, 2, 7, 0]
    ctx = list(prompt)
    oracle = []
    for _ in range(9):            # decode PAST the window span
        nxt = argmax_next(ctx)
        oracle.append(nxt)
        ctx.append(nxt)

    # the python CACHED sampler must agree on the same GQA/window
    # stepping (sampling._block_step) — the docs claim this parity
    from veles_tpu.nn import sampling
    assert sampling.generate(wf, prompt, 9, temperature=0) == oracle

    model = NativeModel(pkg)
    got = model.generate(prompt, 9)
    model.close()
    assert got == oracle, (got, oracle)
