"""Recurrent-unit gradients and the scan ↔ recurrence identity.

Two locks for the O(1)-state lane (ISSUE 16):

1. **numeric gradients** — GDLSTM/GDRNN (and GDSSMBlock) backward is
   plain autodiff through the scan (``GradientDescentBase.
   compute_grads`` = ``jax.vjp``); a finite-difference directional
   derivative of the scalar loss ``sum(apply(params, x) * E)`` must
   agree with the analytic gradient for every parameter tensor AND
   the input cotangent. This is the BPTT correctness anchor — the
   reference's numeric-vs-analytic gradient drill, adapted.

2. **scan-vs-recurrent equivalence** — the serving duality: jitted
   ``scan_state`` (prefill mode) against a host loop of the jitted
   ``step_state`` (decode mode) must agree BIT-EXACTLY on outputs and
   final state, because both are the same step body (`lax.scan` of it
   vs single applications). Any tolerance here would let the serving
   lane's modes drift; equality is asserted with ``==``, not
   allclose. Padded scans (``length=``) must carry bit-identical
   state to the unpadded scan — that is what makes fixed-width chunk
   prefill id-exact.
"""
import functools

import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn
from veles_tpu.memory import Array


@pytest.fixture(autouse=True)
def f32_compute():
    prev = vt.root.common.engine.compute_dtype
    vt.root.common.engine.compute_dtype = "float32"
    yield
    vt.root.common.engine.compute_dtype = prev


def _built_unit(unit_cls, input_shape, seed=11, **kwargs):
    wf = vt.Workflow(name="t")
    u = unit_cls(wf, **kwargs)
    rng = numpy.random.RandomState(seed)
    x = rng.randn(*input_shape).astype(numpy.float32)
    u.input = Array(x, name="x")
    u.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    return wf, u, x


# -- numeric vs analytic gradients (satellite: BPTT anchor) --------------------

def _fd_directional_check(unit_cls, input_shape, rtol=2e-2, **kwargs):
    """Central finite difference of loss(p) = sum(apply(p, x) * E)
    along a random unit direction per tensor vs the analytic vjp."""
    import jax.numpy as jnp
    wf, fwd, x = _built_unit(unit_cls, input_shape, **kwargs)
    fwd.xla_run()
    rng = numpy.random.RandomState(7)
    e_out = rng.randn(*numpy.asarray(fwd.output.map_read()).shape) \
        .astype(numpy.float32)
    gd = nn.nn_units.MATCHING[unit_cls](wf, learning_rate=0.0)
    gd.forward = fwd
    gd.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    xgrad, pgrads = gd.compute_grads(jnp.asarray(e_out))
    params = {k: numpy.asarray(v.map_read(), numpy.float32)
              for k, v in fwd.param_arrays().items()}

    def loss(p, xx):
        y = numpy.asarray(
            fwd.apply({k: jnp.asarray(v) for k, v in p.items()},
                      jnp.asarray(xx), train=True))
        return float((y.astype(numpy.float64)
                      * e_out.astype(numpy.float64)).sum())

    eps = 1e-2
    checked = 0
    for k, p in params.items():
        d = rng.randn(*p.shape).astype(numpy.float32)
        d /= max(numpy.linalg.norm(d), 1e-12)
        hi = dict(params)
        lo = dict(params)
        hi[k] = p + eps * d
        lo[k] = p - eps * d
        fd = (loss(hi, x) - loss(lo, x)) / (2 * eps)
        an = float((numpy.asarray(pgrads[k], numpy.float64) * d).sum())
        scale = max(abs(fd), abs(an), 1e-3)
        assert abs(fd - an) <= rtol * scale, \
            "%s.%s: fd=%g analytic=%g" % (unit_cls.__name__, k, fd, an)
        checked += 1
    assert checked == len(params)
    # and the input cotangent (err_input feeds the previous layer)
    d = rng.randn(*x.shape).astype(numpy.float32)
    d /= numpy.linalg.norm(d)
    fd = (loss(params, x + eps * d) - loss(params, x - eps * d)) \
        / (2 * eps)
    an = float((numpy.asarray(xgrad, numpy.float64) * d).sum())
    scale = max(abs(fd), abs(an), 1e-3)
    assert abs(fd - an) <= rtol * scale, \
        "%s err_input: fd=%g analytic=%g" % (unit_cls.__name__, fd, an)


def test_gdlstm_numeric_gradient():
    _fd_directional_check(nn.LSTM, (3, 6, 5), hidden_size=4,
                          return_sequences=True)


def test_gdlstm_numeric_gradient_last_state():
    _fd_directional_check(nn.LSTM, (2, 5, 4), hidden_size=3)


def test_gdrnn_numeric_gradient():
    _fd_directional_check(nn.RNN, (3, 6, 5), hidden_size=4,
                          return_sequences=True)


def test_gdssm_numeric_gradient():
    _fd_directional_check(nn.SSMBlock, (2, 6, 8), n_heads=2)


def test_gd_units_registered():
    """The workflow builder resolves backward units through MATCHING —
    every recurrent forward must have its GD mate registered."""
    assert nn.nn_units.MATCHING[nn.LSTM] is nn.GDLSTM
    assert nn.nn_units.MATCHING[nn.RNN] is nn.GDRNN
    assert nn.nn_units.MATCHING[nn.SSMBlock] is nn.GDSSMBlock


# -- scan ↔ recurrence bit-identity (the serving duality lock) -----------------

def _params_of(u):
    import jax.numpy as jnp
    return {k: jnp.asarray(numpy.asarray(v.map_read()))
            for k, v in u.param_arrays().items()}


def _bit_identity_check(unit_cls, input_shape, **kwargs):
    import jax
    import jax.numpy as jnp
    wf, u, x = _built_unit(unit_cls, input_shape, **kwargs)
    params = _params_of(u)
    b, t, _ = x.shape
    st0 = u.init_state(b, jnp.float32)

    scan = jax.jit(functools.partial(u.scan_state))
    step = jax.jit(u.step_state)
    ys_scan, st_scan = scan(params, jnp.asarray(x), st0)
    st = st0
    ys_loop = []
    for i in range(t):
        y, st = step(params, jnp.asarray(x[:, i, :]), st)
        ys_loop.append(numpy.asarray(y))
    ys_loop = numpy.stack(ys_loop, axis=1)
    # EXACT equality — the two modes are the same compiled step body
    assert (numpy.asarray(ys_scan) == ys_loop).all(), \
        "%s scan vs recurrent outputs differ" % unit_cls.__name__
    for k in st_scan:
        assert (numpy.asarray(st_scan[k])
                == numpy.asarray(st[k])).all(), \
            "%s final state %r differs" % (unit_cls.__name__, k)
    return u, params, x, st0, scan


def test_lstm_scan_vs_recurrent_bit_identity():
    _bit_identity_check(nn.LSTM, (2, 9, 5), hidden_size=4,
                        return_sequences=True)


def test_rnn_scan_vs_recurrent_bit_identity():
    _bit_identity_check(nn.RNN, (2, 7, 5), hidden_size=4,
                        return_sequences=True)


def test_ssm_scan_vs_recurrent_bit_identity():
    _bit_identity_check(nn.SSMBlock, (2, 9, 8), n_heads=4)


def test_padded_scan_state_bit_identical():
    """length= masking: an (B, T_pad) scan over garbage tail tokens
    must carry EXACTLY the state of the unpadded scan — the chunked
    prefill's correctness hinges on this."""
    import jax
    import jax.numpy as jnp
    for unit_cls, kwargs, d in ((nn.LSTM,
                                 {"hidden_size": 4,
                                  "return_sequences": True}, 5),
                                (nn.SSMBlock, {"n_heads": 2}, 8)):
        wf, u, x = _built_unit(unit_cls, (2, 8, d), **kwargs)
        params = _params_of(u)
        st0 = u.init_state(2, jnp.float32)
        scan = jax.jit(functools.partial(u.scan_state))
        n_real = 5
        _, st_ref = scan(params, jnp.asarray(x[:, :n_real, :]), st0)
        # garbage tail: huge values would poison state if the mask
        # leaked
        x_pad = x.copy()
        x_pad[:, n_real:, :] = 1e6
        _, st_pad = scan(params, jnp.asarray(x_pad), st0,
                         jnp.int32(n_real))
        for k in st_ref:
            assert (numpy.asarray(st_ref[k])
                    == numpy.asarray(st_pad[k])).all(), \
                "%s padded state %r differs" % (unit_cls.__name__, k)


def test_state_shapes_match_init_state():
    import jax.numpy as jnp
    for unit_cls, kwargs, d in ((nn.LSTM, {"hidden_size": 6}, 5),
                                (nn.RNN, {"hidden_size": 6}, 5),
                                (nn.SSMBlock, {"n_heads": 2}, 8)):
        wf, u, x = _built_unit(unit_cls, (3, 4, d), **kwargs)
        st = u.init_state(3, jnp.float32)
        shapes = u.state_shapes(3)
        assert set(st) == set(shapes)
        for k in st:
            assert tuple(st[k].shape) == tuple(shapes[k])


def test_ssm_oracle():
    """XLA scan path vs the numpy oracle (run_both analog, kept here
    with the rest of the recurrent family)."""
    wf, u, x = _built_unit(nn.SSMBlock, (2, 6, 8), n_heads=2)
    u.xla_run()
    y_xla = numpy.asarray(u.output.map_read(), numpy.float32)
    y_np = u.numpy_apply(u.params_np(), x).astype(numpy.float32)
    numpy.testing.assert_allclose(y_xla, y_np, rtol=1e-4, atol=1e-5)


def test_ssm_rejects_bad_heads():
    from veles_tpu.error import VelesError
    wf = vt.Workflow(name="t")
    u = nn.SSMBlock(wf, n_heads=3)
    u.input = Array(numpy.zeros((2, 4, 8), numpy.float32), name="x")
    with pytest.raises(VelesError):
        u.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
