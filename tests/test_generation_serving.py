"""GenerationAPI (restful_api.py): the generation stack served over
REST with micro-batched concurrent requests — greedy/sample/
speculative/beam end-to-end, answers identical to solo decodes
(reference equivalent: veles/restful_api.py:78 serving one forward per
request; here the serving batch axis carries whole decodes).

This file pins ``engine="window"`` — it exercises the legacy
shape-keyed coalescing worker (still the path for speculative/beam and
for requests the slot pool cannot hold). The continuous-batching plane
has its own suite in tests/test_serving_engine.py."""
import json
import threading
import urllib.request
import urllib.error

import numpy
import pytest

import veles_tpu as vt
from veles_tpu import prng

from conftest import import_model


def _post(url, payload, timeout=60.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture(scope="module")
def served():
    lm = import_model("char_lm")
    prng.seed_all(777)
    target = lm.build_workflow(epochs=2, minibatch_size=64, n_blocks=2,
                               dim=32, n_train=256, n_valid=64)
    target.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    target.run()
    prng.seed_all(778)
    draft = lm.build_workflow(epochs=1, minibatch_size=64, n_blocks=1,
                              dim=16, n_train=256, n_valid=64)
    draft.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    draft.run()
    api = vt.GenerationAPI(target, draft=draft, port=0,
                           batch_window=0.25, engine="window",
                           name="genapi")
    api.initialize()
    url = "http://127.0.0.1:%d/generate" % api.port
    yield lm, target, draft, api, url
    api.stop()


def _prompt(lm, seed, length=12):
    return [int(t) for t in
            lm.make_corpus(numpy.random.RandomState(seed), length)]


def test_greedy_roundtrip_matches_solo(served):
    lm, target, draft, api, url = served
    p = _prompt(lm, 1)
    code, out = _post(url, {"prompt": p, "n_new": 12})
    assert code == 200, out
    assert out["tokens"] == lm.generate(target, p, 12, temperature=0)


def test_concurrent_requests_micro_batch(served):
    """Simultaneous same-shape greedy requests coalesce into ONE
    batched decode, and every answer equals its solo decode."""
    lm, target, draft, api, url = served
    prompts = [_prompt(lm, s) for s in (2, 3, 4, 5)]
    # warm the (batch=4, t_p, n_new) executable so the timed window
    # isn't a compile
    from veles_tpu.nn import sampling
    sampling.generate(target, prompts, 10, temperature=0)
    results = {}
    barrier = threading.Barrier(len(prompts))

    def fire(i):
        barrier.wait()
        results[i] = _post(url, {"prompt": prompts[i], "n_new": 10})

    before = api.batches_run
    threads = [threading.Thread(target=fire, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90)
    for i, p in enumerate(prompts):
        code, out = results[i]
        assert code == 200, out
        assert out["tokens"] == lm.generate(target, p, 10,
                                            temperature=0)
    assert api.max_batch >= 2          # coalescing actually happened
    assert api.batches_run - before < len(prompts)


def test_speculative_served(served):
    lm, target, draft, api, url = served
    p = _prompt(lm, 6)
    code, out = _post(url, {"prompt": p, "n_new": 10,
                            "mode": "speculative", "gamma": 3})
    assert code == 200, out
    assert out["tokens"] == lm.generate(target, p, 10, temperature=0)
    assert 0.0 <= out["acceptance"] <= 1.0
    assert out["rounds"] >= 1


def test_beam_served(served):
    lm, target, draft, api, url = served
    from veles_tpu.nn.beam import beam_generate
    p = _prompt(lm, 7)
    code, out = _post(url, {"prompt": p, "n_new": 8, "mode": "beam",
                            "beam": 3})
    assert code == 200, out
    want, _ = beam_generate(target, p, 8, beam=3)
    assert out["tokens"] == want
    assert len(out["scores"]) == 3


def test_sample_mode_seeded(served):
    lm, target, draft, api, url = served
    p = _prompt(lm, 8)
    code, out = _post(url, {"prompt": p, "n_new": 10, "mode": "sample",
                            "temperature": 0.8, "seed": 42})
    assert code == 200, out
    from veles_tpu.nn import sampling
    assert out["tokens"] == sampling.generate(target, p, 10,
                                              temperature=0.8, seed=42)


def test_bad_requests_rejected(served):
    lm, target, draft, api, url = served
    for payload, frag in (
            ({"prompt": [], "n_new": 4}, "prompt"),
            ({"prompt": [1, "x"], "n_new": 4}, "prompt"),
            ({"prompt": [1, 2], "n_new": 0}, "n_new"),
            ({"prompt": [1, 2], "n_new": 4, "mode": "magic"}, "mode"),
            ({"prompt": [1, 2], "n_new": 4, "mode": "sample"},
             "temperature"),
            ({"prompt": [1, 2], "n_new": 4, "gamma": 0}, "gamma"),
            ({"prompt": [1, 2], "n_new": 4, "temperature": None,
              "mode": "sample"}, "non-numeric"),
            ({"prompt": [1, 2], "n_new": 4, "seed": {}}, "non-numeric"),
    ):
        code, out = _post(url, payload)
        assert code == 400, (payload, out)
        assert frag in out["error"], (payload, out)


def test_decoder_shape_errors_are_client_faults(served):
    """ValueError raised by the decoder on a parsed request (beam
    wider than vocab; generation beyond the positional table) must
    come back 400, not 500."""
    lm, target, draft, api, url = served
    p = _prompt(lm, 9)
    code, out = _post(url, {"prompt": p, "n_new": 4, "mode": "beam",
                            "beam": 10_000})
    assert code == 400, (code, out)
    assert "vocab" in out["error"]


def test_concurrent_stochastic_requests_coalesce_and_stay_seeded(served):
    """Two simultaneous same-shape mode=sample requests COALESCE into
    one batched decode (sampling.generate draws per-row PRNG streams,
    so a row's noise is a pure function of its own seed) and each
    still gets exactly its SOLO decode — the determinism contract the
    old _solo singleton tag existed to protect, now held by
    construction."""
    lm, target, draft, api, url = served
    from veles_tpu.nn import sampling
    p1, p2 = _prompt(lm, 21), _prompt(lm, 22)
    want = {0: sampling.generate(target, p1, 8, temperature=0.7,
                                 seed=5),
            1: sampling.generate(target, p2, 8, temperature=0.7,
                                 seed=5)}
    # warm the (batch=2, t_p, n_new, temp) executable so the timed
    # window isn't a compile
    sampling.generate(target, [p1, p2], 8, temperature=0.7, seed=5)
    # same shape key now that _solo is gone for mode=sample
    assert api._batch_key(
        {"mode": "sample", "prompt": p1, "n_new": 8,
         "temperature": 0.7, "gamma": 4, "seed": 5}) == \
        api._batch_key(
        {"mode": "sample", "prompt": p2, "n_new": 8,
         "temperature": 0.7, "gamma": 4, "seed": 5})
    results = {}
    barrier = threading.Barrier(2)

    def fire(i, p):
        barrier.wait()
        results[i] = _post(url, {"prompt": p, "n_new": 8,
                                 "mode": "sample", "temperature": 0.7,
                                 "seed": 5})

    before = api.batches_run
    threads = [threading.Thread(target=fire, args=(i, p))
               for i, p in ((0, p1), (1, p2))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90)
    for i in (0, 1):
        code, out = results[i]
        assert code == 200, out
        assert out["tokens"] == want[i]
    # the pair rode fewer batches than requests — coalescing happened
    assert api.batches_run - before < 2


def test_stochastic_speculative_still_runs_solo(served):
    """generate_speculative's stochastic accept path draws
    batch-shaped noise, so temperature>0 speculative requests keep the
    _solo singleton tag — only mode=sample lost it."""
    lm, target, draft, api, url = served
    p = _prompt(lm, 23)
    base = {"mode": "speculative", "prompt": p, "n_new": 8,
            "temperature": 0.7, "gamma": 3, "seed": 5}
    k1 = api._batch_key(api._parse(dict(base, prompt=list(p))))
    k2 = api._batch_key(api._parse(dict(base, prompt=list(p))))
    assert k1 != k2          # unique _solo per stochastic-spec request


def test_speculative_without_draft_rejected(served):
    lm, target, draft, api, url = served
    api2 = vt.GenerationAPI(target, draft=None, port=0, name="nodraft")
    api2.initialize()
    try:
        code, out = _post(
            "http://127.0.0.1:%d/generate" % api2.port,
            {"prompt": [1, 2], "n_new": 4, "mode": "speculative"})
        assert code == 400
        assert "draft" in out["error"]
    finally:
        api2.stop()


def test_eos_id_truncates_and_does_not_fragment_batch(served):
    """eos_id stops output at the first stop token (inclusive) without
    entering the batch key — two requests differing only in eos_id may
    share one decode, each truncated to its own stop."""
    lm, target, draft, api, url = served
    p = _prompt(lm, 30)
    code, full = _post(url, {"prompt": p, "n_new": 12})
    assert code == 200
    eos = full["tokens"][4]          # a token known to appear at idx 4
    code, cut = _post(url, {"prompt": p, "n_new": 12, "eos_id": eos})
    assert code == 200
    first = full["tokens"].index(eos)
    assert cut["tokens"] == full["tokens"][:first + 1]
    assert cut["tokens"][-1] == eos
    # same key => eos requests batch with non-eos ones
    assert api._batch_key({"mode": "greedy", "prompt": p, "n_new": 12,
                           "temperature": 0.0, "gamma": 4, "seed": 0,
                           "eos_id": eos}) == \
        api._batch_key({"mode": "greedy", "prompt": p, "n_new": 12,
                        "temperature": 0.0, "gamma": 4, "seed": 0,
                        "eos_id": None})
    for bad in ("x", True):
        code, out = _post(url, {"prompt": p, "n_new": 4,
                                "eos_id": bad})
        assert code == 400 and "eos_id" in out["error"], (bad, out)


def test_generation_from_sharded_training_mesh():
    """Serving a model trained on a data x tensor mesh: the decoders
    must accept tp-sharded params (a real user path: train sharded,
    then serve the same in-memory workflow)."""
    lm = import_model("char_lm")
    prng.seed_all(31)
    wf = lm.build_workflow(epochs=1, minibatch_size=64, n_blocks=2,
                           dim=32, n_train=256, n_valid=64)
    wf.initialize(device=vt.XLADevice(
        mesh_axes={"data": 2, "tensor": 2}))
    wf.run()
    w = wf.train_step.params["blk0"]["wq"]
    assert "tensor" in w.sharding.spec        # really sharded
    p = [int(t) for t in
         lm.make_corpus(numpy.random.RandomState(0), 12)]
    from veles_tpu.nn import sampling
    from veles_tpu.nn.beam import beam_generate
    toks = sampling.generate(wf, p, 8, temperature=0)
    assert len(toks) == 8
    best, stats = beam_generate(wf, p, 6, beam=2)
    assert len(best) == 6 and len(stats["scores"]) == 2


def test_stats_endpoint(served):
    lm, target, draft, api, url = served
    # self-contained: issue one request so the counters are non-zero
    # even when this test runs in isolation
    code, _ = _post(url, {"prompt": _prompt(lm, 40), "n_new": 4})
    assert code == 200
    with urllib.request.urlopen(url + "/stats", timeout=30) as r:
        stats = json.loads(r.read())
    assert stats["requests_served"] >= 1
    assert stats["batches_run"] >= 1
    assert stats["speculative_enabled"] is True
    assert "beam" in stats["modes"]
    # unknown GET paths 404
    try:
        urllib.request.urlopen(
            "http://127.0.0.1:%d/nope" % api.port, timeout=30)
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404
