"""Data-parallel scaling (BASELINE config #4 structure: conv net over a
'data' mesh — real 8-chip hardware is unavailable, so the virtual
8-device mesh validates the sharded program; the driver's
dryrun_multichip covers the composed dp×tp×sp case)."""
import os
import tempfile

import jax
import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn
from veles_tpu.loader import FullBatchLoader


class TinyImages(FullBatchLoader):
    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(0)
        x = rng.rand(512, 8, 8, 3).astype(numpy.float32)
        y = (x[:, :, :, 0].mean(axis=(1, 2)) >
             x[:, :, :, 1].mean(axis=(1, 2))).astype(numpy.int32)
        self.create_originals(x, y)
        self.class_lengths = [0, 128, 384]


def run_conv(dp, epochs=8, seed=7):
    vt.prng.seed_all(seed)
    wf = nn.StandardWorkflow(
        name="conv-dp%d" % dp,
        layers=[{"type": "conv_tanh", "n_kernels": 8, "kx": 3, "ky": 3,
                 "learning_rate": 0.05},
                {"type": "max_pooling", "kx": 2, "ky": 2},
                {"type": "all2all_tanh", "output_sample_shape": 32,
                 "learning_rate": 0.05},
                {"type": "softmax", "output_sample_shape": 2,
                 "learning_rate": 0.05}],
        loader_unit=TinyImages(None, minibatch_size=64),
        loss_function="softmax",
        decision_config=dict(max_epochs=epochs))
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": dp}))
    wf.run()
    return wf


def test_conv_dp8_trains_and_shards():
    wf = run_conv(8)
    res = wf.gather_results()
    assert res["epochs"] >= 8
    assert res["best_err"] < 0.45, res  # learns beyond chance
    # the minibatch plan is genuinely sharded over the 8 devices
    idx = wf.loader.minibatch_indices.devmem
    assert len(idx.sharding.device_set) == 8
    assert not idx.sharding.is_fully_replicated
    # params replicated across the data axis (pure DP)
    w = wf.train_step.params["conv_tanh0"]["weights"]
    assert w.sharding.is_fully_replicated


def test_scaling_sweep_1_to_64():
    """The 1→64 proof (BASELINE.json: "master-slave→psum scaling 1→64"):
    scripts/scaling_sweep.py in subprocesses — the conftest's 8-device
    pin can't cover 64, a fresh XLA init per width can. Two widths keep
    CI affordable; the full 1..64 table is SCALING.json at the repo
    root (regenerate with the script)."""
    import json
    import subprocess
    import sys
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "scaling_sweep.py")
    with tempfile.TemporaryDirectory() as tmp:
        out = os.path.join(tmp, "scaling.json")
        # outer budget > sum of the script's per-width child budgets
        # (900 s each) so a slow width can't surface as an opaque
        # TimeoutExpired here instead of the script's own error report
        proc = subprocess.run(
            [sys.executable, script, "--widths", "1,64", "--out", out],
            capture_output=True, text=True, timeout=2 * 900 + 60)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        with open(out) as fin:
            report = json.load(fin)
    assert report["equivalent"] is True
    w64 = report["widths"][-1]
    assert w64["n"] == 64 and w64["n_devices_used"] == 64
    assert w64["indices_sharded"] and w64["params_replicated"]


def test_dp1_vs_dp8_same_learning_trajectory():
    """Same seed, same data: an 8-way data-parallel run must follow the
    single-device trajectory (psum-of-shards == full-batch gradient up to
    reduction order)."""
    err1 = run_conv(1).gather_results()["err_history"]["train"]
    err8 = run_conv(8).gather_results()["err_history"]["train"]
    assert len(err1) == len(err8)
    numpy.testing.assert_allclose(err1, err8, atol=0.02)
