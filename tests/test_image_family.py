"""Image-loader family depth (VERDICT r1 item 7; reference:
veles/loader/file_image.py + fullbatch_image.py):
- per-class directory trees with labels from subdirectory names;
- deterministic hash-based splits, stable as the dataset grows;
- codec fallbacks (incl. raw .npy arrays);
- on-device augmentation: ONE stored copy per image, random mirror/crop
  fused into the train step (vs the host path's RAM multiplicity)."""
import os

import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn
from veles_tpu.loader import (ClassImageLoader, ImageLoader,
                              deterministic_split, TRAIN, VALID, TEST)


def _write_png(path, arr):
    from PIL import Image
    Image.fromarray((arr * 255).astype(numpy.uint8)).save(path)


@pytest.fixture
def class_tree(tmp_path):
    """3 classes x 20 images, each class a distinct mean color."""
    rng = numpy.random.RandomState(0)
    root = tmp_path / "flowers"
    for ci, cls in enumerate(["daisy", "rose", "tulip"]):
        d = root / cls
        d.mkdir(parents=True)
        for i in range(20):
            img = numpy.clip(rng.rand(12, 12, 3) * 0.3
                             + numpy.eye(3)[ci] * 0.7, 0, 1)
            _write_png(str(d / ("img%02d.png" % i)), img)
    return str(root)


def test_class_tree_scan_and_labels(class_tree):
    loader = ClassImageLoader(None, root_dir=class_tree,
                              valid_ratio=0.25, minibatch_size=10,
                              name="flowers")
    loader.load_data()
    assert sorted(loader.labels_mapping) == ["daisy", "rose", "tulip"]
    assert loader.class_lengths[TRAIN] + loader.class_lengths[VALID] == 60
    assert loader.class_lengths[VALID] > 0
    # labels come from the subdirectory
    assert loader.original_data.shape == (60, 12, 12, 3)


def test_deterministic_split_stability():
    files = ["f%03d.png" % i for i in range(200)]
    t1, v1, s1 = deterministic_split(files, 0.2, 0.1)
    # same files → identical split, regardless of input order
    t2, v2, s2 = deterministic_split(list(reversed(files)), 0.2, 0.1)
    assert (t1, v1, s1) == (t2, v2, s2)
    # growing the dataset never reassigns an existing file
    t3, v3, s3 = deterministic_split(
        files + ["g%03d.png" % i for i in range(50)], 0.2, 0.1)
    assert set(v1) <= set(v3) and set(t1) <= set(t3)
    assert 0.1 < len(v3) / 250 < 0.3        # ratios roughly hold


def test_npy_codec(tmp_path):
    arr = numpy.random.RandomState(1).rand(8, 8, 3).astype("float32")
    p = tmp_path / "x.npy"
    numpy.save(p, arr)
    from veles_tpu.loader import decode_image
    out = decode_image(str(p))
    numpy.testing.assert_allclose(out, arr)
    # uint8-scaled arrays normalize to [0, 1]
    numpy.save(p, (arr * 255).astype(numpy.uint8))
    out = decode_image(str(p))
    assert out.max() <= 1.0


def test_device_augmentation_trains(class_tree):
    """device_augmentation=True: dataset holds ONE copy per image; the
    fused step random-crops+mirrors on device; eval center-crops. The
    color-coded classes must still be learned."""
    loader = ClassImageLoader(
        None, root_dir=class_tree, valid_ratio=0.25, minibatch_size=9,
        mirror=True, crop=(8, 8), device_augmentation=True,
        name="flowers-dev")
    wf = nn.StandardWorkflow(
        name="img-aug",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 16},
                {"type": "softmax", "output_sample_shape": 3}],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=8, fail_iterations=99))
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    # multiplicity 1: stored dataset is the raw image count at full size
    assert loader.original_data.shape == (60, 12, 12, 3)
    # downstream units see the POST-crop shape
    assert loader.minibatch_data.shape[1:] == (8, 8, 3)
    wf.run()
    assert wf.decision.best_metric is not None
    assert wf.decision.best_metric < 0.35, wf.decision.epoch_metrics


def test_host_augmentation_multiplicity(class_tree):
    """The host path materializes mirror x crop_number variants (the
    reference behavior) — kept for rotations and for comparison."""
    loader = ClassImageLoader(
        None, root_dir=class_tree, valid_ratio=0.25, minibatch_size=10,
        mirror=True, crop=(8, 8), crop_number=2, name="flowers-host")
    loader.load_data()
    # train gets 2 mirrors x 2 crops = 4 variants; eval 1 center crop
    n_train_files = loader.class_lengths[TRAIN] // 4
    assert loader.class_lengths[TRAIN] == n_train_files * 4
    assert loader.original_data.shape[1:] == (8, 8, 3)


def test_device_augmentation_rejects_rotations(class_tree):
    loader = ClassImageLoader(
        None, root_dir=class_tree, rotations=(0, 90),
        device_augmentation=True, minibatch_size=10, name="rot")
    with pytest.raises(vt.VelesError, match="rotations"):
        loader.load_data()
