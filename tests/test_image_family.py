"""Image-loader family depth (VERDICT r1 item 7; reference:
veles/loader/file_image.py + fullbatch_image.py):
- per-class directory trees with labels from subdirectory names;
- deterministic hash-based splits, stable as the dataset grows;
- codec fallbacks (incl. raw .npy arrays);
- on-device augmentation: ONE stored copy per image, random mirror/crop
  fused into the train step (vs the host path's RAM multiplicity)."""
import os

import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn
from veles_tpu.loader import (ClassImageLoader, ImageLoader,
                              deterministic_split, TRAIN, VALID, TEST)


def _write_png(path, arr):
    from PIL import Image
    Image.fromarray((arr * 255).astype(numpy.uint8)).save(path)


@pytest.fixture
def class_tree(tmp_path):
    """3 classes x 20 images, each class a distinct mean color."""
    rng = numpy.random.RandomState(0)
    root = tmp_path / "flowers"
    for ci, cls in enumerate(["daisy", "rose", "tulip"]):
        d = root / cls
        d.mkdir(parents=True)
        for i in range(20):
            img = numpy.clip(rng.rand(12, 12, 3) * 0.3
                             + numpy.eye(3)[ci] * 0.7, 0, 1)
            _write_png(str(d / ("img%02d.png" % i)), img)
    return str(root)


def test_class_tree_scan_and_labels(class_tree):
    loader = ClassImageLoader(None, root_dir=class_tree,
                              valid_ratio=0.25, minibatch_size=10,
                              name="flowers")
    loader.load_data()
    assert sorted(loader.labels_mapping) == ["daisy", "rose", "tulip"]
    assert loader.class_lengths[TRAIN] + loader.class_lengths[VALID] == 60
    assert loader.class_lengths[VALID] > 0
    # labels come from the subdirectory
    assert loader.original_data.shape == (60, 12, 12, 3)


def test_deterministic_split_stability():
    files = ["f%03d.png" % i for i in range(200)]
    t1, v1, s1 = deterministic_split(files, 0.2, 0.1)
    # same files → identical split, regardless of input order
    t2, v2, s2 = deterministic_split(list(reversed(files)), 0.2, 0.1)
    assert (t1, v1, s1) == (t2, v2, s2)
    # growing the dataset never reassigns an existing file
    t3, v3, s3 = deterministic_split(
        files + ["g%03d.png" % i for i in range(50)], 0.2, 0.1)
    assert set(v1) <= set(v3) and set(t1) <= set(t3)
    assert 0.1 < len(v3) / 250 < 0.3        # ratios roughly hold


def test_npy_codec(tmp_path):
    arr = numpy.random.RandomState(1).rand(8, 8, 3).astype("float32")
    p = tmp_path / "x.npy"
    numpy.save(p, arr)
    from veles_tpu.loader import decode_image
    out = decode_image(str(p))
    numpy.testing.assert_allclose(out, arr)
    # uint8-scaled arrays normalize to [0, 1]
    numpy.save(p, (arr * 255).astype(numpy.uint8))
    out = decode_image(str(p))
    assert out.max() <= 1.0


def test_device_augmentation_trains(class_tree):
    """device_augmentation=True: dataset holds ONE copy per image; the
    fused step random-crops+mirrors on device; eval center-crops. The
    color-coded classes must still be learned."""
    loader = ClassImageLoader(
        None, root_dir=class_tree, valid_ratio=0.25, minibatch_size=9,
        mirror=True, crop=(8, 8), device_augmentation=True,
        name="flowers-dev")
    wf = nn.StandardWorkflow(
        name="img-aug",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 16},
                {"type": "softmax", "output_sample_shape": 3}],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=8, fail_iterations=99))
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    # multiplicity 1: stored dataset is the raw image count at full size
    assert loader.original_data.shape == (60, 12, 12, 3)
    # downstream units see the POST-crop shape
    assert loader.minibatch_data.shape[1:] == (8, 8, 3)
    wf.run()
    assert wf.decision.best_metric is not None
    assert wf.decision.best_metric < 0.35, wf.decision.epoch_metrics


def test_host_augmentation_multiplicity(class_tree):
    """The host path materializes mirror x crop_number variants (the
    reference behavior) — kept for rotations and for comparison."""
    loader = ClassImageLoader(
        None, root_dir=class_tree, valid_ratio=0.25, minibatch_size=10,
        mirror=True, crop=(8, 8), crop_number=2, name="flowers-host")
    loader.load_data()
    # train gets 2 mirrors x 2 crops = 4 variants; eval 1 center crop
    n_train_files = loader.class_lengths[TRAIN] // 4
    assert loader.class_lengths[TRAIN] == n_train_files * 4
    assert loader.original_data.shape[1:] == (8, 8, 3)


def test_device_augmentation_rejects_rotations(class_tree):
    loader = ClassImageLoader(
        None, root_dir=class_tree, rotations=(0, 90),
        device_augmentation=True, minibatch_size=10, name="rot")
    with pytest.raises(vt.VelesError, match="rotations"):
        loader.load_data()


def test_file_list_image_loader(tmp_path):
    """Index-file manifests (reference FileListImageLoader,
    veles/loader/file_image.py:130): 'path label' lines, relative paths
    against the list file, explicit labels winning over auto_label."""
    from veles_tpu.loader import FileListImageLoader
    rng = numpy.random.RandomState(0)
    img_dir = tmp_path / "imgs"
    img_dir.mkdir()
    for i in range(8):
        _write_png(str(img_dir / ("im%d.png" % i)), rng.rand(6, 6, 3))
    train = tmp_path / "train.txt"
    train.write_text(
        "# manifest\n"
        + "".join("imgs/im%d.png %s\n" % (i, "even" if i % 2 == 0
                                          else "odd")
                  for i in range(6)))
    valid = tmp_path / "valid.txt"
    valid.write_text("imgs/im6.png even\nimgs/im7.png odd\n")
    loader = FileListImageLoader(None, train_list=str(train),
                                 validation_list=str(valid),
                                 minibatch_size=2, name="flist")
    loader.load_data()
    assert loader.class_lengths == [0, 2, 6]
    assert sorted(loader.labels_mapping) == ["even", "odd"]
    # explicit labels, not the directory name ('imgs')
    labels = loader.original_labels.mem
    assert set(labels.tolist()) == {0, 1}
    import pytest as _pytest
    from veles_tpu.error import VelesError
    with _pytest.raises(VelesError, match="no such list file"):
        FileListImageLoader(None, train_list=str(tmp_path / "nope.txt"),
                            name="missing")


def test_image_mse_loader_label_targets(tmp_path):
    """Per-label target images (the reference channels scheme): every
    row's target is its class's template image."""
    from veles_tpu.loader import ImageLoaderMSE
    rng = numpy.random.RandomState(1)
    for cls in ("a", "b"):
        d = tmp_path / "train" / cls
        d.mkdir(parents=True)
        for i in range(4):
            _write_png(str(d / ("x%d.png" % i)), rng.rand(5, 5, 3))
        t = tmp_path / "targets" / cls
        t.mkdir(parents=True)
        _write_png(str(t / "ideal.png"),
                   numpy.full((5, 5, 3), 0.25 if cls == "a" else 0.75))
    loader = ImageLoaderMSE(
        None, train_paths=[str(tmp_path / "train")],
        target_paths=[str(tmp_path / "targets")],
        validation_ratio=0.25, minibatch_size=2, name="mse-l")
    loader.load_data()
    # one TABLE row per label, not one per dataset row (8 rows would
    # mean per-row template copies — the HBM-doubling bug)
    assert loader.targets_by_label is True
    assert loader.original_targets.shape == (2, 5, 5, 3)
    # each row's effective target (table gathered through its label)
    # matches its class template — survives the validation-ratio
    # row permutation
    for row, label in enumerate(loader.original_labels.mem):
        want = 0.25 if loader.label_names[int(label)] == "a" else 0.75
        got = float(
            loader.original_targets.mem[int(label)].mean())
        assert abs(got - want) < 0.02, (row, label, got)
    # the host minibatch fill composes the same gather
    loader.create_minibatch_data()
    loader.minibatch_indices.reset(numpy.arange(2))
    loader.minibatch_size = 2
    loader.fill_minibatch()
    for i in range(2):
        lab = int(loader.original_labels.mem[i])
        want = 0.25 if loader.label_names[lab] == "a" else 0.75
        assert abs(float(loader.minibatch_targets.mem[i].mean())
                   - want) < 0.02


def test_image_mse_loader_basename_targets(tmp_path):
    """1:1 reconstruction pairs matched by basename; augmentation
    multiplicity is refused loudly."""
    from veles_tpu.loader import ImageLoaderMSE
    from veles_tpu.error import VelesError
    rng = numpy.random.RandomState(2)
    (tmp_path / "in" / "c").mkdir(parents=True)
    (tmp_path / "tgt").mkdir()
    for i in range(4):
        x = rng.rand(4, 4, 3)
        _write_png(str(tmp_path / "in" / "c" / ("p%d.png" % i)), x)
        _write_png(str(tmp_path / "tgt" / ("p%d.png" % i)), 1.0 - x)
    loader = ImageLoaderMSE(
        None, train_paths=[str(tmp_path / "in")],
        target_paths=[str(tmp_path / "tgt")],
        target_by_label=False, minibatch_size=2, name="mse-b")
    loader.load_data()
    # basename pairing: target ≈ 1 - input, row-aligned
    x = loader.original_data.mem.astype(numpy.float32)
    t = loader.original_targets.mem.astype(numpy.float32)
    assert float(numpy.abs((1.0 - x) - t).max()) < 0.02
    # ANY spatial transform (even one random crop) misaligns pairs
    for bad_kw in ({"mirror": True}, {"crop": (3, 3)}):
        with pytest.raises(VelesError, match="untransformed"):
            ImageLoaderMSE(None, train_paths=[str(tmp_path / "in")],
                           target_paths=[str(tmp_path / "tgt")],
                           target_by_label=False, name="bad", **bad_kw)
    # duplicate basenames across target dirs are ambiguous: refuse
    (tmp_path / "tgt2").mkdir()
    _write_png(str(tmp_path / "tgt2" / "p0.png"), rng.rand(4, 4, 3))
    dup = ImageLoaderMSE(
        None, train_paths=[str(tmp_path / "in")],
        target_paths=[str(tmp_path / "tgt"), str(tmp_path / "tgt2")],
        target_by_label=False, minibatch_size=2, name="dup")
    with pytest.raises(VelesError, match="duplicate target basename"):
        dup.load_data()
    missing = ImageLoaderMSE(
        None, train_paths=[str(tmp_path / "in")],
        target_paths=[str(tmp_path / "tgt" / "p0.png")],
        target_by_label=False, minibatch_size=2, name="mse-m")
    with pytest.raises(VelesError, match="no basename-matched"):
        missing.load_data()


def test_image_mse_trains_end_to_end(tmp_path):
    """The MSE image pair feeds a conv AE through StandardWorkflow —
    loss falls toward the (learnable) inversion mapping."""
    from veles_tpu.loader import ImageLoaderMSE
    rng = numpy.random.RandomState(3)
    (tmp_path / "in" / "c").mkdir(parents=True)
    (tmp_path / "tgt").mkdir()
    for i in range(16):
        x = rng.rand(8, 8, 3)
        _write_png(str(tmp_path / "in" / "c" / ("q%d.png" % i)), x)
        _write_png(str(tmp_path / "tgt" / ("q%d.png" % i)), 1.0 - x)
    loader = ImageLoaderMSE(
        None, train_paths=[str(tmp_path / "in")],
        target_paths=[str(tmp_path / "tgt")],
        target_by_label=False, validation_ratio=0.25,
        minibatch_size=4, name="mse-e2e")
    wf = nn.StandardWorkflow(
        name="inv", layers=[
            {"type": "conv", "n_kernels": 3, "kx": 1, "ky": 1,
             "learning_rate": 0.5},
        ], loader_unit=loader, loss_function="mse",
        decision_config=dict(max_epochs=30, fail_iterations=30))
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    res = wf.gather_results()
    # a 1x1 conv can represent x -> 1-x exactly; well under the
    # do-nothing rmse (~0.41 for uniform pixels)
    assert res["best_rmse"] < 0.15, res


def test_image_mse_label_targets_train_through_fused_step(tmp_path):
    """Label-indexed target TABLE through the fused device step: the
    composed gather (row → label → template) must train — class-coded
    inputs regress onto their class template (affine-learnable)."""
    from veles_tpu.loader import ImageLoaderMSE
    rng = numpy.random.RandomState(4)
    for cls, level, tgt in (("lo", 0.2, 0.25), ("hi", 0.8, 0.75)):
        d = tmp_path / "train" / cls
        d.mkdir(parents=True)
        for i in range(8):
            img = numpy.clip(level + 0.02 * rng.randn(6, 6, 3), 0, 1)
            _write_png(str(d / ("s%d.png" % i)), img)
        t = tmp_path / "targets" / cls
        t.mkdir(parents=True)
        _write_png(str(t / "ideal.png"), numpy.full((6, 6, 3), tgt))
    loader = ImageLoaderMSE(
        None, train_paths=[str(tmp_path / "train")],
        target_paths=[str(tmp_path / "targets")],
        validation_ratio=0.25, minibatch_size=4, name="mse-tbl")
    wf = nn.StandardWorkflow(
        name="tbl", layers=[
            {"type": "conv", "n_kernels": 3, "kx": 1, "ky": 1,
             "learning_rate": 0.5},
        ], loader_unit=loader, loss_function="mse",
        decision_config=dict(max_epochs=30, fail_iterations=30))
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    assert loader.targets_by_label is True
    assert loader.original_targets.shape[0] == 2    # table, not rows
    wf.run()
    res = wf.gather_results()
    # affine map level→target is exactly representable; do-nothing rmse
    # is ~0.06 (|0.2-0.25|, |0.8-0.75|) + noise — gate well below the
    # all-zeros rmse (~0.56) and below predict-global-mean (~0.25)
    assert res["best_rmse"] < 0.06, res
