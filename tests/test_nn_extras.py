"""Tensor-plumbing NN extras: cutter, channel split/merge, zero filler,
image saver, nn plotting units (Znicz modules, SURVEY.md §2.8)."""
import os

import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn
from veles_tpu.config import root
from veles_tpu.error import VelesError
from veles_tpu.memory import Array


def dev():
    return vt.XLADevice(mesh_axes={"data": 1})


def run_oracle_pair(u, x):
    u.input = Array(x)
    u.initialize(device=dev())
    u.xla_run()
    y_xla = numpy.asarray(u.output.map_read())
    y_np = u.numpy_apply(u.params_np(), x)
    numpy.testing.assert_allclose(y_xla, y_np, rtol=1e-5, atol=1e-6)
    return y_np


def test_cutter():
    wf = vt.Workflow(name="t")
    x = numpy.arange(2 * 6 * 8 * 3, dtype=numpy.float32).reshape(2, 6, 8, 3)
    u = nn.Cutter(wf, padding=(2, 1, 1, 2))
    y = run_oracle_pair(u, x)
    assert y.shape == (2, 3, 5, 3)
    numpy.testing.assert_array_equal(y, x[:, 1:4, 2:7])
    with pytest.raises(ValueError):
        nn.Cutter(wf, padding=(4, 0, 4, 0)).output_shape_for((1, 6, 8, 3))


def test_channel_splitter_groups():
    wf = vt.Workflow(name="t")
    x = numpy.random.RandomState(0).rand(3, 4, 4, 6).astype(numpy.float32)
    u = nn.ChannelSplitter(wf, groups=3)
    u.input = Array(x)
    u.initialize(device=dev())
    u.xla_run()
    assert len(u.outputs) == 3
    for i, arr in enumerate(u.outputs):
        numpy.testing.assert_allclose(numpy.asarray(arr.map_read()),
                                      x[..., 2 * i:2 * i + 2])
    u.numpy_run()
    for i, arr in enumerate(u.outputs):
        numpy.testing.assert_allclose(arr.map_read(),
                                      x[..., 2 * i:2 * i + 2])


def test_channel_split_merge_roundtrip():
    wf = vt.Workflow(name="t")
    x = numpy.random.RandomState(1).rand(2, 3, 3, 4).astype(numpy.float32)
    split = nn.ChannelSplitter(wf, sizes=(1, 3))
    split.input = Array(x)
    split.initialize(device=dev())
    split.xla_run()
    merge = nn.ChannelMerger(wf, inputs=split.outputs)
    merge.initialize(device=dev())
    merge.xla_run()
    numpy.testing.assert_allclose(numpy.asarray(merge.output.map_read()),
                                  x, rtol=1e-6)
    merge.numpy_run()
    numpy.testing.assert_allclose(merge.output.map_read(), x, rtol=1e-6)


def test_channel_splitter_validates():
    wf = vt.Workflow(name="t")
    with pytest.raises(VelesError):
        nn.ChannelSplitter(wf)                     # neither groups nor sizes
    u = nn.ChannelSplitter(wf, groups=4)
    with pytest.raises(VelesError):
        u.output_shape_for((1, 2, 2, 6))           # 6 % 4 != 0


def test_zero_filler_masks_weights():
    wf = vt.Workflow(name="t")
    fc = nn.All2All(wf, output_sample_shape=4, name="fc")
    x = numpy.random.RandomState(2).rand(5, 6).astype(numpy.float32)
    fc.input = Array(x)
    fc.initialize(device=dev())
    zf = nn.ZeroFiller(wf, target=fc, grouping=2)
    assert zf.initialize() is None
    w = numpy.asarray(fc.weights.map_read())
    assert (w[:3, 2:] == 0).all() and (w[3:, :2] == 0).all()
    assert (w[:3, :2] != 0).any() and (w[3:, 2:] != 0).any()
    # wrong-shape mask rejected
    bad = nn.ZeroFiller(wf, target=fc, mask=numpy.ones((2, 2)),
                        name="bad")
    with pytest.raises(VelesError):
        bad.initialize()


def test_image_saver(tmp_path):
    wf = vt.Workflow(name="t")
    saver = nn.ImageSaver(wf, out_dir=str(tmp_path / "dump"), limit=10)
    data = numpy.random.RandomState(3).rand(6, 16).astype(numpy.float32)
    labels = numpy.array([0, 1, 0, 1, 0, 1])
    preds = numpy.zeros((6, 2), dtype=numpy.float32)
    preds[:, 0] = 1.0           # predicts class 0 for everything
    saver.input, saver.labels, saver.output = (Array(data), Array(labels),
                                               Array(preds))
    saver.run()
    # the three label-1 samples were wrong → saved under truth dir "1"
    files = os.listdir(tmp_path / "dump" / "1")
    assert len(files) == 3 and all(f.endswith(".png") for f in files)
    assert not (tmp_path / "dump" / "0").exists()
    assert saver.get_metric_values() == {"images_saved": 3}
    saver.reset_epoch()
    assert saver.saved_count == 0
    assert not (tmp_path / "dump").exists()


@pytest.fixture
def plotting_enabled():
    old = root.common.disable.plotting
    root.common.disable.plotting = False
    yield
    root.common.disable.plotting = old


def test_weights2d_plotter(plotting_enabled, tmp_path):
    wf = vt.Workflow(name="t")
    fc = nn.All2All(wf, output_sample_shape=6, name="fc")
    fc.input = Array(numpy.zeros((2, 9), dtype=numpy.float32))
    fc.initialize(device=dev())
    p = nn.Weights2D(wf, unit=fc, redraw_interval=0.0)
    p.run()
    snap = p.last_snapshot
    assert snap["images"].shape == (6, 3, 3)    # 9 weights → 3x3 tiles
    from veles_tpu import graphics
    graphics.render_snapshot(snap, str(tmp_path / "w.png"))


def test_kohonen_hits_plotter(plotting_enabled):
    wf = vt.Workflow(name="t")
    tr = nn.KohonenTrainer(wf, shape=(2, 2))
    tr.input = Array(numpy.random.RandomState(0)
                     .rand(20, 3).astype(numpy.float32))
    tr.initialize(device=dev())
    tr.xla_run()
    p = nn.KohonenHits(wf, trainer=tr, redraw_interval=0.0)
    p.run()
    m = p.last_snapshot["matrix"]
    assert m.shape == (2, 2) and m.sum() == 20
    tr.xla_run()
    p.run()
    assert p.last_snapshot["matrix"].sum() == 40    # accumulates


def test_zero_filler_holds_inside_fused_dispatch():
    """ADVICE r1: with steps_per_dispatch>1 the mask must hold after every
    optimizer update inside the scan, not just at dispatch boundaries —
    ZeroFiller registers a param mask enforced by the compiled step."""
    from tests.test_train_e2e import make_workflow
    wf = make_workflow(minibatch_size=50)
    fc = wf.forwards[0]
    mask = numpy.ones((10, 16), dtype=numpy.float32)
    mask[:, 8:] = 0.0                      # kill half the first layer
    zf = nn.ZeroFiller(wf, target=fc, mask=mask)
    wf.initialize(device=dev())
    zf.run()                               # register with the fused step
    assert fc.name in wf.train_step.param_masks
    assert wf.train_step.loader.plan_steps > 1   # multi-step dispatch
    # run a few dispatches of real training
    for _ in range(3):
        wf.loader.run()
        wf.train_step.run()
    w = numpy.asarray(wf.train_step.params[fc.name]["weights"])
    assert (w[:, 8:] == 0).all(), "mask drifted inside the fused dispatch"
    assert (w[:, :8] != 0).any()
