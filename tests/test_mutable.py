"""Mirrors reference veles/tests/test_mutable.py scope."""
import pickle

from veles_tpu.mutable import Bool, LinkableAttribute, link


def test_bool_identity_mutation():
    a = Bool(False)
    holders = [a, a]
    a <<= True
    assert all(bool(h) for h in holders)
    a <<= False
    assert not any(bool(h) for h in holders)


def test_bool_algebra_lazy():
    a, b = Bool(False), Bool(True)
    expr = ~a & b
    assert bool(expr)
    a <<= True
    assert not bool(expr)        # re-evaluates operands
    o = a | Bool(False)
    assert bool(o)
    x = a ^ b
    assert not bool(x)
    b <<= False
    assert bool(x)


def test_bool_derived_not_assignable():
    e = Bool(True) & Bool(True)
    try:
        e <<= False
        assert False
    except ValueError:
        pass


def test_bool_on_true_callback():
    fired = []
    a = Bool(False)
    a.on_true = lambda: fired.append(1)
    a <<= True
    assert fired == [1]


def test_bool_pickles():
    a, b = Bool(True), Bool(False)
    expr = a & ~b
    expr2 = pickle.loads(pickle.dumps(expr))
    assert bool(expr2)


class Thing:
    def __init__(self):
        self.val = 0


def test_linkable_attribute():
    src, dst = Thing(), Thing()
    src.val = 42
    link(dst, "val", src)
    assert dst.val == 42
    src.val = 7
    assert dst.val == 7
    dst.val = 9          # writes through
    assert src.val == 9


def test_linkable_tuple_mapping():
    src, dst = Thing(), Thing()
    src.other = "X"
    LinkableAttribute.link(dst, "val", src, "other")
    assert dst.val == "X"
