"""Parallelism: ring attention vs exact oracle, sharding rules, tensor/
fsdp-parallel training, watchdog/fault hooks."""
import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn, parallel
from veles_tpu.memory import Array
from veles_tpu.parallel.ring_attention import (ring_attention,
                                               attention_reference)


def seq_mesh(n=8):
    return vt.make_mesh(__import__("jax").devices(), {"sequence": n})


def test_ring_attention_matches_reference():
    import jax.numpy as jnp
    rng = numpy.random.RandomState(0)
    b, t, h, d = 2, 32, 4, 8
    q = jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
    k = jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
    v = jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
    mesh = seq_mesh(8)
    out = ring_attention(q, k, v, mesh)
    ref = attention_reference(q, k, v)
    numpy.testing.assert_allclose(numpy.asarray(out), numpy.asarray(ref),
                                  rtol=2e-4, atol=2e-5)


def test_ring_attention_causal():
    import jax.numpy as jnp
    rng = numpy.random.RandomState(1)
    b, t, h, d = 1, 16, 2, 4
    q = jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
    k = jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
    v = jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
    mesh = seq_mesh(4)
    out = ring_attention(q, k, v, mesh, causal=True)
    ref = attention_reference(q, k, v, causal=True)
    numpy.testing.assert_allclose(numpy.asarray(out), numpy.asarray(ref),
                                  rtol=2e-4, atol=2e-5)


def test_ring_attention_jittable_and_differentiable():
    import jax
    import jax.numpy as jnp
    mesh = seq_mesh(4)
    rng = numpy.random.RandomState(2)
    q = jnp.asarray(rng.randn(1, 8, 2, 4).astype(numpy.float32))

    @jax.jit
    def loss(q):
        o = ring_attention(q, q, q, mesh, causal=True)
        return (o ** 2).sum()
    g = jax.grad(loss)(q)
    assert g.shape == q.shape
    assert numpy.isfinite(numpy.asarray(g)).all()


def test_mha_oracle():
    wf = vt.Workflow(name="t")
    u = nn.MultiHeadAttention(wf, n_heads=2)
    x = numpy.random.RandomState(3).randn(2, 6, 8).astype(numpy.float32)
    u.input = Array(x, name="x")
    u.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    u.xla_run()
    y_dev = numpy.asarray(u.output.map_read())
    y_np = u.numpy_apply(u.params_np(), x)
    numpy.testing.assert_allclose(y_dev, y_np, rtol=2e-4, atol=2e-5)


def test_mha_causal_oracle():
    wf = vt.Workflow(name="t")
    u = nn.MultiHeadAttention(wf, n_heads=2, causal=True)
    x = numpy.random.RandomState(4).randn(1, 5, 4).astype(numpy.float32)
    u.input = Array(x, name="x")
    u.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    u.xla_run()
    numpy.testing.assert_allclose(
        numpy.asarray(u.output.map_read()),
        u.numpy_apply(u.params_np(), x), rtol=2e-4, atol=2e-5)


def test_sharding_rules():
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    mesh = vt.make_mesh(__import__("jax").devices(),
                        {"fsdp": 2, "tensor": 2, "data": 2})
    params = {"fc": {"weights": jnp.zeros((64, 32)),
                     "bias": jnp.zeros((32,))}}
    sh = parallel.param_shardings(params, mesh)
    assert sh["fc"]["weights"].spec == P("fsdp", "tensor")
    assert sh["fc"]["bias"].spec == P(None)   # biases stay replicated


def test_tensor_parallel_training_converges():
    """2-way data x 4-way tensor mesh: fused step still converges."""
    from test_train_e2e import BlobsLoader
    loader = BlobsLoader(None, minibatch_size=48, name="blobs")
    wf = nn.StandardWorkflow(
        name="tp-train",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 16},
                {"type": "softmax", "output_sample_shape": 4}],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=8, fail_iterations=50))
    dev = vt.XLADevice(mesh_axes={"data": 2, "tensor": 4})
    wf.initialize(device=dev)
    w = wf.train_step.params["all2all_tanh0"]["weights"]
    assert not w.sharding.is_fully_replicated     # actually tensor-sharded
    wf.run()
    assert wf.decision.best_metric < 0.1


def test_step_watchdog_records():
    hist = []
    for _ in range(10):
        with parallel.distributed.step_watchdog("s", history=hist):
            pass
    assert len(hist) == 10


def test_fault_injection_zero_probability_noop():
    parallel.distributed.fault_injection(0.0)   # must not exit


def test_restore_latest_no_snapshots(tmp_path):
    wf = vt.Workflow(name="w")
    assert parallel.distributed.restore_latest(wf, str(tmp_path)) is False


def test_ulysses_attention_matches_reference():
    import jax.numpy as jnp
    from veles_tpu.parallel.ulysses import ulysses_attention
    rng = numpy.random.RandomState(2)
    b, t, h, d = 2, 32, 8, 4
    q, k, v = [jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
               for _ in range(3)]
    mesh = seq_mesh(4)
    for causal in (False, True):
        out = ulysses_attention(q, k, v, mesh, causal=causal)
        ref = attention_reference(q, k, v, causal=causal)
        numpy.testing.assert_allclose(numpy.asarray(out),
                                      numpy.asarray(ref),
                                      rtol=2e-4, atol=2e-5)


def test_ulysses_rejects_indivisible_heads():
    import jax.numpy as jnp
    from veles_tpu.parallel.ulysses import ulysses_attention
    q = jnp.zeros((1, 16, 3, 4))
    with pytest.raises(ValueError):
        ulysses_attention(q, q, q, seq_mesh(4))


def test_mha_routes_by_sequence_parallel_config():
    """With sequence_parallel='ulysses' and divisible heads, the unit
    output still matches the numpy oracle on a dp×sp mesh."""
    import veles_tpu as vt
    from veles_tpu import nn
    from veles_tpu.memory import Array
    prev_dtype = vt.root.common.engine.compute_dtype
    prev_scheme = vt.root.common.engine.sequence_parallel
    vt.root.common.engine.compute_dtype = "float32"
    vt.root.common.engine.sequence_parallel = "ulysses"
    try:
        wf = vt.Workflow(name="t")
        u = nn.MultiHeadAttention(wf, n_heads=4, causal=True)
        x = numpy.random.RandomState(0).randn(2, 16, 8).astype(
            numpy.float32)
        u.input = Array(x)
        u.initialize(device=vt.XLADevice(
            mesh_axes={"data": 2, "sequence": 4}))
        assert u.mesh is not None
        u.xla_run()
        y = numpy.asarray(u.output.map_read())
        y_np = u.numpy_apply(u.params_np(), x)
        numpy.testing.assert_allclose(y, y_np, rtol=1e-3, atol=1e-4)
    finally:
        vt.root.common.engine.compute_dtype = prev_dtype
        vt.root.common.engine.sequence_parallel = prev_scheme


def test_ulysses_flash_inner_matches_reference():
    """After the all-to-all each device holds the full sequence, so the
    pallas flash kernel can take the inner attention (forced into
    interpret mode here); result must match the exact reference."""
    import jax.numpy as jnp
    from veles_tpu.parallel.ulysses import ulysses_attention
    rng = numpy.random.RandomState(5)
    b, t, h, d = 1, 128, 4, 16          # t divisible by flash blocks
    q, k, v = [jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
               for _ in range(3)]
    mesh = seq_mesh(4)
    from veles_tpu.ops import flash_attention as fa
    calls = []
    real_flash = fa.flash_attention
    prev = vt.root.common.engine.flash_attention
    vt.root.common.engine.flash_attention = "force"
    fa.flash_attention = lambda *a, **k2: (calls.append(1),
                                           real_flash(*a, **k2))[1]
    try:
        out = ulysses_attention(q, k, v, mesh, causal=True)
    finally:
        vt.root.common.engine.flash_attention = prev
        fa.flash_attention = real_flash
    assert calls, "flash path never taken — test would compare " \
                  "reference against itself"
    ref = attention_reference(q, k, v, causal=True)
    numpy.testing.assert_allclose(numpy.asarray(out),
                                  numpy.asarray(ref),
                                  rtol=2e-4, atol=2e-5)


def test_ring_attention_windowed_matches_reference():
    """Ring + sliding window: masked AND ring-shortened (the scan runs
    ceil((W-1+Tl)/Tl) rotations, not n) vs the windowed reference.
    Windows chosen to need 1, 2, and all ring hops at Tl = 8."""
    import jax.numpy as jnp
    rng = numpy.random.RandomState(7)
    b, t, h, d = 1, 32, 2, 4
    q = jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
    k = jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
    v = jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
    mesh = seq_mesh(4)
    for win in (4, 8, 13, 31):
        out = ring_attention(q, k, v, mesh, causal=True, window=win)
        ref = attention_reference(q, k, v, causal=True, window=win)
        numpy.testing.assert_allclose(
            numpy.asarray(out), numpy.asarray(ref), rtol=2e-4,
            atol=2e-5, err_msg="window=%d" % win)


def test_ring_attention_windowed_differentiable():
    import jax
    import jax.numpy as jnp
    rng = numpy.random.RandomState(8)
    q = jnp.asarray(rng.randn(1, 16, 2, 4).astype(numpy.float32))
    mesh = seq_mesh(4)

    def loss_ring(q):
        return (ring_attention(q, q, q, mesh, causal=True,
                               window=6) ** 2).sum()

    def loss_ref(q):
        from veles_tpu.parallel.ring_attention import attention_reference
        return (attention_reference(q, q, q, causal=True,
                                    window=6) ** 2).sum()

    g1 = jax.grad(loss_ring)(q)
    g2 = jax.grad(loss_ref)(q)
    numpy.testing.assert_allclose(numpy.asarray(g1), numpy.asarray(g2),
                                  rtol=5e-4, atol=5e-4)


def test_ulysses_windowed_matches_reference():
    import jax.numpy as jnp
    from veles_tpu.parallel.ulysses import ulysses_attention
    rng = numpy.random.RandomState(9)
    b, t, h, d = 1, 32, 4, 8
    q = jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
    k = jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
    v = jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
    mesh = seq_mesh(4)
    out = ulysses_attention(q, k, v, mesh, causal=True, window=9)
    ref = attention_reference(q, k, v, causal=True, window=9)
    numpy.testing.assert_allclose(numpy.asarray(out),
                                  numpy.asarray(ref), rtol=2e-4,
                                  atol=2e-5)


def test_ring_window_requires_causal():
    import jax.numpy as jnp
    import pytest as _pytest
    q = jnp.zeros((1, 16, 2, 4), jnp.float32)
    with _pytest.raises(ValueError, match="causal"):
        ring_attention(q, q, q, seq_mesh(4), causal=False, window=4)


def test_ring_attention_flash_engine_matches_reference():
    """Flash-in-ring (Pallas inner engine, peeled diagonal + lse
    merge): forward must match the exact reference for causal AND full
    attention. CPU runs the kernel in interpret mode (use_flash=True
    overrides the TPU gate)."""
    import jax.numpy as jnp
    rng = numpy.random.RandomState(11)
    b, t, h, d = 1, 256, 2, 8
    q = jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
    k = jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
    v = jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
    mesh = seq_mesh(2)
    for causal in (False, True):
        out = ring_attention(q, k, v, mesh, causal=causal,
                             use_flash=True)
        ref = attention_reference(q, k, v, causal=causal)
        numpy.testing.assert_allclose(
            numpy.asarray(out), numpy.asarray(ref), rtol=2e-4,
            atol=2e-5)


def test_ring_attention_flash_engine_gradients():
    """The blockwise ring backward (global-lse recompute) under the
    flash forward: grads of a scalar loss wrt q, k, v match the
    autodiff of the exact reference."""
    import jax
    import jax.numpy as jnp
    rng = numpy.random.RandomState(12)
    b, t, h, d = 1, 256, 2, 8
    q = jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
    k = jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
    v = jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
    mesh = seq_mesh(2)

    def loss_ring(q, k, v):
        o = ring_attention(q, k, v, mesh, causal=True, use_flash=True)
        return (o.astype(jnp.float32) ** 2).sum()

    def loss_ref(q, k, v):
        o = attention_reference(q, k, v, causal=True)
        return (o.astype(jnp.float32) ** 2).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_ref):
        numpy.testing.assert_allclose(
            numpy.asarray(gr), numpy.asarray(gf), rtol=2e-3, atol=2e-4)


def test_ring_attention_flash_refuses_window():
    import jax.numpy as jnp
    x = jnp.zeros((1, 256, 2, 8), jnp.float32)
    with pytest.raises(ValueError, match="window"):
        ring_attention(x, x, x, seq_mesh(2), causal=True, window=32,
                       use_flash=True)


def test_ring_attention_einsum_bwd_window_matches_reference():
    """Window rings stay on the einsum engine; the custom blockwise
    backward must reproduce reference gradients through the
    window-shortened scan (incl. the accumulator fast-forward home)."""
    import jax
    import jax.numpy as jnp
    rng = numpy.random.RandomState(13)
    b, t, h, d = 1, 32, 2, 4
    q = jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
    k = jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
    v = jnp.asarray(rng.randn(b, t, h, d).astype(numpy.float32))
    mesh = seq_mesh(8)          # tl=4; window=6 -> steps=3 of 8

    def loss_ring(q, k, v):
        o = ring_attention(q, k, v, mesh, causal=True, window=6)
        return (o.astype(jnp.float32) ** 2).sum()

    def loss_ref(q, k, v):
        o = attention_reference(q, k, v, causal=True, window=6)
        return (o.astype(jnp.float32) ** 2).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_ref):
        numpy.testing.assert_allclose(
            numpy.asarray(gr), numpy.asarray(gf), rtol=2e-3, atol=2e-4)
