"""Data-parallel equivalence: the actual correctness claim of psum-DP.

The reference's master–slave plane (veles/server.py, veles/client.py)
averaged slave updates into one canonical model; the TPU build's claim is
that sharding the minibatch over the mesh 'data' axis and letting XLA
insert the gradient psum computes the SAME training run. This test proves
it: same seed, same data, same topology — a 1-device run and an 8-device
{"data": 8} run must produce matching per-epoch loss/error trajectories
(tolerance only for float reduction order).
"""
import numpy

import veles_tpu as vt
from veles_tpu import nn, prng
from veles_tpu.loader import FullBatchLoader, TRAIN, VALID


class BlobsLoader(FullBatchLoader):
    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(7)
        n_per, d, k = 160, 10, 3
        centers = rng.randn(k, d) * 3
        data, labels = [], []
        for c in range(k):
            data.append(centers[c] + rng.randn(n_per, d))
            labels.append(numpy.full(n_per, c))
        data = numpy.concatenate(data).astype(numpy.float32)
        labels = numpy.concatenate(labels).astype(numpy.int32)
        perm = rng.permutation(len(data))
        self.create_originals(data[perm], labels[perm])
        self.class_lengths = [0, 120, 360]


def _run(n_devices, epochs=6):
    prng.seed_all(1234)
    loader = BlobsLoader(None, minibatch_size=40, name="blobs-eq")
    wf = nn.StandardWorkflow(
        name="dp-eq-%d" % n_devices,
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 16},
            {"type": "softmax", "output_sample_shape": 3},
        ],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=epochs, fail_iterations=100),
    )
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": n_devices}))
    wf.run()
    d = wf.decision
    import jax
    return {
        "train_err": numpy.asarray(d.epoch_metrics[TRAIN]),
        "valid_err": numpy.asarray(d.epoch_metrics[VALID]),
        "weights": numpy.asarray(
            jax.device_get(wf.train_step.params[wf.forwards[0].name]
                           ["weights"])),
    }


def test_dp_8dev_matches_1dev_trajectory():
    r1 = _run(1)
    r8 = _run(8)
    assert r1["train_err"].shape == r8["train_err"].shape == (6,)
    # reduction order differs (per-shard partial sums + psum); everything
    # else — shuffle order, init, schedule — is identical, so per-epoch
    # error fractions may differ by at most a couple of tie-break flips
    # (360 train / 120 valid samples → 1 flip = 0.0028 / 0.0083)
    numpy.testing.assert_allclose(r8["train_err"], r1["train_err"],
                                  atol=0.01)
    numpy.testing.assert_allclose(r8["valid_err"], r1["valid_err"],
                                  atol=0.02)
    # the strong claim: the trained parameters themselves match
    numpy.testing.assert_allclose(r8["weights"], r1["weights"],
                                  rtol=2e-3, atol=2e-4)


def test_sharded_dataset_matches_replicated():
    """shard_dataset=True: the device-resident dataset shards over the
    'data' axis (HBM/chip scales 1/n); GSPMD inserts the gather
    collectives. Must train identically to the replicated layout."""
    import jax

    def run(shard):
        prng.seed_all(1234)
        loader = BlobsLoader(None, minibatch_size=40,
                             shard_dataset=shard, name="blobs-sh")
        wf = nn.StandardWorkflow(
            name="ds-%s" % shard,
            layers=[
                {"type": "all2all_tanh", "output_sample_shape": 16},
                {"type": "softmax", "output_sample_shape": 3},
            ],
            loader_unit=loader, loss_function="softmax",
            decision_config=dict(max_epochs=4, fail_iterations=100),
        )
        wf.initialize(device=vt.XLADevice(mesh_axes={"data": 8}))
        ds = wf.train_step._inputs()[0]
        if shard:
            assert not ds.sharding.is_fully_replicated
            assert ds.sharding.spec[0] == "data"
        else:
            assert ds.sharding.is_fully_replicated
        wf.run()
        return (numpy.asarray(wf.decision.epoch_metrics[TRAIN]),
                numpy.asarray(jax.device_get(
                    wf.train_step.params[wf.forwards[0].name]
                    ["weights"])))

    e_repl, w_repl = run(False)
    e_sh, w_sh = run(True)
    numpy.testing.assert_allclose(e_sh, e_repl, atol=0.01)
    numpy.testing.assert_allclose(w_sh, w_repl, rtol=2e-3, atol=2e-4)
