"""Data-parallel equivalence: the actual correctness claim of psum-DP.

The reference's master–slave plane (veles/server.py, veles/client.py)
averaged slave updates into one canonical model; the TPU build's claim is
that sharding the minibatch over the mesh 'data' axis and letting XLA
insert the gradient psum computes the SAME training run. This test proves
it: same seed, same data, same topology — a 1-device run and an 8-device
{"data": 8} run must produce matching per-epoch loss/error trajectories
(tolerance only for float reduction order).
"""
import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn, prng
from veles_tpu.loader import FullBatchLoader, TRAIN, VALID


class BlobsLoader(FullBatchLoader):
    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(7)
        n_per, d, k = 160, 10, 3
        centers = rng.randn(k, d) * 3
        data, labels = [], []
        for c in range(k):
            data.append(centers[c] + rng.randn(n_per, d))
            labels.append(numpy.full(n_per, c))
        data = numpy.concatenate(data).astype(numpy.float32)
        labels = numpy.concatenate(labels).astype(numpy.int32)
        perm = rng.permutation(len(data))
        self.create_originals(data[perm], labels[perm])
        self.class_lengths = [0, 120, 360]


def _run(n_devices=None, epochs=6, mesh_axes=None, n_classes=3,
         check_sharding=None, layers=None, watch_param="weights"):
    """One seeded blobs training run under the given mesh; the shared
    body of every equivalence test in this module. check_sharding, if
    given, receives the first layer's watched-param sharding BEFORE the
    run — tests must assert the axis actually engaged, or they pass
    vacuously when a mesh regression silently falls back to
    replication. ``layers`` overrides the 2-layer FC stack (EP uses a
    MoE layer); ``watch_param`` names the first layer's param to
    check/extract."""
    if mesh_axes is None:
        mesh_axes = {"data": n_devices}
    prng.seed_all(1234)
    loader = BlobsLoader(None, minibatch_size=40, name="blobs-eq")
    wf = nn.StandardWorkflow(
        name="eq-%s" % "x".join("%s%d" % kv for kv in
                                sorted(mesh_axes.items())),
        layers=layers or [
            {"type": "all2all_tanh", "output_sample_shape": 16},
            {"type": "softmax", "output_sample_shape": n_classes},
        ],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=epochs, fail_iterations=100),
    )
    wf.initialize(device=vt.XLADevice(mesh_axes=mesh_axes))
    if check_sharding is not None:
        check_sharding(
            wf.train_step.params[wf.forwards[0].name][watch_param]
            .sharding)
    wf.run()
    d = wf.decision
    import jax
    return {
        "train_err": numpy.asarray(d.epoch_metrics[TRAIN]),
        "valid_err": numpy.asarray(d.epoch_metrics[VALID]),
        "weights": numpy.asarray(
            jax.device_get(wf.train_step.params[wf.forwards[0].name]
                           [watch_param])),
    }


def test_dp_8dev_matches_1dev_trajectory():
    r1 = _run(1)
    r8 = _run(8)
    assert r1["train_err"].shape == r8["train_err"].shape == (6,)
    # reduction order differs (per-shard partial sums + psum); everything
    # else — shuffle order, init, schedule — is identical, so per-epoch
    # error fractions may differ by at most a couple of tie-break flips
    # (360 train / 120 valid samples → 1 flip = 0.0028 / 0.0083)
    numpy.testing.assert_allclose(r8["train_err"], r1["train_err"],
                                  atol=0.01)
    numpy.testing.assert_allclose(r8["valid_err"], r1["valid_err"],
                                  atol=0.02)
    # the strong claim: the trained parameters themselves match
    numpy.testing.assert_allclose(r8["weights"], r1["weights"],
                                  rtol=2e-3, atol=2e-4)


def test_sharded_dataset_matches_replicated():
    """shard_dataset=True: the device-resident dataset shards over the
    'data' axis (HBM/chip scales 1/n); GSPMD inserts the gather
    collectives. Must train identically to the replicated layout."""
    import jax

    def run(shard):
        prng.seed_all(1234)
        loader = BlobsLoader(None, minibatch_size=40,
                             shard_dataset=shard, name="blobs-sh")
        wf = nn.StandardWorkflow(
            name="ds-%s" % shard,
            layers=[
                {"type": "all2all_tanh", "output_sample_shape": 16},
                {"type": "softmax", "output_sample_shape": 3},
            ],
            loader_unit=loader, loss_function="softmax",
            decision_config=dict(max_epochs=4, fail_iterations=100),
        )
        wf.initialize(device=vt.XLADevice(mesh_axes={"data": 8}))
        ds = wf.train_step._inputs()[0]
        if shard:
            assert not ds.sharding.is_fully_replicated
            assert ds.sharding.spec[0] == "data"
        else:
            assert ds.sharding.is_fully_replicated
        wf.run()
        return (numpy.asarray(wf.decision.epoch_metrics[TRAIN]),
                numpy.asarray(jax.device_get(
                    wf.train_step.params[wf.forwards[0].name]
                    ["weights"])))

    e_repl, w_repl = run(False)
    e_sh, w_sh = run(True)
    numpy.testing.assert_allclose(e_sh, e_repl, atol=0.01)
    numpy.testing.assert_allclose(w_sh, w_repl, rtol=2e-3, atol=2e-4)


class SeqLoader(FullBatchLoader):
    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(11)
        n, t, d = 256, 16, 8
        x = rng.randn(n, t, d).astype(numpy.float32)
        # order-sensitive rule so attention is load-bearing: does the
        # first half of the sequence carry more energy than the second
        y = (numpy.square(x[:, :t // 2]).sum(axis=(1, 2)) >
             numpy.square(x[:, t // 2:]).sum(axis=(1, 2)))
        self.create_originals(x, y.astype(numpy.int32))
        self.class_lengths = [0, 64, 192]


_SP_BASELINE = {}


def _run_sp(mesh_axes, epochs=4):
    """Attention model under the given mesh; sequence axis engages the
    ring-attention path inside MultiHeadAttention. The 1-device
    baseline is memoized — both equivalence tests compare against the
    same run."""
    key = (tuple(sorted(mesh_axes.items())), epochs)
    if mesh_axes == {"data": 1} and key in _SP_BASELINE:
        return _SP_BASELINE[key]
    prng.seed_all(777)
    loader = SeqLoader(None, minibatch_size=32, name="seq-eq")
    wf = nn.StandardWorkflow(
        name="sp-eq",
        layers=[
            {"type": "multi_head_attention", "n_heads": 2},
            {"type": "mean_pool"},
            {"type": "softmax", "output_sample_shape": 2},
        ],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=epochs, fail_iterations=100),
    )
    wf.initialize(device=vt.XLADevice(mesh_axes=mesh_axes))
    wf.run()
    import jax
    res = {
        "train_err": numpy.asarray(wf.decision.epoch_metrics[TRAIN]),
        "valid_err": numpy.asarray(wf.decision.epoch_metrics[VALID]),
        "wq": numpy.asarray(jax.device_get(
            wf.train_step.params[wf.forwards[0].name]["wq"])),
        "mesh_engaged": wf.forwards[0].mesh is not None,
    }
    if mesh_axes == {"data": 1}:
        _SP_BASELINE[key] = res
    return res


# jax 0.4.37 limitation: ring attention's custom_vjp + scan inside
# shard_map, nested in the jitted train step, lowers to a PartitionId
# instruction XLA's SPMD partitioner rejects ("PartitionId instruction
# is not supported for SPMD partitioning"). check_rep True/False makes
# no difference and minimal shard_map+axis_index repros work, so it is
# the composition itself — unfixable without a jax upgrade. Failed at
# seed too (then as a shard_map ImportError); xfail keeps tier-1
# output clean of a known-unfixable failure while strict=False lets a
# future jax bump surface the fix as an XPASS.
_SP_XFAIL = pytest.mark.xfail(
    strict=False,
    reason="jax 0.4.37: custom_vjp+scan in shard_map nested in jit "
           "lowers to PartitionId, unsupported by SPMD partitioning")


@_SP_XFAIL
def test_sp_4dev_matches_1dev_trajectory():
    """Sequence-parallel equivalence — the SP analogue of the DP proof:
    ring attention over a {'sequence': 4} mesh is EXACT (K/V rotate via
    ppermute, softmax accumulated online), so the training run must
    match the single-device run up to reduction order."""
    r1 = _run_sp({"data": 1})
    r4 = _run_sp({"sequence": 4})
    assert not r1["mesh_engaged"] and r4["mesh_engaged"]
    numpy.testing.assert_allclose(r4["train_err"], r1["train_err"],
                                  atol=0.02)
    numpy.testing.assert_allclose(r4["valid_err"], r1["valid_err"],
                                  atol=0.03)
    numpy.testing.assert_allclose(r4["wq"], r1["wq"], rtol=5e-3,
                                  atol=5e-4)


@_SP_XFAIL
def test_sp_composes_with_dp():
    """dp x sp: batch over 'data' AND sequence over 'sequence' in one
    mesh — the composed run still matches the single-device
    trajectory."""
    r1 = _run_sp({"data": 1})
    r24 = _run_sp({"data": 2, "sequence": 4})
    assert r24["mesh_engaged"]
    numpy.testing.assert_allclose(r24["train_err"], r1["train_err"],
                                  atol=0.02)
    numpy.testing.assert_allclose(r24["wq"], r1["wq"], rtol=5e-3,
                                  atol=5e-4)


def test_fsdp_matches_replicated():
    """ZeRO-3-style parameter sharding ({'fsdp': 8}: params sharded over
    their largest divisible axis, all-gathered at use by GSPMD) must
    train identically to the replicated layout — it changes placement,
    not math. Composed {'data': 2, 'fsdp': 4} likewise."""
    base = _run(1, epochs=4)

    def sharded(sh):
        assert not sh.is_fully_replicated, sh

    for axes in ({"fsdp": 8}, {"data": 2, "fsdp": 4}):
        r = _run(mesh_axes=axes, epochs=4, check_sharding=sharded)
        numpy.testing.assert_allclose(r["train_err"],
                                      base["train_err"], atol=0.01)
        numpy.testing.assert_allclose(r["weights"], base["weights"],
                                      rtol=2e-3, atol=2e-4)


def test_tensor_parallel_matches_replicated():
    """Megatron-style column sharding ({'tensor': 4}: output-feature
    axis split, activation collectives inserted by GSPMD) — same
    trajectory and weights as the replicated run; composed
    {'data': 2, 'tensor': 4} likewise."""
    base = _run(1, epochs=4, n_classes=4)

    def column_split(sh):
        assert sh.spec[-1] == "tensor", sh

    for axes in ({"tensor": 4}, {"data": 2, "tensor": 4}):
        r = _run(mesh_axes=axes, epochs=4, n_classes=4,
                 check_sharding=column_split)
        numpy.testing.assert_allclose(r["train_err"],
                                      base["train_err"], atol=0.01)
        numpy.testing.assert_allclose(r["weights"], base["weights"],
                                      rtol=2e-3, atol=2e-4)


def test_expert_parallel_matches_replicated():
    """{'expert': 4}: MoE expert-leading params shard over the axis and
    GSPMD partitions the expert einsums — placement, not math, so the
    run must match the replicated one exactly (completing the
    per-axis equivalence matrix: dp / tp / fsdp / sp / ep)."""
    moe = [{"type": "moe_ffn", "n_experts": 4, "hidden": 16},
           {"type": "softmax", "output_sample_shape": 3}]
    base = _run(1, epochs=4, layers=moe, watch_param="w1")

    def expert_sharded(sh):
        assert sh.spec[0] == "expert", sh

    for axes in ({"expert": 4}, {"data": 2, "expert": 4}):
        r = _run(mesh_axes=axes, epochs=4, layers=moe,
                 watch_param="w1", check_sharding=expert_sharded)
        numpy.testing.assert_allclose(r["train_err"],
                                      base["train_err"], atol=0.01)
        numpy.testing.assert_allclose(r["weights"], base["weights"],
                                      rtol=2e-3, atol=2e-4)


def test_sp_windowed_matches_1dev():
    """Sliding-window attention composes with the 'sequence' axis: a
    windowed TransformerBlock under {'sequence': 4} (ring path,
    shortened rotation scan) matches the 1-device windowed run."""
    def run(mesh_axes):
        prng.seed_all(555)
        loader = SeqLoader(None, minibatch_size=32, name="seq-win")
        wf = nn.StandardWorkflow(
            name="sp-win",
            layers=[
                {"type": "transformer_block", "n_heads": 2,
                 "ffn_hidden": 16, "causal": True, "window": 5},
                {"type": "mean_pool"},
                {"type": "softmax", "output_sample_shape": 2},
            ],
            loader_unit=loader, loss_function="softmax",
            decision_config=dict(max_epochs=3, fail_iterations=100),
        )
        wf.initialize(device=vt.XLADevice(mesh_axes=mesh_axes))
        wf.run()
        import jax
        return {
            "train_err": numpy.asarray(wf.decision.epoch_metrics[TRAIN]),
            "wq": numpy.asarray(jax.device_get(
                wf.train_step.params[wf.forwards[0].name]["wq"])),
            "mesh_engaged": wf.forwards[0].mesh is not None,
        }

    r1 = run({"data": 1})
    r4 = run({"sequence": 4})
    assert not r1["mesh_engaged"] and r4["mesh_engaged"]
    numpy.testing.assert_allclose(r4["train_err"], r1["train_err"],
                                  atol=0.02)
    numpy.testing.assert_allclose(r4["wq"], r1["wq"], rtol=5e-3,
                                  atol=5e-4)
