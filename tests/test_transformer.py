"""TransformerBlock: the fused pre-LN residual block — oracle parity,
end-to-end training, and the payoff it exists for: transformer stacks
pipeline through {'pipeline': N} with no model changes."""
import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn, prng
from veles_tpu.loader import FullBatchLoader, VALID
from veles_tpu.memory import Array
from veles_tpu.parallel.sharding import PP_BLOCK


def test_block_oracle_agreement():
    prev = vt.root.common.engine.compute_dtype
    vt.root.common.engine.compute_dtype = "float32"
    try:
        wf = vt.Workflow(name="tb")
        u = nn.TransformerBlock(wf, n_heads=2, ffn_hidden=16,
                                causal=True)
        x = numpy.random.RandomState(0).randn(3, 8, 12).astype("float32")
        u.input = Array(x)
        u.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        u.xla_run()
        y = numpy.asarray(u.output.map_read())
        y_np = u.numpy_apply(u.params_np(), x)
        numpy.testing.assert_allclose(y, y_np, rtol=1e-4, atol=1e-4)
        assert y.shape == x.shape
    finally:
        vt.root.common.engine.compute_dtype = prev


class SeqLoader(FullBatchLoader):
    """Classify which feature group carries a burst on a few random
    time steps (content-based: solvable without positional encoding —
    the blocks are permutation-equivariant)."""

    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(6)
        n, t, d = 360, 12, 16
        y = rng.randint(0, 3, n).astype(numpy.int32)
        x = 0.3 * rng.randn(n, t, d).astype(numpy.float32)
        for i in range(n):
            steps = rng.choice(t, 3, replace=False)
            x[i, steps, y[i] * 4:(y[i] + 1) * 4] += 1.5
        self.create_originals(x, y)
        self.class_lengths = [0, 72, 288]


def make_wf(n_blocks=4, epochs=6, mesh_kw=None):
    layers = ([{"type": "transformer_block", "n_heads": 2,
                "ffn_hidden": 32, "causal": False,
                "learning_rate": 0.003, "solver": "adam",
                "name": "blk%d" % i} for i in range(n_blocks)]
              + [{"type": "mean_pool"},
                 {"type": "softmax", "output_sample_shape": 3,
                  "learning_rate": 0.003, "solver": "adam"}])
    return nn.StandardWorkflow(
        name="tiny-transformer", layers=layers,
        loader_unit=SeqLoader(None, minibatch_size=24, name="seqs"),
        loss_function="softmax",
        decision_config=dict(max_epochs=epochs, fail_iterations=100))


def test_transformer_trains():
    prng.seed_all(31)
    wf = make_wf()
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    assert wf.decision.best_metric < 0.1, wf.decision.epoch_metrics


def test_transformer_pipelines():
    """The design payoff: N identical blocks stage-group automatically
    and match the plain run."""
    prng.seed_all(31)
    wf = make_wf()
    wf.initialize(device=vt.XLADevice(mesh_axes={"pipeline": 4}))
    step = wf.train_step
    assert step._pp is not None
    assert step._pp["names"] == ["blk0", "blk1", "blk2", "blk3"]
    assert step.params[PP_BLOCK]["wq"].shape[0] == 4
    wf.run()
    assert wf.decision.best_metric < 0.1

    prng.seed_all(31)
    plain = make_wf()
    plain.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    plain.run()
    e_pp = numpy.asarray(wf.decision.epoch_metrics[VALID])
    e_pl = numpy.asarray(plain.decision.epoch_metrics[VALID])
    numpy.testing.assert_allclose(e_pp, e_pl, atol=0.03)


def test_rope_oracle_agreement():
    prev = vt.root.common.engine.compute_dtype
    vt.root.common.engine.compute_dtype = "float32"
    try:
        wf = vt.Workflow(name="tr")
        u = nn.TransformerBlock(wf, n_heads=2, ffn_hidden=16,
                                causal=True, rope=True)
        x = numpy.random.RandomState(4).randn(2, 8, 12).astype("float32")
        u.input = Array(x)
        u.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        u.xla_run()
        y = numpy.asarray(u.output.map_read())
        y_np = u.numpy_apply(u.params_np(), x)
        numpy.testing.assert_allclose(y, y_np, rtol=1e-4, atol=1e-4)
        # rope actually changes the computation
        u2 = nn.TransformerBlock(wf, n_heads=2, ffn_hidden=16,
                                 causal=True, rope=False, name="nr")
        y_plain = u2.numpy_apply(u.params_np(), x)
        assert numpy.abs(y_np - y_plain).max() > 1e-3
    finally:
        vt.root.common.engine.compute_dtype = prev


def test_rope_solves_position_task():
    """RoPE provides positions WITHOUT a pos_embedding unit: the
    order-classification task (position-dependent) must be learnable
    from rope alone."""
    from conftest import import_model
    mod = import_model("tiny_transformer")

    prng.seed_all(31)
    loader = mod.OrderLoader(None, n_train=2048, n_valid=512,
                             minibatch_size=64, name="order-rope")
    layers = ([{"type": "transformer_block", "n_heads": 4,
                "ffn_hidden": 64, "causal": False, "rope": True,
                "solver": "adam", "learning_rate": 0.003,
                "name": "blk%d" % i} for i in range(2)]
              + [{"type": "mean_pool"},
                 {"type": "softmax", "output_sample_shape": 2,
                  "solver": "adam", "learning_rate": 0.003}])
    wf = nn.StandardWorkflow(
        name="rope-order", layers=layers, loader_unit=loader,
        loss_function="softmax",
        decision_config=dict(max_epochs=15, fail_iterations=50))
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    assert wf.decision.best_metric < 0.35, wf.decision.epoch_metrics


def test_embedding_text_model_trains():
    """Token path end to end: int sequences → Embedding → rope block →
    pool → softmax. Task: does token 7 appear in the sequence."""
    class TokenLoader(FullBatchLoader):
        hide_from_registry = True

        def load_data(self):
            rng = numpy.random.RandomState(9)
            n, t, vocab = 480, 10, 12
            x = rng.randint(0, vocab, (n, t)).astype(numpy.int32)
            x[x == 7] = 6                     # clear marker everywhere
            y = rng.randint(0, 2, n).astype(numpy.int32)
            for i in numpy.where(y == 1)[0]:
                x[i, rng.randint(0, t)] = 7   # plant the marker
            self.create_originals(x, y)
            self.class_lengths = [0, 96, 384]

    prng.seed_all(55)
    wf = nn.StandardWorkflow(
        name="text-clf",
        layers=[{"type": "embedding", "vocab_size": 12, "dim": 16,
                 "solver": "adam", "learning_rate": 0.01},
                {"type": "transformer_block", "n_heads": 2,
                 "ffn_hidden": 32, "causal": False, "rope": True,
                 "solver": "adam", "learning_rate": 0.01},
                {"type": "mean_pool"},
                {"type": "softmax", "output_sample_shape": 2,
                 "solver": "adam", "learning_rate": 0.01}],
        loader_unit=TokenLoader(None, minibatch_size=48, name="toks"),
        loss_function="softmax",
        decision_config=dict(max_epochs=10, fail_iterations=50))
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    assert wf.decision.best_metric < 0.1, wf.decision.epoch_metrics


def test_embedding_oracle():
    wf = vt.Workflow(name="te")
    u = nn.Embedding(wf, vocab_size=9, dim=5)
    x = numpy.random.RandomState(2).randint(0, 9, (3, 7)).astype("int32")
    u.input = Array(x)
    u.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    u.xla_run()
    y = numpy.asarray(u.output.map_read())
    y_np = u.numpy_apply(u.params_np(), x)
    numpy.testing.assert_allclose(y, y_np, rtol=1e-5, atol=1e-6)
    assert y.shape == (3, 7, 5)


def test_embedding_oob_clips_consistently():
    """Out-of-range ids clamp identically in jax, oracle and (by
    construction) the C++ twin — the one semantic on-device code can
    express."""
    wf = vt.Workflow(name="teo")
    u = nn.Embedding(wf, vocab_size=4, dim=3)
    x = numpy.array([[-1, 0, 3, 4, 99]], dtype="int32")
    u.input = Array(numpy.clip(x, 0, 3))
    u.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    params = u.params_np()
    y_np = u.numpy_apply(params, x)
    import jax
    y_jax = numpy.asarray(jax.device_get(
        u.apply({k: jax.numpy.asarray(v) for k, v in params.items()},
                jax.numpy.asarray(x))))
    numpy.testing.assert_allclose(y_jax, y_np, rtol=1e-6)
    numpy.testing.assert_allclose(y_np[0, 0], params["table"][0])
    numpy.testing.assert_allclose(y_np[0, 3], params["table"][3])
    numpy.testing.assert_allclose(y_np[0, 4], params["table"][3])


def test_cached_generation_matches_naive():
    """nn/sampling.py KV-cached sampler: prefill + scan must reproduce
    the re-forward-the-window oracle EXACTLY under greedy decoding
    (same params, same positions, same rope) — and run as one dispatch."""
    import importlib
    import sys as _sys
    import os as _os
    _sys.path.insert(0, _os.path.join(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))), "models"))
    lm = importlib.import_module("char_lm")
    prng.seed_all(1234)
    wf = lm.build_workflow(epochs=3, minibatch_size=64, n_blocks=2,
                           dim=32, n_train=512, n_valid=128)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    rng = numpy.random.RandomState(3)
    # the oracle forwards the FULL growing sequence, the cached path
    # keeps full context too — structurally identical at any length
    # (24 new tokens deliberately crosses the training SEQ_LEN)
    prompt = list(lm.make_corpus(rng, lm.SEQ_LEN // 2))
    naive = lm.generate_naive(wf, prompt, 24, temperature=0)
    cached = lm.generate(wf, prompt, 24, temperature=0)
    assert naive == cached, (naive, cached)
    # stochastic path stays in-vocab and runs
    toks = lm.generate(wf, prompt, 16, temperature=1.0, seed=7)
    assert len(toks) == 16
    assert all(0 <= t < lm.VOCAB for t in toks)


def test_cached_generation_rejects_non_lm_stack():
    from veles_tpu.error import VelesError
    from veles_tpu.nn import sampling

    class FakeUnit:
        PARAMETERIZED = False
    wf = type("WF", (), {"forwards": [FakeUnit()]})()
    with pytest.raises(VelesError):
        sampling.generate(wf, [1, 2, 3], 4)


def test_cached_generation_batched():
    """A batch of equal-length prompts decodes in ONE dispatch; each
    row must equal its own single-prompt greedy generation."""
    from conftest import import_model
    lm = import_model("char_lm")
    prng.seed_all(1234)
    wf = lm.build_workflow(epochs=3, minibatch_size=64, n_blocks=2,
                           dim=32, n_train=512, n_valid=128)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    rng = numpy.random.RandomState(5)
    prompts = [list(lm.make_corpus(rng, 12)) for _ in range(3)]
    from veles_tpu.nn import sampling
    batch_out = sampling.generate(wf, prompts, 10, temperature=0)
    assert len(batch_out) == 3 and all(len(r) == 10 for r in batch_out)
    for p, row in zip(prompts, batch_out):
        single = sampling.generate(wf, p, 10, temperature=0)
        assert row == single, (row, single)


def test_cached_generation_heterogeneous_heads(tmp_path):
    """ADVICE r2: the sampler sized every block's KV cache from
    blocks[0].n_heads while the per-block step reshaped with its own —
    a stack with differing per-block n_heads (allowed by the layers
    config) trace-failed. Each cache now takes its block's own shape."""
    from veles_tpu.loader import TextFileLoader
    from veles_tpu.nn import sampling
    p = tmp_path / "c.txt"
    p.write_text("abcdabcdabcd" * 40)
    prng.seed_all(7)
    loader = TextFileLoader(None, files=[str(p)], seq_len=16,
                            minibatch_size=8, name="text")
    wf = nn.StandardWorkflow(
        name="het-heads",
        layers=[{"type": "embedding", "vocab_size": 8, "dim": 24,
                 "solver": "adam", "learning_rate": 0.01},
                {"type": "transformer_block", "n_heads": 4,
                 "ffn_hidden": 48, "causal": True, "rope": True,
                 "solver": "adam", "learning_rate": 0.01, "name": "b4"},
                {"type": "transformer_block", "n_heads": 2,
                 "ffn_hidden": 48, "causal": True, "rope": True,
                 "solver": "adam", "learning_rate": 0.01, "name": "b2"},
                {"type": "lm_head", "vocab_size": 8,
                 "solver": "adam", "learning_rate": 0.01}],
        loader_unit=loader, loss_function="softmax_seq",
        decision_config=dict(max_epochs=1, fail_iterations=50))
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    toks = sampling.generate(wf, [0, 1, 2], 6, temperature=0)
    assert len(toks) == 6
    assert all(0 <= t < 8 for t in toks)
    # greedy decode is deterministic: same prompt, same continuation
    assert toks == sampling.generate(wf, [0, 1, 2], 6, temperature=0)


def test_gqa_oracle_agreement():
    """Grouped-query attention (n_kv_heads < n_heads): jax apply vs the
    numpy oracle, plus the shrunken wk/wv shapes."""
    prev = vt.root.common.engine.compute_dtype
    vt.root.common.engine.compute_dtype = "float32"
    try:
        wf = vt.Workflow(name="gqa")
        u = nn.TransformerBlock(wf, n_heads=4, n_kv_heads=2,
                                ffn_hidden=16, causal=True)
        x = numpy.random.RandomState(1).randn(3, 8, 16).astype("float32")
        u.input = Array(x)
        u.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        assert u.params_np()["wk"].shape == (16, 8)   # kv_d = 2 * 4
        assert u.params_np()["wv"].shape == (16, 8)
        u.xla_run()
        y = numpy.asarray(u.output.map_read())
        y_np = u.numpy_apply(u.params_np(), x)
        numpy.testing.assert_allclose(y, y_np, rtol=1e-4, atol=1e-4)
    finally:
        vt.root.common.engine.compute_dtype = prev


def test_gqa_equals_mha_with_shared_heads():
    """Semantic pin: a GQA block must equal an MHA block whose K/V
    weight columns are the GQA columns tiled per query-head group —
    kv-head sharing IS column tiling."""
    prev = vt.root.common.engine.compute_dtype
    vt.root.common.engine.compute_dtype = "float32"
    try:
        wf = vt.Workflow(name="gqa-pin")
        d, h, kvh = 16, 4, 2
        hd, g = d // h, h // kvh
        gqa = nn.TransformerBlock(wf, n_heads=h, n_kv_heads=kvh,
                                  ffn_hidden=16, causal=True,
                                  name="gq")
        mha = nn.TransformerBlock(wf, n_heads=h, ffn_hidden=16,
                                  causal=True, name="mh")
        x = numpy.random.RandomState(2).randn(2, 6, d).astype("float32")
        for u in (gqa, mha):
            u.input = Array(x)
            u.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        pg = gqa.params_np()
        pm = dict(pg)
        for key in ("wk", "wv"):
            cols = [pg[key][:, (q // g) * hd:(q // g + 1) * hd]
                    for q in range(h)]
            pm[key] = numpy.concatenate(cols, axis=1)
        y_gqa = gqa.numpy_apply(pg, x)
        y_mha = mha.numpy_apply(pm, x)
        numpy.testing.assert_allclose(y_gqa, y_mha, rtol=1e-5,
                                      atol=1e-5)
    finally:
        vt.root.common.engine.compute_dtype = prev


def test_gqa_generation_matches_naive():
    """GQA end to end: a 4-head/2-kv-head rope LM trains, and the
    KV-cached sampler (whose caches hold the UNREPEATED kv heads —
    half an MHA cache here) reproduces the re-forward oracle exactly
    under greedy decoding."""
    from veles_tpu.loader import TextFileLoader
    from veles_tpu.nn import sampling
    from conftest import import_model
    lm = import_model("char_lm")
    import tempfile, os as _os
    with tempfile.TemporaryDirectory() as td:
        p = _os.path.join(td, "c.txt")
        with open(p, "w") as f:
            f.write("the quick brown fox jumps over the lazy dog. " * 30)
        prng.seed_all(11)
        loader = TextFileLoader(None, files=[p], seq_len=16,
                                minibatch_size=8, name="gqa-text")
        vocab = loader.vocab_size if hasattr(loader, "vocab_size") else 64
        wf = nn.StandardWorkflow(
            name="gqa-lm",
            layers=[{"type": "embedding", "vocab_size": 64, "dim": 24,
                     "solver": "adam", "learning_rate": 0.01},
                    {"type": "transformer_block", "n_heads": 4,
                     "n_kv_heads": 2, "ffn_hidden": 48, "causal": True,
                     "rope": True, "solver": "adam",
                     "learning_rate": 0.01, "name": "g0"},
                    {"type": "transformer_block", "n_heads": 4,
                     "n_kv_heads": 1, "ffn_hidden": 48, "causal": True,
                     "rope": True, "solver": "adam",
                     "learning_rate": 0.01, "name": "g1"},   # MQA
                    {"type": "lm_head", "vocab_size": 64,
                     "solver": "adam", "learning_rate": 0.01}],
            loader_unit=loader, loss_function="softmax_seq",
            decision_config=dict(max_epochs=2, fail_iterations=50))
        wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        wf.run()
        prompt = [1, 2, 3, 4]
        naive = lm.generate_naive(wf, prompt, 8, temperature=0)
        cached = sampling.generate(wf, prompt, 8, temperature=0)
        assert naive == cached, (naive, cached)


def test_sliding_window_oracle_agreement():
    """TransformerBlock(window=W): jax apply (through attention_core)
    vs the numpy oracle's windowed mask."""
    prev = vt.root.common.engine.compute_dtype
    vt.root.common.engine.compute_dtype = "float32"
    try:
        wf = vt.Workflow(name="swa")
        u = nn.TransformerBlock(wf, n_heads=2, ffn_hidden=16,
                                causal=True, window=3)
        x = numpy.random.RandomState(4).randn(2, 10, 12).astype(
            "float32")
        u.input = Array(x)
        u.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        u.xla_run()
        y = numpy.asarray(u.output.map_read())
        y_np = u.numpy_apply(u.params_np(), x)
        numpy.testing.assert_allclose(y, y_np, rtol=1e-4, atol=1e-4)
        # the window genuinely changes the function: a full-attention
        # twin with the same params must differ
        u2 = nn.TransformerBlock(wf, n_heads=2, ffn_hidden=16,
                                 causal=True, name="full")
        y_full = u2.numpy_apply(u.params_np(), x)
        assert numpy.abs(y_np - y_full).max() > 1e-4
    finally:
        vt.root.common.engine.compute_dtype = prev


def test_window_requires_causal_unit():
    wf = vt.Workflow(name="swa-bad")
    with pytest.raises(ValueError, match="causal"):
        nn.TransformerBlock(wf, n_heads=2, causal=False, window=4)


def test_windowed_generation_matches_naive():
    """Sliding-window LM end to end: the KV-cached decode masks the
    cache to the window and must reproduce the re-forward oracle
    (whose windowed mask lives in the SAME apply) exactly."""
    from veles_tpu.loader import TextFileLoader
    from veles_tpu.nn import sampling
    from conftest import import_model
    lm = import_model("char_lm")
    import tempfile, os as _os
    with tempfile.TemporaryDirectory() as td:
        p = _os.path.join(td, "c.txt")
        with open(p, "w") as f:
            f.write("abcdefg hijklmn " * 60)
        prng.seed_all(13)
        loader = TextFileLoader(None, files=[p], seq_len=16,
                                minibatch_size=8, name="swa-text")
        wf = nn.StandardWorkflow(
            name="swa-lm",
            layers=[{"type": "embedding", "vocab_size": 64, "dim": 16,
                     "solver": "adam", "learning_rate": 0.01},
                    {"type": "transformer_block", "n_heads": 2,
                     "ffn_hidden": 32, "causal": True, "rope": True,
                     "window": 6, "solver": "adam",
                     "learning_rate": 0.01, "name": "w0"},
                    {"type": "lm_head", "vocab_size": 64,
                     "solver": "adam", "learning_rate": 0.01}],
            loader_unit=loader, loss_function="softmax_seq",
            decision_config=dict(max_epochs=2, fail_iterations=50))
        wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        wf.run()
        prompt = [1, 2, 3, 4, 5]
        naive = lm.generate_naive(wf, prompt, 10, temperature=0)
        cached = sampling.generate(wf, prompt, 10, temperature=0)
        assert naive == cached, (naive, cached)


def test_rms_swiglu_oracle_agreement():
    """llama-style block options (norm='rms', ffn='swiglu'): jax apply
    vs numpy oracle; param census drops biases and gains w3."""
    prev = vt.root.common.engine.compute_dtype
    vt.root.common.engine.compute_dtype = "float32"
    try:
        wf = vt.Workflow(name="llam")
        u = nn.TransformerBlock(wf, n_heads=2, ffn_hidden=24,
                                causal=True, norm="rms", ffn="swiglu")
        x = numpy.random.RandomState(6).randn(2, 8, 12).astype(
            "float32")
        u.input = Array(x)
        u.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        p = u.params_np()
        assert "w3" in p and "b1" not in p and "b2" not in p
        assert "ln1_b" not in p and "ln2_b" not in p
        u.xla_run()
        y = numpy.asarray(u.output.map_read())
        y_np = u.numpy_apply(p, x)
        numpy.testing.assert_allclose(y, y_np, rtol=1e-4, atol=1e-4)
    finally:
        vt.root.common.engine.compute_dtype = prev


def test_block_option_validation():
    wf = vt.Workflow(name="bad-opts")
    with pytest.raises(ValueError, match="norm"):
        nn.TransformerBlock(wf, norm="batch")
    with pytest.raises(ValueError, match="ffn"):
        nn.TransformerBlock(wf, ffn="relu")


def test_llama_style_lm_trains_and_generates():
    """The modern-LM composition in one stack: RMSNorm + SwiGLU + GQA +
    RoPE + sliding window. Trains through StandardWorkflow; the
    KV-cached sampler (which shares block_norm/block_ffn with the
    trained forward) reproduces the re-forward oracle exactly."""
    from veles_tpu.loader import TextFileLoader
    from veles_tpu.nn import sampling
    from conftest import import_model
    lm = import_model("char_lm")
    import tempfile, os as _os
    with tempfile.TemporaryDirectory() as td:
        p = _os.path.join(td, "c.txt")
        with open(p, "w") as f:
            f.write("to be or not to be that is the question " * 40)
        prng.seed_all(21)
        loader = TextFileLoader(None, files=[p], seq_len=16,
                                minibatch_size=8, name="llama-text")
        wf = nn.StandardWorkflow(
            name="llama-lm",
            layers=[{"type": "embedding", "vocab_size": 64, "dim": 24,
                     "solver": "adam", "learning_rate": 0.01},
                    {"type": "transformer_block", "n_heads": 4,
                     "n_kv_heads": 2, "ffn_hidden": 64, "causal": True,
                     "rope": True, "norm": "rms", "ffn": "swiglu",
                     "window": 8, "solver": "adam",
                     "learning_rate": 0.01, "name": "L0"},
                    {"type": "lm_head", "vocab_size": 64,
                     "solver": "adam", "learning_rate": 0.01}],
            loader_unit=loader, loss_function="softmax_seq",
            decision_config=dict(max_epochs=3, fail_iterations=50))
        wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        wf.run()
        hist = wf.decision.epoch_metrics
        prompt = [2, 3, 4, 5]
        naive = lm.generate_naive(wf, prompt, 10, temperature=0)
        cached = sampling.generate(wf, prompt, 10, temperature=0)
        assert naive == cached, (naive, cached)


def test_rope_base_changes_rotation_and_roundtrips():
    """rope_base != 10000 genuinely changes the rotation (long-context
    theta lever), cached decode still matches the re-forward oracle,
    and the key survives export config."""
    prev = vt.root.common.engine.compute_dtype
    vt.root.common.engine.compute_dtype = "float32"
    try:
        wf = vt.Workflow(name="rb")
        u1 = nn.TransformerBlock(wf, n_heads=2, ffn_hidden=16,
                                 causal=True, rope=True, name="r1")
        u2 = nn.TransformerBlock(wf, n_heads=2, ffn_hidden=16,
                                 causal=True, rope=True,
                                 rope_base=500000.0, name="r2")
        x = numpy.random.RandomState(9).randn(1, 12, 8).astype(
            "float32")
        for u in (u1, u2):
            u.input = Array(x)
            u.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
        y1 = u1.numpy_apply(u1.params_np(), x)
        y2 = u2.numpy_apply(u1.params_np(), x)   # same params, new base
        assert numpy.abs(y1 - y2).max() > 1e-4
        assert u2.rope_base == 500000.0
    finally:
        vt.root.common.engine.compute_dtype = prev


def test_negative_window_refused():
    """A negative window (config typo) must refuse at construction —
    on the reference path an all-false mask would silently degenerate
    to uniform attention over every position including the future."""
    wf = vt.Workflow(name="negw")
    with pytest.raises(ValueError, match="positive"):
        nn.TransformerBlock(wf, n_heads=2, causal=True, window=-64)
