"""Checkpoint/resume: mirrors the reference's snapshot guarantees
(SURVEY.md §5.4) — resume restores params, optimizer state, loader
position, decision bests AND RNG streams; training continuation after
resume is identical to uninterrupted training."""
import glob
import os

import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn, prng
from veles_tpu.loader import FullBatchLoader


class TinyLoader(FullBatchLoader):
    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(5)
        n = 240
        self.create_originals(rng.rand(n, 8).astype(numpy.float32),
                              rng.randint(0, 3, n).astype(numpy.int32))
        self.class_lengths = [0, 40, 200]


def build(tmpdir, max_epochs, with_snap=True, lr_schedule=None,
          epochs_per_dispatch=1):
    loader = TinyLoader(None, minibatch_size=20, name="tiny")
    snap = vt.Snapshotter(None, prefix="tiny", directory=str(tmpdir),
                          compression="gz") if with_snap else None
    wf = nn.StandardWorkflow(
        name="snap-wf",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 8},
                {"type": "softmax", "output_sample_shape": 3}],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=max_epochs, fail_iterations=99),
        snapshotter_unit=snap, steps_per_dispatch=4,
        lr_schedule=lr_schedule,
        epochs_per_dispatch=epochs_per_dispatch,
    )
    return wf


def fresh_prng():
    with prng._lock:
        prng._generators.clear()
    prng.seed_all(1234)


def test_snapshot_write_and_current_symlink(tmp_path):
    fresh_prng()
    wf = build(tmp_path, 3)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    files = glob.glob(str(tmp_path / "tiny_*.pickle.gz"))
    assert files, "no snapshot written"
    cur = tmp_path / "tiny_current.pickle.gz"
    assert cur.exists()
    state = vt.load_snapshot(str(cur))
    assert "all2all_tanh0" in state["__units__"]
    assert "weights" in state["__units__"]["all2all_tanh0"]


def test_resume_restores_everything(tmp_path):
    fresh_prng()
    wf = build(tmp_path, 4)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    cur = str(tmp_path / "tiny_current.pickle.gz")
    w_trained = numpy.array(wf.forwards[0].weights.map_read())
    epoch = wf.decision.epoch_number
    best = wf.decision.best_metric

    fresh_prng()
    wf2 = build(tmp_path, 4, with_snap=False)
    wf2.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    vt.resume(wf2, cur)
    numpy.testing.assert_array_equal(
        wf2.forwards[0].weights.map_read(), w_trained)
    assert wf2.decision.epoch_number == epoch
    assert wf2.decision.best_metric == best
    assert wf2.loader.epoch_number == wf.loader.epoch_number
    assert wf2.restored_from_snapshot


def test_resume_continuation_identical(tmp_path):
    """Train 2+2 epochs with a snapshot boundary vs 4 straight epochs:
    final weights must match exactly (RNG/shuffle/lr-schedule state
    restored)."""
    sched = nn.exp_decay(0.9)
    fresh_prng()
    wf_a = build(tmp_path / "a", 4, with_snap=False, lr_schedule=sched)
    wf_a.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf_a.run()
    w_straight = numpy.array(wf_a.forwards[0].weights.map_read())

    fresh_prng()
    wf_b1 = build(tmp_path / "b", 2, lr_schedule=nn.exp_decay(0.9))
    wf_b1.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf_b1.run()
    cur = str(tmp_path / "b" / "tiny_current.pickle.gz")

    fresh_prng()
    wf_b2 = build(tmp_path / "b2", 4, with_snap=False,
                  lr_schedule=nn.exp_decay(0.9))
    wf_b2.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    vt.resume(wf_b2, cur)
    wf_b2.decision.complete <<= False
    wf_b2.run()
    w_resumed = numpy.array(wf_b2.forwards[0].weights.map_read())
    numpy.testing.assert_allclose(w_straight, w_resumed, rtol=1e-6,
                                  atol=1e-7)


def test_snapshot_gating_interval(tmp_path):
    fresh_prng()
    snap = vt.Snapshotter(None, prefix="g", directory=str(tmp_path),
                          interval=3)
    wf = vt.Workflow(name="w")
    snap.workflow = wf
    wf.add_ref(snap)
    wf.initialize()
    for _ in range(6):
        snap.run()
    files = glob.glob(str(tmp_path / "g_2*.pickle.gz"))
    assert len(files) == 2           # runs 3 and 6


def test_snapshot_skip_bool(tmp_path):
    fresh_prng()
    snap = vt.Snapshotter(None, prefix="s", directory=str(tmp_path))
    wf = vt.Workflow(name="w")
    snap.workflow = wf
    wf.add_ref(snap)
    wf.initialize()
    snap.skip <<= True
    snap.run()
    assert not glob.glob(str(tmp_path / "s_*"))


def test_db_sink_roundtrip(tmp_path):
    """SnapshotterToDB (the ODBC-era sink, veles/snapshotter.py:428):
    export into sqlite, resume from the sqlite:// DSN."""
    fresh_prng()
    loader = TinyLoader(None, minibatch_size=20, name="tiny-db")
    snap = vt.SnapshotterToDB(None, prefix="db", directory=str(tmp_path))
    wf = nn.StandardWorkflow(
        name="snap-db",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 8},
                {"type": "softmax", "output_sample_shape": 3}],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=2, fail_iterations=99),
        snapshotter_unit=snap, steps_per_dispatch=4)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    assert snap.destination and snap.destination.startswith("sqlite://")
    w_trained = numpy.array(wf.forwards[0].weights.map_read())

    fresh_prng()
    wf2 = build(tmp_path, 4, with_snap=False)
    wf2.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    vt.resume(wf2, snap.destination)          # explicit row DSN
    numpy.testing.assert_allclose(
        numpy.array(wf2.forwards[0].weights.map_read()), w_trained)
    assert wf2.decision.epoch_number == 2

    # bare .sqlite3 path → newest row
    state = vt.load_snapshot(str(tmp_path / "snapshots.sqlite3"))
    assert "all2all_tanh0" in state["__units__"]


def test_only_coordinator_writes(tmp_path, monkeypatch):
    """Multihost semantics: only process 0 writes snapshots (reference:
    master-only snapshot, veles/snapshotter.py:160). Both sink types."""
    import jax
    fresh_prng()
    wf = build(tmp_path, 1)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    snap_file = vt.Snapshotter(None, prefix="nonzero",
                               directory=str(tmp_path))
    snap_file.workflow = wf
    snap_db = vt.SnapshotterToDB(None, prefix="nonzero",
                                 directory=str(tmp_path / "db2"))
    snap_db.workflow = wf
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    assert snap_file.export() == ""
    assert snap_db.export() == ""
    assert not glob.glob(str(tmp_path / "nonzero*"))
    assert not (tmp_path / "db2").exists()
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    assert snap_file.export() != ""
    assert snap_db.export().startswith("sqlite://")


def test_resume_continuation_identical_block_mode(tmp_path):
    """The identical-continuation guarantee holds under epoch-block
    dispatch: 2+2 epochs with H=2 blocks and a snapshot boundary vs 4
    straight classic epochs — final weights match."""
    fresh_prng()
    wf_a = build(tmp_path / "a", 4, with_snap=False,
                 lr_schedule=nn.exp_decay(0.9))
    wf_a.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf_a.run()
    w_straight = numpy.array(wf_a.forwards[0].weights.map_read())

    fresh_prng()
    wf_b1 = build(tmp_path / "b", 2, lr_schedule=nn.exp_decay(0.9),
                  epochs_per_dispatch=2)
    wf_b1.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf_b1.run()
    assert wf_b1.loader.block_length == 2        # one block of 2 epochs
    cur = str(tmp_path / "b" / "tiny_current.pickle.gz")

    fresh_prng()
    wf_b2 = build(tmp_path / "b2", 4, with_snap=False,
                  lr_schedule=nn.exp_decay(0.9), epochs_per_dispatch=2)
    wf_b2.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    vt.resume(wf_b2, cur)
    wf_b2.decision.complete <<= False
    wf_b2.run()
    w_resumed = numpy.array(wf_b2.forwards[0].weights.map_read())
    numpy.testing.assert_allclose(w_straight, w_resumed, rtol=1e-5,
                                  atol=1e-6)
