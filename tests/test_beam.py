"""Beam-search decoding (nn/beam.py): beam=1 IS greedy, reported
scores are true model log-probabilities, wider beams never score
worse, eos freezing works."""
import numpy
import pytest

import veles_tpu as vt
from veles_tpu import prng
from veles_tpu.error import VelesError
from veles_tpu.nn.beam import beam_generate

from conftest import import_model


@pytest.fixture(scope="module")
def lm_wf():
    lm = import_model("char_lm")
    prng.seed_all(4321)
    wf = lm.build_workflow(epochs=3, minibatch_size=64, n_blocks=2,
                           dim=32, n_train=512, n_valid=128)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    return lm, wf


def _score(lm, wf, prompt, toks):
    """Teacher-forced total log-prob of `toks` after `prompt`, via the
    units' own numpy oracles — independent of the beam machinery."""
    seq = numpy.asarray(list(prompt) + list(toks),
                        dtype=numpy.int32)[None, :]
    x = seq
    for f in wf.forwards:
        x = f.numpy_apply(f.params_np(), x)
    logits = x[0].astype(numpy.float64)          # (T, V)
    z = logits - logits.max(axis=1, keepdims=True)
    logp = z - numpy.log(numpy.exp(z).sum(axis=1, keepdims=True))
    t_p = len(prompt)
    return sum(logp[t_p - 1 + i, toks[i]] for i in range(len(toks)))


def test_beam_one_is_greedy(lm_wf):
    lm, wf = lm_wf
    rng = numpy.random.RandomState(5)
    prompt = list(lm.make_corpus(rng, lm.SEQ_LEN // 2))
    want = lm.generate(wf, prompt, 16, temperature=0)
    got, stats = beam_generate(wf, prompt, 16, beam=1)
    assert got == want
    assert len(stats["beams"]) == 1


def test_beam_scores_are_true_logprobs_and_monotone(lm_wf):
    """The reported score of every hypothesis equals its teacher-
    forced log-probability under the model, and the beam-4 best is at
    least as probable as the greedy continuation."""
    lm, wf = lm_wf
    rng = numpy.random.RandomState(6)
    prompt = list(lm.make_corpus(rng, lm.SEQ_LEN // 2))
    got1, s1 = beam_generate(wf, prompt, 12, beam=1)
    got4, s4 = beam_generate(wf, prompt, 12, beam=4)
    for toks, score in zip(s4["beams"], s4["scores"]):
        true = _score(lm, wf, prompt, toks)
        numpy.testing.assert_allclose(score, true, rtol=2e-4,
                                      atol=2e-3)
    # NOT a beam-search invariant (width-4 CAN prune the greedy path),
    # but a large loss would mean broken scoring; wide tolerance keeps
    # this a sanity check, not a tie-break-sensitive gate
    assert s4["scores"][0] >= s1["scores"][0] - 0.5
    assert sorted(s4["scores"], reverse=True) == s4["scores"]
    assert all(0 <= t < lm.VOCAB for t in got4)


def test_beam_eos_freezes_hypotheses(lm_wf):
    """With an eos token, finished hypotheses stop accumulating score
    and report finished=True; length_penalty re-ranks by per-token
    score."""
    lm, wf = lm_wf
    rng = numpy.random.RandomState(7)
    prompt = list(lm.make_corpus(rng, lm.SEQ_LEN // 2))
    # pick the model's first greedy token as "eos" so at least one
    # hypothesis finishes immediately
    greedy = lm.generate(wf, prompt, 8, temperature=0)
    eos = greedy[0]
    got, stats = beam_generate(wf, prompt, 8, beam=4, eos_id=eos,
                               length_penalty=0.6)
    assert any(stats["finished"]), stats
    fin = stats["finished"].index(True)
    toks = stats["beams"][fin]
    hit = toks.index(eos)
    # after eos, a frozen hypothesis only repeats eos (zero-cost)
    assert all(t == eos for t in toks[hit:])


def test_beam_rejects_bad_args(lm_wf):
    lm, wf = lm_wf
    with pytest.raises(ValueError, match="beam"):
        beam_generate(wf, [1, 2], 4, beam=0)
    with pytest.raises(VelesError, match="single"):
        beam_generate(wf, [[1], [2]], 4)


def test_beam_rejects_beam_wider_than_vocab(lm_wf):
    lm, wf = lm_wf
    from veles_tpu.nn.sampling import split_stack
    vocab = split_stack(list(wf.forwards))["head"].vocab_size
    with pytest.raises(ValueError, match="vocab"):
        beam_generate(wf, [1, 2], 4, beam=vocab + 1)
