"""Overload-hardened request plane (ISSUE 17): QoS classes with
preempt-and-resume, deadline propagation, adaptive admission +
brownout, dynamic Retry-After.

The contract under test: (1) batch rows preempted at a step boundary
for interactive arrivals requeue with resume progress and finish
**bit-identical** to their uninterrupted solo decodes — greedy AND
sampled, continuous AND recurrent — with exactly one terminal per
request however many times the row bounced; (2) the scheduler's
expiry sweep honors per-ticket deadlines (a short-deadline ticket
expires while its long-deadline co-tenant survives); (3) the AIMD
controller, brownout ladder and retry token bucket are deterministic
given injected clocks/values, and interactive is never throttled;
(4) with every QoS knob off (the default), admission order, outputs
and counters are bit-identical to the QoS-less plane.

Budget discipline: one tiny TRAINED transformer char_lm plus one
initialized lstm twin, both module-scoped; the engines are driven
TICK BY TICK (never started) so every preemption point is
deterministic.
"""
import time

import numpy
import pytest

import veles_tpu as vt
from veles_tpu import prng
from veles_tpu.config import root
from veles_tpu.serving import QOS_COUNTERS, RecurrentEngine
from veles_tpu.serving.engine import ContinuousEngine, make_request
from veles_tpu.serving.overload import (AIMDController, BrownoutLadder,
                                        OverloadGovernor,
                                        RetryTokenBucket,
                                        clear_pressure_provider,
                                        dynamic_retry_after,
                                        governor_from_config,
                                        request_priority,
                                        retry_after_hint,
                                        set_pressure_provider)
from veles_tpu.serving.scheduler import (SlotScheduler, Ticket,
                                         shed_expired, split_expired)
from veles_tpu.telemetry.counters import counters, histograms

from conftest import import_model


# -- pure QoS plumbing (no jax) ----------------------------------------------

def test_request_priority_default_and_labels():
    assert request_priority({}) == "interactive"
    assert request_priority({"priority": "batch"}) == "batch"
    assert request_priority({"priority": "interactive"}) \
        == "interactive"
    # junk never crashes the hot path — it degrades to the default
    assert request_priority({"priority": 7}) == "interactive"


def test_retry_after_hint_scales_and_clamps():
    # empty queue: the base hint passes through untouched
    assert retry_after_hint(0, 16, 1.0, 30.0) == 1.0
    # deeper queue -> larger hint, monotone
    shallow = retry_after_hint(4, 16, 1.0, 30.0)
    deep = retry_after_hint(64, 16, 1.0, 30.0)
    assert 1.0 <= shallow <= deep <= 30.0
    # the clamp holds whatever the depth
    assert retry_after_hint(10 ** 6, 16, 1.0, 30.0) == 30.0


def test_dynamic_retry_after_provider_lifecycle():
    # no provider registered (the feature-off default): passthrough
    assert dynamic_retry_after(5.0) == 5.0
    provider = lambda: (32, 16)  # noqa: E731
    set_pressure_provider(provider)
    try:
        assert dynamic_retry_after(1.0) > 1.0
    finally:
        clear_pressure_provider(provider)
    assert dynamic_retry_after(1.0) == 1.0
    # a ticket's hint rides the same path: base when nothing is
    # registered (tests elsewhere pin ticket.retry_after exactly)
    ticket = Ticket()
    ticket.retry_after = 5.0
    assert ticket.retry_after_hint() == 5.0


def test_aimd_controller_is_deterministic():
    aimd = AIMDController(slo_ms=100.0, floor=0.05, additive=0.1,
                          multiplicative=0.5, interval=0.0)
    assert aimd.rate == 1.0
    aimd.observe(value_ms=250.0)     # over SLO: multiplicative cut
    assert aimd.rate == 0.5
    aimd.observe(value_ms=250.0)
    assert aimd.rate == 0.25
    aimd.observe(value_ms=50.0)      # under SLO: additive recovery
    assert aimd.rate == pytest.approx(0.35)
    for _ in range(50):
        aimd.observe(value_ms=500.0)
    assert aimd.rate == 0.05         # never below the floor
    # the credit-accumulator grant is deterministic, no RNG: at rate
    # 0.5 exactly every other grant passes
    aimd.rate = 0.5
    aimd._credit = 0.0
    grants = [aimd.grant() for _ in range(8)]
    assert grants == [False, True] * 4


def test_brownout_ladder_hysteresis():
    ladder = BrownoutLadder(slo_ms=100.0, enter=1.5, exit=0.8,
                            patience=2, cap_n_new=4)
    assert ladder.level == 0
    ladder.observe(200.0)            # one hot observation: patience
    assert ladder.level == 0         # guards against flapping
    ladder.observe(200.0)
    assert ladder.level == 1         # cap_n_new
    ladder.observe(90.0)             # between exit and enter: hold
    ladder.observe(90.0)
    assert ladder.level == 1
    ladder.observe(200.0)
    ladder.observe(200.0)
    assert ladder.level == 2         # no_spec
    ladder.observe(50.0)
    assert ladder.level == 2         # one cool obs is not enough
    ladder.observe(50.0)
    assert ladder.level == 1
    ladder.observe(50.0)
    ladder.observe(50.0)
    assert ladder.level == 0


def test_retry_token_bucket_injected_clock():
    clock = {"t": 0.0}
    bucket = RetryTokenBucket(rate=2.0, burst=3,
                              clock=lambda: clock["t"])
    assert [bucket.take() for _ in range(3)] == [True] * 3
    assert bucket.take() is False    # burst exhausted, no time passed
    clock["t"] = 1.0                 # 2 tokens refilled
    assert bucket.take() and bucket.take()
    assert bucket.take() is False
    clock["t"] = 100.0               # refill caps at burst
    assert [bucket.take() for _ in range(3)] == [True] * 3
    assert bucket.take() is False


def test_governor_off_by_default_and_interactive_never_shed():
    assert governor_from_config() is None     # feature-off lock
    root.common.router.qos = True
    try:
        gov = governor_from_config()
        assert isinstance(gov, OverloadGovernor)
        # interactive is admitted whatever the controller thinks
        gov.aimd.rate = 0.0
        gov.ladder.level = 3                  # shed_batch
        assert gov.admit({"priority": "interactive"}) is None
        assert gov.admit({}) is None
        # batch is shed at the top rung, with a counted reason
        before = counters.get("veles_qos_throttled_total")
        assert gov.admit({"priority": "batch"}) is not None
        assert counters.get("veles_qos_throttled_total") \
            - before == 1
        snap = gov.snapshot()
        assert set(snap) == {"veles_qos_admit_rate",
                             "veles_qos_brownout_level",
                             "veles_qos_retry_tokens"}
    finally:
        root.common.router.qos = False


# -- scheduler: promotion + the per-ticket deadline sweep --------------------

def _queue_with(scheduler, reqs):
    tickets = [Ticket() for _ in reqs]
    for req, ticket in zip(reqs, tickets):
        scheduler.push(req, ticket)
    return tickets


def test_qos_promotion_and_fifo_when_off():
    reqs = [{"prompt": [1, 2], "n_new": 1, "priority": "batch",
             "tag": 0},
            {"prompt": [1, 2], "n_new": 1, "priority": "interactive",
             "tag": 1},
            {"prompt": [1, 2], "n_new": 1, "priority": "batch",
             "tag": 2},
            {"prompt": [1, 2], "n_new": 1, "priority": "interactive",
             "tag": 3}]
    # feature off (the default): strict FIFO, deferral counter silent
    sched = SlotScheduler(max_slots=4, buckets=(8,), max_context=16)
    before = counters.get("veles_qos_batch_deferrals_total")
    _queue_with(sched, [dict(r) for r in reqs])
    slots, expired = sched.take_admissions()
    assert not expired
    assert [s.req["tag"] for s in slots] == [0, 1, 2, 3]
    assert counters.get("veles_qos_batch_deferrals_total") == before
    # feature on: interactive jumps queued batch, stable per class
    sched = SlotScheduler(max_slots=4, buckets=(8,), max_context=16)
    sched.qos = True
    _queue_with(sched, [dict(r) for r in reqs])
    slots, expired = sched.take_admissions()
    assert not expired
    assert [s.req["tag"] for s in slots] == [1, 3, 0, 2]
    assert counters.get("veles_qos_batch_deferrals_total") > before


def test_deadline_sweep_honors_per_ticket_deadline():
    """Regression: the sweep must expire each ticket against ITS OWN
    deadline — a short-deadline request dies on time while the
    long-deadline co-tenant enqueued at the same instant survives."""
    # one slot but take_admissions is never called: both tickets wait
    # in the queue until the sweep runs
    sched = SlotScheduler(max_slots=1, buckets=(8,), max_context=8)
    now = time.time()
    short, long_ = Ticket(deadline=now - 0.1), \
        Ticket(deadline=now + 60.0)
    sched.push({"tag": "short"}, short)
    sched.push({"tag": "long"}, long_)
    with sched.cv:
        live, expired = split_expired(list(sched._queue))
        sched._queue.clear()
        sched._queue.extend(live)
    shed_expired(expired)
    assert [req["tag"] for req, _t in sched._queue] == ["long"]
    assert short.code == 503 and short.outcome == "expired"
    assert short.error is not None
    assert long_.error is None and not long_.event.is_set()


# -- the engines: preempt-and-resume bit-identical ---------------------------

@pytest.fixture(scope="module")
def paged_wf():
    lm = import_model("char_lm")
    prng.seed_all(1717)
    wf = lm.build_workflow(epochs=1, minibatch_size=32, n_blocks=1,
                           dim=32, n_train=64, n_valid=32)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    return lm, wf


@pytest.fixture(scope="module")
def lstm_wf():
    lm = import_model("char_lm")
    prng.seed_all(1718)
    wf = lm.build_workflow(epochs=1, minibatch_size=32, n_blocks=1,
                           dim=32, n_train=64, n_valid=32,
                           arch="lstm")
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    return lm, wf


def _drive(engine, done, limit=3000):
    """Tick the (never-started) engine until ``done()`` — manual step
    boundaries make the preemption point deterministic."""
    for _ in range(limit):
        if done():
            return True
        engine._tick()
    return done()


def _preempt_drill(engine, prompt_b, prompt_i, mode, temp):
    """Solo-decode a batch request for the reference, then re-run it
    under a mid-decode interactive arrival on a 1-slot pool; returns
    (expected, got, interactive_ticket, accounting deltas)."""
    req = make_request(prompt_b, 12, temperature=temp, seed=99,
                       mode=mode)
    req["priority"] = "batch"
    t_solo = Ticket()
    assert engine.submit(dict(req), t_solo)
    assert _drive(engine, t_solo.event.is_set)
    assert t_solo.error is None
    expected = t_solo.result["tokens"]

    e2e0 = histograms.count("veles_serving_e2e_seconds")
    qw0 = histograms.count("veles_serving_queue_wait_seconds")
    adm0 = counters.get("veles_serving_admitted_total")
    pre0 = counters.get("veles_qos_preemptions_total")
    t_b, t_i = Ticket(), Ticket()
    assert engine.submit(dict(req), t_b)

    def mid_decode():
        active = engine.scheduler.active()
        return bool(active and active[0].tokens
                    and active[0].prefilled is None
                    and len(active[0].tokens) < 8)
    assert _drive(engine, mid_decode, limit=200)
    req_i = make_request(prompt_i, 3)
    req_i["priority"] = "interactive"
    assert engine.submit(req_i, t_i)
    assert _drive(engine, lambda: t_b.event.is_set()
                  and t_i.event.is_set())
    assert t_i.error is None and t_b.error is None
    assert counters.get("veles_qos_preemptions_total") - pre0 >= 1
    deltas = (histograms.count("veles_serving_e2e_seconds") - e2e0,
              histograms.count("veles_serving_queue_wait_seconds")
              - qw0,
              int(counters.get("veles_serving_admitted_total")
                  - adm0))
    return expected, t_b.result["tokens"], t_i, deltas


@pytest.mark.parametrize("mode,temp", [("greedy", 0.0),
                                       ("sample", 0.9)])
def test_continuous_preempt_resume_bit_identical(paged_wf, mode,
                                                 temp):
    lm, wf = paged_wf
    rng = numpy.random.RandomState(5)
    prompt_b = [int(t) for t in rng.randint(0, lm.VOCAB, 6)]
    prompt_i = [int(t) for t in rng.randint(0, lm.VOCAB, 5)]
    root.common.serving.qos = True
    try:
        eng = ContinuousEngine(wf, max_slots=1, buckets=(8, 24),
                               max_context=48,
                               name="qos_cont_" + mode)
        expected, got, t_i, deltas = _preempt_drill(
            eng, prompt_b, prompt_i, mode, temp)
        # THE tentpole bar: preempted == uninterrupted, bit-identical
        assert got == expected
        assert len(t_i.result["tokens"]) == 3
        # exactly-once terminal accounting across
        # preempt -> requeue -> finish: 2 requests, 2 samples in
        # every per-request series, 2 admissions
        assert deltas == (2, 2, 2)
        assert eng.page_pool.in_use() == 0   # ledger drained
    finally:
        root.common.serving.qos = False


@pytest.mark.parametrize("mode,temp", [("greedy", 0.0),
                                       ("sample", 0.9)])
def test_recurrent_preempt_resume_bit_identical(lstm_wf, mode, temp):
    lm, wf = lstm_wf
    rng = numpy.random.RandomState(6)
    prompt_b = [int(t) for t in rng.randint(0, lm.VOCAB, 6)]
    prompt_i = [int(t) for t in rng.randint(0, lm.VOCAB, 5)]
    root.common.serving.qos = True
    try:
        eng = RecurrentEngine(wf, max_slots=1, max_context=48,
                              page_size=8, name="qos_rec_" + mode)
        expected, got, t_i, deltas = _preempt_drill(
            eng, prompt_b, prompt_i, mode, temp)
        assert got == expected
        assert len(t_i.result["tokens"]) == 3
        assert deltas == (2, 2, 2)
    finally:
        root.common.serving.qos = False


def test_feature_off_lock_no_qos_counters(paged_wf):
    """With every knob off (the default), a mixed-priority load moves
    ZERO QoS counters and admits strictly FIFO — the QoS-off plane is
    the PR 16 plane."""
    lm, wf = paged_wf
    rng = numpy.random.RandomState(7)
    before = {name: counters.get(name) for name in QOS_COUNTERS}
    eng = ContinuousEngine(wf, max_slots=2, buckets=(8,),
                           max_context=24, name="qos_off")
    assert eng.qos is False and eng.scheduler.qos is False
    reqs, tickets = [], []
    for i in range(4):
        req = make_request(
            [int(t) for t in rng.randint(0, lm.VOCAB, 5)], 3)
        req["priority"] = "batch" if i % 2 else "interactive"
        ticket = Ticket()
        assert eng.submit(req, ticket)
        reqs.append(req)
        tickets.append(ticket)
    assert _drive(eng, lambda: all(t.event.is_set()
                                   for t in tickets))
    for ticket in tickets:
        assert ticket.error is None
    assert eng.preemptions == 0
    for name in QOS_COUNTERS:
        assert counters.get(name) == before[name], name
