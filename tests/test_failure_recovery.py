"""Failure story, wired end-to-end (SURVEY.md §5.3).

The reference ran its failure machinery in production paths: the server's
job-timeout dropper (veles/server.py:619-635), the client's random-death
fault injection (veles/client.py:303-307,438-442), and snapshot-based
disaster recovery. These tests assert the TPU build's equivalents are
actually ARMED by the launcher — not just importable library functions:

- Launcher wraps every TrainStep dispatch in the hang watchdog;
- --slave-death-probability kills a real training subprocess mid-run;
- rerunning the same command auto-resumes from the newest snapshot and
  completes with sane metrics (kill-and-resume integration).
"""
import json
import os
import subprocess
import sys
import textwrap

import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn
from veles_tpu.config import root
from veles_tpu.launcher import Launcher
from veles_tpu.loader import FullBatchLoader

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TinyLoader(FullBatchLoader):
    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(3)
        self.create_originals(
            rng.rand(150, 8).astype(numpy.float32),
            rng.randint(0, 3, 150).astype(numpy.int32))
        self.class_lengths = [0, 30, 120]


def _workflow(snapshotter=None, **decision_kw):
    return nn.StandardWorkflow(
        name="failure-wiring",
        layers=[{"type": "softmax", "output_sample_shape": 3}],
        loader_unit=TinyLoader(None, minibatch_size=24, name="l"),
        loss_function="softmax",
        decision_config=dict(max_epochs=2, **decision_kw),
        snapshotter_unit=snapshotter)


def test_launcher_arms_watchdog():
    """Every dispatch must be timed into the launcher's step history —
    proof the watchdog context manager wraps the production run path."""
    launcher = Launcher(backend="cpu")
    launcher.initialize(_workflow())
    step = launcher.workflow.train_step
    assert getattr(step, "_failure_hooks_armed", False)
    launcher.run()
    # 2 epochs × (1 train + 1 valid dispatch) = 4 watchdog'd dispatches
    assert len(launcher.step_history) >= 4
    assert all(t >= 0 for t in launcher.step_history)


def test_try_restore_latest(tmp_path):
    """Launcher-level elastic restart: newest snapshot in the workflow's
    snapshot dir is applied, decision reopened."""
    snap = vt.Snapshotter(None, prefix="rec", directory=str(tmp_path))
    wf = _workflow(snapshotter=snap)
    launcher = Launcher(backend="cpu")
    launcher.initialize(wf)
    launcher.run()
    assert wf.decision.epoch_number == 2

    snap2 = vt.Snapshotter(None, prefix="rec", directory=str(tmp_path))
    wf2 = _workflow(snapshotter=snap2)
    launcher2 = Launcher(backend="cpu")
    launcher2.initialize(wf2)
    assert launcher2.try_restore_latest() is True
    assert wf2.decision.epoch_number == 2
    assert not bool(wf2.decision.complete)


def test_try_restore_latest_empty_dir(tmp_path):
    snap = vt.Snapshotter(None, prefix="rec", directory=str(tmp_path))
    wf = _workflow(snapshotter=snap)
    launcher = Launcher(backend="cpu")
    launcher.initialize(wf)
    assert launcher.try_restore_latest() is False


# -- subprocess integration: kill and resume --------------------------------

MODEL_SRC = textwrap.dedent("""
    import os
    import numpy
    import veles_tpu as vt
    from veles_tpu import nn
    from veles_tpu.loader import FullBatchLoader

    class L(FullBatchLoader):
        hide_from_registry = True
        def load_data(self):
            rng = numpy.random.RandomState(3)
            centers = rng.randn(3, 8) * 3
            y = rng.randint(0, 3, 300).astype(numpy.int32)
            x = (centers[y] + rng.randn(300, 8)).astype(numpy.float32)
            self.create_originals(x, y)
            self.class_lengths = [0, 60, 240]

    def build_workflow():
        snap = vt.Snapshotter(None, prefix="rec")
        return nn.StandardWorkflow(
            name="recovery",
            layers=[
                {"type": "all2all_tanh", "output_sample_shape": 8},
                {"type": "softmax", "output_sample_shape": 3},
            ],
            loader_unit=L(None, minibatch_size=24, name="l"),
            loss_function="softmax",
            decision_config=dict(
                max_epochs=int(os.environ.get("MAX_EPOCHS", "4")),
                fail_iterations=100),
            snapshotter_unit=snap)
""")


def _run_cli(model, *argv, env_extra=None, timeout=240):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, "-m", "veles_tpu", str(model), *argv,
         "--backend", "cpu", "-v"],
        cwd=REPO, capture_output=True, text=True, timeout=timeout, env=env)


@pytest.fixture(scope="module")
def recovery_model(tmp_path_factory):
    path = tmp_path_factory.mktemp("rec") / "recovery_model.py"
    path.write_text(MODEL_SRC)
    return path


def test_fault_injection_kills_process(recovery_model, tmp_path):
    """--slave-death-probability 1.0: the process must die with the
    fault-injection exit code (42) instead of completing."""
    r = _run_cli(recovery_model, "--snapshot-dir", str(tmp_path),
                 "--slave-death-probability", "1.0", "--random-seed", "5")
    assert r.returncode == 42, (r.returncode, r.stderr[-2000:])
    assert "fault injection" in r.stderr


def test_kill_and_resume_completes(recovery_model, tmp_path):
    """The elastic-restart loop: run with random fault injection until the
    job completes; every relaunch must pick up the newest snapshot. Seeds
    are fixed per attempt (varying across attempts, as real restarts do),
    so the whole trajectory is reproducible."""
    res = tmp_path / "r.json"
    deaths = resumes = 0
    final = None
    for attempt in range(10):
        r = _run_cli(recovery_model, "--snapshot-dir", str(tmp_path),
                     "--slave-death-probability", "0.3",
                     "--random-seed", str(7 + attempt),
                     "--result-file", str(res))
        if "auto-resumed" in r.stderr:
            resumes += 1
        if r.returncode == 42:
            deaths += 1
            continue
        assert r.returncode == 0, r.stderr[-2000:]
        final = json.loads(res.read_text())
        break
    assert final is not None, "never completed in 10 attempts"
    assert deaths >= 1, "fault injection never fired (p=0.3, seeded)"
    assert resumes >= 1, "no relaunch ever auto-resumed"
    assert final["epochs"] >= 4
    assert final["best_err"] < 0.2


def test_profiler_trace_capture(tmp_path):
    """--profile-dir writes an XPlane trace of the run (SURVEY.md §5.1:
    the reference's Mongo event spans map to jax profiler traces)."""
    launcher = Launcher(backend="cpu", profile_dir=str(tmp_path))
    launcher.initialize(_workflow())
    launcher.run()
    import glob as _glob
    traces = _glob.glob(str(tmp_path / "**" / "*.xplane.pb"),
                        recursive=True)
    assert traces, list(tmp_path.rglob("*"))
