"""Heterogeneous pipeline parallelism (VERDICT r2 weak #6 upgraded).

The uniform gpipe planner refuses shape-changing chains; these tests
cover the fallback: ``plan_pipeline_hetero`` stage-groups the full
conv→pool→dense chain by cost, and ``gpipe_hetero`` runs it with
``lax.switch`` per stage over a padded ppermute wire. Asserted:
- the plan forms (contiguous balanced groups; params stay per-unit);
- training matches the plain 1-device run of the same seed (the
  equivalence claim), and composes with a 'data' axis;
- snapshots move freely between hetero-pipeline and plain meshes
  (per-unit params: nothing to restack);
- a chain shorter than the axis still refuses loudly.
"""
import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn, prng
from veles_tpu.error import Bug
from veles_tpu.loader import FullBatchLoader, VALID
from veles_tpu.parallel.pipeline import plan_pipeline_hetero


class TinyImagesLoader(FullBatchLoader):
    """Synthetic separable 8x8x1 images: class c lights up row c."""
    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(11)
        n_per, k = 80, 3
        data, labels = [], []
        for c in range(k):
            imgs = rng.randn(n_per, 8, 8, 1).astype(numpy.float32) * 0.3
            imgs[:, 2 * c + 1, :, 0] += 2.0
            data.append(imgs)
            labels.append(numpy.full(n_per, c, numpy.int32))
        data = numpy.concatenate(data)
        labels = numpy.concatenate(labels)
        perm = rng.permutation(len(data))
        self.create_originals(data[perm], labels[perm])
        self.class_lengths = [0, 60, 180]


def make_workflow(epochs=6, microbatches=None):
    """conv → pool → activation → dense → head: every hop changes the
    activation shape, so the uniform planner has no viable block."""
    loader = TinyImagesLoader(None, minibatch_size=24, name="timg")
    layers = [
        {"type": "conv", "n_kernels": 4, "kx": 3, "ky": 3,
         "padding": (1, 1, 1, 1), "name": "c0"},
        {"type": "max_pooling", "kx": 2, "ky": 2, "name": "p0"},
        {"type": "activation_str", "name": "a0"},
        {"type": "all2all_tanh", "output_sample_shape": 16,
         "name": "fc0"},
        {"type": "softmax", "output_sample_shape": 3, "name": "head"},
    ]
    return nn.StandardWorkflow(
        name="pp-hetero", layers=layers, loader_unit=loader,
        loss_function="softmax",
        decision_config=dict(max_epochs=epochs, fail_iterations=100),
        pipeline_microbatches=microbatches)


def _run(mesh_axes, epochs=6, **kw):
    prng.seed_all(1717)
    wf = make_workflow(epochs=epochs, **kw)
    wf.initialize(device=vt.XLADevice(mesh_axes=mesh_axes))
    wf.run()
    return wf


def test_hetero_plan_forms():
    prng.seed_all(1717)
    wf = make_workflow()
    wf.initialize(device=vt.XLADevice(mesh_axes={"pipeline": 4}))
    step = wf.train_step
    assert step._pp is None
    pp = step._pp_hetero
    assert pp is not None
    names = [[f.name for f in s] for s in pp["stages"]]
    # contiguous cover of the chain minus the head, every stage nonempty
    assert [n for grp in names for n in grp] == ["c0", "p0", "a0", "fc0"]
    assert all(grp for grp in names)
    assert [f.name for f in pp["post"]] == ["head"]
    # params stay per-unit — nothing stacked, nothing renamed
    assert set(step.params) == {"c0", "fc0", "head"}


def test_hetero_balance_dp():
    """The linear-partition DP puts the split where the max stage cost
    is minimal: for costs [8, 1, 1, 8] over 2 stages the optimum is
    [8,1] | [1,8] (max 9); the 1|3 and 3|1 splits both cost 10."""
    class U:
        PARAMETERIZED = False
        output = None

        def __init__(self, c):
            self._c = c

    import veles_tpu.parallel.pipeline as pl
    units = [U(8), U(1), U(1), U(8)]
    orig = pl.stage_cost
    pl.stage_cost = lambda f: float(f._c)
    try:
        groups = plan_pipeline_hetero(units, 2)
    finally:
        pl.stage_cost = orig
    assert [len(g) for g in groups] == [2, 2]  # [8,1] | [1,8], max 9


def test_gpipe_hetero_matches_sequential_composition():
    """Schedule-level property: for a random shape-changing chain of
    dense stages, gpipe_hetero's outputs AND parameter gradients must
    match plain sequential composition (the schedule is pure
    reordering). Exercises padding (widths 12→20→6→14), switch
    branching, and the replicated-param transpose psum."""
    import jax
    import jax.numpy as jnp
    from veles_tpu.parallel.pipeline import gpipe_hetero
    from jax.sharding import Mesh

    widths = [12, 20, 6, 14]
    rng = numpy.random.RandomState(2)
    ws = [jnp.asarray(rng.randn(widths[i], widths[i + 1]) * 0.3,
                      jnp.float32) for i in range(3)]
    # last stage has no params: pure nonlinearity (stage_params = {})
    fns = [lambda p, x: jnp.tanh(x @ p["w"]) for _ in range(3)]
    fns.append(lambda p, x: jnp.tanh(x) * 2.0)
    params = [{"w": w} for w in ws] + [{}]
    m, mb = 8, 4
    xs = jnp.asarray(rng.randn(m, mb, widths[0]), jnp.float32)

    devices = numpy.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devices, ("pipeline",))

    def piped(params, xs):
        return gpipe_hetero(fns, params, xs, mesh)

    def sequential(params, xs):
        y = xs.reshape((-1,) + xs.shape[2:])
        for fn, p in zip(fns, params):
            y = fn(p, y)
        return y.reshape((m, mb) + y.shape[1:])

    y_pp = piped(params, xs)
    y_seq = sequential(params, xs)
    numpy.testing.assert_allclose(numpy.asarray(y_pp),
                                  numpy.asarray(y_seq), rtol=2e-6,
                                  atol=2e-6)

    def loss_pp(params):
        return (piped(params, xs) ** 2).sum()

    def loss_seq(params):
        return (sequential(params, xs) ** 2).sum()

    g_pp = jax.grad(loss_pp)(params)
    g_seq = jax.grad(loss_seq)(params)
    for gp, gs in zip(g_pp, g_seq):
        for k in gs:
            numpy.testing.assert_allclose(
                numpy.asarray(gp[k]), numpy.asarray(gs[k]),
                rtol=5e-5, atol=5e-5)


def test_hetero_matches_plain_run():
    import jax
    plain = _run({"data": 1})
    pp = _run({"pipeline": 4})
    e1 = numpy.asarray(plain.decision.epoch_metrics[VALID])
    e2 = numpy.asarray(pp.decision.epoch_metrics[VALID])
    assert e1.shape == e2.shape == (6,)
    numpy.testing.assert_allclose(e2, e1, atol=0.04)
    assert pp.decision.best_metric < 0.15
    w1 = jax.device_get(plain.train_step.params["c0"]["weights"])
    w2 = jax.device_get(pp.train_step.params["c0"]["weights"])
    numpy.testing.assert_allclose(numpy.asarray(w2), numpy.asarray(w1),
                                  rtol=2e-3, atol=2e-4)


def test_hetero_with_data_axis():
    wf = _run({"pipeline": 2, "data": 2}, epochs=4)
    assert wf.train_step._pp_hetero is not None
    assert wf.decision.best_metric < 0.2


def test_hetero_snapshot_roundtrip(tmp_path):
    """Per-unit params mean hetero checkpoints ARE plain checkpoints:
    resume into a plain mesh and continue."""
    import jax
    wf = _run({"pipeline": 4}, epochs=3)
    snap = vt.Snapshotter(None, prefix="pph", directory=str(tmp_path))
    snap.workflow = wf
    path = snap.export()
    assert path
    prng.seed_all(31)
    wf2 = make_workflow(epochs=6)
    wf2.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    vt.resume(wf2, path)
    assert wf2.decision.epoch_number == 3
    w_pp = jax.device_get(wf.train_step.params["fc0"]["weights"])
    w_plain = jax.device_get(wf2.train_step.params["fc0"]["weights"])
    numpy.testing.assert_allclose(numpy.asarray(w_plain),
                                  numpy.asarray(w_pp), rtol=1e-6)


def test_cifar_model_takes_hetero_pipeline(monkeypatch):
    """The flagship conv stack (caffe cifar10_quick: conv→pool→act→
    conv→pool→conv→pool→fc→softmax — exactly the AlexNet-era shape
    VERDICT r2 said could not take a pipeline axis) trains through
    {'pipeline': 2, 'data': 2} via the hetero schedule, through the
    models/ zoo builder, not a bespoke toy."""
    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "models"))
    from veles_tpu import datasets
    from test_models_ci import _synthetic_images, _import_model
    prng.seed_all(88)
    monkeypatch.setattr(
        datasets, "load_cifar10",
        lambda n_train=50000, n_test=10000: _synthetic_images(
            (16, 16, 3), 10, 480, 120, flat=False, key="cifar10"))
    cifar = _import_model("cifar")
    wf = cifar.build_workflow(epochs=3, minibatch_size=60, lr=0.05)
    wf.initialize(device=vt.XLADevice(
        mesh_axes={"pipeline": 2, "data": 2}))
    step = wf.train_step
    assert step._pp is None
    assert step._pp_hetero is not None
    assert len(step._pp_hetero["stages"]) == 2
    wf.run()
    res = wf.gather_results()
    assert res["epochs"] == 3
    assert res["best_err"] < 0.9        # moving off chance proves the
    #                                     staged chain trains at all


def test_imagenet_ae_takes_hetero_pipeline(monkeypatch):
    """The conv-AE (encoder conv→pool→conv, decoder depool→deconv — the
    ImagenetAE shape) trains through {'pipeline': 2} via the hetero
    schedule with the deconv head replicated after the staged region;
    reconstruction RMSE must fall."""
    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "models"))
    from veles_tpu import datasets
    from test_models_ci import _synthetic_images, _import_model
    prng.seed_all(55)
    monkeypatch.setattr(
        datasets, "load_cifar10",
        lambda n_train=50000, n_test=10000: _synthetic_images(
            (16, 16, 3), 10, 240, 60, flat=False, key="cifar10"))
    ae = _import_model("imagenet_ae")
    wf = ae.build_workflow(epochs=3, minibatch_size=30, lr=0.02)
    wf.initialize(device=vt.XLADevice(mesh_axes={"pipeline": 2}))
    step = wf.train_step
    assert step._pp is None
    assert step._pp_hetero is not None
    wf.run()
    res = wf.gather_results()
    hist = res["rmse_history"]["validation"]
    assert hist[-1] < hist[0], hist


def test_hetero_composes_with_amp_and_remat():
    """The two throughput/memory knobs must ride through the hetero
    schedule: AMP casts params+batch to bf16 (stage dtype check passes
    because every stage sees bf16), remat wraps the whole pipelined
    forward in jax.checkpoint. Training must still converge."""
    from veles_tpu.config import root
    root.common.engine.mixed_precision = True
    try:
        prng.seed_all(909)
        loader = TinyImagesLoader(None, minibatch_size=24,
                                  name="timg-amp")
        wf = nn.StandardWorkflow(
            name="pp-amp", layers=[
                {"type": "conv", "n_kernels": 4, "kx": 3, "ky": 3,
                 "padding": (1, 1, 1, 1)},
                {"type": "max_pooling", "kx": 2, "ky": 2},
                {"type": "activation_str"},
                {"type": "all2all_tanh", "output_sample_shape": 16},
                {"type": "softmax", "output_sample_shape": 3}],
            loader_unit=loader, loss_function="softmax",
            decision_config=dict(max_epochs=6, fail_iterations=100),
            remat=True)
        wf.initialize(device=vt.XLADevice(mesh_axes={"pipeline": 4}))
        assert wf.train_step._pp_hetero is not None
        assert wf.train_step.mixed_precision
        wf.run()
        assert wf.decision.best_metric < 0.25
    finally:
        root.common.engine.mixed_precision = False


def test_pipeline_sequence_axes_refuse_to_compose():
    """pp x sp nests two manual shard_maps (ring attention inside the
    pipelined region) — XLA's raw error is an opaque context-mesh
    mismatch; the plan must name the real reason at initialize time."""
    loader = TinyImagesLoader(None, minibatch_size=24, name="timg-ps")
    wf = nn.StandardWorkflow(
        name="pp-sp-refuse",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 8},
                {"type": "all2all_tanh", "output_sample_shape": 8},
                {"type": "softmax", "output_sample_shape": 3}],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=1))
    with pytest.raises(Bug, match="sequence"):
        wf.initialize(device=vt.XLADevice(
            mesh_axes={"pipeline": 2, "sequence": 2}))


def test_hetero_short_chain_refuses():
    """A chain shorter than the pipeline axis has no viable hetero plan
    either — the refusal must stay loud."""
    loader = TinyImagesLoader(None, minibatch_size=24, name="timg-s")
    wf = nn.StandardWorkflow(
        name="pp-short",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 8},
                {"type": "softmax", "output_sample_shape": 3}],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=1))
    with pytest.raises(Bug, match="pipeline"):
        wf.initialize(device=vt.XLADevice(mesh_axes={"pipeline": 4}))
