"""All2All variants (rprop_all / resizable_all) and the mcdnnic topology
shorthand (Znicz parity, SURVEY.md §2.8)."""
import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn
from veles_tpu.error import VelesError
from veles_tpu.loader import FullBatchLoader
from veles_tpu.memory import Array
from veles_tpu.nn.standard_workflow import parse_mcdnnic


def dev():
    return vt.XLADevice(mesh_axes={"data": 1})


def test_rprop_rule_sign_dynamics():
    import jax.numpy as jnp
    wf = vt.Workflow(name="t")
    gd = nn.GDRProp(wf, initial_step=0.1)
    params = {"weights": jnp.asarray([[1.0, 1.0]])}
    state = gd.init_state(params)
    g1 = {"weights": jnp.asarray([[0.5, -0.5]])}
    p1, s1 = gd.update(params, g1, state)
    # first step: move by initial step against the gradient sign
    numpy.testing.assert_allclose(numpy.asarray(p1["weights"]),
                                  [[0.9, 1.1]], rtol=1e-6)
    # same sign → step grows ×1.2
    p2, s2 = gd.update(p1, g1, s1)
    numpy.testing.assert_allclose(numpy.asarray(s2["step"]["weights"]),
                                  [[0.12, 0.12]], rtol=1e-6)
    # sign flip → step shrinks ×0.5 and no move this round
    g3 = {"weights": jnp.asarray([[-0.5, 0.5]])}
    p3, s3 = gd.update(p2, g3, s2)
    numpy.testing.assert_allclose(numpy.asarray(p3["weights"]),
                                  numpy.asarray(p2["weights"]), rtol=1e-6)
    numpy.testing.assert_allclose(numpy.asarray(s3["step"]["weights"]),
                                  [[0.06, 0.06]], rtol=1e-6)


def test_rprop_trains_end_to_end():
    class XorishLoader(FullBatchLoader):
        hide_from_registry = True

        def load_data(self):
            rng = numpy.random.RandomState(0)
            x = rng.rand(256, 8).astype(numpy.float32)
            y = (x[:, 0] > x[:, 1]).astype(numpy.int32)
            self.create_originals(x, y)
            self.class_lengths = [0, 64, 192]

    loader = XorishLoader(None, minibatch_size=32)
    wf = nn.StandardWorkflow(
        name="rprop",
        layers=[{"type": "rprop_all2all", "output_sample_shape": 16},
                {"type": "softmax", "output_sample_shape": 2}],
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=15))
    wf.initialize(device=dev())
    assert any(isinstance(g, nn.GDRProp) for g in wf.train_step.gds)
    wf.run()
    res = wf.gather_results()
    assert res["best_err"] < 0.35, res


def test_resizable_all2all_preserves_trained_slice():
    wf = vt.Workflow(name="t")
    u = nn.ResizableAll2All(wf, output_sample_shape=4)
    x = numpy.random.RandomState(0).rand(6, 5).astype(numpy.float32)
    u.input = Array(x)
    u.initialize(device=dev())
    w_before = numpy.array(u.weights.map_read())
    y_before = u.numpy_apply(u.params_np(), x)
    u.resize(7)
    assert u.weights.shape == (5, 7)
    numpy.testing.assert_allclose(
        numpy.asarray(u.weights.map_read())[:, :4], w_before)
    y_after = u.numpy_apply(u.params_np(), x)
    numpy.testing.assert_allclose(y_after[:, :4], y_before, rtol=1e-5)
    u.xla_run()         # device path works after resize
    numpy.testing.assert_allclose(numpy.asarray(u.output.map_read()),
                                  y_after, rtol=1e-4, atol=1e-5)
    u.resize(2)         # shrink too
    assert u.weights.shape == (5, 2)
    numpy.testing.assert_allclose(
        numpy.asarray(u.weights.map_read()), w_before[:, :2])


def test_parse_mcdnnic():
    layers = parse_mcdnnic("28x28-8C3-MP2-32N-10N",
                           {"learning_rate": 0.05})
    assert [l["type"] for l in layers] == [
        "conv_tanh", "max_pooling", "all2all_tanh", "softmax"]
    assert layers[0]["n_kernels"] == 8 and layers[0]["kx"] == 3
    assert layers[1]["kx"] == 2
    assert layers[2]["output_sample_shape"] == 32
    assert layers[3]["output_sample_shape"] == 10
    assert all(l["learning_rate"] == 0.05 for l in layers)
    with pytest.raises(VelesError):
        parse_mcdnnic("28x28-whoops")
    with pytest.raises(VelesError):
        parse_mcdnnic("justinput")


def test_mcdnnic_workflow_builds_and_trains():
    class TinyImages(FullBatchLoader):
        hide_from_registry = True

        def load_data(self):
            rng = numpy.random.RandomState(0)
            x = rng.rand(128, 8, 8, 1).astype(numpy.float32)
            y = (x.mean(axis=(1, 2, 3)) > 0.5).astype(numpy.int32)
            self.create_originals(x, y)
            self.class_lengths = [0, 32, 96]

    loader = TinyImages(None, minibatch_size=32)
    wf = nn.StandardWorkflow(
        name="mcdnnic",
        mcdnnic_topology="8x8-4C3-MP2-16N-2N",
        mcdnnic_parameters={"learning_rate": 0.1},
        loader_unit=loader, loss_function="softmax",
        decision_config=dict(max_epochs=3))
    wf.initialize(device=dev())
    types = [type(f).MAPPING for f in wf.forwards]
    assert types == ["conv_tanh", "max_pooling", "all2all_tanh",
                     "softmax"]
    wf.run()
    assert wf.gather_results()["epochs"] >= 3
    with pytest.raises(VelesError):
        nn.StandardWorkflow(layers=[{"type": "softmax"}],
                            mcdnnic_topology="8x8-2N",
                            loader_unit=None)
