"""Fault-tolerant serving fleet (veles_tpu/serving/router.py): the
replica router with health-gated failover, idempotent retry, graceful
drain, and supervised respawn.

The contract under test: the router routes to the least-occupied READY
replica and never to a not-ready/draining one; consecutive attempt
failures open a per-replica circuit breaker riding RetryPolicy's
seeded backoff (half-open probes close it); a request that dies
mid-decode is retried on another replica keyed on its request_id with
EXACTLY-ONCE response accounting (a slow-then-successful first attempt
can never double-answer); SIGTERM / POST /drain stop admission, flip
/readyz to draining, finish in-flight tickets and exit 0; and the
ReplicaSupervisor respawns dead replicas while the router routes
around the hole — driven by the registered serve.replica_death /
router.replica_request fault points, no ad-hoc monkeypatching.

Budget discipline: everything above the chaos drill is jax-free (fake
HTTP replicas, fake clocks); the drill itself uses one tiny char_lm
workflow shared across its replicas.
"""
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy
import pytest

import veles_tpu as vt
from veles_tpu.resilience import health
from veles_tpu.resilience.retry import RetryPolicy
from veles_tpu.serving.router import (CircuitBreaker, FleetRouter,
                                      ReplicaSupervisor, _Answer,
                                      normalize_endpoint)
from veles_tpu.telemetry.counters import counters
from veles_tpu.telemetry.fleet import read_endpoints

from conftest import import_model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _post(url, payload, timeout=30.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _get(url, timeout=10.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


# -- endpoint + config parsing (no jax, no HTTP) ------------------------------

def test_normalize_endpoint_forms():
    assert normalize_endpoint("127.0.0.1:8080") \
        == "http://127.0.0.1:8080"
    assert normalize_endpoint("http://h:1/") == "http://h:1"
    # the scrape-roster spelling is accepted: routing and metrics
    # aggregation share one endpoint list
    assert normalize_endpoint("h:1/metrics") == "http://h:1"
    assert normalize_endpoint("https://h:1/metrics") == "https://h:1"


def test_router_rejects_empty_and_duplicate_rosters():
    from veles_tpu.error import VelesError
    with pytest.raises(VelesError):
        FleetRouter([])
    with pytest.raises(VelesError):
        FleetRouter(["h:1", "http://h:1"])


def test_read_endpoints_plain_lines(tmp_path):
    f = tmp_path / "fleet.txt"
    f.write_text("# the fleet\n127.0.0.1:1\n\nhttp://h:2  # replica\n")
    assert read_endpoints(str(f)) == ["127.0.0.1:1", "http://h:2"]


def test_read_endpoints_json_forms(tmp_path):
    f = tmp_path / "fleet.json"
    f.write_text(json.dumps(["h:1", "h:2"]))
    assert read_endpoints(str(f)) == ["h:1", "h:2"]
    # the router's GET /roster output saved to disk feeds the same
    # reader — fleet scraping and routing share one roster format
    f.write_text(json.dumps({"router": "r", "endpoints": [
        {"url": "http://h:1", "ready": True}, "h:2"]}))
    assert read_endpoints(str(f)) == ["http://h:1", "h:2"]
    f.write_text(json.dumps({"endpoints": [{"ready": True}]}))
    with pytest.raises(ValueError):
        read_endpoints(str(f))


# -- circuit breaker (fake clock, pinned backoff) -----------------------------

def _breaker(threshold=2, base=1.0):
    clock = {"t": 0.0}
    policy = RetryPolicy(base_delay=base, max_delay=8 * base,
                         jitter=False, name="t")
    return CircuitBreaker(failure_threshold=threshold, backoff=policy,
                          clock=lambda: clock["t"]), clock


def test_breaker_opens_at_threshold_and_backs_off():
    br, clock = _breaker(threshold=2, base=1.0)
    assert br.allow()
    assert br.record_failure() is False          # 1 of 2
    assert br.state == CircuitBreaker.CLOSED
    assert br.record_failure() is True           # threshold: OPEN
    assert br.state == CircuitBreaker.OPEN
    assert br.open_until == pytest.approx(1.0)   # backoff(1) = base
    assert not br.allow()                        # open: refused
    clock["t"] = 1.5
    assert br.allow()                            # half-open probe
    assert br.state == CircuitBreaker.HALF_OPEN
    assert not br.allow()                        # ONE probe at a time
    br.record_success()                          # probe succeeded
    assert br.state == CircuitBreaker.CLOSED
    assert br.allow()


def test_breaker_half_open_failure_reopens_longer():
    br, clock = _breaker(threshold=1, base=1.0)
    assert br.record_failure() is True           # trip 1: open 1s
    clock["t"] = 2.0
    assert br.allow()                            # half-open
    assert br.record_failure() is True           # probe failed: re-open
    assert br.state == CircuitBreaker.OPEN
    # trip 2 backs off harder: backoff(2) = base * 2
    assert br.open_until == pytest.approx(2.0 + 2.0)
    clock["t"] = 3.0
    assert not br.allow()
    # success resets the whole curve, not just the state
    clock["t"] = 10.0
    assert br.allow()
    br.record_success()
    assert br.trips == 0 and br.failures == 0


# -- the exactly-once answer latch --------------------------------------------

def test_answer_latch_first_offer_wins():
    a = _Answer()
    assert a.offer(200, {"tokens": [1]}) is True
    assert a.offer(200, {"tokens": [2]}) is False   # duplicate dropped
    assert a.body == {"tokens": [1]}
    assert a.done and a.status == 200


# -- supervised respawn (fake handles, fake clock) ----------------------------

class _FakeHandle:
    def __init__(self):
        self.code = None

    def poll(self):
        return self.code


def _supervisor(n=2, max_respawns=2, base=1.0):
    clock = {"t": 0.0}
    spawned = []
    handles = {}

    def spawn(i, incarnation):
        spawned.append((i, incarnation))
        handles[i] = _FakeHandle()
        return handles[i]

    sup = ReplicaSupervisor(
        spawn, n, max_respawns=max_respawns,
        backoff=RetryPolicy(base_delay=base, max_delay=8 * base,
                            jitter=False, name="t"),
        clock=lambda: clock["t"], name="t")
    # spawn without the watch thread — tests drive check() directly
    with sup._lock:
        for i in range(n):
            sup._spawn_one(i)
    return sup, clock, spawned, handles


def test_supervisor_respawns_death_after_backoff():
    sup, clock, spawned, handles = _supervisor()
    before = counters.get("veles_router_respawns_total")
    assert sup.alive() == 2
    handles[0].code = 42                         # death (crash code)
    events = sup.check()
    assert any("died" in e for e in events)
    assert sup.alive() == 1
    # the respawn waits out the backoff (incarnation 1 -> base delay)
    assert sup.check() == []
    clock["t"] = 1.1
    events = sup.check()
    assert any("respawned replica 0" in e for e in events)
    assert sup.alive() == 2
    assert spawned == [(0, 1), (1, 1), (0, 2)]
    assert counters.get("veles_router_respawns_total") - before == 1


def test_supervisor_clean_exit_stays_down():
    sup, clock, spawned, handles = _supervisor()
    handles[1].code = 0                          # drained on purpose
    events = sup.check()
    assert any("cleanly" in e for e in events)
    clock["t"] = 100.0
    assert sup.check() == []                     # never respawned
    assert sup.stopped[1] and sup.alive() == 1


def test_supervisor_gives_up_after_max_respawns():
    sup, clock, spawned, handles = _supervisor(max_respawns=2)
    for _ in range(2):
        handles[0].code = 1
        sup.check()
        clock["t"] += 100.0
        sup.check()                              # respawn
    handles[0].code = 1                          # third death
    events = sup.check()
    assert any("giving up" in e for e in events)
    assert sup.given_up[0]
    clock["t"] += 100.0
    assert sup.check() == []                     # stays down
    assert sup.incarnations[0] == 3


# -- routing over fake HTTP replicas (no jax) ---------------------------------

def _fake_replica(state=None):
    """A GenerationAPI-shaped fake: POST /generate answers with the
    request_id echoed (optionally after ``delay``), GET /readyz +
    /metrics render the knobs in ``state`` — the router's whole
    probe/admission surface without a model."""
    state = dict({"ready": True, "draining": False, "dead": False,
                  "slots": 4, "busy": 0, "delay": 0.0,
                  "served": [], "status_code": 200}, **(state or {}))

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.path == "/readyz":
                ok = state["ready"] and not state["draining"]
                payload = {"status": ("ok" if ok else
                                      "draining" if state["draining"]
                                      else "not ready")}
                self._reply(200 if ok else 503, payload)
            elif self.path == "/metrics":
                text = (
                    "# TYPE veles_serving_slots gauge\n"
                    "veles_serving_slots %d\n"
                    "# TYPE veles_serving_slots_busy gauge\n"
                    "veles_serving_slots_busy %d\n"
                    "# TYPE veles_serving_queue_depth gauge\n"
                    "veles_serving_queue_depth 0\n"
                    % (state["slots"], state["busy"]))
                body = text.encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_error(404)

        def do_POST(self):
            if state["dead"]:
                # a crashed replica from the client's view: the
                # connection dies without a response
                self.close_connection = True
                return
            n = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(n) or b"{}")
            if state["delay"]:
                time.sleep(state["delay"])
            state["served"].append(req.get("request_id"))
            code = state["status_code"]
            if code >= 400:
                self._reply(code, {"error": "replica unhappy",
                                   "request_id": req.get("request_id")})
                return
            self._reply(200, {"tokens": [1, 2, 3],
                              "request_id": req.get("request_id"),
                              "port": self.server.server_port})

        def _reply(self, code, payload):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, state


@pytest.fixture
def fake_fleet():
    a_srv, a = _fake_replica({"busy": 3})
    b_srv, b = _fake_replica({"busy": 0})
    router = None
    try:
        router = FleetRouter(
            ["127.0.0.1:%d" % a_srv.server_port,
             "127.0.0.1:%d" % b_srv.server_port],
            probe_interval=0.1, probe_timeout=2.0,
            failure_threshold=2, retry_budget=2,
            attempt_timeout=5.0, request_timeout=20.0,
            name="test_router").start()
        yield router, (a_srv, a), (b_srv, b)
    finally:
        if router is not None:
            router.stop()
        a_srv.shutdown()
        b_srv.shutdown()


def test_routes_to_least_occupied_ready_replica(fake_fleet):
    router, (a_srv, a), (b_srv, b) = fake_fleet
    code, body, _ = _post(
        "http://127.0.0.1:%d/generate" % router.port,
        {"prompt": [1], "n_new": 2})
    assert code == 200
    assert body["port"] == b_srv.server_port      # B idle, A busy
    assert body["request_id"].startswith("req-")
    # flip the occupancy: the router spills to the other replica
    a["busy"], b["busy"] = 0, 4
    router.probe_all()
    code, body, _ = _post(
        "http://127.0.0.1:%d/generate" % router.port,
        {"prompt": [1], "n_new": 2})
    assert code == 200 and body["port"] == a_srv.server_port


def test_never_routes_to_not_ready_or_draining(fake_fleet):
    router, (a_srv, a), (b_srv, b) = fake_fleet
    url = "http://127.0.0.1:%d/generate" % router.port
    b["draining"] = True                          # readyz 503 draining
    router.probe_all()
    for _ in range(3):
        code, body, _ = _post(url, {"prompt": [1], "n_new": 2})
        assert code == 200 and body["port"] == a_srv.server_port
    roster = _get("http://127.0.0.1:%d/roster" % router.port)[1]
    by_url = {e["url"]: e for e in roster["endpoints"]}
    assert by_url["http://127.0.0.1:%d" % b_srv.server_port][
        "draining"] is True
    # both gone -> 503 + Retry-After + request_id, never a silent 504
    a["ready"] = False
    router.probe_all()
    code, body, headers = _post(url, {"prompt": [1], "n_new": 2})
    assert code == 503
    assert "request_id" in body
    assert int(headers.get("Retry-After")) >= 1


def test_failover_keeps_request_id_and_opens_breaker(fake_fleet):
    router, (a_srv, a), (b_srv, b) = fake_fleet
    url = "http://127.0.0.1:%d/generate" % router.port
    b["dead"] = True                    # B ranks first (idle), dies
    fo = counters.get("veles_router_failovers_total")
    er = counters.get("veles_router_replica_errors_total")
    code, body, _ = _post(url, {"prompt": [1], "n_new": 2,
                                "request_id": "req-up-1"})
    assert code == 200
    assert body["port"] == a_srv.server_port      # failed over
    assert body["request_id"] == "req-up-1"       # id survives retry
    assert counters.get("veles_router_failovers_total") - fo == 1
    assert counters.get("veles_router_replica_errors_total") - er == 1
    # threshold 2: one more failed attempt opens B's breaker, after
    # which pick() skips B entirely (no more attempts land on it)
    bo = counters.get("veles_router_breaker_opens_total")
    _post(url, {"prompt": [1], "n_new": 2})
    assert counters.get("veles_router_breaker_opens_total") - bo == 1
    dead = [r for r in router.replicas
            if r.url.endswith(str(b_srv.server_port))][0]
    assert dead.breaker.state == CircuitBreaker.OPEN
    attempts_before = counters.get("veles_router_attempts_total")
    code, body, _ = _post(url, {"prompt": [1], "n_new": 2})
    assert code == 200 and body["port"] == a_srv.server_port
    assert counters.get("veles_router_attempts_total") \
        - attempts_before == 1                    # straight to A


def test_5xx_fails_over_4xx_delivered(fake_fleet):
    router, (a_srv, a), (b_srv, b) = fake_fleet
    url = "http://127.0.0.1:%d/generate" % router.port
    b["status_code"] = 503                        # shedding replica
    code, body, _ = _post(url, {"prompt": [1], "n_new": 2})
    assert code == 200 and body["port"] == a_srv.server_port
    # a 400 is the client's problem on EVERY replica: delivered as-is
    b["status_code"] = 200
    a["status_code"] = 400
    a["busy"], b["busy"] = 0, 4
    router.probe_all()
    code, body, _ = _post(url, {"prompt": [1], "n_new": 2})
    assert code == 400 and "request_id" in body


def test_slow_first_attempt_never_double_answers(fake_fleet):
    """THE idempotent-failover race: attempt 1 outlives the router's
    patience, attempt 2 answers — when attempt 1 then completes, the
    exactly-once latch drops it (counted), and the client saw exactly
    one response."""
    router, (a_srv, a), (b_srv, b) = fake_fleet
    router.attempt_timeout = 0.3
    url = "http://127.0.0.1:%d/generate" % router.port
    b["delay"] = 1.5                              # slow, ranks first
    dup = counters.get("veles_router_duplicate_answers_total")
    code, body, _ = _post(url, {"prompt": [1], "n_new": 2,
                                "request_id": "req-slow-1"})
    assert code == 200
    assert body["port"] == a_srv.server_port      # the failover won
    assert body["request_id"] == "req-slow-1"
    # the slow replica's late success lands in the latch and is
    # dropped as a duplicate — wait for it, then assert exactly one
    deadline = time.time() + 10
    while counters.get("veles_router_duplicate_answers_total") == dup \
            and time.time() < deadline:
        time.sleep(0.05)
    assert counters.get("veles_router_duplicate_answers_total") \
        - dup == 1
    assert b["served"] == ["req-slow-1"]          # it DID serve it


def test_injected_replica_request_fault_drives_failover(fake_fleet,
                                                        monkeypatch):
    """The router.replica_request fault point is the chaos surface:
    an armed raise fails the attempt like a dead replica — counted,
    breaker advanced, failover — with both fakes perfectly healthy."""
    router, (a_srv, a), (b_srv, b) = fake_fleet
    url = "http://127.0.0.1:%d/generate" % router.port
    fo = counters.get("veles_router_failovers_total")
    inj = counters.get("veles_faults_injected_total")
    monkeypatch.setenv("VELES_FAULTS",
                       "router.replica_request:raise:times=1")
    code, body, _ = _post(url, {"prompt": [1], "n_new": 2})
    assert code == 200
    assert counters.get("veles_router_failovers_total") - fo == 1
    assert counters.get("veles_faults_injected_total") - inj == 1


def test_router_drain_sheds_and_finishes_inflight(fake_fleet):
    router, (a_srv, a), (b_srv, b) = fake_fleet
    base = "http://127.0.0.1:%d" % router.port
    b["delay"] = 0.8
    results = {}

    def slow_post():
        results["slow"] = _post(base + "/generate",
                                {"prompt": [1], "n_new": 2})

    t = threading.Thread(target=slow_post)
    t.start()
    time.sleep(0.2)                     # the request is in flight
    code, body, _ = _post(base + "/drain", {})
    assert code == 200 and body["status"] == "draining"
    # /readyz reports draining while the in-flight request finishes
    code, payload = _get(base + "/readyz")
    assert code == 503 and payload["status"] == "draining"
    # new admission is refused with the drain answer
    code, body, headers = _post(base + "/generate",
                                {"prompt": [1], "n_new": 2})
    assert code == 503 and "draining" in body["error"]
    assert "request_id" in body
    t.join(timeout=10)
    code, body, _ = results["slow"]
    assert code == 200                  # in-flight ticket finished
    # the drain thread tears the service down once empty
    deadline = time.time() + 10
    while router._service is not None and time.time() < deadline:
        time.sleep(0.05)
    assert router._service is None


def test_fleet_metrics_and_roster_share_the_roster(fake_fleet,
                                                   tmp_path):
    router, (a_srv, a), (b_srv, b) = fake_fleet
    base = "http://127.0.0.1:%d" % router.port
    # /fleet/metrics is the live fleet-wide aggregation (summed
    # gauges, per-endpoint up rows) over the router's own roster
    with urllib.request.urlopen(base + "/fleet/metrics",
                                timeout=10) as r:
        text = r.read().decode()
    assert "veles_fleet_endpoints 2" in text
    assert "veles_serving_slots 8" in text        # 4 + 4 summed
    assert text.count('veles_fleet_endpoint_up{') == 2
    # the saved /roster page feeds `veles-tpu metrics aggregate
    # --endpoints-file` unchanged: one roster, both consumers
    roster = _get(base + "/roster")[1]
    f = tmp_path / "roster.json"
    f.write_text(json.dumps(roster))
    from veles_tpu.__main__ import main
    import io
    from contextlib import redirect_stdout
    out = io.StringIO()
    with redirect_stdout(out):
        rc = main(["metrics", "aggregate", "--endpoints-file", str(f)])
    assert rc == 0
    assert "veles_serving_slots 8" in out.getvalue()
    # the router's own /metrics page carries its gauges
    with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
        text = r.read().decode()
    assert "veles_router_replicas 2" in text
    assert "veles_router_draining 0" in text


# -- bench gate arithmetic (live proof stubbed; the drill below IS the
# live behavior) --------------------------------------------------------------

def _bench():
    sys.path.insert(0, REPO)
    sys.path.insert(0, os.path.join(REPO, "models"))
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    return bench


def test_gate_fleet_doc_checks(monkeypatch):
    bench = _bench()
    monkeypatch.setattr(bench, "_fleet_failover_proof", lambda: [])
    sec = bench._fleet_section()
    assert set(sec) == {"requests", "attempts", "failovers",
                        "replica_errors", "breaker_opens",
                        "duplicate_answers", "respawns"}
    clean = {"fleet": {k: 0 for k in sec}}
    leaked = {"fleet": dict(clean["fleet"], requests=5, failovers=1)}
    failures = bench.gate_fleet(clean, leaked)
    assert any("leaked" in f for f in failures)
    # registration + clean docs: only the process-zero check remains,
    # and it keys on the live counters (which these tests DO move) —
    # so assert no DOC failures rather than none at all
    failures = bench.gate_fleet(clean, clean)
    assert not any("doc" in f for f in failures)


# -- the route CLI: SIGTERM drains and exits 0 --------------------------------

@pytest.mark.skipif(sys.platform.startswith("win"),
                    reason="SIGTERM semantics")
def test_route_cli_sigterm_drains_inflight_and_exits_zero(tmp_path):
    """The acceptance drill's drain leg, end to end on the real CLI:
    `veles-tpu route` under SIGTERM flips /readyz to draining, lets
    the in-flight request finish (200, not a dropped connection), and
    exits 0."""
    srv, state = _fake_replica({"delay": 2.0})
    endpoints = tmp_path / "fleet.txt"
    endpoints.write_text("127.0.0.1:%d\n" % srv.server_port)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "veles_tpu", "route",
         "--endpoints-file", str(endpoints), "--port", "0",
         "--probe-interval", "0.2", "--drain-grace", "30"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=REPO)
    try:
        line = proc.stdout.readline()
        assert line.startswith("ROUTING port="), line
        port = int(line.split("port=")[1].split()[0])
        base = "http://127.0.0.1:%d" % port
        code, payload = _get(base + "/readyz")
        assert code == 200
        results = {}

        def slow_post():
            results["r"] = _post(base + "/generate",
                                 {"prompt": [1], "n_new": 2},
                                 timeout=30)

        t = threading.Thread(target=slow_post)
        t.start()
        time.sleep(0.5)                 # in flight on the replica
        proc.send_signal(signal.SIGTERM)
        # /readyz reports draining while the in-flight ticket decodes
        saw_draining = False
        deadline = time.time() + 10
        while time.time() < deadline and not saw_draining:
            try:
                code, payload = _get(base + "/readyz", timeout=2)
                saw_draining = (code == 503
                                and payload["status"] == "draining")
            except Exception:           # noqa: BLE001 — gone already
                break
            time.sleep(0.05)
        assert saw_draining, "never observed /readyz draining"
        t.join(timeout=30)
        code, body, _ = results["r"]
        assert code == 200 and body["tokens"] == [1, 2, 3]
        assert proc.wait(timeout=30) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
        srv.shutdown()


# -- the chaos drill: replica death mid-decode over real engines --------------

@pytest.fixture(scope="module")
def lm_wf():
    lm = import_model("char_lm")
    from veles_tpu import prng
    prng.seed_all(2025)
    wf = lm.build_workflow(epochs=1, minibatch_size=32, n_blocks=1,
                           dim=32, n_train=64, n_valid=32)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    return lm, wf


def test_replica_death_failover_respawn_exactly_once(lm_wf,
                                                     monkeypatch):
    """THE acceptance chaos drill: a 2-replica fleet, serve.replica_death
    injected mid-decode → the router opens the breaker and retries the
    in-flight request on the survivor, the Supervisor plane respawns
    the dead replica, and every request is answered exactly once —
    responses keyed by request_id, tokens identical to the solo
    decode, no duplicates, no silent 504s."""
    from veles_tpu.nn import sampling
    from veles_tpu.resilience import faults
    lm, wf = lm_wf
    apis = [vt.GenerationAPI(wf, port=0, engine="continuous",
                             max_slots=2, buckets=(8,), max_context=24,
                             name="drill_%d" % i) for i in range(2)]

    class Handle:
        def __init__(self, api):
            self.api = api

        def poll(self):
            return (None if self.api._service is not None
                    else faults.CRASH_EXIT_CODE)

    def spawn(i, _incarnation):
        apis[i].initialize()
        return Handle(apis[i])

    rng = numpy.random.RandomState(31)
    prompts = [[int(t) for t in rng.randint(0, lm.VOCAB, 5 + i)]
               for i in range(6)]
    expected = [sampling.generate(wf, p, 4, temperature=0)
                for p in prompts]
    sup = ReplicaSupervisor(spawn, 2, poll_interval=0.1,
                            name="drill_sup")
    router = None
    fo = counters.get("veles_router_failovers_total")
    bo = counters.get("veles_router_breaker_opens_total")
    rs = counters.get("veles_router_respawns_total")
    try:
        sup.start()
        router = FleetRouter(
            ["127.0.0.1:%d" % api.port for api in apis],
            probe_interval=0.2, failure_threshold=1, retry_budget=2,
            attempt_timeout=60.0, request_timeout=120.0,
            name="drill_router").start()
        url = "http://127.0.0.1:%d/generate" % router.port
        # warm both engines' programs outside the armed window
        code, body, _ = _post(url, {"prompt": prompts[0], "n_new": 4},
                              timeout=120)
        assert code == 200
        # the 3rd replica-side request dies mid-decode, exactly once
        monkeypatch.setenv(
            "VELES_FAULTS", "serve.replica_death:raise:after=2,times=1")
        answers = {}
        for i, prompt in enumerate(prompts):
            code, body, _ = _post(
                url, {"prompt": prompt, "n_new": 4}, timeout=120)
            assert code == 200, (i, body)         # no dropped requests
            rid = body["request_id"]
            assert rid not in answers             # no double answers
            answers[rid] = body["tokens"]
            assert body["tokens"] == expected[i]  # failover is id-exact
        assert len(answers) == len(prompts)
        assert counters.get("veles_router_failovers_total") - fo >= 1
        assert counters.get("veles_router_breaker_opens_total") \
            - bo >= 1
        monkeypatch.delenv("VELES_FAULTS")
        # the supervisor respawns the hole... (the respawn counter is
        # the event — alive() alone is racy: the dying replica's
        # teardown may still be in flight when the load finishes)
        deadline = time.time() + 60
        while counters.get("veles_router_respawns_total") - rs < 1 \
                and time.time() < deadline:
            time.sleep(0.1)
        assert counters.get("veles_router_respawns_total") - rs >= 1, \
            "dead replica never respawned"
        deadline = time.time() + 30
        while sup.alive() < 2 and time.time() < deadline:
            time.sleep(0.1)
        assert sup.alive() == 2
        # ...and the respawned replica actually serves again
        router.probe_all()
        dead_idx = [i for i, api in enumerate(apis)
                    if sup.incarnations[i] > 1]
        assert len(dead_idx) == 1
        code, body, _ = _post(
            "http://127.0.0.1:%d/generate" % apis[dead_idx[0]].port,
            {"prompt": prompts[0], "n_new": 4}, timeout=120)
        assert code == 200 and body["tokens"] == expected[0]
    finally:
        if router is not None:
            router.stop()
        sup.stop()
        for api in apis:
            api.stop()


def test_generation_api_drain_finishes_inflight(lm_wf):
    """The engine-API side of the LEGACY drain contract
    (handoff=False — the wait-out-the-grace drain): begin_drain stops
    admission (503 "draining" + request_id) and flips /readyz to
    draining while the in-flight ticket keeps decoding to a 200;
    drain() then returns True and tears the service down. The
    default drain-by-handoff path is tests/test_lossless.py's."""
    lm, wf = lm_wf
    api = vt.GenerationAPI(wf, port=0, engine="continuous",
                           max_slots=2, buckets=(8,), max_context=24,
                           name="drain_api")
    api.initialize()
    base = "http://127.0.0.1:%d" % api.port
    try:
        code, body, _ = _post(base + "/generate",
                              {"prompt": [1, 2, 3], "n_new": 2},
                              timeout=120)          # warm the engine
        assert code == 200
        results = {}

        def slow_post():
            results["r"] = _post(base + "/generate",
                                 {"prompt": [1, 2, 3, 4], "n_new": 12},
                                 timeout=120)

        t = threading.Thread(target=slow_post)
        t.start()
        deadline = time.time() + 10
        while not api._inflight and time.time() < deadline:
            time.sleep(0.005)                       # it IS in flight
        assert api.begin_drain() is True
        assert api.begin_drain() is False           # idempotent
        code, payload = _get(base + "/readyz")
        assert code == 503 and payload["status"] == "draining"
        assert payload["components"]["serve.drain_api"] == "draining"
        code, _b = _get(base + "/healthz")
        assert code == 200                          # alive throughout
        code, body, headers = _post(base + "/generate",
                                    {"prompt": [5, 6], "n_new": 2})
        assert code == 503 and "draining" in body["error"]
        assert "request_id" in body
        assert int(headers.get("Retry-After")) >= 1
        assert api.drain(grace=60, handoff=False) is True   # finished
        t.join(timeout=30)
        code, body, _ = results["r"]
        assert code == 200 and len(body["tokens"]) == 12
        assert api._service is None
        # gone: readiness mark and heartbeat both dropped
        assert "serve.drain_api" not in health.readiness()
    finally:
        api.stop()


def test_generation_api_drain_endpoint(lm_wf):
    lm, wf = lm_wf
    api = vt.GenerationAPI(wf, port=0, engine="continuous",
                           max_slots=2, buckets=(8,), max_context=24,
                           name="drain_ep")
    api.initialize()
    base = "http://127.0.0.1:%d" % api.port
    try:
        code, body, _ = _post(base + "/generate/drain", {})
        assert code == 200 and body["status"] == "draining"
        deadline = time.time() + 15
        while api._service is not None and time.time() < deadline:
            time.sleep(0.05)
        assert api._service is None                 # drained + stopped
    finally:
        api.stop()
