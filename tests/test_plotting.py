"""Plotting subsystem tests (reference: veles/tests/test_plotting_units.py,
graphics server/client round trip)."""
import os
import time

import numpy
import pytest

import veles_tpu as vt
from veles_tpu.config import root
from veles_tpu import graphics


@pytest.fixture
def plotting_enabled():
    old = root.common.disable.plotting
    root.common.disable.plotting = False
    yield
    root.common.disable.plotting = old


def test_accumulating_plotter(plotting_enabled, tmp_path):
    wf = vt.Workflow(name="t")
    holder = {"v": 1.0}
    p = vt.AccumulatingPlotter(wf, input=lambda: holder["v"],
                               label="err", redraw_interval=0.0)
    for v in (3.0, 2.0, 1.0):
        holder["v"] = v
        p.run()
    snap = p.last_snapshot
    assert snap["kind"] == "lines" and snap["values"] == [3.0, 2.0, 1.0]
    out = graphics.render_snapshot(snap, str(tmp_path / "lines.png"))
    assert os.path.getsize(out) > 0


def test_matrix_plotter_confusion(plotting_enabled, tmp_path):
    wf = vt.Workflow(name="t")
    conf = numpy.arange(9).reshape(3, 3)
    p = vt.MatrixPlotter(wf, input=lambda: conf, redraw_interval=0.0)
    p.run()
    assert p.last_snapshot["matrix"].shape == (3, 3)
    graphics.render_snapshot(p.last_snapshot, str(tmp_path / "m.png"))


def test_image_histogram_table_stepstats(plotting_enabled, tmp_path):
    wf = vt.Workflow(name="t")
    imgs = numpy.random.RandomState(0).rand(5, 49)  # 7x7 flat
    ip = vt.ImagePlotter(wf, input=lambda: imgs, redraw_interval=0.0)
    ip.run()
    assert ip.last_snapshot["images"].shape == (5, 7, 7)
    h = vt.Histogram(wf, input=lambda: imgs, redraw_interval=0.0,
                     n_bins=10)
    h.run()
    assert h.last_snapshot["counts"].sum() == imgs.size
    mh = vt.MultiHistogram(wf, input=lambda: imgs, redraw_interval=0.0,
                           n_bins=5, hist_number=4)
    mh.run()
    assert mh.last_snapshot["counts"].shape == (4, 5)
    t = vt.TableMaxMin(wf, redraw_interval=0.0)
    t.add_source("imgs", lambda: imgs)
    t.run()
    assert t.last_snapshot["rows"][0][0] == "imgs"
    s = vt.StepStats(wf, redraw_interval=0.0)
    s.run()
    assert s.last_snapshot["header"] == ["unit", "runs", "total s"]
    for u, fname in ((ip, "i"), (h, "h"), (mh, "mh"), (t, "t"), (s, "s")):
        graphics.render_snapshot(u.last_snapshot,
                                 str(tmp_path / (fname + ".png")))


def test_redraw_throttling(plotting_enabled):
    wf = vt.Workflow(name="t")
    p = vt.AccumulatingPlotter(wf, input=lambda: 1.0,
                               redraw_interval=3600.0)
    p.run()
    p.run()     # throttled: second run must not append
    assert len(p.last_snapshot["values"]) == 1
    p.finalize()  # forced redraw bypasses the throttle
    assert len(p.last_snapshot["values"]) == 2


def test_plotting_disabled_is_noop():
    assert root.common.disable.plotting  # test harness default
    wf = vt.Workflow(name="t")
    p = vt.AccumulatingPlotter(wf, input=lambda: 1.0, redraw_interval=0.0)
    p.run()
    assert p.last_snapshot is None


def test_graphics_pubsub_roundtrip(plotting_enabled):
    zmq = pytest.importorskip("zmq")
    server = graphics.GraphicsServer()
    assert server.endpoint
    sub = zmq.Context.instance().socket(zmq.SUB)
    sub.connect(server.endpoint)
    sub.setsockopt(zmq.SUBSCRIBE, b"")
    assert server.wait_subscriber(5.0)
    wf = vt.Workflow(name="t")
    wf.graphics = server
    p = vt.AccumulatingPlotter(wf, input=lambda: 2.5, redraw_interval=0.0)
    p.run()
    poller = zmq.Poller()
    poller.register(sub, zmq.POLLIN)
    assert poller.poll(5000), "snapshot not delivered over PUB/SUB"
    snap = graphics.unpack_snapshot(sub.recv())
    assert snap["name"] == p.name and snap["values"] == [2.5]
    assert server.snapshots[p.name]["values"] == [2.5]
    sub.close(linger=0)
    server.shutdown()


def test_graphics_client_subprocess(plotting_enabled, tmp_path):
    pytest.importorskip("zmq")
    server = graphics.GraphicsServer()
    pid = server.launch_client(out_dir=str(tmp_path))
    assert pid
    wf = vt.Workflow(name="t")
    wf.graphics = server
    p = vt.AccumulatingPlotter(wf, input=lambda: 1.5, name="train err",
                               redraw_interval=0.0)
    p.run()
    deadline = time.time() + 15
    png = tmp_path / "train_err.png"
    while time.time() < deadline and not png.exists():
        time.sleep(0.2)
    server.shutdown()
    assert png.exists() and png.stat().st_size > 0


def test_image_plotter_non_square_flat(plotting_enabled, tmp_path):
    """Non-square flat samples render as 1-pixel-high strips, not crash."""
    wf = vt.Workflow(name="t")
    imgs = numpy.random.RandomState(0).rand(3, 10)   # 10 is not square
    p = vt.ImagePlotter(wf, input=lambda: imgs, redraw_interval=0.0)
    p.run()
    assert p.last_snapshot["images"].shape == (3, 1, 10)
    graphics.render_snapshot(p.last_snapshot, str(tmp_path / "strip.png"))


def test_pack_unpack_roundtrip():
    """The graphics wire codec is data-only (no pickle): arrays — also
    nested in lists (multi_histogram) — survive exactly, scalars/strings
    pass through JSON."""
    snap = {
        "kind": "multi_histogram", "name": "hist",
        "counts": [numpy.arange(4.0), numpy.arange(3.0) * 2],
        "edges": [numpy.linspace(0, 1, 5), numpy.linspace(0, 1, 4)],
        "matrix": numpy.eye(3, dtype=numpy.float32),
        "label": "x", "ylim": (0.0, 1.0), "n": 7,
    }
    out = graphics.unpack_snapshot(graphics.pack_snapshot(snap))
    assert out["kind"] == "multi_histogram" and out["name"] == "hist"
    assert out["label"] == "x" and out["n"] == 7
    assert list(out["ylim"]) == [0.0, 1.0]
    numpy.testing.assert_array_equal(out["matrix"], snap["matrix"])
    for a, b in zip(out["counts"], snap["counts"]):
        numpy.testing.assert_array_equal(a, b)
    assert out["matrix"].dtype == numpy.float32
    # frames must not be unpicklable payloads: codec never calls pickle
    assert b"pickle" not in graphics.pack_snapshot(snap)
