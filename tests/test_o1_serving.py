"""O(1)-state serving lane (ISSUE 16): the recurrent slot pool.

The contract under test: (1) a recurrent stack (Embedding →
SSM/LSTM → LMHead) serves on the continuous plane with a FIXED
per-slot state tensor — no page table, state bytes constant whatever
the token count — through exactly two fixed-shape programs (chunked
scan prefill + recurrent decode); (2) every id-exactness guarantee of
the paged lane carries over verbatim: pooled == solo, resume ==
uninterrupted, cache-hit == cache-miss, greedy AND sampled; (3) the
state-checkpoint prefix cache restores block-boundary snapshots and
degrades to a full re-scan (counted, id-exact) under the
``serve.state_restore`` / ``serve.state_checkpoint`` fault points;
(4) the request plane — GenerationAPI, SSE streaming, serve-artifact
AOT — hosts the lane end-to-end, including the equal-HBM slot
multiplier the bench gate stamps.

Budget discipline: one tiny TRAINED lstm char_lm (the trains-AND-
serves acceptance) plus an initialized ssm/transformer pair, all
module-scoped.
"""
import json
import urllib.error
import urllib.request

import numpy
import pytest

import veles_tpu as vt
from veles_tpu.error import VelesError
from veles_tpu.serving import (O1_COUNTERS, RecurrentEngine, Ticket,
                               fold_resume, generate_recurrent,
                               split_recurrent_stack)
from veles_tpu.serving.engine import ContinuousEngine, make_request
from veles_tpu.telemetry.counters import counters

from conftest import import_model

PROMPT = [1, 5, 3, 2, 4, 6, 1, 2]


@pytest.fixture(scope="module")
def lstm_wf():
    """Trained, not just initialized: the acceptance bar is that the
    LSTM workflow TRAINS (BPTT through the scan) and then serves."""
    lm = import_model("char_lm")
    from veles_tpu import prng
    prng.seed_all(2026)
    wf = lm.build_workflow(epochs=1, minibatch_size=32, n_blocks=1,
                           dim=32, n_train=64, n_valid=32,
                           arch="lstm")
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    return lm, wf


@pytest.fixture(scope="module")
def ssm_wf():
    lm = import_model("char_lm")
    from veles_tpu import prng
    prng.seed_all(2027)
    wf = lm.build_workflow(epochs=1, minibatch_size=32, n_blocks=1,
                           dim=32, n_train=64, n_valid=32, arch="ssm")
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    return lm, wf


@pytest.fixture(scope="module")
def paged_wf():
    lm = import_model("char_lm")
    from veles_tpu import prng
    prng.seed_all(2028)
    wf = lm.build_workflow(epochs=1, minibatch_size=32, n_blocks=1,
                           dim=32, n_train=64, n_valid=32)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    return lm, wf


def _engine(wf, **kw):
    kw.setdefault("max_slots", 3)
    kw.setdefault("max_context", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("name", "o1t_%d" % numpy.random.randint(1 << 30))
    return RecurrentEngine(wf, **kw)


def _post(url, payload, timeout=120.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


# -- stack admission -----------------------------------------------------------

def test_split_stack_accepts_recurrent_rejects_transformer(
        lstm_wf, paged_wf):
    _lm, wf = lstm_wf
    stack = split_recurrent_stack(list(wf.forwards))
    assert stack["blocks"] and all(hasattr(b, "step_state")
                                   for b in stack["blocks"])
    _lm2, twf = paged_wf
    with pytest.raises(VelesError):
        split_recurrent_stack(list(twf.forwards))
    # and the paged engine refuses the recurrent stack right back —
    # the two VelesErrors are what GenerationAPI's fallback chain
    # pivots on
    with pytest.raises(VelesError):
        ContinuousEngine(wf, buckets=(16,), max_context=32,
                         name="o1t_reject")


def test_engine_rejects_spec_and_beam_modes(lstm_wf):
    _lm, wf = lstm_wf
    e = _engine(wf)
    for mode in ("speculative", "beam"):
        req = make_request(PROMPT, 4, mode=mode)
        assert e.accepts(req) is not None
    assert e.accepts(make_request(PROMPT, 4)) is None
    # over-limit lands on the window worker, not a crash
    assert e.accepts(make_request(list(range(80)), 4)) is not None


# -- pooled == solo, both families, both modes ---------------------------------

@pytest.mark.parametrize("temperature,seed",
                         [(0.0, 0), (0.8, 11)],
                         ids=["greedy", "sampled"])
@pytest.mark.parametrize("family", ["lstm", "ssm"])
def test_pool_matches_solo_id_exact(lstm_wf, ssm_wf, family,
                                    temperature, seed):
    _lm, wf = lstm_wf if family == "lstm" else ssm_wf
    mode = "sample" if temperature > 0 else "greedy"
    solo = [generate_recurrent(wf, PROMPT, 10, temperature=temperature,
                               seed=seed + i, mode=mode)
            for i in range(3)]
    e = _engine(wf).start()
    try:
        out = e.serve([make_request(PROMPT, 10, temperature=temperature,
                                    seed=seed + i, mode=mode)
                       for i in range(3)])
    finally:
        e.stop()
    assert out == solo
    assert e.compiled_live == 2 and e.programs_bound() == 2


# -- fixed state bytes (the O(1) claim) ----------------------------------------

def test_state_bytes_constant_vs_token_count(lstm_wf):
    _lm, wf = lstm_wf
    e = _engine(wf).start()
    try:
        e.serve([make_request(PROMPT, 4)])
        st_short = e.stats()
        e.serve([make_request(PROMPT, 40)])
        st_long = e.stats()
    finally:
        e.stop()
    # the whole pool is slots × state — token count must not move it
    assert st_short["kv_pool_bytes"] == st_long["kv_pool_bytes"] > 0
    assert st_long["state_bytes_per_slot"] > 0
    assert st_long["pages_total"] == 0 and st_long["pages_in_use"] == 0
    assert st_long["slot_kind"] == "state"
    assert e.scheduler.slot_kind == "state"


# -- token-level failover resume -----------------------------------------------

@pytest.mark.parametrize("temperature,seed",
                         [(0.0, 0), (0.9, 41)],
                         ids=["greedy", "sampled"])
def test_resume_is_id_exact(lstm_wf, monkeypatch, temperature, seed):
    _lm, wf = lstm_wf
    mode = "sample" if temperature > 0 else "greedy"
    n_new = 12
    solo = generate_recurrent(wf, PROMPT, n_new,
                              temperature=temperature, seed=seed,
                              mode=mode)
    req = make_request(PROMPT, n_new, temperature=temperature,
                       seed=seed, mode=mode)
    e1 = _engine(wf, name="o1t_resume_a_" + mode).start()
    try:
        t1 = Ticket(mode=mode)
        monkeypatch.setenv("VELES_FAULTS",
                           "serve.decode_step:raise:after=4,times=1")
        assert e1.submit(req, t1)
        assert t1.event.wait(60)
        monkeypatch.delenv("VELES_FAULTS")
        assert t1.code == 503 and t1.progress
        k = len(t1.progress)
        assert 0 < k < n_new
        assert t1.progress == solo[:k]
        assert t1.error_payload()["resume"]["tokens_done"] == k
    finally:
        e1.stop()
    rt = counters.get("veles_resume_tokens_total")
    e2 = _engine(wf, name="o1t_resume_b_" + mode).start()
    try:
        t2 = Ticket(mode=mode)
        assert e2.submit(fold_resume(req, t1.progress), t2)
        assert t2.event.wait(60)
        assert t2.error is None, t2.error
        assert t1.progress + t2.result["tokens"] == solo
        assert counters.get("veles_resume_tokens_total") - rt == k
    finally:
        e2.stop()


# -- state-checkpoint prefix cache + chaos -------------------------------------

def _long_prompt(lm, n=24):
    return [int(t) for t in lm.make_corpus(numpy.random.RandomState(5),
                                           n)]


def test_state_cache_restore_is_id_exact(lstm_wf):
    lm, wf = lstm_wf
    prompt = _long_prompt(lm)
    cold = generate_recurrent(wf, prompt, 8)
    e = _engine(wf, state_cache=True).start()
    try:
        first = e.serve([make_request(prompt, 8)])[0]
        st = e.stats()
        assert st["state_checkpoints"] > 0
        assert st["state_cache_blocks"] > 0
        assert st["state_cache_bytes"] > 0
        r0 = counters.get("veles_o1_state_restores_total")
        again = e.serve([make_request(prompt, 8)])[0]
        st = e.stats()
        assert st["state_restores"] >= 1
        assert counters.get("veles_o1_state_restores_total") > r0
        assert e.prefix_requests >= 1
    finally:
        e.stop()
    # cache hit, cache miss, solo: one answer
    assert first == again == cold


def test_chaos_state_restore_raise_degrades_to_rescan(lstm_wf,
                                                      monkeypatch):
    lm, wf = lstm_wf
    prompt = _long_prompt(lm)
    e = _engine(wf, state_cache=True).start()
    try:
        warm = e.serve([make_request(prompt, 6)])[0]
        r0 = counters.get("veles_o1_state_rescans_total")
        monkeypatch.setenv("VELES_FAULTS",
                           "serve.state_restore:raise:times=1")
        hit = e.serve([make_request(prompt, 6)])[0]
        monkeypatch.delenv("VELES_FAULTS")
        assert counters.get("veles_o1_state_rescans_total") == r0 + 1
        assert e.stats()["state_rescans"] >= 1
    finally:
        e.stop()
    assert hit == warm


def test_chaos_state_restore_corrupt_still_id_exact(lstm_wf,
                                                    monkeypatch):
    lm, wf = lstm_wf
    prompt = _long_prompt(lm)
    e = _engine(wf, state_cache=True).start()
    try:
        warm = e.serve([make_request(prompt, 6)])[0]
        fi = counters.get("veles_faults_injected_total")
        monkeypatch.setenv("VELES_FAULTS",
                           "serve.state_restore:corrupt:times=1")
        hit = e.serve([make_request(prompt, 6)])[0]
        monkeypatch.delenv("VELES_FAULTS")
        assert counters.get("veles_faults_injected_total") > fi
    finally:
        e.stop()
    # a rotted lookup key can only SHORTEN the match — token equality
    # is the authority, the answer must not move
    assert hit == warm


def test_chaos_state_checkpoint_raise_skips_caching(lstm_wf,
                                                    monkeypatch):
    lm, wf = lstm_wf
    prompt = _long_prompt(lm)
    oracle = generate_recurrent(wf, prompt, 6)
    e = _engine(wf, state_cache=True).start()
    try:
        monkeypatch.setenv("VELES_FAULTS",
                           "serve.state_checkpoint:raise:times=1")
        out = e.serve([make_request(prompt, 6)])[0]
        monkeypatch.delenv("VELES_FAULTS")
        st = e.stats()
        assert st["state_cache_blocks"] == 0
        assert st["state_checkpoints"] == 0
    finally:
        e.stop()
    assert out == oracle


# -- request plane: GenerationAPI, SSE, artifact -------------------------------

@pytest.fixture(scope="module")
def api_served(lstm_wf):
    _lm, wf = lstm_wf
    api = vt.GenerationAPI(wf, port=0, engine="recurrent",
                           max_slots=3, max_context=64, page_size=8,
                           state_cache=True, name="o1t_api")
    api.initialize()
    yield api
    api.stop()


def test_api_serves_recurrent_engine(lstm_wf, api_served):
    _lm, wf = lstm_wf
    api = api_served
    assert type(api._engine).__name__ == "RecurrentEngine"
    ref = generate_recurrent(wf, PROMPT, 8)
    url = "http://127.0.0.1:%d/generate" % api.port
    code, body, _ = _post(url, {"prompt": PROMPT, "n_new": 8})
    assert code == 200
    assert body["tokens"] == ref and body["engine"] == "recurrent"
    code, body, _ = _post(url, {"prompt": PROMPT, "n_new": 8,
                                "mode": "sample", "temperature": 0.8,
                                "seed": 3})
    assert code == 200
    assert body["tokens"] == generate_recurrent(
        wf, PROMPT, 8, temperature=0.8, seed=3, mode="sample")


def test_api_streams_sse_id_exact(lstm_wf, api_served):
    _lm, wf = lstm_wf
    api = api_served
    ref = generate_recurrent(wf, PROMPT, 8)
    url = "http://127.0.0.1:%d/generate" % api.port
    req = urllib.request.Request(
        url, data=json.dumps({"prompt": PROMPT, "n_new": 8,
                              "stream": True}).encode(),
        headers={"Content-Type": "application/json"})
    events = []
    with urllib.request.urlopen(req, timeout=120) as r:
        assert "text/event-stream" in r.headers.get("Content-Type", "")
        for line in r:
            line = line.strip()
            if line.startswith(b"data:"):
                events.append(json.loads(line[5:]))
    streamed = [t for ev in events if not ev.get("done")
                for t in ev["tokens"]]
    assert streamed == ref
    assert events[-1].get("done") and events[-1]["tokens"] == ref


def test_api_metrics_report_state_not_pages(lstm_wf, api_served):
    lm, _wf = lstm_wf
    # a repeated long prompt touches the checkpoint AND restore
    # counters (the registry only renders touched counters)
    url = "http://127.0.0.1:%d/generate" % api_served.port
    prompt = _long_prompt(lm)
    for _ in range(2):
        code, _body, _h = _post(url, {"prompt": prompt, "n_new": 4})
        assert code == 200
    with urllib.request.urlopen(
            "http://127.0.0.1:%d/metrics" % api_served.port,
            timeout=30) as r:
        text = r.read().decode()
    for gauge in ("veles_o1_state_bytes_per_slot",
                  "veles_o1_state_cache_blocks",
                  "veles_o1_state_cache_bytes",
                  "veles_o1_checkpoint_interval"):
        assert gauge in text, gauge
    # pageless slots must NOT feed the fleet's page-occupancy math
    assert "veles_serving_pages_total" not in text
    for name in ("veles_o1_state_checkpoints_total",
                 "veles_o1_state_restores_total",
                 "veles_o1_state_restored_tokens_total"):
        assert name in O1_COUNTERS and name in text, name


def test_api_transformer_recurrent_falls_back_to_window(paged_wf):
    _lm, wf = paged_wf
    api = vt.GenerationAPI(wf, port=0, engine="recurrent",
                           max_slots=2, max_context=48,
                           name="o1t_fallback")
    api.initialize()
    try:
        # the O(1) lane refuses an attention stack; the window worker
        # answers instead of an outage
        assert api._engine is None
    finally:
        api.stop()


def test_artifact_roundtrip_zero_compiles(lstm_wf, tmp_path):
    _lm, wf = lstm_wf
    from veles_tpu.export.serve_artifact import export_serve_artifact
    path = str(tmp_path / "o1_art")
    export_serve_artifact(wf, path, max_slots=3, max_context=64,
                          decode_block=1, page_size=8)
    with open(path + "/contents.json") as f:
        serving = json.load(f)["serving"]
    assert serving["artifact_version"] == 4
    assert serving["signature"]["kind"] == "recurrent"
    assert sorted(serving["programs"]) == ["rscan", "rstep"]
    reqs = [make_request(PROMPT, 8),
            make_request(PROMPT, 8, temperature=0.7, seed=5,
                         mode="sample")]
    live = _engine(wf).start()
    try:
        ref = live.serve([dict(r) for r in reqs])
    finally:
        live.stop()
    aot = _engine(wf, artifact=path).start()
    try:
        out = aot.serve([dict(r) for r in reqs])
        assert aot.artifact_mode
        assert aot.compiled_live == 0
    finally:
        aot.stop()
    assert out == ref


# -- the HBM headline ----------------------------------------------------------

def test_slots_at_equal_hbm_multiplier(lstm_wf, ssm_wf, paged_wf):
    """The lane's reason to exist: per-slot state is ≥4× smaller than
    the paged transformer's per-slot KV allotment at the same
    geometry, so the same HBM holds ≥4× the concurrent slots."""
    _lm, twf = paged_wf
    paged = ContinuousEngine(twf, max_slots=3, buckets=(16, 32, 64),
                             max_context=64, page_size=8,
                             name="o1t_hbm_paged")
    params = paged._prepare_params()
    paged._ensure_pool(params)
    import jax
    kv_per_slot = sum(
        int(leaf.nbytes)
        for leaf in jax.tree_util.tree_leaves(paged._caches)
    ) // paged.max_slots
    assert kv_per_slot > 0
    for _lm2, wf in (lstm_wf, ssm_wf):
        e = _engine(wf)
        per_slot = e.state_bytes_per_slot()
        assert per_slot > 0
        multiplier = kv_per_slot / per_slot
        assert multiplier >= 4.0, \
            "equal-HBM multiplier %.1f < 4 (kv=%d state=%d)" \
            % (multiplier, kv_per_slot, per_slot)


# -- bench gate wiring ---------------------------------------------------------

def test_o1state_bench_section_and_gate_registration(monkeypatch):
    """The bench doc's o1state section stamps the five lane counters
    and gate_o1state fails a doc that carries leakage (live proof
    stubbed — it runs inside ``python bench.py gate``, not tier-1)."""
    import bench
    section = bench._o1state_section()
    assert sorted(section) == ["checkpoints", "evictions", "rescans",
                               "restored_tokens", "restores"]
    from veles_tpu.telemetry.counters import DESCRIPTIONS
    for name in O1_COUNTERS:
        assert name in DESCRIPTIONS
    monkeypatch.setattr(bench, "_o1state_proof", lambda: ([], {}))
    leaky = {"o1state": {"checkpoints": 2, "restores": 0,
                         "restored_tokens": 0, "rescans": 1,
                         "evictions": 0},
             "serving": {"serving_bench": False}}
    failures = [f for f in bench.gate_o1state(leaky, None)
                if "leaked" in f]
    assert len(failures) == 2          # checkpoints + rescans
    # a serving-mode bench document checkpoints on purpose — not a leak
    serving_doc = dict(leaky, serving={"serving_bench": True})
    assert not [f for f in bench.gate_o1state(serving_doc, None)
                if "leaked" in f]
