"""Mirrors reference veles/tests/test_config.py scope."""
import io

from veles_tpu.config import Config


def test_autovivify_and_update():
    c = Config("r")
    c.a.b.c = 3
    assert c.a.b.c == 3
    c.update({"a": {"b": {"d": 4}}, "e": "x"})
    assert c.a.b.c == 3 and c.a.b.d == 4 and c.e == "x"


def test_contains_and_get():
    c = Config("r")
    assert "missing" not in c
    c.x = 1
    assert "x" in c
    assert c.get("x") == 1
    assert c.get("nope", 7) == 7


def test_protect():
    c = Config("r")
    c.key = 1
    c.protect("key")
    try:
        c.key = 2
        assert False, "protected key assignable"
    except AttributeError:
        pass
    assert c.key == 1


def test_as_dict_and_print():
    c = Config("r")
    c.update({"a": {"b": 1}, "c": 2})
    assert c.as_dict() == {"a": {"b": 1}, "c": 2}
    buf = io.StringIO()
    c.print_(file=buf)
    out = buf.getvalue()
    assert "a:" in out and "b: 1" in out


def test_update_from_file_json(tmp_path):
    p = tmp_path / "o.json"
    p.write_text('{"x": {"y": 5}}')
    c = Config("r")
    c.update_from_file(str(p))
    assert c.x.y == 5


def test_update_from_file_py(tmp_path):
    p = tmp_path / "o.py"
    p.write_text("root.m.n = 'hello'\n")
    c = Config("r")
    c.update_from_file(str(p))
    assert c.m.n == "hello"


def test_global_root_defaults():
    from veles_tpu.config import root
    assert root.common.engine.precision_type in ("float32", "float64")
    assert "data" in root.common.mesh.axes.as_dict() or True


def test_update_from_env_cfg_prefix(monkeypatch):
    monkeypatch.setenv("VELES_TPU_CFG_ENGINE__FORCE_NUMPY", "true")
    monkeypatch.setenv("VELES_TPU_TEST", "1")  # control var: ignored
    c = Config("r")
    c.engine.force_numpy = False
    c.update_from_env()
    assert c.engine.force_numpy is True
    assert "test" not in c


def test_get_treats_vivified_husk_as_unset():
    """__getattr__ vivifies truthy nodes on mere READS (`if root.x.y:`
    creates y); Config.get must not hand such husks back as values —
    the class of bug that needed ad-hoc guards in train_step/publishing
    before this rule lived in get() itself."""
    from veles_tpu.config import Config
    c = Config("test")
    assert c.a.b is not None          # vivifies a and a.b
    assert c.get("a", "dflt") == "dflt"   # all-husk subtree = unset
    assert c.a.get("b", 7) == 7
    # a REAL subtree still comes back
    c.a.b.value = 3
    sub = c.a.get("b")
    assert sub is not None and sub.value == 3


def test_get_husk_check_recurses():
    """A chain `if c.a.b.c:` vivifies the whole path; get('b') one
    level up must treat the all-husk subtree as unset too."""
    from veles_tpu.config import Config
    c = Config("test")
    assert c.a.b.deep is not None       # vivifies a→b→deep
    assert c.a.get("b", "dflt") == "dflt"
    assert c.get("a", "dflt") == "dflt"
    c.a.b.deep.value = 1                # now a real subtree
    assert c.get("a") is not None
    assert c.a.get("b").deep.value == 1
