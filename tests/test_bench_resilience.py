"""bench.py must print ONE parseable JSON line under ANY tunnel state.

Round-2 regression (VERDICT r2 "what's missing" #1): a slow-failing
accelerator backend defeated both the liveness guard and the CPU
fallback — BENCH_r02.json recorded rc=124/parsed=null and every perf
lever shipped unmeasured. The redesign: the parent process never
touches jax outside a pinned-CPU fallback; the whole accelerator bench
runs in a killable child under a hard budget, snapshotting a complete
printable JSON after every section. These tests drive each failure
branch through the real parent via the VELES_BENCH_FAKE_CHILD hook.
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _run_parent(fake_child, budget=None, timeout=150):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)        # parent must take the child path
    env.pop("VELES_BENCH_PARTIAL", None)
    env["VELES_BENCH_FAKE_CHILD"] = fake_child
    if budget is not None:
        env["VELES_BENCH_TPU_BUDGET"] = str(budget)
    r = subprocess.run([sys.executable, BENCH], capture_output=True,
                       text=True, timeout=timeout, env=env)
    return r


FAKE_OK = """
import json
print(json.dumps({"metric": "mnist784_train_samples_per_sec_per_chip",
                  "value": 123.0, "platform": "faketpu"}))
"""

# writes a partial snapshot the way the real child does, then fails
FAKE_PARTIAL_THEN_FAIL = """
import json, os, sys
path = os.environ["VELES_BENCH_PARTIAL"]
with open(path + ".tmp", "w") as f:
    json.dump({"metric": "mnist784_train_samples_per_sec_per_chip",
               "value": 456.0, "platform": "faketpu", "partial": True}, f)
os.replace(path + ".tmp", path)
sys.exit(2)
"""

FAKE_PARTIAL_THEN_HANG = """
import json, os, time
path = os.environ["VELES_BENCH_PARTIAL"]
with open(path + ".tmp", "w") as f:
    json.dump({"metric": "mnist784_train_samples_per_sec_per_chip",
               "value": 789.0, "platform": "faketpu", "partial": True}, f)
os.replace(path + ".tmp", path)
time.sleep(600)
"""


def test_child_success_is_relayed_verbatim():
    r = _run_parent(FAKE_OK)
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout.strip().splitlines()[-1])
    assert doc["value"] == 123.0
    assert doc["platform"] == "faketpu"
    assert "fallback_reason" not in doc


def test_child_failure_relays_partial_snapshot():
    """A mid-bench death must surface the sections that DID finish on
    the real chip, not degrade to a CPU smoke."""
    r = _run_parent(FAKE_PARTIAL_THEN_FAIL)
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout.strip().splitlines()[-1])
    assert doc["value"] == 456.0
    assert "rc=2" in doc["fallback_reason"]


def test_child_overrunning_budget_is_killed_and_partial_relayed():
    """The round-2 killer: unbounded child wall-clock. The parent's
    budget must fire and the partial must still come through."""
    # budget must outlive child python startup even on a loaded box
    # (observed: 3 s lost the race against a full-suite run pegging the
    # single core — the partial never got written before the kill)
    r = _run_parent(FAKE_PARTIAL_THEN_HANG, budget=10)
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout.strip().splitlines()[-1])
    assert doc["value"] == 789.0
    assert "budget" in doc["fallback_reason"]


def test_child_failure_without_partial_falls_back_to_cpu_smoke():
    """Last resort end to end: child dies before any snapshot — the
    parent must still print a parseable smoke line (pinned CPU)."""
    r = _run_parent("import sys; sys.exit(7)", timeout=420)
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout.strip().splitlines()[-1])
    assert doc["metric"] == "mnist784_train_samples_per_sec_per_chip"
    assert doc["smoke"] is True
    assert doc["platform"] == "cpu"
    assert "rc=7" in doc["fallback_reason"]
    assert doc["value"] > 0


def test_method_tag_encodes_dispatch_config(tmp_path, monkeypatch):
    """ADVICE r2: epochs_per_dispatch is methodology — a plan-mode
    baseline must never be compared against a block-dispatch run."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    monkeypatch.chdir(tmp_path)

    def fake_mnist(h, smoke=False):
        return {"samples_per_sec_per_chip": 100.0, "max_window": 110.0,
                "epochs_per_dispatch": h, "smoke": smoke,
                "data": "synthetic"}

    # a LEGACY single-slot baseline (plan-mode 1.52M) must stay the
    # h=1 anchor, not be discarded or matched against h=8
    path = tmp_path / "BENCH_BASELINE.json"
    path.write_text(json.dumps({"value": 50.0,
                                "method": "median_of_3x10s",
                                "ts": 0}))
    monkeypatch.setattr(bench, "BASELINE_PATH", str(path))
    doc = bench._assemble(fake_mnist(8), {}, {}, "tpu", "kind",
                          allow_rebaseline=True)
    assert doc["window"] == "median_of_3x10s_h8"
    assert doc["rebaselined"] is True        # h8 had no anchor yet
    stored = json.load(open(path))
    # per-method slots: the h8 anchor lands WITHOUT evicting the
    # migrated legacy h=1 anchor
    assert stored["baselines"]["median_of_3x10s_h8"]["value"] == 100.0
    assert stored["baselines"]["median_of_3x10s"]["value"] == 50.0
    # a plan-mode run now compares against its own surviving anchor
    doc2 = bench._assemble(fake_mnist(1), {}, {}, "tpu", "kind",
                           allow_rebaseline=True)
    assert doc2["window"] == "median_of_3x10s"
    assert doc2["rebaselined"] is False
    assert doc2["vs_baseline"] == 2.0        # 100 vs the 50 anchor
    # and a repeat h8 run compares instead of flip-flop rebaselining
    doc3 = bench._assemble(fake_mnist(8), {}, {}, "tpu", "kind",
                           allow_rebaseline=True)
    assert doc3["rebaselined"] is False
    assert doc3["vs_baseline"] == 1.0
