"""Publisher report generation (reference: veles/tests/test_publisher.py)."""
import os

import numpy
import pytest

import veles_tpu as vt
from veles_tpu.config import root
from veles_tpu.publishing import BACKENDS


@pytest.fixture
def plotting_enabled():
    old = root.common.disable.plotting
    root.common.disable.plotting = False
    yield
    root.common.disable.plotting = old


def build_workflow_with_plots():
    wf = vt.Workflow(name="report-wf")
    p = vt.AccumulatingPlotter(wf, input=lambda: 0.5, label="err",
                               redraw_interval=0.0, name="err curve")
    p.run()
    m = vt.MatrixPlotter(wf, input=lambda: numpy.eye(3),
                         redraw_interval=0.0, name="confusion")
    m.run()
    return wf


def test_markdown_report(plotting_enabled, tmp_path):
    wf = build_workflow_with_plots()
    pub = vt.Publisher(wf, backends=("markdown",), out_dir=str(tmp_path))
    pub.run()
    report = tmp_path / "report.md"
    assert report.exists()
    text = report.read_text()
    assert "report-wf" in text and "## Plots" in text
    assert (tmp_path / "figures" / "err_curve.png").exists()
    assert (tmp_path / "figures" / "confusion.png").exists()
    assert "digraph" in text            # workflow graph embedded
    assert pub.get_metric_values()["reports"] == [str(report)]


def test_html_report(plotting_enabled, tmp_path):
    wf = build_workflow_with_plots()
    pub = vt.Publisher(wf, backends=("html",), out_dir=str(tmp_path))
    pub.run()
    html = (tmp_path / "report.html").read_text()
    assert "data:image/png;base64," in html
    assert "report-wf" in html


def test_unknown_backend_rejected():
    wf = vt.Workflow(name="t")
    with pytest.raises(KeyError):
        vt.Publisher(wf, backends=("confluence",))
    assert set(BACKENDS) >= {"markdown", "html"}


def test_publisher_without_plots(tmp_path):
    wf = vt.Workflow(name="bare")
    pub = vt.Publisher(wf, backends=("markdown",), out_dir=str(tmp_path),
                       include_config=False)
    pub.run()
    text = (tmp_path / "report.md").read_text()
    assert "bare" in text and "## Plots" not in text


def test_pdf_report(plotting_enabled, tmp_path):
    """PDF backend (reference: veles/publishing/pdf_backend.py) — a real
    multi-page PDF with plot pages, no egress/LaTeX needed."""
    wf = build_workflow_with_plots()
    pub = vt.Publisher(wf, backends=("pdf",), out_dir=str(tmp_path))
    pub.run()
    pdf = tmp_path / "report.pdf"
    assert pdf.exists()
    head = pdf.read_bytes()[:8]
    assert head.startswith(b"%PDF-")
    # 1 summary page + 2 plot pages + graph/config page
    try:
        from pypdf import PdfReader
        n_pages = len(PdfReader(str(pdf)).pages)
        assert n_pages >= 3, n_pages
    except ImportError:
        # no pdf parser in-image: count page objects in the raw stream
        assert pdf.read_bytes().count(b"/Type /Page") >= 3
