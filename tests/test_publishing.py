"""Publisher report generation (reference: veles/tests/test_publisher.py)."""
import os

import numpy
import pytest

import veles_tpu as vt
from veles_tpu.config import root
from veles_tpu.publishing import BACKENDS


@pytest.fixture
def plotting_enabled():
    old = root.common.disable.plotting
    root.common.disable.plotting = False
    yield
    root.common.disable.plotting = old


def build_workflow_with_plots():
    wf = vt.Workflow(name="report-wf")
    p = vt.AccumulatingPlotter(wf, input=lambda: 0.5, label="err",
                               redraw_interval=0.0, name="err curve")
    p.run()
    m = vt.MatrixPlotter(wf, input=lambda: numpy.eye(3),
                         redraw_interval=0.0, name="confusion")
    m.run()
    return wf


def test_markdown_report(plotting_enabled, tmp_path):
    wf = build_workflow_with_plots()
    pub = vt.Publisher(wf, backends=("markdown",), out_dir=str(tmp_path))
    pub.run()
    report = tmp_path / "report.md"
    assert report.exists()
    text = report.read_text()
    assert "report-wf" in text and "## Plots" in text
    assert (tmp_path / "figures" / "err_curve.png").exists()
    assert (tmp_path / "figures" / "confusion.png").exists()
    assert "digraph" in text            # workflow graph embedded
    assert pub.get_metric_values()["reports"] == [str(report)]


def test_html_report(plotting_enabled, tmp_path):
    wf = build_workflow_with_plots()
    pub = vt.Publisher(wf, backends=("html",), out_dir=str(tmp_path))
    pub.run()
    html = (tmp_path / "report.html").read_text()
    assert "data:image/png;base64," in html
    assert "report-wf" in html


def test_unknown_backend_rejected():
    wf = vt.Workflow(name="t")
    with pytest.raises(KeyError):
        vt.Publisher(wf, backends=("no_such_backend",))
    assert set(BACKENDS) >= {"markdown", "html", "pdf", "confluence"}


def test_publisher_without_plots(tmp_path):
    wf = vt.Workflow(name="bare")
    pub = vt.Publisher(wf, backends=("markdown",), out_dir=str(tmp_path),
                       include_config=False)
    pub.run()
    text = (tmp_path / "report.md").read_text()
    assert "bare" in text and "## Plots" not in text


class _StubConfluence:
    """Minimal local double of the Confluence REST content API — no
    egress exists in-image, so the upload path is proven against this
    (same in-process-loopback policy as test_forge/test_services)."""

    def __init__(self):
        import json as _json
        from http.server import BaseHTTPRequestHandler
        from veles_tpu._http import HTTPService, json_reply
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                stub.requests.append(
                    (self.path, dict(self.headers), body))
                if self.path == "/rest/api/content":
                    page = _json.loads(body)
                    stub.pages.append(page)
                    json_reply(self, 200, {"id": "4242"})
                elif "/child/attachment" in self.path:
                    stub.attachments.append(body)
                    json_reply(self, 200, {"results": [{}]})
                else:
                    json_reply(self, 404, {})

            def log_message(self, *a):
                pass

        self.requests, self.pages, self.attachments = [], [], []
        self.service = HTTPService(Handler, thread_name="stub-confluence")
        self.service.start_serving()

    @property
    def url(self):
        return "http://127.0.0.1:%d" % self.service.port

    def stop(self):
        self.service.stop_serving()


def test_confluence_report(plotting_enabled, tmp_path):
    """Confluence backend (reference:
    veles/publishing/confluence_backend.py): page created via the REST
    content API with basic auth, figures attached, local HTML copy
    kept."""
    stub = _StubConfluence()
    cfg = root.common.publishing.confluence
    try:
        cfg.update(server=stub.url, space="ML",
                   username="builder", token="s3cret")
        wf = build_workflow_with_plots()
        pub = vt.Publisher(wf, backends=("confluence",),
                           out_dir=str(tmp_path))
        pub.run()
        assert pub.reports and pub.reports[0].endswith("/pages/4242")
        # page: right space, XHTML body, basic auth header present
        (page,) = stub.pages
        assert page["space"]["key"] == "ML"
        assert "report-wf" in page["title"]
        assert "Results" in page["body"]["storage"]["value"]
        auth = stub.requests[0][1].get("Authorization", "")
        assert auth.startswith("Basic ")
        # both plots uploaded as attachments; local copy kept
        assert len(stub.attachments) == 2
        assert b"image/png" in stub.attachments[0]
        assert (tmp_path / "report.html").exists()
    finally:
        stub.stop()
        cfg.update(server="", space="", username="", token="")


def test_confluence_unconfigured_raises(tmp_path):
    root.common.publishing.confluence.server = ""
    wf = vt.Workflow(name="t")
    pub = vt.Publisher(wf, backends=("confluence",),
                       out_dir=str(tmp_path))
    with pytest.raises(Exception, match="not configured"):
        pub.run()


def test_pdf_report(plotting_enabled, tmp_path):
    """PDF backend (reference: veles/publishing/pdf_backend.py) — a real
    multi-page PDF with plot pages, no egress/LaTeX needed."""
    wf = build_workflow_with_plots()
    pub = vt.Publisher(wf, backends=("pdf",), out_dir=str(tmp_path))
    pub.run()
    pdf = tmp_path / "report.pdf"
    assert pdf.exists()
    head = pdf.read_bytes()[:8]
    assert head.startswith(b"%PDF-")
    # 1 summary page + 2 plot pages + graph/config page
    try:
        from pypdf import PdfReader
        n_pages = len(PdfReader(str(pdf)).pages)
        assert n_pages >= 3, n_pages
    except ImportError:
        # no pdf parser in-image: count page objects in the raw stream
        assert pdf.read_bytes().count(b"/Type /Page") >= 3
